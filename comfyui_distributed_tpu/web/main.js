// Dashboard controller (parity: reference web/main.js DistributedExtension
// + workerLifecycle.js status polling + workerSettings.js CRUD +
// tunnelManager.js — SURVEY §2.7), dependency-free.

import { api, probeHost, normalizeAddress, getAuthToken, setAuthToken } from "./apiClient.js";
import { clampDivideBy, dividerNodes, inactiveLinks, describeAddedHosts, MAX_DIVIDE } from "./widgets.js";
import { editableFields, groupByNode, applyFieldEdit, isMultiline, lintPrompt } from "./forms.js";
import { distributedValueNodes, hostsWithConfigIndex, workerKey, parseWorkerValues,
         valueType, setWorkerValue, serializeWorkerValues, orphanedKeys } from "./valueWidgets.js";
import { newPollState, pollTick } from "./progressLogic.js";
import { graphSvgFromText } from "./graphView.js";
import { telemetryRows } from "./telemetryLogic.js";

const POLL_MS = 3000;
const LOG_REFRESH_MS = 2000;

const state = {
  config: null,
  status: new Map(),       // worker_id → {online, queue_remaining, launching}
  managed: {},             // worker_id → {pid, log}
  logTimer: null,
  editingId: null,
  nodeSpecs: null,         // /distributed/object_info → parameter forms
};

const $ = (id) => document.getElementById(id);

// circuit-breaker suffix for the worker-card meta line; closed (healthy)
// stays silent — only a quarantined or probing breaker is news
function breakerBadge(state) {
  if (state === "open") return " · ⛔ breaker open";
  if (state === "half_open") return " · ⚠ breaker half-open";
  return "";
}

// AOT warmup suffix (diffusion/warmup.py): only a still-compiling worker
// is news — ready/cold/legacy probes stay silent, matching the
// dispatcher's hot-host preference (cluster/dispatch.py is_hot)
function warmupBadge(state) {
  if (state === "warming") return " · 🔥 warming";
  if (state === "error") return " · ⚠ warmup failed";
  return "";
}

// Lifecycle suffix (cluster/elastic): a draining/decommissioned worker
// is leaving ON PURPOSE — badged distinctly from a broken one so an
// operator never mistakes a scale-down for an outage
function drainBadge(state) {
  if (state === "draining") return " · 🪫 draining";
  if (state === "decommissioned") return " · 🚪 decommissioned";
  return "";
}

// ---------------------------------------------------------------------------
// worker cards
// ---------------------------------------------------------------------------

function workerCard(worker) {
  const st = state.status.get(worker.id) || {};
  const managed = state.managed[worker.id];
  const card = document.createElement("div");
  card.className = "worker-card" + (worker.enabled ? "" : " disabled");

  const dot = document.createElement("span");
  // an open breaker (cluster/resilience.py) outranks the probe verdict:
  // the host is quarantined — orchestration won't even probe it
  dot.className = "dot " + (st.breaker === "open" ? "offline"
    : st.launching ? "launching"
    : st.online ? (st.queue_remaining > 0 ? "busy" : "online") : "offline");
  dot.title = st.breaker === "open" ? "breaker open (quarantined)"
    : st.online ? `queue: ${st.queue_remaining ?? 0}` : "offline";

  const info = document.createElement("div");
  info.className = "info";
  const qr = st.online && st.queue_remaining > 0 ? ` — queue ${st.queue_remaining}` : "";
  const breaker = breakerBadge(st.breaker);
  info.innerHTML = `
    <div class="name"></div>
    <div class="addr"></div>
    <div class="meta"></div>`;
  info.querySelector(".name").textContent = worker.name || worker.id;
  info.querySelector(".addr").textContent = worker.address;
  info.querySelector(".meta").textContent =
    `${worker.type || "auto"}${managed ? ` · pid ${managed.pid}` : ""}` +
    `${st.online ? " · online" + qr : " · offline"}` + breaker +
    warmupBadge(st.warmup) + drainBadge(st.drain);

  const toggle = document.createElement("input");
  toggle.type = "checkbox";
  toggle.checked = !!worker.enabled;
  toggle.title = "enabled";
  toggle.onchange = async () => {
    await api.updateWorker({ ...worker, enabled: toggle.checked });
    await refreshConfig();
  };

  const buttons = document.createElement("div");
  buttons.className = "row";
  const mkBtn = (label, cls, fn, title = "") => {
    const b = document.createElement("button");
    b.textContent = label;
    b.className = cls;
    b.title = title;
    b.onclick = fn;
    buttons.appendChild(b);
    return b;
  };
  if (managed) {
    mkBtn("Stop", "small ghost danger", async () => {
      await api.stopWorker(worker.id).catch(alertError);
      await refreshManaged();
      renderWorkers();
    });
    mkBtn("Log", "small ghost", () => openLog(worker.id));
  } else if ((worker.type || "local") === "remote") {
    // remote controller: proxy its in-memory log through the master
    // (reference remote_worker_log, api/worker_routes.py:649-695)
    mkBtn("Log", "small ghost", () => openLog(worker.id, true));
  } else {
    mkBtn("Launch", "small ghost", async (ev) => {
      ev.target.disabled = true;
      state.status.set(worker.id, { ...st, launching: true });
      renderWorkers();
      try { await api.launchWorker(worker.id); } catch (e) { alertError(e); }
      await refreshManaged();
      renderWorkers();
    });
  }
  mkBtn("Edit", "small ghost", () => openEditor(worker));
  mkBtn("✕", "small ghost danger", async () => {
    if (!confirm(`Delete host ${worker.id}?`)) return;
    await api.deleteWorker(worker.id).catch(alertError);
    await refreshConfig();
  }, "delete");

  card.append(dot, info, toggle, buttons);
  return card;
}

function renderWorkers() {
  const root = $("worker-cards");
  root.replaceChildren();
  const hosts = (state.config && state.config.hosts) || [];
  if (!hosts.length) {
    const p = document.createElement("p");
    p.className = "meta";
    p.textContent = "No worker hosts configured — add one, or launch " +
      "additional controllers on other TPU hosts.";
    root.appendChild(p);
    return;
  }
  for (const w of hosts) root.appendChild(workerCard(w));
}

// ---------------------------------------------------------------------------
// polling (parity: workerLifecycle.js status loop)
// ---------------------------------------------------------------------------

async function pollStatus() {
  const hosts = (state.config && state.config.hosts) || [];
  // server-side launching-state machine: flags set at launch, cleared by
  // the worker's clear_launching self-report (reference workerLifecycle.js
  // launching-flag tracking)
  let serverStatus = {};
  try {
    serverStatus = (await api.localWorkerStatus()).workers || {};
  } catch { /* older controller: browser probes only */ }
  await Promise.all(hosts.map(async (w) => {
    const prev = state.status.get(w.id) || {};
    const srv = serverStatus[w.id];
    if (srv && srv.online !== undefined && w.id in serverStatus) {
      // server already probed this (local/managed) host — don't probe twice
      state.status.set(w.id, {
        online: !!srv.online,
        queue_remaining: srv.queue_remaining,
        launching: srv.launching || (prev.launching && !srv.online),
        breaker: srv.breaker,
      });
      return;
    }
    const health = await probeHost(w.address);
    state.status.set(w.id, {
      online: !!health,
      queue_remaining: health ? health.queue_remaining : null,
      launching: prev.launching && !health,
    });
  }));
  try {
    const h = await api.health();
    $("master-dot").className = "dot " + (h.queue_remaining > 0 ? "busy" : "online");
    $("master-label").textContent = `master · ${h.machine_id}` +
      (h.queue_remaining ? ` · queue ${h.queue_remaining}` : "");
  } catch {
    $("master-dot").className = "dot offline";
  }
  renderWorkers();
}

async function refreshConfig() {
  try {
    state.config = await api.getConfig();
  } catch (e) {
    // 401 = auth token configured but not supplied (or wrong): the
    // dashboard must still render the settings panel so the user can
    // paste the token — otherwise a tunnel-protected deployment bricks
    // its own recovery path.
    state.config = null;
    renderSettings();
    if (e && e.status === 401) {
      const root = $("worker-cards");
      root.replaceChildren();
      const note = document.createElement("div");
      note.className = "muted";
      note.textContent =
        "This cluster requires an auth token — paste it under Settings.";
      root.append(note);
      return;
    }
    throw e;
  }
  renderWorkers();
  renderSettings();
  renderMesh();
  renderNodeWidgets();
  renderParamForms();
  renderGraphView();
}

async function refreshManaged() {
  try {
    const res = await api.managedWorkers();
    state.managed = res.workers || {};
  } catch { state.managed = {}; }
}

// ---------------------------------------------------------------------------
// mesh / device info
// ---------------------------------------------------------------------------

async function renderMesh() {
  const root = $("mesh-info");
  root.replaceChildren();
  try {
    const info = await api.systemInfo();
    // degraded payload (device backend unresponsive): entries carry an
    // `error` field instead of a device census — surface it, don't
    // render "1 — undefined"
    const devErr = (info.devices || []).find((d) => d.error);
    const rows = [
      ["Platform", `${info.platform} (${info.environment?.tpu?.tpu_accelerator_type || "no TPU env"})`],
      ["Devices", devErr ? `⚠ ${devErr.error}` :
        String((info.devices || []).length) + " — " +
        [...new Set((info.devices || []).map((d) => d.kind))].join(", ")],
      ["Mesh shape", JSON.stringify((state.config || {}).mesh?.shape || {})],
      ["Machine", info.machine_id],
    ];
    for (const [k, v] of rows) {
      const kd = document.createElement("div"); kd.className = "k"; kd.textContent = k;
      const vd = document.createElement("div"); vd.textContent = v;
      root.append(kd, vd);
    }
  } catch (e) {
    root.textContent = "system info unavailable: " + e.message;
  }
}

// ---------------------------------------------------------------------------
// telemetry panel (/distributed/metrics.json — docs/telemetry.md)
// ---------------------------------------------------------------------------

async function renderTelemetry() {
  const root = $("telemetry-info");
  let rows;
  try {
    const res = await api.metrics();
    rows = telemetryRows((res && res.metrics) || {});
  } catch (e) {
    root.textContent = "telemetry unavailable: " + e.message;
    return;
  }
  root.replaceChildren();
  for (const [k, v] of rows) {
    const kd = document.createElement("div"); kd.className = "k"; kd.textContent = k;
    const vd = document.createElement("div"); vd.textContent = v;
    root.append(kd, vd);
  }
}

// ---------------------------------------------------------------------------
// settings (parity: sidebar settings section)
// ---------------------------------------------------------------------------

const SETTING_FIELDS = [
  ["debug", "checkbox", "Debug logging"],
  ["auto_launch_workers", "checkbox", "Auto-launch local workers on start"],
  ["stop_workers_on_master_exit", "checkbox", "Stop workers on master exit"],
  ["master_delegate_only", "checkbox", "Master delegates only (no compute)"],
  ["worker_timeout_seconds", "number", "Worker timeout (s)"],
  ["worker_probe_concurrency", "number", "Probe concurrency"],
  ["media_sync_concurrency", "number", "Media sync concurrency"],
];

function renderSettings() {
  const root = $("settings-form");
  root.replaceChildren();
  const settings = (state.config && state.config.settings) || {};
  for (const [key, kind, label] of SETTING_FIELDS) {
    const kd = document.createElement("div");
    kd.className = "k";
    kd.textContent = label;
    const input = document.createElement("input");
    input.type = kind;
    if (kind === "checkbox") input.checked = !!settings[key];
    else input.value = settings[key] ?? "";
    input.onchange = async () => {
      const value = kind === "checkbox" ? input.checked : Number(input.value);
      try { await api.updateSetting(key, value); } catch (e) { alertError(e); }
    };
    root.append(kd, input);
  }
  // cluster auth token: stored browser-side only (localStorage) and sent
  // as X-CDT-Auth on every API call — never written into the config via
  // this field (the server already knows it)
  const kd = document.createElement("div");
  kd.className = "k";
  kd.textContent = "Auth token (X-CDT-Auth)";
  const input = document.createElement("input");
  input.type = "password";
  input.placeholder = "paste cluster token";
  input.autocomplete = "off";
  input.value = getAuthToken();
  input.onchange = () => { setAuthToken(input.value.trim()); refreshConfig(); };
  root.append(kd, input);
}

// ---------------------------------------------------------------------------
// queue form (parity: executionUtils.js preflight + POST /distributed/queue)
// ---------------------------------------------------------------------------

async function submitQueue(ev) {
  ev.preventDefault();
  const result = $("queue-result");
  result.hidden = false;
  let prompt;
  try {
    prompt = JSON.parse($("queue-prompt").value);
  } catch (e) {
    result.textContent = "Invalid JSON: " + e.message;
    return;
  }
  result.textContent = "Pre-flight probing workers…";
  const hosts = ((state.config || {}).hosts || []).filter((w) => w.enabled);
  const probes = await Promise.all(hosts.map((w) => probeHost(w.address)));
  const online = hosts.filter((_, i) => probes[i]);
  result.textContent = `Dispatching (${online.length}/${hosts.length} workers online)…`;
  try {
    const res = await api.queue(prompt, {
      load_balance: $("queue-loadbalance").checked,
      delegate_master: $("queue-delegate").checked,
    });
    result.textContent = JSON.stringify(res, null, 2);
    if (res.prompt_id) trackProgress(res.prompt_id);
  } catch (e) {
    result.textContent = "Error: " + e.message +
      (e.data ? "\n" + JSON.stringify(e.data, null, 2) : "");
  }
}

// live sampling progress + latent preview (/distributed/progress|preview —
// the step/preview UX ComfyUI's UI provides, served by our own tracker)
let progressTimer = null;
async function trackProgress(promptId) {
  const box = $("job-progress"), bar = $("job-progress-bar");
  const label = $("job-progress-label"), img = $("job-preview");
  if (progressTimer) clearInterval(progressTimer);
  box.hidden = false;
  bar.style.width = "0%";
  label.textContent = "waiting for first step…";
  img.hidden = true;
  const poll = newPollState();     // state machine in progressLogic.js
  progressTimer = setInterval(async () => {
    let snap = null;
    try { snap = await api.progress(promptId); } catch { /* counted as miss */ }
    const tick = pollTick(poll, snap);
    if (tick.label) label.textContent = tick.label;
    if (tick.widthPct !== null) bar.style.width = tick.widthPct + "%";
    if (tick.refetchPreview) {
      img.src = api.previewUrl(promptId);
      img.hidden = false;
    }
    if (tick.hide) box.hidden = true;
    if (tick.stop) clearInterval(progressTimer);
  }, 750);
}

// ---------------------------------------------------------------------------
// per-node widget layer (parity: reference web/distributedValue.js — per-
// worker value widgets for DistributedValue nodes, two-way synced with the
// prompt JSON's `worker_values` map; keys are 1-indexed worker numbers,
// nodes/utilities.py:86-162)
// ---------------------------------------------------------------------------

function parsePrompt() {
  try { return JSON.parse($("queue-prompt").value); } catch { return null; }
}

function writePromptInput(nodeId, field, value) {
  const prompt = parsePrompt();
  if (!prompt || !prompt[nodeId]) return;
  prompt[nodeId].inputs = prompt[nodeId].inputs || {};
  prompt[nodeId].inputs[field] = value;
  $("queue-prompt").value = JSON.stringify(prompt, null, 2);
  // programmatic value assignment fires no "input" event — keep the
  // graph view in sync with every edit path ("the graph a user sees is
  // the graph that will be queued", docs/api.md)
  renderGraphView();
}

// Read-only DAG render of the loaded workflow (graphView.js): the user
// SEES the graph they are queueing — nodes, links, parameter summaries,
// output nodes highlighted (the reference shows this via ComfyUI's
// canvas; VERDICT r4 next #6).
function renderGraphView() {
  const root = $("graph-panel");
  const outputClasses = new Set();
  for (const [name, spec] of Object.entries((state.nodeSpecs || {}).nodes || {})) {
    if (spec.output_node) outputClasses.add(name);
  }
  const svg = graphSvgFromText($("queue-prompt").value, outputClasses);
  root.innerHTML = svg;
  root.hidden = !svg;
}

// Parameter forms generated from node interface specs (forms.js +
// /distributed/object_info): edit prompt/seed/size/steps without touching
// the raw JSON (VERDICT r3 next #3; the reference gets this from
// ComfyUI's graph editor, web/executionUtils.js:6-23).
function renderParamForms() {
  const root = $("param-forms");
  root.replaceChildren();
  const prompt = parsePrompt();
  const fields = editableFields(prompt, state.nodeSpecs);
  const issues = lintPrompt(prompt, state.nodeSpecs);
  if (!fields.length && !issues.length) {
    root.hidden = true;
    return;
  }
  root.hidden = false;
  // preflight lint (mirrors the server's validate_prompt, so the user
  // sees the node_errors BEFORE queueing)
  for (const issue of issues) {
    const div = document.createElement("div");
    div.className = issue.level === "error" ? "error" : "meta";
    div.textContent =
      `${issue.level === "error" ? "✕" : "⚠"} node #${issue.nodeId}: ` +
      issue.message;
    root.appendChild(div);
  }
  if (!fields.length) return;
  const head = document.createElement("div");
  head.className = "meta";
  head.textContent = "Parameters (writes through to the JSON above)";
  root.appendChild(head);
  for (const group of groupByNode(fields)) {
    const box = document.createElement("div");
    box.className = "dv-node";
    const title = document.createElement("div");
    title.className = "meta";
    title.textContent = `${group.classType} #${group.nodeId}`;
    const grid = document.createElement("div");
    grid.className = "kv";
    for (const f of group.fields) {
      const kd = document.createElement("div");
      kd.className = "k";
      kd.textContent = f.name + (f.optional ? "" : " *");
      let input;
      if (f.kind === "boolean") {
        input = document.createElement("input");
        input.type = "checkbox";
        input.checked = !!f.value;
      } else if (isMultiline(f)) {
        input = document.createElement("textarea");
        input.rows = 2;
        input.value = f.value ?? "";
      } else {
        input = document.createElement("input");
        if (f.kind === "int" || f.kind === "float") {
          input.type = "number";
          if (f.kind === "float") input.step = "any";
        }
        input.value = f.value ?? "";
      }
      input.onchange = () => {
        const prompt = parsePrompt();
        if (!prompt) return;
        try {
          const raw = f.kind === "boolean" ? input.checked : input.value;
          const coerced = applyFieldEdit(prompt, f.nodeId, f.name, f.kind, raw);
          $("queue-prompt").value = JSON.stringify(prompt, null, 2);
          renderGraphView();   // form edits fire no "input" event
          if (f.kind !== "boolean") input.value = coerced;
          input.classList.remove("invalid");
        } catch (e) {
          input.classList.add("invalid");
          input.title = e.message;
        }
      };
      grid.append(kd, input);
    }
    box.append(title, grid);
    root.appendChild(box);
  }
}

function renderNodeWidgets() {
  const root = $("node-widgets");
  root.replaceChildren();
  const prompt = parsePrompt();
  // worker_values keys are 1-indexed positions in the FULL config host
  // list (the orchestrator's stable worker_index contract) — enabled
  // hosts are shown, but each keeps its config-position number
  // (valueWidgets.js carries the pure logic + its node:test suite)
  const hosts = hostsWithConfigIndex(state.config);
  const dvNodes = distributedValueNodes(prompt);
  const divNodes = dividerNodes(prompt);
  if ((!dvNodes.length || !hosts.length) && !divNodes.length) {
    root.hidden = true;
    return;
  }
  root.hidden = false;

  // divider dynamic outputs (parity: web/image_batch_divider.js:10-62 —
  // there the node canvas grows/shrinks outputs; here the widget sets
  // divide_by and flags links into chunks the new count deactivates)
  for (const [nodeId, node] of divNodes) {
    const inputs = node.inputs || {};
    const box = document.createElement("div");
    box.className = "dv-node";
    const title = document.createElement("div");
    title.className = "meta";
    title.textContent = `${node.class_type} #${nodeId}`;
    const grid = document.createElement("div");
    grid.className = "kv";
    const kd = document.createElement("div");
    kd.className = "k";
    kd.textContent = "divide_by (active outputs)";
    const input = document.createElement("input");
    input.type = "number";
    input.min = "1";
    input.max = String(MAX_DIVIDE);
    input.value = clampDivideBy(inputs.divide_by ?? 2);
    const warn = document.createElement("div");
    warn.className = "meta";
    const refreshWarn = (val) => {
      const stale = inactiveLinks(parsePrompt(), nodeId, val);
      warn.textContent = stale.length
        ? `⚠ ${stale.map((s) => `#${s.consumerId}.${s.inputName} uses ` +
            `output ${s.outputIndex}`).join("; ")} — beyond divide_by, ` +
          "will receive an empty batch"
        : "";
    };
    input.onchange = () => {
      const val = clampDivideBy(input.value);
      input.value = val;
      writePromptInput(nodeId, "divide_by", val);
      refreshWarn(val);
    };
    refreshWarn(clampDivideBy(inputs.divide_by ?? 2));
    grid.append(kd, input);
    box.append(title, grid, warn);
    root.appendChild(box);
  }
  if (!dvNodes.length || !hosts.length) return;
  for (const [nodeId, node] of dvNodes) {
    const inputs = node.inputs || {};
    const mapping = parseWorkerValues(inputs.worker_values);
    const vtype = valueType(inputs, mapping);

    const box = document.createElement("div");
    box.className = "dv-node";
    const title = document.createElement("div");
    title.className = "meta";
    const dflt = Array.isArray(inputs.default_value)
      ? `link ${JSON.stringify(inputs.default_value)}`
      : JSON.stringify(inputs.default_value ?? null);
    title.textContent =
      `DistributedValue #${nodeId}${vtype ? ` (${vtype})` : ""} — default ${dflt}`;
    box.appendChild(title);

    const orphans = orphanedKeys(mapping, state.config);
    if (orphans.length) {
      const warn = document.createElement("div");
      warn.className = "meta";
      warn.textContent = `⚠ worker_values keys beyond the host list ` +
        `(never read): ${orphans.join(", ")}`;
      box.appendChild(warn);
    }

    const grid = document.createElement("div");
    grid.className = "kv";
    hosts.forEach(([w, configIdx]) => {
      const key = workerKey(configIdx);
      const kd = document.createElement("div");
      kd.className = "k";
      kd.textContent = `${w.name || w.id} (#${key})`;
      const input = document.createElement("input");
      if (vtype === "INT" || vtype === "FLOAT") input.type = "number";
      input.value = mapping[key] ?? "";
      input.placeholder = "(default)";
      input.onchange = () => {
        try {
          setWorkerValue(mapping, key, input.value, vtype);
          input.classList.remove("invalid");
        } catch (e) {
          input.classList.add("invalid");
          input.title = e.message;
          return;
        }
        writePromptInput(nodeId, "worker_values",
                         serializeWorkerValues(mapping));
      };
      grid.append(kd, input);
    });
    box.appendChild(grid);
    root.appendChild(box);
  }
}

// ---------------------------------------------------------------------------
// log modal (parity: workerLifecycle.js log modal, 2s auto-refresh)
// ---------------------------------------------------------------------------

async function fetchLog(workerId, remote) {
  const res = workerId === "__local__" ? await api.localLog()
    : remote ? await api.remoteWorkerLog(workerId)
    : await api.workerLog(workerId);
  return res.log || res.raw || "";
}

function openLog(workerId, remote = false) {
  $("log-title").textContent = workerId === "__local__"
    ? "Controller log"
    : `Worker ${workerId} log${remote ? " (remote)" : ""}`;
  $("modal-backdrop").hidden = false;
  const body = $("log-body");
  const refresh = async () => {
    try {
      body.textContent = await fetchLog(workerId, remote);
      if ($("log-follow").checked) body.scrollTop = body.scrollHeight;
    } catch (e) {
      body.textContent = "log unavailable: " + e.message;
    }
  };
  refresh();
  state.logTimer = setInterval(refresh, LOG_REFRESH_MS);
}

function closeLog() {
  $("modal-backdrop").hidden = true;
  clearInterval(state.logTimer);
}

// ---------------------------------------------------------------------------
// worker editor (parity: workerSettings.js forms)
// ---------------------------------------------------------------------------

function openEditor(worker) {
  state.editingId = worker ? worker.id : null;
  $("editor-title").textContent = worker ? `Edit ${worker.id}` : "Add host";
  $("ed-id").value = worker?.id || "";
  $("ed-id").disabled = !!worker;
  $("ed-name").value = worker?.name || "";
  $("ed-address").value = worker?.address || "";
  $("ed-type").value = worker?.type || "";
  $("ed-mesh").value = worker?.mesh_devices ?? -1;
  $("ed-extra").value = worker?.extra_args || "";
  $("ed-enabled").checked = worker ? !!worker.enabled : true;
  $("editor-backdrop").hidden = false;
}

async function saveEditor(ev) {
  ev.preventDefault();
  const worker = {
    id: $("ed-id").value.trim(),
    name: $("ed-name").value.trim(),
    address: normalizeAddress($("ed-address").value),
    enabled: $("ed-enabled").checked,
    mesh_devices: Number($("ed-mesh").value),
    extra_args: $("ed-extra").value,
  };
  const type = $("ed-type").value;
  if (type) worker.type = type;
  try {
    await api.updateWorker(worker);
    $("editor-backdrop").hidden = true;
    await refreshConfig();
  } catch (e) { alertError(e); }
}

// ---------------------------------------------------------------------------
// tunnel (parity: tunnelManager.js)
// ---------------------------------------------------------------------------

async function refreshTunnel() {
  try {
    const st = await api.tunnelStatus();
    $("tunnel-dot").className = "dot " + (st.running ? "online" : "");
    $("tunnel-url").textContent = st.running ? st.url : "stopped";
    $("btn-tunnel").textContent = st.running ? "Stop tunnel" : "Start tunnel";
    $("btn-tunnel").dataset.running = st.running ? "1" : "";
    $("tunnel-error").hidden = true;
  } catch { /* section stays as-is */ }
}

async function toggleTunnel() {
  const btn = $("btn-tunnel");
  btn.disabled = true;
  try {
    if (btn.dataset.running) await api.tunnelStop();
    else await api.tunnelStart();
  } catch (e) {
    $("tunnel-error").textContent = e.message;
    $("tunnel-error").hidden = false;
  }
  btn.disabled = false;
  await refreshTunnel();
}

// ---------------------------------------------------------------------------

function alertError(e) {
  console.error(e);
  alert(e.message || String(e));
}

async function loadWorkflowList() {
  try {
    const res = await api.listWorkflows();
    const sel = $("workflow-select");
    for (const name of res.workflows || []) {
      const opt = document.createElement("option");
      opt.value = name;
      opt.textContent = name;
      sel.appendChild(opt);
    }
  } catch { /* route absent on older controllers */ }
}

async function init() {
  $("queue-form").onsubmit = submitQueue;
  $("btn-load-workflow").onclick = async () => {
    const name = $("workflow-select").value;
    if (!name) return;
    try {
      const wf = await api.getWorkflow(name);
      delete wf._meta;
      $("queue-prompt").value = JSON.stringify(wf, null, 2);
      renderNodeWidgets();
      renderParamForms();
      renderGraphView();
    } catch (e) { alertError(e); }
  };
  let widgetDebounce = null;
  $("queue-prompt").addEventListener("input", () => {
    clearTimeout(widgetDebounce);
    widgetDebounce = setTimeout(() => {
      renderNodeWidgets();
      renderParamForms();
      renderGraphView();
    }, 400);
  });
  $("btn-add-worker").onclick = () => openEditor(null);
  $("btn-auto-populate").onclick = async () => {
    // device census → worker rows (reference masterDetection.js:36-100)
    try {
      const res = await api.autoPopulate();
      alert(res.added && res.added.length
        ? `Added: ${describeAddedHosts(res)}`
        : "No new slice hosts found (census advertises none beyond this host)");
      await refreshConfig();
    } catch (e) { alertError(e); }
  };
  $("editor-cancel").onclick = () => { $("editor-backdrop").hidden = true; };
  $("editor-form").onsubmit = saveEditor;
  $("log-close").onclick = closeLog;
  $("modal-backdrop").onclick = (ev) => {
    if (ev.target === $("modal-backdrop")) closeLog();
  };
  $("btn-tunnel").onclick = toggleTunnel;
  $("btn-interrupt").onclick = async () => {
    // fan out to all enabled hosts, then the master (reference
    // workerUtils.js:73-95)
    const hosts = ((state.config || {}).hosts || []).filter((w) => w.enabled);
    await Promise.all(hosts.map((w) =>
      fetch(`${normalizeAddress(w.address)}/distributed/interrupt`,
            { method: "POST" }).catch(() => null)));
    await api.interrupt().catch(alertError);
  };
  $("btn-clear-memory").onclick = async () => {
    const hosts = ((state.config || {}).hosts || []).filter((w) => w.enabled);
    await Promise.all(hosts.map((w) =>
      fetch(`${normalizeAddress(w.address)}/distributed/clear_memory`,
            { method: "POST" }).catch(() => null)));
    await api.clearMemory().catch(alertError);
  };
  $("master-dot").ondblclick = () => openLog("__local__");

  // node interface specs for the parameter forms (one fetch; the
  // registry is static for the controller's lifetime)
  try { state.nodeSpecs = await api.objectInfo(); } catch { state.nodeSpecs = null; }

  await refreshConfig();
  await loadWorkflowList();
  await refreshManaged();
  await refreshTunnel();
  await pollStatus();
  await renderTelemetry();
  setInterval(pollStatus, POLL_MS);
  setInterval(refreshTunnel, POLL_MS * 4);
  setInterval(renderTelemetry, POLL_MS * 2);
}

init();
