// node:test suite for the telemetry panel's pure transforms
// (telemetryLogic.js) over the /distributed/metrics.json snapshot shape.
import assert from "node:assert/strict";
import { test } from "node:test";

import { breakerSummary, cacheSummary, countsByLabel, elasticSummary,
         fleetCacheSummary, fmtSeconds, frontDoorSummary, histQuantile,
         mergeHistogram, preemptionSummary, seriesSum, stagesSummary,
         telemetryRows } from "../telemetryLogic.js";

const METRICS = {
  cdt_prompts_total: {
    type: "counter",
    series: [
      { labels: { status: "success" }, value: 3 },
      { labels: { status: "error" }, value: 1 },
    ],
  },
  cdt_tile_queue_depth: {
    type: "gauge",
    series: [{ labels: {}, value: 5 }],
  },
  cdt_sampler_step_seconds: {
    type: "histogram",
    series: [
      { labels: { pipeline: "txt2img" },
        buckets: [[0.01, 0], [0.1, 8], [1.0, 10]], sum: 1.2, count: 10 },
      { labels: { pipeline: "flow_dp" },
        buckets: [[0.01, 0], [0.1, 0], [1.0, 2]], sum: 1.0, count: 2 },
    ],
  },
};

test("seriesSum totals and filters by labels", () => {
  assert.equal(seriesSum(METRICS, "cdt_prompts_total"), 4);
  assert.equal(seriesSum(METRICS, "cdt_prompts_total",
                         { status: "error" }), 1);
  assert.equal(seriesSum(METRICS, "cdt_tile_queue_depth"), 5);
  assert.equal(seriesSum(METRICS, "nope"), 0);
});

test("countsByLabel buckets a counter family per label value", () => {
  assert.deepEqual(countsByLabel(METRICS, "cdt_prompts_total", "status"),
                   { success: 3, error: 1 });
  assert.deepEqual(countsByLabel(METRICS, "nope", "status"), {});
});

test("mergeHistogram adds cumulative counts bucket-for-bucket", () => {
  const m = mergeHistogram(METRICS, "cdt_sampler_step_seconds");
  assert.equal(m.count, 12);
  assert.deepEqual(m.buckets, [[0.01, 0], [0.1, 8], [1.0, 12]]);
  const only = mergeHistogram(METRICS, "cdt_sampler_step_seconds",
                              { pipeline: "flow_dp" });
  assert.equal(only.count, 2);
  assert.equal(mergeHistogram(METRICS, "nope"), null);
});

test("histQuantile reads the cumulative buckets", () => {
  const m = mergeHistogram(METRICS, "cdt_sampler_step_seconds");
  assert.equal(histQuantile(m, 0.5), 0.1);    // 6th of 12 lands in ≤0.1
  assert.equal(histQuantile(m, 0.99), 1.0);
  assert.equal(histQuantile(null, 0.5), null);
  assert.equal(histQuantile({ count: 0, buckets: [] }, 0.5), null);
  // past the last finite bucket → Infinity (rendered ">max")
  assert.equal(histQuantile({ count: 2, buckets: [[0.1, 0]] }, 0.9),
               Infinity);
});

test("fmtSeconds picks a sane unit", () => {
  assert.equal(fmtSeconds(0.0000005), "1µs");
  assert.equal(fmtSeconds(0.0123), "12.3ms");
  assert.equal(fmtSeconds(2.5), "2.50s");
  assert.equal(fmtSeconds(null), "—");
  assert.equal(fmtSeconds(Infinity), ">max");
});

test("breakerSummary buckets workers by breaker state and names the bad ones", () => {
  assert.equal(breakerSummary({}), "none tracked");
  const metrics = {
    cdt_worker_breaker_state: {
      type: "gauge",
      series: [
        { labels: { worker: "w0" }, value: 0 },
        { labels: { worker: "w1" }, value: 2 },
        { labels: { worker: "w2" }, value: 2 },
        { labels: { worker: "w3" }, value: 1 },
      ],
    },
  };
  assert.equal(breakerSummary(metrics),
               "1 closed · 1 half-open (w3) · 2 open (w1, w2)");
  // telemetryRows carries the row
  const byKey = Object.fromEntries(telemetryRows(metrics));
  assert.match(byKey["Circuit breakers"], /2 open \(w1, w2\)/);
});

test("frontDoorSummary reports admissions, occupancy, and queue wait", () => {
  assert.equal(frontDoorSummary({}), "no traffic");
  const metrics = {
    cdt_admission_total: {
      type: "counter",
      series: [
        { labels: { outcome: "admitted", priority: "interactive" }, value: 10 },
        { labels: { outcome: "shed", priority: "batch" }, value: 4 },
      ],
    },
    cdt_batch_size: {
      type: "histogram",
      series: [{ labels: {}, buckets: [[1, 2], [2, 5], [4, 6]],
                 sum: 14, count: 6 }],
    },
    cdt_queue_wait_seconds: {
      type: "histogram",
      series: [{ labels: { priority: "interactive" },
                 buckets: [[0.1, 3], [1.0, 6]], sum: 1.2, count: 6 }],
    },
    cdt_batch_fallbacks_total: {
      type: "counter",
      series: [{ labels: {}, value: 1 }],
    },
  };
  const row = frontDoorSummary(metrics);
  assert.match(row, /10 admitted · 4 shed/);
  assert.match(row, /batch x̄ 2\.33/);
  assert.match(row, /wait p95 1\.00s/);
  assert.match(row, /1 fallback/);
  // telemetryRows carries the row
  const byKey = Object.fromEntries(telemetryRows(metrics));
  assert.match(byKey["Front door"], /batch x̄/);
});

test("elasticSummary names draining workers and counts scale events", () => {
  assert.equal(elasticSummary({}), "static fleet");
  const metrics = {
    cdt_worker_drain_state: {
      type: "gauge",
      series: [
        { labels: { worker: "w0" }, value: 0 },
        { labels: { worker: "w1" }, value: 1 },
        { labels: { worker: "w2" }, value: 2 },
      ],
    },
    cdt_autoscale_decisions_total: {
      type: "counter",
      series: [
        { labels: { direction: "up", reason: "queue_pressure" }, value: 2 },
        { labels: { direction: "down", reason: "idle_fleet" }, value: 1 },
        { labels: { direction: "hold", reason: "steady" }, value: 40 },
      ],
    },
    cdt_steal_assignments_total: {
      type: "counter",
      series: [
        { labels: { kind: "stolen" }, value: 7 },
        { labels: { kind: "own_job" }, value: 12 },
      ],
    },
    cdt_drain_handbacks_total: {
      type: "counter",
      series: [{ labels: {}, value: 3 }],
    },
  };
  const row = elasticSummary(metrics);
  assert.match(row, /1 active/);
  assert.match(row, /1 draining \(w1\)/);
  assert.match(row, /1 decommissioned/);
  assert.match(row, /scale 2↑ 1↓/);
  assert.match(row, /7 stolen/);
  assert.match(row, /3 handed back/);
  // telemetryRows carries the row; holds alone don't count as events
  const byKey = Object.fromEntries(telemetryRows(metrics));
  assert.match(byKey["Elastic fleet"], /draining \(w1\)/);
  assert.equal(
    elasticSummary({ cdt_autoscale_decisions_total: {
      type: "counter",
      series: [{ labels: { direction: "hold", reason: "steady" },
                 value: 9 }] } }),
    "static fleet");
});

test("cacheSummary reports per-tier hit rates and the loud counters", () => {
  assert.equal(cacheSummary({}), "no cacheable traffic");
  const metrics = {
    cdt_cache_hits_total: {
      type: "counter",
      series: [
        { labels: { tier: "conditioning" }, value: 30 },
        { labels: { tier: "result" }, value: 6 },
      ],
    },
    cdt_cache_misses_total: {
      type: "counter",
      series: [
        { labels: { tier: "conditioning" }, value: 10 },
        { labels: { tier: "result" }, value: 6 },
      ],
    },
    cdt_coalesce_width: {
      type: "histogram",
      series: [{ labels: {}, buckets: [[1, 4], [2, 6], [4, 8]],
                 sum: 14, count: 8 }],
    },
    cdt_cache_corrupt_total: {
      type: "counter",
      series: [{ labels: { tier: "result" }, value: 1 }],
    },
    cdt_hash_tokenization_total: {
      type: "counter",
      series: [{ labels: { tower: "clip_l" }, value: 5 }],
    },
  };
  const row = cacheSummary(metrics);
  assert.match(row, /conditioning 75% of 40/);
  assert.match(row, /result 50% of 12/);
  assert.match(row, /coalesce x̄ 1.75/);
  assert.match(row, /1 CORRUPT rejected/);
  assert.match(row, /5 hash-tokenized/);
  const byKey = Object.fromEntries(telemetryRows(metrics));
  assert.match(byKey["Content cache"], /conditioning 75%/);
  // a width histogram that only ever saw 1s is not worth a fragment
  assert.equal(cacheSummary({ cdt_coalesce_width: {
    type: "histogram",
    series: [{ labels: {}, buckets: [[1, 3]], sum: 3, count: 3 }] } }),
    "no cacheable traffic");
});

test("fleetCacheSummary reports ring size, remote outcomes, near reuse", () => {
  assert.equal(fleetCacheSummary({}), "per-host only");
  const metrics = {
    cdt_fleet_ring_size: {
      type: "gauge",
      series: [{ labels: {}, value: 3 }],
    },
    cdt_fleet_cache_remote_total: {
      type: "counter",
      series: [
        { labels: { op: "get", outcome: "hit" }, value: 6 },
        { labels: { op: "get", outcome: "miss" }, value: 2 },
        { labels: { op: "get", outcome: "error" }, value: 1 },
        { labels: { op: "get", outcome: "skipped" }, value: 1 },
        { labels: { op: "put", outcome: "hit" }, value: 5 },
        { labels: { op: "handback", outcome: "hit" }, value: 4 },
      ],
    },
    cdt_fleet_near_reuse_total: {
      type: "counter",
      series: [{ labels: {}, value: 2 }],
    },
    cdt_fleet_near_steps_saved_total: {
      type: "counter",
      series: [{ labels: {}, value: 8 }],
    },
  };
  const row = fleetCacheSummary(metrics);
  assert.match(row, /ring 3/);
  // errors and breaker-skips read as non-hits: 6 of 10 probes served
  assert.match(row, /remote 6\/10 \(60%\)/);
  assert.match(row, /5 fills/);
  assert.match(row, /4 handed back/);
  assert.match(row, /near 2 reuse \(8 steps saved\)/);
  // telemetryRows carries the row
  const byKey = Object.fromEntries(telemetryRows(metrics));
  assert.match(byKey["Fleet cache"], /ring 3/);
  // a ring with no traffic still renders (membership is a fact worth
  // showing before the first probe)
  assert.equal(
    fleetCacheSummary({ cdt_fleet_ring_size: {
      type: "gauge", series: [{ labels: {}, value: 2 }] } }),
    "ring 2");
});

test("preemptionSummary reports reasons, parked state, and dead-letters", () => {
  assert.equal(preemptionSummary({}), "none");
  const metrics = {
    cdt_preemptions_total: {
      type: "counter",
      series: [
        { labels: { reason: "priority" }, value: 4 },
        { labels: { reason: "drain" }, value: 1 },
      ],
    },
    cdt_jobs_preempted: {
      type: "gauge",
      series: [{ labels: {}, value: 2 }],
    },
    cdt_checkpoint_bytes: {
      type: "gauge",
      series: [
        { labels: { tier: "memory" }, value: 3 * 1024 * 1024 },
        { labels: { tier: "persisted" }, value: 1024 * 1024 },
      ],
    },
    cdt_resume_seconds: {
      type: "histogram",
      series: [{ labels: {}, buckets: [[0.1, 0], [1.0, 3], [10.0, 4]],
                 sum: 2.4, count: 4 }],
    },
    cdt_checkpoint_dead_letters_total: {
      type: "counter",
      series: [{ labels: {}, value: 1 }],
    },
  };
  const row = preemptionSummary(metrics);
  assert.match(row, /4 priority/);
  assert.match(row, /1 drain/);
  assert.match(row, /2 parked/);
  assert.match(row, /4\.0 MB ckpt/);
  assert.match(row, /resume p95 10\.00s/);
  assert.match(row, /1 DEAD-LETTERED/);
  const byKey = Object.fromEntries(telemetryRows(metrics));
  assert.match(byKey["Preemption"], /4 priority/);
  // a parked job with no preemptions yet (gauge-only) renders WITHOUT
  // a dangling "none ·" fragment
  assert.equal(preemptionSummary({ cdt_jobs_preempted: {
    type: "gauge", series: [{ labels: {}, value: 1 }] } }), "1 parked");
});

test("stagesSummary reports per-pool state, decode coalescing, and redispatches", () => {
  assert.equal(stagesSummary({}), "fused path");
  const metrics = {
    cdt_stage_queue_depth: {
      type: "gauge",
      series: [
        { labels: { stage: "encode" }, value: 2 },
        { labels: { stage: "denoise" }, value: 1 },
        { labels: { stage: "decode" }, value: 5 },
      ],
    },
    cdt_stage_occupancy: {
      type: "gauge",
      series: [
        { labels: { stage: "denoise" }, value: 1 },
        { labels: { stage: "decode" }, value: 0.5 },
      ],
    },
    cdt_stage_jobs_total: {
      type: "counter",
      series: [
        { labels: { stage: "decode", outcome: "ok" }, value: 7 },
        { labels: { stage: "decode", outcome: "redispatch" }, value: 2 },
      ],
    },
    cdt_decode_batch_size: {
      type: "histogram",
      series: [{ labels: {}, buckets: [[1, 1], [2, 3], [4, 4]],
                 sum: 11, count: 4 }],
    },
    cdt_latent_transfer_bytes: {
      type: "histogram",
      series: [{ labels: {}, buckets: [[65536, 8]],
                 sum: 8 * 1024 * 1024, count: 8 }],
    },
    cdt_stage_steals_total: {
      type: "counter",
      series: [{ labels: { src: "decode", dst: "encode" }, value: 3 }],
    },
  };
  const row = stagesSummary(metrics);
  assert.match(row, /encode q2/);
  assert.match(row, /denoise q1 100%/);
  assert.match(row, /decode q5 50%/);
  assert.match(row, /decode x̄ 2\.75/);
  assert.match(row, /8 handoffs 8\.0 MB/);
  assert.match(row, /3 steals/);
  assert.match(row, /2 REDISPATCHED/);
  const byKey = Object.fromEntries(telemetryRows(metrics));
  assert.match(byKey["Stages"], /decode q5/);
});

test("telemetryRows tolerates absent families and renders the rest", () => {
  const rows = telemetryRows(METRICS);
  const byKey = Object.fromEntries(rows);
  assert.match(byKey["Prompts"], /3 success/);
  assert.match(byKey["Sampler step p50 / p95"], /12 obs/);
  assert.equal(byKey["Tile tasks"], "none");
  assert.equal(byKey["Tile queue depth"], "5");
  assert.equal(byKey["Dispatches"], "none");
  // an empty snapshot still renders every row
  assert.equal(telemetryRows({}).length, rows.length);
});
