// node:test suite for the DOM-free widget helpers (run via
// scripts/test-web.sh → `node --test`; no build system, reference
// parity with web/tests/*.test.js under vitest).
import assert from "node:assert/strict";
import { test } from "node:test";

import {
  clampDivideBy,
  describeAddedHosts,
  dividerNodes,
  inactiveLinks,
  MAX_DIVIDE,
} from "../widgets.js";

test("clampDivideBy bounds and coerces", () => {
  assert.equal(clampDivideBy(3), 3);
  assert.equal(clampDivideBy("7"), 7);
  assert.equal(clampDivideBy(0), 1);
  assert.equal(clampDivideBy(-5), 1);
  assert.equal(clampDivideBy(99), MAX_DIVIDE);
  assert.equal(clampDivideBy("junk"), 1);
  assert.equal(clampDivideBy(2.9), 2);
});

const PROMPT = {
  1: { class_type: "LoadImage", inputs: { image: "a.png" } },
  2: { class_type: "ImageBatchDivider",
       inputs: { images: ["1", 0], divide_by: 2 } },
  3: { class_type: "SaveImage", inputs: { images: ["2", 0] } },
  4: { class_type: "SaveImage", inputs: { images: ["2", 3] } },
  5: { class_type: "AudioBatchDivider",
       inputs: { audio: ["9", 0], divide_by: 4 } },
};

test("dividerNodes finds both divider classes only", () => {
  const ids = dividerNodes(PROMPT).map(([id]) => id);
  assert.deepEqual(ids, ["2", "5"]);
  assert.deepEqual(dividerNodes(null), []);
  assert.deepEqual(dividerNodes("not-an-object"), []);
});

test("inactiveLinks flags consumers past divide_by", () => {
  const stale = inactiveLinks(PROMPT, "2", 2);
  assert.deepEqual(stale, [
    { consumerId: "4", inputName: "images", outputIndex: 3 },
  ]);
  // raising divide_by past the referenced output clears the warning
  assert.deepEqual(inactiveLinks(PROMPT, "2", 4), []);
  // numeric/string node-id mismatches still match
  assert.equal(inactiveLinks(PROMPT, 2, 2).length, 1);
});

test("describeAddedHosts formats rows", () => {
  assert.equal(
    describeAddedHosts({ added: [
      { id: "host1", address: "tpu-b:8288" },
      { id: "host2", address: "tpu-c:8288" },
    ] }),
    "host1 → tpu-b:8288, host2 → tpu-c:8288");
  assert.equal(describeAddedHosts({}), "");
  assert.equal(describeAddedHosts(null), "");
});
