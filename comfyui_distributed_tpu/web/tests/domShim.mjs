// Minimal DOM/browser shim so node:test can import main.js itself (no
// jsdom in the toolchain — the runner is plain `node --test`). Elements
// auto-vivify: any getElementById returns a persistent stub recording
// the properties main.js sets, which is exactly what the tests assert.

export function makeElement(id = "") {
  const children = [];
  const el = {
    id,
    children,
    dataset: {},
    style: {},
    hidden: false,
    disabled: false,
    checked: false,
    value: "",
    textContent: "",
    innerHTML: "",
    className: "",
    title: "",
    src: "",
    listeners: {},
    appendChild(c) { children.push(c); return c; },
    append(...cs) { children.push(...cs); },
    replaceChildren(...cs) { children.length = 0; children.push(...cs); },
    addEventListener(name, fn) {
      (el.listeners[name] = el.listeners[name] || []).push(fn);
    },
    querySelector() { return makeElement(); },
    querySelectorAll() { return []; },
    setAttribute(k, v) { el[k] = v; },
    focus() {},
    click() { if (el.onclick) return el.onclick({ target: el }); },
  };
  return el;
}

export function installDom({ routes = {}, fetchLog = [] } = {}) {
  const byId = new Map();
  const doc = {
    getElementById(id) {
      if (!byId.has(id)) byId.set(id, makeElement(id));
      return byId.get(id);
    },
    createElement(tag) {
      const el = makeElement();
      el.tagName = String(tag).toUpperCase();
      return el;
    },
    body: makeElement("body"),
  };

  const storage = new Map();
  const localStorage = {
    getItem: (k) => (storage.has(k) ? storage.get(k) : null),
    setItem: (k, v) => storage.set(k, String(v)),
    removeItem: (k) => storage.delete(k),
  };

  // fetch: look up the longest matching route prefix; default 404.
  // Routes map path-prefix → JSON payload or (url, opts) → payload fn.
  // Path-only routes ("/distributed/...") match only SAME-ORIGIN
  // requests — an absolute cross-origin URL (worker probes) must be
  // registered with its full "http://host:port/..." prefix, so
  // unregistered hosts read as offline.
  async function fetch(url, opts = {}) {
    const u = String(url);
    fetchLog.push({ url: u, opts });
    const keys = Object.keys(routes)
      .filter((k) => (k.startsWith("http")
        ? u.startsWith(k)
        : !u.startsWith("http") && u.startsWith(k)))
      .sort((a, b) => b.length - a.length);
    if (!keys.length) {
      return { ok: false, status: 404,
               json: async () => ({ error: "not found" }),
               text: async () => "not found" };
    }
    let payload = routes[keys[0]];
    if (typeof payload === "function") payload = payload(u, opts);
    return { ok: true, status: 200,
             json: async () => payload,
             text: async () => JSON.stringify(payload) };
  }

  class FakeAbortController {
    constructor() { this.signal = { aborted: false }; }
    abort() { this.signal.aborted = true; }
  }

  const timers = [];
  const g = globalThis;
  g.AbortController = g.AbortController || FakeAbortController;
  g.document = doc;
  g.localStorage = localStorage;
  g.fetch = fetch;
  g.alert = () => {};
  g.confirm = () => true;
  g.setInterval = (fn, ms) => { timers.push({ fn, ms }); return timers.length; };
  g.clearInterval = () => {};
  return { doc, byId, fetchLog, timers, routes };
}
