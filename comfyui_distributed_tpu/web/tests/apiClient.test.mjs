// node:test suite for apiClient's pure helpers (URL normalization and
// the auth-token storage contract). fetch-dependent request() paths are
// covered by the Python route tests (tests/test_api.py, tests/test_web.py).
import assert from "node:assert/strict";
import { test } from "node:test";

// localStorage shim: apiClient reads it lazily inside functions
const store = new Map();
globalThis.localStorage = {
  getItem: (k) => (store.has(k) ? store.get(k) : null),
  setItem: (k, v) => store.set(k, String(v)),
  removeItem: (k) => store.delete(k),
};

const { normalizeAddress, getAuthToken, setAuthToken } =
  await import("../apiClient.js");

test("normalizeAddress schemes and cloud-https heuristics", () => {
  assert.equal(normalizeAddress("10.0.0.2:8288"), "http://10.0.0.2:8288");
  assert.equal(normalizeAddress("http://h:1/"), "http://h:1");
  assert.equal(normalizeAddress(""), "");
  assert.equal(
    normalizeAddress("foo.trycloudflare.com"),
    "https://foo.trycloudflare.com");
  // http:// on a cloud domain upgrades to https
  assert.equal(
    normalizeAddress("http://x.ngrok-free.app"),
    "https://x.ngrok-free.app");
});

test("auth token storage round-trip", () => {
  assert.equal(getAuthToken(), "");
  setAuthToken("tok-1");
  assert.equal(getAuthToken(), "tok-1");
  setAuthToken("");
  assert.equal(getAuthToken(), "");
});
