// node:test suite for the progress poll state machine (progressLogic.js).
import assert from "node:assert/strict";
import { test } from "node:test";

import { MAX_MISSES, newPollState, pollTick, progressLabel } from "../progressLogic.js";

test("progressLabel covers running/done/failed", () => {
  assert.equal(progressLabel({ step: 3, total: 30 }), "step 3/30");
  assert.equal(progressLabel({ step: 30, total: 30, done: true }),
               "done (30 steps)");
  assert.equal(progressLabel({ step: 5, total: 30, failed: true }),
               "failed at step 5/30");
});

test("misses show queued… and give up after MAX_MISSES", () => {
  const st = newPollState();
  const t1 = pollTick(st, null);
  assert.equal(t1.label, "queued…");
  assert.equal(t1.stop, false);
  st.misses = MAX_MISSES;            // fast-forward
  const t2 = pollTick(st, null);
  assert.equal(t2.stop, true);
  assert.equal(t2.hide, true);
});

test("a snapshot resets the miss counter", () => {
  const st = newPollState();
  pollTick(st, null);
  pollTick(st, null);
  assert.equal(st.misses, 2);
  pollTick(st, { step: 1, total: 4, fraction: 0.25 });
  assert.equal(st.misses, 0);
});

test("preview refetches only on a NEW step", () => {
  const st = newPollState();
  const snap = { step: 1, total: 4, fraction: 0.25 };
  assert.equal(pollTick(st, snap).refetchPreview, true);
  assert.equal(pollTick(st, snap).refetchPreview, false);   // same step
  assert.equal(pollTick(st, { ...snap, step: 2, fraction: 0.5 })
    .refetchPreview, true);
  // step 0 (no events yet) never refetches
  const st2 = newPollState();
  assert.equal(pollTick(st2, { step: 0, total: 4, fraction: 0 })
    .refetchPreview, false);
});

test("done stops polling with a full bar", () => {
  const st = newPollState();
  const t = pollTick(st, { step: 4, total: 4, fraction: 1, done: true });
  assert.equal(t.stop, true);
  assert.equal(t.hide, false);
  assert.equal(t.widthPct, 100);
  assert.equal(t.label, "done (4 steps)");
});

test("failed freezes the bar where it stopped and keeps it visible", () => {
  const st = newPollState();
  const t = pollTick(st, { step: 5, total: 30, fraction: 5 / 30,
                           failed: true });
  assert.equal(t.widthPct, 17);
  assert.equal(t.label, "failed at step 5/30");
  assert.equal(t.hide, false);
});
