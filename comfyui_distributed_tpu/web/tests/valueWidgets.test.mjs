// node:test suite for the DistributedValue widget logic (valueWidgets.js)
// — the coercion/resync/serialization surface the reference covers with
// vitest over web/distributedValue.js.
import assert from "node:assert/strict";
import { test } from "node:test";

import {
  coerceWorkerValue,
  distributedValueNodes,
  hostsWithConfigIndex,
  orphanedKeys,
  parseWorkerValues,
  serializeWorkerValues,
  setWorkerValue,
  valueType,
  workerKey,
} from "../valueWidgets.js";

const CONFIG = {
  hosts: [
    { id: "w0", enabled: true },
    { id: "w1", enabled: false },
    { id: "w2", enabled: true },
  ],
};

test("hostsWithConfigIndex keeps full-list positions for enabled hosts", () => {
  const hosts = hostsWithConfigIndex(CONFIG);
  assert.equal(hosts.length, 2);
  assert.deepEqual(hosts.map(([w]) => w.id), ["w0", "w2"]);
  // w2 keeps position 2 even though w1 is disabled — disabling one host
  // must not renumber the others (stable worker_index contract)
  assert.deepEqual(hosts.map(([, i]) => i), [0, 2]);
  assert.equal(workerKey(2), "3");          // 1-indexed
  assert.deepEqual(hostsWithConfigIndex(null), []);
});

test("distributedValueNodes filters by class", () => {
  const prompt = {
    1: { class_type: "DistributedValue", inputs: {} },
    2: { class_type: "SaveImage", inputs: {} },
    3: { class_type: "DistributedValue", inputs: {} },
  };
  assert.deepEqual(distributedValueNodes(prompt).map(([id]) => id),
                   ["1", "3"]);
  assert.deepEqual(distributedValueNodes(null), []);
});

test("parseWorkerValues tolerates corrupt input", () => {
  assert.deepEqual(parseWorkerValues('{"1": 5}'), { 1: 5 });
  assert.deepEqual(parseWorkerValues(""), {});
  assert.deepEqual(parseWorkerValues(undefined), {});
  assert.deepEqual(parseWorkerValues("not json"), {});
  assert.deepEqual(parseWorkerValues("[1,2]"), {});   // array is not a map
  assert.deepEqual(parseWorkerValues("null"), {});
});

test("valueType: explicit input wins over recorded _type", () => {
  assert.equal(valueType({ value_type: "int" }, { _type: "FLOAT" }), "INT");
  assert.equal(valueType({}, { _type: "FLOAT" }), "FLOAT");
  assert.equal(valueType({}, {}), "");
  assert.equal(valueType(null, null), "");
});

test("coerceWorkerValue by declared type", () => {
  assert.equal(coerceWorkerValue("INT", "42"), 42);
  assert.equal(coerceWorkerValue("FLOAT", "2.5"), 2.5);
  assert.equal(coerceWorkerValue("BOOLEAN", "true"), true);
  assert.equal(coerceWorkerValue("BOOLEAN", "0"), false);
  assert.equal(coerceWorkerValue("", "free text"), "free text");
  assert.equal(coerceWorkerValue("STRING", "7"), "7");
});

test("coerceWorkerValue rejects NaN-producing input (would serialize null)", () => {
  // '3O' typo'd for '30': NaN would JSON.stringify as null and fail the
  // job at DistributedValue._coerce — must throw at the form instead
  assert.throws(() => coerceWorkerValue("INT", "3O"), /not a number/);
  assert.throws(() => coerceWorkerValue("INT", "1.5"), /not an integer/);
  assert.throws(() => coerceWorkerValue("FLOAT", "abc"), /not a number/);
  // empty string never reaches coercion (setWorkerValue clears first),
  // but reject it anyway if called directly
  assert.throws(() => coerceWorkerValue("FLOAT", " "), /not a number/);
});

test("setWorkerValue sets, coerces, and tags _type", () => {
  const m = setWorkerValue({}, "1", "99", "INT");
  assert.deepEqual(m, { 1: 99, _type: "INT" });
  setWorkerValue(m, "3", "7", "INT");
  assert.equal(m["3"], 7);
});

test("setWorkerValue: empty string clears the override", () => {
  const m = { 1: 5, 2: 6, _type: "INT" };
  setWorkerValue(m, "1", "", "INT");
  assert.deepEqual(m, { 2: 6, _type: "INT" });
  // clearing the last value drops the _type tag too
  setWorkerValue(m, "2", "", "INT");
  assert.deepEqual(m, {});
});

test("setWorkerValue without a type never writes _type", () => {
  const m = setWorkerValue({}, "1", "anything", "");
  assert.deepEqual(m, { 1: "anything" });
});

test("serializeWorkerValues round-trips through parse", () => {
  const m = setWorkerValue({}, "2", "1.25", "FLOAT");
  const s = serializeWorkerValues(m);
  assert.deepEqual(parseWorkerValues(s), { 2: 1.25, _type: "FLOAT" });
});

test("orphanedKeys flags entries beyond the host list", () => {
  const m = { 1: "a", 3: "b", 7: "c", _type: "STRING", junk: "d" };
  assert.deepEqual(orphanedKeys(m, CONFIG), ["7", "junk"]);
  assert.deepEqual(orphanedKeys({}, CONFIG), []);
  assert.deepEqual(orphanedKeys({ 1: "a" }, { hosts: [] }), ["1"]);
});
