// node:test suite for main.js ITSELF (r04 VERDICT weak #3: the 741-LoC
// DOM controller had zero tests; only the extracted logic modules did).
// A minimal DOM/browser shim (domShim.mjs) is installed before the
// module import, so init() runs for real: config load, worker-card
// render, status polling wiring, queue submit, progress tracking.

import assert from "node:assert/strict";
import { test } from "node:test";

import { installDom } from "./domShim.mjs";

const CONFIG = {
  master: { host: "127.0.0.1", port: 8288 },
  hosts: [
    { id: "w0", name: "alpha", address: "http://127.0.0.1:9001",
      enabled: true, type: "local" },
    { id: "w1", name: "beta", address: "http://127.0.0.1:9002",
      enabled: false, type: "remote" },
  ],
  settings: { debug: true },
};

const PROMPT = {
  1: { class_type: "CheckpointLoader", inputs: { ckpt_name: "tiny" } },
  2: { class_type: "SaveImage", inputs: { images: ["1", 0] } },
};

// one shim + one module import for the whole file: main.js wires module-
// level state on import (the browser does the same — one page, one init)
const dom = installDom({
  routes: {
    "/distributed/object_info": { nodes: {
      SaveImage: { required: { images: "IMAGE" }, optional: {},
                   returns: [], output_node: true, category: "x" },
    } },
    "/distributed/config": CONFIG,
    "/distributed/local-worker-status": { workers: {
      w0: { online: true, queue_remaining: 2, launching: false },
    } },
    "/distributed/health": { status: "ok", machine_id: "m0",
                             queue_remaining: 0 },
    "/distributed/managed_workers": { workers: {} },
    "/distributed/tunnel/status": { running: false },
    "/distributed/workflows": { workflows: ["distributed-txt2img"] },
    "/distributed/queue": { prompt_id: "p_test_1", number: 0,
                            node_errors: [], worker_count: 1 },
    "/distributed/progress": { step: 5, total: 10, fraction: 0.5 },
  },
});

await import("../main.js");
// init() is async fire-and-forget at module tail; let it settle
await new Promise((r) => setTimeout(r, 50));

const $ = (id) => dom.doc.getElementById(id);

test("init loads config and renders one card per host", () => {
  const cards = $("worker-cards").children;
  assert.equal(cards.length, 2);
});

test("worker-card lifecycle: status dot and meta reflect polling", () => {
  const cards = $("worker-cards").children;
  // card = [dot, info, toggle, buttons] (workerCard append order)
  const dotOnline = cards[0].children[0];
  assert.ok(dotOnline.className.includes("busy"),
            `w0 has queue 2 → busy dot, got "${dotOnline.className}"`);
  const dotOffline = cards[1].children[0];
  assert.ok(dotOffline.className.includes("offline"));
  // master dot reflects /distributed/health
  assert.ok($("master-dot").className.includes("online"));
  assert.ok($("master-label").textContent.includes("m0"));
});

test("queue submit posts the prompt and starts progress tracking", async () => {
  $("queue-prompt").value = JSON.stringify(PROMPT);
  $("queue-loadbalance").checked = true;
  const before = dom.fetchLog.length;
  assert.equal(typeof $("queue-form").onsubmit, "function");
  await $("queue-form").onsubmit({ preventDefault() {} });
  const calls = dom.fetchLog.slice(before).map((c) => c.url);
  const queueCall = dom.fetchLog.slice(before).find(
    (c) => c.url.includes("/distributed/queue"));
  assert.ok(queueCall, `no queue POST in ${JSON.stringify(calls)}`);
  const body = JSON.parse(queueCall.opts.body);
  assert.deepEqual(body.prompt, PROMPT);
  assert.equal(body.load_balance, true);
  assert.ok($("queue-result").textContent.includes("p_test_1"));
  // trackProgress armed a poll interval and reset the bar
  assert.ok(dom.timers.length >= 1);
  assert.equal($("job-progress").hidden, false);
  assert.equal($("job-progress-bar").style.width, "0%");
});

test("progress poll tick updates the bar from /distributed/progress", async () => {
  const pollFns = dom.timers.map((t) => t.fn);
  const progressPoll = pollFns[pollFns.length - 1];
  await progressPoll();
  assert.equal($("job-progress-bar").style.width, "50%");
  assert.ok($("job-progress-label").textContent.length > 0);
});

test("invalid JSON is reported without a network call", async () => {
  $("queue-prompt").value = "{broken";
  const before = dom.fetchLog.length;
  await $("queue-form").onsubmit({ preventDefault() {} });
  assert.ok($("queue-result").textContent.startsWith("Invalid JSON"));
  const queued = dom.fetchLog.slice(before).filter(
    (c) => c.url.includes("/distributed/queue"));
  assert.equal(queued.length, 0);
});

test("graph panel renders the loaded prompt as SVG", () => {
  $("queue-prompt").value = JSON.stringify(PROMPT);
  const input = $("queue-prompt").listeners.input;
  assert.ok(input && input.length, "textarea input listener wired");
  // fire the debounce immediately (timers are captured, not run)
  input.forEach((fn) => fn());
  // the debounce used setTimeout — run any captured macrotask manually
  return new Promise((resolve) => setTimeout(() => {
    const html = $("graph-panel").innerHTML;
    assert.ok(html.includes("<svg"), "graph svg rendered");
    assert.ok(html.includes("CheckpointLoader"));
    assert.ok(html.includes("graph-node-output"));  // SaveImage highlight
    assert.equal($("graph-panel").hidden, false);
    resolve();
  }, 450));
});
