// node:test suite for the workflow parameter-form logic (forms.js):
// field discovery from object_info specs, coercion, write-through edits.
import assert from "node:assert/strict";
import { test } from "node:test";

import {
  applyFieldEdit,
  coerceFieldValue,
  editableFields,
  fieldKind,
  groupByNode,
  isLink,
  isMultiline,
  lintPrompt,
} from "../forms.js";

const SPECS = {
  nodes: {
    TPUTxt2Img: {
      required: { model_name: "STRING", positive: "STRING", seed: "INT",
                  steps: "INT", cfg: "FLOAT", width: "INT", height: "INT" },
      optional: { negative: "STRING", tiled_vae: "BOOLEAN" },
      returns: ["IMAGE"],
    },
    SaveImage: {
      required: { images: "IMAGE", filename_prefix: "STRING" },
      optional: {},
      returns: [],
    },
    DistributedValue: {
      required: { default_value: "*" },
      optional: { worker_values: "STRING", value_type: "STRING" },
      returns: ["*"],
    },
    ImageBatchDivider: {
      required: { images: "IMAGE", divide_by: "INT" },
      optional: {},
      returns: ["IMAGE"],
    },
  },
};

const PROMPT = {
  1: { class_type: "TPUTxt2Img",
       inputs: { model_name: "sd15", positive: "a cat", seed: 7,
                 steps: 20, cfg: 7.5, width: 512, height: 512 } },
  2: { class_type: "SaveImage",
       inputs: { images: ["1", 0], filename_prefix: "out" } },
};

test("isLink recognizes graph edges only", () => {
  assert.ok(isLink(["1", 0]));
  assert.ok(!isLink([1, 0]));         // node id must be a string
  assert.ok(!isLink(["1", 0.5]));
  assert.ok(!isLink(["1", 0, 2]));
  assert.ok(!isLink("1"));
  assert.ok(!isLink(null));
});

test("fieldKind maps ComfyUI scalar types, rejects the rest", () => {
  assert.equal(fieldKind("INT"), "int");
  assert.equal(fieldKind("FLOAT"), "float");
  assert.equal(fieldKind("STRING"), "string");
  assert.equal(fieldKind("BOOLEAN"), "boolean");
  assert.equal(fieldKind("IMAGE"), null);
  assert.equal(fieldKind("*"), null);
  assert.equal(fieldKind(undefined), null);
});

test("editableFields discovers scalars, skips links", () => {
  const fields = editableFields(PROMPT, SPECS);
  const names = fields.map((f) => `${f.nodeId}.${f.name}`);
  assert.ok(names.includes("1.seed"));
  assert.ok(names.includes("1.positive"));
  assert.ok(names.includes("2.filename_prefix"));
  assert.ok(!names.includes("2.images"));         // link
  const seed = fields.find((f) => f.nodeId === "1" && f.name === "seed");
  assert.equal(seed.kind, "int");
  assert.equal(seed.value, 7);
  assert.equal(seed.optional, false);
});

test("editableFields includes unset optional fields with null value", () => {
  const fields = editableFields(PROMPT, SPECS);
  const neg = fields.find((f) => f.nodeId === "1" && f.name === "negative");
  assert.ok(neg);
  assert.equal(neg.value, null);
  assert.equal(neg.optional, true);
});

test("editableFields skips widgeted fields (worker_values, divide_by)", () => {
  const prompt = {
    5: { class_type: "DistributedValue",
         inputs: { default_value: 1, worker_values: "{}", value_type: "INT" } },
    6: { class_type: "ImageBatchDivider",
         inputs: { images: ["1", 0], divide_by: 2 } },
  };
  const names = editableFields(prompt, SPECS).map((f) => f.name);
  assert.ok(!names.includes("worker_values"));
  assert.ok(!names.includes("divide_by"));
  assert.ok(names.includes("value_type"));   // plain STRING, still editable
});

test("editableFields tolerates unknown classes and junk prompts", () => {
  assert.deepEqual(editableFields(null, SPECS), []);
  assert.deepEqual(editableFields({ 9: { class_type: "Nope", inputs: {} } },
                                  SPECS), []);
  assert.deepEqual(editableFields(PROMPT, null), []);
});

test("coerceFieldValue: int validates integrality", () => {
  assert.equal(coerceFieldValue("int", "42"), 42);
  assert.equal(coerceFieldValue("int", "-3"), -3);
  assert.throws(() => coerceFieldValue("int", "1.5"), /not an integer/);
  assert.throws(() => coerceFieldValue("int", "junk"), /not an integer/);
});

test("coerceFieldValue: cleared numeric fields reject (Number('')===0 trap)", () => {
  // deleting the value in a steps/seed field must NOT write 0
  assert.throws(() => coerceFieldValue("int", ""), /not an integer/);
  assert.throws(() => coerceFieldValue("int", "   "), /not an integer/);
  assert.throws(() => coerceFieldValue("float", ""), /not a number/);
  assert.equal(coerceFieldValue("string", ""), "");   // strings may clear
});

test("coerceFieldValue: float and boolean and string", () => {
  assert.equal(coerceFieldValue("float", "7.5"), 7.5);
  assert.throws(() => coerceFieldValue("float", "abc"), /not a number/);
  assert.equal(coerceFieldValue("boolean", true), true);
  assert.equal(coerceFieldValue("boolean", "true"), true);
  assert.equal(coerceFieldValue("boolean", "false"), false);
  assert.equal(coerceFieldValue("string", 5), "5");
});

test("applyFieldEdit writes through to the prompt", () => {
  const prompt = JSON.parse(JSON.stringify(PROMPT));
  const v = applyFieldEdit(prompt, "1", "seed", "int", "123");
  assert.equal(v, 123);
  assert.equal(prompt[1].inputs.seed, 123);
  applyFieldEdit(prompt, "1", "negative", "string", "blurry");
  assert.equal(prompt[1].inputs.negative, "blurry");
});

test("applyFieldEdit rejects bad values without mutating", () => {
  const prompt = JSON.parse(JSON.stringify(PROMPT));
  assert.throws(() => applyFieldEdit(prompt, "1", "steps", "int", "a lot"));
  assert.equal(prompt[1].inputs.steps, 20);     // untouched
  assert.throws(() => applyFieldEdit(prompt, "99", "x", "int", "1"),
                /no node 99/);
});

test("isMultiline flags prompt-ish strings and long values", () => {
  assert.ok(isMultiline({ kind: "string", name: "positive_prompt", value: "" }));
  assert.ok(isMultiline({ kind: "string", name: "text", value: "" }));
  assert.ok(isMultiline({ kind: "string", name: "other",
                          value: "x".repeat(80) }));
  assert.ok(!isMultiline({ kind: "string", name: "filename_prefix",
                           value: "out" }));
  assert.ok(!isMultiline({ kind: "int", name: "text", value: 5 }));
});

test("lintPrompt: clean prompt has no issues", () => {
  assert.deepEqual(lintPrompt(PROMPT, SPECS), []);
  assert.deepEqual(lintPrompt(null, SPECS), []);
});

test("lintPrompt mirrors validate_prompt error classes", () => {
  const prompt = {
    1: { inputs: {} },                                   // no class_type
    2: { class_type: "Bogus", inputs: {} },              // unknown class
    3: { class_type: "SaveImage",
         inputs: { images: ["9", 0] } },    // dangling + missing required
    4: { class_type: "SaveImage",
         inputs: { images: ["3", 5], filename_prefix: "x" } },  // bad idx
  };
  const issues = lintPrompt(prompt, SPECS);
  const byNode = (id) => issues.filter((i) => i.nodeId === id);
  assert.match(byNode("1")[0].message, /class_type/);
  assert.match(byNode("2")[0].message, /unknown node class/);
  const n3 = byNode("3").map((i) => i.message).join("; ");
  assert.match(n3, /missing required input filename_prefix/);
  assert.match(n3, /links to missing node 9/);
  assert.match(byNode("4")[0].message, /output 5 of SaveImage which has 0/);
  assert.ok(issues.every((i) => i.level === "error"));
});

test("lintPrompt skips _meta keys (raw pasted workflow files)", () => {
  const prompt = {
    _meta: { title: "shipped workflow" },
    1: { class_type: "ImageBatchDivider",
         inputs: { images: [[0.5]], divide_by: 2 } },
    2: { class_type: "SaveImage",
         inputs: { images: ["1", 0], filename_prefix: "x" } },
  };
  assert.deepEqual(lintPrompt(prompt, SPECS), []);
});

test("lintPrompt flags cycles like the server's topo_order", () => {
  const prompt = {
    a: { class_type: "SaveImage",
         inputs: { images: ["b", 0], filename_prefix: "x" } },
    b: { class_type: "SaveImage",
         inputs: { images: ["a", 0], filename_prefix: "x" } },
  };
  const issues = lintPrompt(prompt, SPECS)
    .filter((i) => /cycle/.test(i.message));
  assert.equal(issues.length, 1);
  assert.equal(issues[0].level, "error");
  // acyclic chain stays clean
  const chain = {
    a: { class_type: "ImageBatchDivider",
         inputs: { images: ["b", 0], divide_by: 2 } },
    b: { class_type: "SaveImage",
         inputs: { images: ["c", 0], filename_prefix: "x" } },
    c: { class_type: "SaveImage",
         inputs: { images: [1, 2, 3], filename_prefix: "x" } },
  };
  assert.ok(!lintPrompt(chain, SPECS).some((i) => /cycle/.test(i.message)));
});

test("lintPrompt warns on undeclared inputs, stays quiet without specs", () => {
  const prompt = {
    1: { class_type: "SaveImage",
         inputs: { images: [[0.5]], filename_prefix: "x", typo_arg: 1 } },
  };
  const issues = lintPrompt(prompt, SPECS);
  assert.equal(issues.length, 1);
  assert.equal(issues[0].level, "warning");
  assert.match(issues[0].message, /typo_arg is not declared/);
  // no specs loaded (older controller): unknown classes aren't flagged
  assert.deepEqual(lintPrompt(prompt, null), []);
});

test("groupByNode preserves prompt order and node identity", () => {
  const groups = groupByNode(editableFields(PROMPT, SPECS));
  assert.equal(groups.length, 2);
  assert.equal(groups[0].nodeId, "1");
  assert.equal(groups[0].classType, "TPUTxt2Img");
  assert.ok(groups[0].fields.length >= 7);
  assert.equal(groups[1].nodeId, "2");
});
