// node:test suite for the read-only DAG view (graphView.js) — pure
// logic + SVG-string rendering, no DOM needed.
import assert from "node:assert/strict";
import { test } from "node:test";

import {
  graphModel,
  graphSvgFromText,
  layoutGraph,
  renderGraphSvg,
} from "../graphView.js";

const PROMPT = {
  _meta: { title: "ignored" },
  1: { class_type: "CheckpointLoader", inputs: { ckpt_name: "tiny" } },
  2: { class_type: "CLIPTextEncode",
       inputs: { text: "a cat", clip: ["1", 1] } },
  3: { class_type: "CLIPTextEncode", inputs: { text: "", clip: ["1", 1] } },
  4: { class_type: "TPUTxt2Img",
       inputs: { model: ["1", 0], positive: ["2", 0], negative: ["3", 0],
                 seed: 7, steps: 30, width: 1024, height: 1024 } },
  5: { class_type: "SaveImage", inputs: { images: ["4", 0] } },
};

test("graphModel splits links from params and skips _meta", () => {
  const m = graphModel(PROMPT);
  assert.equal(m.nodes.length, 5);
  assert.equal(m.links.length, 6);     // 2 clip + 3 sampler + 1 save
  const sampler = m.nodes.find((n) => n.id === "4");
  assert.deepEqual(sampler.params.map(([k]) => k).sort(),
                   ["height", "seed", "steps", "width"]);
  const save = m.links.find((l) => l.to === "5");
  assert.deepEqual(save, { from: "4", fromSlot: 0, to: "5",
                           input: "images" });
});

test("graphModel tolerates malformed input", () => {
  assert.deepEqual(graphModel(null), { nodes: [], links: [] });
  assert.deepEqual(graphModel([1, 2]), { nodes: [], links: [] });
  assert.deepEqual(graphModel("x"), { nodes: [], links: [] });
  // dangling link target dropped, node kept
  const m = graphModel({ 1: { class_type: "SaveImage",
                              inputs: { images: ["9", 0] } } });
  assert.equal(m.nodes.length, 1);
  assert.equal(m.links.length, 0);
});

test("layoutGraph layers follow the longest path", () => {
  const { pos } = layoutGraph(graphModel(PROMPT));
  const x = (id) => pos.get(id).x;
  assert.ok(x("1") < x("2"));          // loader left of encoders
  assert.ok(x("2") < x("4"));          // encoders left of sampler
  assert.ok(x("4") < x("5"));          // sampler left of save
  assert.equal(x("2"), x("3"));        // both encoders share a column
  assert.notEqual(pos.get("2").y, pos.get("3").y);  // distinct rows
});

test("layoutGraph survives a cycle without hanging", () => {
  const m = graphModel({
    a: { class_type: "X", inputs: { v: ["b", 0] } },
    b: { class_type: "X", inputs: { v: ["a", 0] } },
  });
  const { pos } = layoutGraph(m);
  assert.equal(pos.size, 2);
});

test("renderGraphSvg emits one group per node and one path per link", () => {
  const m = graphModel(PROMPT);
  const svg = renderGraphSvg(m, new Set(["SaveImage"]));
  assert.equal((svg.match(/<g class="graph-node/g) || []).length, 5);
  assert.equal((svg.match(/graph-link/g) || []).length, 6);
  assert.ok(svg.includes("graph-node-output"));   // SaveImage highlighted
  assert.ok(svg.includes("4 · TPUTxt2Img"));
  assert.ok(svg.includes("seed=7"));
});

test("renderGraphSvg escapes hostile strings", () => {
  const m = graphModel({
    1: { class_type: "<script>alert(1)</script>",
         inputs: { t: '"><img onerror=x>' } },
  });
  const svg = renderGraphSvg(m);
  assert.ok(!svg.includes("<script>"));
  assert.ok(!svg.includes("<img"));
});

test("graphSvgFromText handles empty and invalid JSON", () => {
  assert.equal(graphSvgFromText(""), "");
  assert.equal(graphSvgFromText("   "), "");
  assert.equal(graphSvgFromText("{not json"), "");
  assert.equal(graphSvgFromText("{}"), "");
  const svg = graphSvgFromText(JSON.stringify(PROMPT));
  assert.ok(svg.startsWith("<svg"));
  assert.ok(svg.endsWith("</svg>"));
});

test("param summary truncates long values", () => {
  const m = graphModel({
    1: { class_type: "CLIPTextEncode",
         inputs: { text: "a very long prompt that keeps going on" } },
  });
  const svg = renderGraphSvg(m);
  assert.ok(svg.includes("…"));
});
