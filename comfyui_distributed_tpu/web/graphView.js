// Read-only DAG view of the loaded workflow (r04 VERDICT next-round #6:
// the dashboard edited parameters but could not SHOW the graph a user is
// about to queue — the reference gets a full canvas from ComfyUI).
// Pure logic + SVG-string rendering, DOM-free so node:test can exercise
// every path (scripts/test-web.sh), same discipline as widgets.js.

const NODE_W = 168;
const NODE_H = 54;
const GAP_X = 64;
const GAP_Y = 24;
const PAD = 16;

function isLink(v) {
  return Array.isArray(v) && v.length === 2 &&
    typeof v[0] === "string" && Number.isInteger(v[1]);
}

// prompt JSON → {nodes, links}; tolerant of malformed input (returns
// empty model rather than throwing — the textarea is user-edited)
export function graphModel(prompt) {
  if (!prompt || typeof prompt !== "object" || Array.isArray(prompt)) {
    return { nodes: [], links: [] };
  }
  const nodes = [];
  const links = [];
  for (const [id, node] of Object.entries(prompt)) {
    if (id === "_meta" || !node || typeof node !== "object") continue;
    const inputs = node.inputs || {};
    const params = [];
    for (const [name, value] of Object.entries(inputs)) {
      if (isLink(value)) {
        links.push({ from: value[0], fromSlot: value[1], to: id, input: name });
      } else {
        params.push([name, value]);
      }
    }
    nodes.push({
      id,
      classType: String(node.class_type || "?"),
      params,
      outputNode: false,          // filled by caller from object_info
    });
  }
  const ids = new Set(nodes.map((n) => n.id));
  return { nodes, links: links.filter((l) => ids.has(l.from)) };
}

// longest-path layering: every node sits one column right of its
// deepest upstream source; cycles (invalid but typeable) terminate via
// the visiting set instead of recursing forever
export function layoutGraph(model) {
  const upstream = new Map();     // id → [from ids]
  for (const n of model.nodes) upstream.set(n.id, []);
  for (const l of model.links) upstream.get(l.to).push(l.from);

  const depth = new Map();
  const visiting = new Set();
  function depthOf(id) {
    if (depth.has(id)) return depth.get(id);
    if (visiting.has(id)) return 0;             // cycle guard
    visiting.add(id);
    const ups = upstream.get(id) || [];
    const d = ups.length ? 1 + Math.max(...ups.map(depthOf)) : 0;
    visiting.delete(id);
    depth.set(id, d);
    return d;
  }
  const columns = new Map();      // depth → [node ids]
  for (const n of model.nodes) {
    const d = depthOf(n.id);
    if (!columns.has(d)) columns.set(d, []);
    columns.get(d).push(n.id);
  }
  const pos = new Map();
  for (const [d, ids] of columns) {
    ids.forEach((id, row) => {
      pos.set(id, {
        x: PAD + d * (NODE_W + GAP_X),
        y: PAD + row * (NODE_H + GAP_Y),
      });
    });
  }
  const nCols = columns.size;
  const nRows = Math.max(0, ...[...columns.values()].map((c) => c.length));
  return {
    pos,
    width: PAD * 2 + Math.max(nCols, 1) * NODE_W + (nCols - 1) * GAP_X,
    height: PAD * 2 + Math.max(nRows, 1) * NODE_H + (nRows - 1) * GAP_Y,
  };
}

function esc(s) {
  return String(s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
    .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}

function paramSummary(params, max = 2) {
  return params.slice(0, max).map(([k, v]) => {
    let text = typeof v === "string" ? v : JSON.stringify(v);
    if (text === undefined) text = "";
    if (text.length > 16) text = text.slice(0, 15) + "…";
    return `${k}=${text}`;
  }).join("  ");
}

// model + layout → one self-contained SVG string (no DOM needed; the
// dashboard injects it with innerHTML into the graph panel)
export function renderGraphSvg(model, outputClasses = new Set()) {
  const { pos, width, height } = layoutGraph(model);
  const parts = [
    `<svg class="graph-svg" viewBox="0 0 ${width} ${height}" ` +
    `width="${width}" height="${height}" xmlns="http://www.w3.org/2000/svg">`,
  ];
  for (const l of model.links) {
    const a = pos.get(l.from);
    const b = pos.get(l.to);
    if (!a || !b) continue;
    const x1 = a.x + NODE_W;
    const y1 = a.y + NODE_H / 2;
    const x2 = b.x;
    const y2 = b.y + NODE_H / 2;
    const mid = (x1 + x2) / 2;
    parts.push(
      `<path class="graph-link" d="M ${x1} ${y1} C ${mid} ${y1}, ` +
      `${mid} ${y2}, ${x2} ${y2}" fill="none"/>`);
  }
  for (const n of model.nodes) {
    const p = pos.get(n.id);
    if (!p) continue;
    const cls = "graph-node" +
      (outputClasses.has(n.classType) ? " graph-node-output" : "");
    parts.push(
      `<g class="${cls}" data-node-id="${esc(n.id)}">` +
      `<rect x="${p.x}" y="${p.y}" width="${NODE_W}" height="${NODE_H}" ` +
      `rx="6"/>` +
      `<text class="graph-title" x="${p.x + 8}" y="${p.y + 18}">` +
      `${esc(n.id)} · ${esc(n.classType)}</text>` +
      `<text class="graph-params" x="${p.x + 8}" y="${p.y + 38}">` +
      `${esc(paramSummary(n.params))}</text>` +
      `</g>`);
  }
  parts.push("</svg>");
  return parts.join("");
}

// convenience used by main.js: textarea text → SVG (or a short message
// for empty/invalid JSON)
export function graphSvgFromText(text, outputClasses = new Set()) {
  if (!text || !text.trim()) return "";
  let prompt;
  try {
    prompt = JSON.parse(text);
  } catch {
    return "";                    // the lint panel already reports errors
  }
  const model = graphModel(prompt);
  if (!model.nodes.length) return "";
  return renderGraphSvg(model, outputClasses);
}
