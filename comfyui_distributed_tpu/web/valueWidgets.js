// DistributedValue per-worker widget logic, DOM-free (extracted from
// main.js so node:test can cover it — VERDICT r3 next #8; parity:
// reference web/distributedValue.js:1-481, whose vitest suite covers the
// same coercion/resync/serialization surface).
//
// Contract (graph/nodes_builtin.py DistributedValue ←
// nodes/utilities.py:86-162): `worker_values` is a JSON object mapping
// 1-INDEXED positions in the FULL config host list to per-worker values;
// an optional `_type` key records the coercion type when any value is
// set. Enabled hosts are shown in the UI, but each keeps its
// config-position number — disabling host #1 must not renumber host #2.

export function distributedValueNodes(prompt) {
  if (!prompt || typeof prompt !== "object") return [];
  return Object.entries(prompt).filter(
    ([, n]) => n && n.class_type === "DistributedValue");
}

// [[host, configIndex], …] for enabled hosts, keeping full-list positions.
export function hostsWithConfigIndex(config) {
  return (((config || {}).hosts || []).map((w, i) => [w, i]))
    .filter(([w]) => w.enabled);
}

export function workerKey(configIndex) {
  return String(configIndex + 1);          // 1-indexed per reference
}

// inputs.worker_values (a JSON string) → mapping object; tolerant of
// missing/corrupt values (a hand-edited prompt must not brick the form).
export function parseWorkerValues(raw) {
  try {
    const m = JSON.parse(raw || "{}");
    return m && typeof m === "object" && !Array.isArray(m) ? m : {};
  } catch {
    return {};
  }
}

// The coercion type: explicit value_type input wins, else the mapping's
// recorded _type, else "" (opaque — values pass through as strings).
export function valueType(inputs, mapping) {
  return String((inputs && inputs.value_type) || (mapping && mapping._type)
    || "").toUpperCase();
}

export function coerceWorkerValue(vtype, raw) {
  if (vtype === "INT" || vtype === "FLOAT") {
    const n = Number(raw);
    // NaN would serialize as null and fail the job at execute time
    // (DistributedValue._coerce) — reject at the form instead
    if (!Number.isFinite(n) || (typeof raw === "string" && !raw.trim())) {
      throw new Error(`not a number: ${JSON.stringify(raw)}`);
    }
    if (vtype === "INT" && !Number.isInteger(n)) {
      throw new Error(`not an integer: ${JSON.stringify(raw)}`);
    }
    return n;
  }
  if (vtype === "BOOLEAN") {
    return raw === true || raw === "true" || raw === "1" || raw === 1;
  }
  return raw;
}

// Apply one per-worker edit: empty string clears the override (the worker
// falls back to default_value). Maintains the `_type` tag iff any real
// value remains. Mutates + returns the mapping.
export function setWorkerValue(mapping, key, raw, vtype) {
  if (raw === "" || raw === undefined || raw === null) delete mapping[key];
  else mapping[key] = coerceWorkerValue(vtype, raw);
  const hasValues = Object.keys(mapping).some((k) => k !== "_type");
  if (vtype && hasValues) mapping._type = vtype;
  else delete mapping._type;
  return mapping;
}

export function serializeWorkerValues(mapping) {
  return JSON.stringify(mapping);
}

// When the host set changes under a live form (auto-populate, delete),
// entries keyed beyond the config list are orphans the executor will
// never read — surfaced so the UI can warn instead of silently dropping.
export function orphanedKeys(mapping, config) {
  const hostCount = ((config || {}).hosts || []).length;
  return Object.keys(mapping || {}).filter((k) => {
    if (k === "_type") return false;
    const n = Number(k);
    return !Number.isInteger(n) || n < 1 || n > hostCount;
  });
}
