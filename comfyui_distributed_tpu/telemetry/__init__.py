"""Dependency-free observability: metrics registry, span tracing, export.

The subsystem the reference farm never had (SURVEY §5.1: "no timing
histograms, no flamegraphs") — fine-grained timing of compute vs.
communication is what lets a distributed stack find overlap opportunities
and diagnose concurrency ceilings (PAPERS.md: T3, arxiv 2401.16677;
TPU-concurrency limits, arxiv 2011.03641).

Three modules, stdlib-only by contract:

- ``registry``  — process-global, thread/async-safe Counter / Gauge /
  Histogram with frozen label tuples and a per-metric cardinality cap;
- ``spans``     — nesting span context managers over a ``contextvars``
  context, stitched across HTTP by the ``X-CDT-Trace`` header;
- ``export``    — Prometheus text exposition + structured JSON, both
  rendered from one ``snapshot()``.

``metrics`` declares the framework's standard families; instrumentation
sites import those objects and guard every record with ``enabled()`` —
``CDT_TELEMETRY=0`` turns the whole subsystem into one boolean read per
site. Served by ``GET /distributed/metrics`` (Prometheus),
``GET /distributed/metrics.json``, and ``GET /distributed/trace/{job_id}``
(assembled span tree). See ``docs/telemetry.md``.
"""

from .registry import (BYTES_BUCKETS, COMPILE_BUCKETS, DURATION_BUCKETS,
                       Counter, Gauge, Histogram, MetricRegistry, REGISTRY,
                       enabled, set_enabled)
from .spans import (STORE as SPAN_STORE, TRACE_HEADER, current_span_id,
                    current_trace_id, parse_trace_header, span,
                    trace_headers, use_trace)
from . import metrics  # noqa: F401  — declares the standard families

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram

__all__ = [
    "BYTES_BUCKETS", "COMPILE_BUCKETS", "DURATION_BUCKETS",
    "Counter", "Gauge", "Histogram", "MetricRegistry", "REGISTRY",
    "SPAN_STORE", "TRACE_HEADER", "counter", "current_span_id",
    "current_trace_id", "enabled", "gauge", "histogram", "metrics",
    "parse_trace_header", "set_enabled", "span", "trace_headers",
    "use_trace",
]
