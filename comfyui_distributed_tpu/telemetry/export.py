"""Exporters: Prometheus text exposition + structured JSON.

Both render the one ``MetricRegistry.snapshot()`` form, so the two views
can never disagree about what was measured.
"""

from __future__ import annotations

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    v = float(value)
    if v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _labelstr(labels: dict, extra: dict | None = None) -> str:
    merged = {**labels, **(extra or {})}
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def render_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition format 0.0.4 of a registry snapshot."""
    lines: list[str] = []
    for name in sorted(snapshot):
        m = snapshot[name]
        lines.append(f"# HELP {name} {_escape_help(m.get('help', ''))}")
        lines.append(f"# TYPE {name} {m['type']}")
        for s in m["series"]:
            labels = s.get("labels", {})
            if m["type"] == "histogram":
                for le, cum in s["buckets"]:
                    lines.append(
                        f"{name}_bucket"
                        f"{_labelstr(labels, {'le': _fmt(le)})} {cum}")
                lines.append(
                    f"{name}_bucket{_labelstr(labels, {'le': '+Inf'})} "
                    f"{s['count']}")
                lines.append(f"{name}_sum{_labelstr(labels)} "
                             f"{_fmt(s['sum'])}")
                lines.append(f"{name}_count{_labelstr(labels)} "
                             f"{s['count']}")
            else:
                lines.append(f"{name}{_labelstr(labels)} "
                             f"{_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


def render_json(snapshot: dict) -> dict:
    """Structured JSON form (the dashboard's feed): the snapshot verbatim
    under a ``metrics`` key, with a schema marker for forward-compat."""
    return {"format": "cdt.metrics.v1", "metrics": snapshot}
