"""Span tracing: nested wall-clock spans with cross-HTTP trace stitching.

``span("denoise_step", job_id=...)`` opens a timed span; spans nest via a
``contextvars.ContextVar`` so asyncio handlers and plain call stacks both
get correct parent linkage without threading anything through signatures.
Every finished span is recorded into the process-global ``STORE`` (bounded
ring of traces) and its duration lands in the ``cdt_span_seconds{name=…}``
histogram.

Cross-host stitching: an active span context serializes into the
``X-CDT-Trace`` header (``trace_id:span_id``) via ``trace_headers()``; the
receiving side parses it (``parse_trace_header``) and enters the same
trace with ``use_trace(trace_id, parent_span_id)`` — so a master's
dispatch span and the worker's execution span share one trace ID and a
real parent/child edge, and ``/distributed/trace/{job_id}`` can assemble
both sides into one timeline.

The orchestration layer's existing ``exec_…`` trace IDs are adopted
verbatim (``span(..., trace_id=…)``), so log lines and span trees
correlate on the same key.
"""

from __future__ import annotations

import contextvars
import secrets
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Optional

from .registry import REGISTRY, enabled

TRACE_HEADER = "X-CDT-Trace"

# (trace_id, span_id) of the innermost active span; span_id may be "" when
# only a remote parent context was adopted (use_trace without a local span)
_CTX: "contextvars.ContextVar[Optional[tuple[str, str]]]" = \
    contextvars.ContextVar("cdt_trace", default=None)

_SPAN_SECONDS = REGISTRY.histogram(
    "cdt_span_seconds",
    "Wall-clock duration of telemetry spans, by span name.",
    ("name",))

# span attributes that double as lookup keys for /distributed/trace/{id}
_INDEX_ATTRS = ("job_id", "prompt_id")


def new_trace_id() -> str:
    return f"trace_{int(time.time() * 1000)}_{secrets.token_hex(3)}"


class SpanStore:
    """Bounded in-memory ring of finished spans, grouped by trace.

    Oldest traces are evicted first; a single trace is capped so a runaway
    loop cannot grow one entry without bound. ``resolve`` maps a job or
    prompt id (seen as a span attribute) back to its trace."""

    def __init__(self, max_traces: int = 256, max_spans: int = 512):
        self.max_traces = max_traces
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, list[dict]]" = OrderedDict()
        self._by_key: dict[str, str] = {}

    def record(self, span: dict) -> None:
        tid = span["trace_id"]
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                spans = self._traces[tid] = []
                while len(self._traces) > self.max_traces:
                    old_tid, _ = self._traces.popitem(last=False)
                    for k in [k for k, v in self._by_key.items()
                              if v == old_tid]:
                        del self._by_key[k]
            if len(spans) < self.max_spans:
                spans.append(span)
            for attr in _INDEX_ATTRS:
                v = span.get("attrs", {}).get(attr)
                if v:
                    self._by_key[str(v)] = tid

    def resolve(self, key: str) -> Optional[str]:
        """Trace id for a trace id, job id, or prompt id."""
        with self._lock:
            if key in self._traces:
                return key
            return self._by_key.get(key)

    def spans(self, trace_id: str) -> list[dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def tree(self, trace_id: str) -> list[dict]:
        """Nested span forest (roots may be plural: master and worker both
        contribute top-level spans to one trace)."""
        spans = sorted(self.spans(trace_id), key=lambda s: s["start"])
        nodes = {s["span_id"]: {**s, "children": []} for s in spans}
        roots: list[dict] = []
        for s in spans:
            parent = nodes.get(s.get("parent_id") or "")
            target = parent["children"] if parent is not None else roots
            target.append(nodes[s["span_id"]])
        return roots

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._by_key.clear()


STORE = SpanStore()


@contextmanager
def span(name: str, trace_id: Optional[str] = None,
         parent_id: Optional[str] = None, **attrs):
    """Timed span context manager. No-op (yields ``None``) when telemetry
    is disabled — the guard is the first thing that runs, so the disabled
    hot path costs one boolean read.

    ``trace_id`` adopts an existing trace (e.g. the orchestrator's
    ``exec_…`` id); omitted, the span joins the ambient trace or starts a
    fresh one. ``parent_id`` overrides parent linkage for cross-process
    stitching (the worker's execution span parents onto the master's
    dispatch span id carried by ``X-CDT-Trace``)."""
    if not enabled():
        yield None
        return
    cur = _CTX.get()
    if trace_id is None:
        trace_id = cur[0] if cur else new_trace_id()
    if parent_id is None and cur and cur[0] == trace_id:
        parent_id = cur[1] or None
    span_id = secrets.token_hex(4)
    token = _CTX.set((trace_id, span_id))
    start = time.time()
    t0 = time.perf_counter()
    error = None
    try:
        yield (trace_id, span_id)
    except BaseException as e:
        error = f"{type(e).__name__}: {e}"
        raise
    finally:
        duration = time.perf_counter() - t0
        _CTX.reset(token)
        rec = {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "start": start,
            "duration_s": duration,
            "attrs": {k: str(v) for k, v in attrs.items()},
        }
        if error is not None:
            rec["error"] = error
        STORE.record(rec)
        _SPAN_SECONDS.labels(name=name).observe(duration)


@contextmanager
def use_trace(trace_id: str, parent_span_id: Optional[str] = None):
    """Adopt a remote trace context (parsed from ``X-CDT-Trace``) for the
    duration of the block: spans opened inside join ``trace_id`` with
    ``parent_span_id`` as their parent."""
    token = _CTX.set((trace_id, parent_span_id or ""))
    try:
        yield
    finally:
        _CTX.reset(token)


def current_trace_id() -> Optional[str]:
    cur = _CTX.get()
    return cur[0] if cur else None


def current_span_id() -> Optional[str]:
    cur = _CTX.get()
    return (cur[1] or None) if cur else None


def trace_headers() -> dict:
    """``{"X-CDT-Trace": "trace_id:span_id"}`` for the active context, or
    ``{}`` — safe to splat into any outbound request's headers."""
    if not enabled():
        return {}
    cur = _CTX.get()
    if not cur:
        return {}
    tid, sid = cur
    return {TRACE_HEADER: f"{tid}:{sid}" if sid else tid}


def parse_trace_header(value) -> Optional[tuple[str, Optional[str]]]:
    """``"trace_id[:span_id]"`` → ``(trace_id, span_id | None)``; None on
    anything malformed (headers are peer-controlled input)."""
    if not isinstance(value, str) or not value or len(value) > 200:
        return None
    tid, _, sid = value.partition(":")
    tid = tid.strip()
    if not tid:
        return None
    return tid, (sid.strip() or None)
