"""The framework's standard metric families, declared in one place.

Instrumentation sites import these objects (no stringly-typed lookups on
the hot path) and guard every use with ``telemetry.enabled()``. Naming
follows Prometheus conventions: ``cdt_`` prefix, base-unit suffixes
(``_seconds``, ``_bytes``), counters end in ``_total``.

Label conventions (kept deliberately low-cardinality):

- ``pipeline``: compiled-program family — ``txt2img``, ``img2img``,
  ``flow_dp``, ``flow_sp``, ``video_dp``, ``video_sp``, ``video_i2v``.
- ``event`` (tiles): ``seeded`` / ``assigned`` / ``completed`` /
  ``requeued`` / ``restored`` / ``timed_out``.
- ``transport``: ``http`` / ``ws``; ``outcome``: ``ok`` / ``error`` (or
  probe-specific ``online`` / ``offline``, eviction ``evicted`` /
  ``spared``).
"""

from __future__ import annotations

from .registry import (BYTES_BUCKETS, COMPILE_BUCKETS, REGISTRY)

# --- diffusion pipelines ----------------------------------------------------

SAMPLER_STEP_SECONDS = REGISTRY.histogram(
    "cdt_sampler_step_seconds",
    "Per-step sampler wall-clock (program wall-clock / ladder steps), by "
    "pipeline. The first observation per program includes its compile — "
    "cdt_pipeline_compile_seconds carries the split.",
    ("pipeline",))

PIPELINE_COMPILE_SECONDS = REGISTRY.histogram(
    "cdt_pipeline_compile_seconds",
    "First-call wall-clock of a compiled pipeline program (trace + XLA "
    "compile + first execution), by pipeline.",
    ("pipeline",), buckets=COMPILE_BUCKETS)

PIPELINE_EXECUTE_SECONDS = REGISTRY.histogram(
    "cdt_pipeline_execute_seconds",
    "Steady-state wall-clock of a compiled pipeline program (calls after "
    "the first), by pipeline.",
    ("pipeline",))

# --- attention kernel dispatch / autotune (ops/attention.py, ops/autotune.py)

ATTN_KERNEL_SELECTED = REGISTRY.counter(
    "cdt_attn_kernel_selected",
    "Attention kernel-tier selections at trace time, by tier "
    "(fused/packed/bh/xla) and geometry (hH.dD.qN.kvN.dtype — bucketed, "
    "so cardinality is bounded by the model zoo). Increments once per "
    "traced program per geometry; the dispatch decision is observable "
    "without a profiler.",
    ("tier", "geometry"))

AUTOTUNE_SWEEP_SECONDS = REGISTRY.histogram(
    "cdt_autotune_sweep_seconds",
    "Wall-clock of one attention autotune sweep (all candidates for one "
    "geometry). Runs off the request path — during warmup or the "
    "autotune_sweep.py CLI.",
    buckets=COMPILE_BUCKETS)

# --- tile farm --------------------------------------------------------------

TILE_EVENTS = REGISTRY.counter(
    "cdt_tile_tasks_total",
    "Tile-farm task lifecycle events.",
    ("event",))

TILE_QUEUE_DEPTH = REGISTRY.gauge(
    "cdt_tile_queue_depth",
    "Pending (unassigned) tile tasks across all live tile jobs.")

TILE_WORKER_EVICTIONS = REGISTRY.counter(
    "cdt_tile_worker_evictions_total",
    "Heartbeat-timeout verdicts on tile workers.",
    ("outcome",))   # evicted | spared | draining

# --- cluster dispatch / probing --------------------------------------------

DISPATCH_SECONDS = REGISTRY.histogram(
    "cdt_dispatch_seconds",
    "Prompt dispatch round-trip latency to a worker host.",
    ("transport", "outcome"))

DISPATCH_PAYLOAD_BYTES = REGISTRY.histogram(
    "cdt_dispatch_payload_bytes",
    "Serialized prompt payload size per dispatch.",
    ("transport",), buckets=BYTES_BUCKETS)

WORKER_PROBES = REGISTRY.counter(
    "cdt_worker_probe_total",
    "Worker health-probe outcomes (orchestration fan-out).",
    ("outcome",))   # online | offline | quarantined | draining

MEDIA_SYNC_FILES = REGISTRY.counter(
    "cdt_media_sync_files_total",
    "Per-file media sync outcomes (master -> remote host).",
    ("outcome",))   # uploaded | skipped | missing | failed

MEDIA_SYNC_BYTES = REGISTRY.counter(
    "cdt_media_sync_bytes_total",
    "Bytes uploaded by media sync.")

# --- resilience (cluster/resilience.py + cluster/faults.py) -----------------

BREAKER_STATE = REGISTRY.gauge(
    "cdt_worker_breaker_state",
    "Per-worker circuit breaker state (0=closed, 1=half-open, 2=open).",
    ("worker",))

BREAKER_TRANSITIONS = REGISTRY.counter(
    "cdt_worker_breaker_transitions_total",
    "Breaker state transitions by destination state.",
    ("to",))   # closed | half_open | open

RETRY_ATTEMPTS = REGISTRY.counter(
    "cdt_retry_attempts_total",
    "Retries performed by the unified RetryPolicy, by operation.",
    ("op",))   # dispatch | request_work | submit | collect | media | ...

FAULTS_INJECTED = REGISTRY.counter(
    "cdt_faults_injected_total",
    "Faults injected by the deterministic chaos harness (CDT_FAULTS).",
    ("op", "kind"))

# --- cold start: compile cache / warmup / residency -------------------------
# (utils/compile_cache.py, diffusion/warmup.py, cluster/residency.py)

COMPILE_CACHE_ENABLED = REGISTRY.gauge(
    "cdt_compile_cache_enabled",
    "1 when the persistent XLA compilation cache is active, 0 when "
    "disabled or unavailable (the reason is logged at enable time).")

WARMUP_PROGRAMS = REGISTRY.counter(
    "cdt_warmup_programs_total",
    "AOT warmup outcomes per catalog program.",
    ("outcome",))   # cache_hit | compiled | error | skipped

WARMUP_SECONDS = REGISTRY.histogram(
    "cdt_warmup_seconds",
    "Per-program AOT lower+compile wall-clock during warmup (cache hits "
    "land in the low buckets; fresh compiles in the high ones).",
    buckets=COMPILE_BUCKETS)

WARMUP_STATE = REGISTRY.gauge(
    "cdt_warmup_state",
    "Worker warmup state (0=cold, 1=warming, 2=ready, -1=error).")

RESIDENCY_EVICTIONS = REGISTRY.counter(
    "cdt_residency_evictions_total",
    "Model bundles evicted by the HBM residency planner.",
    ("reason",))   # budget | manual

RESIDENT_MODELS = REGISTRY.gauge(
    "cdt_resident_models",
    "Model bundles currently resident under the HBM residency planner.")

RESIDENT_BYTES = REGISTRY.gauge(
    "cdt_resident_bytes",
    "Estimated bytes of resident model bundles (planner accounting).")

# --- serving front door (cluster/frontdoor, docs/serving.md) ---------------

ADMISSION_TOTAL = REGISTRY.counter(
    "cdt_admission_total",
    "Front-door admission decisions. admitted = fast path; queued = "
    "accepted past the soft high-watermark; shed = refused with 429 + "
    "Retry-After (overload or tenant rate).",
    ("outcome", "priority"))   # admitted | queued | shed

BATCH_SIZE = REGISTRY.histogram(
    "cdt_batch_size",
    "Microbatch occupancy per executed sampler program (1 = solo "
    "pass-through). Mean > 1 means cross-user coalescing is working.",
    buckets=(1, 2, 4, 8, 16, 32, 64))

BATCH_FALLBACKS = REGISTRY.counter(
    "cdt_batch_fallbacks_total",
    "Microbatched programs that failed and fell back to per-member solo "
    "execution (admitted jobs are retried solo, never dropped).")

FD_QUEUE_DEPTH = REGISTRY.gauge(
    "cdt_fd_queue_depth",
    "Per-priority-class request depth by stage: coalescing (held in a "
    "front-door window) or queued (in the prompt queue).",
    ("stage", "priority"))

QUEUE_WAIT_SECONDS = REGISTRY.histogram(
    "cdt_queue_wait_seconds",
    "Time-in-queue per request (submission to execution start, "
    "coalescing window included), by priority class.",
    ("priority",))

# --- elastic fleet (cluster/elastic, docs/elasticity.md) --------------------

AUTOSCALE_DECISIONS = REGISTRY.counter(
    "cdt_autoscale_decisions_total",
    "Autoscaler verdicts per evaluation tick. direction=up|down|hold; "
    "reason names the dominant signal (queue_pressure, idle_fleet, "
    "cooldown, envelope_min, envelope_max, no_capacity, ...).",
    ("direction", "reason"))

WORKER_DRAIN_STATE = REGISTRY.gauge(
    "cdt_worker_drain_state",
    "Per-worker lifecycle state (0=active, 1=draining, 2=decommissioned). "
    "Intentional departure — never failure evidence for the breaker.",
    ("worker",))

FLEET_SIZE = REGISTRY.gauge(
    "cdt_fleet_size",
    "Workers known to the elastic manager, by lifecycle state.",
    ("state",))   # active | draining | decommissioned

DRAIN_HANDBACKS = REGISTRY.counter(
    "cdt_drain_handbacks_total",
    "Tile tasks handed back to the queue by a draining worker "
    "(deadline expiry or early exit) — requeued WITHOUT counting toward "
    "the poison bound.")

STEAL_ASSIGNMENTS = REGISTRY.counter(
    "cdt_steal_assignments_total",
    "Cross-job scheduler grants. kind=own_job (the job the puller named) "
    "or stolen (work lifted from another open job).",
    ("kind",))

# --- content-addressed cache (cluster/cache, docs/caching.md) ---------------

CACHE_HITS = REGISTRY.counter(
    "cdt_cache_hits_total",
    "Content-cache hits by tier (conditioning = a text-encode skipped; "
    "result = a whole sampler program skipped). Disk hits count here too "
    "— a hit is a hit wherever the bytes came from.",
    ("tier",))

CACHE_MISSES = REGISTRY.counter(
    "cdt_cache_misses_total",
    "Content-cache misses by tier (the computation ran and filled the "
    "entry).",
    ("tier",))

CACHE_BYTES = REGISTRY.gauge(
    "cdt_cache_bytes",
    "In-memory bytes held per cache tier (LRU under the "
    "CDT_CACHE_*_MAX_BYTES caps).",
    ("tier",))

CACHE_ENTRIES = REGISTRY.gauge(
    "cdt_cache_entries",
    "In-memory entries per cache tier.",
    ("tier",))

CACHE_EVICTIONS = REGISTRY.counter(
    "cdt_cache_evictions_total",
    "LRU evictions per cache tier (memory budget or persisted-tier cap).",
    ("tier",))

CACHE_CORRUPT = REGISTRY.counter(
    "cdt_cache_corrupt_total",
    "Persisted cache entries rejected at load: checksum mismatch or "
    "unreadable sidecar. Always followed by a recompute — corruption is "
    "never served.",
    ("tier",))

COALESCE_WIDTH = REGISTRY.histogram(
    "cdt_coalesce_width",
    "Requests answered per executed fingerprint (1 = no duplicates were "
    "in flight; N = one execution fanned out to N-1 waiters).",
    buckets=(1, 2, 4, 8, 16, 32, 64))

# --- fleet cache tier (cluster/cache/fleet.py, docs/caching.md) -------------

FLEET_CACHE_REMOTE = REGISTRY.counter(
    "cdt_fleet_cache_remote_total",
    "Fleet-tier remote operations by op (get = probe of the ring owner; "
    "put = async fill; handback = drain-time shard move) and outcome "
    "(hit / miss / error / skipped). Every error degrades to a local "
    "recompute — the ladder never turns a slow owner into a failed "
    "request.",
    ("op", "outcome"))

FLEET_RING_SIZE = REGISTRY.gauge(
    "cdt_fleet_ring_size",
    "Workers currently owning arcs on the fleet-cache consistent-hash "
    "ring (active members; draining workers leave before decommission).")

FLEET_NEAR_REUSE = REGISTRY.counter(
    "cdt_fleet_near_reuse_total",
    "Opt-in near-tier serves: a cache:\"near\" request resumed from a "
    "donor mid-trajectory checkpoint instead of denoising from pure "
    "noise. Never bit-identical — see docs/caching.md.")

FLEET_NEAR_STEPS_SAVED = REGISTRY.counter(
    "cdt_fleet_near_steps_saved_total",
    "Denoise steps the near tier skipped (donor checkpoint step count, "
    "summed over reuses).")

HASH_TOKENIZATION = REGISTRY.counter(
    "cdt_hash_tokenization_total",
    "Text encodes that used the deterministic hash-tokenization fallback "
    "(no BPE vocab loaded), by tower. Nonzero on a production worker "
    "means conditioning does not reflect the prompt — a boot-time log "
    "line made fleet-visible (models/clip.py).",
    ("tower",))

# --- step-granular preemption (cluster/preemption.py, docs/preemption.md) ---

PREEMPTIONS_TOTAL = REGISTRY.counter(
    "cdt_preemptions_total",
    "Jobs preempted at a denoise segment boundary, by reason "
    "(priority = a higher class was waiting; drain = the worker is "
    "leaving; manual = operator request). Intentional departure — never "
    "poison or breaker evidence.",
    ("reason",))

JOBS_PREEMPTED = REGISTRY.gauge(
    "cdt_jobs_preempted",
    "Jobs currently parked mid-denoise (checkpoint held, waiting to "
    "resume).")

CHECKPOINT_BYTES = REGISTRY.gauge(
    "cdt_checkpoint_bytes",
    "Bytes of latent checkpoints held, by tier (memory / persisted).",
    ("tier",))

RESUME_SECONDS = REGISTRY.histogram(
    "cdt_resume_seconds",
    "Restore-to-first-segment-complete wall-clock when a preempted job "
    "resumes from its checkpoint (device upload + one segment program).")

CHECKPOINT_DEAD_LETTERS = REGISTRY.counter(
    "cdt_checkpoint_dead_letters_total",
    "Checkpoints dead-lettered after exhausting the resume-retry bound "
    "(CDT_PREEMPT_RESUME_RETRIES) — the job restarts from scratch "
    "instead of looping on a checkpoint that cannot restore.")

# --- disaggregated stage-split serving (cluster/stages, docs/stages.md) -----

STAGE_QUEUE_DEPTH = REGISTRY.gauge(
    "cdt_stage_queue_depth",
    "Work items queued per serving stage pool (encode / denoise / "
    "decode). Each pool scales on ITS OWN depth — a decode backlog must "
    "never read as denoise pressure (docs/stages.md).",
    ("stage",))

STAGE_OCCUPANCY = REGISTRY.gauge(
    "cdt_stage_occupancy",
    "Fraction of a stage pool's workers currently busy (0..1). The "
    "denoise pool's value is the number the whole refactor exists to "
    "raise — the mesh should spend its time denoising, not encoding or "
    "decoding.",
    ("stage",))

STAGE_JOBS = REGISTRY.counter(
    "cdt_stage_jobs_total",
    "Work items completed per stage pool, by outcome (ok / error / "
    "redispatch — redispatch = a dead worker's items re-queued to a "
    "survivor, bounded by CDT_STAGE_MAX_REDISPATCH).",
    ("stage", "outcome"))

STAGE_STEALS = REGISTRY.counter(
    "cdt_stage_steals_total",
    "Cross-stage steals: an idle host-side stage worker served the "
    "deepest sibling stage's queue (the PR 7 most-starved-first idiom "
    "generalized across stages).",
    ("src", "dst"))

DECODE_BATCH_SIZE = REGISTRY.histogram(
    "cdt_decode_batch_size",
    "Latents decoded per executed VAE program (cross-request decode "
    "coalescing per shape bucket). Mean > 1 means the decode pool is "
    "amortizing programs across concurrent requests.",
    buckets=(1, 2, 4, 8, 16, 32, 64))

LATENT_TRANSFER_BYTES = REGISTRY.histogram(
    "cdt_latent_transfer_bytes",
    "Bytes per denoise-to-decode latent handoff (host materialization, "
    "plus the checksummed wire round trip under CDT_STAGE_WIRE=1).",
    buckets=(4096, 65536, 1 << 20, 16 << 20, 256 << 20))

LATENT_TRANSFER_SECONDS = REGISTRY.histogram(
    "cdt_latent_transfer_seconds",
    "Wall-clock per latent handoff transfer — overlapped with the "
    "denoise pool's next program (T3-style), so this shows up in "
    "decode-lane latency, not denoise occupancy.")

# --- prompt queue -----------------------------------------------------------

PROMPTS_TOTAL = REGISTRY.counter(
    "cdt_prompts_total",
    "Prompt executions by terminal status.",
    ("status",))   # success | error | interrupted | expired

PROMPT_SECONDS = REGISTRY.histogram(
    "cdt_prompt_duration_seconds",
    "End-to-end graph execution wall-clock per prompt.")

PROMPT_QUEUE_DEPTH = REGISTRY.gauge(
    "cdt_prompt_queue_depth",
    "Prompts queued or executing on this controller.")

# --- HTTP control plane -----------------------------------------------------

HTTP_REQUESTS = REGISTRY.counter(
    "cdt_http_requests_total",
    "Control-plane requests by route template and status.",
    ("method", "path", "status"))

# --- worker monitor (standalone watchdog) ----------------------------------
# NOTE: when the monitor runs as its own OS process (the production
# launch path, workers/lifecycle.py) this family lives in THAT process
# and is not scrapable; it surfaces only when monitor_and_run is embedded
# in a serving process (tests, custom supervisors).

WORKER_MONITOR_CHECKS = REGISTRY.counter(
    "cdt_worker_monitor_checks_total",
    "Watchdog verdicts (master_died / worker_exit / signal).",
    ("outcome",))
