"""Process-global metrics registry: Counter / Gauge / Histogram.

Dependency-free (stdlib only) by design: the telemetry core must be
importable from anything — including the standalone worker monitor, which
may run from a bare file path — and must never pull jax/aiohttp into a
process that doesn't already have them.

Thread/async safety: child creation and every mutation happen under the
owning metric's lock (asyncio handlers and the graph-executor thread both
record into the same families). The hot-path guard is ``enabled()`` — one
module-global boolean read — so a disabled deployment (``CDT_TELEMETRY=0``)
pays a single attribute load per instrumentation site and nothing else:
no clock reads, no label lookups, no lock traffic.

Label sets are frozen at declaration (``labelnames``); per-series children
are keyed by the tuple of label *values* in declaration order. Cardinality
is capped per metric (``MAX_SERIES``): past the cap, new label sets
collapse into one ``~overflow~`` series and the drop is counted — a
runaway label (e.g. a per-request id) can degrade resolution but can
never leak memory without bound.
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional, Sequence

from ..lint.lockorder import tracked_lock
from ..utils.constants import TELEMETRY

_enabled = TELEMETRY.get()


def enabled() -> bool:
    """The cheap hot-path guard: instrumentation sites check this before
    doing any work (clock reads, serialization, label lookups)."""
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


# Fixed log-scale buckets (1-2.5-5 per decade) — chosen once so histograms
# from different hosts always merge bucket-for-bucket.
DURATION_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0, 150.0)
# compiles regularly take minutes on big models
COMPILE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
                   150.0, 300.0, 600.0, 1800.0)
BYTES_BUCKETS = (256.0, 1024.0, 4096.0, 16384.0, 65536.0, 262144.0,
                 1048576.0, 4194304.0, 16777216.0, 67108864.0, 268435456.0)

MAX_SERIES = 256
_OVERFLOW = "~overflow~"


class _CounterValue:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self.value += amount

    def snap(self) -> dict:
        return {"value": self.value}


class _GaugeValue:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def snap(self) -> dict:
        return {"value": self.value}


class _HistogramValue:
    __slots__ = ("_lock", "bounds", "counts", "sum", "count")

    def __init__(self, lock: threading.Lock, bounds: Sequence[float]):
        self._lock = lock
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)   # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if not _enabled:
            return
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def snap(self) -> dict:
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        cum = 0
        buckets = []
        for le, c in zip(self.bounds, counts):
            cum += c
            buckets.append([le, cum])
        return {"buckets": buckets, "sum": s, "count": total}


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = tracked_lock("telemetry.family")
        self._children: dict[tuple, object] = {}
        self._dropped = 0
        if not self.labelnames:
            self._children[()] = self._make_value()

    def _make_value(self):
        raise NotImplementedError

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labelvalues))}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= MAX_SERIES:
                    self._dropped += 1
                    key = (_OVERFLOW,) * len(self.labelnames)
                    child = self._children.get(key)
                    if child is None:
                        child = self._children[key] = self._make_value()
                    return child
                child = self._children[key] = self._make_value()
            return child

    # --- label-less convenience (mirrors prometheus_client) ----------------

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; "
                "use .labels(...)")
        return self._children[()]

    def series(self) -> list[tuple[dict, dict]]:
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child.snap())
                for key, child in items]

    def _reset(self) -> None:
        with self._lock:
            self._children = {}
            self._dropped = 0
            if not self.labelnames:
                self._children[()] = self._make_value()


class Counter(_Metric):
    kind = "counter"

    def _make_value(self):
        return _CounterValue(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)


class Gauge(_Metric):
    kind = "gauge"

    def _make_value(self):
        return _GaugeValue(self._lock)

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(),
                 buckets: Optional[Sequence[float]] = None):
        self.buckets = tuple(sorted(buckets or DURATION_BUCKETS))
        if not self.buckets:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        super().__init__(name, help, labelnames)

    def _make_value(self):
        return _HistogramValue(self._lock, self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)


class MetricRegistry:
    """Name-keyed metric collection; get-or-create is idempotent so every
    instrumentation site can declare the family it needs without import-
    order coupling (a re-declaration with a different type or label set is
    a programming error and raises)."""

    def __init__(self):
        self._lock = tracked_lock("telemetry.registry")
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-declared with a different "
                        f"type/labels (have {type(m).__name__}"
                        f"{m.labelnames})")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def snapshot(self) -> dict:
        """Structured export form — the single source both renderers
        (Prometheus text and JSON) consume."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        dropped = 0
        for m in metrics:
            dropped += m._dropped
            out[m.name] = {
                "type": m.kind,
                "help": m.help,
                "labelnames": list(m.labelnames),
                "series": [{"labels": labels, **snap}
                           for labels, snap in m.series()],
            }
        out["cdt_telemetry_series_dropped_total"] = {
            "type": "counter",
            "help": "Label sets collapsed into the overflow series by the "
                    "per-metric cardinality cap.",
            "labelnames": [],
            "series": [{"labels": {}, "value": float(dropped)}],
        }
        return out

    def reset(self) -> None:
        """Zero every series in place (test isolation). Metric OBJECTS are
        kept — module-level references held by instrumentation sites stay
        valid."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()


REGISTRY = MetricRegistry()
