"""Runtime lock-order detector (``CDT_LOCK_ORDER=1``, docs/lint.md).

The static rule L001 proves each registry guards its own state; it cannot
see CROSS-registry ordering — thread A taking BREAKERS then DRAIN while
thread B takes DRAIN then BREAKERS is invisible to any per-class check and
presents in production as an opaque 870 s hang. This module is the runtime
companion: the shared registries create their locks through
:func:`tracked_lock`, and when the ``CDT_LOCK_ORDER`` knob is on, every
acquisition records the (held -> acquired) edge in a process-global order
graph. Observing both ``A -> B`` and ``B -> A`` is an inversion — a
potential deadlock — and fails LOUDLY (:class:`LockOrderError`) at the
moment the second ordering is attempted, with both stacks in the message,
instead of deadlocking silently some run later.

The knob is latched at process start, so the disabled path costs one
module-global boolean read per acquire and the wrappers stay on in
production builds; the chaos suite runs a stage with
the detector armed, making every chaos event double as a race-detector run.

Known approximation: locks are tracked by ROLE name, not instance — two
sibling instances of one registry class share a name, so same-name
re-acquisition is treated as reentrancy rather than an ordering edge. For
the process-global singletons this module exists for (BREAKERS, DRAIN, the
default tables) the detection is exact.
"""

from __future__ import annotations

import threading
import traceback
from typing import Optional

from ..utils.constants import LOCK_ORDER


class LockOrderError(RuntimeError):
    """Two locks were taken in both orders — a potential deadlock."""


_tls = threading.local()

# process-global order graph, guarded by its own (untracked) meta-lock:
# (held, acquired) -> formatted stack of the first observation
_graph_lock = threading.Lock()
_edges: dict[tuple[str, str], str] = {}
_inversions: list[dict] = []
_forced: Optional[bool] = None          # test hook: overrides the latch
# The knob is latched ONCE at import: the chaos suite arms the detector
# via env before process start, and tests use force_enabled(). A per-
# acquire env lookup would tax every telemetry-counter increment and
# breaker check — the disabled path must stay one module-global read.
_latched: bool = bool(LOCK_ORDER.get())


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return _latched


def force_enabled(on: Optional[bool]) -> None:
    """Test hook: True/False overrides the import-time latch; None
    restores it (re-reading ``CDT_LOCK_ORDER`` in case the env changed)."""
    global _forced, _latched
    _forced = on
    if on is None:
        _latched = bool(LOCK_ORDER.get())


def reset() -> None:
    """Drop the recorded graph (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _inversions.clear()


def snapshot() -> dict:
    """{'edges': [[held, acquired], ...], 'inversions': [...]} — what the
    chaos suite asserts on."""
    with _graph_lock:
        return {"edges": sorted(_edges),
                "inversions": list(_inversions)}


def assert_clean() -> None:
    with _graph_lock:
        if _inversions:
            pairs = [(i["first"], i["second"]) for i in _inversions]
            raise LockOrderError(
                f"{len(_inversions)} lock-order inversion(s) recorded: "
                f"{pairs}")


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _record_acquire(name: str) -> None:
    held = _held_stack()
    if name in held:            # reentrant (or same-role sibling): no edge
        held.append(name)
        return
    here = "".join(traceback.format_stack(limit=8)[:-2])
    with _graph_lock:
        for h in held:
            edge = (h, name)
            rev = (name, h)
            if rev in _edges and edge not in _edges:
                inv = {"first": f"{name} -> {h}", "second": f"{h} -> {name}",
                       "first_stack": _edges[rev], "second_stack": here}
                _inversions.append(inv)
                # deliberately NOT appended to `held`: the caller releases
                # the raw lock and re-raises, so this thread never holds
                # it — a stale entry would fabricate edges forever after
                raise LockOrderError(
                    f"lock-order inversion: this thread holds '{h}' and is "
                    f"acquiring '{name}', but the order '{name}' -> '{h}' "
                    f"was already observed — potential deadlock.\n"
                    f"--- first ordering ({name} then {h}):\n"
                    f"{_edges[rev]}"
                    f"--- this ordering ({h} then {name}):\n{here}")
            _edges.setdefault(edge, here)
    held.append(name)


def _record_release(name: str) -> None:
    held = _held_stack()
    # release the most recent matching hold (locks release LIFO in the
    # with-statement idiom this repo uses everywhere)
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class TrackedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper with a role name.

    Tracking is checked per-acquire against the import-time latch (one
    module-global boolean read when off), so arming the detector is an
    env var at process start — no code changes.
    """

    __slots__ = ("name", "_lock")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got and enabled():
            try:
                _record_acquire(self.name)
            except LockOrderError:
                self._lock.release()
                raise
        return got

    def release(self) -> None:
        # pop bookkeeping whenever this thread has tracked holds, even if
        # the knob flipped off mid-critical-section — a stale held entry
        # would fabricate edges forever after
        if getattr(_tls, "held", None):
            _record_release(self.name)
        self._lock.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked() if hasattr(self._lock, "locked") \
            else False

    def __repr__(self) -> str:                        # pragma: no cover
        return f"TrackedLock({self.name!r})"


def tracked_lock(name: str, reentrant: bool = False) -> TrackedLock:
    """Factory the shared registries use in place of ``threading.Lock()``.

    Always returns a :class:`TrackedLock`; the disabled-path overhead is
    one module-global boolean read per acquire. ``CDT_LOCK_ORDER`` is
    latched at import (set it before process start, as the chaos suite
    does); in-process tests toggle via :func:`force_enabled`.
    """
    return TrackedLock(name, reentrant=reentrant)
