"""Source→sink taint engine for cdtlint v2 (docs/lint.md).

Sits on top of :mod:`lint.callgraph` and answers one question per
function: *does its return value derive from a nondeterministic source?*
D001 catches ``time.time()`` typed directly into ``cluster/cache/keys.py``;
it cannot catch the laundered version — a helper in ``utils/`` that
returns ``f"{job_id}-{time.time_ns()}"`` and is called from the digest
path two modules away. This engine computes per-function **return
taint** to a fixpoint over the project call graph so D002 can flag the
call site inside the bit-identity-critical module.

Taint kinds:

- ``nondet`` — wall-clock / random / uuid / OS-entropy / filesystem-order
  reads (the D001 source tables, shared so the two rules never disagree).
- ``set-order`` — iteration over a set (order is hash-seed-dependent).
  ``sorted(...)`` is the sanitizer: sorting a set-derived value restores
  determinism, so it kills this taint kind (and only this kind).
- ``env`` — raw ``os.environ`` / ``os.getenv`` reads. The sanctioned path
  is the typed knob registry (utils/constants.py): knob reads are
  deliberate, documented, and K001-checked, so calls resolving into the
  registry (``knob_bool``/``knob_int``/``knob_float`` and anything defined
  in utils.constants) never carry env taint.

Propagation is a light def-use pass, deliberately simple (docs/lint.md#limits):
assignments to plain names, returns, f-strings/binops/containers, attribute
and subscript access on tainted values, and calls — an internal callee's
return taint flows out; an external call is conservatively tainted when any
argument is (``str(t)``, ``repr(t)``, ``sha(t)``...). No per-parameter
tracking: a tainted value passed INTO a helper is the caller's problem at
the call site, not traced through the callee body.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .callgraph import PACKAGE, FunctionInfo, ProjectGraph

# -- nondeterminism sources (shared with D001 in rules.py) ------------------

NONDET_EXACT = {
    "time.time": "wall-clock read", "time.time_ns": "wall-clock read",
    "time.monotonic": "clock read", "time.perf_counter": "clock read",
    "uuid.uuid1": "nondeterministic uuid",
    "uuid.uuid4": "nondeterministic uuid",
    "os.urandom": "OS entropy", "os.listdir": "filesystem order is "
                                              "not deterministic",
    "glob.glob": "filesystem order is not deterministic",
    "glob.iglob": "filesystem order is not deterministic",
}
NONDET_PREFIX = {
    "random.": "module-level random.* (use a seeded "
               "Random/jax.random key threaded from the request)",
    "secrets.": "OS entropy",
    "datetime.datetime.now": "wall-clock read",
    "datetime.datetime.utcnow": "wall-clock read",
}

ENV_SOURCES = ("os.getenv", "os.environ.get")

# calls resolving here never carry env taint (the sanctioned read path)
KNOB_REGISTRY_MODULE = f"{PACKAGE}.utils.constants"
KNOB_TAILS = ("knob_bool", "knob_int", "knob_float", "knob_str")


def classify_nondet(name: str) -> Optional[str]:
    if name in NONDET_EXACT:
        return NONDET_EXACT[name]
    for prefix, why in NONDET_PREFIX.items():
        if name.startswith(prefix):
            return why
    return None


@dataclasses.dataclass(frozen=True)
class Taint:
    kind: str                 # "nondet" | "set-order" | "env"
    chain: tuple[str, ...]    # call path, source last
    why: str

    def via(self, hop: str) -> "Taint":
        return Taint(self.kind, (hop,) + self.chain, self.why)


class TaintAnalysis:
    """Per-function return taint, computed to a fixpoint over the graph.

    ``returns[key]`` maps ``module:qualname`` -> :class:`Taint` for every
    function whose return value derives from a source. Async functions
    participate like sync ones: awaiting a tainted coroutine's result is
    just as nondeterministic.
    """

    def __init__(self, graph: ProjectGraph):
        self.graph = graph
        self.returns: dict[str, Taint] = {}
        changed = True
        guard = 0
        while changed and guard < 50:
            changed = False
            guard += 1
            for fi in graph.functions.values():
                t = self._return_taint(fi)
                if t is not None and fi.key not in self.returns:
                    self.returns[fi.key] = t
                    changed = True

    # -- per-function pass ---------------------------------------------

    def _return_taint(self, fi: FunctionInfo) -> Optional[Taint]:
        if fi.module == KNOB_REGISTRY_MODULE:
            return None              # the registry IS the sanitizer
        tainted: dict[str, Taint] = {}
        found: list[Taint] = []
        self._scan_body(fi, fi.node.body, tainted, found)
        return found[0] if found else None

    def _scan_body(self, fi, body, tainted: dict[str, Taint],
                   found: list[Taint]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue              # their own FunctionInfo
            if isinstance(stmt, ast.Assign):
                t = self.expr_taint(fi, stmt.value, tainted)
                if t:
                    for target in stmt.targets:
                        for n in ast.walk(target):
                            if isinstance(n, ast.Name):
                                tainted.setdefault(n.id, t)
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                value = stmt.value
                t = self.expr_taint(fi, value, tainted) if value else None
                if t and isinstance(stmt.target, ast.Name):
                    tainted.setdefault(stmt.target.id, t)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                t = self.expr_taint(fi, stmt.value, tainted)
                if t:
                    found.append(t)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                t = self._iter_taint(fi, stmt.iter, tainted)
                if t:
                    for n in ast.walk(stmt.target):
                        if isinstance(n, ast.Name):
                            tainted.setdefault(n.id, t)
            # recurse into compound statements
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub and not isinstance(stmt, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef,
                                                 ast.ClassDef)):
                    self._scan_body(fi, sub, tainted, found)
            for handler in getattr(stmt, "handlers", ()) or ():
                self._scan_body(fi, handler.body, tainted, found)
            for item in getattr(stmt, "items", ()) or ():
                pass                  # `with` ctx exprs carry no value taint

    def _iter_taint(self, fi, it: ast.AST,
                    tainted: dict[str, Taint]) -> Optional[Taint]:
        imp = self.graph.imports[fi.module]
        if isinstance(it, (ast.Set, ast.SetComp)):
            return Taint("set-order", ("set-iteration",),
                         "iteration order over a set is not deterministic")
        if isinstance(it, ast.Call) and imp.resolve(it.func) in (
                "set", "frozenset"):
            return Taint("set-order", ("set-iteration",),
                         "iteration order over a set is not deterministic")
        return self.expr_taint(fi, it, tainted)

    # -- expression taint ----------------------------------------------

    def expr_taint(self, fi, expr: ast.AST,
                   tainted: dict[str, Taint]) -> Optional[Taint]:
        imp = self.graph.imports[fi.module]

        if isinstance(expr, ast.Name):
            return tainted.get(expr.id)
        if isinstance(expr, ast.Await):
            return self.expr_taint(fi, expr.value, tainted)
        if isinstance(expr, ast.Attribute):
            return self.expr_taint(fi, expr.value, tainted)
        if isinstance(expr, ast.Subscript):
            # os.environ["X"] is an env source; t[i] propagates t's taint
            if isinstance(expr.value, ast.Attribute) \
                    and imp.resolve(expr.value) == "os.environ":
                return Taint("env", ("os.environ[...]",), "raw env read")
            return self.expr_taint(fi, expr.value, tainted)
        if isinstance(expr, (ast.BinOp, ast.BoolOp, ast.JoinedStr,
                             ast.Tuple, ast.List, ast.Set, ast.Dict,
                             ast.FormattedValue, ast.IfExp, ast.Starred,
                             ast.UnaryOp, ast.Compare)):
            for child in ast.iter_child_nodes(expr):
                t = self.expr_taint(fi, child, tainted)
                if t:
                    return t
            return None

        if not isinstance(expr, ast.Call):
            return None

        name, target = self.graph.resolve_ref(fi, expr.func)
        tail = name.split(".")[-1]

        # sanitizers first
        if tail in KNOB_TAILS or name.startswith("constants.") \
                or name.startswith(KNOB_REGISTRY_MODULE + "."):
            return None
        arg_taints = [t for t in (
            self.expr_taint(fi, a, tainted) for a in expr.args)
            if t is not None]
        if tail == "sorted":
            # sorting restores a deterministic order — kills set-order
            arg_taints = [t for t in arg_taints if t.kind != "set-order"]
            return arg_taints[0] if arg_taints else None

        # sources
        why = classify_nondet(name)
        if why is not None:
            return Taint("nondet", (name,), why)
        if name in ENV_SOURCES or name.startswith("os.environ."):
            return Taint("env", (name,), "raw env read")
        if name in ("set", "frozenset"):
            # building a set is fine; ITERATING it is the hazard — but a
            # set fed onward (e.g. "".join(set(x))) is order-tainted
            return Taint("set-order", (name,),
                         "set ordering is not deterministic")

        # internal callee: its return taint flows out
        if target is not None and target in self.returns:
            return self.returns[target].via(
                self.graph.functions[target].short)
        if target is not None:
            return arg_taints[0] if arg_taints else None

        # external call: conservatively tainted when an argument is
        # (str(t), sha256(t), "".join(t)...)
        if arg_taints:
            return arg_taints[0]
        for kw in expr.keywords:
            t = self.expr_taint(fi, kw.value, tainted)
            if t:
                return t
        return None

    # -- rule-facing helpers -------------------------------------------

    def tainted_call_sites(self, fi: FunctionInfo):
        """(CallInfo, Taint) for call sites in ``fi`` that invoke an
        INTERNAL function whose return value is tainted — the ≥1-hop
        laundering case D001 cannot see."""
        for c in fi.calls:
            if c.target and c.target in self.returns:
                yield c, self.returns[c.target].via(
                    self.graph.functions[c.target].short)


def analyze(graph: ProjectGraph) -> TaintAnalysis:
    return TaintAnalysis(graph)
