"""cdtlint engine: findings, module contexts, suppressions, baseline.

The engine is deliberately small: rules get a parsed module
(:class:`ModuleCtx`) and yield :class:`Finding`\\ s; the engine handles file
walking, ``# cdtlint: disable=RULE`` suppressions, and the committed
baseline (grandfathered sites with one-line justifications; the gate fails
when a finding is not baselined AND when a baseline entry goes stale, so
the baseline can only shrink).

Site ids are line-number-free on purpose (``rule:path:qualname:token[#n]``):
a refactor that moves code without changing it must not churn the baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable, Iterator, Optional

SUPPRESS_RE = re.compile(
    r"#\s*cdtlint:\s*disable=([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)")

SKIP_DIRS = {"__pycache__", ".git", "web", "native"}


class LintError(Exception):
    """The linter itself failed (unreadable file, bad baseline, ...)."""


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative, forward slashes
    line: int
    message: str
    site: str            # stable id: rule:path:qualname:token[#n]

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class ModuleCtx:
    """One parsed module handed to every rule."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        try:
            self.tree = ast.parse(source, filename=str(path))
        except SyntaxError as exc:
            raise LintError(f"{rel}: cannot parse: {exc}") from exc
        self._site_counts: dict[str, int] = {}
        # module-level `NAME = "literal"` string constants, for resolving
        # e.g. os.environ.get(AUTH_ENV) where AUTH_ENV = "CDT_AUTH_TOKEN"
        self.str_consts: dict[str, str] = {}
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                self.str_consts[node.targets[0].id] = node.value.value

    def suppressed(self, line: int, rule: str) -> bool:
        """``# cdtlint: disable=RULE`` on the finding's line suppresses
        it; a comment-ONLY line directly above does too (for statements
        whose line is already full). A trailing comment on the previous
        statement deliberately does not reach past its own line."""
        def match(text: str) -> bool:
            m = SUPPRESS_RE.search(text)
            return bool(m and rule in re.split(r"\s*,\s*", m.group(1)))

        if 1 <= line <= len(self.lines) and match(self.lines[line - 1]):
            return True
        above = line - 1
        if 1 <= above <= len(self.lines):
            text = self.lines[above - 1]
            if text.lstrip().startswith("#") and match(text):
                return True
        return False

    def finding(self, rule: str, node: ast.AST, qualname: str,
                token: str, message: str) -> Finding:
        """Build a Finding with a stable, de-duplicated site id."""
        base = f"{rule}:{self.rel}:{qualname}:{token}"
        n = self._site_counts.get(base, 0)
        self._site_counts[base] = n + 1
        site = base if n == 0 else f"{base}#{n + 1}"
        return Finding(rule=rule, path=self.rel,
                       line=getattr(node, "lineno", 1),
                       message=message, site=site)


def iter_py_files(root: Path) -> Iterator[Path]:
    if root.is_file():
        yield root
        return
    for p in sorted(root.rglob("*.py")):
        if not any(part in SKIP_DIRS for part in p.parts):
            yield p


def build_contexts(paths: Iterable[Path], repo_root: Path) -> list[ModuleCtx]:
    ctxs = []
    for root in paths:
        for f in iter_py_files(root):
            try:
                source = f.read_text(encoding="utf-8")
            except OSError as exc:
                raise LintError(f"cannot read {f}: {exc}") from exc
            try:
                rel = f.resolve().relative_to(repo_root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            ctxs.append(ModuleCtx(f, rel, source))
    return ctxs


def run_lint(paths: Iterable[Path], rules, repo_root: Path,
             collect_rels: Optional[list] = None) -> list[Finding]:
    """Run every rule over every module (plus project-level ``finalize``
    hooks), dropping comment-suppressed findings. ``collect_rels``
    (out-param) receives the repo-relative paths actually linted, so the
    CLI can scope the baseline gate to this run."""
    ctxs = build_contexts(paths, repo_root)
    if collect_rels is not None:
        collect_rels.extend(c.rel for c in ctxs)
    findings: list[Finding] = []
    for rule in rules:
        for ctx in ctxs:
            for f in rule.check_module(ctx):
                if not ctx.suppressed(f.line, f.rule):
                    findings.append(f)
        finalize = getattr(rule, "finalize", None)
        if finalize is not None:
            findings.extend(finalize(ctxs, repo_root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.site))
    return findings


# ---------------------------------------------------------------------------
# baseline


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Optional[Path] = None) -> dict[str, str]:
    """{site -> justification}. A missing file is an empty baseline."""
    p = path or default_baseline_path()
    if not p.is_file():
        return {}
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as exc:
        raise LintError(f"cannot read baseline {p}: {exc}") from exc
    entries = data.get("entries", [])
    out: dict[str, str] = {}
    for e in entries:
        site = e.get("site", "")
        if not site:
            raise LintError(f"baseline {p}: entry without a site: {e!r}")
        if site in out:
            raise LintError(f"baseline {p}: duplicate site {site}")
        out[site] = e.get("justification", "")
    return out


def write_baseline(findings: list[Finding], path: Path,
                   justifications: Optional[dict[str, str]] = None,
                   preserve: Optional[dict[str, str]] = None) -> None:
    """``preserve`` carries {site: justification} entries OUTSIDE the
    current run's scope (other rules/paths) — a scoped ``--write-baseline``
    must never silently drop another rule's grandfathers."""
    just = justifications or {}
    entries = [{"site": f.site,
                "justification": just.get(f.site, "TODO: justify"),
                "message": f.message}
               for f in findings]
    seen = {f.site for f in findings}
    for site, j in sorted((preserve or {}).items()):
        if site not in seen:
            entries.append({"site": site, "justification": j})
    path.write_text(
        json.dumps({"entries": entries}, indent=2, sort_keys=False) + "\n",
        encoding="utf-8")


def split_baseline_scope(baseline: dict[str, str], rules,
                         linted_rels: Iterable[str],
                         findings: Iterable[Finding],
                         ) -> tuple[dict[str, str], dict[str, str]]:
    """(in_scope, out_of_scope): an entry is in scope when its rule was
    active AND its path was linted (or it matched a current finding).
    Out-of-scope entries are neither reported stale nor dropped by a
    scoped ``--write-baseline``. Project-level sites (non-``.py`` paths,
    e.g. the K001 docs sync) are in scope only on a full run — detected
    by the registry module being among the linted paths."""
    rule_ids = {r.id for r in rules}
    rels = set(linted_rels) | {f.path for f in findings}
    full_run = any(r.endswith("utils/constants.py") for r in rels)
    in_scope: dict[str, str] = {}
    out_scope: dict[str, str] = {}
    for site, just in baseline.items():
        rule, _, rest = site.partition(":")
        path = rest.split(":", 1)[0]
        covered = path in rels or (not path.endswith(".py") and full_run)
        (in_scope if rule in rule_ids and covered else out_scope)[site] = just
    return in_scope, out_scope


@dataclasses.dataclass
class GateResult:
    new: list[Finding]           # findings not in the baseline -> FAIL
    stale: list[str]             # baseline sites with no finding -> FAIL
    unjustified: list[str]       # baselined without a justification -> FAIL
    baselined: list[Finding]     # grandfathered findings (reported, pass)

    @property
    def ok(self) -> bool:
        return not (self.new or self.stale or self.unjustified)


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, str]) -> GateResult:
    by_site = {f.site: f for f in findings}
    new = [f for f in findings if f.site not in baseline]
    stale = [s for s in baseline if s not in by_site]
    unjustified = [s for s, j in baseline.items()
                   if s in by_site
                   and (not j.strip() or j.strip().startswith("TODO"))]
    baselined = [f for f in findings if f.site in baseline]
    return GateResult(new=new, stale=stale, unjustified=unjustified,
                      baselined=baselined)
