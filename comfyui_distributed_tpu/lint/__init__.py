"""cdtlint: the repo-native static-analysis suite (ISSUE 12, docs/lint.md).

Eight PRs of cluster growth accumulated load-bearing invariants — lock-guarded
shared registries, bit-identity-critical modules, the ``CDT_*`` knob surface,
traced-function purity, async hot paths — that were enforced only by
convention and review. This package turns them into code:

- ``python -m comfyui_distributed_tpu.lint`` runs the AST rules (L001, A001,
  D001, K001, J001) over the package against a committed suppression baseline
  (``lint/baseline.json``; the CI gate asserts the baseline only shrinks).
- :mod:`.lockorder` is the companion RUNTIME piece: a dev-mode instrumented
  lock wrapper (``CDT_LOCK_ORDER=1``) that records cross-registry lock
  acquisition order and fails loudly on an inversion. The chaos suite runs a
  stage under it, so every chaos event doubles as a race-detector run.
- :mod:`.loopstall` is the second runtime companion (ISSUE 20): a
  ``CDT_LOOP_STALL=1`` watchdog that samples the asyncio loop and records
  any callback blocking it past ``CDT_LOOP_STALL_MS``, with the offending
  stack — the runtime complement of A001/A002's static executor discipline.

Dependency-free by design (stdlib ``ast`` only): the linter must run in CI
images, pre-commit hooks, and broken checkouts where jax cannot import.

Imports here are LAZY (module ``__getattr__``): the serving path imports
``lint.lockorder`` for :func:`tracked_lock`, and a future syntax error in the
dev-only analysis engine must not brick a booting controller.
"""

_EXPORTS = {
    "Finding": "core", "LintError": "core", "load_baseline": "core",
    "run_lint": "core", "ALL_RULES": "rules", "rule_by_id": "rules",
}

__all__ = list(_EXPORTS) + ["lockorder", "loopstall"]


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    if name in ("lockorder", "loopstall"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
