"""Project-wide call graph for cdtlint v2 (docs/lint.md).

The v1 rules (A001/D001/L001) see one function body at a time: a blocking
``np.savez``+``sha256`` buried two frames under an async route, or a
``time.time()`` laundered through a helper into a cache key, pass the gate.
This module gives the flow rules (lint/flowrules.py) the interprocedural
substrate, still stdlib-``ast``-only so the linter keeps running where jax
cannot import:

- :class:`ModuleImports` — import/alias resolution with RELATIVE imports
  resolved against the module's dotted name (``from ..utils import x`` in
  ``api/app.py`` -> ``comfyui_distributed_tpu.utils.x``).
- :class:`ProjectGraph` — one :class:`FunctionInfo` per function/method
  (nested defs included), with every call site resolved to an internal
  function key (``module:qualname``) or an external dotted name, and
  per-function :class:`Summary` facts computed to a fixpoint: blocks?,
  awaits?, does heavy encode/checksum work?, acquires which locks?

Executor-offload sanitizer (the A001 false-positive fix A002 inherits):
callables handed to ``run_in_executor`` / ``asyncio.to_thread`` /
``Executor.submit`` run OFF the loop, so they must not contribute
blocking taint — whether passed directly (``run_in_executor(None, work)``),
wrapped in ``functools.partial(work, x)``, wrapped in a ``lambda``, or
bound to a local name first (``run = lambda: ...; run_in_executor(None,
run)``). The unwrap is surgical: a call nested in a partial's ARGUMENT
list (``partial(open(path).read)``) still executes on the loop at wrapper
construction time and stays un-sanitized.

The converse edge matters too: callables handed to the LOOP's own
schedulers (``call_soon``, ``call_later``, ``call_at``,
``call_soon_threadsafe``, ``add_done_callback``) run ON the loop, so a
``partial(blocking_helper)`` scheduled there propagates blocking taint
exactly like a direct call.

Resolution is best-effort, not sound (docs/lint.md#limits): calls through
unknown objects, dynamic dispatch, and inheritance are not followed — the
flow rules are tripwires, not proofs.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

from .core import ModuleCtx

PACKAGE = "comfyui_distributed_tpu"


# ---------------------------------------------------------------------------
# call-semantics tables (shared with A001 in rules.py)

BLOCKING_EXACT = {
    "time.sleep": "time.sleep blocks the event loop — use "
                  "`await asyncio.sleep(...)`",
    "os.system": "os.system blocks the event loop",
    "os.popen": "os.popen blocks the event loop",
    "open": "sync file I/O in async def — offload via "
            "loop.run_in_executor / asyncio.to_thread",
}
BLOCKING_PREFIX = {
    "subprocess.": "subprocess in async def blocks the event loop — "
                   "use asyncio.create_subprocess_* or an executor",
    "fcntl.": "fcntl file locking blocks the event loop — offload to "
              "an executor",
}
BLOCKING_METHODS = {
    "read_text": "sync file I/O", "write_text": "sync file I/O",
    "read_bytes": "sync file I/O", "write_bytes": "sync file I/O",
}

# Heavy CPU work on the wire path (W001): not "blocking" in A001's sense,
# but multi-MB encode/checksum on the loop stalls every other request just
# the same — the PR 9/14/17 media-and-checkpoint-route executor discipline.
HEAVY_EXACT = {
    "base64.b64encode": "base64 encode of a payload",
    "base64.b64decode": "base64 decode of a payload",
    "numpy.savez": "npz serialization", "numpy.savez_compressed":
        "npz serialization", "numpy.load": "npz parse",
    "np.savez": "npz serialization", "np.savez_compressed":
        "npz serialization", "np.load": "npz parse",
}
HEAVY_PREFIX = {
    "hashlib.": "checksum work",
    "zlib.": "compression work",
}
# wire-codec entry points by trailing name (cross-module spellings vary)
HEAVY_TAILS = {
    "encode_array_payload": "npz+b64+sha256 wire encode",
    "decode_array_payload": "b64+sha256+npz wire decode",
}

# Callables handed to these run OFF the loop: sanitize blocking taint.
EXECUTOR_TAILS = ("run_in_executor", "to_thread", "submit")
# Callables handed to these run ON the loop: propagate blocking taint.
LOOP_SCHEDULE_TAILS = ("call_soon", "call_soon_threadsafe", "call_later",
                       "call_at", "add_done_callback")


def classify_blocking(name: str, call: ast.Call) -> Optional[str]:
    """Why a resolved call name is loop-blocking ('' sentinel never used)."""
    if name in BLOCKING_EXACT:
        return BLOCKING_EXACT[name]
    for prefix, why in BLOCKING_PREFIX.items():
        if name.startswith(prefix):
            return why
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "result" and not call.args and not call.keywords:
            return "blocking .result() — await the future instead"
        if attr in BLOCKING_METHODS:
            return f"{BLOCKING_METHODS[attr]} (.{attr}())"
    return None


def classify_heavy(name: str) -> Optional[str]:
    if name in HEAVY_EXACT:
        return HEAVY_EXACT[name]
    for prefix, why in HEAVY_PREFIX.items():
        if name.startswith(prefix):
            return why
    return HEAVY_TAILS.get(name.split(".")[-1])


# ---------------------------------------------------------------------------
# imports


class ModuleImports:
    """Import table resolving LOCAL names to ABSOLUTE dotted targets,
    relative imports included (needs the module's own dotted name)."""

    def __init__(self, tree: ast.AST, module: str, is_package: bool):
        self.module = module
        self.module_alias: dict[str, str] = {}           # local -> module
        self.from_name: dict[str, tuple[str, str]] = {}  # local -> (mod, orig)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.module_alias[a.asname] = a.name
                    else:
                        self.module_alias[a.name.split(".")[0]] = \
                            a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                mod = self._abs_module(node, module, is_package)
                for a in node.names:
                    self.from_name[a.asname or a.name] = (mod, a.name)

    @staticmethod
    def _abs_module(node: ast.ImportFrom, module: str,
                    is_package: bool) -> str:
        if not node.level:
            return node.module or ""
        parts = module.split(".")
        if not is_package:
            parts = parts[:-1]
        if node.level > 1:
            parts = parts[:max(0, len(parts) - (node.level - 1))]
        base = ".".join(parts)
        if node.module:
            return f"{base}.{node.module}" if base else node.module
        return base

    def resolve(self, func: ast.AST) -> str:
        """Dotted name of a call target, import-aware; unknown roots keep
        their literal spelling (same contract as rules.Imports)."""
        parts: list[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            base = node.id
            if base in self.from_name:
                mod, orig = self.from_name[base]
                base = f"{mod}.{orig}" if mod else orig
            elif base in self.module_alias:
                base = self.module_alias[base]
            parts.append(base)
        elif isinstance(node, ast.Call):
            parts.append("()")
        else:
            parts.append("?")
        return ".".join(reversed(parts))


def module_name_of(rel: str) -> str:
    """``comfyui_distributed_tpu/lint/core.py`` ->
    ``comfyui_distributed_tpu.lint.core``; ``pkg/__init__.py`` -> ``pkg``;
    bare fixture files keep their stem (``snippet.py`` -> ``snippet``)."""
    parts = rel.split("/")
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    return ".".join(parts)


# ---------------------------------------------------------------------------
# per-function facts


@dataclasses.dataclass
class CallInfo:
    """One resolved call site."""
    node: ast.Call
    name: str                      # absolute dotted spelling
    target: Optional[str] = None   # internal key "module:qualname"
    sanitized: bool = False        # inside an executor-offloaded wrapper
    deferred: bool = False         # inside a lambda body (runs later, maybe)
    on_loop: bool = False          # scheduled via call_soon/call_later/...


@dataclasses.dataclass
class RefInfo:
    """A function REFERENCE (not call) scheduled onto the loop — e.g.
    ``loop.call_soon(helper)`` or ``call_soon(partial(helper, x))``."""
    node: ast.AST
    target: Optional[str]
    name: str


@dataclasses.dataclass
class Summary:
    blocks: Optional[tuple[str, ...]] = None   # call chain ending at leaf
    blocks_why: str = ""
    heavy: Optional[tuple[str, ...]] = None
    heavy_why: str = ""
    awaits: bool = False
    acquires: tuple[str, ...] = ()             # lock spellings (with stmts)


class FunctionInfo:
    def __init__(self, ctx: ModuleCtx, module: str, qualname: str,
                 node, self_class: Optional[str]):
        self.ctx = ctx
        self.module = module
        self.qualname = qualname
        self.node = node
        self.self_class = self_class       # qualname of class `self` binds to
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.key = f"{module}:{qualname}"
        self.calls: list[CallInfo] = []
        self.loop_refs: list[RefInfo] = []
        self.sanitized_ids: set[int] = set()
        self.summary = Summary()

    @property
    def short(self) -> str:
        return self.qualname.split(".")[-1]

    def __repr__(self) -> str:                         # pragma: no cover
        return f"FunctionInfo({self.key})"


def iter_functions_cls(tree: ast.AST) -> Iterator[
        tuple[str, Optional[str], object]]:
    """(qualname, self-class-qualname, node) for every function; the
    self-class propagates into defs nested inside methods (their ``self``
    closes over the method's)."""

    def walk(node, prefix, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield q, cls, child
                yield from walk(child, q, cls)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield from walk(child, q, q)
            else:
                yield from walk(child, prefix, cls)

    yield from walk(tree, "", None)


def walk_own(fn, include_lambdas: bool = False) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested defs (they are
    their own FunctionInfo); lambdas optionally included (their bodies
    execute in this function's context when invoked)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Lambda) and not include_lambdas:
                continue
            yield child
            yield from walk(child)

    yield from walk(fn)


def wrapper_binds(fn) -> dict[str, ast.AST]:
    """Local ``run = lambda: ...`` / ``run = partial(f, ...)`` bindings,
    so ``loop.run_in_executor(None, run)`` sanitizes through the alias
    (the worker_routes.warmup_start idiom)."""
    binds: dict[str, ast.AST] = {}
    for node in walk_own(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, (ast.Lambda, ast.Call)):
            binds[node.targets[0].id] = node.value
    return binds


def callable_args(call: ast.Call, tail: str) -> list[ast.AST]:
    """The argument of an executor/scheduler call that names the deferred
    work: ``run_in_executor(exec, fn, *a)`` -> fn; ``call_later(delay,
    cb)`` / ``call_at(when, cb)`` -> cb; ``to_thread/submit/call_soon/
    add_done_callback(fn, *a)`` -> fn."""
    idx = 1 if tail in ("run_in_executor", "call_later", "call_at") else 0
    return call.args[idx:idx + 1]


def _mark_offloaded(arg, imp: ModuleImports, sanitized: set[int],
                    binds: dict[str, ast.AST]) -> None:
    """Sanitize a callable handed to an executor, unwrapping partial and
    lambda wrappers (and one level of local-name aliasing). Calls nested
    in a partial's ARGUMENT list execute at wrapper-build time ON the
    loop, so they are deliberately NOT sanitized."""
    if isinstance(arg, ast.Name) and arg.id in binds:
        arg = binds[arg.id]
    if isinstance(arg, ast.Lambda):
        for sub in ast.walk(arg):
            sanitized.add(id(sub))
    elif isinstance(arg, ast.Call) \
            and imp.resolve(arg.func).split(".")[-1] == "partial":
        sanitized.add(id(arg))
        for sub in ast.walk(arg):
            if not isinstance(sub, ast.Call) or sub is arg:
                sanitized.add(id(sub))
    else:
        sanitized.add(id(arg))         # bare reference: no call node anyway


def offload_sanitized_ids(fn, imp: ModuleImports) -> set[int]:
    """Node ids inside ``fn`` that are executor-offloaded and therefore
    exempt from on-loop blocking checks (A001 uses this directly; the
    graph bakes it into each CallInfo for A002)."""
    sanitized: set[int] = set()
    binds = wrapper_binds(fn)
    for node in walk_own(fn):
        if isinstance(node, ast.Call):
            tail = imp.resolve(node.func).split(".")[-1]
            if tail in EXECUTOR_TAILS:
                for arg in callable_args(node, tail):
                    _mark_offloaded(arg, imp, sanitized, binds)
    return sanitized


def lock_spelling(expr: ast.AST, imp: ModuleImports) -> Optional[str]:
    """``with self._lock`` / ``with some_lock`` — an attribute or name
    whose spelling contains "lock" (the L001 heuristic, shared)."""
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        return imp.resolve(expr)
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return expr.id
    return None


# ---------------------------------------------------------------------------
# the graph


class ProjectGraph:
    """Build once per lint run from the full ModuleCtx list."""

    def __init__(self, ctxs: list[ModuleCtx]):
        self.ctxs = ctxs
        self.functions: dict[str, FunctionInfo] = {}
        self.imports: dict[str, ModuleImports] = {}
        self.modules: dict[str, ModuleCtx] = {}
        # parent-qualname -> {local def name -> key} (parent "" = module)
        self._children: dict[str, dict[str, str]] = {}
        self._lambda_cache: dict[int, set[int]] = {}
        for ctx in ctxs:
            self._index_module(ctx)
        for fi in list(self.functions.values()):
            self._resolve_function(fi)
        self._fixpoint()

    # -- indexing ------------------------------------------------------

    def _index_module(self, ctx: ModuleCtx) -> None:
        module = module_name_of(ctx.rel)
        self.modules[module] = ctx
        self.imports[module] = ModuleImports(
            ctx.tree, module, ctx.rel.endswith("__init__.py"))
        for qual, cls, fn in iter_functions_cls(ctx.tree):
            fi = FunctionInfo(ctx, module, qual, fn, cls)
            self.functions[fi.key] = fi
            parent = qual.rsplit(".", 1)[0] if "." in qual else ""
            self._children.setdefault(f"{module}:{parent}", {})[
                fn.name] = fi.key

    def lookup(self, module: str, qualname: str) -> Optional[FunctionInfo]:
        return self.functions.get(f"{module}:{qualname}")

    def child_of(self, module: str, parent_qual: str,
                 name: str) -> Optional[str]:
        return self._children.get(f"{module}:{parent_qual}", {}).get(name)

    # -- reference resolution ------------------------------------------

    def resolve_ref(self, fi: FunctionInfo,
                    node: ast.AST) -> tuple[str, Optional[str]]:
        """(absolute dotted name, internal key or None) for a callable
        reference — a Name, an Attribute chain, or ``self.method``."""
        imp = self.imports[fi.module]
        name = imp.resolve(node)

        # self.method() -> the class `self` binds to (no inheritance walk)
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and fi.self_class):
            key = self.child_of(fi.module, fi.self_class, node.attr)
            if key:
                return name, key

        if isinstance(node, ast.Name):
            # innermost enclosing scope outward: nested defs, then
            # siblings at each level, then module level, then imports
            qual = fi.qualname
            scopes = [qual]
            while "." in qual:
                qual = qual.rsplit(".", 1)[0]
                scopes.append(qual)
            scopes.append("")
            for scope in scopes:
                key = self.child_of(fi.module, scope, node.id)
                if key:
                    return name, key
            if node.id in imp.from_name:
                mod, orig = imp.from_name[node.id]
                key = f"{mod}:{orig}"
                if key in self.functions:
                    return name, key
            return name, None

        if isinstance(node, ast.Attribute):
            # mod.f() via `import mod` / `from pkg import mod`
            base = node.value
            attr = node.attr
            if isinstance(base, ast.Name):
                target_mod = None
                if base.id in imp.module_alias:
                    target_mod = imp.module_alias[base.id]
                elif base.id in imp.from_name:
                    m, o = imp.from_name[base.id]
                    candidate = f"{m}.{o}" if m else o
                    if candidate in self.modules:
                        target_mod = candidate
                if target_mod and target_mod in self.modules:
                    key = self.child_of(target_mod, "", attr)
                    if key:
                        return name, key
        return name, None

    # -- per-function call extraction ----------------------------------

    def _resolve_function(self, fi: FunctionInfo) -> None:
        imp = self.imports[fi.module]
        fi.sanitized_ids = offload_sanitized_ids(fi.node, imp)
        sanitized = fi.sanitized_ids
        on_loop_ids: set[int] = set()
        binds = wrapper_binds(fi.node)

        # pass 1: find loop-scheduler entries (deferred on-loop edges)
        for node in walk_own(fi.node):
            if not isinstance(node, ast.Call):
                continue
            tail = imp.resolve(node.func).split(".")[-1]
            if tail in LOOP_SCHEDULE_TAILS:
                for arg in callable_args(node, tail):
                    self._mark_on_loop(fi, arg, imp, on_loop_ids, binds)

        # pass 2: classify every call site
        for node in walk_own(fi.node, include_lambdas=True):
            if isinstance(node, ast.Await):
                fi.summary.awaits = True
            if isinstance(node, ast.With):
                for item in node.items:
                    lock = lock_spelling(item.context_expr, imp)
                    if lock and lock not in fi.summary.acquires:
                        fi.summary.acquires += (lock,)
            if not isinstance(node, ast.Call):
                continue
            name, target = self.resolve_ref(fi, node.func)
            info = CallInfo(node=node, name=name, target=target)
            info.sanitized = id(node) in sanitized
            info.on_loop = id(node) in on_loop_ids
            info.deferred = (not info.on_loop
                             and id(node) in self._lambda_ids(fi.node))
            fi.calls.append(info)

    def _mark_on_loop(self, fi, arg, imp, on_loop_ids: set[int],
                      local_wrappers: dict[str, ast.AST]) -> None:
        """A callable scheduled ON the loop: lambda bodies become on-loop
        calls; partial/bare references become loop refs (deferred edges
        that propagate blocking taint like direct calls)."""
        if isinstance(arg, ast.Name) and arg.id in local_wrappers:
            arg = local_wrappers[arg.id]
        if isinstance(arg, ast.Lambda):
            for sub in ast.walk(arg.body):
                on_loop_ids.add(id(sub))
            return
        ref: Optional[ast.AST] = None
        if isinstance(arg, ast.Call) \
                and imp.resolve(arg.func).split(".")[-1] == "partial" \
                and arg.args:
            ref = arg.args[0]
        elif isinstance(arg, (ast.Name, ast.Attribute)):
            ref = arg
        if ref is not None:
            name, target = self.resolve_ref(fi, ref)
            fi.loop_refs.append(RefInfo(node=ref, target=target, name=name))

    def _lambda_ids(self, fn) -> set[int]:
        cached = self._lambda_cache.get(id(fn))
        if cached is None:
            cached = set()
            for node in walk_own(fn, include_lambdas=True):
                if isinstance(node, ast.Lambda):
                    for sub in ast.walk(node.body):
                        cached.add(id(sub))
            self._lambda_cache[id(fn)] = cached
        return cached

    # -- summaries ------------------------------------------------------

    def _fixpoint(self) -> None:
        """Propagate blocks/heavy through SYNC call edges until stable.
        Async callees do not propagate (calling one just builds a
        coroutine; its own body is the async rules' jurisdiction)."""
        changed = True
        guard = 0
        while changed and guard < 50:
            changed = False
            guard += 1
            for fi in self.functions.values():
                changed |= self._update_summary(fi)

    def _update_summary(self, fi: FunctionInfo) -> bool:
        s = fi.summary
        changed = False
        for c in fi.calls:
            if c.sanitized or c.deferred:
                continue
            # `# cdtlint: disable=A002` on the SOURCE line exempts the
            # whole transitive class: one justified comment at the root
            # (e.g. an mtime-cached config read) instead of a baseline
            # entry per caller (docs/lint.md)
            if fi.ctx.suppressed(getattr(c.node, "lineno", 1), "A002"):
                continue
            if s.blocks is None:
                why = classify_blocking(c.name, c.node)
                if why is not None:
                    s.blocks, s.blocks_why = (c.name,), why
                    changed = True
                elif c.target:
                    callee = self.functions[c.target]
                    if not callee.is_async and callee.summary.blocks:
                        s.blocks = (callee.short,) + callee.summary.blocks
                        s.blocks_why = callee.summary.blocks_why
                        changed = True
            if s.heavy is None:
                why = classify_heavy(c.name)
                if why is not None:
                    s.heavy, s.heavy_why = (c.name,), why
                    changed = True
                elif c.target:
                    callee = self.functions[c.target]
                    if not callee.is_async and callee.summary.heavy:
                        s.heavy = (callee.short,) + callee.summary.heavy
                        s.heavy_why = callee.summary.heavy_why
                        changed = True
        return changed


def build_graph(ctxs: list[ModuleCtx]) -> ProjectGraph:
    return ProjectGraph(ctxs)
