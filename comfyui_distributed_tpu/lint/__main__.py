"""``python -m comfyui_distributed_tpu.lint`` — the cdtlint CLI.

Exit codes: 0 = clean (all findings baselined, baseline fresh and
justified), 1 = violations (new findings, stale baseline entries, or
unjustified baseline entries), 2 = the linter itself failed.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import (LintError, apply_baseline, default_baseline_path,
                   load_baseline, run_lint, split_baseline_scope,
                   write_baseline)
from .rules import ALL_RULES, rule_by_id


def package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def changed_package_files(ref: str):
    """Package ``.py`` files changed vs ``ref`` (committed diff plus
    untracked), as absolute paths; ``None`` means git itself failed."""
    import subprocess

    root = repo_root()
    names: set[str] = set()
    for cmd in (["git", "diff", "--name-only", ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            out = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, check=True)
        except (OSError, subprocess.CalledProcessError) as exc:
            err = getattr(exc, "stderr", "") or str(exc)
            print(f"cdtlint --diff: {' '.join(cmd)} failed: "
                  f"{err.strip()}", file=sys.stderr)
            return None
        names.update(line.strip() for line in out.stdout.splitlines())
    pkg_prefix = package_root().name + "/"
    changed = {n for n in names
               if n.endswith(".py") and n.startswith(pkg_prefix)
               and (root / n).is_file()}       # deleted files drop out
    # W001 checks the FULL route surface against docs/api.md; with only
    # the diffed files in scope, routes registered in unchanged api/
    # modules would read as missing and fail the fast path spuriously —
    # so any api/ change pulls the whole (small) api/ package in
    if any(n.startswith(pkg_prefix + "api/") for n in changed):
        changed.update(
            str(p.relative_to(root))
            for p in (package_root() / "api").glob("*.py"))
    return [root / n for n in sorted(changed)]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m comfyui_distributed_tpu.lint",
        description="repo-native static analysis (docs/lint.md)")
    p.add_argument("paths", nargs="*", type=Path,
                   help="files/dirs to lint (default: the package)")
    p.add_argument("--rules", help="comma list of rule ids (default: all)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", type=Path,
                   help=f"baseline path (default: {default_baseline_path()})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignore the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings as the baseline "
                        "(keeps existing justifications; new entries get "
                        "a TODO placeholder the gate rejects until edited)")
    p.add_argument("--write-knob-docs", action="store_true",
                   help="regenerate docs/knobs.md from the knob registry")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print grandfathered (baselined) findings")
    p.add_argument("--diff", metavar="REF",
                   help="lint only package files changed vs the git REF "
                        "(diff + untracked) — the fast pre-commit path; "
                        "note the flow rules (A002/L002/D002/W001) see "
                        "only the changed files' call graph, so CI still "
                        "runs the full gate")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id}  {r.title}")
        return 0

    if args.write_knob_docs:
        from .knobdocs import write

        out = repo_root() / "docs" / "knobs.md"
        write(out)
        print(f"wrote {out}")
        return 0

    rules = ALL_RULES
    if args.rules:
        try:
            rules = [rule_by_id(r.strip())
                     for r in args.rules.split(",") if r.strip()]
        except KeyError as exc:
            print(f"unknown rule {exc}", file=sys.stderr)
            return 2

    paths = args.paths or [package_root()]
    if args.diff:
        changed = changed_package_files(args.diff)
        if changed is None:
            return 2
        if not changed:
            print(f"cdtlint --diff {args.diff}: no package files changed "
                  "— OK")
            return 0
        paths = changed
    linted_rels: list = []
    try:
        findings = run_lint(paths, rules, repo_root(),
                            collect_rels=linted_rels)
    except LintError as exc:
        print(f"cdtlint error: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = args.baseline or default_baseline_path()
        try:
            old = load_baseline(path)
        except LintError:
            old = {}
        # scoped runs must not drop other rules'/paths' grandfathers
        _, out_of_scope = split_baseline_scope(old, rules, linted_rels,
                                               findings)
        write_baseline(findings, path, justifications=old,
                       preserve=out_of_scope)
        print(f"wrote {len(findings)} entries to {path} "
              f"({len(out_of_scope)} out-of-scope entries preserved)")
        return 0

    if args.no_baseline:
        gate = apply_baseline(findings, {})
    else:
        try:
            baseline = load_baseline(args.baseline)
        except LintError as exc:
            print(f"cdtlint error: {exc}", file=sys.stderr)
            return 2
        # only entries within this run's rule/path scope can go stale —
        # a scoped run must not flag the rest of the baseline
        in_scope, _ = split_baseline_scope(baseline, rules, linted_rels,
                                           findings)
        gate = apply_baseline(findings, in_scope)

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in gate.new],
            "stale_baseline": gate.stale,
            "unjustified_baseline": gate.unjustified,
            "baselined": [vars(f) for f in gate.baselined],
            "ok": gate.ok,
        }, indent=2))
        return 0 if gate.ok else 1

    for f in gate.new:
        print(f.render())
    for s in gate.stale:
        print(f"STALE baseline entry (site no longer exists — remove it, "
              f"the baseline only shrinks): {s}")
    for s in gate.unjustified:
        print(f"UNJUSTIFIED baseline entry (add a one-line reason): {s}")
    if args.show_baselined:
        for f in gate.baselined:
            print(f"[baselined] {f.render()}")
    n_rules = ",".join(r.id for r in rules)
    print(f"cdtlint [{n_rules}]: {len(gate.new)} new, "
          f"{len(gate.baselined)} baselined, {len(gate.stale)} stale, "
          f"{len(gate.unjustified)} unjustified"
          + (" — OK" if gate.ok else " — FAIL"))
    return 0 if gate.ok else 1


if __name__ == "__main__":
    sys.exit(main())
