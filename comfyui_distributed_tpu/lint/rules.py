"""cdtlint rules: the fleet's invariants as AST checks (docs/lint.md).

=====  =====================================================================
L001   lock-discipline: mutation of a lock-guarded shared-registry attribute
       outside a ``with self._lock`` block (BREAKERS, DRAIN, CacheTier,
       TuningTable, ShapeCatalog, ResidencyPlanner, telemetry registry, ...).
A001   async-hygiene: blocking calls (``time.sleep``, sync file I/O,
       ``subprocess``, ``fcntl``, ``Future.result()``) directly in an
       ``async def`` body without executor offload.
D001   determinism: wall-clock, ``random.*``, ``uuid4``, set-order
       dependence in modules declared bit-identity-critical.
K001   knob-discipline: raw ``os.environ`` reads of ``CDT_*`` outside the
       typed knob registry, plus the two-way code<->docs sync check.
J001   traced-purity: functions passed to ``jax.jit``/``shard_map`` must
       not perform I/O, env reads, or telemetry calls inside the trace.
=====  =====================================================================

Every rule is heuristic, not sound: the escape hatches are a same-line
``# cdtlint: disable=RULE`` comment (with justification) or a baseline
entry (``lint/baseline.json``). See docs/lint.md for the workflow.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from pathlib import Path
from typing import Iterator, Optional

from .core import Finding, ModuleCtx
from . import callgraph as _callgraph
from . import dataflow as _dataflow

CDT_NAME_RE = re.compile(r"CDT_[A-Z0-9_]*[A-Z0-9]$")

PACKAGE = "comfyui_distributed_tpu"


# ---------------------------------------------------------------------------
# shared AST helpers


class Imports:
    """Per-module import table so rules resolve ``sleep(...)`` ->
    ``time.sleep`` and ``sp.run(...)`` -> ``subprocess.run``."""

    def __init__(self, tree: ast.AST):
        self.module_alias: dict[str, str] = {}   # local name -> module
        self.from_name: dict[str, tuple[str, str]] = {}  # local -> (mod, orig)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    top = a.name if a.asname else a.name.split(".")[0]
                    self.module_alias[local] = top
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    self.from_name[a.asname or a.name] = (mod, a.name)

    def resolve(self, func: ast.AST) -> str:
        """Dotted name of a call target, import-aware. Attribute chains
        rooted in unknown objects keep their literal spelling
        (``self._lock.acquire`` -> ``self._lock.acquire``)."""
        parts: list[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            base = node.id
            if base in self.from_name:
                mod, orig = self.from_name[base]
                base = f"{mod}.{orig}" if mod else orig
            elif base in self.module_alias:
                base = self.module_alias[base]
            parts.append(base)
        elif isinstance(node, ast.Call):
            parts.append("()")
        else:
            parts.append("?")
        return ".".join(reversed(parts))

    def from_module_of(self, name: str) -> str:
        """Source module of a from-imported local name ('' if not one)."""
        return self.from_name.get(name, ("", ""))[0]


def imports_of(ctx: ModuleCtx) -> Imports:
    imp = getattr(ctx, "_imports", None)
    if imp is None:
        imp = Imports(ctx.tree)
        ctx._imports = imp
    return imp


def iter_functions(tree: ast.AST) -> Iterator[tuple[str, ast.AST]]:
    """Yield (qualname, FunctionDef|AsyncFunctionDef) for every function,
    methods included."""

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield q, child
                yield from walk(child, q)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield from walk(child, q)
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def qualname_map(ctx: ModuleCtx) -> dict[int, str]:
    """id(node) -> qualname of the innermost enclosing function."""
    cached = getattr(ctx, "_qualmap", None)
    if cached is not None:
        return cached
    out: dict[int, str] = {}
    for qual, fn in iter_functions(ctx.tree):   # outer first; inner wins
        for sub in ast.walk(fn):
            out[id(sub)] = qual
    ctx._qualmap = out
    return out


def is_self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def mutated_self_attrs(stmt: ast.AST) -> list[tuple[str, ast.AST]]:
    """Self attributes this single node mutates: assignments to
    ``self.X`` / ``self.X[...]``, ``del``, and mutating method calls
    (``self.X.append(...)``, ``self.X[k].update(...)``)."""
    MUTATORS = {"append", "extend", "add", "remove", "discard", "clear",
                "pop", "popitem", "update", "setdefault", "insert",
                "appendleft", "popleft", "sort", "reverse"}
    out: list[tuple[str, ast.AST]] = []

    def target_attr(t: ast.AST) -> Optional[str]:
        a = is_self_attr(t)
        if a is not None:
            return a
        if isinstance(t, ast.Subscript):
            return target_attr(t.value)
        return None

    def scan_target(t: ast.AST):
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                scan_target(el)
            return
        a = target_attr(t)
        if a is not None:
            out.append((a, t))

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            scan_target(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if getattr(stmt, "value", True) is not None:   # AnnAssign decl only
            scan_target(stmt.target)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            scan_target(t)
    elif isinstance(stmt, ast.Call):
        f = stmt.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            base = f.value
            if isinstance(base, ast.Subscript):
                base = base.value
            a = is_self_attr(base)
            if a is not None:
                out.append((a, stmt))
    return out


# ---------------------------------------------------------------------------
# L001 — lock discipline


class LockDisciplineRule:
    """Classes are auto-discovered: any class that takes ``with self.X``
    on an attribute whose name contains "lock" is lock-disciplined; an
    attribute mutated at least once under the lock is *guarded*; mutating
    a guarded attribute outside the lock (outside ``__init__``/``__new__``
    and helpers named ``*_locked``, which the caller must hold the lock
    for) is a finding."""

    id = "L001"
    title = "lock-guarded registry attribute mutated outside its lock"

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _lock_attrs(self, cls: ast.ClassDef) -> set[str]:
        attrs: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    a = is_self_attr(item.context_expr)
                    if a is not None and "lock" in a.lower():
                        attrs.add(a)
        return attrs

    def _check_class(self, ctx: ModuleCtx,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        lock_attrs = self._lock_attrs(cls)
        if not lock_attrs:
            return

        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

        def holds_lock(with_node) -> bool:
            return any(is_self_attr(i.context_expr) in lock_attrs
                       for i in with_node.items)

        # pass 1: guarded attrs = mutated at least once under the lock
        guarded: set[str] = set()

        def collect(node, in_lock):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                inner = in_lock
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    inner = in_lock or holds_lock(child)
                if in_lock or inner:
                    for attr, _ in mutated_self_attrs(child):
                        if inner:
                            guarded.add(attr)
                collect(child, inner)

        for m in methods:
            collect(m, False)
        guarded -= lock_attrs
        if not guarded:
            return

        # pass 2: mutations of guarded attrs outside the lock
        findings: list[Finding] = []

        def hunt(method, node, in_lock):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                inner = in_lock
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    inner = in_lock or holds_lock(child)
                if not inner:
                    for attr, site in mutated_self_attrs(child):
                        if attr in guarded:
                            findings.append(ctx.finding(
                                self.id, site, f"{cls.name}.{method.name}",
                                attr,
                                f"{cls.name}.{method.name} mutates "
                                f"self.{attr} outside `with self."
                                f"{sorted(lock_attrs)[0]}` (guarded: "
                                f"mutated under the lock elsewhere in "
                                f"this class)"))
                hunt(method, child, inner)

        for m in methods:
            if m.name in ("__init__", "__new__") or m.name.endswith("_locked"):
                continue
            hunt(m, m, False)
        yield from findings


# ---------------------------------------------------------------------------
# A001 — async hygiene


class AsyncHygieneRule:
    id = "A001"
    title = "blocking call directly in an async def body"

    # single source of truth shared with the call-graph engine, so A001
    # and A002 can never disagree about what "blocking" means
    BLOCKING_EXACT = _callgraph.BLOCKING_EXACT
    BLOCKING_PREFIX = _callgraph.BLOCKING_PREFIX
    BLOCKING_METHODS = _callgraph.BLOCKING_METHODS

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        imp = imports_of(ctx)
        for qual, fn in iter_functions(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            yield from self._check_async_fn(ctx, imp, qual, fn)

    def _check_async_fn(self, ctx, imp, qual, fn) -> Iterator[Finding]:
        # Executor-offload exemption (ISSUE 20): callables handed to
        # run_in_executor / to_thread / submit run OFF the loop, so
        # blocking calls inside their partial/lambda wrappers (including
        # `run = lambda: ...; run_in_executor(None, run)` aliases) are
        # exempt. Everything else — lambdas included, since a lambda
        # invoked inline or scheduled via call_soon runs ON the loop —
        # is checked. A call nested in a partial's ARGUMENT list
        # (`partial(open(path).read)`) evaluates at wrapper-build time
        # on the loop and stays flagged.
        sanitized = _callgraph.offload_sanitized_ids(fn, imp)

        def walk(node):
            for child in ast.iter_child_nodes(node):
                # nested defs run on their own schedule (and nested async
                # defs are visited separately)
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.Call) \
                        and id(child) not in sanitized:
                    yield from check_call(child)
                yield from walk(child)

        def check_call(call) -> Iterator[Finding]:
            name = imp.resolve(call.func)
            if name in self.BLOCKING_EXACT:
                yield ctx.finding(self.id, call, qual, name.split(".")[-1],
                                  f"{self.BLOCKING_EXACT[name]} "
                                  f"(async def {fn.name})")
                return
            for prefix, why in self.BLOCKING_PREFIX.items():
                if name.startswith(prefix):
                    yield ctx.finding(self.id, call, qual, name,
                                      f"{why} (async def {fn.name})")
                    return
            if isinstance(call.func, ast.Attribute):
                attr = call.func.attr
                if attr == "result" and not call.args and not call.keywords:
                    yield ctx.finding(
                        self.id, call, qual, "result",
                        f"blocking .result() in async def {fn.name} — "
                        "await the future (or wrap_future) instead")
                elif attr in self.BLOCKING_METHODS:
                    yield ctx.finding(
                        self.id, call, qual, attr,
                        f"{self.BLOCKING_METHODS[attr]} (.{attr}()) in "
                        f"async def {fn.name} — offload to an executor")

        yield from walk(fn)


# ---------------------------------------------------------------------------
# D001 — determinism in bit-identity-critical modules


class DeterminismRule:
    """Scope: the modules whose outputs feed the bit-identity guarantee
    (cache keys, microbatch demux, steal scheduling, the pipelines), as a
    path list plus a per-module ``__bit_identity_critical__ = True``
    opt-in dunder."""

    id = "D001"
    title = "nondeterminism in a bit-identity-critical module"

    MODULES = (
        f"{PACKAGE}/cluster/cache/keys.py",
        f"{PACKAGE}/cluster/frontdoor/microbatch.py",
        f"{PACKAGE}/cluster/elastic/scheduler.py",
        f"{PACKAGE}/diffusion/pipeline*.py",
    )

    # shared with the taint engine (lint/dataflow.py) so D001's direct
    # checks and D002's interprocedural taint use identical source tables
    BANNED_EXACT = _dataflow.NONDET_EXACT
    BANNED_PREFIX = _dataflow.NONDET_PREFIX

    def in_scope(self, ctx: ModuleCtx) -> bool:
        if any(fnmatch.fnmatch(ctx.rel, pat) for pat in self.MODULES):
            return True
        for node in ctx.tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "__bit_identity_critical__"
                            for t in node.targets)
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                return True
        return False

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if not self.in_scope(ctx):
            return
        imp = imports_of(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = imp.resolve(node.func)
                why = self.BANNED_EXACT.get(name)
                if why is None:
                    for prefix, w in self.BANNED_PREFIX.items():
                        if name.startswith(prefix):
                            why = w
                            break
                if why is not None:
                    yield ctx.finding(
                        self.id, node, "<module>", name,
                        f"{name}: {why} in a bit-identity-critical module")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                if (isinstance(it, (ast.Set, ast.SetComp))
                        or (isinstance(it, ast.Call)
                            and imp.resolve(it.func) in ("set",
                                                         "frozenset"))):
                    yield ctx.finding(
                        self.id, node, "<module>", "set-iteration",
                        "iterating a set: order is not deterministic in a "
                        "bit-identity-critical module — sort it first")


# ---------------------------------------------------------------------------
# K001 — knob discipline (raw env reads + two-way doc sync)


class KnobDisciplineRule:
    id = "K001"
    title = "CDT_* knob read outside the typed registry / doc drift"

    REGISTRY_MODULE = f"{PACKAGE}/utils/constants.py"

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel == self.REGISTRY_MODULE:
            return
        imp = imports_of(ctx)
        for qual, key_node, node in self._env_reads(ctx, imp):
            key = self._literal_key(ctx, key_node)
            if key is not None and key.startswith("CDT_"):
                yield ctx.finding(
                    self.id, node, qual, key,
                    f"raw env read of {key} — declare it in "
                    "utils/constants.py and read via the knob registry "
                    "(constants.<KNOB>.get())")
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = imp.resolve(node.func)
                if name.split(".")[-1] in ("env_int", "env_float") \
                        and "constants" in name:
                    key = self._literal_key(
                        ctx, node.args[0] if node.args else None)
                    if key and key.startswith("CDT_"):
                        yield ctx.finding(
                            self.id, node, "<module>", key,
                            f"legacy env_{'int' if 'int' in name else 'float'}"
                            f" read of {key} — declare a Knob in "
                            "utils/constants.py instead")

    def _env_reads(self, ctx, imp):
        """(qualname, key-node, call/subscript-node) for os.environ.get /
        os.getenv / os.environ[...] loads — one yield per site."""
        quals = qualname_map(ctx)
        for sub in ast.walk(ctx.tree):
            if isinstance(sub, ast.Call):
                name = imp.resolve(sub.func)
                if name in ("os.environ.get", "os.getenv"):
                    yield (quals.get(id(sub), "<module>"),
                           sub.args[0] if sub.args else None, sub)
            elif (isinstance(sub, ast.Subscript)
                  and isinstance(sub.ctx, ast.Load)
                  and imp.resolve(sub.value) == "os.environ"):
                yield quals.get(id(sub), "<module>"), sub.slice, sub

    def _literal_key(self, ctx, node) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return ctx.str_consts.get(node.id)
        return None

    # -- project-level two-way sync ------------------------------------

    def finalize(self, ctxs, repo_root: Path) -> list[Finding]:
        """Full-package checks (skipped when the registry module is not
        part of the lint run, e.g. fixture-snippet tests): every CDT_*
        literal in code must be a declared knob, and docs/knobs.md must
        be regeneration-clean against the registry."""
        if not any(c.rel == self.REGISTRY_MODULE for c in ctxs):
            return []
        try:
            from ..utils.constants import KNOBS
        except Exception as exc:                      # pragma: no cover
            return [Finding(self.id, self.REGISTRY_MODULE, 1,
                            f"cannot import the knob registry: {exc}",
                            f"{self.id}:{self.REGISTRY_MODULE}:registry")]
        declared = set(KNOBS.names())
        findings: list[Finding] = []
        for ctx in ctxs:
            for name, node in self._cdt_literals(ctx):
                if name not in declared and not ctx.suppressed(
                        node.lineno, self.id):
                    findings.append(ctx.finding(
                        self.id, node, "<module>", name,
                        f"{name} referenced in code but not declared in "
                        "the knob registry (utils/constants.py) — "
                        "undeclared knobs can't reach docs/knobs.md"))
        findings.extend(self._check_docs(repo_root, declared))
        return findings

    def _cdt_literals(self, ctx):
        docstrings = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                body = node.body
                if (body and isinstance(body[0], ast.Expr)
                        and isinstance(body[0].value, ast.Constant)
                        and isinstance(body[0].value.value, str)):
                    docstrings.add(id(body[0].value))
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and id(node) not in docstrings
                    and CDT_NAME_RE.fullmatch(node.value)):
                yield node.value, node

    def _check_docs(self, repo_root: Path, declared) -> list[Finding]:
        from .knobdocs import render_markdown

        rel = "docs/knobs.md"
        path = repo_root / rel
        want = render_markdown()
        have = path.read_text(encoding="utf-8") if path.is_file() else ""
        if have != want:
            verb = "missing" if not have else "stale"
            return [Finding(
                self.id, rel, 1,
                f"docs/knobs.md is {verb} — the knob docs are GENERATED "
                "from the registry; run `python -m "
                f"{PACKAGE}.lint --write-knob-docs`",
                f"{self.id}:{rel}:regen")]
        return []


# ---------------------------------------------------------------------------
# J001 — traced purity


class TracedPurityRule:
    """Functions handed to ``jax.jit``/``shard_map`` (decorator or call
    form) are traced: anything they do besides math is either silently
    baked into the compiled program (env reads, flags) or runs only at
    trace time (I/O, telemetry) — both are bugs. Resolution is
    module-local and shallow: helpers the traced function calls are not
    followed (docs/lint.md#limits)."""

    id = "J001"
    title = "impure call inside a jit/shard_map-traced function"

    # matched on the LAST dotted component so every spelling works:
    # jax.jit, jit, jax_compat.shard_map, jax.experimental...shard_map
    TRACE_ENTRY_TAILS = ("jit", "pjit", "shard_map")

    IMPURE_EXACT = {
        "open": "file I/O", "print": "stdout I/O (use jax.debug.print)",
        "os.getenv": "env read (baked into the trace)",
        "os.environ.get": "env read (baked into the trace)",
        "time.time": "clock read (runs at trace time only)",
        "time.monotonic": "clock read (runs at trace time only)",
        "time.perf_counter": "clock read (runs at trace time only)",
    }
    IMPURE_PREFIX = {
        "random.": "python-level randomness (runs at trace time only — "
                   "use jax.random with a threaded key)",
        "logging.": "logging inside a trace runs at trace time only",
    }

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        imp = imports_of(ctx)
        defs: dict[str, ast.AST] = {name.split(".")[-1]: fn
                                    for name, fn in iter_functions(ctx.tree)}
        seen: set[int] = set()
        for target, how in self._traced_functions(ctx, imp, defs):
            if id(target) in seen:
                continue
            seen.add(id(target))
            yield from self._check_traced(ctx, imp, target, how)

    def _traced_functions(self, ctx, imp, defs):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_trace_entry(imp, dec):
                        yield node, f"@{imp.resolve(dec if not isinstance(dec, ast.Call) else dec.func)}"
            elif isinstance(node, ast.Call):
                name = imp.resolve(node.func)
                if name.split(".")[-1] in self.TRACE_ENTRY_TAILS \
                        and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Lambda):
                        yield arg, name
                    elif isinstance(arg, ast.Name) and arg.id in defs:
                        yield defs[arg.id], name
                # functools.partial(jax.jit, f) is rare; skipped.

    def _is_trace_entry(self, imp, dec) -> bool:
        if isinstance(dec, ast.Call):
            dec = dec.func
        return imp.resolve(dec).split(".")[-1] in self.TRACE_ENTRY_TAILS

    def _check_traced(self, ctx, imp, fn, how) -> Iterator[Finding]:
        qual = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = imp.resolve(node.func)
            why = self.IMPURE_EXACT.get(name)
            if why is None:
                for prefix, w in self.IMPURE_PREFIX.items():
                    if name.startswith(prefix):
                        why = w
                        break
            if why is None and "telemetry" in name:
                why = "telemetry call (runs at trace time only — " \
                      "record outside the traced function)"
            if why is not None:
                yield ctx.finding(
                    self.id, node, qual, name,
                    f"{name} inside {how}-traced `{qual}`: {why}")


from .flowrules import FLOW_RULES  # noqa: E402

ALL_RULES = (LockDisciplineRule(), AsyncHygieneRule(), DeterminismRule(),
             KnobDisciplineRule(), TracedPurityRule()) + FLOW_RULES


def rule_by_id(rule_id: str):
    for r in ALL_RULES:
        if r.id == rule_id:
            return r
    raise KeyError(rule_id)
