"""Runtime event-loop stall sanitizer (``CDT_LOOP_STALL=1``, docs/lint.md).

The static rules A001/A002 prove that *known* blocking work stays off the
event loop; they cannot see work that only BECOMES blocking at runtime — a
C extension holding the GIL, a "fast" codec handed a pathological input, a
lock wait inside a third-party callback. This module is the runtime
companion, mirroring :mod:`.lockorder`: when the ``CDT_LOOP_STALL`` knob
is on, every asyncio callback records its start into a process-global
in-flight slot (via a patched ``asyncio.events.Handle._run``), and a
daemon sampler thread watches that slot. A callback still running past
``CDT_LOOP_STALL_MS`` is recorded as a **stall** together with the loop
thread's live stack at the moment of observation (``sys._current_frames``)
— so the report names the exact frame that was hogging the loop, not just
"p99 went bad".

The knob is latched at process start like ``CDT_LOCK_ORDER``: the chaos
suite arms it via env before launching the smoke drivers, and in-process
tests toggle :func:`force_enabled`. Disabled, the patched ``Handle._run``
costs one module-global boolean read per callback.

Known approximations:

- Sampling granularity is ``threshold/4`` (floor 5 ms): a stall that both
  starts and finishes between two samples is still caught — the patched
  wrapper double-checks elapsed time on completion and records the stall
  without a stack (``observed="completed"``).
- One in-flight slot per process, not per loop: if two threads each run
  an event loop, a sample may attribute a stall to whichever callback
  wrote the slot last. The serving stack runs ONE loop (the controller's),
  so in practice attribution is exact.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Optional

from ..utils.constants import LOOP_STALL, LOOP_STALL_MS


class LoopStallError(RuntimeError):
    """The event loop was blocked past the configured threshold."""


# in-flight slot written by the loop thread, read by the sampler:
# [t0_monotonic, callback_name, loop_thread_id, sampler_reported?]
# (a fresh list per callback — identity distinguishes invocations)
_inflight: Optional[list] = None

_meta = threading.Lock()          # guards _stalls + the reported flag
_stalls: list[dict] = []
_forced: Optional[bool] = None    # test hook: overrides the latch
# Latched ONCE at import, same discipline as lockorder: per-callback env
# lookups would tax every timer tick and socket event on the loop.
_latched: bool = bool(LOOP_STALL.get())

_installed = False
_orig_run = None
_sampler_started = False


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return _latched


def force_enabled(on: Optional[bool]) -> None:
    """Test hook: True/False overrides the import-time latch; None
    restores it (re-reading ``CDT_LOOP_STALL`` in case the env changed).
    Enabling also installs the patch + sampler if not yet running."""
    global _forced, _latched
    _forced = on
    if on is None:
        _latched = bool(LOOP_STALL.get())
    if enabled():
        install()


def threshold_ms() -> float:
    try:
        return float(LOOP_STALL_MS.get())
    except (TypeError, ValueError):
        return 100.0


def reset() -> None:
    """Drop recorded stalls (test isolation)."""
    with _meta:
        _stalls.clear()


def snapshot() -> dict:
    """{'stalls': [{duration_ms, callback, stack, observed}, ...]} —
    what the chaos suite asserts on."""
    with _meta:
        return {"stalls": [dict(s) for s in _stalls]}


def assert_clean() -> None:
    with _meta:
        if _stalls:
            worst = max(_stalls, key=lambda s: s["duration_ms"])
            raise LoopStallError(
                f"{len(_stalls)} event-loop stall(s) recorded "
                f"(threshold {threshold_ms():.0f} ms); worst: "
                f"{worst['callback']} blocked the loop for "
                f"{worst['duration_ms']:.0f} ms\n{worst['stack']}")


def _callback_name(handle) -> str:
    cb = getattr(handle, "_callback", None)
    if cb is None:
        return repr(handle)
    # unwrap functools.partial / method wrappers to a readable qualname
    inner = getattr(cb, "func", cb)
    name = getattr(inner, "__qualname__", None) or repr(inner)
    code = getattr(inner, "__code__", None)
    if code is not None:
        return f"{name} ({code.co_filename}:{code.co_firstlineno})"
    return str(name)


def _record(entry: list, duration_ms: float, stack: str,
            observed: str) -> None:
    with _meta:
        if entry[3] is not False:
            # sampler already reported mid-flight with a partial elapsed
            # time; the completion path upgrades it to the full duration
            if observed == "completed":
                entry[3]["duration_ms"] = round(duration_ms, 1)
            return
        report = {
            "duration_ms": round(duration_ms, 1),
            "callback": entry[1],
            "stack": stack,
            "observed": observed,
        }
        entry[3] = report
        _stalls.append(report)
    # outside the lock: one log line so stalls are visible in live server
    # logs too, not only to in-process snapshot() readers
    sys.stderr.write(
        f"[loopstall] {report['callback']} blocked the event loop for "
        f"{report['duration_ms']:.0f} ms ({observed})\n")


def _patched_run(self):
    if not enabled():
        return _orig_run(self)
    global _inflight
    entry = [time.monotonic(), _callback_name(self),
             threading.get_ident(), False]
    _inflight = entry
    try:
        return _orig_run(self)
    finally:
        _inflight = None
        dt = (time.monotonic() - entry[0]) * 1000.0
        if dt >= threshold_ms():
            # stall shorter than one sampler period: no live stack was
            # captured, but the offender still gets named
            _record(entry, dt, "(completed before the sampler fired — "
                    "no live stack)", observed="completed")


def _sample_once() -> None:
    entry = _inflight
    if entry is None or entry[3]:
        return
    dt = (time.monotonic() - entry[0]) * 1000.0
    if dt < threshold_ms():
        return
    frame = sys._current_frames().get(entry[2])
    stack = ("".join(traceback.format_stack(frame)) if frame is not None
             else "(loop thread frame unavailable)")
    _record(entry, dt, stack, observed="sampled")


def _sampler_loop() -> None:          # pragma: no cover - timing loop
    while True:
        interval = max(threshold_ms() / 4.0, 5.0) / 1000.0
        time.sleep(min(interval, 0.25))
        if enabled():
            try:
                _sample_once()
            except Exception:
                pass                  # the watchdog must never kill itself


def install() -> None:
    """Patch ``asyncio.events.Handle._run`` and start the sampler thread.

    Idempotent and process-global. Called automatically at import when
    ``CDT_LOOP_STALL`` is set (the chaos-suite path) and by
    :func:`force_enabled` (the in-process test path)."""
    global _installed, _orig_run, _sampler_started
    if not _installed:
        import asyncio.events

        _orig_run = asyncio.events.Handle._run
        asyncio.events.Handle._run = _patched_run
        _installed = True
    if not _sampler_started:
        t = threading.Thread(target=_sampler_loop,
                             name="cdt-loopstall-sampler", daemon=True)
        t.start()
        _sampler_started = True


if _latched:                          # armed via env before process start
    install()
