"""docs/knobs.md generator: the knob registry rendered as markdown.

The doc is GENERATED — never hand-edit it. Rule K001's project check
asserts the committed file equals :func:`render_markdown`'s output, so the
knob surface can never silently drift from its docs again (the rendered
footer carries the live count).
"""

from __future__ import annotations

from pathlib import Path

HEADER = """\
# CDT_* knob reference

> **Generated file — do not edit.** This page is rendered from the typed
> knob registry in `comfyui_distributed_tpu/utils/constants.py` by
> `python -m comfyui_distributed_tpu.lint --write-knob-docs`, and lint
> rule **K001** (docs/lint.md) fails tier-1 when it goes stale.

Every `CDT_*` environment knob is declared once in the registry with a
type, default, and owning subsystem; call sites read through it
(`constants.<KNOB>.get()`), parse once per value, and raise a descriptive
`KnobError` on garbage unless the knob explicitly opts into
warn-and-default (marked *fallback* below).
"""


def _fmt_default(knob) -> str:
    if knob.default is None:
        return "*(unset)*"
    if knob.default == "":
        return '`""`'
    return f"`{knob.default!r}`" if isinstance(knob.default, str) \
        else f"`{knob.default}`"


def _fmt_kind(knob) -> str:
    kind = knob.kind
    if kind == "enum":
        kind = "enum(" + ", ".join(f"`{c}`" if c else '`""`'
                                   for c in knob.choices) + ")"
    if knob.on_garbage == "default":
        kind += " *(fallback)*"
    return kind


def render_markdown() -> str:
    from ..utils.constants import KNOBS

    by_subsystem: dict[str, list] = {}
    for k in KNOBS.all():
        by_subsystem.setdefault(k.subsystem, []).append(k)

    out = [HEADER]
    for subsystem in sorted(by_subsystem):
        knobs = by_subsystem[subsystem]
        docs = sorted({k.doc for k in knobs if k.doc})
        title = f"## {subsystem}"
        if docs:
            title += " — " + ", ".join(
                f"[{Path(d).name}](../{d})" if not d.startswith("docs/")
                else f"[{d[5:]}]({d[5:]})" for d in docs)
        out.append(title + "\n")
        out.append("| knob | type | default | description |")
        out.append("| --- | --- | --- | --- |")
        for k in knobs:
            help_text = " ".join(k.help.split())
            out.append(f"| `{k.name}` | {_fmt_kind(k)} | "
                       f"{_fmt_default(k)} | {help_text} |")
        out.append("")
    out.append(f"*{len(KNOBS.names())} knobs declared.*")
    return "\n".join(out) + "\n"


def write(path: Path) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(render_markdown(), encoding="utf-8")
