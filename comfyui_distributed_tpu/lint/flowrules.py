"""cdtlint v2 flow rules: project-wide checks on the call graph
(docs/lint.md).

=====  =====================================================================
A002   transitive async-blocking: an ``async def`` reaching a blocking
       call (``time.sleep``, sync file I/O, ``subprocess``) or heavy
       encode/checksum work (b64/npz/sha256/wire codecs) through ≥1 sync
       call hops — or any function scheduling a blocking callable onto
       the event loop via ``call_soon``/``call_later``/callbacks. The
       executor exemption unwraps ``functools.partial`` and lambda
       wrappers (shared with A001 via lint/callgraph.py).
L002   lock-held-across-await/blocking: a sync ``with <lock>:`` block in
       an ``async def`` whose body awaits or (transitively) blocks — the
       static complement of the runtime lock-order detector. ``async
       with`` is the sanctioned pattern and is exempt.
D002   interprocedural nondeterminism taint: a wall-clock / random /
       uuid / env / set-order source laundered through ≥1 helper into a
       bit-identity-critical module (lint/dataflow.py computes the
       per-function return taint; D001 still owns the direct calls).
W001   wire/route contract: every aiohttp route registered via
       ``add_get``/``add_post``/``add_put`` must appear in docs/api.md
       (two-way sync, like K001<->knobs.md), and body-reading POST/PUT
       handlers must validate their payload through api/schemas.
       Heavy-work-on-the-loop for handlers is A002's jurisdiction.
=====  =====================================================================

All four rules do their work in ``finalize`` (they need the whole
project), so ``check_module`` is a no-op and suppression comments are
applied manually, exactly like K001. The graph and taint analysis are
built once per run and shared across the rules.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator, Optional

from .core import Finding, ModuleCtx
from . import callgraph as cg
from . import dataflow as df

PACKAGE = cg.PACKAGE


def _shared(ctxs: list[ModuleCtx]):
    """(ProjectGraph, TaintAnalysis), built once per run_lint call and
    cached on the first ctx (the ctx list is per-run, so this never
    leaks across runs)."""
    anchor = ctxs[0]
    cached = getattr(anchor, "_cdt_flow_cache", None)
    if cached is None:
        graph = cg.build_graph(ctxs)
        cached = (graph, df.analyze(graph))
        anchor._cdt_flow_cache = cached
    return cached


def _chain(parts) -> str:
    return " -> ".join(parts)


# ---------------------------------------------------------------------------
# A002 — transitive async-blocking


class TransitiveAsyncRule:
    id = "A002"
    title = "async def reaches blocking/heavy work through call hops"

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        return iter(())

    def finalize(self, ctxs, repo_root: Path) -> list[Finding]:
        graph, _ = _shared(ctxs)
        findings: list[Finding] = []
        for fi in graph.functions.values():
            findings.extend(self._check_fn(graph, fi))
        return [f for f in findings if not self._suppressed(graph, f)]

    def _suppressed(self, graph, f: Finding) -> bool:
        ctx = next((c for c in graph.ctxs if c.rel == f.path), None)
        return ctx is not None and ctx.suppressed(f.line, self.id)

    def _check_fn(self, graph, fi) -> Iterator[Finding]:
        for c in fi.calls:
            if c.sanitized or c.deferred:
                continue
            if fi.is_async:
                yield from self._check_async_call(graph, fi, c)
            elif c.on_loop:
                # sync code scheduling a lambda onto the loop: its body
                # runs ON the loop, so direct blocking there counts
                why = cg.classify_blocking(c.name, c.node)
                if why is not None:
                    yield fi.ctx.finding(
                        self.id, c.node, fi.qualname, c.name.split(".")[-1],
                        f"{c.name} scheduled onto the event loop from "
                        f"`{fi.short}`: {why}")
        for ref in fi.loop_refs:
            yield from self._check_loop_ref(graph, fi, ref)

    def _check_async_call(self, graph, fi, c) -> Iterator[Finding]:
        # ≥1 hop: a sync internal callee that (transitively) blocks.
        # Depth 0 is A001's jurisdiction and is not re-reported here.
        if c.target is not None:
            callee = graph.functions[c.target]
            if not callee.is_async and callee.summary.blocks:
                chain = (callee.short,) + callee.summary.blocks
                yield fi.ctx.finding(
                    self.id, c.node, fi.qualname, callee.short,
                    f"async def {fi.short} reaches blocking "
                    f"{chain[-1]} via {_chain(chain)}: "
                    f"{callee.summary.blocks_why}")
            if not callee.is_async and callee.summary.heavy:
                chain = (callee.short,) + callee.summary.heavy
                yield fi.ctx.finding(
                    self.id, c.node, fi.qualname, callee.short,
                    f"async def {fi.short} does {callee.summary.heavy_why} "
                    f"on the event loop via {_chain(chain)} — offload to "
                    "an executor")
        else:
            # 0-hop heavy work directly in an async def (A001 only covers
            # blocking calls, so this is new surface, not a duplicate)
            why = cg.classify_heavy(c.name)
            if why is not None:
                yield fi.ctx.finding(
                    self.id, c.node, fi.qualname, c.name,
                    f"async def {fi.short} does {why} ({c.name}) on the "
                    "event loop — offload to an executor")

    def _check_loop_ref(self, graph, fi, ref) -> Iterator[Finding]:
        # `loop.call_soon(partial(helper))` / `fut.add_done_callback(f)`:
        # the referenced callable runs ON the loop later
        if ref.target is not None:
            callee = graph.functions[ref.target]
            if not callee.is_async and callee.summary.blocks:
                chain = (callee.short,) + callee.summary.blocks
                yield fi.ctx.finding(
                    self.id, ref.node, fi.qualname, callee.short,
                    f"`{fi.short}` schedules {callee.short} onto the event "
                    f"loop but it blocks via {_chain(chain)}: "
                    f"{callee.summary.blocks_why}")
        elif ref.name in cg.BLOCKING_EXACT or any(
                ref.name.startswith(p) for p in cg.BLOCKING_PREFIX):
            yield fi.ctx.finding(
                self.id, ref.node, fi.qualname, ref.name,
                f"`{fi.short}` schedules blocking {ref.name} onto the "
                "event loop")


# ---------------------------------------------------------------------------
# L002 — lock held across await / blocking call


class LockHeldAcrossAwaitRule:
    id = "L002"
    title = "sync lock held across an await or blocking call in async code"

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        return iter(())

    def finalize(self, ctxs, repo_root: Path) -> list[Finding]:
        graph, _ = _shared(ctxs)
        findings: list[Finding] = []
        for fi in graph.functions.values():
            if not fi.is_async:
                continue
            imp = graph.imports[fi.module]
            for node in cg.walk_own(fi.node):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    lock = cg.lock_spelling(item.context_expr, imp)
                    if lock:
                        findings.extend(
                            self._check_with(graph, fi, node, lock))
        return [f for f in findings
                if not self._suppressed(graph, f)]

    def _suppressed(self, graph, f: Finding) -> bool:
        ctx = next((c for c in graph.ctxs if c.rel == f.path), None)
        return ctx is not None and ctx.suppressed(f.line, self.id)

    def _check_with(self, graph, fi, with_node, lock) -> Iterator[Finding]:
        by_id = {id(c.node): c for c in fi.calls}
        for stmt in with_node.body:
            for node in self._iter(stmt):
                if isinstance(node, ast.Await):
                    yield fi.ctx.finding(
                        self.id, node, fi.qualname, lock,
                        f"`with {lock}:` held across an await in async "
                        f"def {fi.short} — a sync lock parks every other "
                        "task; use asyncio.Lock or release before "
                        "awaiting")
                elif isinstance(node, ast.Call):
                    c = by_id.get(id(node))
                    if c is None or c.sanitized or c.deferred:
                        continue
                    why = cg.classify_blocking(c.name, node)
                    chain: Optional[tuple] = None
                    if why is not None:
                        chain = (c.name,)
                    elif c.target is not None:
                        callee = graph.functions[c.target]
                        if not callee.is_async and callee.summary.blocks:
                            chain = ((callee.short,)
                                     + callee.summary.blocks)
                            why = callee.summary.blocks_why
                    if chain:
                        yield fi.ctx.finding(
                            self.id, node, fi.qualname, lock,
                            f"`with {lock}:` held across blocking "
                            f"{_chain(chain)} in async def {fi.short}: "
                            f"{why}")

    @staticmethod
    def _iter(stmt):
        yield stmt
        yield from cg.walk_own(stmt, include_lambdas=False)


# ---------------------------------------------------------------------------
# D002 — interprocedural nondeterminism taint


class TaintedDeterminismRule:
    """D001's interprocedural sibling: the direct ``time.time()`` in a
    bit-identity module is D001; the helper two modules away that RETURNS
    a wall-clock-derived value INTO the digest path is D002."""

    id = "D002"
    title = "nondeterministic value laundered into a bit-identity module"

    SINKS = (
        f"{PACKAGE}/cluster/cache/keys.py",
        f"{PACKAGE}/cluster/frontdoor/microbatch.py",
        f"{PACKAGE}/cluster/elastic/scheduler.py",
        f"{PACKAGE}/diffusion/pipeline*.py",
        f"{PACKAGE}/diffusion/checkpoint.py",
        f"{PACKAGE}/cluster/stages/latents.py",
    )

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        return iter(())

    def in_scope(self, ctx: ModuleCtx) -> bool:
        import fnmatch
        if any(fnmatch.fnmatch(ctx.rel, pat) for pat in self.SINKS):
            return True
        for node in ctx.tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "__bit_identity_critical__"
                            for t in node.targets)
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                return True
        return False

    def finalize(self, ctxs, repo_root: Path) -> list[Finding]:
        graph, taint = _shared(ctxs)
        findings: list[Finding] = []
        sink_modules = {cg.module_name_of(c.rel)
                        for c in ctxs if self.in_scope(c)}
        for fi in graph.functions.values():
            if fi.module not in sink_modules:
                continue
            for c, t in taint.tainted_call_sites(fi):
                if fi.ctx.suppressed(c.node.lineno, self.id):
                    continue
                findings.append(fi.ctx.finding(
                    self.id, c.node, fi.qualname, c.name.split(".")[-1],
                    f"{c.name}() returns a value derived from "
                    f"{t.chain[-1]} ({t.why}) — flows {_chain(t.chain)} "
                    "into a bit-identity-critical module"))
        return findings


# ---------------------------------------------------------------------------
# W001 — wire/route contract


class WireContractRule:
    id = "W001"
    title = "route missing doc row / payload validation"

    APP_MODULE = f"{PACKAGE}/api/app.py"
    SCHEMAS_MODULE = f"{PACKAGE}.api.schemas"
    DOC = "docs/api.md"
    EXEMPT_PATHS = {"/"}
    ROUTE_TAILS = {"add_get": "GET", "add_post": "POST", "add_put": "PUT"}
    DOC_PATH_RE = re.compile(
        r"(/(?:distributed|prompt|upload)[A-Za-z0-9_/{}.-]*|/prompt)")

    def check_module(self, ctx: ModuleCtx) -> Iterator[Finding]:
        return iter(())

    @staticmethod
    def _norm(path: str) -> str:
        """Strip query strings, collapse `{param}` spellings so
        `/x/{id}` and `/x/{worker_id}` compare equal."""
        return re.sub(r"\{[^}]*\}", "{}", path.split("?")[0]).rstrip("/")

    def finalize(self, ctxs, repo_root: Path) -> list[Finding]:
        app_ctx = next((c for c in ctxs if c.rel == self.APP_MODULE), None)
        if app_ctx is None:
            return []        # fixture-snippet runs: contract not in scope
        graph, _ = _shared(ctxs)
        findings: list[Finding] = []

        routes = list(self._routes(graph))
        doc_text = ""
        doc_file = repo_root / self.DOC
        if doc_file.exists():
            doc_text = doc_file.read_text(encoding="utf-8")
        doc_norms = {self._norm(p)
                     for p in self.DOC_PATH_RE.findall(doc_text)}
        code_norms = {self._norm(path) for _, path, *_ in routes}

        for method, path, fi, call, handler_key in routes:
            if fi.ctx.suppressed(call.lineno, self.id):
                continue
            if path not in self.EXEMPT_PATHS \
                    and self._norm(path) not in doc_norms:
                findings.append(fi.ctx.finding(
                    self.id, call, fi.qualname, path,
                    f"route {method} {path} is not documented in "
                    f"{self.DOC} — the doc and the route table are a "
                    "two-way contract (like K001<->knobs.md)"))
            if method in ("POST", "PUT") and handler_key:
                findings.extend(
                    self._check_validation(graph, fi, call, method,
                                           path, handler_key))

        # stale doc rows: documented paths no route serves anymore
        for norm in sorted(doc_norms - code_norms
                           - {self._norm(p) for p in self.EXEMPT_PATHS}):
            findings.append(app_ctx.finding(
                self.id, app_ctx.tree, "<docs>", norm,
                f"{self.DOC} documents {norm} but no route registers "
                "that path — remove the row or restore the route"))
        return findings

    def _routes(self, graph):
        for fi in graph.functions.values():
            for c in fi.calls:
                tail = c.name.split(".")[-1]
                if tail not in self.ROUTE_TAILS:
                    continue
                args = c.node.args
                if len(args) < 2 or not (
                        isinstance(args[0], ast.Constant)
                        and isinstance(args[0].value, str)):
                    continue
                _, handler_key = graph.resolve_ref(fi, args[1])
                yield (self.ROUTE_TAILS[tail], args[0].value, fi,
                       c.node, handler_key)

    # -- payload validation --------------------------------------------

    def _check_validation(self, graph, reg_fi, call, method, path,
                          handler_key) -> Iterator[Finding]:
        handler = graph.functions.get(handler_key)
        if handler is None:
            return
        if not self._reaches(graph, handler, self._reads_body):
            return           # no body parse (path/query-only POST)
        if self._reaches(graph, handler, self._validates):
            return
        yield reg_fi.ctx.finding(
            self.id, call, reg_fi.qualname, f"{path}:validate",
            f"handler `{handler.short}` for {method} {path} parses a "
            "JSON body but never reaches an api/schemas validator — "
            "unvalidated wire input feeds the cluster control plane")

    def _reaches(self, graph, handler, pred, depth: int = 3) -> bool:
        seen = {handler.key}
        frontier = [handler]
        while frontier and depth >= 0:
            nxt = []
            for fi in frontier:
                for c in fi.calls:
                    if pred(graph, c):
                        return True
                    if c.target and c.target not in seen:
                        seen.add(c.target)
                        nxt.append(graph.functions[c.target])
            frontier = nxt
            depth -= 1
        return False

    @staticmethod
    def _reads_body(graph, c) -> bool:
        return c.name.split(".")[-1] == "json" \
            and isinstance(c.node.func, ast.Attribute)

    def _validates(self, graph, c) -> bool:
        if c.target is not None:
            mod = c.target.split(":", 1)[0]
            if mod == self.SCHEMAS_MODULE or mod.endswith(".schemas") \
                    or mod == "schemas":
                return True
        tail = c.name.split(".")[-1]
        if ".schemas." in c.name or c.name.startswith("schemas."):
            return True
        # raising schemas.ValidationError inline IS contract enforcement
        # (error_middleware converts it to a structured 400)
        return tail in ("require_fields", "ValidationError") \
            or tail.startswith(("validate_", "parse_positive"))


FLOW_RULES = (TransitiveAsyncRule(), LockHeldAcrossAwaitRule(),
              TaintedDeterminismRule(), WireContractRule())
