"""comfyui_distributed_tpu — a TPU-native distributed diffusion framework.

A ground-up rebuild of the capabilities of ComfyUI-Distributed
(reference: /root/reference, a master/worker HTTP job farm for diffusion
workloads) designed for TPU hardware:

- compute is SPMD over a ``jax.sharding.Mesh`` (data/tensor/sequence axes)
  instead of one OS process per GPU;
- the "collector" gather is an on-pod ``all_gather`` over ICI instead of
  base64-PNG HTTP envelopes (reference ``nodes/collector.py:143-178``);
- the Ultimate-SD-Upscale tile scatter is a statically sharded computation
  with host-level requeue, instead of a per-tile HTTP pull queue
  (reference ``upscale/modes/static.py``);
- a thin HTTP control plane retains the reference's public API surface
  (``POST /distributed/queue`` et al., reference ``docs/comfyui-distributed-api.md``)
  because orchestration/config/health are transport-agnostic.

Subpackages
-----------
utils       config / logging / codecs / constants (reference L0, ``utils/``)
parallel    mesh bootstrap, sharding, RNG, collectives (net-new: TPU substrate)
models      flax diffusion models (UNet / DiT / VAE) — supplied here because the
            reference free-rides on ComfyUI for model code
diffusion   schedules, samplers, guidance, pipelines
tiles       tile grid math + sharded tile engine (reference L2, ``upscale/``)
graph       workflow graph: nodes, executor, prompt transforms (reference L3/L4)
cluster     job store, scheduler, dispatch, orchestration (reference L4, ``api/``)
api         aiohttp control plane (reference L5, ``api/*_routes.py``)
workers     host-controller process management (reference L1, ``workers/``)
"""

__version__ = "0.1.0"
