"""Two-level logging + execution tracing.

Parity: reference ``utils/logging.py:15-43`` (always-on ``log`` and a
config-gated ``debug_log`` whose gate is cached with a short TTL) and
``utils/trace_logger.py:4-13`` (per-run trace IDs prefixed
``[Distributed][exec:<id>]``).
"""

from __future__ import annotations

import collections
import secrets
import sys
import time
from typing import Callable

_PREFIX = "[Distributed-TPU]"

# Rolling in-memory buffer of recent log lines, served by
# /distributed/local_log and proxied cross-host by
# /distributed/remote_worker_log (reference keeps the same rolling buffer
# on app.logger, api/worker_routes.py:348-390).
_BUFFER_LINES = 400
_log_buffer: collections.deque[str] = collections.deque(maxlen=_BUFFER_LINES)


def get_log_buffer() -> list[str]:
    return list(_log_buffer)

# TTL cache of the debug gate so hot loops don't re-read config every call
# (reference utils/logging.py:15-39 uses a 5 s TTL for the same reason).
_DEBUG_TTL = 5.0
_debug_cache: tuple[float, bool] | None = None
_debug_source: Callable[[], bool] | None = None


def set_debug_source(fn: Callable[[], bool] | None) -> None:
    """Install the callable that reports whether debug logging is enabled
    (normally ``config.get_setting('debug')``); ``None`` resets to env."""
    global _debug_source, _debug_cache
    _debug_source = fn
    _debug_cache = None


def _debug_enabled() -> bool:
    global _debug_cache
    now = time.monotonic()
    if _debug_cache is not None and now - _debug_cache[0] < _DEBUG_TTL:
        return _debug_cache[1]
    # the env var is ALWAYS honored; an installed source (the config's
    # settings.debug) can only add to it — so sources never need to
    # re-implement the env check
    from .constants import DEBUG

    enabled = DEBUG.get()
    if not enabled and _debug_source is not None:
        try:
            enabled = bool(_debug_source())
        except Exception:
            enabled = False
    _debug_cache = (now, enabled)
    return enabled


def log(msg: str) -> None:
    line = f"{_PREFIX} {msg}"
    _log_buffer.append(f"{time.strftime('%H:%M:%S')} {line}")
    print(line, file=sys.stderr, flush=True)


def debug_log(msg: str) -> None:
    if _debug_enabled():
        log(f"[DEBUG] {msg}")


# --- execution tracing -----------------------------------------------------

def new_trace_id() -> str:
    """``exec_<ms>_<6hex>`` — same shape as reference trace IDs
    (``web/executionUtils.js:26`` / ``api/queue_orchestration.py:38-39``)."""
    return f"exec_{int(time.time() * 1000)}_{secrets.token_hex(3)}"


def trace_info(trace_id: str | None, msg: str) -> None:
    log(f"[exec:{trace_id or '-'}] {msg}")


def trace_debug(trace_id: str | None, msg: str) -> None:
    debug_log(f"[exec:{trace_id or '-'}] {msg}")
