"""Shared utilities (reference L0: ``utils/``)."""
