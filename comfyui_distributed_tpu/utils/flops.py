"""Analytic FLOP counting by walking a jaxpr.

XLA's ``compiled.cost_analysis()`` on TPU reports near-zero FLOPs for
convolutions that lower into custom fusions, which makes benchmark MFU
numbers meaningless (observed: SDXL counted at ~10× under its analytic
FLOPs). This walks the traced jaxpr instead and counts the two op
families that carry essentially all diffusion-model FLOPs:

- ``dot_general``: 2 · batch · M · N · K
- ``conv_general_dilated``: 2 · out_elements · K_spatial · C_in / groups

Control-flow bodies (scan/while/cond/pjit/remat/custom_jvp…) are
recursed into, with scan bodies multiplied by their trip count — so a
30-step sampler scan counts 30×. Elementwise/normalization work is
ignored (<1% for these models). Counts are *algorithmic* FLOPs — what
MFU conventionally divides by — not whatever XLA rewrites them into.
"""

from __future__ import annotations

import math

import jax
import numpy as np


def _dot_flops(eqn) -> float:
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    contract = math.prod(a.shape[i] for i in lc) if lc else 1
    batch = math.prod(a.shape[i] for i in lb) if lb else 1
    m = math.prod(a.shape[i] for i in range(len(a.shape))
                  if i not in lc and i not in lb)
    n = math.prod(b.shape[i] for i in range(len(b.shape))
                  if i not in rc and i not in rb)
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    groups = (eqn.params.get("feature_group_count", 1)
              * eqn.params.get("batch_group_count", 1))
    k_spatial = math.prod(rhs.shape[i] for i in dn.rhs_spec[2:])
    c_in = lhs.shape[dn.lhs_spec[1]]
    return 2.0 * out.size * k_spatial * c_in / max(groups, 1)


def _jaxpr_flops(jaxpr) -> float:
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "scan":
            total += eqn.params["length"] * _jaxpr_flops(
                eqn.params["jaxpr"].jaxpr)
        elif name == "pallas_call":
            # the kernel body runs once PER GRID STEP — counting it once
            # undercounts flash attention ~1000× (bq·bk block vs full N²)
            gm = eqn.params.get("grid_mapping")
            grid = math.prod(gm.grid) if gm is not None and gm.grid else 1
            sub = eqn.params.get("jaxpr")
            if sub is not None:
                total += grid * _jaxpr_flops(
                    sub.jaxpr if hasattr(sub, "jaxpr") else sub)
        elif name == "while":
            # trip count unknowable statically; count one iteration
            total += _jaxpr_flops(eqn.params["body_jaxpr"].jaxpr)
        elif name == "cond":
            branches = [_jaxpr_flops(b.jaxpr)
                        for b in eqn.params["branches"]]
            total += max(branches) if branches else 0.0
        else:
            for key in ("jaxpr", "call_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    total += _jaxpr_flops(
                        sub.jaxpr if hasattr(sub, "jaxpr") else sub)
                    break
    return total


def estimate_flops(fn, *args, **kwargs) -> float:
    """Analytic matmul+conv FLOPs of one call of ``fn(*args)``.

    Tracing is abstract (no execution, no device); args may be concrete
    arrays or ``jax.ShapeDtypeStruct``s."""
    closed = jax.make_jaxpr(fn, **kwargs)(*args)
    return _jaxpr_flops(closed.jaxpr)


def shape_args(*specs) -> tuple:
    """Convenience: (shape, dtype) pairs → ShapeDtypeStructs."""
    return tuple(jax.ShapeDtypeStruct(s, np.dtype(d)) for s, d in specs)
