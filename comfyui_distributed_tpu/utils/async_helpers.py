"""Sync↔async bridging for node execution.

Parity: reference ``utils/async_helpers.py:13-54``
(``run_async_in_server_loop``). Graph execution is synchronous (JAX compute
blocks a thread); the control plane is an asyncio loop. Nodes that must
talk to the control plane (collector send/collect) hop onto the loop via
``run_in_loop``. The controller itself is async-first — this bridge exists
only at the node-execution boundary (SURVEY §7 hard-part #5).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
from typing import Any, Coroutine, Optional


def run_in_loop(
    coro: Coroutine,
    loop: asyncio.AbstractEventLoop,
    timeout: Optional[float] = None,
) -> Any:
    """Run ``coro`` on ``loop`` from a non-loop thread and wait for it."""
    if loop.is_closed():
        raise RuntimeError("event loop is closed")
    try:
        running = asyncio.get_running_loop()
    except RuntimeError:
        running = None
    if running is loop:
        raise RuntimeError(
            "run_in_loop called from the loop's own thread; await instead"
        )
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    try:
        return fut.result(timeout)
    except concurrent.futures.TimeoutError:
        fut.cancel()
        raise TimeoutError(f"coroutine did not finish within {timeout}s")
