"""Atomic JSON persistence shared by the merge-on-save registries.

Two artifacts persist next to the XLA compilation cache and are written
by multiple processes (serving master, warmup CLI, autotune sweeps): the
shape catalog (``cluster/shape_catalog.py``) and the attention tuning
table (``ops/autotune.py``). Both follow the same contract:

- **reads never crash**: a missing, unreadable, or garbled file degrades
  to "no data" (the caller logs at debug level and starts empty);
- **writes are atomic**: payload lands in a sibling ``.tmp`` file first
  and is ``os.replace``d into place, so a concurrent reader never sees a
  half-written file;
- **savers merge first**: callers re-read the file before writing so
  concurrent writers union rather than clobber (the merge policy itself
  — set union vs keyed overlay — stays with the caller).

Extracted from the shape catalog's PR 4 implementation so the tuning
table can't drift from it.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Optional

from .logging import debug_log


def read_json(path: "Path | str") -> Optional[Any]:
    """Parsed JSON content of ``path``, or None when the file is missing,
    unreadable, or not valid JSON (never raises)."""
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError, AttributeError):
        return None


def atomic_write_json(path: "Path | str", payload: Any,
                      indent: int = 1) -> bool:
    """Serialize ``payload`` and atomically replace ``path`` with it
    (tmp + rename; parent directories are created). Returns False —
    never raises — when the write fails."""
    path = Path(path)
    tmp = path.with_suffix(".tmp")
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp.write_text(json.dumps(payload, indent=indent))
        os.replace(tmp, path)
        return True
    except (OSError, TypeError, ValueError) as e:
        debug_log(f"jsonio: atomic write to {path} failed: {e}")
        return False
