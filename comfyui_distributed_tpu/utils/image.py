"""Image tensor ↔ PNG codecs — control-plane edge only.

Parity: reference ``utils/image.py:8-24`` (tensor[B,H,W,C] ↔ PIL) and the
base64-PNG envelope of the collector protocol (``nodes/collector.py:152-174``,
``api/job_routes.py:104-132``). In this framework these codecs are used ONLY
at the UI/cross-pod edge — on-pod results stay device arrays (SURVEY §7
translation table) — which is precisely the reference's "single biggest
overhead" eliminated (SURVEY §3 hot-loop note).
"""

from __future__ import annotations

import base64
import io

import numpy as np

from .exceptions import ValidationError


def to_uint8(images) -> np.ndarray:
    """[B,H,W,C] float [0,1] (or uint8) → uint8, contiguous."""
    arr = np.asarray(images)
    if arr.ndim == 3:
        arr = arr[None]
    if arr.ndim != 4:
        raise ValidationError(f"expected [B,H,W,C] image batch, got shape {arr.shape}")
    if arr.dtype != np.uint8:
        arr = (np.clip(arr.astype(np.float32), 0.0, 1.0) * 255.0).round().astype(np.uint8)
    return np.ascontiguousarray(arr)


def from_uint8(arr: np.ndarray) -> np.ndarray:
    """uint8 [B,H,W,C] → float32 [0,1]."""
    return arr.astype(np.float32) / 255.0


def encode_png(image: np.ndarray, compress_level: int = 0) -> bytes:
    """One [H,W,C] image → PNG bytes (compress_level 0 for speed, matching
    ``nodes/collector.py:156``)."""
    from PIL import Image

    img = Image.fromarray(to_uint8(image)[0])
    buf = io.BytesIO()
    img.save(buf, format="PNG", compress_level=compress_level)
    return buf.getvalue()


def decode_png(data: bytes) -> np.ndarray:
    """PNG bytes → float32 [H,W,C] in [0,1]."""
    from PIL import Image

    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB") if img.mode not in ("RGB", "RGBA") else img
    return np.asarray(img, dtype=np.float32) / 255.0


def encode_image_b64(image: np.ndarray, compress_level: int = 0) -> str:
    return base64.b64encode(encode_png(image, compress_level)).decode("ascii")


def decode_image_b64(data: str) -> np.ndarray:
    try:
        raw = base64.b64decode(data)
    except Exception as e:
        raise ValidationError(f"invalid base64 image payload: {e}") from e
    return decode_png(raw)
