"""Typed error hierarchy (parity: reference ``utils/exceptions.py:4-43``).

Unlike the reference — where the hierarchy exists but is "barely used in
practice" (SURVEY §2.5) — these are raised throughout the cluster layer so
callers can branch on failure class.
"""

from __future__ import annotations


class DistributedError(Exception):
    """Base class for all framework errors."""


class ConfigError(DistributedError):
    """Invalid or unwritable configuration."""


class WorkerError(DistributedError):
    """A worker host misbehaved (bad payload, bad state transition)."""

    def __init__(self, message: str, worker_id: str | None = None):
        super().__init__(message)
        self.worker_id = worker_id


class WorkerTimeoutError(WorkerError):
    """A worker host went silent past the heartbeat timeout."""


class WorkerNotAvailableError(WorkerError):
    """No reachable worker host satisfies the request."""


class JobQueueError(DistributedError):
    """Job store misuse: unknown job, double-init, enqueue on closed job."""

    def __init__(self, message: str, job_id: str | None = None):
        super().__init__(message)
        self.job_id = job_id


class TileCollectionError(DistributedError):
    """Tile/shard result collection failed or timed out."""


class ProcessError(DistributedError):
    """Host-controller process management failure."""


class TunnelError(DistributedError):
    """Tunnel (NAT traversal) lifecycle failure."""


class ValidationError(DistributedError):
    """Request/prompt payload failed validation (reference api/schemas.py)."""

    def __init__(self, message: str, field: str | None = None):
        super().__init__(message)
        self.field = field


class ShardingError(DistributedError):
    """Mesh/sharding construction failed (axis mismatch, bad device count)."""
