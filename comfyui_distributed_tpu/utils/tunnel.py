"""Cloudflare quick-tunnel manager for NAT traversal to remote hosts.

Parity: reference ``utils/cloudflare/`` — tunnel lifecycle under an async
lock with state restore from config (``tunnel.py:19-207``), binary
discovery (``binary.py:69-83``), a stdout reader thread capturing the
``*.trycloudflare.com`` URL plus errors into a rolling buffer
(``process_reader.py:14-97``), and state persistence that swaps the
config's master host to the public URL so remote workers call back through
the tunnel, restoring the previous host on stop (``state.py:28-81``).

Difference: the reference downloads ``cloudflared`` from GitHub at runtime
(``binary.py:47-66``); this build only *discovers* an installed binary
(env ``CLOUDFLARED_PATH`` → package-local ``bin/`` → ``$PATH``) and reports
a clear error otherwise — the controller may run with zero egress, and a
framework should not fetch executables behind the operator's back.
"""

from __future__ import annotations

import asyncio
import os
import re
import shutil
import subprocess
import sys
import threading
import time
from collections import deque
from pathlib import Path
from typing import Optional

from . import constants
from .config import load_config, update_config
from .exceptions import TunnelError
from .logging import debug_log, log

URL_RE = re.compile(r"https://[a-z0-9-]+\.trycloudflare\.com")
START_TIMEOUT = constants.TUNNEL_START_TIMEOUT.get()
LOG_BUFFER_LINES = 200


def find_cloudflared() -> Optional[str]:
    """Binary discovery (reference ``binary.py:69-83``)."""
    env = os.environ.get("CLOUDFLARED_PATH")
    if env and Path(env).is_file():
        return env
    local = _local_bin_path()
    if local.is_file():
        return str(local)
    return shutil.which("cloudflared")


def _local_bin_path() -> Path:
    name = "cloudflared.exe" if os.name == "nt" else "cloudflared"
    return Path(__file__).resolve().parent.parent / "bin" / name


# --- auto-download (reference utils/cloudflare/binary.py:47-66) -------------

# Pinned by default for reproducible installs (and so a shipped
# CDT_CLOUDFLARED_SHA256 pin stays meaningful); CDT_CLOUDFLARED_VERSION
# overrides, "latest" opts into the moving target. A pinned tag that
# 404s falls back to latest with a log line.
PINNED_VERSION = "2025.2.0"
RELEASE_URL = ("https://github.com/cloudflare/cloudflared/releases/"
               "download/{version}/{asset}")
LATEST_URL = ("https://github.com/cloudflare/cloudflared/releases/"
              "latest/download/{asset}")


def _platform_asset() -> str:
    """Release asset name for this platform (the reference keys the same
    GitHub release assets by os/arch)."""
    import platform as _platform

    mach = _platform.machine().lower()
    arch = {"x86_64": "amd64", "amd64": "amd64",
            "aarch64": "arm64", "arm64": "arm64"}.get(mach, "amd64")
    sysname = _platform.system().lower()
    if sysname == "windows":
        return f"cloudflared-windows-{arch}.exe"
    if sysname == "darwin":
        return f"cloudflared-darwin-{arch}.tgz"
    return f"cloudflared-linux-{arch}"


def _http_fetch(url: str, timeout: float = 120.0) -> bytes:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def download_cloudflared(dest_dir: Optional[Path] = None, fetcher=None,
                         expected_sha256: Optional[str] = None) -> str:
    """Download the platform's cloudflared release into the package-local
    ``bin/`` dir (where ``find_cloudflared`` looks first). Atomic write +
    exec bit; the SHA-256 is always computed and logged, and enforced
    when ``expected_sha256`` (or ``CDT_CLOUDFLARED_SHA256``) is set —
    release assets are fetched over TLS from GitHub, and a pinned hash
    upgrades that to content verification."""
    import hashlib
    import io
    import tarfile

    import tempfile

    asset = _platform_asset()
    fetch = fetcher or _http_fetch
    version = constants.CLOUDFLARED_VERSION.get() or PINNED_VERSION
    if version == "latest":
        url = LATEST_URL.format(asset=asset)
    else:
        url = RELEASE_URL.format(version=version, asset=asset)
    log(f"downloading {asset} ({version}) from GitHub releases")
    try:
        data = fetch(url)
    except Exception as e:
        if version == "latest":
            raise
        # a pinned tag can age out — latest keeps the feature working,
        # at the cost of reproducibility (logged so the drift is visible)
        log(f"pinned cloudflared {version} unavailable ({e}); "
            "falling back to latest")
        data = fetch(LATEST_URL.format(asset=asset))
    expected = expected_sha256 or constants.CLOUDFLARED_SHA256.get()
    digest = hashlib.sha256(data).hexdigest()
    if expected and digest != expected.strip().lower():
        raise TunnelError(
            f"cloudflared download checksum mismatch: got {digest}, "
            f"expected {expected} — refusing to install")
    if asset.endswith(".tgz"):
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
            try:
                member = tar.extractfile(tar.getmember("cloudflared"))
            except KeyError:
                member = None
            if member is None:
                raise TunnelError("cloudflared missing from release tgz")
            data = member.read()
    dest = (Path(dest_dir) if dest_dir else _local_bin_path().parent)
    dest.mkdir(parents=True, exist_ok=True)
    out = dest / _local_bin_path().name
    # unique temp + os.replace: concurrent downloaders (master + local
    # worker) can't corrupt each other, and replace overwrites atomically
    # on every platform (same discipline as config.save_config)
    fd, tmp = tempfile.mkstemp(dir=str(dest), prefix=".cloudflared_")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.chmod(tmp, 0o755)
        os.replace(tmp, out)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    log(f"cloudflared installed at {out} (sha256 {digest})")
    return str(out)


def ensure_cloudflared(fetcher=None) -> str:
    """Discovery first, download as the fallback (reference
    ``binary.py:69-83`` order). ``CDT_CLOUDFLARED_AUTO_DOWNLOAD=0``
    restores the old discovery-only behavior (e.g. air-gapped hosts
    where the download can only time out)."""
    found = find_cloudflared()
    if found:
        return found
    if not constants.CLOUDFLARED_AUTO_DOWNLOAD.get():
        raise TunnelError(
            "cloudflared binary not found and auto-download is disabled — "
            "install it or set CLOUDFLARED_PATH")
    try:
        return download_cloudflared(fetcher=fetcher)
    except TunnelError:
        raise
    except Exception as e:
        raise TunnelError(
            f"cloudflared not found and download failed ({e}) — install "
            "it manually or set CLOUDFLARED_PATH") from e


class _ProcessReader(threading.Thread):
    """Scan tunnel stdout for the public URL + keep a rolling log buffer
    (reference ``process_reader.py:14-97``)."""

    def __init__(self, proc: subprocess.Popen):
        super().__init__(daemon=True)
        self.proc = proc
        self.url: Optional[str] = None
        self.error: Optional[str] = None
        self.lines: deque[str] = deque(maxlen=LOG_BUFFER_LINES)
        self._url_event = threading.Event()

    def run(self) -> None:
        stream = self.proc.stdout
        if stream is None:
            return
        for raw in stream:
            line = raw.decode("utf-8", "replace").rstrip() \
                if isinstance(raw, bytes) else raw.rstrip()
            self.lines.append(line)
            if self.url is None:
                m = URL_RE.search(line)
                if m:
                    self.url = m.group(0)
                    self._url_event.set()
            low = line.lower()
            if "error" in low and self.error is None:
                self.error = line

    def wait_for_url(self, timeout: float) -> Optional[str]:
        self._url_event.wait(timeout)
        return self.url


class TunnelManager:
    """Lifecycle of one quick tunnel exposing this controller's port."""

    def __init__(self, config_path: Optional[Path] = None):
        self.config_path = config_path
        self._lock = asyncio.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[_ProcessReader] = None
        self.url: Optional[str] = None

    # --- status -------------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def status(self) -> dict:
        cfg_tunnel = load_config(self.config_path).get("tunnel", {})
        return {
            "running": self.running,
            "url": self.url or cfg_tunnel.get("url"),
            "enabled": bool(cfg_tunnel.get("enabled")),
            "binary": find_cloudflared(),
            "log": list(self._reader.lines) if self._reader else [],
            "error": self._reader.error if self._reader else None,
        }

    # --- lifecycle ----------------------------------------------------------

    async def start_tunnel(self, port: int) -> str:
        async with self._lock:
            if self.running and self.url:
                return self.url
            # the download is blocking urllib I/O — keep it off the event
            # loop (same executor discipline as wait_for_url below)
            binary = await asyncio.get_running_loop().run_in_executor(
                None, ensure_cloudflared)
            # arm auth BEFORE the URL becomes publicly routable — once
            # cloudflared registers with the edge, requests can arrive;
            # generating the token afterwards would leave a window with a
            # fully open mutating control plane
            self._ensure_auth_token()
            cmd = [binary, "tunnel", "--url", f"http://127.0.0.1:{port}"]
            debug_log(f"starting tunnel: {' '.join(cmd)}")
            # fork+exec can stall hundreds of ms on a loaded host — keep
            # it off the event loop (lint rule A001)
            self._proc = await asyncio.get_running_loop().run_in_executor(
                None, lambda: subprocess.Popen(
                    cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
            self._reader = _ProcessReader(self._proc)
            self._reader.start()
            url = await asyncio.get_running_loop().run_in_executor(
                None, self._reader.wait_for_url, START_TIMEOUT)
            if not url:
                err = self._reader.error or "no URL within timeout"
                await self._stop_locked()
                raise TunnelError(f"tunnel failed to start: {err}")
            self.url = url
            self._persist_started(url, port)
            log(f"tunnel up: {url}")
            return url

    async def stop_tunnel(self) -> bool:
        async with self._lock:
            return await self._stop_locked()

    async def _stop_locked(self) -> bool:
        was_running = self.running
        if self._proc is not None:
            self._proc.terminate()
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self._proc.wait, 5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None
        self.url = None
        self._persist_stopped()
        return was_running

    # --- state persistence (reference state.py:28-81) -----------------------

    def _ensure_auth_token(self) -> None:
        """A public tunnel must never expose an unauthenticated control
        plane: if no cluster token exists, generate one, persist it, and
        print it ONCE so the operator can hand it to workers/dashboards
        (env ``CDT_AUTH_TOKEN`` overrides; see ``utils/auth.py``)."""
        from .auth import AUTH_ENV, configured_token, generate_token

        if configured_token(load_config(self.config_path)):
            return
        token = generate_token()

        def mutate(cfg: dict) -> None:
            cfg.setdefault("settings", {}).setdefault("auth_token", token)
        update_config(mutate, self.config_path)
        # The token goes to the operator's terminal ONLY — log() feeds the
        # rolling buffer behind /distributed/local_log, which would leak
        # the secret through the very tunnel it protects.
        print(f"[Distributed-TPU] auth token generated for the public "
              f"tunnel: {token}", file=sys.stderr, flush=True)
        log(f"auth token generated and persisted to settings.auth_token — "
            f"pass it to workers/dashboards via {AUTH_ENV} or the "
            "X-CDT-Auth header; mutating routes now require it")

    def _persist_started(self, url: str, port: int) -> None:
        def mutate(cfg: dict) -> None:
            tunnel = cfg.setdefault("tunnel", {})
            master = cfg.setdefault("master", {})
            # remote workers must call back through the tunnel: swap the
            # advertised master host, remembering the previous value
            if master.get("host") != url:
                tunnel["previous_master_host"] = master.get("host", "")
            tunnel.update(enabled=True, url=url, port=port,
                          started_at=time.time())
            master["host"] = url
        update_config(mutate, self.config_path)

    def _persist_stopped(self) -> None:
        def mutate(cfg: dict) -> None:
            tunnel = cfg.setdefault("tunnel", {})
            master = cfg.setdefault("master", {})
            if tunnel.get("url") and master.get("host") == tunnel["url"]:
                master["host"] = tunnel.get("previous_master_host", "")
            tunnel.update(enabled=False, url=None)
        update_config(mutate, self.config_path)


_manager: Optional[TunnelManager] = None


def get_tunnel_manager(config_path: Optional[Path] = None) -> TunnelManager:
    global _manager
    if _manager is None or (config_path is not None
                            and _manager.config_path != config_path):
        _manager = TunnelManager(config_path)
    return _manager
