"""Audio envelope codec.

Parity: reference ``utils/audio_payload.py:11-103`` — AUDIO dicts
(``{"waveform": [B,C,S], "sample_rate": int}``) travel as base64 float32
with shape/dtype/size validation and a byte cap.
"""

from __future__ import annotations

import base64
from typing import Any

import numpy as np

from . import constants
from .exceptions import ValidationError


def encode_audio(audio: dict[str, Any]) -> dict[str, Any]:
    wf = np.asarray(audio.get("waveform"))
    if wf.ndim != 3:
        raise ValidationError(f"waveform must be [B,C,S], got shape {wf.shape}")
    wf = np.ascontiguousarray(wf.astype(np.float32))
    if wf.nbytes > constants.MAX_AUDIO_PAYLOAD_BYTES:
        raise ValidationError(
            f"audio payload {wf.nbytes} bytes exceeds cap "
            f"{constants.MAX_AUDIO_PAYLOAD_BYTES}"
        )
    return {
        "data": base64.b64encode(wf.tobytes()).decode("ascii"),
        "dtype": "float32",
        "shape": list(wf.shape),
        "sample_rate": int(audio.get("sample_rate", 44100)),
    }


def decode_audio(envelope: dict[str, Any]) -> dict[str, Any]:
    for field in ("data", "shape", "sample_rate"):
        if field not in envelope:
            raise ValidationError(f"audio envelope missing {field!r}", field=field)
    if envelope.get("dtype", "float32") != "float32":
        raise ValidationError(f"unsupported audio dtype {envelope['dtype']!r}")
    shape = tuple(int(s) for s in envelope["shape"])
    if len(shape) != 3 or any(s < 0 for s in shape):
        raise ValidationError(f"invalid audio shape {shape}")
    expected = int(np.prod(shape)) * 4
    if expected > constants.MAX_AUDIO_PAYLOAD_BYTES:
        raise ValidationError("audio envelope exceeds byte cap")
    try:
        raw = base64.b64decode(envelope["data"])
    except Exception as e:
        raise ValidationError(f"invalid base64 audio payload: {e}") from e
    if len(raw) != expected:
        raise ValidationError(
            f"audio payload size {len(raw)} != expected {expected} for shape {shape}"
        )
    wf = np.frombuffer(raw, dtype=np.float32).reshape(shape)
    return {"waveform": wf, "sample_rate": int(envelope["sample_rate"])}


# --- WAV file codec (stdlib only) ------------------------------------------
# The reference free-rides on ComfyUI's LoadAudio/SaveAudio for files and
# only ships the transport envelope (utils/audio_payload.py); a standalone
# framework needs the file edge too. 16-bit PCM WAV via the stdlib `wave`
# module — no external deps, good enough for the speech/music clips the
# collector/divider fabric carries.

def wav_bytes(waveform: Any, sample_rate: int) -> bytes:
    """Encode one clip ``[C, S]`` (float32, [-1, 1]) as 16-bit PCM WAV."""
    import io
    import wave as _wave

    wf = np.asarray(waveform, dtype=np.float32)
    if wf.ndim == 1:
        wf = wf[None]
    if wf.ndim != 2:
        raise ValidationError(f"wav clip must be [C,S], got shape {wf.shape}")
    pcm = (np.clip(wf, -1.0, 1.0) * 32767.0).astype("<i2")
    buf = io.BytesIO()
    with _wave.open(buf, "wb") as w:
        w.setnchannels(pcm.shape[0])
        w.setsampwidth(2)
        w.setframerate(int(sample_rate))
        w.writeframes(np.ascontiguousarray(pcm.T).tobytes())  # interleaved
    return buf.getvalue()


def wav_decode(data: bytes) -> dict[str, Any]:
    """Decode a PCM WAV (8/16/32-bit int) into an AUDIO dict
    ``{"waveform": [1, C, S] float32, "sample_rate": int}``."""
    import io
    import wave as _wave

    try:
        with _wave.open(io.BytesIO(data), "rb") as w:
            n_ch = w.getnchannels()
            width = w.getsampwidth()
            rate = w.getframerate()
            frames = w.readframes(w.getnframes())
    except (_wave.Error, EOFError) as e:
        raise ValidationError(f"invalid WAV data: {e}") from e
    if width == 2:
        pcm = np.frombuffer(frames, dtype="<i2").astype(np.float32) / 32768.0
    elif width == 4:
        pcm = np.frombuffer(frames, dtype="<i4").astype(np.float32) / 2147483648.0
    elif width == 1:  # 8-bit WAV is unsigned
        pcm = (np.frombuffer(frames, dtype=np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValidationError(f"unsupported WAV sample width {width}")
    if n_ch > 0 and pcm.size % n_ch:
        pcm = pcm[: pcm.size - pcm.size % n_ch]
    wf = pcm.reshape(-1, max(1, n_ch)).T[None]          # [1, C, S]
    return {"waveform": np.ascontiguousarray(wf), "sample_rate": int(rate)}
