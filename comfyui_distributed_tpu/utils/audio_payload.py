"""Audio envelope codec.

Parity: reference ``utils/audio_payload.py:11-103`` — AUDIO dicts
(``{"waveform": [B,C,S], "sample_rate": int}``) travel as base64 float32
with shape/dtype/size validation and a byte cap.
"""

from __future__ import annotations

import base64
from typing import Any

import numpy as np

from . import constants
from .exceptions import ValidationError


def encode_audio(audio: dict[str, Any]) -> dict[str, Any]:
    wf = np.asarray(audio.get("waveform"))
    if wf.ndim != 3:
        raise ValidationError(f"waveform must be [B,C,S], got shape {wf.shape}")
    wf = np.ascontiguousarray(wf.astype(np.float32))
    if wf.nbytes > constants.MAX_AUDIO_PAYLOAD_BYTES:
        raise ValidationError(
            f"audio payload {wf.nbytes} bytes exceeds cap "
            f"{constants.MAX_AUDIO_PAYLOAD_BYTES}"
        )
    return {
        "data": base64.b64encode(wf.tobytes()).decode("ascii"),
        "dtype": "float32",
        "shape": list(wf.shape),
        "sample_rate": int(audio.get("sample_rate", 44100)),
    }


def decode_audio(envelope: dict[str, Any]) -> dict[str, Any]:
    for field in ("data", "shape", "sample_rate"):
        if field not in envelope:
            raise ValidationError(f"audio envelope missing {field!r}", field=field)
    if envelope.get("dtype", "float32") != "float32":
        raise ValidationError(f"unsupported audio dtype {envelope['dtype']!r}")
    shape = tuple(int(s) for s in envelope["shape"])
    if len(shape) != 3 or any(s < 0 for s in shape):
        raise ValidationError(f"invalid audio shape {shape}")
    expected = int(np.prod(shape)) * 4
    if expected > constants.MAX_AUDIO_PAYLOAD_BYTES:
        raise ValidationError("audio envelope exceeds byte cap")
    try:
        raw = base64.b64decode(envelope["data"])
    except Exception as e:
        raise ValidationError(f"invalid base64 audio payload: {e}") from e
    if len(raw) != expected:
        raise ValidationError(
            f"audio payload size {len(raw)} != expected {expected} for shape {shape}"
        )
    wf = np.frombuffer(raw, dtype=np.float32).reshape(shape)
    return {"waveform": wf, "sample_rate": int(envelope["sample_rate"])}
