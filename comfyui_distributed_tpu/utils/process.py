"""Cross-platform process primitives (parity: reference
``utils/process.py:9-37``)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys


def is_process_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    if os.name == "posix":
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True
        # signal-0 succeeds on zombies; consult /proc state where available
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                stat = f.read()
            state = stat.rsplit(b") ", 1)[-1][:1]
            return state != b"Z"
        except OSError:
            return True
    out = subprocess.run(  # pragma: no cover - windows
        ["tasklist", "/FI", f"PID eq {pid}"], capture_output=True, text=True)
    return str(pid) in out.stdout


def terminate_process(pid: int, force: bool = False) -> None:
    try:
        if os.name == "posix":
            os.kill(pid, signal.SIGKILL if force else signal.SIGTERM)
        else:  # pragma: no cover - windows
            subprocess.run(["taskkill", "/PID", str(pid)] +
                           (["/F"] if force else []), capture_output=True)
    except (ProcessLookupError, PermissionError):
        pass


def python_executable() -> str:
    return sys.executable
