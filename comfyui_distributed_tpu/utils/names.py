"""Shared filesystem-name hygiene.

One sanitizer for every place a client- or job-derived string becomes a
path component (profiler trace names, tile-journal keys, shipped-workflow
lookups) — duplicated security-sensitive logic drifts.
"""

from __future__ import annotations

from .exceptions import ValidationError

_ALLOWED = set("-_.")


def sanitize_name(name: str, max_len: int = 120, fallback: str = "item") -> str:
    """Coerce to a safe single path component: non [alnum-_.] chars become
    '_', length capped; never empty, never a dot-only name."""
    out = "".join(c if (c.isalnum() or c in _ALLOWED) else "_"
                  for c in str(name))[:max_len]
    if not out or set(out) <= {"."}:
        return fallback
    return out


def validate_name(name: str, max_len: int = 120) -> str:
    """Strict variant: reject instead of coerce (for lookups where a
    coerced name would silently resolve to a different resource)."""
    if (not name or len(name) > max_len or ".." in name
            or not all(c.isalnum() or c in _ALLOWED for c in name)
            or set(name) <= {"."}):
        raise ValidationError(f"invalid name {name!r}")
    return name
