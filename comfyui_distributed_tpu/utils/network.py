"""HTTP helpers: pooled session, URL builders, host probing.

Parity: reference ``utils/network.py`` — shared aiohttp session with
connection limits (``:14-26``), URL builders with cloud-HTTPS heuristics
(``:88-105,139-183``), ``probe_worker`` (``:108-136``), standardized error
payloads (``:35-44``).
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

import aiohttp

from . import constants
from .logging import debug_log

_session: Optional[aiohttp.ClientSession] = None
_session_loop: Optional[asyncio.AbstractEventLoop] = None
_session_token: Optional[str] = None

# Domains that imply TLS regardless of scheme given (reference ``:96-104``)
_HTTPS_DOMAINS = ("trycloudflare.com", "ngrok.io", "ngrok-free.app", "proxy.runpod.net")


# Sessions displaced by a token rotation: in-flight requests keep using
# them (closing immediately would fail mid-job calls); they are drained on
# the next close_client_session().
_retired_sessions: list[aiohttp.ClientSession] = []

# Config path the outbound token is read from. A Controller constructed
# with an explicit config_path registers it here so inbound enforcement
# and outbound credentials always read the SAME config (otherwise a
# custom-path deployment would 401 its own peer calls).
_auth_config_path = None


def set_auth_config_path(path) -> None:
    global _auth_config_path
    _auth_config_path = path


def get_client_session() -> aiohttp.ClientSession:
    """Shared pooled session (limit 100, 30 per host), rebuilt if the
    running loop changed (tests create fresh loops) or the cluster auth
    token changed (tunnel start auto-generates one — every outbound
    peer call carries it from then on). The previous session is retired,
    NOT closed: coroutines holding it finish their in-flight requests."""
    global _session, _session_loop, _session_token
    from .auth import resolve_token

    loop = asyncio.get_event_loop()
    token = resolve_token(_auth_config_path)
    if (_session is None or _session.closed or _session_loop is not loop
            or token != _session_token):
        if _session is not None and not _session.closed \
                and _session_loop is loop:
            _retired_sessions.append(_session)
        headers = {}
        if token:
            from .auth import AUTH_HEADER

            headers[AUTH_HEADER] = token
        _session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=100, limit_per_host=30),
            timeout=aiohttp.ClientTimeout(total=constants.DISPATCH_TIMEOUT),
            headers=headers,
        )
        _session_loop = loop
        _session_token = token
    # deterministic chaos harness: when a FaultPlan is active (CDT_FAULTS
    # or test fixture) every outbound call flows through its injector;
    # inactive deployments pay one None check (cluster/faults.py)
    from ..cluster import faults

    return faults.wrap_session(_session)


async def close_client_session() -> None:
    global _session
    if _session is not None and not _session.closed:
        await _session.close()
    _session = None
    while _retired_sessions:
        s = _retired_sessions.pop()
        if not s.closed:
            try:
                await s.close()
            except Exception:
                pass


def normalize_host_url(address: str) -> str:
    """'host:port' or bare host → full URL; cloud domains force https."""
    addr = address.strip().rstrip("/")
    if not addr:
        return ""
    if "://" not in addr:
        scheme = "https" if any(d in addr for d in _HTTPS_DOMAINS) else "http"
        addr = f"{scheme}://{addr}"
    if addr.startswith("http://") and any(d in addr for d in _HTTPS_DOMAINS):
        addr = "https://" + addr[len("http://"):]
    return addr


def build_host_url(host: dict[str, Any], path: str = "") -> str:
    base = normalize_host_url(host.get("address", ""))
    return f"{base}{path}"


def build_master_callback_url(master_cfg: dict[str, Any], for_local: bool = False) -> str:
    """URL a worker host uses to reach the master; local workers short-
    circuit to loopback (reference ``:185-201``)."""
    port = master_cfg.get("port", 8288)
    if for_local or not master_cfg.get("host"):
        return f"http://127.0.0.1:{port}"
    base = normalize_host_url(str(master_cfg["host"]))
    if base.rsplit(":", 1)[-1].isdigit() or base.startswith("https://"):
        return base
    return f"{base}:{port}"


async def probe_host(address_or_host: Any, timeout: float | None = None
                     ) -> Optional[dict]:
    """GET /distributed/health → status dict, or None if unreachable
    (reference ``probe_worker`` GETs ``/prompt``, ``:108-136``)."""
    url = (
        build_host_url(address_or_host, "/distributed/health")
        if isinstance(address_or_host, dict)
        else normalize_host_url(str(address_or_host)) + "/distributed/health"
    )
    try:
        session = get_client_session()
        async with session.get(
            url, timeout=aiohttp.ClientTimeout(total=timeout or constants.PROBE_TIMEOUT)
        ) as resp:
            if resp.status != 200:
                return None
            return await resp.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        debug_log(f"probe {url} failed: {e}")
        return None


async def fetch_system_info(host: dict[str, Any], timeout: float = 10.0
                            ) -> Optional[dict]:
    """GET a host's ``/distributed/system_info`` → dict, or None when
    unreachable (shared by media sync's path-separator lookup and
    detection's machine-id comparison)."""
    url = build_host_url(host, "/distributed/system_info")
    try:
        session = get_client_session()
        async with session.get(
            url, timeout=aiohttp.ClientTimeout(total=timeout)
        ) as resp:
            if resp.status != 200:
                return None
            return await resp.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        debug_log(f"system_info fetch from {url} failed: {e}")
        return None


def error_payload(message: str, status: int = 400) -> dict:
    return {"error": message, "status": status}
