"""jax version-compatibility shims.

jax promoted ``shard_map`` into the top-level namespace (0.5+); 0.4.x
only ships ``jax.experimental.shard_map``, and its replication-check
kwarg is spelled ``check_rep`` instead of ``check_vma``. Every in-repo
shard_map call imports the symbol from here so one build runs on both
lines — the baked container image carries 0.4.37.
"""

from __future__ import annotations

import inspect

import jax

try:
    _shard_map = jax.shard_map
except AttributeError:                      # jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map

_CHECK_KW = ("check_vma"
             if "check_vma" in inspect.signature(_shard_map).parameters
             else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """``jax.shard_map`` with the replication-check kwarg normalized.

    ``check_vma=None`` means "library default" — except on 0.4.x, where
    the check is force-disabled: its scan-under-shard_map replication
    inference has a known false positive ("Scan carry input and output
    got mismatched replication types"), and jax's own error message
    prescribes exactly this workaround. On 0.5+ the default check stays
    on.
    """
    if check_vma is None and _CHECK_KW == "check_rep":
        check_vma = False
    if check_vma is not None:
        kw[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` polyfill: 0.4.x lacks it; ``psum`` of a unit
    literal is the classic equivalent (special-cased to constant-fold to
    the mapped axis size)."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:                  # jax < 0.6
        return jax.lax.psum(1, axis_name)


__all__ = ["axis_size", "shard_map"]
