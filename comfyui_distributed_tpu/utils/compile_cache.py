"""Persistent XLA compilation cache for the product server.

Full-scale programs here are expensive to compile — the SDXL 30-step
sampler scan is ~1 min on a v5e, and the offloaded one-jit ladders
(``diffusion/offload.py``) retrace per sigma-ladder LENGTH, so a user
changing ``steps`` from 30 to 25 pays a fresh full-model compile.
``bench.py`` has always enabled jax's persistent cache for itself; the
server gets the same treatment so restarts and step-count changes hit
disk instead of the compiler.

Reference analogue: ComfyUI relies on torch CUDA kernels being
pre-built, so its server has no compile-latency problem to manage; an
XLA-based server does, and this is the standard jax answer.

Knobs: ``CDT_COMPILE_CACHE_DIR`` (default
``~/.cache/comfyui_distributed_tpu/xla``; empty string disables).
"""

from __future__ import annotations

import os

_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache",
                        "comfyui_distributed_tpu", "xla")


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point jax's persistent compilation cache at ``path`` (or the
    ``CDT_COMPILE_CACHE_DIR``/default location). Never fatal: an
    unwritable directory just leaves caching off. Returns the directory
    in use, or None when disabled/unavailable."""
    d = path if path is not None else os.environ.get(
        "CDT_COMPILE_CACHE_DIR", _DEFAULT)
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        return d
    except Exception:  # noqa: BLE001 — degrade, don't crash the server
        return None
