"""Persistent XLA compilation cache for the product server AND bench.

Full-scale programs here are expensive to compile — the SDXL 30-step
sampler scan is ~1 min on a v5e, and the offloaded one-jit ladders
(``diffusion/offload.py``) retrace per sigma-ladder LENGTH, so a user
changing ``steps`` from 30 to 25 pays a fresh full-model compile.
This module is the ONE cache-config path: the server enables it at
controller boot, ``bench.py`` with ``min_compile_secs=0.0`` (on the
flaky tunneled accelerator a compile from ANY earlier attempt must be
reusable), and the warmup pass (``diffusion/warmup.py``) reads the same
directory to classify cache hits vs fresh compiles.

Reference analogue: ComfyUI relies on torch CUDA kernels being
pre-built, so its server has no compile-latency problem to manage; an
XLA-based server does, and this is the standard jax answer.

Knobs: ``CDT_COMPILE_CACHE_DIR`` (default
``~/.cache/comfyui_distributed_tpu/xla``; empty string disables).
"""

from __future__ import annotations

import os
from typing import Optional

from .logging import log

_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache",
                        "comfyui_distributed_tpu", "xla")

# resolved state of the last enable_compile_cache call — the warmup
# pass and telemetry read it instead of re-deriving the env logic
_state: dict = {"dir": None, "reason": "never enabled"}


def cache_dir_default() -> str:
    """The directory ``enable_compile_cache()`` would resolve to (env or
    default), WITHOUT enabling anything — the shape catalog persists
    next to it even when caching is off."""
    from .constants import COMPILE_CACHE_DIR

    return COMPILE_CACHE_DIR.get() or _DEFAULT


def active_cache_dir() -> Optional[str]:
    """Directory the live jax process is actually caching into (None
    when disabled/never enabled)."""
    return _state["dir"]


def enable_compile_cache(path: Optional[str] = None,
                         min_compile_secs: float = 1.0) -> Optional[str]:
    """Point jax's persistent compilation cache at ``path`` (or the
    ``CDT_COMPILE_CACHE_DIR``/default location). Never fatal: an
    unwritable directory just leaves caching off — but never *silently*:
    the resolved directory (or the reason caching is off) is logged and
    exported as the ``cdt_compile_cache_enabled`` gauge. Returns the
    directory in use, or None when disabled/unavailable.

    ``min_compile_secs``: persistence threshold. The server default
    (1.0 s) skips trivial programs; bench and warmup pass 0.0 so every
    program a retry might need lands on disk.
    """
    from .constants import COMPILE_CACHE_DIR

    env = COMPILE_CACHE_DIR.get()
    d = path if path is not None else (_DEFAULT if env is None else env)
    if not d:
        _set_state(None, "disabled (CDT_COMPILE_CACHE_DIR='')")
        return None
    try:
        os.makedirs(d, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        _set_state(d, None)
        return d
    except Exception as e:  # noqa: BLE001 — degrade, don't crash the server
        _set_state(None, f"unavailable: {e}")
        return None


def _set_state(d: Optional[str], reason: Optional[str]) -> None:
    _state["dir"] = d
    _state["reason"] = reason
    if d is not None:
        log(f"compile cache: persisting XLA programs under {d}")
    else:
        log(f"compile cache: OFF — {reason}")
    try:
        from ..telemetry import enabled as _tm_enabled
        from ..telemetry import metrics as _tm

        if _tm_enabled():
            _tm.COMPILE_CACHE_ENABLED.set(1.0 if d else 0.0)
    except Exception:  # noqa: BLE001 — telemetry is never load-bearing
        pass
