"""Framework tunables, all env-overridable.

Parity with reference ``utils/constants.py:1-68`` (heartbeat cadence, payload
caps, orchestration concurrencies), re-keyed for the TPU build. Values are
read once at import; tests may monkeypatch module attributes directly.
"""

from __future__ import annotations

import os


_warned_envs: set[str] = set()


def _warn_malformed(name: str, default) -> None:
    if name not in _warned_envs:
        _warned_envs.add(name)
        from .logging import log   # lazy: keep this module stdlib-only

        log(f"ignoring malformed {name}={os.environ.get(name)!r}; "
            f"using default {default}")


def env_int(name: str, default: int) -> int:
    """Safe env-int read: a malformed value logs one warning and falls
    back to the default instead of raising mid-job (an env typo must not
    crash a worker's hot loop)."""
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        _warn_malformed(name, default)
        return default


def env_float(name: str, default: float) -> float:
    """Safe env-float read; same malformed-value fallback as ``env_int``."""
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        _warn_malformed(name, default)
        return default


# --- cluster liveness (reference utils/constants.py:43-68) -----------------
# Workers heartbeat per processed shard; master requeues work of hosts silent
# longer than HEARTBEAT_TIMEOUT (reference upscale/job_timeout.py:17-150).
# Optional crash-resume journal for long tile jobs (empty = disabled);
# completed tasks persist as CDTF frames and a restarted master resumes.
TILE_JOURNAL_DIR = os.environ.get("CDT_TILE_JOURNAL_DIR", "")

# Activation rematerialization for the big-model presets (trade FLOPs for
# HBM headroom on large latents/frames); tiny test configs ignore it.
REMAT = os.environ.get("CDT_REMAT", "") not in ("", "0", "false")

HEARTBEAT_INTERVAL = env_float("CDT_HEARTBEAT_INTERVAL", 10.0)
HEARTBEAT_TIMEOUT = env_float("CDT_HEARTBEAT_TIMEOUT", 60.0)

# --- payload caps ----------------------------------------------------------
# Reference caps tile uploads at 50 MB (upscale/job_store.py:12) and audio
# envelopes at 256 MB (utils/audio_payload.py:11-13).
MAX_PAYLOAD_SIZE = env_int("CDT_MAX_PAYLOAD_SIZE", 50 * 1024 * 1024)
MAX_AUDIO_PAYLOAD_BYTES = env_int("CDT_MAX_AUDIO_PAYLOAD_BYTES", 256 * 1024 * 1024)

# Max result items per flush from a worker host (reference MAX_BATCH=20,
# utils/constants.py; upscale/modes/static.py:303-306).
MAX_BATCH = env_int("CDT_MAX_BATCH", 20)

# --- orchestration concurrencies (reference utils/config.py:22-45) ---------
WORKER_PROBE_CONCURRENCY = env_int("CDT_PROBE_CONCURRENCY", 10)
WORKER_PREP_CONCURRENCY = env_int("CDT_PREP_CONCURRENCY", 4)
MEDIA_SYNC_CONCURRENCY = env_int("CDT_MEDIA_SYNC_CONCURRENCY", 4)

# --- timeouts --------------------------------------------------------------
PROBE_TIMEOUT = env_float("CDT_PROBE_TIMEOUT", 5.0)
DISPATCH_TIMEOUT = env_float("CDT_DISPATCH_TIMEOUT", 30.0)
MEDIA_SYNC_TIMEOUT = env_float("CDT_MEDIA_SYNC_TIMEOUT", 120.0)
COLLECT_POLL_TIMEOUT = env_float("CDT_COLLECT_POLL_TIMEOUT", 5.0)
# On collector drain timeout, silent-but-busy workers are granted grace
# extensions of COLLECT_GRACE_S each, at most COLLECT_MAX_GRACE_ROUNDS times
# (reference probes /prompt and extends while queue_remaining>0,
# nodes/collector.py:414-470).
COLLECT_GRACE_S = env_float("CDT_COLLECT_GRACE_S", 30.0)
COLLECT_MAX_GRACE_ROUNDS = env_int("CDT_COLLECT_MAX_GRACE_ROUNDS", 20)
JOB_INIT_GRACE = env_float("CDT_JOB_INIT_GRACE", 10.0)
WORK_REQUEST_BUDGET = env_float("CDT_WORK_REQUEST_BUDGET", 30.0)

# --- retries (reference upscale/worker_comms.py:88-104) --------------------
SEND_MAX_RETRIES = env_int("CDT_SEND_MAX_RETRIES", 5)
SEND_BACKOFF_BASE = env_float("CDT_SEND_BACKOFF_BASE", 0.5)
# Per-sleep ceiling for the unified RetryPolicy's full-jitter backoff
# (cluster/resilience.py) — exponential growth is clamped here.
RETRY_CAP_S = env_float("CDT_RETRY_CAP_S", 5.0)
# Prompt-dispatch re-sends (only for provably-unsent failures; see
# cluster/dispatch.py idempotency notes). Deliberately smaller than
# SEND_MAX_RETRIES: orchestration fans out and a slow host should fail
# over quickly rather than stall the whole prep gather.
DISPATCH_MAX_RETRIES = env_int("CDT_DISPATCH_MAX_RETRIES", 3)

# --- resilience (cluster/resilience.py, docs/resilience.md) -----------------
# Per-worker circuit breaker: consecutive failures before the breaker
# opens, and how long it stays open before admitting one half-open trial.
BREAKER_FAIL_THRESHOLD = env_int("CDT_BREAKER_FAIL_THRESHOLD", 3)
BREAKER_RECOVERY_S = env_float("CDT_BREAKER_RECOVERY_S", 30.0)
# Poison-tile bound: a task evicted/failed more than this many times moves
# to the job's dead-letter list instead of being requeued forever
# (surfaced via GET /distributed/job_status).
MAX_TILE_REQUEUES = env_int("CDT_MAX_TILE_REQUEUES", 3)

# --- mesh / sharding defaults ---------------------------------------------
# Axis names used across the framework. "dp" shards independent jobs/seeds
# (the reference's worker fan-out), "tp" shards model weights, "sp" shards
# the sequence/spatial axis (ring attention / tile axis).
AXIS_DATA = "dp"
AXIS_TENSOR = "tp"
AXIS_SEQUENCE = "sp"

# --- serving front door (cluster/frontdoor, docs/serving.md) ---------------
# Priority classes in strict order (first = most latency-sensitive; the
# lowest class sheds first under overload). The queue-request `priority`
# field validates against this tuple.
PRIORITY_CLASSES = ("interactive", "batch")
DEFAULT_PRIORITY = "interactive"
DEFAULT_TENANT = "default"
# Coalescing window: how long a group waits for same-shape company before
# flushing (ms), and the largest microbatch one program executes.
FD_WINDOW_MS = env_float("CDT_FD_WINDOW_MS", 25.0)
FD_MAX_BATCH = env_int("CDT_FD_MAX_BATCH", 8)
# Batch jobs the front door keeps in the prompt queue at once; pending
# groups keep coalescing while the queue is at this depth (continuous
# batching: later arrivals join the waiting group instead of a new one).
FD_INFLIGHT = env_int("CDT_FD_INFLIGHT", 2)
# Backpressure thresholds on the controller depth signal (queued +
# executing + coalescing): past SOFT the admission outcome is "queued"
# (accepted, but the client is told the fleet is busy); past SHED the
# request is refused with 429 + Retry-After. The lowest priority class
# sheds at half the threshold.
FD_SOFT_DEPTH = env_int("CDT_FD_SOFT_DEPTH", 64)
FD_SHED_DEPTH = env_int("CDT_FD_SHED_DEPTH", 256)
# Per-tenant token bucket: sustained requests/second and burst capacity.
FD_TENANT_RATE = env_float("CDT_FD_TENANT_RATE", 20.0)
FD_TENANT_BURST = env_float("CDT_FD_TENANT_BURST", 40.0)
FD_MAX_TENANTS = env_int("CDT_FD_MAX_TENANTS", 1024)
# Base Retry-After seconds for shed responses (scaled by overload ratio).
FD_RETRY_AFTER_S = env_float("CDT_FD_RETRY_AFTER_S", 2.0)

# --- content-addressed cache (cluster/cache, docs/caching.md) ---------------
# In-memory byte caps per tier (LRU, pinned entries untouchable).
# Conditioning entries are small (a context tensor per unique prompt);
# result entries are full decoded image batches — budget accordingly.
CACHE_COND_MAX_BYTES = env_int("CDT_CACHE_COND_MAX_BYTES",
                               256 * 1024 * 1024)
CACHE_RESULT_MAX_BYTES = env_int("CDT_CACHE_RESULT_MAX_BYTES",
                                 1024 * 1024 * 1024)
# Persisted-tier byte cap per tier (oldest-first eviction). The directory
# itself is CDT_CACHE_DIR (default: content_cache next to the XLA cache;
# empty string = memory-only). CDT_CACHE=0 disables the whole subsystem.
CACHE_DISK_MAX_BYTES = env_int("CDT_CACHE_DISK_MAX_BYTES",
                               4 * 1024 * 1024 * 1024)

# --- elastic fleet (cluster/elastic, docs/elasticity.md) --------------------
# Graceful drain: how long a draining worker may keep its in-flight work
# before the master hands it back to the queue (no poison-bound count,
# no breaker evidence — intentional departure).
DRAIN_DEADLINE_S = env_float("CDT_DRAIN_DEADLINE_S", 120.0)
# Autoscaler policy loop (enabled via CDT_AUTOSCALE=1): evaluation
# cadence, fleet envelope, per-capacity-unit pressure thresholds with
# hysteresis streaks, and up/down cooldowns (adding capacity is fast,
# removing it is reluctant).
AUTOSCALE_INTERVAL_S = env_float("CDT_AUTOSCALE_INTERVAL_S", 5.0)
AUTOSCALE_MIN = env_int("CDT_AUTOSCALE_MIN", 0)
AUTOSCALE_MAX = env_int("CDT_AUTOSCALE_MAX", 4)
AUTOSCALE_UP_DEPTH = env_float("CDT_AUTOSCALE_UP_DEPTH", 4.0)
AUTOSCALE_DOWN_DEPTH = env_float("CDT_AUTOSCALE_DOWN_DEPTH", 0.5)
AUTOSCALE_UP_STREAK = env_int("CDT_AUTOSCALE_UP_STREAK", 2)
AUTOSCALE_DOWN_STREAK = env_int("CDT_AUTOSCALE_DOWN_STREAK", 4)
AUTOSCALE_UP_COOLDOWN_S = env_float("CDT_AUTOSCALE_UP_COOLDOWN_S", 30.0)
AUTOSCALE_DOWN_COOLDOWN_S = env_float("CDT_AUTOSCALE_DOWN_COOLDOWN_S", 120.0)

# --- VAE decode tiling ------------------------------------------------------
# 3D-VAE decodes switch to spatially-tiled mode when the latent frame area
# exceeds this (latent pixels): a 480p WAN clip decode holds >31 GB of f32
# activations untiled. 0 disables the threshold (always whole-frame).
VAE_TILE_THRESHOLD = env_int("CDT_VAE_TILE_THRESHOLD", 48 * 48)
VAE_TILE = env_int("CDT_VAE_TILE", 32)
VAE_TILE_OVERLAP = env_int("CDT_VAE_TILE_OVERLAP", 8)
