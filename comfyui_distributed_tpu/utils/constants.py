"""Framework tunables: the typed ``CDT_*`` knob registry.

Parity with reference ``utils/constants.py:1-68`` (heartbeat cadence, payload
caps, orchestration concurrencies), re-keyed for the TPU build, and — since
ISSUE 12 — the single place every ``CDT_*`` environment knob is declared.

Design (docs/lint.md, rule K001):

- Every knob is declared ONCE here as a :class:`Knob` with a type, default,
  subsystem, and one-line doc. ``docs/knobs.md`` is generated from this
  registry and tier-1 asserts it is regeneration-clean, so the knob surface
  can never silently drift from the docs.
- Call sites read knobs through the registry (``constants.WARMUP.get()``),
  never via raw ``os.environ`` — cdtlint rule K001 machine-checks this.
- Parsing is once-per-value (cached against the raw string, so a
  monkeypatched env var re-parses) with validation: garbage raises a
  descriptive :class:`KnobError` at the first read (the
  ``resolve_flash_blocks`` precedent from PR 5) instead of letting a typo'd
  knob silently fall back or crash something deep. The few hot-loop gate
  knobs whose warn-and-default behavior is a tested contract opt out via
  ``on_garbage="default"``.
- Import-time module constants (``HEARTBEAT_INTERVAL`` et al.) are kept for
  back-compat: values are read once at import; tests may monkeypatch the
  module attributes directly, exactly as before.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional


class KnobError(ValueError):
    """A ``CDT_*`` env knob holds a value that cannot be parsed or
    validated. Raised at the first read of the bad value — loud and
    early, instead of a silent fallback masking an operator typo."""


_warned_envs: set[str] = set()


def _warn_malformed(name: str, default) -> None:
    if name not in _warned_envs:
        _warned_envs.add(name)
        from .logging import log   # lazy: keep this module stdlib-only

        log(f"ignoring malformed {name}={os.environ.get(name)!r}; "
            f"using default {default}")


_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")
_UNSET = object()       # cache sentinel: distinguishes "never read" from None


class Knob:
    """One declared ``CDT_*`` knob: typed, documented, parse-once.

    ``kind``: ``int`` | ``float`` | ``bool`` | ``optbool`` | ``str`` |
    ``enum``. ``optbool`` is tri-state (unset/empty -> ``default``, which
    is usually ``None`` so the call site can apply context-dependent
    defaults). ``keep_empty`` returns ``""`` as-is instead of treating it
    as unset (for knobs where ``CDT_X=`` means "explicitly off" rather
    than "use the default"). ``on_garbage``: ``"raise"`` (default, the
    loud contract) or ``"default"`` (warn once + fall back — only for
    hot-loop gates whose fallback behavior is a tested contract).
    """

    __slots__ = ("name", "kind", "default", "subsystem", "help", "doc",
                 "choices", "keep_empty", "on_garbage", "validator",
                 "_cached_raw", "_cached_value")

    def __init__(self, name: str, kind: str, default, subsystem: str,
                 help: str, doc: str = "", choices: tuple = (),
                 keep_empty: bool = False, on_garbage: str = "raise",
                 validator: Optional[Callable[[Any], None]] = None):
        self.name = name
        self.kind = kind
        self.default = default
        self.subsystem = subsystem
        self.help = help
        self.doc = doc
        self.choices = choices
        self.keep_empty = keep_empty
        self.on_garbage = on_garbage
        self.validator = validator
        self._cached_raw = _UNSET
        self._cached_value = None

    # -- reads ---------------------------------------------------------

    def raw(self) -> Optional[str]:
        """The raw env string (None when unset). Escape hatch for sites
        with bespoke parsing/validation (``resolve_flash_blocks``) —
        still counts as a registry read for lint rule K001."""
        return os.environ.get(self.name)

    def is_set(self) -> bool:
        return os.environ.get(self.name) is not None

    def get(self):
        """Parse-once-per-value read: the parsed result is cached against
        the raw string, so repeated reads are one dict lookup and a
        monkeypatched env var re-parses on the next read."""
        raw = os.environ.get(self.name)
        if raw == self._cached_raw:
            return self._cached_value
        value = self._parse(raw)
        # value BEFORE raw: a concurrent reader that matches the new raw
        # string must never see the previous value
        self._cached_value = value
        self._cached_raw = raw
        return value

    # -- parsing -------------------------------------------------------

    def _garbage(self, raw: str, why: str):
        if self.on_garbage == "default":
            _warn_malformed(self.name, self.default)
            return self.default
        raise KnobError(f"{self.name}={raw!r} {why}")

    def _parse(self, raw: Optional[str]):
        if raw is None:
            return self.default
        if raw.strip() == "" and not (self.keep_empty and raw == ""):
            return self.default
        if self.keep_empty and raw == "":
            # "" is meaningful for this knob: explicit-off for bools
            # (`CDT_TELEMETRY=` shell idiom), zero for numerics (the old
            # `int(env or 0)` idiom — e.g. "" lifts a cap), empty-path
            # for str knobs
            if self.kind in ("bool", "optbool"):
                return False
            if self.kind == "int":
                return 0
            if self.kind == "float":
                return 0.0
            return ""
        value: Any
        if self.kind == "int":
            try:
                value = int(raw.strip())
            except ValueError:
                return self._garbage(raw, "is not an integer")
        elif self.kind == "float":
            try:
                value = float(raw.strip())
            except ValueError:
                return self._garbage(raw, "is not a number")
        elif self.kind in ("bool", "optbool"):
            low = raw.strip().lower()
            if low in _TRUE:
                value = True
            elif low in _FALSE:
                value = False
            else:
                return self._garbage(
                    raw, f"is not a boolean (use one of {_TRUE + _FALSE})")
        elif self.kind == "enum":
            value = raw.strip().lower()
            if value not in self.choices:
                return self._garbage(
                    raw, f"is not one of {self.choices}")
        elif self.kind == "str":
            value = raw
        else:                                          # pragma: no cover
            raise AssertionError(f"unknown knob kind {self.kind!r}")
        if self.validator is not None:
            try:
                self.validator(value)
            except KnobError:
                raise
            except Exception as exc:
                return self._garbage(raw, str(exc))
        return value


class KnobRegistry:
    """Ordered declaration table. One instance (``KNOBS``) per process;
    ``docs/knobs.md`` and the K001 two-way sync check are generated from
    it."""

    def __init__(self):
        self._knobs: dict[str, Knob] = {}

    def declare(self, knob: Knob) -> Knob:
        if knob.name in self._knobs:
            raise KnobError(f"duplicate knob declaration: {knob.name}")
        if not knob.name.startswith("CDT_"):
            raise KnobError(f"knob names must start with CDT_: {knob.name}")
        self._knobs[knob.name] = knob
        return knob

    def get(self, name: str) -> Knob:
        try:
            return self._knobs[name]
        except KeyError:
            raise KnobError(
                f"{name} is not a declared knob — declare it in "
                "utils/constants.py (rule K001, docs/lint.md)") from None

    def __contains__(self, name: str) -> bool:
        return name in self._knobs

    def names(self) -> list[str]:
        return sorted(self._knobs)

    def all(self) -> list[Knob]:
        return [self._knobs[n] for n in sorted(self._knobs)]


KNOBS = KnobRegistry()


def knob(name: str) -> Knob:
    """Dynamic lookup (for sites resolving the knob name at runtime,
    e.g. the model-dir resolver in graph/nodes_builtin.py)."""
    return KNOBS.get(name)


def _k(name: str, kind: str, default, subsystem: str, help: str,
       **kw) -> Knob:
    return KNOBS.declare(Knob(name, kind, default, subsystem, help, **kw))


def knob_int(name, default, subsystem, help, **kw) -> Knob:
    return _k(name, "int", default, subsystem, help, **kw)


def knob_float(name, default, subsystem, help, **kw) -> Knob:
    return _k(name, "float", default, subsystem, help, **kw)


def knob_bool(name, default, subsystem, help, **kw) -> Knob:
    return _k(name, "bool", default, subsystem, help, **kw)


def knob_optbool(name, subsystem, help, **kw) -> Knob:
    return _k(name, "optbool", None, subsystem, help, **kw)


def knob_str(name, default, subsystem, help, **kw) -> Knob:
    return _k(name, "str", default, subsystem, help, **kw)


def knob_enum(name, default, choices, subsystem, help, **kw) -> Knob:
    return _k(name, "enum", default, subsystem, help, choices=choices, **kw)


# Legacy helpers, kept for back-compat with external callers; in-package
# reads go through declared knobs (rule K001 flags new uses).
def env_int(name: str, default: int) -> int:
    """Safe env-int read: a malformed value logs one warning and falls
    back to the default instead of raising mid-job (an env typo must not
    crash a worker's hot loop)."""
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        _warn_malformed(name, default)
        return default


def env_float(name: str, default: float) -> float:
    """Safe env-float read; same malformed-value fallback as ``env_int``."""
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        _warn_malformed(name, default)
        return default


# =========================================================================
# Knob declarations, grouped by subsystem. ``doc`` names the docs page
# that explains the subsystem; docs/knobs.md is GENERATED from this table
# (python -m comfyui_distributed_tpu.lint --write-knob-docs).
# =========================================================================

# --- cluster liveness (reference utils/constants.py:43-68) -----------------
# Workers heartbeat per processed shard; master requeues work of hosts silent
# longer than HEARTBEAT_TIMEOUT (reference upscale/job_timeout.py:17-150).
TILE_JOURNAL_DIR = knob_str(
    "CDT_TILE_JOURNAL_DIR", "", "cluster",
    "Crash-resume journal dir for long tile jobs (empty = disabled); "
    "completed tasks persist as CDTF frames and a restarted master resumes.",
    doc="docs/resilience.md").get()

# Activation rematerialization for the big-model presets (trade FLOPs for
# HBM headroom on large latents/frames); tiny test configs ignore it.
REMAT = knob_bool(
    "CDT_REMAT", False, "models",
    "Activation rematerialization for big-model presets (trade FLOPs for "
    "HBM headroom).", doc="docs/roofline.md").get()

HEARTBEAT_INTERVAL = knob_float(
    "CDT_HEARTBEAT_INTERVAL", 10.0, "cluster",
    "Worker heartbeat cadence (seconds).",
    doc="docs/resilience.md").get()
HEARTBEAT_TIMEOUT = knob_float(
    "CDT_HEARTBEAT_TIMEOUT", 60.0, "cluster",
    "Master evicts a worker silent longer than this (seconds).",
    doc="docs/resilience.md").get()

# --- payload caps ----------------------------------------------------------
MAX_PAYLOAD_SIZE = knob_int(
    "CDT_MAX_PAYLOAD_SIZE", 50 * 1024 * 1024, "cluster",
    "Per-route wire cap for tile uploads (bytes).", doc="docs/api.md").get()
MAX_AUDIO_PAYLOAD_BYTES = knob_int(
    "CDT_MAX_AUDIO_PAYLOAD_BYTES", 256 * 1024 * 1024, "cluster",
    "Wire cap for audio envelopes (bytes).", doc="docs/api.md").get()

# Max result items per flush from a worker host (reference MAX_BATCH=20).
MAX_BATCH = knob_int(
    "CDT_MAX_BATCH", 20, "cluster",
    "Max result items per flush from a worker host.",
    doc="docs/api.md").get()

# --- orchestration concurrencies (reference utils/config.py:22-45) ---------
WORKER_PROBE_CONCURRENCY = knob_int(
    "CDT_PROBE_CONCURRENCY", 10, "cluster",
    "Concurrent worker liveness probes during orchestration fan-out.").get()
WORKER_PREP_CONCURRENCY = knob_int(
    "CDT_PREP_CONCURRENCY", 4, "cluster",
    "Concurrent per-worker prompt preparations.").get()
MEDIA_SYNC_CONCURRENCY = knob_int(
    "CDT_MEDIA_SYNC_CONCURRENCY", 4, "cluster",
    "Concurrent media-sync uploads.").get()

# --- timeouts --------------------------------------------------------------
PROBE_TIMEOUT = knob_float(
    "CDT_PROBE_TIMEOUT", 5.0, "cluster",
    "Worker liveness probe timeout (seconds).").get()
DISPATCH_TIMEOUT = knob_float(
    "CDT_DISPATCH_TIMEOUT", 30.0, "cluster",
    "Prompt dispatch timeout (seconds).").get()
MEDIA_SYNC_TIMEOUT = knob_float(
    "CDT_MEDIA_SYNC_TIMEOUT", 120.0, "cluster",
    "Media sync transfer timeout (seconds).").get()
COLLECT_POLL_TIMEOUT = knob_float(
    "CDT_COLLECT_POLL_TIMEOUT", 5.0, "cluster",
    "Collector result-poll timeout (seconds).").get()
# On collector drain timeout, silent-but-busy workers are granted grace
# extensions of COLLECT_GRACE_S each, at most COLLECT_MAX_GRACE_ROUNDS times.
COLLECT_GRACE_S = knob_float(
    "CDT_COLLECT_GRACE_S", 30.0, "cluster",
    "Grace extension per round for silent-but-busy workers at collector "
    "drain (seconds).").get()
COLLECT_MAX_GRACE_ROUNDS = knob_int(
    "CDT_COLLECT_MAX_GRACE_ROUNDS", 20, "cluster",
    "Max collector grace extensions before giving up on a worker.").get()
JOB_INIT_GRACE = knob_float(
    "CDT_JOB_INIT_GRACE", 10.0, "cluster",
    "Grace for a freshly-dispatched job to appear in worker status "
    "(seconds).").get()
WORK_REQUEST_BUDGET = knob_float(
    "CDT_WORK_REQUEST_BUDGET", 30.0, "cluster",
    "Wall-clock budget for one worker work-request cycle (seconds).").get()

# --- retries (reference upscale/worker_comms.py:88-104) --------------------
SEND_MAX_RETRIES = knob_int(
    "CDT_SEND_MAX_RETRIES", 5, "resilience",
    "Attempt bound for result sends.", doc="docs/resilience.md").get()
SEND_BACKOFF_BASE = knob_float(
    "CDT_SEND_BACKOFF_BASE", 0.5, "resilience",
    "Base of the exponential full-jitter backoff (seconds).",
    doc="docs/resilience.md").get()
RETRY_CAP_S = knob_float(
    "CDT_RETRY_CAP_S", 5.0, "resilience",
    "Per-sleep ceiling for the unified RetryPolicy's backoff (seconds).",
    doc="docs/resilience.md").get()
# Prompt-dispatch re-sends (only for provably-unsent failures; deliberately
# smaller than SEND_MAX_RETRIES: a slow host should fail over quickly).
DISPATCH_MAX_RETRIES = knob_int(
    "CDT_DISPATCH_MAX_RETRIES", 3, "resilience",
    "Attempt bound for provably-unsent prompt dispatch re-sends.",
    doc="docs/resilience.md").get()

# --- resilience (cluster/resilience.py, docs/resilience.md) -----------------
BREAKER_FAIL_THRESHOLD = knob_int(
    "CDT_BREAKER_FAIL_THRESHOLD", 3, "resilience",
    "Consecutive failures before a worker's circuit breaker opens.",
    doc="docs/resilience.md").get()
BREAKER_RECOVERY_S = knob_float(
    "CDT_BREAKER_RECOVERY_S", 30.0, "resilience",
    "Open-state dwell before one half-open trial is admitted (seconds).",
    doc="docs/resilience.md").get()
MAX_TILE_REQUEUES = knob_int(
    "CDT_MAX_TILE_REQUEUES", 3, "resilience",
    "Poison-tile bound: requeues before a task dead-letters.",
    doc="docs/resilience.md").get()
FAULTS = knob_str(
    "CDT_FAULTS", "", "resilience",
    "Deterministic fault-plan spec (op@sel:kind[=value];... with seed=N) "
    "for the chaos harness.", doc="docs/resilience.md")

# --- mesh / sharding defaults ---------------------------------------------
# Axis names used across the framework. "dp" shards independent jobs/seeds
# (the reference's worker fan-out), "tp" shards model weights, "sp" shards
# the sequence/spatial axis (ring attention / tile axis).
AXIS_DATA = "dp"
AXIS_TENSOR = "tp"
AXIS_SEQUENCE = "sp"

# --- serving front door (cluster/frontdoor, docs/serving.md) ---------------
# Priority classes in strict order (first = most latency-sensitive; the
# lowest class sheds first under overload).
PRIORITY_CLASSES = ("interactive", "batch")
DEFAULT_PRIORITY = "interactive"
DEFAULT_TENANT = "default"
FRONTDOOR = knob_bool(
    "CDT_FRONTDOOR", True, "serving",
    "Kill switch: 0 restores the verbatim legacy queue route.",
    doc="docs/serving.md")
FD_WINDOW_MS = knob_float(
    "CDT_FD_WINDOW_MS", 25.0, "serving",
    "Coalescing window: how long a group waits for same-shape company "
    "before flushing (ms).", doc="docs/serving.md").get()
FD_MAX_BATCH = knob_int(
    "CDT_FD_MAX_BATCH", 8, "serving",
    "Largest microbatch one SPMD program executes.",
    doc="docs/serving.md").get()
FD_INFLIGHT = knob_int(
    "CDT_FD_INFLIGHT", 2, "serving",
    "Batch jobs the front door keeps in the prompt queue at once "
    "(continuous batching).", doc="docs/serving.md").get()
FD_SOFT_DEPTH = knob_int(
    "CDT_FD_SOFT_DEPTH", 64, "serving",
    "Depth past which admission answers 'queued' (accepted, fleet busy).",
    doc="docs/serving.md").get()
FD_SHED_DEPTH = knob_int(
    "CDT_FD_SHED_DEPTH", 256, "serving",
    "Depth past which requests are shed with 429 + Retry-After (lowest "
    "priority sheds at half).", doc="docs/serving.md").get()
FD_TENANT_RATE = knob_float(
    "CDT_FD_TENANT_RATE", 20.0, "serving",
    "Per-tenant token bucket: sustained requests/second.",
    doc="docs/serving.md").get()
FD_TENANT_BURST = knob_float(
    "CDT_FD_TENANT_BURST", 40.0, "serving",
    "Per-tenant token bucket: burst capacity.", doc="docs/serving.md").get()
FD_MAX_TENANTS = knob_int(
    "CDT_FD_MAX_TENANTS", 1024, "serving",
    "LRU cap on the per-tenant bucket map.", doc="docs/serving.md").get()
FD_RETRY_AFTER_S = knob_float(
    "CDT_FD_RETRY_AFTER_S", 2.0, "serving",
    "Base Retry-After for shed responses (scaled by overload ratio).",
    doc="docs/serving.md").get()
FD_MAX_WAIT_MS = knob_float(
    "CDT_FD_MAX_WAIT_MS", None, "serving",
    "Force-flush valve: max ms a ready group may wait for capacity "
    "(default: 20x the window).", doc="docs/serving.md")

# --- content-addressed cache (cluster/cache, docs/caching.md) ---------------
CACHE = knob_bool(
    "CDT_CACHE", True, "caching",
    "Kill switch for the content-addressed cache subsystem.",
    doc="docs/caching.md")
CACHE_DIR = knob_str(
    "CDT_CACHE_DIR", None, "caching",
    "Persisted-tier directory (default: content_cache next to the XLA "
    "cache; empty string = memory-only).", doc="docs/caching.md",
    keep_empty=True)
CACHE_COND_MAX_BYTES = knob_int(
    "CDT_CACHE_COND_MAX_BYTES", 256 * 1024 * 1024, "caching",
    "In-memory conditioning-tier LRU cap (bytes).",
    doc="docs/caching.md").get()
CACHE_RESULT_MAX_BYTES = knob_int(
    "CDT_CACHE_RESULT_MAX_BYTES", 1024 * 1024 * 1024, "caching",
    "In-memory result-tier LRU cap (bytes) — full decoded image batches; "
    "budget accordingly.", doc="docs/caching.md").get()
CACHE_DISK_MAX_BYTES = knob_int(
    "CDT_CACHE_DISK_MAX_BYTES", 4 * 1024 * 1024 * 1024, "caching",
    "Persisted-tier byte cap (oldest-first eviction).",
    doc="docs/caching.md").get()

# --- fleet-wide distributed cache (cluster/cache/fleet.py) ------------------
# Runtime-read (no .get() at import): the fleet tier is rebuilt per
# controller in tests/bench, so these must track the live environment.
FLEET_CACHE = knob_bool(
    "CDT_FLEET_CACHE", True, "caching",
    "Kill switch for the fleet cache tier (consistent-hash shards, remote "
    "fills, near tier); 0 restores strictly per-host PR 8 behavior.",
    doc="docs/caching.md")
FLEET_CACHE_VNODES = knob_int(
    "CDT_FLEET_CACHE_VNODES", 64, "caching",
    "Virtual nodes per worker on the consistent-hash ring (more = smoother "
    "shard balance, slower ring rebuild).", doc="docs/caching.md")
FLEET_CACHE_SEED = knob_str(
    "CDT_FLEET_CACHE_SEED", "cdt-fleet-ring-v1", "caching",
    "Ring placement seed — every worker in a fleet must share it or they "
    "disagree on shard ownership (a disagreement degrades to misses, "
    "never wrong bytes).", doc="docs/caching.md")
FLEET_CACHE_TIMEOUT_S = knob_float(
    "CDT_FLEET_CACHE_TIMEOUT_S", 2.0, "caching",
    "Remote-serve budget (seconds): a ring owner slower than this degrades "
    "to a local miss (recompute), never an error.", doc="docs/caching.md")
FLEET_CACHE_NEAR_MAX = knob_int(
    "CDT_FLEET_CACHE_NEAR_MAX", 64, "caching",
    "Mid-trajectory donor checkpoints the near tier keeps (LRU; only "
    "consulted by opt-in cache:\"near\" requests).",
    doc="docs/caching.md")

# --- elastic fleet (cluster/elastic, docs/elasticity.md) --------------------
AUTOSCALE = knob_bool(
    "CDT_AUTOSCALE", False, "elasticity",
    "Enable the telemetry-driven autoscaler policy loop.",
    doc="docs/elasticity.md")
SCALE_PROVIDER = knob_str(
    "CDT_SCALE_PROVIDER", "", "elasticity",
    "module:factory spec for a custom ScaleProvider (remote/tunnel "
    "capacity); empty = in-repo local process provider.",
    doc="docs/elasticity.md")
STEAL_SEED = knob_int(
    "CDT_STEAL_SEED", 0, "elasticity",
    "Seed for the deterministic cross-job steal scheduler's tie-breaks.",
    doc="docs/elasticity.md")
DRAIN_DEADLINE_S = knob_float(
    "CDT_DRAIN_DEADLINE_S", 120.0, "elasticity",
    "How long a draining worker may keep in-flight work before handback "
    "(seconds).", doc="docs/elasticity.md").get()
AUTOSCALE_INTERVAL_S = knob_float(
    "CDT_AUTOSCALE_INTERVAL_S", 5.0, "elasticity",
    "Autoscaler evaluation cadence (seconds).",
    doc="docs/elasticity.md").get()
AUTOSCALE_MIN = knob_int(
    "CDT_AUTOSCALE_MIN", 0, "elasticity",
    "Fleet envelope floor (managed workers).",
    doc="docs/elasticity.md").get()
AUTOSCALE_MAX = knob_int(
    "CDT_AUTOSCALE_MAX", 4, "elasticity",
    "Fleet envelope ceiling (managed workers).",
    doc="docs/elasticity.md").get()
AUTOSCALE_UP_DEPTH = knob_float(
    "CDT_AUTOSCALE_UP_DEPTH", 4.0, "elasticity",
    "Per-capacity-unit pressure above which the fleet scales up.",
    doc="docs/elasticity.md").get()
AUTOSCALE_DOWN_DEPTH = knob_float(
    "CDT_AUTOSCALE_DOWN_DEPTH", 0.5, "elasticity",
    "Pressure below which the fleet scales down.",
    doc="docs/elasticity.md").get()
AUTOSCALE_UP_STREAK = knob_int(
    "CDT_AUTOSCALE_UP_STREAK", 2, "elasticity",
    "Consecutive over-threshold ticks required to scale up (hysteresis).",
    doc="docs/elasticity.md").get()
AUTOSCALE_DOWN_STREAK = knob_int(
    "CDT_AUTOSCALE_DOWN_STREAK", 4, "elasticity",
    "Consecutive under-threshold ticks required to scale down.",
    doc="docs/elasticity.md").get()
AUTOSCALE_UP_COOLDOWN_S = knob_float(
    "CDT_AUTOSCALE_UP_COOLDOWN_S", 30.0, "elasticity",
    "Min seconds between scale-ups.", doc="docs/elasticity.md").get()
AUTOSCALE_DOWN_COOLDOWN_S = knob_float(
    "CDT_AUTOSCALE_DOWN_COOLDOWN_S", 120.0, "elasticity",
    "Min seconds between scale-downs (removing capacity is reluctant).",
    doc="docs/elasticity.md").get()

# --- step-granular preemption (cluster/preemption.py, docs/preemption.md) ---
PREEMPT = knob_bool(
    "CDT_PREEMPT", True, "preemption",
    "Step-granular preemption: run serving sampler loops in resumable "
    "segments and let higher-priority work (or a drain) preempt the "
    "running job at the next segment boundary (0 = monolithic scans, "
    "no preemption).", doc="docs/preemption.md")
PREEMPT_SEGMENT_STEPS = knob_int(
    "CDT_PREEMPT_SEGMENT_STEPS", 8, "preemption",
    "Denoise steps per resumable segment — the preemption granularity "
    "(smaller = faster preemption, more per-segment dispatch overhead).",
    doc="docs/preemption.md")
PREEMPT_MAX = knob_int(
    "CDT_PREEMPT_MAX", 4, "preemption",
    "Per-job preemption bound: past this many preemptions a job runs to "
    "completion (starvation guard).", doc="docs/preemption.md")
PREEMPT_RESUME_RETRIES = knob_int(
    "CDT_PREEMPT_RESUME_RETRIES", 2, "preemption",
    "Restore attempts before a checkpoint is dead-lettered and its job "
    "restarts from scratch (a checkpoint that cannot restore must not "
    "loop).", doc="docs/preemption.md")
PREEMPT_SWEEP_S = knob_float(
    "CDT_PREEMPT_SWEEP_S", 0.5, "preemption",
    "Queued-deadline sweep cadence (seconds): a job whose deadline "
    "passes while queued goes terminal 'expired' within one sweep, not "
    "only when a dispatch next touches it (0 = sweep off).",
    doc="docs/preemption.md")
CKPT_MEM_BYTES = knob_int(
    "CDT_CKPT_MEM_BYTES", 512 * 1024 * 1024, "preemption",
    "In-memory latent-checkpoint store cap (bytes, LRU; pinned = the "
    "currently-resuming entry).", doc="docs/preemption.md")
CKPT_DIR = knob_str(
    "CDT_CKPT_DIR", None, "preemption",
    "Optional persisted checkpoint tier directory (checksummed sidecar "
    "files; unset/empty = memory-only).", doc="docs/preemption.md",
    keep_empty=True)

# --- disaggregated stage-split serving (cluster/stages, docs/stages.md) -----
STAGES = knob_bool(
    "CDT_STAGES", True, "stages",
    "Kill switch for disaggregated stage-split serving: 0 restores the "
    "fused one-program-per-group path (encode + denoise + decode on one "
    "worker thread).", doc="docs/stages.md")
STAGE_ENCODE_WORKERS = knob_int(
    "CDT_STAGE_ENCODE_WORKERS", 2, "stages",
    "Encode-pool worker threads (graph prefix + text encode; host-side, "
    "fed through the conditioning cache).", doc="docs/stages.md")
STAGE_DECODE_WORKERS = knob_int(
    "CDT_STAGE_DECODE_WORKERS", 2, "stages",
    "Decode-pool worker threads (batched VAE decode + graph suffix).",
    doc="docs/stages.md")
STAGE_MAX_WORKERS = knob_int(
    "CDT_STAGE_MAX_WORKERS", 4, "stages",
    "Per-pool ceiling the stage rebalancer may grow encode/decode pools "
    "to on backlog (the denoise pool is always exactly one — it owns "
    "the mesh).", doc="docs/stages.md")
STAGE_SCALE_DEPTH = knob_float(
    "CDT_STAGE_SCALE_DEPTH", 8.0, "stages",
    "Queue depth per worker above which a host-side stage pool grows by "
    "one (its own queue-depth gauge, never another stage's).",
    doc="docs/stages.md")
STAGE_DECODE_BATCH = knob_int(
    "CDT_STAGE_DECODE_BATCH", 8, "stages",
    "Largest cross-request VAE decode batch one program executes.",
    doc="docs/stages.md")
STAGE_DECODE_WINDOW_MS = knob_float(
    "CDT_STAGE_DECODE_WINDOW_MS", 5.0, "stages",
    "Decode coalescing window: how long a latent waits for same-bucket "
    "company before the decode pool flushes the bucket (ms).",
    doc="docs/stages.md")
STAGE_SHED_DEPTH = knob_int(
    "CDT_STAGE_SHED_DEPTH", 128, "stages",
    "Per-stage backlog cap: stage queue depths past this read as "
    "overload (they feed the front door's admission depth).",
    doc="docs/stages.md")
STAGE_WIRE = knob_bool(
    "CDT_STAGE_WIRE", False, "stages",
    "Force every denoise-to-decode handoff through the checksummed "
    "latent wire format (cross-worker simulation / integrity "
    "validation; in-process handoffs otherwise skip serialization).",
    doc="docs/stages.md")
STAGE_STEAL = knob_bool(
    "CDT_STAGE_STEAL", True, "stages",
    "Cross-stage work stealing: an idle encode/decode worker serves the "
    "deepest sibling host-side stage queue (the denoise pool never "
    "steals — it owns the mesh).", doc="docs/stages.md")
STAGE_MAX_REDISPATCH = knob_int(
    "CDT_STAGE_MAX_REDISPATCH", 3, "stages",
    "Re-dispatch bound for work a dead stage worker was holding; past "
    "it the member errors loudly instead of ping-ponging.",
    doc="docs/stages.md")

# --- VAE decode tiling ------------------------------------------------------
# 3D-VAE decodes switch to spatially-tiled mode when the latent frame area
# exceeds this (latent pixels): a 480p WAN clip decode holds >31 GB of f32
# activations untiled. 0 disables the threshold (always whole-frame).
VAE_TILE_THRESHOLD = knob_int(
    "CDT_VAE_TILE_THRESHOLD", 48 * 48, "models",
    "Latent frame area past which 3D-VAE decodes tile spatially "
    "(0 = always whole-frame).").get()
VAE_TILE = knob_int(
    "CDT_VAE_TILE", 32, "models", "Spatial tile edge for tiled VAE decode "
    "(latent pixels).").get()
VAE_TILE_OVERLAP = knob_int(
    "CDT_VAE_TILE_OVERLAP", 8, "models",
    "Tile overlap for seam blending (latent pixels).").get()

# =========================================================================
# Runtime-read knobs: call sites hold the Knob and call .get() per read
# (parse-once-per-value keeps that a dict hit). Grouped by subsystem.
# =========================================================================

# --- identity / paths / boot (cluster/controller.py, workers/) --------------
IS_WORKER = knob_bool(
    "CDT_IS_WORKER", False, "workers",
    "Set by the launch builder in spawned worker processes.",
    doc="docs/deployment.md")
WORKER_ID = knob_str(
    "CDT_WORKER_ID", "", "workers",
    "This controller's worker id (set by the launch builder).",
    doc="docs/deployment.md")
WORKER_INDEX = knob_int(
    "CDT_WORKER_INDEX", 0, "workers",
    "This controller's worker index.", doc="docs/deployment.md")
MASTER_PORT = knob_str(
    "CDT_MASTER_PORT", "", "workers",
    "Master control-plane port a spawned worker reports ready to.",
    doc="docs/deployment.md")
MASTER_PID = knob_int(
    "CDT_MASTER_PID", 0, "workers",
    "Master PID the worker monitor polls (kills the worker when the "
    "master dies).", doc="docs/deployment.md")
PID_FILE = knob_str(
    "CDT_PID_FILE", "", "workers",
    "Where the worker monitor writes 'monitor_pid,worker_pid'.",
    doc="docs/deployment.md")
MONITOR_POLL = knob_float(
    "CDT_MONITOR_POLL", 2.0, "workers",
    "Worker-monitor master-liveness poll cadence (seconds).",
    doc="docs/deployment.md")
MESH_DEVICES = knob_int(
    "CDT_MESH_DEVICES", None, "workers",
    "Restrict a spawned controller to this many local chips.",
    doc="docs/deployment.md")
LOG_DIR = knob_str(
    "CDT_LOG_DIR", "logs", "workers",
    "Directory for per-worker log files.", doc="docs/deployment.md")
LOG_FILE = knob_str(
    "CDT_LOG_FILE", "", "workers",
    "This process's log file (set by the lifecycle launcher; the log "
    "route tails it).", doc="docs/deployment.md")
CONFIG_PATH = knob_str(
    "CDT_CONFIG_PATH", None, "cluster",
    "Cluster config JSON path override.", doc="docs/deployment.md")
CHECKPOINT_ROOT = knob_str(
    "CDT_CHECKPOINT_ROOT", None, "models",
    "Root directory for model checkpoints.", doc="docs/weights.md")
OUTPUT_DIR = knob_str(
    "CDT_OUTPUT_DIR", "output", "cluster",
    "Where finished images/videos land.")
INPUT_DIR = knob_str(
    "CDT_INPUT_DIR", "input", "cluster",
    "Input directory media sync mirrors into.")
DEBUG = knob_bool(
    "CDT_DEBUG", False, "cluster",
    "Verbose debug logging (config settings.debug can only add to it).")
AUTH_TOKEN = knob_str(
    "CDT_AUTH_TOKEN", None, "cluster",
    "Cluster auth token (wins over the config so operators can rotate "
    "without editing files).", doc="docs/api.md")
PROFILE_DIR = knob_str(
    "CDT_PROFILE_DIR", "/tmp/cdt_profile", "cluster",
    "Where /distributed/profile traces are written.", doc="docs/api.md")
WORKFLOWS_DIR = knob_str(
    "CDT_WORKFLOWS_DIR", None, "cluster",
    "Override for the shipped workflows/ directory.")
TELEMETRY = knob_bool(
    "CDT_TELEMETRY", True, "telemetry",
    "Kill switch for the telemetry subsystem (empty string = off, the "
    "shell `CDT_TELEMETRY=` idiom).", doc="docs/telemetry.md",
    keep_empty=True)
NO_NATIVE = knob_bool(
    "CDT_NO_NATIVE", False, "cluster",
    "Skip loading/building the native codec library.")
MAX_FRAME_RAW_BYTES = knob_int(
    "CDT_MAX_FRAME_RAW_BYTES", 1 << 30, "cluster",
    "Bound on the zlib expansion of one decoded CDTF frame (bytes).")

# --- model-file resolution (graph/nodes_builtin.py, models/) ----------------
UPSCALE_MODEL_DIR = knob_str(
    "CDT_UPSCALE_MODEL_DIR", None, "models",
    "Directory of RRDBNet upscaler .safetensors (falls back to "
    "CDT_CHECKPOINT_ROOT/upscalers).", doc="docs/weights.md")
CONTROLNET_DIR = knob_str(
    "CDT_CONTROLNET_DIR", None, "models",
    "Directory of ControlNet .safetensors (falls back to "
    "CDT_CHECKPOINT_ROOT/controlnet).", doc="docs/weights.md")
LORA_DIR = knob_str(
    "CDT_LORA_DIR", None, "models",
    "Directory of LoRA .safetensors (falls back to "
    "CDT_CHECKPOINT_ROOT/loras).", doc="docs/weights.md")
TOKENIZER_DIR = knob_str(
    "CDT_TOKENIZER_DIR", None, "models",
    "CLIP BPE tokenizer root (vocab.json + merges.txt).",
    doc="docs/weights.md")
T5_TOKENIZER_DIR = knob_str(
    "CDT_T5_TOKENIZER_DIR", None, "models",
    "HF T5/UMT5 tokenizer directory.", doc="docs/weights.md")

# --- multi-host bootstrap (parallel/bootstrap.py) ---------------------------
COORDINATOR = knob_str(
    "CDT_COORDINATOR", None, "parallel",
    "jax.distributed coordinator address.", doc="docs/deployment.md")
NUM_HOSTS = knob_int(
    "CDT_NUM_HOSTS", None, "parallel",
    "Process count for multi-host init.", doc="docs/deployment.md")
HOST_INDEX = knob_int(
    "CDT_HOST_INDEX", None, "parallel",
    "This host's process id for multi-host init.",
    doc="docs/deployment.md")

# --- executed mesh serving tier (parallel/, docs/parallelism.md) ------------
VIRTUAL_DEVICES = knob_int(
    "CDT_VIRTUAL_DEVICES", None, "parallel",
    "Create this many virtual CPU devices before jax initializes "
    "(--xla_force_host_platform_device_count); fails loudly if jax is "
    "already imported.", doc="docs/parallelism.md")
MESH_TIER = knob_bool(
    "CDT_MESH_TIER", True, "parallel",
    "Executed mesh serving tier: warm sp/dp-tp programs and prefer the "
    "mesh placement for batchable groups (0 = dp-only legacy tier).",
    doc="docs/parallelism.md")
MESH_TP = knob_int(
    "CDT_MESH_TP", 0, "parallel",
    "tp degree for the mesh serving tier (0 = derive from the mesh "
    "config / HBM fit).", doc="docs/parallelism.md")
MESH_OVERLAP = knob_bool(
    "CDT_MESH_OVERLAP", True, "parallel",
    "Overlap-schedule mesh collectives: decompose all-reduce/all-gather "
    "into per-block ppermute rings instead of one fused collective.",
    doc="docs/parallelism.md")
COLLECTIVE_QUANT = knob_enum(
    "CDT_COLLECTIVE_QUANT", "none", ("none", "int8"), "parallel",
    "Quantized-collective wire format (EQuARX-style bf16->int8); "
    "'none' (default) keeps every collective bit-exact.",
    doc="docs/parallelism.md")

# --- compile cache / shape catalog / warmup (PR 4) --------------------------
COMPILE_CACHE_DIR = knob_str(
    "CDT_COMPILE_CACHE_DIR", None, "warmup",
    "Persistent XLA compile cache directory (empty string = caching "
    "off; unset = the shared default).", doc="docs/deployment.md",
    keep_empty=True)
SHAPE_CATALOG = knob_str(
    "CDT_SHAPE_CATALOG", None, "warmup",
    "Shape-catalog JSON path (default: next to the XLA cache).",
    doc="docs/deployment.md")
SHAPE_OBSERVE = knob_bool(
    "CDT_SHAPE_OBSERVE", True, "warmup",
    "Record request-path shapes into the catalog.",
    doc="docs/deployment.md")
SHAPE_CATALOG_MAX = knob_int(
    "CDT_SHAPE_CATALOG_MAX", 128, "warmup",
    "Cap on runtime-observed catalog entries (each costs an AOT compile "
    "on every future boot); empty string or 0 = uncapped.",
    doc="docs/deployment.md", keep_empty=True)
WARMUP = knob_bool(
    "CDT_WARMUP", False, "warmup",
    "AOT-compile the shape catalog on controller boot (cold/warming/"
    "ready health gating).", doc="docs/deployment.md")
WARMUP_MODELS = knob_str(
    "CDT_WARMUP_MODELS", "", "warmup",
    "Comma list of models to warm ('all'/'*' = the full workflow "
    "catalog; default: loaded + tiny presets).", doc="docs/deployment.md")

# --- attention kernels / autotuner (PR 5, docs/kernels.md) ------------------
FLASH_ATTENTION = knob_optbool(
    "CDT_FLASH_ATTENTION", "kernels",
    "Force the flash path on (1) or off (0); unset = table/heuristics.",
    doc="docs/kernels.md", on_garbage="default")
FLASH_LAYOUT = knob_enum(
    "CDT_FLASH_LAYOUT", "", ("", "bh", "packed"), "kernels",
    "Force the flash kernel layout ('bh' classic per-head, 'packed' "
    "head-packed).", doc="docs/kernels.md", keep_empty=True,
    on_garbage="default")
FLASH_BLOCK_Q = knob_int(
    "CDT_FLASH_BLOCK_Q", None, "kernels",
    "Flash q-axis block size (positive multiple of 8; validated by "
    "resolve_flash_blocks).", doc="docs/kernels.md")
FLASH_BLOCK_K = knob_int(
    "CDT_FLASH_BLOCK_K", None, "kernels",
    "Flash k-axis block size (positive multiple of 128).",
    doc="docs/kernels.md")
# Hot-loop gate knobs: warn-and-default on garbage is a TESTED contract
# (an env typo must not crash the attention dispatch mid-job).
FLASH_MIN_SEQ = knob_int(
    "CDT_FLASH_MIN_SEQ", 8192, "kernels",
    "Min q-length before the classic flash tier engages.",
    doc="docs/kernels.md", on_garbage="default")
FLASH_MIN_SEQ_PACKED = knob_int(
    "CDT_FLASH_MIN_SEQ_PACKED", 1024, "kernels",
    "Min q-length before the packed tier engages.",
    doc="docs/kernels.md", on_garbage="default")
FLASH_MIN_KV_PACKED = knob_int(
    "CDT_FLASH_MIN_KV_PACKED", 256, "kernels",
    "Min kv-length before the packed tier engages.",
    doc="docs/kernels.md", on_garbage="default")
RING_BLOCK = knob_int(
    "CDT_RING_BLOCK", 1024, "kernels",
    "Ring-attention block size for the sp axis.",
    doc="docs/kernels.md", on_garbage="default")
ATTN_TABLE = knob_str(
    "CDT_ATTN_TABLE", None, "kernels",
    "Local tuning-table overlay path (default: next to the XLA cache).",
    doc="docs/kernels.md")
ATTN_TUNE = knob_bool(
    "CDT_ATTN_TUNE", True, "kernels",
    "Sweep untuned geometries inside the warmup window.",
    doc="docs/kernels.md")

# --- HBM residency / offload (cluster/residency.py, diffusion/offload.py) ---
HBM_BUDGET_GB = knob_float(
    "CDT_HBM_BUDGET_GB", 0.0, "residency",
    "HBM budget for the residency planner (GB; 0 = unlimited, planner "
    "off).", doc="docs/deployment.md")
OFFLOAD = knob_optbool(
    "CDT_OFFLOAD", "offload",
    "Force host-offloaded execution on/off; unset = per-preset default.",
    doc="docs/deployment.md")
OFFLOAD_RESIDENT_GB = knob_float(
    "CDT_OFFLOAD_RESIDENT_GB", 13.0, "offload",
    "HBM the offload executor may keep resident (GB).",
    doc="docs/deployment.md")
OFFLOAD_STREAM_DTYPE = knob_str(
    "CDT_OFFLOAD_STREAM_DTYPE", "float8_e4m3fn", "offload",
    "Stream dtype for offloaded blocks ('float8_e4m3fn' or 'native').",
    doc="docs/deployment.md")
OFFLOAD_LADDER = knob_enum(
    "CDT_OFFLOAD_LADDER", "jit", ("jit", "step"), "offload",
    "How a fully-resident offloaded sample runs its sigma ladder.",
    doc="docs/deployment.md")
OFFLOAD_CACHE_DIR = knob_str(
    "CDT_OFFLOAD_CACHE_DIR", None, "offload",
    "Quantized-block cache dir (cuts a warm 12B executor build to a "
    "disk read).", doc="docs/deployment.md")

# --- serving / caching / elastic runtime switches ---------------------------
TILES_PER_DEVICE = knob_int(
    "CDT_TILES_PER_DEVICE", 0, "tiles",
    "Override tiles-per-device for the tile engine (0 = computed).",
    on_garbage="default")
TILE_MASTER_HOLDBACK_S = knob_float(
    "CDT_TILE_MASTER_HOLDBACK_S", 0.0, "tiles",
    "Master holds back from taking tile work this long so remote "
    "workers win the race (0 = disabled).")
TILE_READY_POLLS = knob_int(
    "CDT_TILE_READY_POLLS", 120, "tiles",
    "Polls while waiting for a tile job to initialize.",
    on_garbage="default")

# --- tunnel (utils/tunnel.py, docs/cloud-presets.md) ------------------------
TUNNEL_START_TIMEOUT = knob_float(
    "CDT_TUNNEL_START_TIMEOUT", 30.0, "tunnel",
    "Seconds to wait for cloudflared to print its URL.",
    doc="docs/cloud-presets.md")
CLOUDFLARED_VERSION = knob_str(
    "CDT_CLOUDFLARED_VERSION", None, "tunnel",
    "cloudflared release to download ('latest' or a version; default: "
    "the pinned version).", doc="docs/cloud-presets.md")
CLOUDFLARED_SHA256 = knob_str(
    "CDT_CLOUDFLARED_SHA256", None, "tunnel",
    "Expected sha256 of the cloudflared download.",
    doc="docs/cloud-presets.md")
CLOUDFLARED_AUTO_DOWNLOAD = knob_bool(
    "CDT_CLOUDFLARED_AUTO_DOWNLOAD", True, "tunnel",
    "Allow downloading cloudflared when no binary is found.",
    doc="docs/cloud-presets.md")

# --- lint / testing / bench (docs/lint.md) ----------------------------------
LOCK_ORDER = knob_bool(
    "CDT_LOCK_ORDER", False, "lint",
    "Dev-mode runtime lock-order detector: record cross-registry lock "
    "acquisition order and fail loudly on an inversion.",
    doc="docs/lint.md")
LOOP_STALL = knob_bool(
    "CDT_LOOP_STALL", False, "lint",
    "Dev-mode event-loop stall sanitizer: sample the asyncio loop and "
    "record any callback that blocks it past CDT_LOOP_STALL_MS, with "
    "the offending stack.",
    doc="docs/lint.md")
LOOP_STALL_MS = knob_float(
    "CDT_LOOP_STALL_MS", 100.0, "lint",
    "Stall threshold (milliseconds) for the CDT_LOOP_STALL sanitizer: a "
    "loop callback running longer than this is recorded as a stall.",
    doc="docs/lint.md")
TEST_WATCHDOG_S = knob_float(
    "CDT_TEST_WATCHDOG_S", 300.0, "testing",
    "Per-test watchdog: dump all thread stacks (faulthandler) after this "
    "many seconds so a deadlock leaves evidence (0 = off).",
    doc="docs/lint.md")
TEST_XLA_CACHE = knob_str(
    "CDT_TEST_XLA_CACHE", "/tmp/cdt_xla_cache_tests", "testing",
    "Persistent XLA compile cache for the test suite.")
CHAOS_SEED = knob_int(
    "CDT_CHAOS_SEED", 42, "testing",
    "Fixed seed for the chaos suite so failures replay exactly.",
    doc="docs/resilience.md")
BENCH_PREFLIGHT_TIMEOUT_S = knob_float(
    "CDT_BENCH_PREFLIGHT_TIMEOUT_S", 120.0, "bench",
    "Budget for bench.py's subprocess TPU preflight probe (seconds).")
BENCH_BUDGET_S = knob_float(
    "CDT_BENCH_BUDGET_S", 2400.0, "bench",
    "Total wall-clock budget for bench.py's accelerator attempts "
    "(seconds).")
BENCH_ATTEMPT_TIMEOUT_S = knob_float(
    "CDT_BENCH_ATTEMPT_TIMEOUT_S", 1800.0, "bench",
    "Per-attempt subprocess timeout for bench.py (seconds).")
PROBE_RUNS = knob_int(
    "CDT_PROBE_RUNS", None, "bench",
    "Override the timed-run count in scripts/mfu_probe.py.")
