"""Deadline-guarded device-backend queries for the control plane.

The r04 chip outage exposed a failure mode the reference never has
(CUDA is local; this runtime may sit behind a network-attached device
service): when the accelerator backend goes unreachable,
``jax.devices()`` / per-device ``memory_stats()`` RPCs block
**indefinitely**, and any aiohttp route that calls them synchronously
freezes the whole event loop — including ``/distributed/health``, the
exact endpoint peers use to decide this host is dead. Reference
analogue for the *shape* of the guard: its worker probes use bounded
HTTP timeouts everywhere (``utils/network.py``); the device backend
deserves the same discipline.

Leak discipline: a stalled RPC can never be cancelled, so each timeout
permanently occupies its thread for the outage's duration. Queries run
on dedicated **daemon** threads (never the shared default executor —
worker launch, tunnel setup, and media hashing live there) behind a
2-permit semaphore: at most TWO threads can ever be stuck, further
calls fall back immediately, and interpreter shutdown is never blocked.
A cooldown gate additionally short-circuits attempts after a stall.

Exceptions are NOT conflated with stalls: a query that *fails fast*
(e.g. a misconfigured backend raising at init) propagates to the
caller — the app-level error middleware reports the real error — and
does not close the gate.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Callable

from .logging import log

_blocked_until = 0.0
_inflight = threading.Semaphore(2)


def gate_open() -> bool:
    return time.monotonic() >= _blocked_until


def _note_stall(cooldown_s: float) -> None:
    global _blocked_until
    _blocked_until = time.monotonic() + cooldown_s


def reset_gate() -> None:
    """Test hook / manual recovery."""
    global _blocked_until
    _blocked_until = 0.0
    # NOTE: permits held by genuinely-stuck threads are unrecoverable by
    # design (the thread itself must finish to release)


async def deadline_call(fn: Callable[[], Any], timeout_s: float = 5.0,
                        cooldown_s: float = 120.0,
                        fallback: Any = None) -> Any:
    """Run a (possibly-hanging) device-backend query off the event loop
    with a deadline.

    - timeout → log, close the gate for ``cooldown_s``, return
      ``fallback`` (the thread stays parked until the RPC dies);
    - gate closed or both leak permits consumed → ``fallback``
      immediately;
    - ``fn`` raises → the exception PROPAGATES (fast failures carry
      real diagnostics; only stalls degrade)."""
    if not gate_open():
        return fallback
    if not _inflight.acquire(blocking=False):
        return fallback
    loop = asyncio.get_running_loop()
    fut: asyncio.Future = loop.create_future()

    def deliver(cb):
        try:
            loop.call_soon_threadsafe(cb)
        except RuntimeError:
            pass      # loop already closed — a freed stale thread's
                      # result has nowhere to go, and that's fine

    def runner():
        try:
            result = fn()
        except BaseException as e:  # noqa: BLE001 — delivered, not dropped
            # bind NOW: CPython clears the except-variable at block
            # exit, racing the scheduled callback (a bare closure over
            # `e` intermittently dies with NameError and the failure
            # would misclassify as a stall)
            deliver(lambda exc=e: fut.set_exception(exc)
                    if not fut.done() else None)
        else:
            deliver(lambda: fut.set_result(result)
                    if not fut.done() else None)
        finally:
            _inflight.release()

    threading.Thread(target=runner, daemon=True,
                     name="cdt-device-query").start()
    try:
        return await asyncio.wait_for(fut, timeout=timeout_s)
    except asyncio.TimeoutError:
        _note_stall(cooldown_s)
        log(f"device backend unresponsive (> {timeout_s:.0f}s) — "
            f"degrading device queries for {cooldown_s:.0f}s")
        return fallback
