"""Optional shared-secret auth for the control plane.

The reference leaves every mutating route unauthenticated while
simultaneously shipping one-click public tunnels
(``/root/reference/utils/cloudflare/tunnel.py:19-207`` exposes the whole
``/distributed/*`` surface to the internet); a TPU-first rebuild should
not inherit that. One cluster-wide token (``CDT_AUTH_TOKEN`` env, or
``settings.auth_token`` in the cluster config) gates every mutating
route: requests must carry it in the ``X-CDT-Auth`` header (or
``Authorization: Bearer``). Probe/health GETs stay open so liveness
checks and dashboards keep working.

No token configured → everything stays open (back-compat for private
networks). Starting a tunnel auto-generates and persists a token if none
exists, printing it once, so the public URL is never born unprotected.
"""

from __future__ import annotations

import hmac
import secrets
from typing import Any, Optional

AUTH_HEADER = "X-CDT-Auth"
AUTH_ENV = "CDT_AUTH_TOKEN"      # knob: constants.AUTH_TOKEN

def configured_token(cfg: Optional[dict[str, Any]] = None) -> Optional[str]:
    """The cluster token, if any: the env var wins over the config so an
    operator can rotate without editing files."""
    from .constants import AUTH_TOKEN

    env = AUTH_TOKEN.get()
    if env:
        return env
    if cfg:
        tok = cfg.get("settings", {}).get("auth_token")
        if tok:
            return str(tok)
    return None


def resolve_token(config_path=None) -> Optional[str]:
    """Hot-path token lookup: env var, else a no-deepcopy config peek
    (``config.peek_setting`` — one stat when the mtime cache is warm).
    Used by the per-request auth middleware and the outbound session."""
    from .constants import AUTH_TOKEN

    env = AUTH_TOKEN.get()
    if env:
        return env
    from .config import peek_setting

    tok = peek_setting("auth_token", None, config_path)
    return str(tok) if tok else None


def generate_token() -> str:
    return secrets.token_urlsafe(24)


def token_matches(request_headers, token: str) -> bool:
    """Constant-time check of ``X-CDT-Auth`` / ``Authorization: Bearer``.
    Compares as bytes: ``compare_digest`` raises on non-ASCII *strings*,
    and a malformed header must read as 401, not a 500."""
    presented = request_headers.get(AUTH_HEADER, "")
    if not presented:
        bearer = request_headers.get("Authorization", "")
        if bearer.startswith("Bearer "):
            presented = bearer[len("Bearer "):]
    if not presented:
        return False
    return hmac.compare_digest(
        presented.encode("utf-8", "surrogateescape"),
        token.encode("utf-8", "surrogateescape"))


# Reads that are gated when a token is set: the config payload contains
# the token itself, and the log surfaces can carry operational secrets
# (and would otherwise leak whatever startup printed).
_GATED_READ_PREFIXES = (
    "/distributed/config",
    "/distributed/local_log",
    "/distributed/worker_log/",
    "/distributed/remote_worker_log/",
)


def requires_auth(method: str, path: str) -> bool:
    """Every mutating (non-GET/HEAD/OPTIONS) route needs the token —
    cluster peers carry it automatically (``utils/network.py`` session
    headers). Reads stay open so probes, health, the dashboard, and
    progress polling keep working — except the config (which contains the
    token) and the log-tail surfaces (which can carry secrets)."""
    if any(path == p or path.startswith(p) for p in _GATED_READ_PREFIXES):
        return True
    return method not in ("GET", "HEAD", "OPTIONS")
