"""JSON configuration with cached loads, atomic saves, and async transactions.

Parity: reference ``utils/config.py`` — single JSON file next to the package
(``:13``), defaults deep-merged with unknown-key preservation (``:47-65``),
mtime-based read cache (``:75-97``), atomic tmp+fsync+rename save (``:99-116``),
async-locked read-modify-write transaction (``:119-129``).

Schema differences are deliberate (TPU-first): the reference's per-GPU
``workers[{cuda_device, port}]`` become per-*host* entries — on a pod, chips
are mesh slots, not processes (SURVEY §7 translation table) — and a ``mesh``
section declares topology (shape + axis names) instead of CUDA device pins.
"""

from __future__ import annotations

import asyncio
import copy
import json
import os
import tempfile
import threading
from pathlib import Path
from typing import Any, AsyncIterator, Callable
from contextlib import asynccontextmanager

from .exceptions import ConfigError

CONFIG_ENV = "CDT_CONFIG_PATH"
_DEFAULT_NAME = "tpu_cluster_config.json"

DEFAULT_CONFIG: dict[str, Any] = {
    "master": {
        "host": "",          # advertised callback host ("" = auto-detect)
        "port": 8288,
        "delegate_only": False,   # master coordinates but contributes no compute
    },
    # One entry per *host controller* (reference: one per GPU process).
    # On-pod chips are addressed through `mesh`, not through host entries.
    "hosts": [],
    "mesh": {
        # Device mesh shape as {axis_name: size}; -1 means "all remaining
        # devices". Axis names follow utils.constants AXIS_*.
        "shape": {"dp": -1},
        # Which axis collects seed-parallel results (the Collector axis).
        "collect_axis": "dp",
    },
    "settings": {
        "debug": False,
        "auto_launch_workers": False,
        "stop_workers_on_master_exit": True,
        "master_delegate_only": False,
        "worker_timeout_seconds": 60,
        "worker_probe_concurrency": 10,
        "worker_prep_concurrency": 4,
        "media_sync_concurrency": 4,
        "media_sync_timeout_seconds": 120,
    },
    "tunnel": {},
    "managed_processes": {},
}

_HOST_DEFAULTS: dict[str, Any] = {
    "id": "",
    "name": "",
    "address": "",       # http(s)://host:port of the host controller
    "enabled": False,
    "type": "remote",    # "local" | "remote" | "cloud"
    "mesh_devices": -1,  # chips contributed by this host (-1 = all)
    "extra_args": "",
}


def config_path() -> Path:
    from .constants import CONFIG_PATH

    override = CONFIG_PATH.get()
    if override:
        return Path(override)
    return Path(__file__).resolve().parent.parent / _DEFAULT_NAME


def _deep_merge(defaults: dict, loaded: dict) -> dict:
    """Defaults filled in under loaded values; unknown keys in ``loaded`` are
    preserved (reference utils/config.py:47-65)."""
    out = copy.deepcopy(defaults)
    for k, v in loaded.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def normalize_host(entry: dict) -> dict:
    return _deep_merge(_HOST_DEFAULTS, entry)


# --- cached load -----------------------------------------------------------

_cache_lock = threading.Lock()
_cache: tuple[Path, float, dict] | None = None  # (path, mtime, config)


def load_config(path: Path | None = None) -> dict[str, Any]:
    """Load config with defaults merged; cached by (path, mtime)."""
    global _cache
    p = path or config_path()
    with _cache_lock:
        try:
            mtime = p.stat().st_mtime
        except OSError:
            _cache = None
            return copy.deepcopy(DEFAULT_CONFIG)
        if _cache is not None and _cache[0] == p and _cache[1] == mtime:
            return copy.deepcopy(_cache[2])
        try:
            # cold read only: mtime-cached above, so async callers hit
            # this open() once per config EDIT, for a few-KB local JSON
            with open(p, "r", encoding="utf-8") as f:  # cdtlint: disable=A002
                loaded = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigError(f"cannot read config {p}: {e}") from e
        merged = _deep_merge(DEFAULT_CONFIG, loaded)
        merged["hosts"] = [normalize_host(h) for h in merged.get("hosts", [])]
        _cache = (p, mtime, merged)
        return copy.deepcopy(merged)


def save_config(config: dict[str, Any], path: Path | None = None) -> None:
    """Atomic save: tmp file in the same dir + fsync + rename
    (reference utils/config.py:99-116)."""
    global _cache
    p = path or config_path()
    p.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(p.parent), prefix=".cdt_cfg_")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(config, f, indent=2, sort_keys=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    except OSError as e:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise ConfigError(f"cannot write config {p}: {e}") from e
    with _cache_lock:
        _cache = None


def invalidate_cache() -> None:
    global _cache
    with _cache_lock:
        _cache = None


# --- transaction -----------------------------------------------------------

_txn_lock = asyncio.Lock()


@asynccontextmanager
async def config_transaction(path: Path | None = None) -> AsyncIterator[dict]:
    """Async read-modify-write: mutate the yielded dict; it is saved on exit
    (reference utils/config.py:119-129)."""
    async with _txn_lock:
        cfg = load_config(path)
        yield cfg
        save_config(cfg, path)


def update_config(mutate: Callable[[dict], None], path: Path | None = None) -> dict:
    """Synchronous read-modify-write for non-async callers."""
    cfg = load_config(path)
    mutate(cfg)
    save_config(cfg, path)
    return cfg


# --- accessors (reference utils/config.py:141-166) -------------------------

def get_setting(name: str, default: Any = None, path: Path | None = None) -> Any:
    return load_config(path).get("settings", {}).get(name, default)


def peek_setting(name: str, default: Any = None,
                 path: Path | None = None) -> Any:
    """Read ONE settings key without deep-copying the whole config —
    hot-path safe (one stat + dict lookup when the mtime cache is warm).
    Use for per-request/per-call gates (auth token, debug flag); callers
    must not mutate the returned value."""
    p = path or config_path()
    with _cache_lock:
        if _cache is not None and _cache[0] == p:
            try:
                if p.stat().st_mtime == _cache[1]:
                    return _cache[2].get("settings", {}).get(name, default)
            except OSError:
                return DEFAULT_CONFIG.get("settings", {}).get(name, default)
    try:
        return load_config(p).get("settings", {}).get(name, default)
    except ConfigError:
        return default


def get_worker_timeout_seconds(path: Path | None = None) -> float:
    from . import constants
    v = get_setting("worker_timeout_seconds", None, path)
    return float(v) if v else constants.HEARTBEAT_TIMEOUT


def is_master_delegate_only(path: Path | None = None) -> bool:
    cfg = load_config(path)
    return bool(
        cfg.get("settings", {}).get("master_delegate_only")
        or cfg.get("master", {}).get("delegate_only")
    )


def enabled_hosts(config: dict[str, Any] | None = None) -> list[dict]:
    cfg = config or load_config()
    return [h for h in cfg.get("hosts", []) if h.get("enabled")]


def ensure_config_exists(path: Path | None = None) -> Path:
    p = path or config_path()
    if not p.exists():
        save_config(copy.deepcopy(DEFAULT_CONFIG), p)
    return p
