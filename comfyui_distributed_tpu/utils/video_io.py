"""Video container I/O — the file edge for video workflows.

The reference free-rides on the VideoHelperSuite ecosystem nodes for
this: ``VHS_LoadVideo`` / ``VHS_VideoCombine`` appear in
``/root/reference/workflows/distributed-upscale-video.json`` and carry
mp4/webm in and out of the graph, with audio muxed by ffmpeg. This image
has no ffmpeg binary and no PyAV, so the TPU build closes the same loop
with what is actually available:

- **mp4 / webm** — OpenCV's ``VideoWriter``/``VideoCapture`` (mp4v /
  VP80 fourccs verified in this image). cv2 cannot mux audio, so when an
  AUDIO track is attached the waveform is written as a sidecar
  ``<name>.wav`` beside the container and re-attached automatically by
  ``load_video``.
- **avi** — a pure-Python RIFF muxer/demuxer (MJPG video + 16-bit PCM
  audio, interleaved per frame): the one mainstream container whose
  writer is simple enough to own outright, giving a genuinely *muxed*
  audio track with zero native dependencies. Playable by VLC/ffplay/
  anything with MJPG support.

Frames ride the graph as IMAGE batches ``[T, H, W, C]`` float32 in
[0, 1] (the framework's tensor convention, ``utils/image.py``); AUDIO is
the ``{"waveform": [B, C, S], "sample_rate"}`` dict of
``utils/audio_payload.py``.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Any, Optional

import numpy as np

from .exceptions import ValidationError

# Writable cv2 containers (fourccs verified working in this image);
# reading is extension-agnostic (whatever cv2's backend decodes). The
# media-sync gating list lives in cluster/media_sync.py.
_FOURCC = {".mp4": "mp4v", ".webm": "VP80"}


def _require_cv2():
    try:
        import cv2
    except ImportError as exc:                       # pragma: no cover
        raise ValidationError(
            "video container I/O needs OpenCV (cv2), which is not "
            "importable in this environment") from exc
    return cv2


def _to_uint8_frames(frames: Any) -> np.ndarray:
    """IMAGE batch → [T, H, W, 3] uint8 (grayscale replicated, alpha
    stripped); quantization delegates to the framework-wide rule in
    ``utils.image.to_uint8`` so video and PNG output can't diverge."""
    from .image import to_uint8

    arr = np.asarray(frames)
    if arr.ndim == 3 and arr.shape[-1] > 4:      # [T,H,W] grayscale
        arr = arr[..., None]
    arr = to_uint8(arr)
    if arr.shape[-1] == 1:
        arr = np.repeat(arr, 3, axis=-1)
    elif arr.shape[-1] == 4:
        arr = arr[..., :3]
    return arr


def _first_clip(audio: dict[str, Any]) -> tuple[np.ndarray, int]:
    """AUDIO dict → ([C, S] float32 of clip 0, sample_rate); one
    container carries one track, so a multi-clip batch keeps clip 0 and
    WARNS about the rest (SaveAudio is the node that writes one file per
    element). Shared by the AVI mux and the cv2-format sidecar path so
    their normalization and diagnostics cannot diverge."""
    wf = np.asarray(audio["waveform"], dtype=np.float32)
    if wf.ndim == 2:
        wf = wf[None]
    if wf.ndim != 3:
        raise ValidationError(
            f"audio waveform must be [B,C,S], got shape {wf.shape}")
    if wf.shape[0] > 1:
        from .logging import log

        log(f"video audio track: batch of {wf.shape[0]} clips, writing "
            f"clip 0 only (use SaveAudio for one file per clip)")
    return wf[0], int(audio.get("sample_rate", 44100))


def _audio_pcm16(audio: dict[str, Any]) -> tuple[np.ndarray, int]:
    """AUDIO dict → ([S, C] int16 of clip 0, sample_rate)."""
    clip, sr = _first_clip(audio)
    pcm = (np.clip(clip, -1.0, 1.0) * 32767.0).astype(np.int16)
    return pcm.T.copy(), sr                          # [S, C]


# --------------------------------------------------------------------------
# AVI (RIFF) muxer: MJPG video + PCM audio, interleaved
# --------------------------------------------------------------------------

def _chunk(ckid: bytes, payload: bytes) -> bytes:
    pad = b"\x00" if len(payload) % 2 else b""
    return ckid + struct.pack("<I", len(payload)) + payload + pad


def _list_chunk(list_type: bytes, payload: bytes) -> bytes:
    return _chunk(b"LIST", list_type + payload)


def write_avi_mjpg(path: Path, frames: np.ndarray, fps: float,
                   pcm: Optional[np.ndarray] = None,
                   sample_rate: int = 44100, quality: int = 95) -> None:
    """Write an AVI container: MJPG frames + optional interleaved 16-bit
    PCM audio. ``frames`` [T,H,W,3] uint8 RGB; ``pcm`` [S, C] int16."""
    cv2 = _require_cv2()
    T, H, W, _ = frames.shape
    jpegs = []
    for i in range(T):
        ok, buf = cv2.imencode(
            ".jpg", cv2.cvtColor(frames[i], cv2.COLOR_RGB2BGR),
            [int(cv2.IMWRITE_JPEG_QUALITY), int(quality)])
        if not ok:                                   # pragma: no cover
            raise ValidationError(f"JPEG encode failed for frame {i}")
        jpegs.append(buf.tobytes())

    has_audio = pcm is not None and pcm.size > 0
    n_ch = int(pcm.shape[1]) if has_audio else 0
    block_align = 2 * n_ch
    byte_rate = sample_rate * block_align

    # ---- stream headers --------------------------------------------------
    # fps as a rational with ms precision: rate/scale
    scale, rate = 1000, int(round(fps * 1000))
    strh_v = struct.pack(
        "<4s4sIHHIIIIIIII4H", b"vids", b"MJPG", 0, 0, 0, 0,
        scale, rate, 0, T, max(len(j) for j in jpegs), 0xFFFFFFFF, 0,
        0, 0, W, H)
    # BITMAPINFOHEADER
    strf_v = struct.pack("<IiiHH4sIiiII", 40, W, H, 1, 24, b"MJPG",
                         W * H * 3, 0, 0, 0, 0)
    strl_v = _list_chunk(b"strl",
                         _chunk(b"strh", strh_v) + _chunk(b"strf", strf_v))

    streams = [strl_v]
    if has_audio:
        n_samples = pcm.shape[0]
        strh_a = struct.pack(
            "<4s4sIHHIIIIIIII4H", b"auds", b"\x00\x00\x00\x00", 0, 0, 0, 0,
            block_align, byte_rate, 0,
            n_samples * block_align // max(block_align, 1),
            byte_rate, 0xFFFFFFFF, block_align, 0, 0, 0, 0)
        # WAVEFORMATEX (PCM)
        strf_a = struct.pack("<HHIIHHH", 1, n_ch, sample_rate, byte_rate,
                             block_align, 16, 0)
        streams.append(_list_chunk(
            b"strl", _chunk(b"strh", strh_a) + _chunk(b"strf", strf_a)))

    usec_per_frame = int(round(1_000_000 / max(fps, 1e-6)))
    avih = struct.pack(
        "<IIIIIIIIIIIIII", usec_per_frame,
        int(byte_rate + np.mean([len(j) for j in jpegs]) * fps),
        0, 0x10,                                     # AVIF_HASINDEX
        T, 0, len(streams), max(len(j) for j in jpegs), W, H, 0, 0, 0, 0)
    hdrl = _list_chunk(b"hdrl", _chunk(b"avih", avih) + b"".join(streams))

    # ---- movi: interleave one audio slice per video frame ----------------
    movi_parts: list[bytes] = []
    index: list[tuple[bytes, int, int]] = []         # (ckid, offset, size)
    offset = 4                                       # past the 'movi' tag
    spf = sample_rate / max(fps, 1e-6)               # samples per frame
    for i in range(T):
        data = jpegs[i]
        movi_parts.append(_chunk(b"00dc", data))
        index.append((b"00dc", offset, len(data)))
        offset += 8 + len(data) + (len(data) % 2)
        if has_audio:
            lo, hi = int(round(i * spf)), int(round((i + 1) * spf))
            chunk_pcm = pcm[lo:min(hi, pcm.shape[0])]
            if i == T - 1:                           # tail: rest of track
                chunk_pcm = pcm[lo:]
            if chunk_pcm.size:
                data = chunk_pcm.tobytes()
                movi_parts.append(_chunk(b"01wb", data))
                index.append((b"01wb", offset, len(data)))
                offset += 8 + len(data) + (len(data) % 2)
    movi = _list_chunk(b"movi", b"".join(movi_parts))

    idx1 = _chunk(b"idx1", b"".join(
        struct.pack("<4sIII", ckid, 0x10, off, size)
        for ckid, off, size in index))

    riff_payload = b"AVI " + hdrl + movi + idx1
    path.write_bytes(b"RIFF" + struct.pack("<I", len(riff_payload))
                     + riff_payload)


def _iter_riff_chunks(buf: bytes, start: int, end: int):
    pos = start
    while pos + 8 <= end:
        ckid = buf[pos:pos + 4]
        size = struct.unpack("<I", buf[pos + 4:pos + 8])[0]
        yield ckid, pos + 8, size
        pos += 8 + size + (size % 2)


def read_avi_mjpg(path: Path, skip: int = 0, nth: int = 1,
                  cap: int = 0) -> Optional[dict[str, Any]]:
    """Demux an AVI written by ``write_avi_mjpg`` (or any MJPG+PCM AVI).
    Returns ``{"frames", "fps", "audio", "truncated"}`` or None if the
    file is not an MJPG AVI (caller falls back to cv2). Frame selection
    (skip / every-nth / cap) happens BEFORE JPEG decode, so only the
    requested frames are ever decoded or held as float arrays; raw
    chunk bytes are cheap. ``fps`` is the SOURCE rate and ``audio`` the
    full track — ``load_video`` rescales/trims them coherently."""
    cv2 = _require_cv2()
    buf = path.read_bytes()
    if len(buf) < 12 or buf[:4] != b"RIFF" or buf[8:12] != b"AVI ":
        return None

    fps = 30.0
    audio_fmt: Optional[tuple[int, int]] = None      # (channels, rate)
    jpegs: list[bytes] = []
    pcm_parts: list[bytes] = []
    saw_mjpg = False

    def walk(start: int, end: int):
        nonlocal fps, audio_fmt, saw_mjpg
        pending_stream = [None]                      # fccType of last strh
        for ckid, data_off, size in _iter_riff_chunks(buf, start, end):
            body = buf[data_off:data_off + size]
            if ckid == b"LIST":
                walk(data_off + 4, data_off + size)
            elif ckid == b"strh" and size >= 32:
                fcc_type, handler = body[:4], body[4:8]
                pending_stream[0] = fcc_type
                if fcc_type == b"vids":
                    if handler not in (b"MJPG", b"mjpg"):
                        return
                    saw_mjpg = True
                    scale, rate = struct.unpack("<II", body[20:28])
                    if scale:
                        fps = rate / scale
            elif ckid == b"strf" and pending_stream[0] == b"auds" \
                    and size >= 16:
                fmt, n_ch, sr = struct.unpack("<HHI", body[:8])
                if fmt == 1:                         # PCM
                    audio_fmt = (n_ch, sr)
            elif ckid[2:] == b"dc":
                jpegs.append(body)
            elif ckid[2:] == b"wb":
                pcm_parts.append(body)

    walk(12, len(buf))
    if not saw_mjpg or not jpegs:
        return None

    selected = jpegs[max(0, skip)::max(1, nth)]
    truncated = bool(cap and cap > 0 and len(selected) > cap)
    if truncated:
        selected = selected[:cap]
    frames = []
    for j in selected:
        img = cv2.imdecode(np.frombuffer(j, np.uint8), cv2.IMREAD_COLOR)
        if img is None:                              # pragma: no cover
            return None
        frames.append(cv2.cvtColor(img, cv2.COLOR_BGR2RGB))
    out: dict[str, Any] = {
        "frames": (np.stack(frames).astype(np.float32) / 255.0 if frames
                   else np.zeros((0, 1, 1, 3), np.float32)),
        "fps": float(fps), "audio": None, "truncated": truncated,
    }
    if audio_fmt and pcm_parts:
        n_ch, sr = audio_fmt
        pcm = np.frombuffer(b"".join(pcm_parts), np.int16)
        if n_ch and pcm.size % n_ch == 0:
            wf = (pcm.reshape(-1, n_ch).T.astype(np.float32)
                  / 32768.0)[None]                   # [1, C, S]
            out["audio"] = {"waveform": wf, "sample_rate": sr}
    return out


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

def save_video(path, frames, fps: float = 8.0,
               audio: Optional[dict[str, Any]] = None,
               quality: int = 95) -> list[str]:
    """Write an IMAGE batch as a video container; format from suffix
    (.mp4 / .webm / .avi). Returns the written file paths (the container
    plus, for cv2 formats with audio, the sidecar ``.wav``)."""
    path = Path(path)
    ext = path.suffix.lower()
    arr = _to_uint8_frames(frames)
    if arr.shape[0] == 0:
        raise ValidationError("cannot write a video with 0 frames")
    if audio is not None and np.asarray(audio["waveform"]).size == 0:
        audio = None                     # empty track (e.g. silent source)
    path.parent.mkdir(parents=True, exist_ok=True)
    written = [str(path)]

    if ext == ".avi":
        pcm, sr = _audio_pcm16(audio) if audio is not None else (None, 44100)
        write_avi_mjpg(path, arr, fps, pcm=pcm, sample_rate=sr,
                       quality=quality)
        return written

    if ext not in _FOURCC:
        raise ValidationError(
            f"unsupported video format {ext!r} (supported: "
            f"{sorted(_FOURCC) + ['.avi']})")
    cv2 = _require_cv2()
    T, H, W, _ = arr.shape
    writer = cv2.VideoWriter(str(path),
                             cv2.VideoWriter_fourcc(*_FOURCC[ext]),
                             float(fps), (W, H))
    if not writer.isOpened():
        raise ValidationError(
            f"OpenCV cannot open a {ext} writer in this environment")
    try:
        for i in range(T):
            writer.write(cv2.cvtColor(arr[i], cv2.COLOR_RGB2BGR))
    finally:
        writer.release()
    if audio is not None:
        # no ffmpeg in this image → cv2 formats carry audio as a sidecar
        # wav that load_video re-attaches (divergence from the
        # reference's VHS_VideoCombine, which muxes via ffmpeg; use the
        # .avi format for a truly muxed track)
        from .audio_payload import wav_bytes

        clip, sr = _first_clip(audio)
        sidecar = path.with_suffix(".wav")
        sidecar.write_bytes(wav_bytes(clip, sr))
        written.append(str(sidecar))
    return written


def load_video(path, frame_load_cap: int = 0, skip_first_frames: int = 0,
               select_every_nth: int = 1) -> dict[str, Any]:
    """Read a video container → ``{"frames" [T,H,W,3] float32 0..1,
    "fps", "audio" (dict|None), "frame_count"}``. Frame selection
    mirrors the reference ecosystem's VHS_LoadVideo knobs (cap / skip /
    stride) and is applied AT DECODE TIME — only selected frames are
    ever stored or converted, and decode stops once the cap is hit, so
    ``frame_load_cap=16`` on an hour-long clip stays cheap. When
    selection alters the frame set, the outputs stay coherent the way
    VHS does it: ``fps`` is divided by the stride and the audio track is
    trimmed to the source-time span the selected frames cover. Audio:
    muxed track for our AVIs, else a sidecar ``.wav`` beside the file."""
    path = Path(path)
    if not path.exists():
        raise ValidationError(f"video file not found: {path}")
    nth = max(1, int(select_every_nth))
    skip = max(0, int(skip_first_frames))
    cap_n = int(frame_load_cap) if frame_load_cap else 0

    result = (read_avi_mjpg(path, skip=skip, nth=nth, cap=cap_n)
              if path.suffix.lower() == ".avi" else None)
    if result is None:
        cv2 = _require_cv2()
        cap = cv2.VideoCapture(str(path))
        if not cap.isOpened():
            raise ValidationError(f"cannot decode video: {path}")
        fps = cap.get(cv2.CAP_PROP_FPS) or 30.0
        frames = []
        truncated = False
        i = 0
        try:
            while True:
                ok, frame = cap.read()
                if not ok:
                    break
                if i >= skip and (i - skip) % nth == 0:
                    if cap_n > 0 and len(frames) >= cap_n:
                        truncated = True     # more frames were available
                        break
                    frames.append(cv2.cvtColor(frame, cv2.COLOR_BGR2RGB))
                i += 1
        finally:
            cap.release()
        result = {
            "frames": (np.stack(frames).astype(np.float32) / 255.0
                       if frames else np.zeros((0, 1, 1, 3), np.float32)),
            "fps": float(fps), "audio": None, "truncated": truncated,
        }

    if result["frames"].shape[0] == 0:
        raise ValidationError(
            f"no decodable frames after selection (cap/skip/stride): {path}")

    if result["audio"] is None:
        sidecar = path.with_suffix(".wav")
        if sidecar.exists():
            from .audio_payload import wav_decode

            result["audio"] = wav_decode(sidecar.read_bytes())

    n_sel = int(result["frames"].shape[0])
    src_fps = result["fps"]
    truncated = result.pop("truncated", False)   # pop unconditionally —
    # a short-circuited `or` would leak the internal flag into the
    # returned dict whenever skip/stride is set
    selection_active = skip > 0 or nth > 1 or truncated
    if selection_active:
        result["fps"] = src_fps / nth
        if result["audio"] is not None:
            sr = int(result["audio"].get("sample_rate", 44100))
            lo = int(round(skip / src_fps * sr))
            hi = int(round((skip + (n_sel - 1) * nth + 1) / src_fps * sr))
            result["audio"] = {
                "waveform": result["audio"]["waveform"][..., lo:hi],
                "sample_rate": sr,
            }

    result["frames"] = np.ascontiguousarray(result["frames"])
    result["frame_count"] = n_sel
    return result
