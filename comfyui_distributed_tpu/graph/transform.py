"""Prompt-graph rewriting — pure functions, no I/O.

Parity: reference ``api/orchestration/prompt_transform.py`` (558 LoC, the
most heavily unit-tested module in the reference — 61 tests). Same
semantics, same participant model:

- ``PromptIndex`` — class→nodes index + memoized, cycle-safe upstream
  reachability (``:7-53``);
- ``prune_prompt_for_worker`` — workers receive only distributed nodes +
  their upstream closure, with a preview injected where downstream
  consumers were cut (``:331-365``);
- ``prepare_delegate_master_prompt`` — a delegate-only master keeps
  collectors + downstream + provably-safe scalar upstream branches, and
  feeds collectors from ``DistributedEmptyImage`` (``:128-328,368-420``);
- ``apply_participant_overrides`` — hidden inputs (job id, role, callback
  URL) written per participant (``:434-558``);
- ``generate_job_id_map`` — per-node ids ``exec_<ts>_<rand>_<node>``
  (``:423-431``).
"""

from __future__ import annotations

import copy
import secrets
import time
from typing import Iterable

from .node import NODE_REGISTRY, is_link

Prompt = dict[str, dict]

# Node classes that participate in distribution (reference constants,
# web/constants.js:172-231 and prompt_transform usage).
COLLECTOR_CLASSES = frozenset({"DistributedCollector"})
USDU_CLASSES = frozenset({"UltimateSDUpscaleDistributed"})
DISTRIBUTED_CLASSES = COLLECTOR_CLASSES | USDU_CLASSES
# Per-participant nodes that receive role overrides but don't anchor pruning
PARTICIPANT_CLASSES = frozenset(
    {"DistributedSeed", "DistributedValue", "DistributedModelName"}
)
# Upstream classes a delegate master may safely keep (cheap scalar/source
# nodes; reference keeps Primitive*/LoadImage + registered scalar outputs,
# prompt_transform.py:128-328)
SAFE_SCALAR_CLASSES = frozenset(
    {"PrimitiveInt", "PrimitiveFloat", "PrimitiveString", "LoadImage",
     "DistributedSeed", "DistributedValue"}
)
PREVIEW_CLASS = "PreviewImage"
EMPTY_IMAGE_CLASS = "DistributedEmptyImage"


class PromptIndex:
    """Index over a prompt: class lookup + upstream reachability."""

    def __init__(self, prompt: Prompt):
        self.prompt = prompt
        self._by_class: dict[str, list[str]] = {}
        for nid, node in prompt.items():
            self._by_class.setdefault(node.get("class_type", ""), []).append(nid)
        self._upstream_cache: dict[str, frozenset[str]] = {}

    def nodes_of_class(self, class_type: str) -> list[str]:
        return list(self._by_class.get(class_type, []))

    def nodes_of_classes(self, class_types: Iterable[str]) -> list[str]:
        out: list[str] = []
        for ct in class_types:
            out.extend(self._by_class.get(ct, []))
        return out

    def direct_inputs(self, nid: str) -> list[str]:
        node = self.prompt.get(nid)
        if not node:
            return []
        return [
            v[0] for v in node.get("inputs", {}).values()
            if is_link(v) and v[0] in self.prompt
        ]

    def upstream_of(self, nid: str) -> frozenset[str]:
        """All transitive input node ids (cycle-safe, memoized;
        reference ``PromptIndex`` ``:7-53``)."""
        cached = self._upstream_cache.get(nid)
        if cached is not None:
            return cached
        seen: set[str] = set()
        stack = list(self.direct_inputs(nid))
        while stack:
            cur = stack.pop()
            if cur in seen or cur == nid:
                continue
            seen.add(cur)
            stack.extend(self.direct_inputs(cur))
        result = frozenset(seen)
        self._upstream_cache[nid] = result
        return result

    def is_upstream(self, maybe_up: str, of: str) -> bool:
        return maybe_up in self.upstream_of(of)

    def downstream_of(self, nid: str) -> frozenset[str]:
        return frozenset(
            other for other in self.prompt if nid in self.upstream_of(other)
        )


def generate_job_id_map(prompt: Prompt, trace_id: str | None = None) -> dict[str, str]:
    """Per distributed-node job ids: ``exec_<ms>_<6hex>_<node_id>``
    (reference ``:423-431`` + ``api/queue_orchestration.py:315-316``)."""
    index = PromptIndex(prompt)
    base = trace_id or f"exec_{int(time.time() * 1000)}_{secrets.token_hex(3)}"
    return {
        nid: f"{base}_{nid}"
        for nid in index.nodes_of_classes(DISTRIBUTED_CLASSES)
    }


def _drop_dangling_links(prompt: Prompt) -> None:
    """Remove link-valued inputs pointing at nodes not present (in place);
    required inputs that become dangling are left absent — downstream
    validation reports them (reference drops them the same way)."""
    for node in prompt.values():
        inputs = node.get("inputs", {})
        for name in [n for n, v in inputs.items()
                     if is_link(v) and v[0] not in prompt]:
            del inputs[name]


def prune_prompt_for_worker(prompt: Prompt) -> Prompt:
    """Worker payload: distributed nodes + upstream closure only.

    Nodes downstream of a distributed node (e.g. SaveImage after a
    collector) are cut on workers — results flow back via the collector,
    not via worker-side outputs. When a collector thereby loses all its
    consumers, a ``PreviewImage`` is injected so the graph still has a
    terminal output node (reference ``:331-365``).
    """
    index = PromptIndex(prompt)
    anchors = index.nodes_of_classes(DISTRIBUTED_CLASSES)
    keep: set[str] = set(anchors)
    for nid in anchors:
        keep |= index.upstream_of(nid)
    pruned: Prompt = {nid: copy.deepcopy(prompt[nid]) for nid in keep}
    _drop_dangling_links(pruned)

    # re-terminate collectors whose consumers were cut
    consumed = {
        v[0]
        for node in pruned.values()
        for v in node.get("inputs", {}).values()
        if is_link(v)
    }
    counter = 0
    for nid in list(pruned):
        if (
            pruned[nid].get("class_type") in COLLECTOR_CLASSES
            and nid not in consumed
        ):
            counter += 1
            pruned[f"_preview_{counter}"] = {
                "class_type": PREVIEW_CLASS,
                "inputs": {"images": [nid, 0]},
            }
    return pruned


def _is_safe_scalar_branch(prompt: Prompt, index: PromptIndex, nid: str,
                           _visiting: frozenset[str] = frozenset()) -> bool:
    """A branch is safe for a delegate master iff the node and all its
    transitive inputs are in SAFE_SCALAR_CLASSES (recursively validated,
    reference ``:128-328``)."""
    if nid in _visiting:
        return False
    node = prompt.get(nid)
    if node is None or node.get("class_type") not in SAFE_SCALAR_CLASSES:
        return False
    return all(
        _is_safe_scalar_branch(prompt, index, src, _visiting | {nid})
        for src in index.direct_inputs(nid)
    )


def prepare_delegate_master_prompt(prompt: Prompt) -> Prompt:
    """Delegate-only master payload: collectors + everything downstream of
    them + safe scalar upstream branches; collector tensor inputs are fed
    from an injected 0-batch ``DistributedEmptyImage`` so the master
    contributes no compute (reference ``:368-420``)."""
    index = PromptIndex(prompt)
    collectors = index.nodes_of_classes(COLLECTOR_CLASSES)
    keep: set[str] = set(collectors)
    for nid in collectors:
        keep |= index.downstream_of(nid)
    # safe scalar upstream branches of kept nodes
    for nid in list(keep):
        for src in index.direct_inputs(nid):
            if _is_safe_scalar_branch(prompt, index, src):
                keep.add(src)
                keep |= {
                    up for up in index.upstream_of(src)
                    if _is_safe_scalar_branch(prompt, index, up)
                }
    out: Prompt = {nid: copy.deepcopy(prompt[nid]) for nid in keep}

    # feed collectors from an empty image instead of the (cut) producer
    if collectors:
        empty_id = "_delegate_empty"
        out[empty_id] = {
            "class_type": EMPTY_IMAGE_CLASS,
            "inputs": {"height": 64, "width": 64, "channels": 3},
        }
        for nid in collectors:
            inputs = out[nid].setdefault("inputs", {})
            for name, v in list(inputs.items()):
                if is_link(v) and v[0] not in out:
                    inputs[name] = [empty_id, 0]
    _drop_dangling_links(out)
    return out


def apply_participant_overrides(
    prompt: Prompt,
    participant: str,                 # "master" | worker id
    job_id_map: dict[str, str],
    master_url: str = "",
    enabled_worker_ids: tuple[str, ...] = (),
    delegate_only: bool = False,
    worker_index: int | None = None,
) -> Prompt:
    """Write per-participant hidden inputs (in a copy).

    Reference ``:434-558``: distributed nodes get ``multi_job_id``,
    ``is_worker``, ``worker_id``, ``master_url``, ``enabled_worker_ids``,
    ``delegate_only``; participant nodes (seed/value) get role fields;
    collectors that sit downstream of a USDU node get ``pass_through``
    (tiles already travelled through the tile engine).
    """
    out = copy.deepcopy(prompt)
    index = PromptIndex(out)
    is_worker = participant != "master"
    usdu_nodes = set(index.nodes_of_classes(USDU_CLASSES))

    for nid, node in out.items():
        ct = node.get("class_type", "")
        inputs = node.setdefault("inputs", {})
        if ct in DISTRIBUTED_CLASSES:
            if nid in job_id_map:
                inputs["multi_job_id"] = job_id_map[nid]
            inputs["is_worker"] = is_worker
            inputs["worker_id"] = participant if is_worker else ""
            inputs["master_url"] = master_url
            inputs["enabled_worker_ids"] = list(enabled_worker_ids)
            if not is_worker:
                inputs["delegate_only"] = delegate_only
        if ct in COLLECTOR_CLASSES:
            inputs["pass_through"] = any(
                u in usdu_nodes for u in index.upstream_of(nid)
            )
        if ct in PARTICIPANT_CLASSES:
            inputs["is_worker"] = is_worker
            inputs["worker_id"] = participant if is_worker else ""
            if worker_index is not None:
                inputs["worker_index"] = worker_index
    return out
