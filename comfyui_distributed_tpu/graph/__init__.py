"""Workflow graph layer (reference L3/L4: ``nodes/`` + prompt rewriting).

The reference is a ComfyUI *extension*: its graphs execute inside ComfyUI's
executor and its public API accepts ComfyUI prompt JSON
(``{node_id: {"class_type", "inputs": {k: value | [src_id, out_idx]}}}``).
This standalone framework keeps that wire format — so reference workflows
translate directly — but owns the node registry and executor, and the
"distributed" node semantics map onto the SPMD substrate instead of HTTP.
"""

from .node import NODE_REGISTRY, NodeDef, register_node, get_node  # noqa: F401
from .executor import GraphExecutor, validate_prompt  # noqa: F401
from .transform import (  # noqa: F401
    PromptIndex,
    apply_participant_overrides,
    generate_job_id_map,
    prepare_delegate_master_prompt,
    prune_prompt_for_worker,
)
from . import nodes_builtin  # noqa: F401  (registers the node set)
