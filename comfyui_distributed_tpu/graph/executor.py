"""Prompt validation + topological execution.

The reference delegates both to ComfyUI (``execution.validate_prompt`` and
the PromptExecutor; invoked at ``utils/async_helpers.py:108-149``). This is
the standalone equivalent: validate structure/types, then execute in
dependency order with per-node output caching.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from ..utils.exceptions import ValidationError
from .node import NODE_REGISTRY, get_node, is_link

Prompt = dict[str, dict]


@dataclasses.dataclass
class NodeError:
    node_id: str
    message: str

    def as_dict(self) -> dict:
        return {"node_id": self.node_id, "message": self.message}


def strip_meta(prompt: Prompt) -> Prompt:
    """Drop underscore-prefixed keys (``_meta`` workflow headers etc.) —
    shipped workflow files carry documentation alongside the nodes."""
    if isinstance(prompt, dict) and any(k.startswith("_") for k in prompt):
        return {k: v for k, v in prompt.items() if not k.startswith("_")}
    return prompt


def validate_prompt(prompt: Prompt) -> list[NodeError]:
    """Structural validation; returns per-node errors (empty = valid).

    Mirrors the checks ComfyUI's ``validate_prompt`` performs for the
    reference (unknown class, missing required input, dangling link, cycle)
    and reports them in the ``node_errors`` shape of the public API
    (``api/job_routes.py:206-236``).
    """
    errors: list[NodeError] = []
    if not isinstance(prompt, dict) or not prompt:
        return [NodeError("", "prompt must be a non-empty object")]

    for nid, node in prompt.items():
        if not isinstance(node, dict) or "class_type" not in node:
            errors.append(NodeError(nid, "node must have class_type"))
            continue
        cls_name = node["class_type"]
        if cls_name not in NODE_REGISTRY:
            errors.append(NodeError(nid, f"unknown node class {cls_name!r}"))
            continue
        cls = NODE_REGISTRY[cls_name]
        inputs = node.get("inputs", {})
        for name in cls.INPUTS:
            if name not in inputs:
                errors.append(NodeError(nid, f"missing required input {name!r}"))
        for name, value in inputs.items():
            if is_link(value):
                src, out_idx = value
                if src not in prompt:
                    errors.append(NodeError(nid, f"input {name!r} links to missing node {src!r}"))
                else:
                    src_cls_name = prompt[src].get("class_type")
                    src_cls = NODE_REGISTRY.get(src_cls_name)
                    if src_cls is not None and out_idx >= len(src_cls.RETURNS):
                        errors.append(NodeError(
                            nid, f"input {name!r} links to output {out_idx} of "
                                 f"{src_cls_name!r} which has {len(src_cls.RETURNS)}"))
    if not errors:
        try:
            topo_order(prompt)
        except ValidationError as e:
            errors.append(NodeError("", str(e)))
    return errors


def topo_order(prompt: Prompt) -> list[str]:
    """Dependency-first order; raises on cycles."""
    state: dict[str, int] = {}   # 0=visiting, 1=done
    order: list[str] = []

    def visit(nid: str, stack: tuple[str, ...]):
        mark = state.get(nid)
        if mark == 1:
            return
        if mark == 0:
            raise ValidationError(f"cycle involving node {nid!r}")
        state[nid] = 0
        for value in prompt[nid].get("inputs", {}).values():
            if is_link(value) and value[0] in prompt:
                visit(value[0], stack + (nid,))
        state[nid] = 1
        order.append(nid)

    for nid in prompt:
        visit(nid, ())
    return order


def node_kwargs(prompt: Prompt, nid: str, cache: dict[str, tuple],
                context: dict[str, Any]) -> dict[str, Any]:
    """Resolve one node's call kwargs: links from ``cache``, literals as
    given, HIDDEN names from ``context``. Shared by the full executor and
    the front door's microbatch executor (``cluster/frontdoor``), which
    resolves a sampler's inputs without invoking it."""
    node = prompt[nid]
    cls = get_node(node["class_type"])
    kwargs: dict[str, Any] = {}
    for name, value in node.get("inputs", {}).items():
        if name not in cls.all_input_names():
            continue              # tolerate extra inputs (forward compat)
        if is_link(value):
            src, out_idx = value
            kwargs[name] = cache[src][out_idx]
        else:
            kwargs[name] = value
    for name in cls.HIDDEN:
        if name not in kwargs and name in context:
            kwargs[name] = context[name]
    return kwargs


class GraphExecutor:
    """Execute a validated prompt. ``context`` is shared framework state
    (mesh, pipelines, job store handles) that nodes may request via their
    HIDDEN declaration names.
    """

    def __init__(self, context: dict[str, Any] | None = None):
        self.context = context or {}

    def execute(self, prompt: Prompt, outputs_for: list[str] | None = None
                ) -> dict[str, tuple]:
        errs = validate_prompt(prompt)
        if errs:
            raise ValidationError(
                "; ".join(f"{e.node_id}: {e.message}" for e in errs)
            )
        cache: dict[str, tuple] = {}
        self.execute_nodes(prompt, topo_order(prompt), cache)
        if outputs_for is not None:
            return {nid: cache[nid] for nid in outputs_for if nid in cache}
        return cache

    def execute_nodes(self, prompt: Prompt, node_ids: list[str],
                      cache: dict[str, tuple]) -> dict[str, tuple]:
        """Execute ``node_ids`` in the given order into ``cache`` (which
        may carry already-computed results — the microbatch executor runs
        a prompt's prefix, injects the batched sampler output, then runs
        the suffix through this same loop). Callers own validation and
        ordering."""
        interrupt = self.context.get("interrupt_event")
        for nid in node_ids:
            if interrupt is not None and interrupt.is_set():
                # checked between nodes (the reference checks ComfyUI's
                # interrupt flag inside its drain/tile loops; an in-flight
                # XLA dispatch itself is not preemptible)
                raise InterruptedError(f"execution interrupted before {nid}")
            if nid in cache:
                continue
            cls = get_node(prompt[nid]["class_type"])
            kwargs = node_kwargs(prompt, nid, cache, self.context)
            cache[nid] = tuple(cls().execute(**kwargs))
        return cache
