"""Node registry.

Parity: the reference registers 8 node classes into ComfyUI's
``NODE_CLASS_MAPPINGS`` (``nodes/__init__.py:14-22``). Here nodes are plain
classes registered by name with a small declared interface:

- ``INPUTS``: ``{name: type_str}`` required graph inputs;
- ``OPTIONAL``: optional inputs;
- ``HIDDEN``: inputs injected by orchestration, never wired by users
  (the reference's hidden ``is_worker``/``worker_id``/``multi_job_id``);
- ``RETURNS``: tuple of output type names;
- ``execute(**inputs)`` returning a tuple matching ``RETURNS``.

Type names are ComfyUI's ("IMAGE", "LATENT", "INT", ...) so reference
workflow JSON maps 1:1. The wildcard ``"*"`` matches anything (reference
``AnyType``, ``nodes/utilities.py:79-83``).
"""

from __future__ import annotations

from typing import Any, Type

from ..utils.exceptions import ValidationError

NODE_REGISTRY: dict[str, Type["NodeDef"]] = {}


class NodeDef:
    """Base node. Subclass, fill the declarations, implement execute()."""

    INPUTS: dict[str, str] = {}
    OPTIONAL: dict[str, str] = {}
    HIDDEN: dict[str, str] = {}
    RETURNS: tuple[str, ...] = ()
    OUTPUT_NODE = False      # terminal node (kept when pruning, like SaveImage)
    CATEGORY = "distributed-tpu"

    def execute(self, **inputs) -> tuple:
        raise NotImplementedError

    @classmethod
    def all_input_names(cls) -> set[str]:
        return set(cls.INPUTS) | set(cls.OPTIONAL) | set(cls.HIDDEN)


def register_node(name: str):
    def deco(cls: Type[NodeDef]) -> Type[NodeDef]:
        if name in NODE_REGISTRY:
            raise ValidationError(f"duplicate node class {name!r}")
        NODE_REGISTRY[name] = cls
        cls.CLASS_NAME = name
        return cls
    return deco


def get_node(name: str) -> Type[NodeDef]:
    try:
        return NODE_REGISTRY[name]
    except KeyError:
        raise ValidationError(f"unknown node class {name!r}")


def is_link(value: Any) -> bool:
    """Graph-edge encoding: ``[source_node_id, output_index]``."""
    return (
        isinstance(value, (list, tuple))
        and len(value) == 2
        and isinstance(value[0], str)
        and isinstance(value[1], int)
    )
