"""Built-in node set.

Two groups:

1. **Parity nodes** — the reference's 8 distributed node classes
   (``nodes/__init__.py:14-22``) with the same names and contracts:
   DistributedCollector, DistributedSeed, DistributedValue,
   DistributedModelName, ImageBatchDivider, AudioBatchDivider,
   DistributedEmptyImage, UltimateSDUpscaleDistributed.

2. **Substrate nodes** — the minimum ComfyUI-core surface reference
   workflows assume (checkpoint loading, text encode, sampling, VAE,
   save/preview, primitives). The reference free-rides on ComfyUI for
   these; a standalone framework supplies them. The TPU twist: sampling
   nodes execute the *whole* distributed program (shard_map over the mesh
   in executor context) rather than single-device ops.

Graph value conventions: IMAGE = float32 [B,H,W,C] in [0,1];
AUDIO = {"waveform": [B,C,S], "sample_rate": int}; CONDITIONING =
{"context": [1,N,D], "pooled": [1,P]}; MODEL = ModelBundle; LATENT =
{"samples": [B,h,w,c]}.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.exceptions import ValidationError
from ..utils.logging import debug_log, log
from .node import NODE_REGISTRY, NodeDef, register_node


def _chunk_bounds(total: int, parts: int) -> list[tuple[int, int]]:
    """Contiguous chunk bounds, sizes differing by ≤1, larger chunks first
    (reference ``_chunk_bounds``, ``nodes/utilities.py:7-20``)."""
    parts = max(1, min(parts, total)) if total > 0 else 1
    base, extra = divmod(total, parts)
    bounds, start = [], 0
    for i in range(parts):
        size = base + (1 if i < extra else 0)
        bounds.append((start, start + size))
        start += size
    return bounds


# --------------------------------------------------------------------------
# Parity nodes
# --------------------------------------------------------------------------


@register_node("DistributedSeed")
class DistributedSeed(NodeDef):
    """Master passes ``seed`` through; worker N yields ``seed + N + 1``
    (reference ``nodes/utilities.py:52-75``). The sharded pipeline uses
    fold_in internally; this node carries the *visible* seed contract for
    graph-level fan-out across hosts."""

    INPUTS = {"seed": "INT"}
    HIDDEN = {"is_worker": "BOOLEAN", "worker_id": "STRING", "worker_index": "INT"}
    RETURNS = ("INT",)

    def execute(self, seed: int, is_worker: bool = False, worker_id: str = "",
                worker_index: int = 0, **_):
        if not is_worker:
            return (int(seed),)
        return (int(seed) + int(worker_index) + 1,)


@register_node("DistributedValue")
class DistributedValue(NodeDef):
    """Per-worker override with typed coercion and default fallback
    (reference ``nodes/utilities.py:86-162``): ``worker_values`` is a JSON
    map of 1-indexed worker number → value."""

    INPUTS = {"default_value": "*"}
    OPTIONAL = {"worker_values": "STRING", "value_type": "STRING"}
    HIDDEN = {"is_worker": "BOOLEAN", "worker_id": "STRING", "worker_index": "INT"}
    RETURNS = ("*",)

    _COERCERS = {
        "INT": lambda v: int(float(v)),
        "FLOAT": float,
        "STRING": str,
        "COMBO": str,
    }

    def _coerce(self, value: Any, value_type: str) -> Any:
        fn = self._COERCERS.get(value_type.upper())
        if fn is None:
            return value
        try:
            return fn(value)
        except (TypeError, ValueError):
            raise ValidationError(
                f"cannot coerce {value!r} to {value_type}", field="worker_values"
            )

    def execute(self, default_value, worker_values: str = "", value_type: str = "",
                is_worker: bool = False, worker_id: str = "", worker_index: int = 0,
                **_):
        if not is_worker or not worker_values:
            return (default_value,)
        try:
            mapping = json.loads(worker_values)
        except json.JSONDecodeError:
            return (default_value,)
        key = str(int(worker_index) + 1)   # 1-indexed per reference
        if key not in mapping:
            return (default_value,)
        vtype = value_type or mapping.get("_type", "")
        return (self._coerce(mapping[key], vtype) if vtype else mapping[key],)


@register_node("DistributedModelName")
class DistributedModelName(NodeDef):
    """OUTPUT_NODE passing model names through as strings so delegate-mode
    workers can load models the master lacks (reference
    ``nodes/utilities.py:164-224``)."""

    INPUTS = {"model_name": "*"}
    HIDDEN = {"is_worker": "BOOLEAN", "worker_id": "STRING"}
    RETURNS = ("STRING",)
    OUTPUT_NODE = True

    def execute(self, model_name, **_):
        return (str(model_name),)


@register_node("ImageBatchDivider")
class ImageBatchDivider(NodeDef):
    """Split an IMAGE batch into up to 10 contiguous chunks (reference
    ``nodes/utilities.py:235-268``); chunks beyond the batch repeat the
    empty image."""

    INPUTS = {"images": "IMAGE", "divide_by": "INT"}
    RETURNS = tuple(["IMAGE"] * 10)

    def execute(self, images, divide_by: int = 2, **_):
        divide_by = max(1, min(int(divide_by), 10))
        arr = jnp.asarray(images)
        bounds = _chunk_bounds(arr.shape[0], divide_by)
        chunks = [arr[s:e] for s, e in bounds]
        empty = arr[:0]
        while len(chunks) < 10:
            chunks.append(empty)
        return tuple(chunks)


@register_node("AudioBatchDivider")
class AudioBatchDivider(NodeDef):
    """Split AUDIO along the samples dim (reference
    ``nodes/utilities.py:271-329``)."""

    INPUTS = {"audio": "AUDIO", "divide_by": "INT"}
    RETURNS = tuple(["AUDIO"] * 10)

    def execute(self, audio, divide_by: int = 2, **_):
        divide_by = max(1, min(int(divide_by), 10))
        wf = np.asarray(audio["waveform"])
        sr = int(audio.get("sample_rate", 44100))
        bounds = _chunk_bounds(wf.shape[-1], divide_by)
        chunks = [
            {"waveform": wf[..., s:e], "sample_rate": sr} for s, e in bounds
        ]
        empty = {"waveform": wf[..., :0], "sample_rate": sr}
        while len(chunks) < 10:
            chunks.append(empty)
        return tuple(chunks)


@register_node("ImageFromBatch")
class ImageFromBatch(NodeDef):
    """Slice [batch_index : batch_index+length] out of an IMAGE batch
    (ComfyUI-core node the reference's video-upscale workflow assumes —
    ``/root/reference/workflows/distributed-upscale-video.json``; index
    and length clamp to the batch like the original)."""

    INPUTS = {"image": "IMAGE", "batch_index": "INT", "length": "INT"}
    RETURNS = ("IMAGE",)

    def execute(self, image, batch_index: int, length: int, **_):
        arr = jnp.asarray(image)
        start = min(max(int(batch_index), 0), max(arr.shape[0] - 1, 0))
        count = min(max(int(length), 1), arr.shape[0] - start)
        return (arr[start:start + count],)


@register_node("SolidMask")
class SolidMask(NodeDef):
    """Constant-value mask (ComfyUI's SolidMask): the building block for
    inpaint regions and USDU spatial conditioning."""

    INPUTS = {"value": "FLOAT", "width": "INT", "height": "INT"}
    RETURNS = ("MASK",)

    def execute(self, value: float = 1.0, width: int = 64,
                height: int = 64, **_):
        import numpy as np

        return (np.full((1, int(height), int(width)),
                        float(value), np.float32),)


@register_node("DistributedEmptyImage")
class DistributedEmptyImage(NodeDef):
    """0-batch IMAGE placeholder for delegate-only masters (reference
    ``nodes/utilities.py:332-354``)."""

    INPUTS = {"height": "INT", "width": "INT"}
    OPTIONAL = {"channels": "INT"}
    RETURNS = ("IMAGE",)

    def execute(self, height: int = 64, width: int = 64, channels: int = 3, **_):
        return (jnp.zeros((0, int(height), int(width), int(channels)), jnp.float32),)


@register_node("DistributedCollector")
class DistributedCollector(NodeDef):
    """Result gather point (reference ``nodes/collector.py``).

    On-pod, the "gather" already happened inside the SPMD program (the
    sharded output array), so locally this node is identity. Across hosts
    the executor context provides a ``collector_bridge`` (cluster layer):
    worker role pushes its batch to the master; master role drains and
    concatenates master-first (``nodes/collector.py:252-295``). With
    ``pass_through`` (downstream of USDU) it is always identity
    (``nodes/collector.py:121-124``).
    """

    INPUTS = {"images": "IMAGE"}
    OPTIONAL = {"audio": "AUDIO"}
    HIDDEN = {
        "multi_job_id": "STRING", "is_worker": "BOOLEAN", "worker_id": "STRING",
        "master_url": "STRING", "enabled_worker_ids": "*",
        "delegate_only": "BOOLEAN", "pass_through": "BOOLEAN",
        "collector_bridge": "*",
    }
    RETURNS = ("IMAGE", "AUDIO")

    def execute(self, images, audio=None, multi_job_id: str = "",
                is_worker: bool = False, worker_id: str = "",
                master_url: str = "", enabled_worker_ids=(),
                delegate_only: bool = False, pass_through: bool = False,
                collector_bridge=None, **_):
        if pass_through or not multi_job_id or collector_bridge is None:
            return (images, audio)
        if is_worker:
            collector_bridge.send(multi_job_id, worker_id, images, audio,
                                  master_url)
            return (images, audio)
        images, audio = collector_bridge.collect(
            multi_job_id, images, audio,
            enabled_worker_ids=tuple(enabled_worker_ids),
            delegate_only=delegate_only,
        )
        return (images, audio)


@register_node("UltimateSDUpscaleDistributed")
class UltimateSDUpscaleDistributed(NodeDef):
    """Tile-sharded upscale (reference ``nodes/distributed_upscale.py``).

    Mode selection collapses on TPU: static/dynamic/single-gpu pull-queues
    (``:230-267``) become one SPMD program over however many chips the
    executor's mesh has; the video 4n+1 batch rule (``:131-142``) is a
    padding rule applied by the video divider, not a constraint here.
    """

    INPUTS = {
        "image": "IMAGE", "model": "MODEL",
        "positive": "CONDITIONING", "negative": "CONDITIONING",
        "seed": "INT", "steps": "INT", "denoise": "FLOAT",
        "upscale_by": "FLOAT",
    }
    OPTIONAL = {
        "tile_width": "INT", "tile_height": "INT", "tile_padding": "INT",
        "cfg": "FLOAT", "sampler_name": "STRING", "scheduler": "STRING",
        "spatial_cond": "MASK", "dynamic_threshold": "INT",
    }
    HIDDEN = {
        "mesh": "*", "multi_job_id": "STRING", "is_worker": "BOOLEAN",
        "worker_id": "STRING", "master_url": "STRING",
        "enabled_worker_ids": "*", "delegate_only": "BOOLEAN",
        "tile_farm": "*",
    }
    RETURNS = ("IMAGE",)

    def execute(self, image, model, positive, negative, seed: int, steps: int,
                denoise: float, upscale_by: float, tile_width: int = 512,
                tile_height: int = 512, tile_padding: int = 32,
                cfg: float = 5.0, sampler_name: str = "euler",
                scheduler: str = "karras", spatial_cond=None,
                dynamic_threshold: int = 8, mesh=None,
                multi_job_id: str = "", is_worker: bool = False,
                worker_id: str = "", master_url: str = "",
                enabled_worker_ids=(), tile_farm=None, **_):
        from ..parallel.mesh import build_mesh
        from ..tiles.engine import TileUpscaler, UpscaleSpec

        if mesh is None:
            mesh = build_mesh({"dp": len(jax.devices())})
        spec = UpscaleSpec(
            scale=float(upscale_by), tile_w=int(tile_width), tile_h=int(tile_height),
            padding=int(tile_padding), steps=int(steps), denoise=float(denoise),
            sampler=sampler_name, scheduler=scheduler, guidance_scale=float(cfg),
        )
        # ControlNet rides the positive conditioning; hints are cropped
        # per tile inside the SPMD program (reference crop_cond +
        # crop_model_patch semantics, SURVEY §7 hard-part #3)
        control = positive.get("control") if isinstance(positive, dict) else None
        pipeline = model.pipeline
        control_hint = None
        if control:
            pipeline = pipeline.with_control(control["model"],
                                             control.get("strength", 1.0))
            # hints arrive 4-D (normalized by ControlNetApply)
            control_hint = jnp.asarray(control["hint"], jnp.float32)
        upscaler = TileUpscaler(pipeline)
        adm = model.pipeline.unet.config.adm_in_channels
        y = uy = None
        if adm:
            y = _adm_from_cond(positive, adm)
            uy = _adm_from_cond(negative, adm)

        # cross-host farm engages when orchestration assigned a job id and
        # remote worker hosts participate (reference mode selection,
        # nodes/distributed_upscale.py:230-267; on-pod SPMD otherwise)
        farm_active = (tile_farm is not None and multi_job_id
                       and (is_worker or enabled_worker_ids))
        smap = None
        if spatial_cond is not None:
            # MASK convention [B,H,W] → [B,H,W,1]; cropped per tile inside
            # the engine (reference crop_cond, usdu_utils.py:506)
            smap = jnp.asarray(spatial_cond, jnp.float32)
            if smap.ndim == 3:
                smap = smap[..., None]
        if not farm_active:
            out = upscaler.upscale(
                mesh, jnp.asarray(image), spec, int(seed),
                positive["context"], negative["context"], y, uy,
                spatial_cond=smap, control_hint=control_hint,
            )
            return (out,)
        if control_hint is not None:
            log("USDU farm mode: ControlNet hints apply to locally "
                "processed work only; cross-host STATIC tile tasks run "
                "without control this round")

        images = jnp.asarray(image)

        # dynamic (per-image) mode for large batches — reference
        # upscale/modes/dynamic.py: the pull queue holds IMAGE indices and
        # full processed images travel back, not tiles. Here each task is
        # one image run through the on-pod SPMD tile program; global image
        # index seeds the noise so assignment/requeue stays invisible.
        if images.shape[0] >= max(2, int(dynamic_threshold)):
            def process_images(start: int, end: int) -> np.ndarray:
                done = []
                for i in range(start, end):
                    ch = control_hint
                    if ch is not None and ch.shape[0] == images.shape[0]:
                        ch = ch[i:i + 1]
                    done.append(np.asarray(upscaler.upscale(
                        mesh, images[i:i + 1], spec, int(seed) + i,
                        positive["context"], negative["context"], y, uy,
                        spatial_cond=None if smap is None else smap[i:i + 1],
                        control_hint=ch,
                    )))
                return np.concatenate(done, axis=0)

            from ..cluster.tile_farm import assemble_tiles

            if is_worker:
                from ..ops.resize import upscale_image

                tile_farm.worker_run(multi_job_id, worker_id, master_url,
                                     process_images)
                return (upscale_image(images, spec.scale,
                                      spec.resize_method),)
            from ..utils import constants as _c

            results = tile_farm.master_run(
                multi_job_id, images.shape[0], process_images, chunk=1,
                journal_dir=_c.TILE_JOURNAL_DIR or None,
                journal_key=_journal_key(images, spec, seed, 0, 1,
                                         images.shape[0])
                if _c.TILE_JOURNAL_DIR else None)

            def _plain_resize(start: int, end: int) -> np.ndarray:
                # degraded fill for dead-lettered images: plain resize,
                # no diffusion — one poison image costs one unrefined
                # frame, not the job
                from ..ops.resize import upscale_image

                return np.asarray(upscale_image(
                    images[start:end], spec.scale, spec.resize_method),
                    np.float32)

            full = assemble_tiles(results, images.shape[0], 1,
                                  fallback_fn=_plain_resize)
            return (jnp.asarray(full),)

        outs = []
        for b in range(images.shape[0]):
            plan = upscaler.range_plan(
                mesh, images[b], spec, int(seed),
                positive["context"], negative["context"], y, uy,
                spatial_cond=None if smap is None else smap[b],
            )
            job_id = (f"{multi_job_id}_b{b}" if images.shape[0] > 1
                      else multi_job_id)
            if is_worker:
                from ..ops.resize import upscale_image

                tile_farm.worker_run(job_id, worker_id, master_url,
                                     plan.run_range)
                # master owns the composite; the worker returns a size-
                # correct plain resize so its downstream graph stays
                # shape-consistent (reference worker role,
                # nodes/distributed_upscale.py:164)
                outs.append(upscale_image(images[b][None], spec.scale,
                                          spec.resize_method)[0])
                continue
            from ..cluster.tile_farm import assemble_tiles

            from ..utils import constants as _c

            results = tile_farm.master_run(
                job_id, plan.num_tiles, plan.run_range, chunk=plan.chunk,
                journal_dir=_c.TILE_JOURNAL_DIR or None,
                journal_key=_journal_key(images[b], spec, seed, b,
                                         plan.chunk, plan.num_tiles)
                if _c.TILE_JOURNAL_DIR else None)
            tiles = assemble_tiles(results, plan.num_tiles, plan.chunk,
                                   fallback_fn=plan.source_range)
            outs.append(upscaler.composite(tiles, plan))
        return (jnp.stack([jnp.asarray(o) for o in outs], axis=0),)


def _journal_key(images, spec, seed: int, index: int = 0,
                 chunk: int = 1, total: int = 0) -> str:
    """Stable crash-resume key: a re-submitted workflow gets a fresh
    execution job id, so the journal is keyed by job CONTENT (input
    pixels + spec + seed) — plus the task topology (chunk/total): a
    restart on a different chip count must NOT restore payloads whose
    arrays cover different tile ranges."""
    import hashlib

    h = hashlib.sha1()
    h.update(np.ascontiguousarray(np.asarray(images, np.float32)).tobytes())
    h.update(repr((spec, int(seed), int(index), int(chunk),
                   int(total))).encode())
    return f"usdu_{h.hexdigest()[:20]}"


def _stop_cb(interrupt_event):
    """should_stop callable for the offloaded python ladders — ONE
    definition for every offload-capable sampler node."""
    return interrupt_event.is_set if interrupt_event is not None else None


class _ProgressScope:
    """Progress lifecycle shared by the sampler nodes: allocates a token
    on entry; ``complete(out)`` blocks on the result AND drains pending
    ``jax.debug.callback`` effects (block_until_ready alone does not
    flush them) before exit marks the run done — anything else marks it
    failed, freezing progress where it stopped instead of reporting
    100%. ``on_step`` is the host-side reporter for the offloaded
    (python-ladder) samplers — same tracker, no traced token."""

    def on_step(self, sigma: float, x0) -> None:
        if self.token is not None:
            self.tracker.report(self.token, sigma, x0)

    def __init__(self, tracker, prompt_id: str, total_calls: int):
        self.tracker, self.prompt_id = tracker, prompt_id
        self.token = (tracker.start(prompt_id, total_calls)
                      if tracker is not None and prompt_id else None)
        self._ok = False

    def complete(self, out) -> None:
        if self.token is not None:
            jax.block_until_ready(out)
            jax.effects_barrier()
        self._ok = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self.token is not None:
            self.tracker.finish(self.prompt_id, failed=not self._ok)
        return False


def _adm_from_cond(cond: dict, adm_channels: int) -> jax.Array:
    """Build the ADM vector from pooled conditioning, zero-padded/truncated
    to the UNet's expected width (full SDXL micro-conds via
    ``diffusion.pipeline.sdxl_adm`` when sizes are known)."""
    pooled = cond.get("pooled")
    if pooled is None:
        return jnp.zeros((1, adm_channels), jnp.float32)
    pooled = jnp.asarray(pooled)
    pad = adm_channels - pooled.shape[-1]
    if pad > 0:
        return jnp.pad(pooled, ((0, 0), (0, pad)))
    return pooled[:, :adm_channels]


# --------------------------------------------------------------------------
# Substrate nodes (ComfyUI-core surface the reference assumes)
# --------------------------------------------------------------------------


def _resolve_model_file(env_var: str, subdir: str, name: str):
    """Shared weight-file resolution for the model-loader nodes:
    ``$<env_var>`` (or ``$CDT_CHECKPOINT_ROOT/<subdir>``) + ``name`` with
    ``.safetensors`` appended unless present. Returns (path_or_None,
    root, source_key) where ``source_key`` identifies the weight SOURCE
    (path + mtime for files) so loader caches invalidate when the file
    appears or changes."""
    import os

    from ..utils import constants

    ckpt_root = constants.CHECKPOINT_ROOT.get()
    root = constants.knob(env_var).get() or (
        os.path.join(ckpt_root, subdir) if ckpt_root else "")
    if not root:
        return None, "", None
    fname = name if name.endswith(".safetensors") else f"{name}.safetensors"
    path = Path(root) / fname
    if path.is_file():
        return path, root, ("file", str(path), path.stat().st_mtime_ns)
    return None, root, None


_UPSCALER_PRESETS = {
    "tiny-x2": lambda cfg_mod: cfg_mod.UpscalerConfig.tiny(scale=2),
    "tiny-x4": lambda cfg_mod: cfg_mod.UpscalerConfig.tiny(scale=4),
    "esrgan-x4": lambda cfg_mod: cfg_mod.UpscalerConfig.esrgan_x4(),
    "realesrgan-x2": lambda cfg_mod: cfg_mod.UpscalerConfig.realesrgan_x2(),
}
_upscaler_cache: dict[str, Any] = {}


@register_node("UpscaleModelLoader")
class UpscaleModelLoader(NodeDef):
    """ESRGAN-family model loader (ComfyUI-core surface the reference's
    upscale workflows assume: ``UpscaleModelLoader`` →
    ``ImageUpscaleWithModel`` feeding USDU's input,
    ``workflows/distributed-upscale.json``). ``model_name`` is either a
    published RRDBNet ``.safetensors`` under ``CDT_UPSCALE_MODEL_DIR``
    (falling back to ``CDT_CHECKPOINT_ROOT/upscalers``) — converted on
    load — or an architecture preset name (random-init, for tests and
    architecture work)."""

    INPUTS = {"model_name": "STRING"}
    RETURNS = ("UPSCALE_MODEL",)

    def execute(self, model_name: str, **_):
        name = str(model_name)
        candidate, root, source = _resolve_model_file(
            "CDT_UPSCALE_MODEL_DIR", "upscalers", name)
        # cache entries are keyed by their weight SOURCE: a checkpoint
        # dropped in after a random-init fallback (or replaced on disk)
        # must win on the next load, not be shadowed until restart
        if source is None and name in _UPSCALER_PRESETS:
            source = ("preset", name)
        if source is None:
            raise ValidationError(
                f"unknown upscale model {name!r}: no checkpoint under "
                f"{root or '$CDT_UPSCALE_MODEL_DIR'} and not one of "
                f"{sorted(_UPSCALER_PRESETS)}", field="model_name")
        cached = _upscaler_cache.get(name)
        if cached is not None and cached[0] == source:
            return (cached[1],)
        if source[0] == "file":
            from ..models.convert import load_upscaler_checkpoint

            bundle = load_upscaler_checkpoint(candidate)
        else:
            from ..models import upscaler as upscaler_mod

            cfg = _UPSCALER_PRESETS[name](upscaler_mod)
            bundle = upscaler_mod.init_upscaler(cfg, jax.random.key(0))
            bundle.name = name
            log(f"upscaler {name!r}: no checkpoint found — random init")
        _upscaler_cache[name] = (source, bundle)
        return (bundle,)


@register_node("ImageUpscaleWithModel")
class ImageUpscaleWithModel(NodeDef):
    """Tile-sharded learned upscale: the tile batch shards over the mesh's
    dp axis in one SPMD program (TPU redesign of ComfyUI's single-GPU
    tiled torch loop the reference free-rides on)."""

    INPUTS = {"upscale_model": "UPSCALE_MODEL", "image": "IMAGE"}
    OPTIONAL = {"tile": "INT", "tile_padding": "INT"}
    HIDDEN = {"mesh": "*"}
    RETURNS = ("IMAGE",)

    def execute(self, upscale_model, image, tile: int = 256,
                tile_padding: int = 16, mesh=None, **_):
        from ..parallel.mesh import build_mesh
        from ..tiles.model_upscale import tiled_model_upscale

        if mesh is None:
            mesh = build_mesh({"dp": len(jax.devices())})
        images = jnp.asarray(image, jnp.float32)
        if images.ndim == 3:
            images = images[None]
        tile = min(int(tile), images.shape[1], images.shape[2])
        out = tiled_model_upscale(mesh, upscale_model, images,
                                  tile=tile, padding=int(tile_padding))
        return (np.asarray(out),)


_controlnet_cache: dict[str, Any] = {}


@register_node("ControlNetLoader")
class ControlNetLoader(NodeDef):
    """ControlNet loader (ComfyUI-core surface; the reference's USDU
    crops control hints per tile, ``utils/usdu_utils.py:506``).
    ``control_net_name`` is a published ``.safetensors`` under
    ``CDT_CONTROLNET_DIR`` (or ``CDT_CHECKPOINT_ROOT/controlnet``) — the
    base architecture (sd15/sdxl) is detected from the checkpoint — or a
    preset name (``tiny``/``sd15``/``sdxl``, random init)."""

    INPUTS = {"control_net_name": "STRING"}
    RETURNS = ("CONTROL_NET",)

    _PRESETS = ("tiny", "sd15", "sdxl")

    def execute(self, control_net_name: str, **_):
        from ..models.unet import UNetConfig

        name = str(control_net_name)
        candidate, root, source = _resolve_model_file(
            "CDT_CONTROLNET_DIR", "controlnet", name)
        if source is None and name in self._PRESETS:
            source = ("preset", name)
        if source is None:
            raise ValidationError(
                f"unknown control net {name!r}: no checkpoint under "
                f"{root or '$CDT_CONTROLNET_DIR'} and not one of "
                f"{self._PRESETS}", field="control_net_name")
        cached = _controlnet_cache.get(name)
        if cached is not None and cached[0] == source:
            return (cached[1],)

        from ..models.controlnet import ControlNet, ControlNetBundle, \
            init_controlnet

        if source[0] == "file":
            from ..models.convert import convert_controlnet, load_safetensors

            sd = load_safetensors(candidate)
            # base architecture from the checkpoint itself
            if "control_model.label_emb.0.0.weight" in sd:
                cfg = UNetConfig.sdxl()
            else:
                cfg = UNetConfig.sd15()
            params = convert_controlnet(sd, self._template(cfg), cfg)
            bundle = ControlNetBundle(ControlNet(cfg), params,
                                      name=candidate.stem)
            log(f"converted controlnet {candidate} ({cfg.context_dim}-ctx)")
        else:
            cfg = {"tiny": UNetConfig.tiny, "sd15": UNetConfig.sd15,
                   "sdxl": UNetConfig.sdxl}[name]()
            hw = (8, 8) if name == "tiny" else (32, 32)
            bundle = init_controlnet(cfg, jax.random.key(0), sample_shape=(
                *hw, cfg.in_channels))
            bundle.name = name
            log(f"controlnet {name!r}: no checkpoint found — random init")
        if len(_controlnet_cache) >= 4:
            _controlnet_cache.pop(next(iter(_controlnet_cache)))
        _controlnet_cache[name] = (source, bundle)
        return (bundle,)

    @staticmethod
    def _template(cfg):
        """Shape-only template via eval_shape — the converter checks leaf
        shapes, so a full (GB-scale) random init would be pure waste."""
        from ..models.controlnet import ControlNet

        model = ControlNet(cfg)
        h, w = 8, 8
        return jax.eval_shape(
            model.init, jax.random.key(0),
            jnp.zeros((1, h, w, cfg.in_channels), jnp.float32),
            jnp.zeros((1,), jnp.float32),
            jnp.zeros((1, 8, cfg.context_dim), jnp.float32),
            (jnp.zeros((1, cfg.adm_in_channels), jnp.float32)
             if cfg.adm_in_channels else None),
            jnp.zeros((1, h * 8, w * 8, 3), jnp.float32))


@register_node("ControlNetApply")
class ControlNetApply(NodeDef):
    """Attach a control hint to a conditioning (ComfyUI semantics): the
    sampler nodes read ``conditioning["control"]`` and thread the hint
    through every denoise step. Under CFG the control conditions both
    passes (A1111 convention)."""

    INPUTS = {"conditioning": "CONDITIONING", "control_net": "CONTROL_NET",
              "image": "IMAGE"}
    OPTIONAL = {"strength": "FLOAT"}
    RETURNS = ("CONDITIONING",)

    def execute(self, conditioning, control_net, image,
                strength: float = 1.0, **_):
        hint = np.asarray(image, np.float32)
        if hint.ndim == 3:
            hint = hint[None]
        return ({**conditioning,
                 "control": {"model": control_net, "hint": hint,
                             "strength": float(strength)}},)


def _control_from_cond(pipeline, cond: dict, height: int, width: int):
    """Activate the conditioning's ControlNet on a pipeline clone and
    shape the hint for the stem: the published hint stem downscales by 8,
    so the hint target is latent-res × 8 (equal to the image size for
    SD-family VAEs; differs only for toy test VAEs). Returns
    (pipeline, hint)."""
    control = cond.get("control") if isinstance(cond, dict) else None
    if not control:
        return pipeline, None
    # ControlNetApply normalizes hints to 4-D at the producer side
    hint = jnp.asarray(control["hint"], jnp.float32)
    ds = pipeline.vae.config.downscale
    target = (height // ds * 8, width // ds * 8)
    if hint.shape[1:3] != target:
        hint = jax.image.resize(
            hint, (hint.shape[0], *target, hint.shape[-1]),
            method="bilinear")
    return (pipeline.with_control(control["model"],
                                  control.get("strength", 1.0)), hint)


@register_node("LoraLoader")
class LoraLoader(NodeDef):
    """Merge a kohya-format LoRA into copies of the model/clip (ComfyUI
    core ``LoraLoader`` surface; the reference free-rides on it). The
    registry's shared bundle is never mutated — patched params live in a
    shallow pipeline clone with a fresh compile cache. ``lora_name``
    resolves under ``CDT_LORA_DIR`` (or ``CDT_CHECKPOINT_ROOT/loras``)."""

    INPUTS = {"model": "MODEL", "clip": "CLIP", "lora_name": "STRING"}
    OPTIONAL = {"strength_model": "FLOAT", "strength_clip": "FLOAT"}
    RETURNS = ("MODEL", "CLIP")

    _cache: dict = {}

    def execute(self, model, clip, lora_name: str,
                strength_model: float = 1.0, strength_clip: float = 1.0,
                **_):
        from ..models.lora import apply_lora, load_lora_file

        if not strength_model and not strength_clip:
            return (model, clip)
        name = str(lora_name)
        path, root, source = _resolve_model_file("CDT_LORA_DIR", "loras",
                                                 name)
        if source is None:
            raise ValidationError(
                f"LoRA {name!r} not found under "
                f"{root or '$CDT_LORA_DIR'}", field="lora_name")
        # merge + compile are expensive; memoize per (base model, weight
        # source, strengths). The cached entry pins the base bundle, so
        # identity comparison is safe (ids can't recycle while cached).
        key = (name, source, float(strength_model), float(strength_clip))
        cached = self._cache.get(key)
        if (cached is not None and cached[0] is model
                and cached[1] is clip):
            return cached[2]
        patched, conditioner = apply_lora(
            model, load_lora_file(path),
            strength_model=float(strength_model),
            strength_clip=float(strength_clip), name=name)
        result = (patched, conditioner if conditioner is not None else clip)
        if len(self._cache) >= 4:
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = (model, clip, result)
        return result


@register_node("ImageScale")
class ImageScale(NodeDef):
    """Plain device-side resize (ComfyUI-core surface the reference's
    workflows interleave between model stages). Accepts ComfyUI's
    ``upscale_method`` input name and method vocabulary; width/height 0
    derives that dimension keeping aspect (ComfyUI convention)."""

    INPUTS = {"image": "IMAGE", "width": "INT", "height": "INT"}
    OPTIONAL = {"method": "STRING", "upscale_method": "STRING",
                "crop": "STRING"}
    RETURNS = ("IMAGE",)

    def execute(self, image, width: int, height: int,
                method: str = "lanczos3", upscale_method: str = "",
                crop: str = "disabled", **_):
        from ..ops.resize import normalize_method, resize_to

        try:
            method = normalize_method(upscale_method or method)
        except ValueError as e:
            raise ValidationError(str(e), field="upscale_method")
        if crop not in ("disabled", "center"):
            raise ValidationError(
                f"unknown crop mode {crop!r}; have disabled|center",
                field="crop")
        images = jnp.asarray(image, jnp.float32)
        if images.ndim == 3:
            images = images[None]
        _, H, W, _ = images.shape
        width, height = int(width), int(height)
        if width < 0 or height < 0:
            raise ValidationError(
                "width/height must be >= 0 (0 keeps aspect)", field="width")
        if width == 0 and height == 0:
            raise ValidationError("width and height cannot both be 0",
                                  field="width")
        if width == 0:
            width = max(1, round(W * height / H))
        if height == 0:
            height = max(1, round(H * width / W))
        if crop == "center" and (H * width != W * height):
            # center-crop the source to the target aspect before resizing
            # (ComfyUI-core ImageScale crop="center" semantics)
            if W * height > H * width:            # too wide
                new_w = max(1, round(H * width / height))
                x0 = (W - new_w) // 2
                images = images[:, :, x0:x0 + new_w, :]
            else:                                  # too tall
                new_h = max(1, round(W * height / width))
                y0 = (H - new_h) // 2
                images = images[:, y0:y0 + new_h, :, :]
        return (resize_to(images, height, width, method),)


@register_node("ImageScaleBy")
class ImageScaleBy(NodeDef):
    INPUTS = {"image": "IMAGE", "scale_by": "FLOAT"}
    OPTIONAL = {"method": "STRING", "upscale_method": "STRING"}
    RETURNS = ("IMAGE",)

    def execute(self, image, scale_by: float, method: str = "lanczos3",
                upscale_method: str = "", **_):
        from ..ops.resize import normalize_method, upscale_image

        try:
            method = normalize_method(upscale_method or method)
        except ValueError as e:
            raise ValidationError(str(e), field="upscale_method")
        if float(scale_by) <= 0:
            raise ValidationError("scale_by must be > 0", field="scale_by")
        images = jnp.asarray(image, jnp.float32)
        if images.ndim == 3:
            images = images[None]
        return (upscale_image(images, float(scale_by), method),)


@register_node("CheckpointLoader")
class CheckpointLoader(NodeDef):
    INPUTS = {"ckpt_name": "STRING"}
    HIDDEN = {"model_registry": "*"}
    RETURNS = ("MODEL", "CLIP", "VAE")

    def execute(self, ckpt_name: str, model_registry=None, **_):
        if model_registry is None:
            from ..models.registry import ModelRegistry
            model_registry = ModelRegistry()
        bundle = model_registry.get(ckpt_name)
        return (bundle, bundle.text_encoder, bundle.pipeline.vae)


class _ShiftedModel:
    """MODEL proxy carrying a sampling-shift override; every other
    attribute forwards to the wrapped bundle (the ComfyUI patched-model
    clone pattern, minus torch model cloning)."""

    def __init__(self, base, shift: float):
        self._base = base
        self.sampling_shift = float(shift)

    def __getattr__(self, name):
        return getattr(self._base, name)


@register_node("ModelSamplingSD3")
class ModelSamplingSD3(NodeDef):
    """Sigma-shift control for flow models (ComfyUI-core node used by the
    reference's video workflow, ``distributed-upscale-video.json``):
    returns a MODEL whose default flow shift is overridden — the flow
    ladder becomes σ' = shift·σ / (1 + (shift−1)·σ). Sampler nodes
    consult it whenever the graph does not wire an explicit shift."""

    INPUTS = {"model": "MODEL", "shift": "FLOAT"}
    RETURNS = ("MODEL",)

    def execute(self, model, shift: float, **_):
        return (_ShiftedModel(model, shift),)


@register_node("CLIPTextEncode")
class CLIPTextEncode(NodeDef):
    INPUTS = {"text": "STRING", "clip": "CLIP"}
    HIDDEN = {"content_cache": "*"}
    RETURNS = ("CONDITIONING",)

    def execute(self, text: str, clip, content_cache=None, **_):
        # text-encode through the fleet conditioning cache when the
        # controller carries one (cluster/cache): identical prompts —
        # and the negative prompt nearly every request shares — encode
        # once, fleet-wide. Falls through to a plain encode for
        # unidentified encoders or CDT_CACHE=0.
        from ..cluster.cache.conditioning import cached_encode

        ctx, pooled = cached_encode(content_cache, clip, [str(text)])
        return ({"context": ctx, "pooled": pooled},)


@register_node("EmptyLatentImage")
class EmptyLatentImage(NodeDef):
    INPUTS = {"width": "INT", "height": "INT"}
    OPTIONAL = {"batch_size": "INT", "ckpt_name": "STRING"}
    RETURNS = ("LATENT",)

    def execute(self, width: int, height: int, batch_size: int = 1,
                ckpt_name: str = "", **_):
        # latent geometry follows the model preset (flux/wan latents are
        # 16-channel; the tiny test VAE downscales 2×, not 8×); SD-family
        # 8×/4ch is the default for preset-less graphs
        downscale, channels = 8, 4
        if ckpt_name:
            from ..models.registry import PRESETS

            preset = PRESETS.get(str(ckpt_name))
            if preset is not None:
                downscale = preset.vae.downscale
                channels = preset.vae.latent_channels
        return ({"samples": jnp.zeros(
                    (int(batch_size), int(height) // downscale,
                     int(width) // downscale, channels), jnp.float32),
                 "height": int(height), "width": int(width)},)


def _pinned(model):
    """Residency pin for the duration of a generate call: with
    ``CDT_HBM_BUDGET_GB`` set, a concurrent acquire (warmup thread,
    another model's request) must never evict THIS bundle mid-program
    (``cluster/residency.pinned_bundle``; no-op without a planner)."""
    from ..cluster.residency import pinned_bundle

    return pinned_bundle(model)


def _observe_shape(pipeline: str, model, height: int, width: int,
                   steps: int, batch: int = 1, frames: int = 0) -> None:
    """Feed the shape catalog (``cluster/shape_catalog.py``) from the
    request path so the NEXT restart warms the programs this fleet
    actually serves. Never fatal, and cheap after first sight."""
    from ..cluster.shape_catalog import observe

    name = getattr(getattr(model, "preset", None), "name", None)
    if name:
        observe(pipeline, name, height, width, steps, batch=batch,
                frames=frames)


@register_node("TPUTxt2Img")
class TPUTxt2Img(NodeDef):
    """The distributed sampler node: runs the whole sharded generation
    (per-shard seeds + sampling + decode + gather) as one SPMD program —
    the TPU equivalent of the reference's entire dispatch/collect cycle
    for ``distributed-txt2img.json``."""

    INPUTS = {
        "model": "MODEL", "positive": "CONDITIONING", "negative": "CONDITIONING",
        "seed": "INT", "steps": "INT", "cfg": "FLOAT",
        "width": "INT", "height": "INT",
    }
    OPTIONAL = {
        "sampler_name": "STRING", "scheduler": "STRING", "batch_per_device": "INT",
    }
    HIDDEN = {"mesh": "*", "prompt_id": "STRING", "progress_tracker": "*",
              "preemption": "*"}
    RETURNS = ("IMAGE",)

    def execute(self, model, positive, negative, seed: int, steps: int,
                cfg: float, width: int, height: int,
                sampler_name: str = "euler", scheduler: str = "karras",
                batch_per_device: int = 1, mesh=None, prompt_id: str = "",
                progress_tracker=None, preemption=None, **_):
        from ..diffusion.pipeline import GenerationSpec
        from ..parallel.mesh import build_mesh

        if mesh is None:
            mesh = build_mesh({"dp": len(jax.devices())})
        spec = GenerationSpec(
            height=int(height), width=int(width), steps=int(steps),
            sampler=sampler_name, scheduler=scheduler,
            guidance_scale=float(cfg), per_device_batch=int(batch_per_device),
        )
        _observe_shape("txt2img", model, spec.height, spec.width,
                       spec.steps, batch=spec.per_device_batch)
        adm = model.pipeline.unet.config.adm_in_channels
        y = _adm_from_cond(positive, adm) if adm else None
        uy = _adm_from_cond(negative, adm) if adm else None
        pipeline, hint = _control_from_cond(model.pipeline, positive,
                                            spec.height, spec.width)
        if preemption is not None and hint is None:
            # serving lane (cluster/preemption.py): resumable K-step
            # segments, preempt checks at segment boundaries, optional
            # checkpoint restore. Bit-identical to the monolithic path,
            # and per-step preview streaming rides the segment programs
            # exactly like the monolithic token variant. (ControlNet
            # graphs keep the monolithic path: per-request hints are
            # not threaded through the segment programs.)
            with _pinned(model):
                return (self._execute_preemptible(
                    pipeline, mesh, spec, int(seed), positive, negative,
                    y, uy, preemption, progress_tracker, prompt_id),)
        from ..diffusion.progress import total_calls

        with _pinned(model), \
                _ProgressScope(progress_tracker, prompt_id,
                               total_calls(sampler_name, spec.steps)) as ps:
            images = pipeline.generate(
                mesh, spec, int(seed), positive["context"],
                negative["context"], y, uy, hint=hint,
                progress_token=ps.token,
            )
            ps.complete(images)
        return (images,)

    def _execute_preemptible(self, pipeline, mesh, spec, seed,
                             positive, negative, y, uy, token,
                             progress_tracker, prompt_id):
        from ..diffusion.checkpoint import PreemptedError
        from ..diffusion.progress import total_calls

        # identity (incl. the conditioning digest) is validated inside
        # generate_preemptible; a mismatch raises CheckpointRestoreError
        # toward the runtime's bounded resume-retry machinery
        token.resume_consumed = token.resume is not None
        with _ProgressScope(progress_tracker, prompt_id,
                            total_calls(spec.sampler, spec.steps)) as ps:
            result = pipeline.generate_preemptible(
                mesh, spec, seed, positive["context"],
                negative["context"], y, uy,
                segment_steps=token.segment_steps,
                should_preempt=token.should_preempt, resume=token.resume,
                progress_token=ps.token,
            )
            if "checkpoint" in result:
                # scope exit freezes the progress bar where it stopped
                # (preempted ≠ failed-to-0; resume re-registers a fresh
                # token under the same prompt_id)
                raise PreemptedError(result["checkpoint"],
                                     result["reason"])
            images = result["images"]
            ps.complete(images)
        return images


@register_node("TPUImg2Img")
class TPUImg2Img(NodeDef):
    """Distributed img2img: every chip produces its own seed-varied edit
    of the (replicated) source batch in one SPMD program — the img2img
    analogue of the reference's seed-offset fan-out. ``denoise`` sets the
    partial sigma-ladder fraction (k-diffusion convention, like the
    reference's KSampler denoise)."""

    INPUTS = {
        "model": "MODEL", "image": "IMAGE",
        "positive": "CONDITIONING", "negative": "CONDITIONING",
        "seed": "INT", "steps": "INT", "cfg": "FLOAT", "denoise": "FLOAT",
    }
    OPTIONAL = {"sampler_name": "STRING", "scheduler": "STRING"}
    HIDDEN = {"mesh": "*"}
    RETURNS = ("IMAGE",)

    def execute(self, model, image, positive, negative, seed: int,
                steps: int, cfg: float, denoise: float,
                sampler_name: str = "euler", scheduler: str = "karras",
                mesh=None, **_):
        mesh, images, spec, y, uy, pipeline, hint = _i2i_setup(
            model, image, positive, negative, steps, cfg, denoise,
            sampler_name, scheduler, mesh)
        out = pipeline.img2img(
            mesh, spec, int(seed), images,
            positive["context"], negative["context"], y, uy, hint=hint,
        )
        return (out,)


def _i2i_setup(model, image, positive, negative, steps, cfg, denoise,
               sampler_name, scheduler, mesh):
    """Shared img2img/inpaint node prelude: mesh fallback, image batch
    coercion, spec construction, ADM + ControlNet extraction."""
    from ..diffusion.pipeline import GenerationSpec
    from ..parallel.mesh import build_mesh

    if mesh is None:
        mesh = build_mesh({"dp": len(jax.devices())})
    images = jnp.asarray(image, jnp.float32)
    if images.ndim == 3:
        images = images[None]
    B, H, W, _ = images.shape
    spec = GenerationSpec(
        height=int(H), width=int(W), steps=int(steps),
        sampler=sampler_name, scheduler=scheduler,
        guidance_scale=float(cfg), per_device_batch=B,
        denoise=float(denoise),
    )
    adm = model.pipeline.unet.config.adm_in_channels
    y = _adm_from_cond(positive, adm) if adm else None
    uy = _adm_from_cond(negative, adm) if adm else None
    pipeline, hint = _control_from_cond(model.pipeline, positive, H, W)
    return mesh, images, spec, y, uy, pipeline, hint


@register_node("TPUInpaint")
class TPUInpaint(NodeDef):
    """Distributed inpainting: img2img with a repaint mask (1 = repaint,
    0 = keep). ComfyUI KSamplerX0Inpaint semantics on every model call:
    the sampler input is recomposited with the source latent re-noised
    at the current sigma and the denoised estimate is pinned to the
    source (``diffusion/pipeline.inpaint_denoiser``), so unmasked
    regions track the reference trajectory — ancestral/SDE samplers
    included; each chip produces its own seed-varied repaint."""

    INPUTS = {
        "model": "MODEL", "image": "IMAGE", "mask": "MASK",
        "positive": "CONDITIONING", "negative": "CONDITIONING",
        "seed": "INT", "steps": "INT", "cfg": "FLOAT", "denoise": "FLOAT",
    }
    OPTIONAL = {"sampler_name": "STRING", "scheduler": "STRING"}
    HIDDEN = {"mesh": "*"}
    RETURNS = ("IMAGE",)

    def execute(self, model, image, mask, positive, negative, seed: int,
                steps: int, cfg: float, denoise: float,
                sampler_name: str = "euler", scheduler: str = "karras",
                mesh=None, **_):
        mesh, images, spec, y, uy, pipeline, hint = _i2i_setup(
            model, image, positive, negative, steps, cfg, denoise,
            sampler_name, scheduler, mesh)
        B, H, W, _ = images.shape
        m = jnp.asarray(mask, jnp.float32)
        if m.ndim == 2:
            m = m[None]
        if m.ndim == 3:
            m = m[..., None]
        if m.shape[-1] > 1:      # an IMAGE wired as mask: take channel 0
            m = m[..., :1]
        if m.shape[0] != B:
            m = jnp.broadcast_to(m, (B,) + m.shape[1:])
        if m.shape[1:3] != (H, W):
            m = jax.image.resize(m, (B, H, W, 1), method="bilinear")
        # both composites assume a convex blend — out-of-range masks
        # would EXTRAPOLATE pixels/latents outside [0,1]
        m = jnp.clip(m, 0.0, 1.0)
        out = pipeline.img2img(
            mesh, spec, int(seed), images,
            positive["context"], negative["context"], y, uy, hint=hint,
            mask=m,
        )
        return (out,)


@register_node("TPUFlowTxt2Img")
class TPUFlowTxt2Img(NodeDef):
    """Sharded rectified-flow sampler (FLUX-class DiT bundles).

    ``mode="dp"`` fans seeds over chips; ``mode="sp"`` shards ONE image's
    tokens over chips with ring attention (single-image latency scaling —
    beyond the reference's capability census, SURVEY §2.10)."""

    INPUTS = {
        "model": "MODEL", "positive": "CONDITIONING",
        "seed": "INT", "steps": "INT", "width": "INT", "height": "INT",
    }
    OPTIONAL = {
        "negative": "CONDITIONING", "cfg": "FLOAT",
        "guidance": "FLOAT", "shift": "FLOAT", "mode": "STRING",
        "batch_per_device": "INT",
    }
    HIDDEN = {"mesh": "*", "prompt_id": "STRING", "progress_tracker": "*",
              "interrupt_event": "*"}
    RETURNS = ("IMAGE",)

    def execute(self, model, positive, seed: int, steps: int, width: int,
                height: int, negative=None, cfg: float = 1.0,
                guidance: float = 3.5, shift=None,
                mode: str = "dp", batch_per_device: int = 1, mesh=None,
                prompt_id: str = "", progress_tracker=None,
                interrupt_event=None, **_):
        from ..diffusion.pipeline_flow import FlowSpec
        from ..parallel.mesh import build_mesh
        from ..utils.exceptions import ValidationError

        if mesh is None:
            mesh = build_mesh({"dp": len(jax.devices())})
        # unwired shift falls back to a ModelSamplingSD3 override on the
        # model, then the FLUX-convention default
        if shift is None:
            shift = getattr(model, "sampling_shift", 3.0)
        spec = FlowSpec(height=int(height), width=int(width), steps=int(steps),
                        shift=float(shift), guidance=float(guidance),
                        cfg=float(cfg),
                        per_device_batch=int(batch_per_device))
        if mode == "dp":
            _observe_shape("flow_dp", model, spec.height, spec.width,
                           spec.steps, batch=spec.per_device_batch)
        ctx = positive["context"]
        pooled = positive.get("pooled")
        if pooled is None:
            pooled = jnp.zeros((1, model.pipeline.dit.config.pooled_dim))
        # true CFG (SD3-family): the 'negative' conditioning rides along;
        # asking for cfg != 1.0 without it is a loud error, never a
        # silent unguided sample
        uncond_ctx = uncond_pooled = None
        if negative is not None:
            uncond_ctx = negative["context"]
            uncond_pooled = negative.get("pooled")
        if spec.cfg != 1.0 and uncond_ctx is None:
            raise ValidationError(
                f"cfg={spec.cfg} needs the 'negative' conditioning input "
                "(true CFG); FLUX-dev distilled guidance uses cfg=1.0 "
                "with 'guidance'")
        from ..diffusion.offload import offload_enabled

        if mode == "offload" or (mode == "dp" and offload_enabled()):
            # CDT_OFFLOAD=1 (or mode="offload"): full-size single-chip
            # execution with quantized-resident/streamed blocks — how
            # FLUX-12B runs without a pod (docs/deployment.md §5).
            # Progress: fully-resident runs stream in-trace via
            # ps.token; streamed runs report host-side via ps.on_step.
            from ..diffusion.progress import total_calls

            with _pinned(model), \
                    _ProgressScope(progress_tracker, prompt_id,
                                   total_calls(spec.sampler,
                                               spec.steps)) as ps:
                images = model.pipeline.generate_offloaded(
                    spec, int(seed), ctx, pooled, on_step=ps.on_step,
                    progress_token=ps.token,
                    should_stop=_stop_cb(interrupt_event))
                ps.complete(images)
        elif mode == "sp":
            from jax.sharding import Mesh

            axes = dict(mesh.shape)
            if "sp" not in axes:   # re-lay the same devices as an sp mesh
                mesh = build_mesh({"sp": mesh.devices.size},
                                  list(mesh.devices.flat))
            # sp mode: single-image token sharding. Progress streaming is
            # intentionally dp-only for now — each sp shard holds a row
            # BLOCK, so a per-shard preview would be a partial strip; the
            # tracker would need cross-shard assembly to be meaningful.
            with _pinned(model):
                images = model.pipeline.generate_sp(
                    mesh, spec, int(seed), ctx, pooled,
                    uncond_context=uncond_ctx,
                    uncond_pooled=uncond_pooled)
        else:
            from ..diffusion.progress import total_calls

            with _pinned(model), \
                    _ProgressScope(progress_tracker, prompt_id,
                                   total_calls(spec.sampler,
                                               spec.steps)) as ps:
                images = model.pipeline.generate(
                    mesh, spec, int(seed), ctx, pooled,
                    progress_token=ps.token,
                    uncond_context=uncond_ctx,
                    uncond_pooled=uncond_pooled)
                ps.complete(images)
        return (images,)


def _video_pooled_default(model, positive):
    """Shared video-node prologue: real-WAN configs have no pooled-vector
    input (the model ignores it); any width satisfies the signature."""
    pooled = positive.get("pooled")
    if pooled is None:
        pooled = jnp.zeros(
            (1, getattr(model.pipeline.dit.config, "pooled_dim", 768)))
    return pooled


def _flatten_video_batch(videos):
    """[B,F,H,W,3] → IMAGE batch [B·F,H,W,3] (ImageBatchDivider splits it
    back per video/chunk — reference workflow parity)."""
    B, F = videos.shape[:2]
    return videos.reshape((B * F,) + videos.shape[2:])


@register_node("TPUTxt2Video")
class TPUTxt2Video(NodeDef):
    """Sharded WAN-class t2v sampler (reference parity: the WAN t2v/i2v
    workflows, SURVEY §2.9, which the reference runs job-per-worker).

    ``mode="dp"``: each chip samples a full seed-varied video (the
    reference's whole dispatch/collect cycle as one SPMD program).
    ``mode="sp"``: ONE video's frame blocks shard over chips with joint
    ring attention spanning the full spatio-temporal sequence — single-
    video latency scaling the reference cannot express (SURVEY §5.7).
    Frame count pads to 4n+1 (``nodes/distributed_upscale.py:131-142``'s
    rule, applied as padding not a constraint)."""

    INPUTS = {
        "model": "MODEL", "positive": "CONDITIONING",
        "seed": "INT", "frames": "INT", "steps": "INT",
        "width": "INT", "height": "INT",
    }
    OPTIONAL = {"cfg": "FLOAT", "shift": "FLOAT", "mode": "STRING"}
    HIDDEN = {"mesh": "*", "prompt_id": "STRING", "progress_tracker": "*",
              "interrupt_event": "*"}
    RETURNS = ("IMAGE",)

    def execute(self, model, positive, seed: int, frames: int, steps: int,
                width: int, height: int, cfg: float = 1.0,
                shift=None, mode: str = "dp", mesh=None,
                prompt_id: str = "", progress_tracker=None,
                interrupt_event=None, **_):
        from ..diffusion.pipeline_video import VideoSpec
        from ..diffusion.progress import total_calls
        from ..parallel.mesh import build_mesh

        if mesh is None:
            mesh = build_mesh({"dp": len(jax.devices())})
        if shift is None:   # ModelSamplingSD3 override, then WAN default
            shift = getattr(model, "sampling_shift", 3.0)
        spec = VideoSpec(frames=int(frames), height=int(height),
                         width=int(width), steps=int(steps),
                         shift=float(shift), guidance_scale=float(cfg))
        if mode == "dp":
            _observe_shape("video_dp", model, spec.height, spec.width,
                           spec.steps, frames=spec.frames)
        ctx = positive["context"]
        pooled = _video_pooled_default(model, positive)
        key = jax.random.key(int(seed))
        # t2v is the longest-running job type — stream per-step progress
        # and previews exactly like the image samplers do
        from ..diffusion.offload import offload_enabled

        with _pinned(model), \
                _ProgressScope(progress_tracker, prompt_id,
                               total_calls(spec.sampler,
                                           spec.steps)) as ps:
            if mode == "offload" or (mode == "dp" and offload_enabled()):
                # full-size single-chip execution with quantized expert
                # residency + dual-expert HBM swap — how WAN-14B runs
                # without a pod (diffusion/offload.OffloadedWan).
                # Progress: in-trace via ps.token when resident,
                # host-side via ps.on_step when streaming.
                videos = model.pipeline.generate_offloaded(
                    spec, int(seed), ctx, on_step=ps.on_step,
                    progress_token=ps.token,
                    should_stop=_stop_cb(interrupt_event))
            elif mode == "sp":
                if "sp" not in mesh.shape:
                    mesh = build_mesh({"sp": mesh.devices.size},
                                      list(mesh.devices.flat))
                videos = model.pipeline.generate_frames(
                    mesh, spec, int(seed), ctx, pooled,
                    progress_token=ps.token)
            else:
                videos = model.pipeline.generate(mesh, spec, int(seed),
                                                 ctx, pooled,
                                                 progress_token=ps.token)
            ps.complete(videos)
        return (_flatten_video_batch(videos),)


@register_node("TPUImg2Video")
class TPUImg2Video(NodeDef):
    """Sharded WAN-class i2v sampler: the start image conditions every
    sample via causal-VAE latent concat (WAN-2.2 style — no CLIP-vision
    branch), seeds fan out over ``dp`` (reference parity: the WAN i2v
    workflow, SURVEY §2.9, run job-per-worker there)."""

    INPUTS = {
        "model": "MODEL", "positive": "CONDITIONING", "image": "IMAGE",
        "seed": "INT", "frames": "INT", "steps": "INT",
    }
    OPTIONAL = {"cfg": "FLOAT", "shift": "FLOAT", "mode": "STRING"}
    HIDDEN = {"mesh": "*", "prompt_id": "STRING", "progress_tracker": "*",
              "interrupt_event": "*"}
    RETURNS = ("IMAGE",)

    def execute(self, model, positive, image, seed: int, frames: int,
                steps: int, cfg: float = 1.0, shift=None,
                mode: str = "dp", mesh=None, prompt_id: str = "",
                progress_tracker=None, interrupt_event=None, **_):
        from ..diffusion.pipeline_video import VideoSpec
        from ..diffusion.progress import total_calls
        from ..parallel.mesh import build_mesh
        from ..utils.exceptions import ValidationError

        image = jnp.asarray(image)
        if image.ndim == 3:
            image = image[None]
        din = model.pipeline.dit.config.in_channels
        dout = getattr(model.pipeline.dit.config, "out_channels", din)
        if din == dout:
            raise ValidationError(
                f"model {model.preset.name!r} is a t2v architecture "
                "(in_channels == out_channels) — i2v needs a preset with "
                "latent-concat conditioning channels, e.g. 'wan-i2v'")
        if mesh is None:
            mesh = build_mesh({"dp": len(jax.devices())})
        H, W = int(image.shape[1]), int(image.shape[2])
        if shift is None:   # ModelSamplingSD3 override, then WAN default
            shift = getattr(model, "sampling_shift", 3.0)
        spec = VideoSpec(frames=int(frames), height=H, width=W,
                         steps=int(steps), shift=float(shift),
                         guidance_scale=float(cfg))
        ctx = positive["context"]
        pooled = _video_pooled_default(model, positive)
        from ..diffusion.offload import offload_enabled

        with _pinned(model), \
                _ProgressScope(progress_tracker, prompt_id,
                               total_calls(spec.sampler,
                                           spec.steps)) as ps:
            if mode == "offload" or (mode == "dp" and offload_enabled()):
                videos = model.pipeline.generate_offloaded_i2v(
                    spec, int(seed), image[:1], ctx, on_step=ps.on_step,
                    progress_token=ps.token,
                    should_stop=_stop_cb(interrupt_event))
            elif mode == "sp":
                if "sp" not in mesh.shape:
                    mesh = build_mesh({"sp": mesh.devices.size},
                                      list(mesh.devices.flat))
                videos = model.pipeline.generate_i2v_frames(
                    mesh, spec, int(seed), image[:1], ctx, pooled,
                    progress_token=ps.token)
            else:
                videos = model.pipeline.generate_i2v(
                    mesh, spec, int(seed), image[:1], ctx, pooled,
                    progress_token=ps.token)
            ps.complete(videos)
        return (_flatten_video_batch(videos),)


@register_node("VAEEncode")
class VAEEncode(NodeDef):
    INPUTS = {"pixels": "IMAGE", "vae": "VAE"}
    RETURNS = ("LATENT",)

    def execute(self, pixels, vae, **_):
        return ({"samples": vae.encode(jnp.asarray(pixels) * 2.0 - 1.0)},)


@register_node("VAEDecode")
class VAEDecode(NodeDef):
    INPUTS = {"samples": "LATENT", "vae": "VAE"}
    RETURNS = ("IMAGE",)

    def execute(self, samples, vae, **_):
        out = vae.decode(samples["samples"])
        return (jnp.clip(out / 2.0 + 0.5, 0.0, 1.0),)


@register_node("SaveImage")
class SaveImage(NodeDef):
    INPUTS = {"images": "IMAGE"}
    OPTIONAL = {"filename_prefix": "STRING"}
    HIDDEN = {"output_dir": "STRING"}
    RETURNS = ()
    OUTPUT_NODE = True

    def execute(self, images, filename_prefix: str = "output",
                output_dir: str = "", **_):
        from ..utils.image import encode_png, to_uint8

        out_dir = Path(output_dir or "output")
        out_dir.mkdir(parents=True, exist_ok=True)
        arr = to_uint8(images)
        paths = []
        for i in range(arr.shape[0]):
            p = out_dir / f"{filename_prefix}_{i:05d}.png"
            p.write_bytes(encode_png(arr[i]))
            paths.append(str(p))
        log(f"saved {len(paths)} images to {out_dir}")
        return ()


@register_node("PreviewImage")
class PreviewImage(NodeDef):
    INPUTS = {"images": "IMAGE"}
    RETURNS = ()
    OUTPUT_NODE = True

    def execute(self, images, **_):
        debug_log(f"preview: batch of {np.asarray(images).shape[0]}")
        return ()


@register_node("LoadImage")
class LoadImage(NodeDef):
    INPUTS = {"image": "STRING"}
    HIDDEN = {"input_dir": "STRING"}
    RETURNS = ("IMAGE",)

    def execute(self, image: str, input_dir: str = "", **_):
        from ..utils.image import decode_png

        path = Path(input_dir or "input") / image
        if not path.exists():
            raise ValidationError(f"image file not found: {path}", field="image")
        return (jnp.asarray(decode_png(path.read_bytes()))[None],)


@register_node("LoadAudio")
class LoadAudio(NodeDef):
    """WAV file → AUDIO dict ``{"waveform": [1,C,S], "sample_rate"}``.

    The reference free-rides on ComfyUI's LoadAudio for the file edge and
    only ships the transport envelope (``utils/audio_payload.py``); here
    the stdlib WAV codec closes the loop so audio workflows are drivable
    end-to-end (media sync already handles ``.wav`` inputs)."""

    INPUTS = {"audio": "STRING"}
    HIDDEN = {"input_dir": "STRING"}
    RETURNS = ("AUDIO",)

    def execute(self, audio: str, input_dir: str = "", **_):
        from ..utils.audio_payload import wav_decode

        path = Path(input_dir or "input") / audio
        if not path.exists():
            raise ValidationError(f"audio file not found: {path}",
                                  field="audio")
        return (wav_decode(path.read_bytes()),)


@register_node("SaveAudio")
class SaveAudio(NodeDef):
    """AUDIO → one 16-bit PCM WAV per batch element (ComfyUI SaveAudio
    parity via the stdlib codec)."""

    INPUTS = {"audio": "AUDIO"}
    OPTIONAL = {"filename_prefix": "STRING"}
    HIDDEN = {"output_dir": "STRING"}
    RETURNS = ()
    OUTPUT_NODE = True

    def execute(self, audio, filename_prefix: str = "audio",
                output_dir: str = "", **_):
        from ..utils.audio_payload import wav_bytes

        wf = np.asarray(audio["waveform"])
        if wf.ndim == 2:               # tolerate [C,S]
            wf = wf[None]
        sr = int(audio.get("sample_rate", 44100))
        out_dir = Path(output_dir or "output")
        out_dir.mkdir(parents=True, exist_ok=True)
        paths = []
        for i in range(wf.shape[0]):
            p = out_dir / f"{filename_prefix}_{i:05d}.wav"
            p.write_bytes(wav_bytes(wf[i], sr))
            paths.append(str(p))
        log(f"saved {len(paths)} audio clips to {out_dir}")
        return ()


@register_node("LoadVideo")
class LoadVideo(NodeDef):
    """Video container → IMAGE frame batch + AUDIO + fps + frame count.

    Reference-ecosystem parity: the ``VHS_LoadVideo`` node type its video
    workflows assume (``/root/reference/workflows/
    distributed-upscale-video.json``; the reference itself free-rides on
    VideoHelperSuite for the file edge). Frame-selection knobs (cap /
    skip / stride) mirror that surface. Containers: mp4/webm via OpenCV,
    plus this framework's MJPG+PCM AVI with a truly muxed audio track
    (``utils/video_io.py`` — no ffmpeg exists in this environment)."""

    INPUTS = {"video": "STRING"}
    OPTIONAL = {"frame_load_cap": "INT", "skip_first_frames": "INT",
                "select_every_nth": "INT"}
    HIDDEN = {"input_dir": "STRING"}
    RETURNS = ("IMAGE", "AUDIO", "FLOAT", "INT")

    def execute(self, video: str, frame_load_cap: int = 0,
                skip_first_frames: int = 0, select_every_nth: int = 1,
                input_dir: str = "", **_):
        from ..utils.video_io import load_video

        path = Path(input_dir or "input") / video
        if not path.exists():
            raise ValidationError(f"video file not found: {path}",
                                  field="video")
        clip = load_video(path, frame_load_cap=int(frame_load_cap),
                          skip_first_frames=int(skip_first_frames),
                          select_every_nth=int(select_every_nth))
        # audio-less containers emit a valid zero-length AUDIO dict so
        # any downstream AUDIO consumer (SaveAudio, dividers) degrades
        # to a no-op instead of crashing on None
        audio = clip["audio"] or {
            "waveform": np.zeros((1, 1, 0), np.float32),
            "sample_rate": 44100,
        }
        return (jnp.asarray(clip["frames"]), audio,
                float(clip["fps"]), int(clip["frame_count"]))


@register_node("SaveVideo")
class SaveVideo(NodeDef):
    """IMAGE frame batch (+ optional AUDIO) → playable video container.

    Reference-ecosystem parity: the ``VHS_VideoCombine`` surface (frame
    rate, format, audio mux, filename prefix). Formats: ``avi`` writes
    MJPG+PCM with the audio track genuinely muxed (pure-Python RIFF
    muxer); ``mp4``/``webm`` write via OpenCV with audio as a sidecar
    ``.wav`` that ``LoadVideo`` re-attaches — a documented divergence
    from the reference's ffmpeg mux (no ffmpeg in this image). Returns
    the container path for downstream chaining."""

    INPUTS = {"images": "IMAGE", "frame_rate": "FLOAT"}
    OPTIONAL = {"audio": "AUDIO", "format": "STRING",
                "filename_prefix": "STRING", "quality": "INT"}
    HIDDEN = {"output_dir": "STRING"}
    RETURNS = ("STRING",)
    OUTPUT_NODE = True

    _FORMATS = ("mp4", "webm", "avi")

    def execute(self, images, frame_rate: float = 8.0, audio=None,
                format: str = "mp4", filename_prefix: str = "video",
                quality: int = 95, output_dir: str = "", **_):
        from ..utils.video_io import save_video

        # tolerate VHS-style format strings ("video/h264-mp4")
        fmt = str(format).lower()
        fmt = next((f for f in self._FORMATS if f in fmt), fmt)
        if fmt not in self._FORMATS:
            raise ValidationError(
                f"unsupported video format {format!r} "
                f"(supported: {list(self._FORMATS)})", field="format")
        out_dir = Path(output_dir or "output")
        out_dir.mkdir(parents=True, exist_ok=True)
        # uniqueness must cover the audio-sidecar namespace too: all
        # formats share "<stem>.wav", so a free .webm slot whose .wav is
        # taken by an earlier .mp4 save would silently clobber that
        # video's audio
        i = 0
        while True:
            stem = out_dir / f"{filename_prefix}_{i:05d}.{fmt}"
            if not stem.exists() and not stem.with_suffix(".wav").exists():
                break
            i += 1
        written = save_video(stem, images, fps=float(frame_rate),
                             audio=audio, quality=int(quality))
        log(f"saved video {written[0]}"
            + (f" (+ sidecar {written[1]})" if len(written) > 1 else ""))
        return (written[0],)


# Drop-in aliases so reference workflow JSON naming the VideoHelperSuite
# node types executes unchanged (distributed-upscale-video.json uses
# VHS_LoadVideo / VHS_VideoCombine; extra VHS-only inputs are tolerated
# by the executor's forward-compat rule).
NODE_REGISTRY["VHS_LoadVideo"] = LoadVideo
NODE_REGISTRY["VHS_VideoCombine"] = SaveVideo


@register_node("PrimitiveInt")
class PrimitiveInt(NodeDef):
    INPUTS = {"value": "INT"}
    RETURNS = ("INT",)

    def execute(self, value, **_):
        return (int(value),)


@register_node("PrimitiveFloat")
class PrimitiveFloat(NodeDef):
    INPUTS = {"value": "FLOAT"}
    RETURNS = ("FLOAT",)

    def execute(self, value, **_):
        return (float(value),)


@register_node("PrimitiveString")
class PrimitiveString(NodeDef):
    INPUTS = {"value": "STRING"}
    RETURNS = ("STRING",)

    def execute(self, value, **_):
        return (str(value),)
