"""ctypes bindings for the native C++ data-plane library (``native/``).

Everything here has a numpy fallback: the framework is fully functional
without a C++ toolchain, and `is_native()` reports which path is active.
The native build is attempted once per process (make in ``native/``) when
the shared library is missing and ``g++`` is available.

Surface:
- ``pack_frame``/``unpack_frame`` — crc32-checked, optionally
  zlib-compressed tensor frames (the cross-host wire format; replaces the
  reference's base64-PNG JSON envelopes, ``nodes/collector.py:152-174``)
- ``blend_tile``/``accumulate_tile`` — master-side feathered compositing
  (reference ``upscale/tile_ops.py:289-349`` runs this per tile in
  PIL/torch)
- ``hash64`` — media-sync content hash (cheaper than md5 on video files)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

from .utils.logging import debug_log

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_LIB_NAME = "libcdt_native.so"

# frame dtype codes (wire format; the C++ codec treats the code as opaque)
_DTYPES: dict[int, np.dtype] = {
    0: np.dtype(np.uint8),
    1: np.dtype(np.float32),
    2: np.dtype(np.float16),
    3: np.dtype(np.int32),
    4: np.dtype(np.uint16),
    5: np.dtype(np.int64),
    6: np.dtype(np.float64),
    7: np.dtype(np.bool_),
}
try:  # jax always ships ml_dtypes; frames then round-trip bf16 losslessly
    import ml_dtypes

    _DTYPES[8] = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    pass
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}

_MAGIC = b"CDTF"
_VERSION = 1

# decompression ceiling: frames claiming a larger raw size are rejected
# before any allocation (the wire size itself is already capped per-route
# by MAX_PAYLOAD_SIZE — this bounds the zlib expansion of what got past)
from .utils.constants import MAX_FRAME_RAW_BYTES as _MAX_FRAME_RAW_KNOB
from .utils.constants import NO_NATIVE as _NO_NATIVE_KNOB

MAX_FRAME_RAW_BYTES = _MAX_FRAME_RAW_KNOB.get()

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_load_attempted = False


def _try_build() -> bool:
    import shutil

    if not shutil.which("make") or not shutil.which(
            os.environ.get("CXX", "g++")):
        return False
    try:
        subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                       capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        debug_log(f"native build failed: {e}")
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _lib_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if _NO_NATIVE_KNOB.get():
            return None
        so = _NATIVE_DIR / _LIB_NAME
        if not so.is_file() and _NATIVE_DIR.is_dir():
            _try_build()
        if not so.is_file():
            return None
        try:
            lib = ctypes.CDLL(str(so))
        except OSError as e:
            debug_log(f"native load failed: {e}")
            return None
        lib.cdt_hash64.restype = ctypes.c_uint64
        lib.cdt_hash64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.cdt_frame_bound.restype = ctypes.c_int64
        lib.cdt_frame_bound.argtypes = [ctypes.c_int64, ctypes.c_int32]
        lib.cdt_pack_frame.restype = ctypes.c_int64
        lib.cdt_pack_frame.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64]
        lib.cdt_unpack_frame.restype = ctypes.c_int64
        lib.cdt_unpack_frame.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
        lib.cdt_frame_info.restype = ctypes.c_int64
        lib.cdt_frame_info.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        for name in ("cdt_blend_tile", "cdt_accumulate_tile"):
            fn = getattr(lib, name)
            fn.restype = None
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                           ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                           ctypes.c_int64]
            if name == "cdt_accumulate_tile":
                fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p] + fn.argtypes[1:]
        _lib = lib
        debug_log(f"native data-plane library loaded: {so}")
        return _lib


def is_native() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# content hash
# ---------------------------------------------------------------------------

def hash64(data: bytes) -> int:
    lib = _load()
    if lib is not None:
        return int(lib.cdt_hash64(data, len(data)))
    # numpy-free fallback (FNV-1a 64)
    h = 14695981039346656037
    for b in data:
        h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------

def _np_view(arr: np.ndarray) -> tuple[np.ndarray, int]:
    """Contiguous view + wire dtype code. Unsupported dtypes raise rather
    than silently cast — the codec must round-trip losslessly."""
    a = np.ascontiguousarray(arr)
    dt = np.dtype(a.dtype)
    if dt not in _DTYPE_CODES:
        raise ValueError(
            f"unsupported frame dtype {dt}; supported: "
            f"{sorted(str(d) for d in _DTYPE_CODES)}")
    return a, _DTYPE_CODES[dt]


def pack_frame(arr: np.ndarray, level: int = 1) -> bytes:
    """Array → framed bytes. ``level`` 0 = raw, 1-9 = zlib (kept only when
    it actually shrinks the payload)."""
    a, code = _np_view(arr)
    raw = a.tobytes()
    lib = _load()
    if lib is not None:
        dims = (ctypes.c_int64 * max(1, a.ndim))(*(a.shape or (1,)))
        cap = lib.cdt_frame_bound(len(raw), a.ndim)
        out = ctypes.create_string_buffer(cap)
        n = lib.cdt_pack_frame(raw, len(raw), code, dims, a.ndim,
                               level, out, cap)
        if n > 0:
            return out.raw[:n]
        debug_log(f"native pack failed ({n}); falling back")
    # pure-python fallback, identical wire format
    payload = raw
    flags = 0
    if level > 0:
        z = zlib.compress(raw, level)
        if len(z) < len(raw):
            payload, flags = z, 1
    head = _MAGIC + bytes([_VERSION, code, a.ndim, flags])
    head += b"".join(int(d).to_bytes(8, "little") for d in a.shape)
    head += zlib.crc32(raw).to_bytes(4, "little")
    head += len(payload).to_bytes(8, "little")
    head += len(raw).to_bytes(8, "little")
    return head + payload


def unpack_frame(data: bytes) -> np.ndarray:
    """Framed bytes → array (crc-verified)."""
    data = bytes(data)          # bytearray/memoryview → bytes for ctypes
    if len(data) < 8 or data[:4] != _MAGIC or data[4] != _VERSION:
        raise ValueError("not a CDTF frame")
    code, ndim, flags = data[5], data[6], data[7]
    if ndim > 8 or code not in _DTYPES:
        raise ValueError(f"bad frame header (dtype={code} ndim={ndim})")
    off = 8
    shape = tuple(int.from_bytes(data[off + 8 * i: off + 8 * i + 8], "little")
                  for i in range(ndim))
    off += 8 * ndim
    crc = int.from_bytes(data[off:off + 4], "little"); off += 4
    stored = int.from_bytes(data[off:off + 8], "little"); off += 8
    raw_len = int.from_bytes(data[off:off + 8], "little"); off += 8

    # header fields are attacker-controlled (frames arrive on unauthenticated
    # routes): bound every size before any allocation
    if any(d < 0 for d in shape):
        raise ValueError("bad frame header (negative dim)")
    expected = _DTYPES[code].itemsize
    for d in shape:
        expected *= d
    if raw_len != expected:
        raise ValueError(
            f"frame raw size {raw_len} != shape/dtype size {expected}")
    if raw_len > MAX_FRAME_RAW_BYTES:
        raise ValueError(
            f"frame raw size {raw_len} exceeds cap {MAX_FRAME_RAW_BYTES}")
    if stored > len(data) - off:
        raise ValueError("frame payload truncated")

    lib = _load()
    if lib is not None:
        out = ctypes.create_string_buffer(raw_len if raw_len > 0 else 1)
        n = lib.cdt_unpack_frame(data, len(data), out, raw_len)
        if n < 0:
            raise ValueError(f"frame unpack failed (code {n})")
        raw = out.raw[:n]
    else:
        payload = data[off:off + stored]
        if flags & 1:
            # bounded inflate: never produce more than raw_len+1 bytes no
            # matter what the stream claims (zlib-bomb guard for the pure-
            # python path; the native path bounds by the output buffer)
            try:
                d = zlib.decompressobj()
                raw = d.decompress(payload, raw_len + 1)
            except zlib.error as e:
                raise ValueError(f"frame decompress failed: {e}")
        else:
            raw = payload
        if len(raw) != raw_len or zlib.crc32(raw) != crc:
            raise ValueError("frame crc mismatch")
    return np.frombuffer(raw, dtype=_DTYPES[code]).reshape(shape)


# ---------------------------------------------------------------------------
# compositing
# ---------------------------------------------------------------------------

def blend_tile(canvas: np.ndarray, tile: np.ndarray, mask: np.ndarray,
               y: int, x: int) -> None:
    """In-place: ``canvas[y:y+th, x:x+tw] = canvas*(1-m) + tile*m`` with
    bounds clipping. canvas [H,W,C] f32, tile [th,tw,C] f32, mask [th,tw]."""
    if canvas.dtype != np.float32 or not canvas.flags["C_CONTIGUOUS"]:
        # in-place semantics require the caller's own buffer
        raise ValueError("canvas must be contiguous float32")
    tile = np.ascontiguousarray(tile, np.float32)
    mask = np.ascontiguousarray(mask, np.float32)
    H, W, C = canvas.shape
    th, tw = mask.shape
    lib = _load()
    if lib is not None:
        lib.cdt_blend_tile(
            canvas.ctypes.data, H, W, C, tile.ctypes.data, mask.ctypes.data,
            th, tw, y, x)
        return
    y0, x0 = max(y, 0), max(x, 0)
    y1, x1 = min(y + th, H), min(x + tw, W)
    if y0 >= y1 or x0 >= x1:
        return
    m = mask[y0 - y:y1 - y, x0 - x:x1 - x, None]
    canvas[y0:y1, x0:x1] = (canvas[y0:y1, x0:x1] * (1.0 - m)
                            + tile[y0 - y:y1 - y, x0 - x:x1 - x] * m)


def accumulate_tile(acc: np.ndarray, wsum: np.ndarray, tile: np.ndarray,
                    mask: np.ndarray, y: int, x: int) -> None:
    """In-place order-independent compositing: ``acc += tile*mask``;
    ``wsum += mask`` (divide at the end)."""
    for buf, name in ((acc, "acc"), (wsum, "wsum")):
        if buf.dtype != np.float32 or not buf.flags["C_CONTIGUOUS"]:
            raise ValueError(f"{name} must be contiguous float32")
    tile = np.ascontiguousarray(tile, np.float32)
    mask = np.ascontiguousarray(mask, np.float32)
    H, W, C = acc.shape
    th, tw = mask.shape
    lib = _load()
    if lib is not None:
        lib.cdt_accumulate_tile(
            acc.ctypes.data, wsum.ctypes.data, H, W, C,
            tile.ctypes.data, mask.ctypes.data, th, tw, y, x)
        return
    y0, x0 = max(y, 0), max(x, 0)
    y1, x1 = min(y + th, H), min(x + tw, W)
    if y0 >= y1 or x0 >= x1:
        return
    m = mask[y0 - y:y1 - y, x0 - x:x1 - x]
    acc[y0:y1, x0:x1] += tile[y0 - y:y1 - y, x0 - x:x1 - x] * m[..., None]
    wsum[y0:y1, x0:x1] += m
