"""Array ops: blending, resize — XLA-native replacements for the
reference's PIL/torch image manipulation (``upscale/tile_ops.py``,
``utils/usdu_utils.py``)."""

from .blend import feather_mask, composite_tiles  # noqa: F401
from .resize import upscale_image  # noqa: F401
