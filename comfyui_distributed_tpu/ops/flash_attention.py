"""Pallas flash attention for TPU.

The hot op of every model family here (SDXL UNet cross/self attention,
FLUX/WAN DiT joint attention) is bidirectional dense attention over
10³–10⁵ tokens. XLA's fused ``dot_product_attention`` is good; a pallas
kernel is better on two axes the compiler can't reach:

- **VMEM residency**: K/V stream through VMEM in ``block_k`` tiles while
  the O(N²) logits matrix never exists in HBM — at video sequence lengths
  (WAN: ~32k tokens) the materialized-logits path is HBM-bound and the
  streaming-softmax path is MXU-bound.
- **fp32 accumulation over bf16 MXU inputs**: QKᵀ and PV run on the MXU
  in bf16 with fp32 accumulators (``preferred_element_type``), matching
  flash-attention numerics exactly.

The reference has no analogue (its compute hot loop is ComfyUI's
``common_ksampler``, SURVEY §3.3); this kernel sits *under* the parity
surface as the execution engine's attention primitive.

Kernel structure (standard TPU flash attention):
grid = (batch·heads, Nq/block_q, Nk/block_k), K-blocks innermost so the
running max ``m``, denominator ``l`` and output accumulator live in VMEM
scratch across grid steps; the output block is written once on the final
K step. Sequence lengths are padded to block multiples at trace time and
masked with a static-length comparison — shapes stay static for XLA.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# lane width: scratch vectors m/l are stored lane-replicated (BQ, 128)
_LANES = 128
NEG_INF = -1e30      # large-but-finite: -inf breaks max on fully-masked rows


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, kv_len: int, block_k: int, num_k_blocks: int,
                  scale: float, precision):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                   # [BQ, D]
    k = k_ref[0]                                   # [BK, D]
    v = v_ref[0]                                   # [BK, D]

    # [BQ, BK] logits in fp32 (bf16 inputs use the MXU natively)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ) * scale

    # static-shape masking of the K padding tail (kv_len is a Python int)
    if kv_len % block_k != 0:
        base = j * block_k
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(base + col < kv_len, s, NEG_INF)

    m_prev = m_ref[:, :1]                          # [BQ, 1] (lane-replicated)
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)     # [BQ, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)                         # [BQ, BK]
    corr = jnp.exp(m_prev - m_new)                 # [BQ, 1]
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)

    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    )                                              # [BQ, D]
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)            # fully-masked rows → 0
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_kernel_packed(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                         *, kv_len: int, block_k: int, num_k_blocks: int,
                         scale: float, precision, num_heads: int,
                         head_dim: int):
    """Packed-heads variant: refs are [1, block, H·D] slices of the
    model's NATURAL layout — the fused QKV projection emits [B, N, H·D]
    and splitting heads along the minor axis is free, so no transpose
    ever happens at the custom-call boundary (the boundary relayout, not
    the kernel body, is what made the classic [B·H, N, D] call lose to
    XLA fused attention at SDXL sequence lengths — `docs/roofline.md`
    finding 1). Heads unroll statically inside the kernel; head h's
    running max / denominator each live in lane h of one [BQ, 128]
    scratch (hence ``num_heads ≤ 128``)."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                   # [BQ, H·D]
    k = k_ref[0]                                   # [BK, H·D]
    v = v_ref[0]                                   # [BK, H·D]

    col = jax.lax.broadcasted_iota(
        jnp.int32, (q.shape[0], block_k), 1) if kv_len % block_k else None

    for h in range(num_heads):
        sl = slice(h * head_dim, (h + 1) * head_dim)
        s = jax.lax.dot_general(
            q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale                                   # [BQ, BK]

        if col is not None:                        # mask the K padding tail
            s = jnp.where(j * block_k + col < kv_len, s, NEG_INF)

        m_prev = m_ref[:, h:h + 1]                 # [BQ, 1] (lane h)
        l_prev = l_ref[:, h:h + 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)

        pv = jax.lax.dot_general(
            p.astype(v.dtype), v[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                          # [BQ, D]
        acc_ref[:, sl] = acc_ref[:, sl] * corr + pv
        m_ref[:, h:h + 1] = m_new
        l_ref[:, h:h + 1] = l_new

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        for h in range(num_heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            l = l_ref[:, h:h + 1]
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, :, sl] = (acc_ref[:, sl] / l).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    n = x.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _in_manual_trace(x) -> bool:
    """True when tracing inside ``shard_map`` (the aval carries varying
    manual axes)."""
    try:
        return bool(getattr(jax.typeof(x), "vma", None))
    except Exception:  # noqa: BLE001 — typeof unavailable on some inputs
        return False


def _flash_emulated(q, k, v, block_q: int, block_k: int):
    """The kernel's streaming-softmax algorithm in plain JAX ops.

    Used only where the pallas *interpreter* cannot run: inside a
    ``shard_map`` trace in interpret mode, JAX's HLO interpreter issues
    ``dynamic_slice`` calls whose index operands lack the varying manual
    axes of the data operand and trips ``check_vma`` (jax-ml/jax — the
    error itself suggests ``check_vma=False`` as the workaround, which we
    cannot impose on callers). This emulation runs the same block
    schedule, padding, NEG_INF tail masking and fp32 accumulation as
    ``_flash_kernel``, so CPU shard_map tests exercise the same math;
    compiled TPU runs still take the pallas path.
    """
    BH, Nq, D = q.shape
    _, Nk, _ = k.shape
    scale = 1.0 / (D ** 0.5)
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    nkb = kp.shape[1] // block_k

    m = jnp.full((BH, qp.shape[1], 1), NEG_INF, jnp.float32)
    l = jnp.zeros((BH, qp.shape[1], 1), jnp.float32)
    acc = jnp.zeros((BH, qp.shape[1], D), jnp.float32)
    for j in range(nkb):  # static unroll — nkb is a Python int
        kb = jax.lax.dynamic_slice_in_dim(kp, j * block_k, block_k, 1)
        vb = jax.lax.dynamic_slice_in_dim(vp, j * block_k, block_k, 1)
        s = jax.lax.dot_general(
            qp, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        if Nk % block_k != 0:
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            s = jnp.where(j * block_k + col < Nk, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc = acc * corr + pv
        m = m_new
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l).astype(q.dtype)[:, :Nq]


def _pad_and_prepare(q, k, v, block_q: int, block_k: int):
    """Shared prologue of both pallas drivers: pad q/k/v sequence dims to
    block multiples, pick the matmul precision, and build the vma-aware
    output aval. f32 inputs ask for real f32 matmuls (3-pass bf16 on the
    MXU); bf16 inputs take the fast single-pass path — the production
    dtype. Inside shard_map the output must declare which mesh axes it
    varies over (check_vma) — it varies exactly like q does."""
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    precision = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    try:
        vma = getattr(jax.typeof(qp), "vma", None)
    except Exception:  # noqa: BLE001 — typeof unavailable outside tracing
        vma = None
    out_sds = (jax.ShapeDtypeStruct(qp.shape, q.dtype, vma=vma)
               if vma else jax.ShapeDtypeStruct(qp.shape, q.dtype))
    return qp, kp, vp, precision, out_sds


@functools.partial(jax.jit, static_argnames=("block_q", "block_k", "interpret"))
def _flash_mha(q, k, v, block_q: int, block_k: int, interpret: bool):
    BH, Nq, D = q.shape
    _, Nk, _ = k.shape
    scale = 1.0 / (D ** 0.5)

    qp, kp, vp, precision, out_sds = _pad_and_prepare(q, k, v, block_q,
                                                      block_k)
    nqb = qp.shape[1] // block_q
    nkb = kp.shape[1] // block_k

    kernel = functools.partial(
        _flash_kernel, kv_len=Nk, block_k=block_k, num_k_blocks=nkb,
        scale=scale, precision=precision)

    out = pl.pallas_call(
        kernel,
        grid=(BH, nqb, nkb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_sds,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running sum
            pltpu.VMEM((block_q, D), jnp.float32),        # output acc
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :Nq]


@functools.partial(jax.jit, static_argnames=("num_heads", "block_q",
                                             "block_k", "interpret"))
def _flash_mha_packed(q, k, v, num_heads: int, block_q: int, block_k: int,
                      interpret: bool):
    """Packed-heads pallas call: operands stay [B, N, H·D] — the QKV
    projection's own output layout — and the kernel splits heads along
    the minor axis (free). Legality (``_packed_legal``): H·D % 128 == 0,
    H ≤ 128, H·D ≤ ``_PACKED_MAX_HD``, and D % 64 == 0 (lane-aligned
    head slices) — true for SDXL (640/1280) and WAN (1536); FLUX (3072)
    exceeds the VMEM bound and stays on the classic [B·H, N, D] call."""
    B, Nq, HD = q.shape
    _, Nk, _ = k.shape
    D = HD // num_heads
    scale = 1.0 / (D ** 0.5)

    qp, kp, vp, precision, out_sds = _pad_and_prepare(q, k, v, block_q,
                                                      block_k)
    nqb = qp.shape[1] // block_q
    nkb = kp.shape[1] // block_k

    kernel = functools.partial(
        _flash_kernel_packed, kv_len=Nk, block_k=block_k, num_k_blocks=nkb,
        scale=scale, precision=precision, num_heads=num_heads, head_dim=D)

    q_spec = pl.BlockSpec((1, block_q, HD), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, HD), lambda b, i, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(B, nqb, nkb),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        out_shape=out_sds,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # per-head max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # per-head sum
            pltpu.VMEM((block_q, HD), jnp.float32),       # output acc
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :Nq]


# past this packed width the kernel needs shrunken q/k blocks to keep
# its VMEM working set (double-buffered [block, H·D] K/V tiles + the
# f32 accumulator) inside the ~16 MB budget, and the shrink costs more
# than the boundary relayout saves — measured r04 at FLUX's H·D = 3072:
# 128/256 blocks ran the offload ladder at 1.34 s/step vs the classic
# [B·H, N, D] call's 1.21 s (`benchmarks/r04_tpu_flux.json`). Wide
# layouts therefore stay on the classic call.
_PACKED_MAX_HD = 2048


def _packed_blocks(hd: int, block_q: int, block_k: int) -> tuple[int, int]:
    """Block sizes for the packed call — a hook for shapes whose VMEM
    working set needs smaller tiles (none under the current
    ``_PACKED_MAX_HD``; see the measured note above)."""
    return block_q, block_k


def _flash_min_seq_packed() -> int:
    """Engagement floor for the packed-heads layout: measured r04 it
    beats XLA already at SDXL self-attention lengths (docs/roofline.md
    finding 1a) but not below ~1024 tokens."""
    from ..utils.constants import env_int

    return env_int("CDT_FLASH_MIN_SEQ_PACKED", 1024)


def _flash_min_kv_packed() -> int:
    """Short-K floor for the packed kernel: at SDXL cross-attention
    (K = 77 text tokens padded to one 512 block) the kernel wastes most
    of its K tile and measures behind XLA (1.20 vs 1.04 ms/64-op chain,
    r04) — those sites stay on XLA's fused lowering / the classic bh
    call."""
    from ..utils.constants import env_int

    return env_int("CDT_FLASH_MIN_KV_PACKED", 256)


def _packed_legal(H: int, D: int) -> bool:
    """Pure geometric legality of the packed-heads layout. D % 64 keeps
    the in-kernel head slices register-lane aligned and confines the
    layout to the tested head-dim classes (64/128); e.g. H=128, D=16
    would pass the packed-width checks but unroll a 128-way head loop
    over 16-wide lane slices — a shape class never measured and likely
    Mosaic-hostile."""
    return ((H * D) % _LANES == 0 and H <= _LANES
            and H * D <= _PACKED_MAX_HD and D % 64 == 0)


def _layout_packed(H: int, D: int,
                   Nq: Optional[int] = None,
                   Nk: Optional[int] = None) -> bool:
    """Kernel I/O layout: ``packed`` (default where legal AND the
    measured engagement floors hold) keeps q/k/v in the model's natural
    [B, N, H·D] layout and splits heads inside the kernel; ``bh`` is the
    classic pre-transposed [B·H, N, D] call.

    ``CDT_FLASH_LAYOUT=bh`` restores the classic call everywhere;
    ``CDT_FLASH_LAYOUT=packed`` is the default (packed where legal and
    the floors hold — both env states behave identically, preserving
    the historical meaning of an exported ``packed``). An explicit
    per-call layout override is ``flash_attention(..., layout=...)``.
    Without ``Nq``/``Nk`` (the shape-gate site, which applies its own
    thresholds) only legality and the env override are checked."""
    import os

    env = os.environ.get("CDT_FLASH_LAYOUT", "").lower()
    if env == "bh":
        return False
    if not _packed_legal(H, D):
        return False
    # The packed call must also clear its measured floors, so a
    # user-raised CDT_FLASH_MIN_SEQ_PACKED/KV floor is never bypassed by
    # the shape gate's classic fall-through (r04 review finding).
    return ((Nq is None or Nq >= _flash_min_seq_packed())
            and (Nk is None or Nk >= _flash_min_kv_packed()))


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    layout: Optional[str] = None,
) -> jax.Array:
    """Exact bidirectional attention, [B,N,H,D] layout (matching
    ``ops.attention.full_attention``), computed by the pallas kernel.

    ``interpret=None`` auto-selects: compiled on TPU, interpreter
    elsewhere (CPU tests run the same kernel code path).

    ``block_q``/``block_k=None`` resolve to ``CDT_FLASH_BLOCK_Q``/
    ``CDT_FLASH_BLOCK_K`` (defaults 256/512, measured r04; the r05 WAN
    probes showed 512 is also the largest K block the 16 MB scoped VMEM
    admits at H·D=1536 — docs/roofline.md).

    ``layout`` forces the kernel I/O layout for this call: ``"packed"``
    (where geometrically legal — illegal geometries still fall back) or
    ``"bh"``; ``None`` auto-selects per ``_layout_packed`` (legality +
    measured floors + ``CDT_FLASH_LAYOUT``). Used by layout-equivalence
    tests and power users; the env var remains the global knob.
    """
    if interpret is None:
        interpret = not _on_tpu()
    if block_q is None or block_k is None:
        from ..utils.constants import env_int

        # defaults measured r04 at SDXL shapes; env knobs for per-shape
        # tuning experiments (r05: larger K blocks probed at WAN's 14k
        # tokens — see docs/roofline.md). Non-positive values fall back
        # to the defaults — same no-crash contract as env_int itself.
        if block_q is None:
            block_q = env_int("CDT_FLASH_BLOCK_Q", 256)
            block_q = block_q if block_q > 0 else 256
        if block_k is None:
            block_k = env_int("CDT_FLASH_BLOCK_K", 512)
            block_k = block_k if block_k > 0 else 512
    B, Nq, H, D = q.shape
    _, Nk, _, _ = k.shape
    if layout == "packed":
        use_packed = _packed_legal(H, D)   # explicit beats env + floors
    elif layout == "bh":
        use_packed = False
    elif layout is None:
        use_packed = _layout_packed(H, D, Nq=Nq, Nk=Nk)
    else:
        raise ValueError(
            f"layout must be 'packed', 'bh', or None, got {layout!r}")
    # [B,N,H,D] → [B·H, N, D]
    def to_bh(x, n):
        return x.transpose(0, 2, 1, 3).reshape(B * H, n, D)
    if interpret and _in_manual_trace(q):
        out = _flash_emulated(to_bh(q, Nq), to_bh(k, Nk), to_bh(v, Nk),
                              block_q=block_q, block_k=block_k)
    elif use_packed:
        bq, bk = _packed_blocks(H * D, block_q, block_k)
        out = _flash_mha_packed(
            q.reshape(B, Nq, H * D), k.reshape(B, Nk, H * D),
            v.reshape(B, Nk, H * D), num_heads=H,
            block_q=bq, block_k=bk, interpret=interpret)
        return out.reshape(B, Nq, H, D)
    else:
        out = _flash_mha(to_bh(q, Nq), to_bh(k, Nk), to_bh(v, Nk),
                         block_q=block_q, block_k=block_k,
                         interpret=interpret)
    return out.reshape(B, H, Nq, D).transpose(0, 2, 1, 3)
