"""Pallas flash attention for TPU.

The hot op of every model family here (SDXL UNet cross/self attention,
FLUX/WAN DiT joint attention) is bidirectional dense attention over
10³–10⁵ tokens. XLA's fused ``dot_product_attention`` is good; a pallas
kernel is better on two axes the compiler can't reach:

- **VMEM residency**: K/V stream through VMEM in ``block_k`` tiles while
  the O(N²) logits matrix never exists in HBM — at video sequence lengths
  (WAN: ~32k tokens) the materialized-logits path is HBM-bound and the
  streaming-softmax path is MXU-bound.
- **fp32 accumulation over bf16 MXU inputs**: QKᵀ and PV run on the MXU
  in bf16 with fp32 accumulators (``preferred_element_type``), matching
  flash-attention numerics exactly.

The reference has no analogue (its compute hot loop is ComfyUI's
``common_ksampler``, SURVEY §3.3); this kernel sits *under* the parity
surface as the execution engine's attention primitive.

Kernel structure (standard TPU flash attention):
grid = (batch·heads, Nq/block_q, Nk/block_k), K-blocks innermost so the
running max ``m``, denominator ``l`` and output accumulator live in VMEM
scratch across grid steps; the output block is written once on the final
K step. Sequence lengths are padded to block multiples at trace time and
masked with a static-length comparison — shapes stay static for XLA.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import constants as _constants

# lane width: scratch vectors m/l are stored lane-replicated (BQ, 128)
_LANES = 128
_SUBLANES = 8        # f32 sublane tile height — block_q granularity
NEG_INF = -1e30      # large-but-finite: -inf breaks max on fully-masked rows

_DEFAULT_BLOCK_Q = 256   # measured r04 at SDXL shapes (docs/roofline.md)
_DEFAULT_BLOCK_K = 512


def _parse_block_env(name: str, multiple: int) -> Optional[int]:
    """Parse one ``CDT_FLASH_BLOCK_*`` knob, rejecting values pallas
    would only reject deep in Mosaic lowering (or worse, mis-tile): the
    block size must be a positive multiple of the hardware tile for its
    axis (``block_q``: 8 sublanes, ``block_k``: 128 lanes). Unset/empty
    returns None (caller applies the default)."""
    raw = _constants.knob(name).raw()
    if raw is None or raw.strip() == "":
        return None
    try:
        value = int(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"{name}={raw!r} is not an integer: flash block sizes must be "
            f"positive multiples of {multiple}") from None
    _check_block(name, value, multiple)
    return value


def _check_block(name: str, value: int, multiple: int) -> None:
    if value <= 0 or value % multiple:
        raise ValueError(
            f"{name}={value} is not a legal flash block size: must be a "
            f"positive multiple of {multiple} (TPU "
            f"{'sublane' if multiple == _SUBLANES else 'lane'} tiling) — "
            "pallas would fail during Mosaic lowering otherwise")


def resolve_flash_blocks(block_q: Optional[int] = None,
                         block_k: Optional[int] = None) -> tuple[int, int]:
    """Resolve (block_q, block_k): explicit args win, then the
    ``CDT_FLASH_BLOCK_Q``/``CDT_FLASH_BLOCK_K`` env knobs, then the
    measured defaults (256/512, r04). Both sources are validated at
    parse time — a non-positive or non-(8,128)-divisible value raises a
    descriptive ``ValueError`` here instead of letting pallas fail deep
    in lowering (tuning-table entries pass through the same check via
    ``ops/autotune.py``)."""
    if block_q is None:
        block_q = _parse_block_env("CDT_FLASH_BLOCK_Q", _SUBLANES)
        block_q = _DEFAULT_BLOCK_Q if block_q is None else block_q
    else:
        _check_block("block_q", block_q, _SUBLANES)
    if block_k is None:
        block_k = _parse_block_env("CDT_FLASH_BLOCK_K", _LANES)
        block_k = _DEFAULT_BLOCK_K if block_k is None else block_k
    else:
        _check_block("block_k", block_k, _LANES)
    return block_q, block_k


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, kv_len: int, block_k: int, num_k_blocks: int,
                  scale: float, precision):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                   # [BQ, D]
    k = k_ref[0]                                   # [BK, D]
    v = v_ref[0]                                   # [BK, D]

    # [BQ, BK] logits in fp32 (bf16 inputs use the MXU natively)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ) * scale

    # static-shape masking of the K padding tail (kv_len is a Python int)
    if kv_len % block_k != 0:
        base = j * block_k
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(base + col < kv_len, s, NEG_INF)

    m_prev = m_ref[:, :1]                          # [BQ, 1] (lane-replicated)
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)     # [BQ, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)                         # [BQ, BK]
    corr = jnp.exp(m_prev - m_new)                 # [BQ, 1]
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)

    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    )                                              # [BQ, D]
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)            # fully-masked rows → 0
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _accumulate_packed_heads(q, k, v, j, m_ref, l_ref, acc_ref, *,
                             kv_len: int, block_k: int, scale: float,
                             precision, num_heads: int, head_dim: int):
    """One K-block accumulation over statically-unrolled heads, operands
    in the packed [block, H·D] layout. Head h's running max/denominator
    live in lane h of the [BQ, 128] m/l scratches (hence ``num_heads ≤
    128``). Shared by the packed and fused kernel tiers — the fused tier
    differs only in where q/k/v come from (projected in-kernel), not in
    the accumulation math."""
    col = jax.lax.broadcasted_iota(
        jnp.int32, (q.shape[0], block_k), 1) if kv_len % block_k else None

    for h in range(num_heads):
        sl = slice(h * head_dim, (h + 1) * head_dim)
        s = jax.lax.dot_general(
            q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale                                   # [BQ, BK]

        if col is not None:                        # mask the K padding tail
            s = jnp.where(j * block_k + col < kv_len, s, NEG_INF)

        m_prev = m_ref[:, h:h + 1]                 # [BQ, 1] (lane h)
        l_prev = l_ref[:, h:h + 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)

        pv = jax.lax.dot_general(
            p.astype(v.dtype), v[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                          # [BQ, D]
        acc_ref[:, sl] = acc_ref[:, sl] * corr + pv
        m_ref[:, h:h + 1] = m_new
        l_ref[:, h:h + 1] = l_new


def _finalize_packed_heads(o_ref, m_ref, l_ref, acc_ref, *,
                           num_heads: int, head_dim: int):
    """Write the normalized output block once, on the final K step."""
    for h in range(num_heads):
        sl = slice(h * head_dim, (h + 1) * head_dim)
        l = l_ref[:, h:h + 1]
        l = jnp.where(l == 0.0, 1.0, l)            # fully-masked rows → 0
        o_ref[0, :, sl] = (acc_ref[:, sl] / l).astype(o_ref.dtype)


def _flash_kernel_packed(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                         *, kv_len: int, block_k: int, num_k_blocks: int,
                         scale: float, precision, num_heads: int,
                         head_dim: int):
    """Packed-heads variant: refs are [1, block, H·D] slices of the
    model's NATURAL layout — the fused QKV projection emits [B, N, H·D]
    and splitting heads along the minor axis is free, so no transpose
    ever happens at the custom-call boundary (the boundary relayout, not
    the kernel body, is what made the classic [B·H, N, D] call lose to
    XLA fused attention at SDXL sequence lengths — `docs/roofline.md`
    finding 1)."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _accumulate_packed_heads(
        q_ref[0], k_ref[0], v_ref[0], j, m_ref, l_ref, acc_ref,
        kv_len=kv_len, block_k=block_k, scale=scale, precision=precision,
        num_heads=num_heads, head_dim=head_dim)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        _finalize_packed_heads(o_ref, m_ref, l_ref, acc_ref,
                               num_heads=num_heads, head_dim=head_dim)


def _flash_kernel_fused(xq_ref, xkv_ref, wq_ref, wk_ref, wv_ref, o_ref,
                        q_ref, m_ref, l_ref, acc_ref, *,
                        kv_len: int, block_k: int, num_k_blocks: int,
                        scale: float, precision, num_heads: int,
                        head_dim: int):
    """Fused QKV-projection + attention: the kernel's inputs are the
    attention block's INPUT activations (x, [1, block, C] row tiles) and
    the three [C, H·D] projection weights — q/k/v are projected on-chip
    and never round-trip HBM, so there is no custom-call boundary for
    XLA to lose fusions at (the ~15 ms/forward relayout + lost-fusion
    cost `docs/roofline.md` finding 1 measured).

    Schedule: the q row-block is projected ONCE per grid row (j == 0)
    into VMEM scratch; each K step projects its own [BK, C]·[C, H·D]
    k/v tiles before the shared packed-heads accumulation. The K/V
    projection is therefore recomputed once per q block — ``Nq/block_q``
    times total, an extra ``C/block_q`` of the attention FLOPs — which
    is why the tier is selected per geometry by the autotune sweep
    (``ops/autotune.py``) rather than by default: it wins where the
    boundary cost beats the recompute (narrow C, long N), loses where it
    doesn't. Projections accumulate in f32 on the MXU and cast back to
    the operand dtype, matching the out-of-kernel Dense numerics."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        q = jax.lax.dot_general(
            xq_ref[0], wq_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        q_ref[:] = q.astype(q_ref.dtype)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    xkv = xkv_ref[0]                               # [BK, C]
    k = jax.lax.dot_general(
        xkv, wk_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ).astype(q_ref.dtype)                          # [BK, H·D]
    v = jax.lax.dot_general(
        xkv, wv_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ).astype(q_ref.dtype)

    _accumulate_packed_heads(
        q_ref[:], k, v, j, m_ref, l_ref, acc_ref,
        kv_len=kv_len, block_k=block_k, scale=scale, precision=precision,
        num_heads=num_heads, head_dim=head_dim)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        _finalize_packed_heads(o_ref, m_ref, l_ref, acc_ref,
                               num_heads=num_heads, head_dim=head_dim)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    n = x.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _in_manual_trace(x) -> bool:
    """True when tracing inside ``shard_map`` (the aval carries varying
    manual axes)."""
    try:
        return bool(getattr(jax.typeof(x), "vma", None))
    except Exception:  # noqa: BLE001 — typeof unavailable on some inputs
        return False


def _flash_emulated(q, k, v, block_q: int, block_k: int):
    """The kernel's streaming-softmax algorithm in plain JAX ops.

    Used only where the pallas *interpreter* cannot run: inside a
    ``shard_map`` trace in interpret mode, JAX's HLO interpreter issues
    ``dynamic_slice`` calls whose index operands lack the varying manual
    axes of the data operand and trips ``check_vma`` (jax-ml/jax — the
    error itself suggests ``check_vma=False`` as the workaround, which we
    cannot impose on callers). This emulation runs the same block
    schedule, padding, NEG_INF tail masking and fp32 accumulation as
    ``_flash_kernel``, so CPU shard_map tests exercise the same math;
    compiled TPU runs still take the pallas path.
    """
    BH, Nq, D = q.shape
    _, Nk, _ = k.shape
    scale = 1.0 / (D ** 0.5)
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    nkb = kp.shape[1] // block_k

    m = jnp.full((BH, qp.shape[1], 1), NEG_INF, jnp.float32)
    l = jnp.zeros((BH, qp.shape[1], 1), jnp.float32)
    acc = jnp.zeros((BH, qp.shape[1], D), jnp.float32)
    for j in range(nkb):  # static unroll — nkb is a Python int
        kb = jax.lax.dynamic_slice_in_dim(kp, j * block_k, block_k, 1)
        vb = jax.lax.dynamic_slice_in_dim(vp, j * block_k, block_k, 1)
        s = jax.lax.dot_general(
            qp, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        if Nk % block_k != 0:
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            s = jnp.where(j * block_k + col < Nk, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc = acc * corr + pv
        m = m_new
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l).astype(q.dtype)[:, :Nq]


def _pad_and_prepare(q, k, v, block_q: int, block_k: int):
    """Shared prologue of both pallas drivers: pad q/k/v sequence dims to
    block multiples, pick the matmul precision, and build the vma-aware
    output aval. f32 inputs ask for real f32 matmuls (3-pass bf16 on the
    MXU); bf16 inputs take the fast single-pass path — the production
    dtype. Inside shard_map the output must declare which mesh axes it
    varies over (check_vma) — it varies exactly like q does."""
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    precision = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    try:
        vma = getattr(jax.typeof(qp), "vma", None)
    except Exception:  # noqa: BLE001 — typeof unavailable outside tracing
        vma = None
    out_sds = (jax.ShapeDtypeStruct(qp.shape, q.dtype, vma=vma)
               if vma else jax.ShapeDtypeStruct(qp.shape, q.dtype))
    return qp, kp, vp, precision, out_sds


@functools.partial(jax.jit, static_argnames=("block_q", "block_k", "interpret"))
def _flash_mha(q, k, v, block_q: int, block_k: int, interpret: bool):
    BH, Nq, D = q.shape
    _, Nk, _ = k.shape
    scale = 1.0 / (D ** 0.5)

    qp, kp, vp, precision, out_sds = _pad_and_prepare(q, k, v, block_q,
                                                      block_k)
    nqb = qp.shape[1] // block_q
    nkb = kp.shape[1] // block_k

    kernel = functools.partial(
        _flash_kernel, kv_len=Nk, block_k=block_k, num_k_blocks=nkb,
        scale=scale, precision=precision)

    out = pl.pallas_call(
        kernel,
        grid=(BH, nqb, nkb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_sds,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running sum
            pltpu.VMEM((block_q, D), jnp.float32),        # output acc
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :Nq]


@functools.partial(jax.jit, static_argnames=("num_heads", "block_q",
                                             "block_k", "interpret"))
def _flash_mha_packed(q, k, v, num_heads: int, block_q: int, block_k: int,
                      interpret: bool):
    """Packed-heads pallas call: operands stay [B, N, H·D] — the QKV
    projection's own output layout — and the kernel splits heads along
    the minor axis (free). Legality (``_packed_legal``): H·D % 128 == 0,
    H ≤ 128, H·D ≤ ``_PACKED_MAX_HD``, and D % 64 == 0 (lane-aligned
    head slices) — true for SDXL (640/1280) and WAN (1536); FLUX (3072)
    exceeds the VMEM bound and stays on the classic [B·H, N, D] call."""
    B, Nq, HD = q.shape
    _, Nk, _ = k.shape
    D = HD // num_heads
    scale = 1.0 / (D ** 0.5)

    qp, kp, vp, precision, out_sds = _pad_and_prepare(q, k, v, block_q,
                                                      block_k)
    nqb = qp.shape[1] // block_q
    nkb = kp.shape[1] // block_k

    kernel = functools.partial(
        _flash_kernel_packed, kv_len=Nk, block_k=block_k, num_k_blocks=nkb,
        scale=scale, precision=precision, num_heads=num_heads, head_dim=D)

    q_spec = pl.BlockSpec((1, block_q, HD), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, HD), lambda b, i, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(B, nqb, nkb),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        out_shape=out_sds,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # per-head max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # per-head sum
            pltpu.VMEM((block_q, HD), jnp.float32),       # output acc
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :Nq]


# past this packed width the kernel needs shrunken q/k blocks to keep
# its VMEM working set (double-buffered [block, H·D] K/V tiles + the
# f32 accumulator) inside the ~16 MB budget. The DEFAULT auto-layout
# stays classic past this width — the one shrink probed at r04 (FLUX's
# H·D = 3072, 128/256 blocks) ran the offload ladder at 1.34 s/step vs
# the classic [B·H, N, D] call's 1.21 s (`benchmarks/r04_tpu_flux.json`)
# — but shrunken-packed is now *reachable* (explicit ``layout="packed"``
# or a tuning-table entry, ``ops/autotune.py``): the r04 probe tried one
# block pair, and the autotune sweep walks the whole feasible set.
_PACKED_MAX_HD = 2048

# scoped-VMEM budget the working-set model checks against. The r05 WAN
# probe anchors it: 1024 K-blocks at H·D=1536 died at 25.09 MB scoped
# vs the chip's 16 MB, 512 K-blocks fit (docs/roofline.md).
_VMEM_BUDGET_BYTES = 16 * 1024 * 1024
_MIN_BLOCK_Q = 64     # shrink floors: below these tiles the grid is all
_MIN_BLOCK_K = 128    # overhead (one lane tile / 8 sublane tiles)


def _packed_vmem_bytes(hd: int, block_q: int, block_k: int,
                       itemsize: int) -> int:
    """Working-set estimate of one packed-kernel grid step: double-
    buffered q/k/v/out tiles in the operand dtype plus the f32 output
    accumulator and the two lane-replicated m/l scratches."""
    io = 2 * (2 * block_q * hd + 2 * block_k * hd) * itemsize
    scratch = block_q * hd * 4 + 2 * block_q * _LANES * 4
    return io + scratch


def _fused_vmem_bytes(c: int, hd: int, block_q: int, block_k: int,
                      itemsize: int) -> int:
    """Working set of one fused-kernel grid step: double-buffered x
    row-tiles ([block, C]) and the out tile, the three resident [C, H·D]
    projection weights (constant index map — fetched once, not double-
    buffered), the projected-q scratch (operand dtype) and the f32
    accumulator + m/l scratches."""
    io = 2 * (block_q * c + block_k * c + block_q * hd) * itemsize
    weights = 3 * c * hd * itemsize
    scratch = (block_q * hd * itemsize          # projected q
               + block_q * hd * 4               # f32 accumulator
               + 2 * block_q * _LANES * 4)      # m / l
    return io + weights + scratch


def _shrink_blocks_for_vmem(bytes_fn, block_q: int, block_k: int
                            ) -> Optional[tuple[int, int]]:
    """Halve block_k (first — K tiles dominate the working set), then
    block_q, until ``bytes_fn(bq, bk)`` fits ``_VMEM_BUDGET_BYTES``;
    None when even the floor tiles blow the budget. Deterministic: the
    same request always shrinks to the same blocks."""
    bq, bk = block_q, block_k
    while bytes_fn(bq, bk) > _VMEM_BUDGET_BYTES:
        if bk > _MIN_BLOCK_K:
            bk //= 2
        elif bq > _MIN_BLOCK_Q:
            bq //= 2
        else:
            return None
    return bq, bk


_shrink_logged: set = set()


def _log_shrink(hd: int, block_q: int, block_k: int,
                shrunk: Optional[tuple[int, int]], itemsize: int) -> None:
    """Once per combination: a VMEM shrink of OPERATOR-requested blocks
    is never silent — block-tuning experiments (`CDT_FLASH_BLOCK_Q/K`,
    docs/roofline.md r05) must not measure different blocks than they
    record. Candidate enumeration (the sweep) calls the feasibility
    helpers directly and is exempt by construction."""
    if not shrunk or shrunk == (block_q, block_k):
        return
    sig = (hd, block_q, block_k, itemsize)
    if sig in _shrink_logged:
        return
    _shrink_logged.add(sig)
    from ..utils.logging import log

    log(f"flash packed: requested blocks {block_q}/{block_k} exceed the "
        f"VMEM model at H·D={hd} ({itemsize}B operands); shrunk to "
        f"{shrunk[0]}/{shrunk[1]}")


def _packed_blocks(hd: int, block_q: int, block_k: int,
                   itemsize: int = 2) -> tuple[int, int]:
    """Block sizes for the packed call: the requested blocks, shrunk
    (K first) until the VMEM working-set model fits — the legality path
    that lets geometries past the native ``_PACKED_MAX_HD`` ceiling
    (FLUX's H·D = 3072) run packed with shrunken [block, H·D] tiles
    instead of falling back to the classic [B·H, N, D] call. Raises when
    no feasible blocks exist (callers check ``_packed_feasible`` first).

    A shrink is LOGGED (once per combination): block-tuning experiments
    (`CDT_FLASH_BLOCK_Q/K`, docs/roofline.md r05) must never silently
    measure different blocks than the operator requested."""
    shrunk = _shrink_blocks_for_vmem(
        functools.partial(_packed_vmem_bytes, hd, itemsize=itemsize),
        block_q, block_k)
    if shrunk is None:
        raise ValueError(
            f"packed flash attention infeasible at H·D={hd}: even "
            f"{_MIN_BLOCK_Q}/{_MIN_BLOCK_K} blocks exceed the "
            f"{_VMEM_BUDGET_BYTES >> 20} MB VMEM budget")
    _log_shrink(hd, block_q, block_k, shrunk, itemsize)
    return shrunk


def _packed_feasible(H: int, D: int, block_q: int = _DEFAULT_BLOCK_Q,
                     block_k: int = _DEFAULT_BLOCK_K,
                     itemsize: int = 2) -> Optional[tuple[int, int]]:
    """Shrink-aware packed legality: the geometric constraints of
    ``_packed_legal`` minus its native width ceiling, plus a feasible
    block pair under the VMEM model. Returns the (possibly shrunken)
    blocks, or None. Used by explicit ``layout=\"packed\"`` requests and
    tuning-table entries; the DEFAULT auto layout keeps the conservative
    ``_packed_legal`` ceiling (shrunken-packed engages only where a
    sweep or an operator asked for it)."""
    if not ((H * D) % _LANES == 0 and H <= _LANES and D % 64 == 0):
        return None
    return _shrink_blocks_for_vmem(
        functools.partial(_packed_vmem_bytes, H * D, itemsize=itemsize),
        block_q, block_k)


def _flash_min_seq_packed() -> int:
    """Engagement floor for the packed-heads layout: measured r04 it
    beats XLA already at SDXL self-attention lengths (docs/roofline.md
    finding 1a) but not below ~1024 tokens."""
    return _constants.FLASH_MIN_SEQ_PACKED.get()


def _flash_min_kv_packed() -> int:
    """Short-K floor for the packed kernel: at SDXL cross-attention
    (K = 77 text tokens padded to one 512 block) the kernel wastes most
    of its K tile and measures behind XLA (1.20 vs 1.04 ms/64-op chain,
    r04) — those sites stay on XLA's fused lowering / the classic bh
    call."""
    return _constants.FLASH_MIN_KV_PACKED.get()


def _packed_legal(H: int, D: int) -> bool:
    """Pure geometric legality of the packed-heads layout. D % 64 keeps
    the in-kernel head slices register-lane aligned and confines the
    layout to the tested head-dim classes (64/128); e.g. H=128, D=16
    would pass the packed-width checks but unroll a 128-way head loop
    over 16-wide lane slices — a shape class never measured and likely
    Mosaic-hostile."""
    return ((H * D) % _LANES == 0 and H <= _LANES
            and H * D <= _PACKED_MAX_HD and D % 64 == 0)


def _layout_packed(H: int, D: int,
                   Nq: Optional[int] = None,
                   Nk: Optional[int] = None) -> bool:
    """Kernel I/O layout: ``packed`` (default where legal AND the
    measured engagement floors hold) keeps q/k/v in the model's natural
    [B, N, H·D] layout and splits heads inside the kernel; ``bh`` is the
    classic pre-transposed [B·H, N, D] call.

    ``CDT_FLASH_LAYOUT=bh`` restores the classic call everywhere;
    ``CDT_FLASH_LAYOUT=packed`` is the default (packed where legal and
    the floors hold — both env states behave identically, preserving
    the historical meaning of an exported ``packed``). An explicit
    per-call layout override is ``flash_attention(..., layout=...)``.
    Without ``Nq``/``Nk`` (the shape-gate site, which applies its own
    thresholds) only legality and the env override are checked."""
    env = _constants.FLASH_LAYOUT.get()
    if env == "bh":
        return False
    if not _packed_legal(H, D):
        return False
    # The packed call must also clear its measured floors, so a
    # user-raised CDT_FLASH_MIN_SEQ_PACKED/KV floor is never bypassed by
    # the shape gate's classic fall-through (r04 review finding).
    return ((Nq is None or Nq >= _flash_min_seq_packed())
            and (Nk is None or Nk >= _flash_min_kv_packed()))


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    layout: Optional[str] = None,
) -> jax.Array:
    """Exact bidirectional attention, [B,N,H,D] layout (matching
    ``ops.attention.full_attention``), computed by the pallas kernel.

    ``interpret=None`` auto-selects: compiled on TPU, interpreter
    elsewhere (CPU tests run the same kernel code path).

    ``block_q``/``block_k=None`` resolve to ``CDT_FLASH_BLOCK_Q``/
    ``CDT_FLASH_BLOCK_K`` (defaults 256/512, measured r04; the r05 WAN
    probes showed 512 is also the largest K block the 16 MB scoped VMEM
    admits at H·D=1536 — docs/roofline.md). Both the env knobs and
    explicit arguments are validated at parse time
    (``resolve_flash_blocks``): non-positive or non-(8,128)-divisible
    values raise a descriptive error instead of failing in lowering.

    ``layout`` forces the kernel I/O layout for this call: ``"packed"``
    (where geometrically feasible — including widths past the native
    ``_PACKED_MAX_HD`` ceiling via VMEM-model block shrinking; truly
    infeasible geometries still fall back to the classic call) or
    ``"bh"``; ``None`` auto-selects per ``_layout_packed`` (legality +
    measured floors + ``CDT_FLASH_LAYOUT``). Used by the tuning table
    (``ops/autotune.py``), layout-equivalence tests and power users; the
    env var remains the global knob.
    """
    if interpret is None:
        interpret = not _on_tpu()
    block_q, block_k = resolve_flash_blocks(block_q, block_k)
    B, Nq, H, D = q.shape
    _, Nk, _, _ = k.shape
    itemsize = jnp.dtype(q.dtype).itemsize
    packed_blocks: Optional[tuple[int, int]] = None
    if layout == "packed":
        # explicit beats env + floors; shrink-aware so FLUX-width
        # geometries run packed instead of silently degrading to classic
        packed_blocks = _packed_feasible(H, D, block_q, block_k, itemsize)
        _log_shrink(H * D, block_q, block_k, packed_blocks, itemsize)
    elif layout == "bh":
        packed_blocks = None
    elif layout is None:
        if _layout_packed(H, D, Nq=Nq, Nk=Nk):
            packed_blocks = _packed_blocks(H * D, block_q, block_k,
                                           itemsize)
    else:
        raise ValueError(
            f"layout must be 'packed', 'bh', or None, got {layout!r}")
    # [B,N,H,D] → [B·H, N, D]
    def to_bh(x, n):
        return x.transpose(0, 2, 1, 3).reshape(B * H, n, D)
    if interpret and _in_manual_trace(q):
        out = _flash_emulated(to_bh(q, Nq), to_bh(k, Nk), to_bh(v, Nk),
                              block_q=block_q, block_k=block_k)
    elif packed_blocks is not None:
        bq, bk = packed_blocks
        out = _flash_mha_packed(
            q.reshape(B, Nq, H * D), k.reshape(B, Nk, H * D),
            v.reshape(B, Nk, H * D), num_heads=H,
            block_q=bq, block_k=bk, interpret=interpret)
        return out.reshape(B, Nq, H, D)
    else:
        out = _flash_mha(to_bh(q, Nq), to_bh(k, Nk), to_bh(v, Nk),
                         block_q=block_q, block_k=block_k,
                         interpret=interpret)
    return out.reshape(B, H, Nq, D).transpose(0, 2, 1, 3)


# --- fused QKV-projection + attention tier ----------------------------------


def _fused_feasible(C: int, H: int, D: int,
                    block_q: int = _DEFAULT_BLOCK_Q,
                    block_k: int = _DEFAULT_BLOCK_K,
                    itemsize: int = 2) -> Optional[tuple[int, int]]:
    """Hardware legality of the fused tier: packed-heads geometric
    constraints plus a lane-aligned model width (C on the x-tile minor
    axis) plus a feasible block pair under the fused VMEM model — the
    three resident [C, H·D] weights dominate it, so wide models (WAN
    1536, FLUX 3072) are fused-infeasible on chip and take the packed
    (possibly block-shrunk) tier from the tuning table instead. Returns
    the (possibly shrunken) blocks, or None."""
    HD = H * D
    if not (HD % _LANES == 0 and H <= _LANES and D % 64 == 0
            and C % _LANES == 0):
        return None
    return _shrink_blocks_for_vmem(
        functools.partial(_fused_vmem_bytes, C, HD, itemsize=itemsize),
        block_q, block_k)


def split_qkv_weight(w_qkv: jax.Array) -> tuple[jax.Array, jax.Array,
                                                jax.Array]:
    """[C, 3·H·D] fused-projection weight → (wq, wk, wv) static slices
    (the layout ``models/dit.py``'s ``qkv`` Dense emits)."""
    hd = w_qkv.shape[-1] // 3
    return w_qkv[:, :hd], w_qkv[:, hd:2 * hd], w_qkv[:, 2 * hd:]


def _fused_emulated(x, wq, wk, wv, num_heads: int, block_q: int,
                    block_k: int):
    """Fused tier in plain JAX ops: projection (f32 MXU accumulation,
    cast back to the operand dtype — exactly the kernel's epilogue) then
    the shared `_flash_emulated` block schedule. The CPU/shard_map
    stand-in that keeps the fused tier testable everywhere the pallas
    interpreter can't run; the block schedule and masking are identical,
    so parity tests of this path cover the kernel's math."""
    B, N, C = x.shape
    HD = wq.shape[-1]
    D = HD // num_heads

    def proj(w):
        y = jax.lax.dot_general(x, w, (((2,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return y.astype(x.dtype)

    def to_bh(t):
        return (t.reshape(B, N, num_heads, D)
                .transpose(0, 2, 1, 3).reshape(B * num_heads, N, D))

    out = _flash_emulated(to_bh(proj(wq)), to_bh(proj(wk)), to_bh(proj(wv)),
                          block_q=block_q, block_k=block_k)
    return (out.reshape(B, num_heads, N, D).transpose(0, 2, 1, 3))


@functools.partial(jax.jit, static_argnames=("num_heads", "block_q",
                                             "block_k", "interpret"))
def _flash_mha_fused(x, wq, wk, wv, num_heads: int, block_q: int,
                     block_k: int, interpret: bool):
    B, N, C = x.shape
    HD = wq.shape[-1]
    D = HD // num_heads
    scale = 1.0 / (D ** 0.5)
    precision = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)

    # x is streamed twice under different paddings: q row-tiles walk
    # block_q-grained rows, k/v row-tiles walk block_k-grained rows
    xq = _pad_to(x, 1, block_q)
    xkv = _pad_to(x, 1, block_k)
    nqb = xq.shape[1] // block_q
    nkb = xkv.shape[1] // block_k

    try:
        vma = getattr(jax.typeof(xq), "vma", None)
    except Exception:  # noqa: BLE001 — typeof unavailable outside tracing
        vma = None
    out_shape = (B, xq.shape[1], HD)
    out_sds = (jax.ShapeDtypeStruct(out_shape, x.dtype, vma=vma)
               if vma else jax.ShapeDtypeStruct(out_shape, x.dtype))

    kernel = functools.partial(
        _flash_kernel_fused, kv_len=N, block_k=block_k, num_k_blocks=nkb,
        scale=scale, precision=precision, num_heads=num_heads, head_dim=D)

    w_spec = pl.BlockSpec((C, HD), lambda b, i, j: (0, 0),
                          memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(B, nqb, nkb),
        in_specs=[
            pl.BlockSpec((1, block_q, C), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, C), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            w_spec, w_spec, w_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, HD), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_sds,
        scratch_shapes=[
            pltpu.VMEM((block_q, HD), x.dtype),           # projected q
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # per-head max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # per-head sum
            pltpu.VMEM((block_q, HD), jnp.float32),       # output acc
        ],
        interpret=interpret,
    )(xq, xkv, wq, wk, wv)
    return out[:, :N]


def fused_qkv_attention(
    x: jax.Array, wq: jax.Array, wk: jax.Array, wv: jax.Array,
    num_heads: int,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Self-attention computed straight from the block's input
    activations: ``x`` [B, N, C] and the three bias-free projection
    weights [C, H·D] (``split_qkv_weight`` splits a packed [C, 3·H·D]).
    Returns [B, N, H, D] — the same contract as ``full_attention`` on
    the projected operands, without q/k/v ever materializing in HBM.

    Serves projection→attention sites with nothing in between (SDXL
    UNet self-attention); sites that qk-norm/RoPE between projection and
    attention (FLUX, WAN) cannot fuse and take the packed tier instead.
    ``interpret=None`` auto-selects like ``flash_attention``; blocks
    resolve via the same validated env knobs. On hardware, infeasible
    geometries (the VMEM model — weights resident) raise; in interpret
    mode the requested blocks run regardless, keeping every geometry
    CPU-testable."""
    if interpret is None:
        interpret = not _on_tpu()
    block_q, block_k = resolve_flash_blocks(block_q, block_k)
    B, N, C = x.shape
    HD = wq.shape[-1]
    if wq.shape != (C, HD) or wk.shape != (C, HD) or wv.shape != (C, HD):
        raise ValueError(
            f"fused qkv attention needs three [C, H·D] weights; got "
            f"wq={wq.shape}, wk={wk.shape}, wv={wv.shape} for C={C}")
    if HD % num_heads:
        raise ValueError(
            f"projection width {HD} not divisible by num_heads={num_heads}")
    D = HD // num_heads
    if interpret and _in_manual_trace(x):
        return _fused_emulated(x, wq, wk, wv, num_heads,
                               block_q=block_q, block_k=block_k)
    itemsize = jnp.dtype(x.dtype).itemsize
    blocks = _fused_feasible(C, num_heads, D, block_q, block_k, itemsize)
    if blocks is None:
        if not interpret:
            raise ValueError(
                f"fused qkv attention infeasible at C={C}, H·D={HD} "
                f"({x.dtype}): the resident projection weights exceed the "
                f"{_VMEM_BUDGET_BYTES >> 20} MB VMEM budget at any block "
                "size — use the packed tier (ops/autotune.py picks this "
                "per geometry)")
        blocks = (block_q, block_k)
    bq, bk = blocks
    out = _flash_mha_fused(x, wq, wk, wv, num_heads=num_heads,
                           block_q=bq, block_k=bk, interpret=interpret)
    return out.reshape(B, N, num_heads, D)
