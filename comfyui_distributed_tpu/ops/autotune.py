"""Persistent per-shape attention-kernel autotuner.

``docs/roofline.md`` ended r05 with every workload pinned to a measured
attention config — but the measurements lived in a human's shell
history as ``CDT_FLASH_BLOCK_Q/K`` experiments. This module makes them
an artifact: the first time a (heads, head_dim, N, dtype) geometry is
met, a sweep walks the legal kernel tiers and block sizes, and the
winner persists to a tuning table consulted by ``ops/attention.py``'s
dispatcher ahead of the env knobs — so every new model generation lands
on its best kernel config without code edits, and a fleet shares one
table the way it shares one XLA cache.

Layout of the decision data:

- **GeometryKey** — (num_heads, head_dim, q_bucket, kv_bucket, dtype);
  sequence lengths bucket to the next power of two so one entry serves
  a resolution family instead of every ±8-token variant compiling its
  own sweep.
- **KernelChoice** — (tier, block_q, block_k): tier is one of ``fused``
  (QKV projection folded into the flash grid), ``packed`` ([B, N, H·D]
  native layout, VMEM-shrunk blocks where needed), ``bh`` (classic
  [B·H, N, D] call), ``xla`` (the fused XLA lowering).
- **TuningTable** — two layers: the resolved table for the known model
  zoo shipped in-repo (``ops/attn_table_default.json``, rebakeable with
  ``scripts/autotune_sweep.py``) plus a local overlay persisted next to
  the XLA compilation cache, stored and atomically merged exactly like
  the shape catalog (``utils/jsonio.py``: tmp+rename writes, merge on
  save, corrupt files degrade to empty).

Sweeps run OFF the request path: ``diffusion/warmup.py`` tunes every
catalog geometry during the worker's AOT pass (the worker reports
``warming`` until its geometries are tuned), and the CLI pre-bakes
fleet images. On hardware the sweep times real candidates; off
hardware (``mode="dry"``) it resolves the same deterministic
legality-ranked policy the shipped table was baked with — same
geometry + same table ⇒ same choice, always.

Knobs: ``CDT_ATTN_TABLE`` (local overlay path; default
``<CDT_COMPILE_CACHE_DIR>/attn_tuning.json``), ``CDT_ATTN_TUNE=0``
disables table lookups AND sweeps (env knobs and measured defaults
rule, the pre-tuning-table behavior).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from pathlib import Path
from typing import Callable, Iterable, Optional

from ..lint.lockorder import tracked_lock
from ..utils import constants
from ..utils.jsonio import atomic_write_json, read_json
from ..utils.logging import debug_log, log

TABLE_VERSION = 1
TIERS = ("fused", "packed", "bh", "xla")

# the in-repo resolved table for the known model zoo
_SHIPPED_PATH = Path(__file__).resolve().parent / "attn_table_default.json"

_DTYPE_NAMES = {"bfloat16": "bf16", "float32": "f32", "float16": "f16",
                "bf16": "bf16", "f32": "f32", "f16": "f16"}


def dtype_name(dtype) -> str:
    """Canonical short dtype tag for table keys ('bf16', 'f32', ...).
    Accepts numpy/jax dtypes, scalar types (``jnp.bfloat16``) and
    strings; already-short tags pass through."""
    import numpy as np

    try:
        name = np.dtype(dtype).name
    except TypeError:
        name = getattr(dtype, "name", None) or str(dtype)
    return _DTYPE_NAMES.get(name, name)


def itemsize_of(dtype) -> int:
    """Operand byte width for the VMEM working-set model. One
    definition — the dispatcher, the validator and the policy all key
    legality on it, and a drift between them would approve blocks the
    kernel can't fit."""
    return 4 if dtype_name(dtype) == "f32" else 2


def seq_bucket(n: int) -> int:
    """Next power of two ≥ n, floored at 128 — one table entry serves a
    resolution family (SDXL 4096 → 4096, WAN 14040 → 16384, a 77-token
    text context → 128) instead of every exact length sweeping anew."""
    b = 128
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True, order=True)
class GeometryKey:
    """One attention geometry as the dispatcher sees it at trace time."""

    num_heads: int
    head_dim: int
    q_bucket: int
    kv_bucket: int
    dtype: str = "bf16"

    def __post_init__(self):
        if self.num_heads <= 0 or self.head_dim <= 0:
            raise ValueError(f"bad geometry {self!r}")

    @classmethod
    def from_shape(cls, num_heads: int, head_dim: int, q_len: int,
                   kv_len: int, dtype="bfloat16") -> "GeometryKey":
        return cls(num_heads=int(num_heads), head_dim=int(head_dim),
                   q_bucket=seq_bucket(int(q_len)),
                   kv_bucket=seq_bucket(int(kv_len)),
                   dtype=dtype_name(dtype))

    def key_str(self) -> str:
        """Stable JSON map key / telemetry geometry label."""
        return (f"h{self.num_heads}.d{self.head_dim}.q{self.q_bucket}"
                f".kv{self.kv_bucket}.{self.dtype}")

    def shard(self, tp: int) -> "GeometryKey":
        """The PER-SHARD geometry a tp-sharded site executes: the
        Megatron column split lands on the head axis, so each shard
        runs H/tp heads of the same sequence. Table lookups and
        legality checks must key on THIS geometry — an entry tuned for
        the full H can pick blocks that are illegal (or slow) at H/tp.
        Indivisible head counts don't shard (the TP placement rules
        fall back to replication there too), so the key is unchanged.
        """
        if tp <= 1 or self.num_heads % tp:
            return self
        return dataclasses.replace(self, num_heads=self.num_heads // tp)

    @classmethod
    def from_key_str(cls, s: str) -> "GeometryKey":
        try:
            h, d, q, kv, dt = s.split(".")
            return cls(num_heads=int(h[1:]), head_dim=int(d[1:]),
                       q_bucket=int(q[1:]), kv_bucket=int(kv[2:]), dtype=dt)
        except (ValueError, IndexError):
            raise ValueError(f"malformed geometry key {s!r}") from None


@dataclasses.dataclass(frozen=True)
class KernelChoice:
    """A resolved kernel config: what ``full_attention`` should run."""

    tier: str
    block_q: Optional[int] = None      # None: tier has no blocks (xla)
    block_k: Optional[int] = None
    source: str = "default"            # default | env | table | sweep
    reason: str = ""

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(f"unknown kernel tier {self.tier!r}; "
                             f"have {TIERS}")

    def to_dict(self) -> dict:
        d = {"tier": self.tier}
        if self.block_q is not None:
            d["block_q"] = self.block_q
        if self.block_k is not None:
            d["block_k"] = self.block_k
        if self.reason:
            d["reason"] = self.reason
        return d

    @classmethod
    def from_dict(cls, d: dict, source: str = "table") -> "KernelChoice":
        return cls(tier=str(d["tier"]),
                   block_q=(int(d["block_q"]) if d.get("block_q") is not None
                            else None),
                   block_k=(int(d["block_k"]) if d.get("block_k") is not None
                            else None),
                   source=source, reason=str(d.get("reason", "")))


def validate_entry(key: GeometryKey, choice: KernelChoice) -> list[str]:
    """Legality errors for one table entry (empty = legal). The shipped
    table's tier-1 test and the CLI both run every entry through this,
    so a bad bake fails fast instead of failing in Mosaic lowering on a
    serving host."""
    from . import flash_attention as fa

    errors: list[str] = []
    itemsize = itemsize_of(key.dtype)
    H, D = key.num_heads, key.head_dim
    if choice.tier == "xla":
        if choice.block_q is not None or choice.block_k is not None:
            errors.append("xla tier takes no block sizes")
        return errors
    try:
        bq, bk = fa.resolve_flash_blocks(choice.block_q, choice.block_k)
    except ValueError as e:
        return [str(e)]
    if choice.tier == "packed":
        feas = fa._packed_feasible(H, D, bq, bk, itemsize)
        if feas is None:
            errors.append(
                f"packed tier infeasible at H={H}, D={D} ({key.dtype})")
        elif feas != (bq, bk):
            errors.append(
                f"blocks {bq}/{bk} exceed the VMEM model at H·D={H * D} "
                f"({key.dtype}); largest feasible {feas[0]}/{feas[1]}")
    elif choice.tier == "fused":
        feas = fa._fused_feasible(H * D, H, D, bq, bk, itemsize)
        if feas is None:
            errors.append(
                f"fused tier infeasible at C=H·D={H * D} ({key.dtype})")
        elif feas != (bq, bk):
            errors.append(
                f"fused blocks {bq}/{bk} exceed the VMEM model at "
                f"C=H·D={H * D} ({key.dtype}); largest feasible "
                f"{feas[0]}/{feas[1]}")
    return errors


def table_path() -> Path:
    env = constants.ATTN_TABLE.get()
    if env:
        return Path(env)
    from ..utils.compile_cache import cache_dir_default

    return Path(cache_dir_default()) / "attn_tuning.json"


class TuningTable:
    """Layered geometry → KernelChoice map.

    The shipped layer (in-repo, read-only) resolves the known model zoo;
    the local layer (next to the XLA cache) holds sweep results and
    overrides shipped entries on conflict — a fleet that re-swept a
    geometry on its own hardware generation trusts its own numbers.
    Thread-safe; persistence follows the shape-catalog contract (atomic
    tmp+rename, merge-on-save, corrupt files degrade to empty)."""

    def __init__(self, path: "Path | str | None" = None,
                 shipped: bool = True, autoload: bool = True):
        self.path = Path(path) if path is not None else table_path()
        self._lock = tracked_lock("autotune.table")
        self._shipped: dict[GeometryKey, KernelChoice] = {}
        self._local: dict[GeometryKey, KernelChoice] = {}
        if autoload:
            if shipped:
                self._shipped = self._load_file(_SHIPPED_PATH,
                                                source="table")
            self.load()

    @staticmethod
    def _load_file(path: Path, source: str) -> dict:
        raw = read_json(path)
        entries = raw.get("entries", {}) if isinstance(raw, dict) else {}
        out: dict[GeometryKey, KernelChoice] = {}
        if not isinstance(entries, dict):
            return out
        for ks, d in entries.items():
            try:
                out[GeometryKey.from_key_str(ks)] = \
                    KernelChoice.from_dict(d, source=source)
            except (KeyError, TypeError, ValueError):
                debug_log(f"attn table: skipping malformed entry "
                          f"{ks!r} in {path}")
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(set(self._shipped) | set(self._local))

    def entries(self) -> dict[GeometryKey, KernelChoice]:
        """Effective view, local overriding shipped; sorted for
        deterministic walks."""
        with self._lock:
            merged = dict(self._shipped)
            merged.update(self._local)
        return dict(sorted(merged.items()))

    def lookup(self, num_heads: int, head_dim: int, q_len: int,
               kv_len: int, dtype="bfloat16") -> Optional[KernelChoice]:
        key = GeometryKey.from_shape(num_heads, head_dim, q_len, kv_len,
                                     dtype)
        with self._lock:
            return self._local.get(key) or self._shipped.get(key)

    def get(self, key: GeometryKey) -> Optional[KernelChoice]:
        with self._lock:
            return self._local.get(key) or self._shipped.get(key)

    def record(self, key: GeometryKey, choice: KernelChoice,
               save: bool = True) -> None:
        with self._lock:
            self._local[key] = choice
        if save:
            self.save()

    # --- persistence (local layer only — shipped is read-only) -------------

    def load(self) -> int:
        """Merge the on-disk local layer into memory. In-memory entries
        win on conflict (they are newer sweeps)."""
        loaded = self._load_file(self.path, source="table")
        added = 0
        with self._lock:
            for k, v in loaded.items():
                if k not in self._local:
                    self._local[k] = v
                    added += 1
        return added

    def save(self) -> bool:
        """Merge-write the local layer (re-load first so concurrent
        sweepers union; atomic tmp+rename)."""
        self.load()
        with self._lock:
            payload = {
                "version": TABLE_VERSION,
                "entries": {k.key_str(): v.to_dict()
                            for k, v in sorted(self._local.items())},
            }
        if atomic_write_json(self.path, payload):
            return True
        debug_log(f"attn table: save to {self.path} failed")
        return False


# --- process-global default table -------------------------------------------

_default: "TuningTable | None" = None
_default_lock = tracked_lock("autotune.default")


def tuning_enabled() -> bool:
    return constants.ATTN_TUNE.get()


def default_table() -> TuningTable:
    global _default
    with _default_lock:
        if _default is None:
            _default = TuningTable()
        return _default


def reset_default_table() -> None:
    """Test isolation: drop the cached instance so env-var paths
    re-resolve."""
    global _default
    with _default_lock:
        _default = None


def lookup(num_heads: int, head_dim: int, q_len: int, kv_len: int,
           dtype="bfloat16") -> Optional[KernelChoice]:
    """Table consultation for the dispatcher: None when tuning is
    disabled, the table is empty for this geometry, or the lookup itself
    fails (a corrupt table must never take attention down)."""
    if not tuning_enabled():
        return None
    try:
        return default_table().lookup(num_heads, head_dim, q_len, kv_len,
                                      dtype)
    except Exception as e:  # noqa: BLE001 — lookup is advisory
        debug_log(f"attn table: lookup failed: {e}")
        return None


# --- sweeping ----------------------------------------------------------------

BLOCK_Q_CANDIDATES = (128, 256, 512)
BLOCK_K_CANDIDATES = (128, 256, 512, 1024)

# engagement floors measured r04 (docs/roofline.md finding 1a): below
# them XLA's fused lowering wins and the sweep doesn't bother timing
# pallas tiers — they'd be legal but pointless
_PACKED_MIN_Q = 1024
_PACKED_MIN_KV = 256
_BH_MIN_Q = 8192


def candidates_for(key: GeometryKey) -> list[KernelChoice]:
    """Deterministic candidate list for one geometry: every legal
    (tier, block_q, block_k) worth timing, xla always last (the
    baseline). Order is fixed so timed ties and dry-mode policy picks
    are reproducible."""
    from . import flash_attention as fa

    itemsize = itemsize_of(key.dtype)
    H, D = key.num_heads, key.head_dim
    out: list[KernelChoice] = []
    long_enough = (key.q_bucket >= _PACKED_MIN_Q
                   and key.kv_bucket >= _PACKED_MIN_KV)
    # fused is self-attention only (q and k/v project from the SAME x);
    # cross geometries never get fused candidates — no fusable site can
    # present them, and timing one would race an Nq×Nq problem against
    # the other tiers' Nq×Nk
    if long_enough and key.q_bucket == key.kv_bucket:
        for bq in BLOCK_Q_CANDIDATES:
            for bk in BLOCK_K_CANDIDATES:
                if fa._fused_feasible(H * D, H, D, bq, bk,
                                      itemsize) == (bq, bk):
                    out.append(KernelChoice("fused", bq, bk,
                                            source="sweep"))
        for bq in BLOCK_Q_CANDIDATES:
            for bk in BLOCK_K_CANDIDATES:
                if fa._packed_feasible(H, D, bq, bk, itemsize) == (bq, bk):
                    out.append(KernelChoice("packed", bq, bk,
                                            source="sweep"))
    if key.q_bucket >= _BH_MIN_Q or long_enough:
        for bq, bk in ((256, 512), (256, 1024), (512, 512)):
            out.append(KernelChoice("bh", bq, bk, source="sweep"))
    out.append(KernelChoice("xla", source="sweep"))
    return out


def resolve_policy_choice(key: GeometryKey) -> KernelChoice:
    """Deterministic no-timing resolution — what ``mode=\"dry\"`` sweeps
    and the shipped-table bake use. Encodes the r04/r05 measurements as
    a ranking instead of a stopwatch: fused where it fits with real
    tiles (boundary cost beats the K/V-projection recompute only when
    the working set isn't starved), else packed (VMEM-shrunk blocks
    where the native ceiling is exceeded), else the classic bh call at
    long-N, else xla. A timed sweep on hardware overrides all of this."""
    from . import flash_attention as fa

    itemsize = itemsize_of(key.dtype)
    H, D = key.num_heads, key.head_dim
    if key.q_bucket < _PACKED_MIN_Q or key.kv_bucket < _PACKED_MIN_KV:
        if key.q_bucket >= _BH_MIN_Q:
            return KernelChoice("bh", fa._DEFAULT_BLOCK_Q,
                                fa._DEFAULT_BLOCK_K, source="sweep",
                                reason="long q, short kv: streamed "
                                       "softmax memory win (r04 gate)")
        return KernelChoice("xla", source="sweep",
                            reason="below packed floors (r04: XLA fused "
                                   "lowering wins short sequences)")
    fused = (fa._fused_feasible(H * D, H, D, itemsize=itemsize)
             if key.q_bucket == key.kv_bucket else None)  # self-attn only
    if fused is not None and fused[0] >= 128 and fused[1] >= 256:
        return KernelChoice("fused", fused[0], fused[1], source="sweep",
                            reason="fused feasible with non-starved "
                                   "tiles: boundary cost > projection "
                                   "recompute")
    packed = fa._packed_feasible(H, D, itemsize=itemsize)
    if packed is not None:
        why = ("native packed layout (r04 finding 1a)"
               if H * D <= fa._PACKED_MAX_HD
               else "VMEM-shrunk packed tiles past the native H·D "
                    "ceiling (block-shrink legality path, ISSUE 8)")
        return KernelChoice("packed", packed[0], packed[1],
                            source="sweep", reason=why)
    return KernelChoice("bh", fa._DEFAULT_BLOCK_Q, fa._DEFAULT_BLOCK_K,
                        source="sweep",
                        reason="packed geometrically illegal")


def _time_candidate(key: GeometryKey, choice: KernelChoice,
                    runs: int = 3) -> float:
    """Median seconds/op of one candidate on the live backend (chained
    scan so per-op time isn't swamped by dispatch overhead)."""
    import jax
    import jax.numpy as jnp

    from . import flash_attention as fa

    dt = {"bf16": jnp.bfloat16, "f32": jnp.float32,
          "f16": jnp.float16}[key.dtype]
    H, D = key.num_heads, key.head_dim
    B, Nq, Nk = 1, key.q_bucket, key.kv_bucket
    scan_len = 8

    if choice.tier == "fused":
        C = H * D
        x = jax.random.normal(jax.random.key(0), (B, Nq, C), dt)
        ws = [jax.random.normal(jax.random.key(i), (C, C), dt) / (C ** 0.5)
              for i in (1, 2, 3)]

        def op(carry):
            o = fa.fused_qkv_attention(carry, *ws, H,
                                       block_q=choice.block_q,
                                       block_k=choice.block_k,
                                       interpret=False)
            return o.reshape(B, Nq, C)
    else:
        q = jax.random.normal(jax.random.key(0), (B, Nq, H, D), dt)
        k = jax.random.normal(jax.random.key(1), (B, Nk, H, D), dt)
        v = jax.random.normal(jax.random.key(2), (B, Nk, H, D), dt)

        if choice.tier == "xla":
            def op(carry):
                return jax.nn.dot_product_attention(carry, k, v)
        else:
            def op(carry):
                return fa.flash_attention(
                    carry, k, v, block_q=choice.block_q,
                    block_k=choice.block_k, interpret=False,
                    layout="packed" if choice.tier == "packed" else "bh")

    @jax.jit
    def run(seed, first):
        def body(carry, _):
            out = op(carry)
            return (first + out * (seed * 1e-6).astype(first.dtype)), None

        final, _ = jax.lax.scan(body, first, None, length=scan_len)
        return jnp.sum(final.astype(jnp.float32))

    first = x if choice.tier == "fused" else q
    import statistics

    float(run(jnp.float32(0.0), first))            # compile + warm
    times = []
    for i in range(runs):
        t0 = time.perf_counter()
        float(run(jnp.float32(i + 1.0), first))
        times.append(time.perf_counter() - t0)
    return statistics.median(times) / scan_len


@dataclasses.dataclass
class SweepEntry:
    key: GeometryKey
    choice: Optional[KernelChoice]
    outcome: str                  # swept | dry | cached | error
    seconds: float = 0.0
    detail: str = ""

    def to_dict(self) -> dict:
        return {"geometry": self.key.key_str(),
                "choice": self.choice.to_dict() if self.choice else None,
                "outcome": self.outcome,
                "seconds": round(self.seconds, 3),
                "detail": self.detail}


def sweep_geometry(key: GeometryKey, mode: str = "auto",
                   runs: int = 3) -> SweepEntry:
    """Resolve the best kernel config for one geometry.

    ``mode="timed"`` measures every candidate on the live backend (TPU);
    ``mode="dry"`` resolves the deterministic policy (CPU-safe, what the
    shipped table was baked with); ``mode="auto"`` picks timed on TPU,
    dry elsewhere. Per-geometry failures are recorded, never raised."""
    from .flash_attention import _on_tpu

    if mode == "auto":
        mode = "timed" if _on_tpu() else "dry"
    t0 = time.perf_counter()
    try:
        if mode == "dry":
            choice = resolve_policy_choice(key)
            return SweepEntry(key, choice, "dry",
                              time.perf_counter() - t0)
        timings = []
        for cand in candidates_for(key):
            try:
                timings.append((_time_candidate(key, cand, runs), cand))
            except Exception as e:  # noqa: BLE001 — candidate isolation
                debug_log(f"autotune: candidate {cand.tier} "
                          f"{cand.block_q}/{cand.block_k} failed on "
                          f"{key.key_str()}: {e}")
        if not timings:
            return SweepEntry(key, None, "error",
                              time.perf_counter() - t0,
                              detail="every candidate failed")
        best_t, best = min(timings, key=lambda tc: tc[0])
        best = dataclasses.replace(
            best, reason=f"timed sweep: {best_t * 1e6:.0f} us/op over "
                         f"{len(timings)} candidates")
        return SweepEntry(key, best, "swept", time.perf_counter() - t0)
    except Exception as e:  # noqa: BLE001 — sweeps must never sink warmup
        return SweepEntry(key, None, "error", time.perf_counter() - t0,
                          detail=str(e))


def ensure_tuned(geometries: Iterable[GeometryKey],
                 table: Optional[TuningTable] = None, mode: str = "auto",
                 on_entry: Optional[Callable[[SweepEntry], None]] = None
                 ) -> list[SweepEntry]:
    """Sweep every geometry not already in the table; persist winners
    once at the end (one atomic merge-write). Already-tuned geometries
    report ``cached`` — same geometry + same table ⇒ same config, no
    re-sweep, which is what keeps the tuner off the request path after
    the first boot."""
    from ..telemetry import enabled as _tm_enabled
    from ..telemetry import metrics as _tm

    if table is None:
        table = default_table()
    report: list[SweepEntry] = []
    dirty = False
    for key in sorted(set(geometries)):
        existing = table.get(key)
        if existing is not None:
            entry = SweepEntry(key, existing, "cached")
        else:
            entry = sweep_geometry(key, mode=mode)
            if entry.choice is not None:
                table.record(key, entry.choice, save=False)
                dirty = True
            if _tm_enabled():
                _tm.AUTOTUNE_SWEEP_SECONDS.observe(entry.seconds)
        report.append(entry)
        if on_entry is not None:
            on_entry(entry)
    if dirty:
        table.save()
    return report


# --- geometry derivation (warmup + CLI) --------------------------------------


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """CLI mesh shape: ``'dp4xtp2'`` / ``'tp=2'`` / ``'dp=2,tp=4'`` →
    ``{'dp': 4, 'tp': 2}``. Raises ``ValueError`` on malformed tokens."""
    import re

    axes: dict[str, int] = {}
    for tok in re.split(r"[x,]", spec.strip()):
        tok = tok.strip()
        if not tok:
            continue
        m = re.fullmatch(r"([a-z]+)=?(\d+)", tok)
        if not m:
            raise ValueError(f"malformed mesh token {tok!r} in {spec!r} "
                             "(want e.g. 'dp4xtp2' or 'tp=2')")
        axes[m.group(1)] = int(m.group(2))
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


def _cfg_heads_dim(cfg) -> tuple[int, int]:
    heads = getattr(cfg, "num_heads", None) or getattr(cfg, "heads")
    width = getattr(cfg, "dim", None) or getattr(cfg, "hidden")
    head_dim = getattr(cfg, "head_dim", None) or width // heads
    return int(heads), int(head_dim)


def geometries_for_program(bundle, key) -> list[GeometryKey]:
    """Attention geometries one catalog program (``ProgramKey``) will
    trace — what the warmup pass hands to ``ensure_tuned`` so a worker
    reports ready only once its serving geometries are tuned. Geometry
    math mirrors the model definitions (UNet level downsampling, DiT
    patchify, WAN 3D-VAE temporal compression); unknown pipeline shapes
    raise — the caller records the error per program.

    Mesh-aware: a ``tp`` axis in ``key.mesh`` divides the head counts
    (``GeometryKey.shard``) — the per-shard geometry is what the traced
    kernels execute, so THAT is what must be tuned before warmup bakes
    kernel choices into the compiled programs. ``flow_sp`` programs run
    ring attention (their collective is the kernel schedule itself, not
    a table-dispatched tier), so they contribute no table geometries."""
    out: list[GeometryKey] = []
    text_len = int(bundle.preset.text.max_len)
    if key.pipeline == "flow_sp":
        return out
    if key.pipeline == "txt2img":
        cfg = bundle.pipeline.unet.config
        dt = cfg.dtype
        lat_h, lat_w = key.height // 8, key.width // 8
        for level, depth in enumerate(cfg.transformer_depth):
            if not depth:
                continue
            tokens = (lat_h >> level) * (lat_w >> level)
            ch = cfg.model_channels * cfg.channel_mult[level]
            heads = (cfg.num_heads if cfg.num_heads > 0
                     else ch // cfg.head_dim)
            head_dim = ch // heads
            out.append(GeometryKey.from_shape(heads, head_dim, tokens,
                                              tokens, dt))
            out.append(GeometryKey.from_shape(heads, head_dim, tokens,
                                              text_len, dt))
    elif key.pipeline in ("flow_dp", "flow_tp"):
        cfg = bundle.pipeline.dit.config
        heads, head_dim = _cfg_heads_dim(cfg)
        patch = int(getattr(cfg, "patch_size", 2))
        img_tokens = (key.height // 8 // patch) * (key.width // 8 // patch)
        joint = img_tokens + text_len
        out.append(GeometryKey.from_shape(heads, head_dim, joint, joint,
                                          cfg.dtype))
    elif key.pipeline == "video_dp":
        pipeline = bundle.pipeline
        cfg = pipeline.dit.config
        heads, head_dim = _cfg_heads_dim(cfg)
        patch = getattr(cfg, "patch_size", (1, 2, 2))
        if isinstance(patch, int):
            patch = (1, patch, patch)
        pt, ph, pw = patch
        frames = key.frames or 17
        padded = frames + (-(frames - 1)) % 4     # pad_frames_4n1
        tds = int(getattr(pipeline, "temporal_downscale", 1))
        lat_f = (padded - 1) // tds + 1
        tokens = ((lat_f // pt) * (key.height // 8 // ph)
                  * (key.width // 8 // pw))
        out.append(GeometryKey.from_shape(heads, head_dim, tokens, tokens,
                                          cfg.dtype))
        out.append(GeometryKey.from_shape(heads, head_dim, tokens,
                                          text_len, cfg.dtype))
    else:
        raise ValueError(f"no geometry recipe for pipeline "
                         f"{key.pipeline!r}")
    tp = dict(key.mesh).get(constants.AXIS_TENSOR, 1) if key.mesh else 1
    if tp > 1:
        out = [g.shard(tp) for g in out]
    return out


def model_zoo_geometries() -> dict[str, GeometryKey]:
    """The known model zoo's serving geometries (docs/roofline.md r05
    table) — what the shipped table resolves and what the CLI and the
    r07 bench A/B walk. Static so baking needs no checkpoints."""
    zoo = {
        # SDXL UNet at 1024²: 64²=4096 tokens @ 10 heads × 64, 32²=1024
        # tokens @ 20 × 64, plus the 77-token cross-attention contexts
        "sdxl_self64": GeometryKey.from_shape(10, 64, 4096, 4096),
        "sdxl_self32": GeometryKey.from_shape(20, 64, 1024, 1024),
        "sdxl_cross64": GeometryKey.from_shape(10, 64, 4096, 77),
        "sdxl_cross32": GeometryKey.from_shape(20, 64, 1024, 77),
        # FLUX-12B at 1024²: 4096 image + 512 text joint tokens,
        # 24 heads × 128 (H·D = 3072 — past the native packed ceiling)
        "flux_joint": GeometryKey.from_shape(24, 128, 4608, 4608),
        # WAN-1.3B t2v 33f 480p: 14040 spatio-temporal tokens,
        # 12 heads × 128, plus the 512-token text cross-attention
        "wan_self": GeometryKey.from_shape(12, 128, 14040, 14040),
        "wan_cross": GeometryKey.from_shape(12, 128, 14040, 512),
    }
    return zoo
