"""Feathered-mask tile compositing.

Parity: the reference blends each returned tile into the working image with
a Gaussian-blurred rectangular mask and sequential alpha compositing
(``upscale/tile_ops.py:289-349``, blend order fixed at
``upscale/modes/static.py:521-553`` to stay deterministic). That design is
inherently serial. Here each tile gets a *feathered weight mask* (1 inside
its core cell, smoothstep ramp to 0 across the padding ring) and the canvas
is the weight-normalized sum of all tiles — commutative and associative, so
tiles can be produced in any order on any shard and the result is
deterministic by construction. Every pixel is in some tile's core (weight
1), so the denominator is always ≥ 1.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # avoid a runtime cycle with the tiles package
    from ..tiles.grid import TileGrid


def _ramp(n: int, start_inside: int, width: int, ascending: bool) -> np.ndarray:
    """1-D smoothstep ramp of length ``n``: reaches 1 at ``start_inside``
    (from either the left or right edge) over ``width`` pixels."""
    idx = np.arange(n, dtype=np.float32)
    d = idx - (start_inside - width) if ascending else (start_inside + width - 1) - idx
    t = np.clip(d / max(width, 1), 0.0, 1.0)
    return t * t * (3.0 - 2.0 * t)


def feather_mask(grid: "TileGrid", feather: int | None = None) -> jax.Array:
    """Per-tile weight masks [T, crop_h, crop_w, 1].

    Weight is 1 over the tile's core cell and smoothsteps to 0 across
    ``feather`` pixels of the padding ring (default: the grid padding, the
    analogue of the reference's ``mask_blur`` radius). Crop edges that
    coincide with image borders keep weight 1 (no neighbour to blend with).
    """
    f = grid.padding if feather is None else feather
    masks = np.zeros((grid.num_tiles, grid.crop_h, grid.crop_w), np.float32)
    for i, reg in enumerate(grid.regions):
        # horizontal profile
        wx = np.ones(grid.crop_w, np.float32)
        if reg.x0 > 0:  # crop's left edge is interior → ramp up into the core
            wx *= _ramp(grid.crop_w, reg.core_x0, f, ascending=True)
        if reg.x0 + grid.crop_w < grid.image_w:
            wx *= _ramp(grid.crop_w, reg.core_x0 + reg.core_w - 1, f, ascending=False)
        wy = np.ones(grid.crop_h, np.float32)
        if reg.y0 > 0:
            wy *= _ramp(grid.crop_h, reg.core_y0, f, ascending=True)
        if reg.y0 + grid.crop_h < grid.image_h:
            wy *= _ramp(grid.crop_h, reg.core_y0 + reg.core_h - 1, f, ascending=False)
        masks[i] = wy[:, None] * wx[None, :]
    return jnp.asarray(masks)[..., None]


def composite_tiles(
    tiles: jax.Array,          # [T, crop_h, crop_w, C]
    masks: jax.Array,          # [T, crop_h, crop_w, 1]
    grid: "TileGrid",
) -> jax.Array:
    """Weight-normalized scatter of tiles onto the [H, W, C] canvas.

    Origins are static Python ints, so each accumulation lowers to a
    ``dynamic_update_slice`` chain XLA can schedule freely.
    """
    C = tiles.shape[-1]
    canvas = jnp.zeros((grid.image_h, grid.image_w, C), tiles.dtype)
    weight = jnp.zeros((grid.image_h, grid.image_w, 1), tiles.dtype)
    for i, reg in enumerate(grid.regions):
        ys = slice(reg.y0, reg.y0 + grid.crop_h)
        xs = slice(reg.x0, reg.x0 + grid.crop_w)
        canvas = canvas.at[ys, xs, :].add(tiles[i] * masks[i])
        weight = weight.at[ys, xs, :].add(masks[i])
    return canvas / jnp.maximum(weight, 1e-8)


def extract_tiles(image: jax.Array, grid: "TileGrid") -> jax.Array:
    """Gather all crops of one [H, W, C] image → [T, crop_h, crop_w, C]
    (static origins; parity: ``extract_tile_with_padding``,
    ``upscale/tile_ops.py:34-155``)."""
    crops = [
        jax.lax.dynamic_slice(
            image,
            (reg.y0, reg.x0, 0),
            (grid.crop_h, grid.crop_w, image.shape[-1]),
        )
        for reg in grid.regions
    ]
    return jnp.stack(crops, axis=0)
