"""Image resize via ``jax.image.resize``.

Parity: the reference upscales with PIL LANCZOS (``upscale/tile_ops.py``,
``:34-155``) — ``lanczos3`` is the same kernel family; ``bilinear`` is the
cheap option. Runs on device, fuses with the surrounding program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_METHODS = {"bilinear", "lanczos3", "lanczos5", "nearest", "cubic"}


def upscale_image(
    images: jax.Array, scale: float, method: str = "lanczos3"
) -> jax.Array:
    """Resize [B,H,W,C] by ``scale`` (rounded to ints)."""
    if method not in _METHODS:
        raise ValueError(f"unknown resize method {method!r}; have {sorted(_METHODS)}")
    B, H, W, C = images.shape
    out_h, out_w = int(round(H * scale)), int(round(W * scale))
    out = jax.image.resize(images.astype(jnp.float32), (B, out_h, out_w, C), method=method)
    return jnp.clip(out, 0.0, 1.0) if method != "nearest" else out
