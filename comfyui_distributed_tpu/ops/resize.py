"""Image resize via ``jax.image.resize``.

Parity: the reference upscales with PIL LANCZOS (``upscale/tile_ops.py``,
``:34-155``) — ``lanczos3`` is the same kernel family; ``bilinear`` is the
cheap option. Runs on device, fuses with the surrounding program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_METHODS = {"bilinear", "lanczos3", "lanczos5", "nearest", "cubic"}
# ComfyUI workflow vocabulary → jax.image kernels (reference workflows
# carry these names in `upscale_method` inputs)
_ALIASES = {
    "nearest-exact": "nearest",
    "nearest_exact": "nearest",
    "bicubic": "cubic",
    "lanczos": "lanczos3",
    "linear": "bilinear",
    "area": "bilinear",    # closest jax kernel; area is downscale-only
}


def normalize_method(method: str) -> str:
    """Accept both jax kernel names and ComfyUI workflow values."""
    m = _ALIASES.get(method, method)
    if m not in _METHODS:
        raise ValueError(
            f"unknown resize method {method!r}; have "
            f"{sorted(_METHODS | set(_ALIASES))}")
    return m


def resize_to(images: jax.Array, height: int, width: int,
              method: str = "lanczos3") -> jax.Array:
    """Resize [B,H,W,C] to exact (height, width)."""
    m = normalize_method(method)
    B, _, _, C = images.shape
    out = jax.image.resize(images.astype(jnp.float32),
                           (B, int(height), int(width), C), method=m)
    return jnp.clip(out, 0.0, 1.0) if m != "nearest" else out


def upscale_image(
    images: jax.Array, scale: float, method: str = "lanczos3"
) -> jax.Array:
    """Resize [B,H,W,C] by ``scale`` (rounded to ints)."""
    B, H, W, C = images.shape
    return resize_to(images, int(round(H * scale)), int(round(W * scale)),
                     method)
