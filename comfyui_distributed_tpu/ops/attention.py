"""Attention ops, including sequence-parallel variants.

The reference has NO attention-level sharding (SURVEY §5.7 — its only
long-input scaling is spatial tiling); for a TPU framework long-context is
first-class: DiT models attend over ~10⁴–10⁵ image/video tokens, and a
single chip runs out of HBM long before compute. Two standard schemes:

- **Ring attention** (`ring_attention`): K/V shards rotate around the mesh
  ring via ``ppermute`` while each shard's queries accumulate
  flash-style (running max / running sum), so no shard ever materializes
  the full sequence. Communication rides ICI neighbour links.
- **Ulysses** (`ulysses_attention`): ``all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs dense local attention per head
  group, and re-shards back. Cheaper at moderate sequence lengths when
  heads divide evenly.

Both are exact (not approximations) and bitwise-stable in float32; tests
verify equality against dense attention.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from ..utils.jax_compat import axis_size as _axis_size

from ..utils import constants


def _pvary(x, axis):
    """Mark ``x`` axis-varying (jax>=0.9 renamed pvary → pcast). On
    0.4.x neither exists — there is no varying-manual-axes type system
    to satisfy (shard_map runs with check_rep off, utils/jax_compat), so
    the mark is a no-op."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis)
    return x


def _flash_min_seq() -> int:
    """Below this q length the classic pre-transposed ([B·H,N,D]) flash
    call LOSES to XLA's fused attention on TPU — measured r04
    (`scripts/mfu_probe.py forward`, SDXL 1024²: flash-bh 0.1763 s/fwd
    vs XLA 0.1677, trace shows the boundary relayout, not the kernel
    body, as the cost): at N ≤ a few K the O(N²) score matrix fits HBM
    comfortably and XLA fuses softmax into the matmuls. Reached when
    the packed-heads layout is not legal AND when a packed-legal shape
    fails the packed floors (the short-K / short-q fall-through below);
    flash-bh's win is memory at long N (ring/SP sequences, video token
    counts)."""
    return constants.FLASH_MIN_SEQ.get()


def _flash_enabled(q_len: Optional[int] = None,
                   kv_len: Optional[int] = None,
                   num_heads: Optional[int] = None,
                   head_dim: Optional[int] = None) -> bool:
    """Pallas flash attention: env-forceable; default = TPU AND the
    shape is one where flash beats XLA's fused lowering — for the
    packed-heads layout that is q ≥ 1024 with non-tiny K; for the
    classic transposed layout q ≥ 8192 (both measured r04, overridable
    via ``CDT_FLASH_MIN_SEQ[_PACKED]`` / ``CDT_FLASH_MIN_KV_PACKED``)."""
    flag = constants.FLASH_ATTENTION.get()
    if flag is not None:
        return flag
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False
    if not on_tpu:
        return False
    if q_len is None:
        return True
    from .flash_attention import _layout_packed

    if (num_heads is not None and head_dim is not None
            and _layout_packed(num_heads, head_dim, Nq=q_len, Nk=kv_len)):
        # _layout_packed is env + legality + the packed seq/KV floors —
        # the same predicate flash_attention uses for its layout choice,
        # so gate and kernel can't drift.
        return True
    # Packed illegal, or a packed-legal shape failed its floors (e.g.
    # tiny cross-attn K): the classic bh gate — at very long q the
    # memory win of the streamed softmax still applies, and
    # ``flash_attention`` makes the matching layout choice.
    return q_len >= _flash_min_seq()


# --- kernel-tier dispatch ----------------------------------------------------
# selections made at trace time, remembered for observability: the log
# line fires once per (geometry, choice), the counter feeds
# cdt_attn_kernel_selected, and selection_summary() labels pipeline
# spans so traces show which tier served each step without a profiler.

import contextlib as _contextlib
import contextvars as _contextvars
import threading as _threading

# tp shard degree of the program currently being traced: a tp-sharded
# attention site runs H/tp heads per shard, and the kernel choice must
# resolve (and legality-check) THAT geometry, not the full-H one the
# model config states. Set by the dp×tp call wrappers
# (parallel/tensor.tp_fanout_call) and the warmup pass around tracing.
_TP_SHARDS: _contextvars.ContextVar = _contextvars.ContextVar(
    "cdt_attn_tp_shards", default=1)


@_contextlib.contextmanager
def tp_shard_scope(tp: int):
    """Trace-scope marker: attention sites traced inside this scope
    resolve their tuning-table entry by PER-SHARD geometry (heads/tp).
    No-op for tp <= 1."""
    token = _TP_SHARDS.set(max(int(tp), 1))
    try:
        yield
    finally:
        _TP_SHARDS.reset(token)


def current_tp_shards() -> int:
    return _TP_SHARDS.get()

_SELECTIONS: "dict[str, str]" = {}
_SELECTIONS_LOCK = _threading.Lock()


def _note_selection(geometry: str, choice) -> None:
    desc = choice.tier
    if choice.block_q is not None:
        desc += f":{choice.block_q}/{choice.block_k}"
    with _SELECTIONS_LOCK:
        if _SELECTIONS.get(geometry) == desc:
            return
        _SELECTIONS[geometry] = desc
    from ..utils.logging import log

    why = f" ({choice.reason})" if choice.reason else ""
    log(f"attention: {geometry} → {desc} [{choice.source}]{why}")
    try:
        from ..telemetry import enabled as _tm_enabled
        from ..telemetry import metrics as _tm

        if _tm_enabled():
            _tm.ATTN_KERNEL_SELECTED.labels(
                tier=choice.tier, geometry=geometry).inc()
    except Exception:  # noqa: BLE001 — observability must not sink dispatch
        pass


def selection_summary() -> str:
    """Compact 'geometry=tier' list of every kernel choice this process
    has traced — attached to pipeline-call spans as ``attn_kernels``."""
    with _SELECTIONS_LOCK:
        return ",".join(f"{g}={d}" for g, d in sorted(_SELECTIONS.items()))


def reset_selections() -> None:
    with _SELECTIONS_LOCK:
        _SELECTIONS.clear()


def select_kernel(q_len: int, kv_len: int, num_heads: int, head_dim: int,
                  dtype="bfloat16", fusable: bool = False,
                  prefer_flash: bool = False):
    """Resolve the kernel tier + block config for one attention geometry.

    Precedence: explicit ``CDT_FLASH_ATTENTION`` > tuning table
    (``ops/autotune.py`` — the per-geometry swept winner) > env knobs
    (``CDT_FLASH_LAYOUT``/``CDT_FLASH_BLOCK_Q/K``) > measured-floor
    defaults (the r04/r05 gates in ``_flash_enabled``). Deterministic:
    same geometry + same table ⇒ same choice with no env set.

    ``fusable=True`` marks a projection→attention site with nothing in
    between (SDXL UNet self-attention) where the fused QKV tier is
    executable; elsewhere a table entry saying ``fused`` downgrades to
    the packed tier with the same blocks — same layout family, q/k/v
    just arrive pre-projected. ``prefer_flash`` (memory-constrained
    callers, see ``full_attention``) keeps its guarantee ahead of the
    table: a table entry saying ``xla`` is ignored there, because the
    sweep optimized for time while the caller needs the streamed
    softmax to fit HBM at all.

    Mesh-aware: inside a :func:`tp_shard_scope` the head count is
    divided by the tp degree BEFORE key derivation — the per-shard
    geometry (H/tp heads) is what actually executes, and a full-H table
    entry can carry blocks that are illegal (or slow) at H/tp."""
    from .autotune import KernelChoice, GeometryKey, lookup

    # ONE definition of the per-shard rule (GeometryKey.shard): sweeps,
    # table keys and this dispatch must never disagree about it
    gkey = GeometryKey.from_shape(num_heads, head_dim, q_len, kv_len,
                                  dtype).shard(current_tp_shards())
    num_heads = gkey.num_heads
    geometry = gkey.key_str()
    flag = constants.FLASH_ATTENTION.get()
    if flag is False:
        choice = KernelChoice("xla", source="env",
                              reason="CDT_FLASH_ATTENTION=0")
        _note_selection(geometry, choice)
        return choice
    forced = flag is True
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:
        on_tpu = False
    if not on_tpu and not forced:
        # off-accelerator serving always takes XLA (interpret-mode pallas
        # is a test vehicle, not a CPU fallback); not recorded — CPU
        # hosts would flood the selection log with xla lines
        return KernelChoice("xla", reason="not on TPU")

    tuned = lookup(num_heads, head_dim, q_len, kv_len, dtype)
    # a table "xla" entry yields to BOTH explicit force (=1 promised
    # flash) and prefer_flash (the sweep optimized for time; the caller
    # needs the streamed softmax to fit HBM at all)
    if tuned is not None and not ((forced or prefer_flash)
                                  and tuned.tier == "xla"):
        choice = tuned
        if choice.tier == "fused" and not fusable:
            from .autotune import itemsize_of
            from .flash_attention import _packed_feasible

            feas = _packed_feasible(num_heads, head_dim,
                                    choice.block_q, choice.block_k,
                                    itemsize_of(dtype))
            choice = KernelChoice(
                "packed" if feas else "bh",
                *(feas or (choice.block_q, choice.block_k)),
                source="table",
                reason="fused choice at a non-fusable site")
        _note_selection(geometry, choice)
        return choice

    # env knobs + measured-floor defaults (the pre-table behavior)
    from .flash_attention import _layout_packed

    if forced or prefer_flash:
        use_flash = True
        why = ("CDT_FLASH_ATTENTION=1" if forced
               else "prefer_flash (memory-constrained caller)")
    else:
        use_flash = _flash_enabled(q_len=q_len, kv_len=kv_len,
                                   num_heads=num_heads, head_dim=head_dim)
        why = "measured r04 shape gates"
    if not use_flash:
        choice = KernelChoice("xla", reason=why)
    elif _layout_packed(num_heads, head_dim, Nq=q_len, Nk=kv_len):
        # the same legality + measured-floors + CDT_FLASH_LAYOUT
        # predicate flash_attention's auto layout used, so forced-flash
        # keeps its historical layout choices
        choice = KernelChoice("packed", reason=why)
    else:
        choice = KernelChoice("bh", reason=why)
    _note_selection(geometry, choice)
    return choice


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   prefer_flash: bool = False) -> jax.Array:
    """Dense [B,N,H,D] attention dispatched per geometry: the tuning
    table's swept winner where one exists (``select_kernel`` — table >
    env knobs > measured defaults), the r04 shape gates otherwise, XLA
    off-TPU.

    ``prefer_flash=True`` skips the shape gates AND table ``xla``
    entries (still TPU-only, still overridable by an explicit
    ``CDT_FLASH_ATTENTION``): set by memory-constrained callers — the
    fp8-resident offload executor's block programs OOM'd at compile with
    XLA attention (measured r04: 16.89 GB needed vs 15.75 HBM at FLUX's
    4608 tokens × 24 heads with 12 GB of weights resident) while flash's
    streamed softmax fits."""
    B, Nq, H, D = q.shape
    choice = select_kernel(int(Nq), int(k.shape[1]), int(H), int(D),
                           dtype=q.dtype, prefer_flash=prefer_flash)
    if choice.tier == "xla":
        return jax.nn.dot_product_attention(q, k, v)
    from .flash_attention import flash_attention

    # a "fused" table entry reaching this pre-projected site runs the
    # same packed layout family (select_kernel already downgraded it)
    layout = "packed" if choice.tier == "packed" else "bh"
    return flash_attention(q, k, v, block_q=choice.block_q,
                           block_k=choice.block_k, layout=layout)


def _flash_block(q, k, v, m, l, acc, scale):
    """One K/V block accumulation step of streaming-softmax attention.

    q: [B,Nq,H,D]; k,v: [B,Nk,H,D]; m,l: [B,H,Nq]; acc: [B,Nq,H,D].
    """
    # logits [B,H,Nq,Nk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    m_new = jnp.maximum(m, s.max(axis=-1))
    corr = jnp.exp(m - m_new)                      # [B,H,Nq]
    p = jnp.exp(s - m_new[..., None])              # [B,H,Nq,Nk]
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def _ring_block() -> int:
    """K sub-block length for one ring hop's accumulation. The naive hop
    materializes [B, H, Nq, Nk_hop] fp32 logits — at video scale (e.g.
    WAN 32k tokens over 8 shards: 4k × 4k × H) that transient is the
    largest allocation in the program. Scanning the hop's K/V in
    sub-blocks bounds it at [B, H, Nq, block]; the accumulation is
    already streaming-softmax, so the identity is exact (floating-point
    round-off differs at the usual flash-blocking level). 0 disables
    sub-blocking (whole hop at once, the pre-r04 behavior)."""
    return constants.RING_BLOCK.get()


def _hop_attend(qf, k_cur, v_cur, m, l, acc, scale):
    """Accumulate one ring hop's K/V shard into the running softmax
    state, walking K sub-blocks so the logits transient stays bounded
    (`_ring_block`) for EVERY hop length — full blocks via a fori_loop
    of dynamic slices (no transposed copy of the hop shard), plus one
    remainder block when the length doesn't divide. Exact: each
    sub-block is one `_flash_block` step of the same streaming
    accumulation."""
    Nk = k_cur.shape[1]
    blk = _ring_block()
    if blk <= 0 or Nk <= blk:
        return _flash_block(qf, k_cur.astype(jnp.float32),
                            v_cur.astype(jnp.float32), m, l, acc, scale)

    def block_at(start, length):
        kb = jax.lax.dynamic_slice_in_dim(k_cur, start, length, 1)
        vb = jax.lax.dynamic_slice_in_dim(v_cur, start, length, 1)
        return kb.astype(jnp.float32), vb.astype(jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        kb, vb = block_at(i * blk, blk)
        return _flash_block(qf, kb, vb, m, l, acc, scale)

    n_full = Nk // blk
    m, l, acc = jax.lax.fori_loop(0, n_full, body, (m, l, acc))
    rem = Nk - n_full * blk
    if rem:                                    # static remainder tail
        kb, vb = block_at(n_full * blk, rem)
        m, l, acc = _flash_block(qf, kb, vb, m, l, acc, scale)
    return m, l, acc


def _collective_quant() -> "str | None":
    """Wire format for rotating K/V payloads (``CDT_COLLECTIVE_QUANT``).
    ``None`` (the default) keeps the ring bit-exact; ``"int8"`` halves
    the per-hop ICI bytes with one quantization round of error per
    payload (``parallel/overlap.quant_error_bound``). Resolved at trace
    time, like every other kernel gate."""
    mode = constants.COLLECTIVE_QUANT.get()
    return None if mode == "none" else mode


def _ring_rotate(axis: str, n_shards: int, *payloads):
    """One ring hop of the K/V payload set — (tensor, scale) pairs when
    quantized (the scale rotates with its tensor), plain tensors
    otherwise."""
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    return tuple(jax.lax.ppermute(p, axis, perm) for p in payloads)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis: str = constants.AXIS_SEQUENCE,
) -> jax.Array:
    """Exact attention with K/V sharded over ``axis``.

    Call inside ``shard_map``: every shard holds [B, N/s, H, D] of q/k/v;
    returns the local query shard's outputs [B, N/s, H, D]. The K/V pair
    makes ``s`` hops around the ring (``ppermute``) — the collective is
    already decomposed into per-block steps interleaved with the
    attention compute each arriving block unblocks, so XLA schedules
    hop ``i+1``'s neighbour transfer under hop ``i``'s FLOPs (the
    overlap schedule the fused-collective tiers borrow from here).

    Under ``CDT_COLLECTIVE_QUANT=int8`` each shard quantizes its K/V
    block ONCE and the int8 payload (+ absmax scale) rotates; every
    contribution carries exactly one quantization round
    (``absmax/254`` per element) regardless of ring length. Default is
    the bit-exact bf16/f32 ring.
    """
    n_shards = _axis_size(axis)
    B, Nq, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32)
    quant = _collective_quant()

    # initial carries must be marked axis-varying for the fori_loop carry
    # types to match (they mix with shard-varying q/k/v on step one)
    m0 = _pvary(jnp.full((B, H, Nq), -jnp.inf, jnp.float32), axis)
    l0 = _pvary(jnp.zeros((B, H, Nq), jnp.float32), axis)
    acc0 = _pvary(jnp.zeros((B, Nq, H, D), jnp.float32), axis)

    if quant == "int8":
        from ..parallel.overlap import wire_dequantize, wire_quantize

        kq, ks = wire_quantize(k)
        vq, vs = wire_quantize(v)

        def body(i, carry):
            m, l, acc, kq, ks, vq, vs = carry
            m, l, acc = _hop_attend(qf, wire_dequantize(kq, ks),
                                    wire_dequantize(vq, vs), m, l, acc,
                                    scale)
            kq, ks, vq, vs = _ring_rotate(axis, n_shards, kq, ks,
                                          vq, vs)
            return m, l, acc, kq, ks, vq, vs

        m, l, acc = jax.lax.fori_loop(
            0, n_shards, body, (m0, l0, acc0, kq, ks, vq, vs))[:3]
    else:
        def body(i, carry):
            m, l, acc, k_cur, v_cur = carry
            m, l, acc = _hop_attend(qf, k_cur, v_cur, m, l, acc, scale)
            k_nxt, v_nxt = _ring_rotate(axis, n_shards, k_cur, v_cur)
            return m, l, acc, k_nxt, v_nxt

        m, l, acc = jax.lax.fori_loop(
            0, n_shards, body, (m0, l0, acc0, k, v))[:3]
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def joint_ring_attention(
    q: jax.Array,
    txt_k: jax.Array, txt_v: jax.Array,
    img_k: jax.Array, img_v: jax.Array,
    axis: str = constants.AXIS_SEQUENCE,
) -> jax.Array:
    """Ring attention for MMDiT-style joint text+image sequences.

    Image K/V are sharded over ``axis`` and rotate around the ring; text
    K/V are short and replicated on every shard, folded in once as the
    first accumulation block (folding them per-hop would double-count).
    ``q`` may contain any mix of text/image queries — every query attends
    over the full joint sequence exactly.

    ``CDT_COLLECTIVE_QUANT=int8`` applies to the ROTATING image K/V only
    (one quantization round per payload); the replicated text block is
    never on the wire and stays exact.
    """
    n_shards = _axis_size(axis)
    B, Nq, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32)
    quant = _collective_quant()

    m0 = jnp.full((B, H, Nq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Nq), jnp.float32)
    acc0 = jnp.zeros((B, Nq, H, D), jnp.float32)
    # text block once (replicated on all shards)
    m0, l0, acc0 = _flash_block(
        qf, txt_k.astype(jnp.float32), txt_v.astype(jnp.float32),
        m0, l0, acc0, scale)
    m0 = _pvary(m0, axis)
    l0 = _pvary(l0, axis)
    acc0 = _pvary(acc0, axis)

    if quant == "int8":
        from ..parallel.overlap import wire_dequantize, wire_quantize

        kq, ks = wire_quantize(img_k)
        vq, vs = wire_quantize(img_v)

        def body(i, carry):
            m, l, acc, kq, ks, vq, vs = carry
            m, l, acc = _hop_attend(qf, wire_dequantize(kq, ks),
                                    wire_dequantize(vq, vs), m, l, acc,
                                    scale)
            kq, ks, vq, vs = _ring_rotate(axis, n_shards, kq, ks,
                                          vq, vs)
            return m, l, acc, kq, ks, vq, vs

        m, l, acc = jax.lax.fori_loop(
            0, n_shards, body, (m0, l0, acc0, kq, ks, vq, vs))[:3]
    else:
        def body(i, carry):
            m, l, acc, k_cur, v_cur = carry
            m, l, acc = _hop_attend(qf, k_cur, v_cur, m, l, acc, scale)
            k_nxt, v_nxt = _ring_rotate(axis, n_shards, k_cur, v_cur)
            return m, l, acc, k_nxt, v_nxt

        m, l, acc = jax.lax.fori_loop(
            0, n_shards, body, (m0, l0, acc0, img_k, img_v))[:3]
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis: str = constants.AXIS_SEQUENCE,
) -> jax.Array:
    """Exact attention via head redistribution.

    Inside ``shard_map`` with [B, N/s, H, D] shards: all_to_all to
    [B, N, H/s, D] (full sequence, head subset), dense local attention,
    all_to_all back. Requires ``H % axis_size == 0``.
    """
    n_shards = _axis_size(axis)
    H = q.shape[2]
    if H % n_shards:
        raise ValueError(
            f"ulysses needs heads ({H}) divisible by shards ({n_shards})")
    # [B, N/s, H, D] → [B, N, H/s, D]: split heads, concat sequence
    def to_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = full_attention(qh, kh, vh)
    return to_seq(out)
