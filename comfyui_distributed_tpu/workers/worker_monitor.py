"""Out-of-process watchdog.

Parity: reference ``workers/worker_monitor.py:41-132`` — runs as its own
process wrapping the real worker: spawns it, writes
``monitor_pid,worker_pid`` to ``CDT_PID_FILE``, polls the master PID every
2 s, and kills the worker when the master dies or on signal. Keeps orphaned
controllers from outliving a crashed master.

Standalone: importable with no package deps (it may run from a bare file
path), so liveness helpers are inlined.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

POLL_INTERVAL = float(os.environ.get("CDT_MONITOR_POLL", "2.0"))

# Telemetry is OPTIONAL here: the monitor must keep working when run from
# a bare file path with no package on sys.path (its standalone contract).
# The telemetry core is stdlib-only, so when the package IS importable
# this costs nothing extra.
try:
    from comfyui_distributed_tpu.telemetry import (enabled as _tm_enabled,
                                                   metrics as _tm)
except Exception:  # pragma: no cover — bare-file execution
    _tm = None


def _count(outcome: str) -> None:
    if _tm is not None and _tm_enabled():
        _tm.WORKER_MONITOR_CHECKS.labels(outcome=outcome).inc()


def _alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _kill_worker(proc: subprocess.Popen) -> None:
    if proc.poll() is not None:
        return
    try:
        pgid = os.getpgid(proc.pid)
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        proc.terminate()
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            proc.kill()


def monitor_and_run(argv: list[str]) -> int:
    master_pid = int(os.environ.get("CDT_MASTER_PID", "0") or 0)
    kwargs: dict = {}
    if os.name == "posix":
        kwargs["start_new_session"] = True
    proc = subprocess.Popen(argv, **kwargs)

    pid_file = os.environ.get("CDT_PID_FILE", "")
    if pid_file:
        try:
            with open(pid_file, "w", encoding="utf-8") as f:
                f.write(f"{os.getpid()},{proc.pid}")
        except OSError:
            pass

    def on_signal(signum, frame):
        _count("signal")
        _kill_worker(proc)
        sys.exit(128 + signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, on_signal)

    while True:
        code = proc.poll()
        if code is not None:
            _count("worker_exit")
            return code
        if master_pid and not _alive(master_pid):
            _count("master_died")
            print(f"[worker_monitor] master {master_pid} died; stopping worker",
                  file=sys.stderr)
            _kill_worker(proc)
            return 0
        time.sleep(POLL_INTERVAL)


if __name__ == "__main__":
    sys.exit(monitor_and_run(sys.argv[1:]))
