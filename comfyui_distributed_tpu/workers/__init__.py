"""Host-controller process management (reference L1: ``workers/``).

The reference spawns one ComfyUI process per GPU pinned via
``CUDA_VISIBLE_DEVICES`` (``workers/process/lifecycle.py:32-36``). Here a
managed process is a *host controller* serving the control plane on a port,
optionally restricted to a subset of local chips (``CDT_MESH_DEVICES``) —
on-pod chips don't need processes, but local multi-controller setups (one
controller per pod slice) and dev/test clusters do.
"""

from .detection import (  # noqa: F401
    auto_populate_hosts,
    classify_host,
    detect_environment,
    get_machine_id,
    is_local_host,
)
from .process_manager import WorkerProcessManager, get_worker_manager  # noqa: F401
