"""Process spawn/stop/liveness for managed host controllers.

Parity: reference ``workers/process/lifecycle.py`` — platform-aware Popen
(new session on Unix, ``:78-96``), watchdog wrapping when
``stop_workers_on_master_exit`` (``:67-76``), process-tree kill with
fallbacks (``:210-293``), dead-PID reaping (``:165-180``).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Optional

from ..utils.exceptions import ProcessError
from ..utils.logging import log
from ..utils.process import is_process_alive, terminate_process
from .launch_builder import build_launch_command, log_file_for


class ManagedProcess:
    def __init__(self, worker_id: str, popen: Optional[subprocess.Popen] = None,
                 pid: Optional[int] = None, log_path: Optional[Path] = None):
        self.worker_id = worker_id
        self.popen = popen
        self.pid = pid if pid is not None else (popen.pid if popen else None)
        self.log_path = log_path
        self.started_at = time.time()

    def is_alive(self) -> bool:
        if self.popen is not None:
            return self.popen.poll() is None
        return self.pid is not None and is_process_alive(self.pid)


def launch_worker_process(
    worker: dict,
    master_port: int,
    config_path: str | None = None,
    use_watchdog: bool = True,
    log_dir: Path | None = None,
) -> ManagedProcess:
    worker_id = str(worker.get("id", ""))
    if not worker_id:
        raise ProcessError("worker entry has no id")
    argv, env_overrides = build_launch_command(worker, master_port, config_path)
    if use_watchdog:
        monitor = Path(__file__).parent / "worker_monitor.py"
        argv = [sys.executable, str(monitor)] + argv
    env = {**os.environ, **env_overrides}
    log_path = log_file_for(worker_id, log_dir)
    env["CDT_LOG_FILE"] = str(log_path)

    with open(log_path, "a", encoding="utf-8") as lf:
        lf.write(
            f"\n===== launch {worker_id} at {time.strftime('%F %T')} "
            f"argv={argv} =====\n")
        lf.flush()
        kwargs: dict = {
            "stdout": lf, "stderr": subprocess.STDOUT, "env": env,
        }
        if os.name == "posix":
            kwargs["start_new_session"] = True     # own process group
        else:  # pragma: no cover - windows
            kwargs["creationflags"] = 0x08000000   # CREATE_NO_WINDOW
        try:
            popen = subprocess.Popen(argv, **kwargs)
        except OSError as e:
            raise ProcessError(f"failed to launch worker {worker_id}: {e}") from e
    log(f"launched worker {worker_id} pid={popen.pid} log={log_path}")
    return ManagedProcess(worker_id, popen, log_path=log_path)


def kill_process_tree(pid: int, grace: float = 5.0) -> bool:
    """SIGTERM the process group, escalate to SIGKILL (reference
    ``_kill_process_tree`` with psutil + taskkill/pkill fallbacks)."""
    try:
        pgid = os.getpgid(pid)
    except (ProcessLookupError, PermissionError):
        return not is_process_alive(pid)
    try:
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        pass
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not is_process_alive(pid):
            return True
        time.sleep(0.1)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        terminate_process(pid, force=True)
    time.sleep(0.2)
    return not is_process_alive(pid)
