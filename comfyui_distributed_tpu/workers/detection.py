"""Host environment detection + local/remote classification.

Parity: reference ``workers/detection.py`` — machine identity from
MAC/hostname (``:49-62``), local-vs-remote worker classification by
comparing machine IDs over ``/distributed/system_info`` (``:11-47``),
container/cloud environment detection (``:64-73``).

TPU additions: the "cloud" environments that matter here are TPU VMs and
GKE pods rather than Runpod; topology env vars published by the TPU runtime
are surfaced so the UI/auto-config can tell a single host from a pod slice.
"""

from __future__ import annotations

import os
import platform
import uuid
from pathlib import Path
from typing import Any, Optional


def get_machine_id() -> str:
    """Stable machine identity (reference ``:49-62`` — MAC + hostname)."""
    return f"{platform.node()}-{uuid.getnode():012x}"


def is_docker() -> bool:
    """Reference ``:64-68`` checks /.dockerenv and cgroup hints."""
    if Path("/.dockerenv").exists():
        return True
    try:
        return "docker" in Path("/proc/1/cgroup").read_text()
    except OSError:
        return False


def is_kubernetes() -> bool:
    return bool(os.environ.get("KUBERNETES_SERVICE_HOST"))


def tpu_environment() -> dict[str, Any]:
    """Topology hints published by the TPU runtime (the analogue of the
    reference's Runpod env probe, ``:69-73``)."""
    env = {}
    for var in ("TPU_ACCELERATOR_TYPE", "TPU_WORKER_ID",
                "TPU_WORKER_HOSTNAMES", "TPU_CHIPS_PER_HOST_BOUNDS",
                "MEGASCALE_COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"):
        if os.environ.get(var):
            env[var.lower()] = os.environ[var]
    return env


def detect_environment() -> dict[str, Any]:
    return {
        "machine_id": get_machine_id(),
        "platform": platform.system().lower(),
        "docker": is_docker(),
        "kubernetes": is_kubernetes(),
        "tpu": tpu_environment(),
    }


async def fetch_remote_machine_id(host: dict) -> Optional[str]:
    """The host's ``/distributed/system_info`` → machine_id, or None
    when unreachable (reference ``:23-40``)."""
    from ..utils.network import fetch_system_info

    info = await fetch_system_info(host)
    return info.get("machine_id") if info else None


async def is_local_host(host: dict) -> bool:
    """A host is local iff it reports this machine's identity (reference
    ``is_local_worker``, ``:11-47``). Loopback addresses short-circuit."""
    address = str(host.get("address", ""))
    if any(lb in address for lb in ("127.0.0.1", "localhost", "[::1]")):
        return True
    remote = await fetch_remote_machine_id(host)
    return remote is not None and remote == get_machine_id()


async def classify_host(host: dict) -> str:
    """'local' | 'remote' — used to decide media sync + callback URLs when
    config doesn't pin a type (reference auto-classifies the same way)."""
    declared = host.get("type")
    if declared in ("local", "remote"):
        return declared
    return "local" if await is_local_host(host) else "remote"


def auto_populate_hosts(config: dict, base_port: Optional[int] = None,
                        force: bool = False) -> bool:
    """First-launch auto-configuration (reference auto-creates one worker
    per non-master CUDA device at ports 8189+, ``web/masterDetection.js:36-100``
    guarded by ``has_auto_populated_workers``).

    TPU translation (SURVEY §5.6): chips on one host are mesh slots inside a
    single controller, so nothing is populated for a single multi-chip host.
    Only when the TPU runtime advertises *other hosts* in the slice
    (``TPU_WORKER_HOSTNAMES``) does each get a controller entry. Returns
    True when the config was modified. ``force=True`` bypasses the
    first-launch guard (the dashboard button is explicit user consent).
    """
    settings = config.setdefault("settings", {})
    if settings.get("has_auto_populated_workers") and not force:
        return False
    settings["has_auto_populated_workers"] = True

    if base_port is None:
        # slice hosts all run `serve` with defaults, i.e. on master.port
        base_port = config.get("master", {}).get("port", 8288)
    hostnames = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
                 if h.strip()]
    me = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
    hosts = config.setdefault("hosts", [])
    existing_addrs = {h.get("address") for h in hosts}
    existing_ids = {h.get("id") for h in hosts}
    for i, name in enumerate(h.strip() for h in hostnames):
        if i == me:
            continue        # this controller is the master
        address = f"{name}:{base_port}"
        if address in existing_addrs:
            continue
        hid = f"host{i}"
        while hid in existing_ids:
            hid += "_auto"
        existing_ids.add(hid)
        hosts.append({
            "id": hid,
            "name": f"TPU host {i}",
            "address": address,
            "enabled": True,
            "type": "remote",
        })
    return True             # the guard flag itself was set
