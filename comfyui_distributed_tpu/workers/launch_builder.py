"""Build the argv + env for a managed host controller.

Parity: reference ``workers/process/launch_builder.py`` — inherit the
master's relevant CLI flags, force required flags, shlex-split
``extra_args`` with a shell-metacharacter denylist (``:133-142``).
"""

from __future__ import annotations

import os
import shlex
import sys
from pathlib import Path

from ..utils.constants import LOG_DIR
from ..utils.exceptions import ProcessError

_SHELL_META = set(";&|<>`$(){}[]!*?~#\n")


def split_extra_args(extra: str) -> list[str]:
    if not extra:
        return []
    bad = _SHELL_META & set(extra)
    if bad:
        raise ProcessError(
            f"extra_args contains shell metacharacters {sorted(bad)}")
    return shlex.split(extra)


def build_launch_command(
    worker: dict,
    master_port: int,
    config_path: str | None = None,
) -> tuple[list[str], dict[str, str]]:
    """Returns (argv, env_overrides) for the controller subprocess."""
    port = worker.get("port") or _port_from_address(worker.get("address", ""))
    if not port:
        raise ProcessError(f"worker {worker.get('id')!r} has no port")
    argv = [
        sys.executable, "-m", "comfyui_distributed_tpu",
        "serve", "--port", str(port),
    ]
    argv += split_extra_args(worker.get("extra_args", ""))

    env = {
        "CDT_IS_WORKER": "1",                       # COMFYUI_IS_WORKER parity
        "CDT_WORKER_ID": str(worker.get("id", "")),
        "CDT_MASTER_PID": str(os.getpid()),         # COMFYUI_MASTER_PID parity
        "CDT_MASTER_PORT": str(master_port),
    }
    if config_path:
        env["CDT_CONFIG_PATH"] = str(config_path)
    mesh_devices = worker.get("mesh_devices", -1)
    if mesh_devices and mesh_devices > 0:
        env["CDT_MESH_DEVICES"] = str(mesh_devices)
    return argv, env


def _port_from_address(address: str) -> int | None:
    tail = address.rsplit(":", 1)
    if len(tail) == 2 and tail[1].split("/")[0].isdigit():
        return int(tail[1].split("/")[0])
    return None


def log_file_for(worker_id: str, log_dir: Path | None = None) -> Path:
    """Per-worker dated log file (reference ``lifecycle.py:41-65``)."""
    import datetime

    base = log_dir or Path(LOG_DIR.get())
    base.mkdir(parents=True, exist_ok=True)
    stamp = datetime.date.today().isoformat()
    return base / f"worker_{worker_id}_{stamp}.log"
