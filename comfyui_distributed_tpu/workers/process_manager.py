"""Worker process manager facade + persistence.

Parity: reference ``workers/process_manager.py`` (facade + lazy singleton),
``workers/process/persistence.py`` (PIDs persisted into config
``managed_processes``, restored + verified on restart), startup/cleanup
hooks from ``workers/startup.py``.
"""

from __future__ import annotations

import asyncio
import os
from pathlib import Path
from typing import Optional

from ..utils.config import load_config, update_config
from ..utils.constants import CONFIG_PATH
from ..utils.exceptions import ProcessError
from ..utils.logging import log
from ..utils.process import is_process_alive
from .lifecycle import ManagedProcess, kill_process_tree, launch_worker_process


# A worker that never self-reports ready (crash during boot) must not pin
# the launching flag forever; the dashboard falls back to the probe result.
LAUNCHING_FLAG_TTL = 180.0


class WorkerProcessManager:
    def __init__(self, config_path: Optional[Path] = None):
        self.config_path = config_path
        self._managed: dict[str, ManagedProcess] = {}
        # launching-state machine (reference: flag set at launch,
        # lifecycle.py:106; cleared by the worker's self-report through
        # POST /distributed/worker/clear_launching, api/worker_routes.py:115-139)
        self._launching: dict[str, float] = {}
        self._restore_persisted()

    # --- persistence (reference persistence.py:11-48) ----------------------

    def _restore_persisted(self) -> None:
        cfg = load_config(self.config_path)
        for wid, info in (cfg.get("managed_processes") or {}).items():
            pid = int(info.get("pid", 0) or 0)
            if pid and is_process_alive(pid):
                self._managed[wid] = ManagedProcess(
                    wid, pid=pid,
                    log_path=Path(info["log"]) if info.get("log") else None)
                log(f"restored managed worker {wid} pid={pid}")
        self._persist()

    def _persist(self) -> None:
        snapshot = {
            wid: {"pid": mp.pid, "log": str(mp.log_path) if mp.log_path else ""}
            for wid, mp in self._managed.items()
        }
        update_config(lambda c: c.update(managed_processes=snapshot),
                      self.config_path)

    # --- lifecycle ----------------------------------------------------------

    def launch_worker(self, worker_id: str) -> ManagedProcess:
        self.reap_dead()
        if worker_id in self._managed:
            raise ProcessError(f"worker {worker_id!r} already running "
                               f"(pid {self._managed[worker_id].pid})")
        cfg = load_config(self.config_path)
        worker = next(
            (h for h in cfg.get("hosts", []) if h.get("id") == worker_id), None)
        if worker is None:
            raise ProcessError(f"no configured host {worker_id!r}")
        stop_on_exit = cfg.get("settings", {}).get(
            "stop_workers_on_master_exit", True)
        mp = launch_worker_process(
            worker,
            master_port=cfg.get("master", {}).get("port", 8288),
            config_path=str(self.config_path) if self.config_path else
            CONFIG_PATH.get(),
            use_watchdog=stop_on_exit,
        )
        self._managed[worker_id] = mp
        import time

        self._launching[worker_id] = time.monotonic()
        self._persist()
        return mp

    def stop_worker(self, worker_id: str) -> bool:
        mp = self._managed.pop(worker_id, None)
        self._launching.pop(worker_id, None)
        if mp is None:
            return False
        ok = kill_process_tree(mp.pid) if mp.pid else True
        self._persist()
        log(f"stopped worker {worker_id} (pid {mp.pid}, clean={ok})")
        return True

    def clear_launching(self, worker_id: str) -> bool:
        """Worker self-reported ready; returns whether the flag was set."""
        return self._launching.pop(worker_id, None) is not None

    def is_launching(self, worker_id: str) -> bool:
        import time

        ts = self._launching.get(worker_id)
        if ts is None:
            return False
        if time.monotonic() - ts > LAUNCHING_FLAG_TTL:
            del self._launching[worker_id]
            return False
        return True

    def get_managed_workers(self) -> dict[str, dict]:
        self.reap_dead()
        return {
            wid: {"pid": mp.pid, "alive": True,
                  "log": str(mp.log_path) if mp.log_path else "",
                  "launching": self.is_launching(wid),
                  "started_at": mp.started_at}
            for wid, mp in self._managed.items()
        }

    def reap_dead(self) -> list[str]:
        """Drop entries whose process died (reference
        ``get_managed_workers`` liveness reaping, ``lifecycle.py:165-180``)."""
        dead = [wid for wid, mp in self._managed.items() if not mp.is_alive()]
        for wid in dead:
            del self._managed[wid]
            self._launching.pop(wid, None)
        if dead:
            self._persist()
        return dead

    def cleanup_all(self) -> None:
        for wid in list(self._managed):
            self.stop_worker(wid)


_manager: Optional[WorkerProcessManager] = None


def get_worker_manager(config_path: Optional[Path] = None) -> WorkerProcessManager:
    global _manager
    if _manager is None:
        _manager = WorkerProcessManager(config_path)
    return _manager


async def delayed_auto_launch(manager: WorkerProcessManager, delay: float = 2.0
                              ) -> list[str]:
    """Auto-launch enabled local workers after a settle delay (reference
    ``workers/startup.py:19-84``: clears stale managed PIDs first)."""
    await asyncio.sleep(delay)
    cfg = load_config(manager.config_path)
    if not cfg.get("settings", {}).get("auto_launch_workers"):
        return []
    launched = []
    for host in cfg.get("hosts", []):
        if host.get("enabled") and host.get("type") == "local":
            try:
                manager.launch_worker(host["id"])
                launched.append(host["id"])
            except ProcessError as e:
                log(f"auto-launch {host.get('id')} failed: {e}")
    return launched
