"""Sharded text→image pipeline — the framework's "distributed txt2img".

Reference parity (SURVEY §3.2): the reference dispatches the same workflow
to N worker processes with per-worker seed offsets and gathers PNG envelopes
over HTTP. Here the whole fan-out is ONE SPMD program: ``shard_map`` over
the ``dp`` mesh axis, per-shard ``fold_in`` of the seed (DistributedSeed
parity), per-shard sampling + VAE decode, and the sharded output array *is*
the collected batch (Collector parity) — materializing it performs the
all-gather over ICI. No serialization, no control-plane round trips inside
the step.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils.jax_compat import shard_map

from ..models.layers import timestep_embedding
from ..models.unet import UNet2D, UNetConfig
from ..models.vae import AutoencoderKL
from ..parallel.rng import participant_key
from ..utils import constants
from .guidance import cfg_denoiser, eps_denoiser
from .samplers import sample
from .schedules import (NoiseSchedule, sigmas_beta, sigmas_exponential,
                        sigmas_karras, sigmas_linear_quadratic,
                        sigmas_normal, sigmas_sgm_uniform, vp_schedule)


@dataclasses.dataclass(frozen=True)
class GenerationSpec:
    height: int = 1024
    width: int = 1024
    steps: int = 30
    sampler: str = "euler"
    scheduler: str = "karras"  # karras | normal | exponential |
    #                            sgm_uniform | beta | linear_quadratic
    guidance_scale: float = 5.0
    per_device_batch: int = 1
    denoise: float = 1.0           # <1.0: img2img partial ladder (tile engine)


def mesh_cache_key(mesh: Mesh) -> tuple:
    """Value key for a mesh: axis names + shape + device ids.

    ``id(mesh)`` is wrong here — ids are recycled after GC, so a
    long-lived controller could be handed a stale compiled fn for a
    *different* mesh with a coincident id. Shared by every pipeline's
    compile cache."""
    return (tuple(mesh.axis_names), tuple(mesh.shape.values()),
            tuple(d.id for d in mesh.devices.flat))


def cached_build(holder, key, builder, max_entries: int = 8):
    """Value-keyed compile cache shared by every pipeline/tile engine.

    ``holder`` is an object (cache lives on its ``_fn_cache`` attribute)
    or a dict (module-level caches). One definition so the eviction
    policy (FIFO at ``max_entries``) and key hygiene can't drift between
    the five call sites that used to hand-roll this."""
    cache = holder if isinstance(holder, dict) \
        else getattr(holder, "_fn_cache", None)
    if cache is None:
        cache = {}
        holder._fn_cache = cache
    fn = cache.get(key)
    if fn is None:
        if len(cache) >= max_entries:
            cache.pop(next(iter(cache)))
        fn = builder()
        cache[key] = fn
    return fn


class _AttnKernelSummary:
    """Span-attr shim: ``telemetry.spans`` stringifies attrs when the
    span CLOSES, so this resolves the kernel-selection summary after the
    wrapped call's trace has run its dispatch."""

    def __str__(self) -> str:
        from ..ops.attention import selection_summary

        return selection_summary() or "none"


def bind_weights(jitted, weights, label: "str | None" = None,
                 steps: "int | None" = None):
    """Wrap a jitted function whose LEADING argument is the weight pytree:
    the returned callable supplies it automatically, while ``.jitted`` /
    ``.weights`` expose the raw jit object for AOT use
    (``bench.py``: ``fn.jitted.lower(fn.weights, *args)``). One shared
    definition — every pipeline factory returns this shape.

    ``label`` opts the wrapper into telemetry: each call is timed to
    completion (``block_until_ready`` — callers materialize the output
    immediately anyway) and recorded as
    ``cdt_pipeline_compile_seconds{pipeline=label}`` on the first call
    (which pays trace + XLA compile) vs ``cdt_pipeline_execute_seconds``
    after; with ``steps`` the per-step quotient also lands in
    ``cdt_sampler_step_seconds``. With telemetry disabled (or no label)
    the call path is exactly the old one-liner."""
    from ..telemetry import enabled as _tm_enabled

    state = {"first": True}

    def call(*args, **kw):
        if label is None or not _tm_enabled():
            return jitted(weights, *args, **kw)
        from ..telemetry import metrics as _tm
        from ..telemetry.spans import span

        # step-time telemetry only: never feeds the program or keys
        t0 = time.perf_counter()  # cdtlint: disable=D001
        # the attn_kernels attr records which kernel tier served each
        # geometry this program traced (ops/attention.py dispatch), so
        # the trace view answers "which kernel ran this step" without a
        # profiler. Lazy: spans stringify attrs at close, AFTER the
        # first call's trace has made its selections.
        with span("pipeline_call", pipeline=label,
                  attn_kernels=_AttnKernelSummary()):
            out = jitted(weights, *args, **kw)
            jax.block_until_ready(out)
        dt = time.perf_counter() - t0  # cdtlint: disable=D001
        if state["first"]:
            state["first"] = False
            _tm.PIPELINE_COMPILE_SECONDS.labels(pipeline=label).observe(dt)
        else:
            _tm.PIPELINE_EXECUTE_SECONDS.labels(pipeline=label).observe(dt)
        if steps:
            _tm.SAMPLER_STEP_SECONDS.labels(pipeline=label).observe(
                dt / steps)
        return out

    call.jitted = jitted
    call.weights = weights
    return call


def make_sigma_ladder(spec: GenerationSpec, schedule: NoiseSchedule) -> jax.Array:
    n = max(1, round(spec.steps * spec.denoise))
    if spec.scheduler == "karras":
        smin = float(schedule.sigmas[0])
        smax = float(schedule.sigmas[-1])
        full = sigmas_karras(spec.steps, smin, smax)
    elif spec.scheduler == "normal":
        full = sigmas_normal(spec.steps, schedule)
    elif spec.scheduler == "exponential":
        full = sigmas_exponential(spec.steps, float(schedule.sigmas[0]),
                                  float(schedule.sigmas[-1]))
    elif spec.scheduler == "sgm_uniform":
        full = sigmas_sgm_uniform(spec.steps, schedule)
    elif spec.scheduler == "beta":
        full = sigmas_beta(spec.steps, schedule)
    elif spec.scheduler == "linear_quadratic":
        full = sigmas_linear_quadratic(
            spec.steps, sigma_max=float(schedule.sigmas[-1]))
    else:
        raise ValueError(f"unknown scheduler {spec.scheduler!r}")
    # partial denoise keeps the *tail* of the ladder (img2img convention)
    return full[-(n + 1):]


def sdxl_adm(
    pooled: jax.Array,
    orig_size: tuple[int, int],
    crop: tuple[int, int] = (0, 0),
    target_size: Optional[tuple[int, int]] = None,
) -> jax.Array:
    """SDXL micro-conditioning vector: pooled text ⊕ 6×256-dim Fourier
    embeddings of (orig_h, orig_w, crop_top, crop_left, tgt_h, tgt_w)."""
    target_size = target_size or orig_size
    vals = [orig_size[0], orig_size[1], crop[0], crop[1], target_size[0], target_size[1]]
    embs = [
        timestep_embedding(jnp.full((pooled.shape[0],), float(v)), 256) for v in vals
    ]
    return jnp.concatenate([pooled] + embs, axis=-1)


def inpaint_denoiser(base, src: jax.Array, noise: jax.Array,
                     mask: jax.Array):
    """ComfyUI ``KSamplerX0Inpaint`` semantics (mask: 1 = regenerate).

    Both sides of every model call are composited: the sampler *input* is
    recomposited with the source latent re-noised at the CURRENT sigma —
    using the same fixed ``noise`` draw as the run's initial noising — and
    the denoised *output* is pinned to the source in unmasked regions.
    Input-side recompositing is what keeps ancestral/SDE samplers on the
    reference trajectory near mask boundaries; output-side pinning alone
    only hides the drift for fully-unmasked pixels."""

    def denoise(xx, sigma):
        xx = xx * mask + (src + noise * sigma) * (1.0 - mask)
        return base(xx, sigma) * mask + src * (1.0 - mask)

    return denoise


class Txt2ImgPipeline:
    """Bundle of UNet + VAE + schedule with compiled sharded generation.

    ``generate_fn(mesh, spec)`` returns a jitted SPMD function
    ``(key, context, uncond_context, y, uncond_y) -> images`` where images
    is a globally-sharded ``[n_dp · per_device_batch, H, W, 3]`` array in
    [0, 1] (ComfyUI IMAGE layout, ``utils/image.py:8-24`` in the reference).
    """

    def __init__(
        self,
        unet: UNet2D,
        unet_params,
        vae: AutoencoderKL,
        schedule: NoiseSchedule | None = None,
    ):
        self.unet = unet
        self.unet_params = unet_params
        self.vae = vae
        self.schedule = schedule or vp_schedule()

    @property
    def latent_channels(self) -> int:
        return self.unet.config.in_channels

    def _weights(self, img2img: bool = False) -> dict:
        """Weight pytree passed as a jit ARGUMENT. Closing over params
        instead would embed them as lowering constants — for SDXL that is
        >5 GB serialized into the MLIR module (each leaf fetched to host
        first), which makes compilation effectively unbounded on a
        tunneled accelerator and bloats every executable."""
        w = {"unet": self.unet_params, "vae_dec": self.vae.dec_params}
        if img2img:
            w["vae_enc"] = self.vae.enc_params
        control_cfg = getattr(self, "_control", None)
        if control_cfg is not None:
            w["control"] = control_cfg[0].params
        return w

    def _denoiser(self, context, y, hint=None, weights=None):
        """``hint``: control map [B,H,W,C] when this pipeline carries a
        ControlNet (``with_control``); residuals are scaled and fed into
        the UNet's control hook every step. Under CFG's batch-dim concat
        the hint tiles to the doubled batch, so control conditions the
        cond AND uncond passes (A1111 convention). ``weights``: explicit
        param pytree (``_weights``) when called under jit."""
        control_cfg = getattr(self, "_control", None)

        def model_fn(x, t, ctx, y_):
            control = None
            if control_cfg is not None and hint is not None:
                cn, strength = control_cfg
                cn_params = (cn.params if weights is None
                             else weights["control"])
                hf = hint.astype(jnp.float32)
                if hf.shape[0] != x.shape[0]:
                    if x.shape[0] % hf.shape[0]:
                        raise ValueError(
                            f"control hint batch {hf.shape[0]} does not "
                            f"divide model batch {x.shape[0]}")
                    hf = jnp.concatenate(
                        [hf] * (x.shape[0] // hf.shape[0]), axis=0)
                down, mid = cn.model.apply(cn_params, x, t, ctx, y_, hf)
                control = ([d * strength for d in down], mid * strength)
            unet_params = (self.unet_params if weights is None
                           else weights["unet"])
            return self.unet.apply(unet_params, x, t, ctx, y_,
                                   control=control)

        return eps_denoiser(model_fn, self.schedule, context, y)

    def with_control(self, cn_bundle, strength: float = 1.0):
        """Clone carrying a ControlNet (fresh compile caches; the base
        pipeline is untouched — same discipline as LoRA patching).
        Clones are memoized per (cn uid, strength) so repeated node
        executions reuse their compiled programs."""
        import copy as _copy

        cache = getattr(self, "_control_clones", None)
        if cache is None:
            cache = self._control_clones = {}
        key = (getattr(cn_bundle, "uid", id(cn_bundle)), float(strength))
        clone = cache.get(key)
        if clone is None:
            if len(cache) >= 4:
                cache.pop(next(iter(cache)))
            clone = _copy.copy(self)
            clone._control = (cn_bundle, float(strength))
            clone._fn_cache = {}
            clone._i2i_cache = {}
            clone._control_clones = {}
            cache[key] = clone
        return clone

    def _build_sampling(self, key, context, uncond_context, y, uncond_y,
                        spec: GenerationSpec, batch: int, sigmas: jax.Array,
                        init_latent: Optional[jax.Array] = None,
                        hint: Optional[jax.Array] = None,
                        progress=None, weights=None,
                        inpaint_mask: Optional[jax.Array] = None):
        """Everything before the sampler scan: noise draw + denoiser
        closure. Returns ``(denoise, x, k_samp)``. ONE definition shared
        by the monolithic ``_sample_and_decode`` and the preemptible
        segment programs (``preemptible_fns``) — the key split, noise
        draw, and guidance wiring must be byte-for-byte the same math on
        both paths or checkpoint/resume loses bit-identity."""
        k_noise, k_samp = jax.random.split(key)
        if init_latent is None:
            lat_h = spec.height // self.vae.config.downscale
            lat_w = spec.width // self.vae.config.downscale
            noise = jax.random.normal(
                k_noise, (batch, lat_h, lat_w, self.latent_channels),
                jnp.float32,
            )
            x = noise * sigmas[0]
        else:
            noise = jax.random.normal(k_noise, init_latent.shape, jnp.float32)
            x = init_latent + noise * sigmas[0]

        if spec.guidance_scale != 1.0:
            denoise = cfg_denoiser(
                lambda ctx, yy: self._denoiser(ctx, yy, hint=hint,
                                               weights=weights),
                jnp.broadcast_to(context, (batch,) + context.shape[1:]),
                jnp.broadcast_to(uncond_context, (batch,) + uncond_context.shape[1:]),
                spec.guidance_scale,
                None if y is None else jnp.broadcast_to(y, (batch,) + y.shape[1:]),
                None if uncond_y is None else jnp.broadcast_to(uncond_y, (batch,) + uncond_y.shape[1:]),
            )
        else:
            denoise = self._denoiser(
                jnp.broadcast_to(context, (batch,) + context.shape[1:]),
                None if y is None else jnp.broadcast_to(y, (batch,) + y.shape[1:]),
                hint=hint, weights=weights,
            )
        if inpaint_mask is not None and init_latent is not None:
            denoise = inpaint_denoiser(denoise, init_latent, noise,
                                       inpaint_mask)
        if progress is not None:
            from .progress import wrap_denoiser

            denoise = wrap_denoiser(denoise, progress[0], progress[1])
        return denoise, x, k_samp

    def _sample_and_decode(self, key, context, uncond_context, y, uncond_y,
                           spec: GenerationSpec, batch: int, sigmas: jax.Array,
                           init_latent: Optional[jax.Array] = None,
                           hint: Optional[jax.Array] = None,
                           progress=None, weights=None,
                           inpaint_mask: Optional[jax.Array] = None):
        """Single-shard work: noise → sampler scan → VAE decode.

        ``init_latent`` switches to img2img: the source latent is noised
        to the (partial) ladder's head instead of starting from pure
        noise (k-diffusion img2img convention). ``hint`` feeds the
        pipeline's ControlNet (``with_control``). ``progress`` is an
        optional ``(token, shard_index)`` pair that streams per-step x0
        previews to the host (``diffusion/progress.wrap_denoiser``).
        ``inpaint_mask`` (latent-res [.,h,w,1], 1 = regenerate) applies
        ComfyUI's KSamplerX0Inpaint semantics on both sides of each model
        call: the sampler *input* is recomposited with the source latent
        re-noised at the current sigma (same fixed noise draw as the
        initial noising), and the denoised *output* is pinned to the
        source in unmasked regions — so ancestral/SDE samplers track the
        reference trajectory at mask boundaries, not just at the end."""
        x0 = self._sample_latent(
            key, context, uncond_context, y, uncond_y, spec, batch, sigmas,
            init_latent=init_latent, hint=hint, progress=progress,
            weights=weights, inpaint_mask=inpaint_mask)
        return self._decode_latent(
            x0, None if weights is None else weights["vae_dec"])

    def _sample_latent(self, key, context, uncond_context, y, uncond_y,
                       spec: GenerationSpec, batch: int, sigmas: jax.Array,
                       init_latent: Optional[jax.Array] = None,
                       hint: Optional[jax.Array] = None,
                       progress=None, weights=None,
                       inpaint_mask: Optional[jax.Array] = None):
        """The sampling half of :meth:`_sample_and_decode`: noise →
        sampler scan → final latent ``x0`` (no VAE). ONE definition for
        the fused path and the stage-split denoise programs
        (``latent_microbatch_fn``) — the split must be a pure program
        boundary, never a second copy of the math (docs/stages.md)."""
        denoise, x, k_samp = self._build_sampling(
            key, context, uncond_context, y, uncond_y, spec, batch, sigmas,
            init_latent=init_latent, hint=hint, progress=progress,
            weights=weights, inpaint_mask=inpaint_mask)
        return sample(spec.sampler, denoise, x, sigmas, key=k_samp)

    def _decode_latent(self, x0, vae_params):
        """The decode half: VAE decode + the [0,1] clip. Shared by the
        fused path, the preemptible ``fin`` program, and the decode
        pool's batched program (``decode_fn``) so the image math cannot
        drift between the serving tiers."""
        images = self.vae.decode(x0, params=vae_params)
        return jnp.clip(images / 2.0 + 0.5, 0.0, 1.0)

    def generate_fn(self, mesh: Mesh, spec: GenerationSpec,
                    axis: str = constants.AXIS_DATA,
                    progress: bool = False):
        """Compile the SPMD generator over ``mesh[axis]``.

        Every shard derives its own key via ``fold_in(key, axis_index)`` —
        shard 0 is the reference's "master", shard N its worker N
        (``nodes/utilities.py:52-75``) — then samples and decodes its own
        ``per_device_batch`` images. Output dim 0 is sharded over ``axis``
        in participant order (Collector ordering contract,
        ``nodes/collector.py:252-295``).
        """
        has_y = self.unet.config.adm_in_channels > 0
        has_control = getattr(self, "_control", None) is not None
        # ladder is built eagerly (host-side) so it's a compile-time constant
        sigmas = make_sigma_ladder(spec, self.schedule)

        def shard_body(weights, key, context, uncond_context, y, uncond_y,
                       hint=None, token=None):
            k = participant_key(key, axis)
            prog = ((token, jax.lax.axis_index(axis))
                    if token is not None else None)
            return self._sample_and_decode(
                k, context, uncond_context,
                y if has_y else None, uncond_y if has_y else None,
                spec, spec.per_device_batch, sigmas, hint=hint,
                progress=prog, weights=weights,
            )

        # weights lead the argument list (replicated pytree — P() broadcasts
        # over its leaves); passing them as arguments keeps multi-GB params
        # OUT of the lowered module (see _weights)
        # shard_body's trailing defaults (hint=None, token=None) bind the
        # shorter arities directly; only progress-WITHOUT-control needs a
        # wrapper, because there the 7th positional must skip `hint`
        per_shard = shard_body
        in_specs = (P(), P(), P(None, None, None), P(None, None, None),
                    P(None, None), P(None, None))
        if has_control and progress:
            in_specs += (P(None, None, None, None), P())
        elif has_control:
            # control hint rides as a replicated trailing argument
            in_specs += (P(None, None, None, None),)
        elif progress:
            # progress token: replicated int32 scalar, traced so one
            # compiled program serves every run
            per_shard = (lambda w, key, c, u, y_, uy, token:
                         shard_body(w, key, c, u, y_, uy, None, token))
            in_specs += (P(),)
        f = shard_map(
            per_shard, mesh=mesh, in_specs=in_specs,
            out_specs=P(axis, None, None, None),
        )
        jitted = jax.jit(f)
        weights = self._weights()

        return bind_weights(jitted, weights, label="txt2img",
                            steps=len(sigmas) - 1)

    def img2img_fn(self, mesh: Mesh, spec: GenerationSpec,
                   axis: str = constants.AXIS_DATA,
                   with_mask: bool = False):
        """Compile the SPMD img2img program over ``mesh[axis]``.

        The source batch is replicated; every shard encodes it, noises it
        at the partial ladder's head (``spec.denoise`` sets the fraction)
        with its participant-folded key, samples the tail, and decodes —
        N seed-varied edits of the same source in one step-time (the
        img2img analogue of the reference's seed-offset fan-out).

        ``with_mask`` adds a trailing image-res mask input [B,H,W,1]
        (1 = repaint): the program downsamples it to latent resolution
        and applies ComfyUI KSamplerX0Inpaint semantics on every model
        call — the sampler input is recomposited with the source latent
        re-noised at the current sigma, and the denoised output is
        pinned to the source (``inpaint_denoiser``)."""
        has_y = self.unet.config.adm_in_channels > 0
        has_control = getattr(self, "_control", None) is not None
        sigmas = make_sigma_ladder(spec, self.schedule)

        base_specs = (P(), P(None, None, None, None), P(),
                      P(None, None, None),
                      P(None, None, None), P(None, None), P(None, None))

        def shard_body(weights, images, key, context, uncond_context, y,
                       uncond_y, hint=None, mask=None):
            k = participant_key(key, axis)
            images = images.astype(jnp.float32)
            lat = self.vae.encode(images * 2.0 - 1.0,
                                  params=weights["vae_enc"])
            m = None
            if mask is not None:
                m = jax.image.resize(
                    mask.astype(jnp.float32),
                    (lat.shape[0], lat.shape[1], lat.shape[2], 1),
                    method="bilinear")
            out = self._sample_and_decode(
                k, context, uncond_context,
                y if has_y else None, uncond_y if has_y else None,
                spec, images.shape[0], sigmas, init_latent=lat,
                hint=hint, weights=weights, inpaint_mask=m,
            )
            if mask is not None:
                # pixel-level composite: the latent pinning keeps seams
                # coherent, but the VAE decoder's global mid-attention
                # still bleeds repainted content everywhere — unmasked
                # pixels must be EXACTLY the source (the final composite
                # every inpainting UI performs)
                out = images * (1.0 - mask) + out * mask
            return out

        # shard_body's trailing defaults bind the shorter arities
        # directly; mask-without-control needs a wrapper to skip `hint`
        per_shard = shard_body
        in_specs = base_specs
        if has_control:
            in_specs += (P(None, None, None, None),)
        if with_mask:
            if not has_control:
                per_shard = (lambda w, im, key, c, u, y_, uy, mask:
                             shard_body(w, im, key, c, u, y_, uy,
                                        None, mask))
            in_specs += (P(None, None, None, None),)
        f = shard_map(
            per_shard, mesh=mesh, in_specs=in_specs,
            out_specs=P(axis, None, None, None),
        )
        jitted = jax.jit(f)
        weights = self._weights(img2img=True)

        return bind_weights(jitted, weights, label="img2img",
                            steps=len(sigmas) - 1)

    def img2img(
        self,
        mesh: Mesh,
        spec: GenerationSpec,
        seed: int,
        images: jax.Array,
        context: jax.Array,
        uncond_context: jax.Array,
        y: Optional[jax.Array] = None,
        uncond_y: Optional[jax.Array] = None,
        hint: Optional[jax.Array] = None,
        mask: Optional[jax.Array] = None,
    ) -> jax.Array:
        """One-shot img2img (value-keyed compile cache). ``mask``
        [B,H,W,1] or [B,H,W] (1 = repaint) switches to inpainting."""
        if mask is not None:
            mask = jnp.asarray(mask, jnp.float32)
            if mask.ndim == 3:
                mask = mask[..., None]
        if not hasattr(self, "_i2i_cache"):
            self._i2i_cache: "dict[tuple, Any]" = {}
        key = (self._mesh_cache_key(mesh), spec, tuple(images.shape),
               None if hint is None else tuple(hint.shape),
               mask is not None)
        fn = self._i2i_cache.get(key)
        if fn is None:
            if len(self._i2i_cache) >= self._CACHE_MAX:
                self._i2i_cache.pop(next(iter(self._i2i_cache)))
            fn = self.img2img_fn(mesh, spec, with_mask=mask is not None)
            self._i2i_cache[key] = fn
        if y is None:
            adm = self.unet.config.adm_in_channels
            y = jnp.zeros((1, max(adm, 1)), jnp.float32)
        if uncond_y is None:
            uncond_y = jnp.zeros_like(y)
        args = [jnp.asarray(images, jnp.float32), jax.random.key(seed),
                context, uncond_context, y, uncond_y]
        if getattr(self, "_control", None) is not None:
            if hint is None:
                raise ValueError("pipeline carries a ControlNet but no "
                                 "hint was given")
            args.append(jnp.asarray(hint, jnp.float32))
        if mask is not None:
            args.append(mask)
        return fn(*args)

    def generate(
        self,
        mesh: Mesh,
        spec: GenerationSpec,
        seed: int,
        context: jax.Array,
        uncond_context: jax.Array,
        y: Optional[jax.Array] = None,
        uncond_y: Optional[jax.Array] = None,
        hint: Optional[jax.Array] = None,
        progress_token: Optional[int] = None,
    ) -> jax.Array:
        """Convenience one-shot generate (compiles on first distinct spec).
        ``progress_token``: a ``ProgressTracker.start`` token — enables
        per-step x0 streaming (one extra compiled variant, shared by every
        tokened run)."""
        fn = self._cached_fn(mesh, spec, hint=hint,
                             progress=progress_token is not None)
        if y is None:
            adm = self.unet.config.adm_in_channels
            y = jnp.zeros((1, max(adm, 1)), jnp.float32)
        if uncond_y is None:
            uncond_y = jnp.zeros_like(y)
        key = jax.random.key(seed)
        args = [key, context, uncond_context, y, uncond_y]
        if getattr(self, "_control", None) is not None:
            if hint is None:
                raise ValueError("pipeline carries a ControlNet but no "
                                 "hint was given")
            args.append(jnp.asarray(hint, jnp.float32))
        if progress_token is not None:
            args.append(jnp.asarray(progress_token, jnp.int32))
        return fn(*args)

    _CACHE_MAX = 8

    # back-compat alias — the shared definition lives at module level
    _mesh_cache_key = staticmethod(mesh_cache_key)

    def _cached_fn(self, mesh: Mesh, spec: GenerationSpec, hint=None,
                   progress: bool = False):
        key = (self._mesh_cache_key(mesh), spec,
               None if hint is None else tuple(hint.shape), progress)
        return cached_build(
            self, key, lambda: self.generate_fn(mesh, spec,
                                                progress=progress),
            self._CACHE_MAX)

    # --- step-granular preemption (docs/preemption.md) ----------------------

    def preemptible_fns(self, mesh: Mesh, spec: GenerationSpec,
                        axis: str = constants.AXIS_DATA):
        """The solo generator split at segment boundaries: three compiled
        SPMD pieces over the same shard math as :meth:`generate_fn` —

        - ``prep(key, ctx, unc, y, uy) -> carry``: participant key
          fold-in + noise draw + the sampler's ``init``;
        - ``seg(L)(key, ctx, unc, y, uy, start, carry) -> carry``: ``L``
          denoise steps from traced global index ``start`` (one compiled
          program per distinct length serves every offset);
        - ``fin(carry) -> images``: output-slot extract + VAE decode.

        The carry rides shard_map per the sampler contract
        (``diffusion/samplers.py``): state-shaped leaves shard over
        ``axis``, step-derived scalars replicate. Between segments the
        carry can be materialized to host numpy (a
        :class:`~..diffusion.checkpoint.LatentCheckpoint`) and resumed
        on any worker with the same dp width — bit-identically, because
        every step applies the same closure at the same global index
        (tested: ``tests/test_checkpoint.py``,
        ``tests/test_preemption.py``)."""
        from .samplers import carry_structure, extract_output, make_program
        from .samplers import run_segment as _run_segment

        key_cache = (mesh_cache_key(mesh), spec, axis)
        if not hasattr(self, "_preempt_cache"):
            self._preempt_cache: "dict[tuple, Any]" = {}
        bundle = self._preempt_cache.get(key_cache)
        if bundle is not None:
            return bundle

        has_y = self.unet.config.adm_in_channels > 0
        sigmas = make_sigma_ladder(spec, self.schedule)
        n = len(sigmas) - 1
        B = spec.per_device_batch
        lat_h = spec.height // self.vae.config.downscale
        lat_w = spec.width // self.vae.config.downscale
        x_shape = (B, lat_h, lat_w, self.latent_channels)
        x_struct = jax.ShapeDtypeStruct(x_shape, jnp.float32)
        carry_struct = carry_structure(spec.sampler, x_struct)
        carry_specs = tuple(
            P(axis, *(None,) * (len(leaf.shape) - 1))
            if tuple(leaf.shape) == x_shape else P()
            for leaf in carry_struct)
        base_specs = (P(), P(), P(None, None, None), P(None, None, None),
                      P(None, None), P(None, None))
        weights = self._weights()

        def build_program(weights, key, context, uncond, y, uy,
                          token=None):
            k = participant_key(key, axis)
            # in-trace progress rides exactly like generate_fn's token
            # variant: each denoise call streams its x0 preview — the
            # callback only OBSERVES, so bit-identity is untouched
            prog_pair = ((token, jax.lax.axis_index(axis))
                         if token is not None else None)
            denoise, x, k_samp = self._build_sampling(
                k, context, uncond,
                y if has_y else None, uy if has_y else None,
                spec, B, sigmas, progress=prog_pair, weights=weights)
            return make_program(spec.sampler, denoise, sigmas,
                                key=k_samp), x

        def prep_body(weights, key, context, uncond, y, uy):
            prog, x = build_program(weights, key, context, uncond, y, uy)
            return prog.init(x)

        prep = bind_weights(jax.jit(shard_map(
            prep_body, mesh=mesh, in_specs=base_specs,
            out_specs=carry_specs)), weights)

        def make_seg(length: int, with_token: bool):
            if with_token:
                def seg_body(weights, key, context, uncond, y, uy,
                             start, carry, token):
                    prog, _ = build_program(weights, key, context,
                                            uncond, y, uy, token=token)
                    return _run_segment(prog, tuple(carry), start,
                                        length)

                in_specs = base_specs + (P(), carry_specs, P())
            else:
                def seg_body(weights, key, context, uncond, y, uy,
                             start, carry):
                    prog, _ = build_program(weights, key, context,
                                            uncond, y, uy)
                    return _run_segment(prog, tuple(carry), start,
                                        length)

                in_specs = base_specs + (P(), carry_specs)
            return bind_weights(jax.jit(shard_map(
                seg_body, mesh=mesh, in_specs=in_specs,
                out_specs=carry_specs)), weights,
                label="txt2img_seg", steps=length)

        def fin_body(weights, carry):
            x0 = extract_output(spec.sampler, tuple(carry))
            return self._decode_latent(x0, weights["vae_dec"])

        fin = bind_weights(jax.jit(shard_map(
            fin_body, mesh=mesh, in_specs=(P(), carry_specs),
            out_specs=P(axis, None, None, None))), weights)

        segs: "dict[tuple, Any]" = {}

        def seg(length: int, with_token: bool = False):
            fn = segs.get((length, with_token))
            if fn is None:
                fn = segs[(length, with_token)] = make_seg(length,
                                                           with_token)
            return fn

        n_dp = dict(mesh.shape)[axis]
        global_shapes = tuple(
            (n_dp * B,) + tuple(leaf.shape[1:])
            if tuple(leaf.shape) == x_shape else tuple(leaf.shape)
            for leaf in carry_struct)
        bundle = {"prep": prep, "seg": seg, "fin": fin, "n_steps": n,
                  "carry_shapes": global_shapes}
        if len(self._preempt_cache) >= self._CACHE_MAX:
            self._preempt_cache.pop(next(iter(self._preempt_cache)))
        self._preempt_cache[key_cache] = bundle
        return bundle

    def checkpoint_identity(self, mesh: Mesh, spec: GenerationSpec,
                            seed: int,
                            axis: str = constants.AXIS_DATA,
                            conditioning=None) -> dict:
        """The run-identity dict a checkpoint must match to resume this
        exact trajectory (validated by ``LatentCheckpoint.validate_meta``
        — a mismatch is a restore failure, never a silent wrong image).
        ``conditioning`` (the (context, uncond, y, uy) tuple) binds the
        checkpoint to the PROMPT CONTENT: without it, a different prompt
        with coincidentally equal sampler/geometry/seed could resume
        someone else's half-denoised latent into a blended image."""
        identity = {
            "sampler": spec.sampler, "scheduler": spec.scheduler,
            "steps": int(spec.steps), "height": int(spec.height),
            "width": int(spec.width), "cfg": float(spec.guidance_scale),
            "per_device_batch": int(spec.per_device_batch),
            "seed": int(seed), "n_dp": int(dict(mesh.shape)[axis]),
        }
        if conditioning is not None:
            identity["conditioning"] = _conditioning_digest(*conditioning)
        return identity

    def generate_preemptible(
        self,
        mesh: Mesh,
        spec: GenerationSpec,
        seed: int,
        context: jax.Array,
        uncond_context: jax.Array,
        y: Optional[jax.Array] = None,
        uncond_y: Optional[jax.Array] = None,
        *,
        segment_steps: Optional[int] = None,
        should_preempt=None,
        resume=None,
        progress_token: Optional[int] = None,
    ) -> dict:
        """Run the solo generation in resumable K-step segments.

        Between segments ``should_preempt()`` is consulted (cheap host
        callback; returns a reason string or None). On preemption the
        FULL sampler carry is materialized and returned as
        ``{"checkpoint": LatentCheckpoint, "reason": str}`` — nothing is
        decoded, nothing is lost. ``resume`` restores a prior
        checkpoint (identity-validated; a mismatch raises
        :class:`~.checkpoint.CheckpointRestoreError` toward the bounded
        resume-retry machinery). Completion returns
        ``{"images": array}`` — bit-identical to :meth:`generate` for
        the same inputs, interrupted or not.

        At least one segment always runs per invocation, so a
        preempt-storm cannot live-lock a job into never advancing."""
        import numpy as np

        from ..utils import constants as _c
        from .checkpoint import CheckpointRestoreError, LatentCheckpoint

        seg_steps = max(1, int(segment_steps
                               or _c.PREEMPT_SEGMENT_STEPS.get()))
        bundle = self.preemptible_fns(mesh, spec)
        n = bundle["n_steps"]
        if y is None:
            adm = self.unet.config.adm_in_channels
            y = jnp.zeros((1, max(adm, 1)), jnp.float32)
        if uncond_y is None:
            uncond_y = jnp.zeros_like(y)
        args = (jax.random.key(seed), context, uncond_context, y, uncond_y)
        identity = self.checkpoint_identity(
            mesh, spec, seed,
            conditioning=(context, uncond_context, y, uncond_y))

        resume_t0 = None
        if resume is not None:
            resume.validate_meta(identity)
            got = tuple(tuple(np.asarray(leaf).shape)
                        for leaf in resume.carry)
            if got != bundle["carry_shapes"]:
                raise CheckpointRestoreError(
                    f"checkpoint carry shapes {got} do not match this "
                    f"program's {bundle['carry_shapes']}")
            if not 0 <= resume.step <= n:
                raise CheckpointRestoreError(
                    f"checkpoint step {resume.step} outside ladder "
                    f"0..{n}")
            # resume latency: device upload + the first segment program
            resume_t0 = time.perf_counter()  # cdtlint: disable=D001
            carry = tuple(jnp.asarray(leaf) for leaf in resume.carry)
            start = int(resume.step)
        else:
            carry = bundle["prep"](*args)
            start = 0

        done_here = 0
        while start < n:
            if done_here > 0 and should_preempt is not None:
                reason = should_preempt()
                if reason:
                    leaves = tuple(np.asarray(leaf)
                                   for leaf in jax.device_get(carry))
                    ckpt = LatentCheckpoint(
                        sampler=spec.sampler, step=start, total_steps=n,
                        carry=leaves, meta=identity)
                    return {"checkpoint": ckpt, "reason": reason,
                            "step": start}
            length = min(seg_steps, n - start)
            if progress_token is not None:
                carry = bundle["seg"](length, True)(
                    *args, jnp.int32(start), carry,
                    jnp.asarray(progress_token, jnp.int32))
            else:
                carry = bundle["seg"](length)(*args, jnp.int32(start),
                                              carry)
            # materialize: the segment boundary IS the preemption point —
            # an unbounded dispatch pipeline would make it meaningless
            jax.block_until_ready(carry)
            if resume_t0 is not None:
                from .. import telemetry
                if telemetry.enabled():
                    from ..telemetry import metrics as _tm
                    _tm.RESUME_SECONDS.observe(
                        time.perf_counter() - resume_t0)  # cdtlint: disable=D001
                resume_t0 = None
            start += length
            done_here += length
        return {"images": bundle["fin"](carry), "step": n}

    # --- near-tier trajectory reuse (cluster/cache/fleet.py) ---------------

    def near_fn(self, mesh: Mesh, spec: GenerationSpec,
                axis: str = constants.AXIS_DATA):
        """Compile the trajectory-reuse program: a replicated donor
        LATENT (a mid-trajectory sampler state from the fleet cache's
        near tier) is re-noised at the partial ladder's head with each
        shard's own participant-folded key, then the remaining tail is
        sampled and decoded. This is :meth:`img2img_fn`'s math with the
        VAE encode replaced by the donor latent — ``spec.denoise``
        (remaining/total) selects the tail. Deliberately NOT
        bit-identical to a from-scratch run: the donor state stands in
        for a clean init, and the fresh draw re-rolls the trajectory
        under the request's own seed (docs/caching.md, "Fleet tier")."""
        has_y = self.unet.config.adm_in_channels > 0
        sigmas = make_sigma_ladder(spec, self.schedule)

        def shard_body(weights, latent, key, context, uncond_context, y,
                       uncond_y):
            k = participant_key(key, axis)
            return self._sample_and_decode(
                k, context, uncond_context,
                y if has_y else None, uncond_y if has_y else None,
                spec, latent.shape[0], sigmas,
                init_latent=latent.astype(jnp.float32), weights=weights,
            )

        in_specs = (P(), P(None, None, None, None), P(),
                    P(None, None, None), P(None, None, None),
                    P(None, None), P(None, None))
        f = shard_map(shard_body, mesh=mesh, in_specs=in_specs,
                      out_specs=P(axis, None, None, None))
        return bind_weights(jax.jit(f), self._weights(),
                            label="txt2img_near",
                            steps=len(sigmas) - 1)

    def generate_near(
        self,
        mesh: Mesh,
        spec: GenerationSpec,
        seed: int,
        latent: jax.Array,
        context: jax.Array,
        uncond_context: jax.Array,
        y: Optional[jax.Array] = None,
        uncond_y: Optional[jax.Array] = None,
    ) -> jax.Array:
        """One-shot near-tier generation from a donor latent
        (value-keyed compile cache; ``spec.denoise`` must carry the
        remaining-step fraction)."""
        key = ("near", self._mesh_cache_key(mesh), spec,
               tuple(latent.shape))
        fn = cached_build(self, key,
                          lambda: self.near_fn(mesh, spec),
                          self._CACHE_MAX)
        if y is None:
            adm = self.unet.config.adm_in_channels
            y = jnp.zeros((1, max(adm, 1)), jnp.float32)
        if uncond_y is None:
            uncond_y = jnp.zeros_like(y)
        return fn(jnp.asarray(latent, jnp.float32), jax.random.key(seed),
                  context, uncond_context, y, uncond_y)

    # --- cross-request microbatching (cluster/frontdoor) -------------------

    def microbatch_fn(self, mesh: Mesh, spec: GenerationSpec,
                      n_requests: int, axis: str = constants.AXIS_DATA):
        """Compile ONE SPMD program executing ``n_requests`` independent
        generations (stacked seeds + per-request conditioning) in a single
        dispatch — the front door's cross-user microbatch.

        Bit-identity contract: each request's subgraph is the *solo*
        program's math, unrolled — per-request ``fold_in`` of its own
        seed, per-request noise draw with the solo tensor shapes, and a
        trailing concat along the batch axis. Stacking requests *inside*
        the matmul batch dimension instead (one ``[R·B, …]`` UNet call)
        is NOT used: XLA's reduction strategy changes with the batch
        extent, which breaks the bit-identical-to-solo guarantee the
        demux relies on (measured on CPU: ~1e-2 drift after 3 steps).
        The unrolled form keeps every per-request tensor shape equal to
        the solo program's, so XLA computes identical values while still
        amortizing dispatch, scheduling the independent subgraphs inside
        one executable, and emitting one sharded output.

        Output rows are shard-major then request-major then batch:
        request ``r`` occupies rows ``[i·R·B + r·B, i·R·B + (r+1)·B)`` of
        each shard block ``i`` (see :func:`demux_microbatch`).

        Only deterministic samplers are microbatchable: stochastic
        samplers draw step noise shaped by the whole batch from one key
        (``samplers.py``), which cannot reproduce N solo runs.
        """
        if spec.sampler not in DETERMINISTIC_SAMPLERS:
            raise ValueError(
                f"sampler {spec.sampler!r} is stochastic — microbatching "
                f"requires one of {sorted(DETERMINISTIC_SAMPLERS)}")
        if getattr(self, "_control", None) is not None:
            raise ValueError("microbatching does not support ControlNet "
                             "pipelines (per-request hints are not stacked)")
        has_y = self.unet.config.adm_in_channels > 0
        sigmas = make_sigma_ladder(spec, self.schedule)
        R, B = int(n_requests), spec.per_device_batch

        def shard_body(weights, seeds, contexts, uncond_contexts, ys, uys):
            outs = []
            for r in range(R):
                k = participant_key(jax.random.key(seeds[r]), axis)
                outs.append(self._sample_and_decode(
                    k, contexts[r:r + 1], uncond_contexts[r:r + 1],
                    ys[r:r + 1] if has_y else None,
                    uys[r:r + 1] if has_y else None,
                    spec, B, sigmas, weights=weights))
            return jnp.concatenate(outs, axis=0)

        f = shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P(), P(None, None, None), P(None, None, None),
                      P(None, None), P(None, None)),
            out_specs=P(axis, None, None, None),
        )
        return bind_weights(jax.jit(f), self._weights(),
                            label="txt2img_mb", steps=len(sigmas) - 1)

    def microbatch_tp_fn(self, mesh: Mesh, spec: GenerationSpec,
                         n_requests: int,
                         dp_axis: str = constants.AXIS_DATA,
                         tp_axis: str = constants.AXIS_TENSOR):
        """Mesh-tier microbatch: the SAME unrolled per-request subgraphs
        as :meth:`microbatch_fn`, executed on a dp×tp mesh — UNet
        weights shard over ``tp`` (Megatron column/row rules,
        ``parallel/tensor.py``) and the dp seed fan-out is a vmapped
        per-shard fold-in GSPMD partitions over ``dp``, so each device
        computes the solo program's local shapes while holding 1/tp of
        the weights. This is what lets a microbatched group serve models
        too large to replicate — the mesh tier as the front door's
        default placement, not a benchmark mode.

        Equivalence contract — WEAKER than :meth:`microbatch_fn`'s:
        key derivation (``fold_in(key(seed), i)`` per dp shard) and the
        unrolled per-request structure match the solo path exactly, but
        tp splits matmul contractions and the vmapped dp fan-out
        re-batches ops, both of which reassociate float sums — outputs
        track solo runs to the repo's 2e-4 sharding tolerance (f32),
        NOT bit-identically (tested:
        ``test_mesh_serving.TestMeshTierMicrobatch``). The
        content-addressed result cache stays sound because its keys
        include ``execution_signature(mesh)`` — entries never span
        placements — and ``CDT_MESH_TIER=0`` restores the bit-identical
        replicated-weights path on any mesh. Output row order matches
        :func:`demux_microbatch` (shard-major, request, batch)."""
        if spec.sampler not in DETERMINISTIC_SAMPLERS:
            raise ValueError(
                f"sampler {spec.sampler!r} is stochastic — microbatching "
                f"requires one of {sorted(DETERMINISTIC_SAMPLERS)}")
        if getattr(self, "_control", None) is not None:
            raise ValueError("microbatching does not support ControlNet "
                             "pipelines (per-request hints are not stacked)")
        from ..ops.attention import tp_shard_scope
        from ..parallel.tensor import (UNET_TP_RULES, require_tp_match,
                                       shard_params)

        has_y = self.unet.config.adm_in_channels > 0
        sigmas = make_sigma_ladder(spec, self.schedule)
        R, B = int(n_requests), spec.per_device_batch
        shape = dict(mesh.shape)
        n_dp, tp = shape[dp_axis], shape[tp_axis]
        # same fail-fast as generate_tp_fn: a model matching no rule
        # would silently serve the "tp" path fully replicated and OOM
        # as an opaque allocator error at the scale this tier exists for
        require_tp_match(self.unet_params, mesh, UNET_TP_RULES, tp_axis,
                         "unet")
        # tp-placed weights ride as committed sharded ARGUMENTS (vae/
        # norm leaves match no rule and replicate); GSPMD propagates the
        # layouts and inserts the row-parallel all-reduces. ONE sharded
        # copy per mesh, shared across every (spec, bucket) program —
        # a fresh copy per cache entry would multiply per-chip HBM by
        # the entry count on exactly the models this tier exists for
        if not hasattr(self, "_tp_weights_cache"):
            self._tp_weights_cache: "dict[tuple, Any]" = {}
        weights = cached_build(
            self._tp_weights_cache, (mesh_cache_key(mesh), tp_axis),
            lambda: shard_params(self._weights(), mesh, UNET_TP_RULES,
                                 tp_axis), 2)

        def run(weights, seeds, contexts, uncond_contexts, ys, uys):
            # traced inside the tp scope so every attention site resolves
            # its PER-SHARD (H/tp) kernel choice from the tuning table
            with tp_shard_scope(tp):
                def per_dp(i):
                    outs = []
                    for r in range(R):
                        k = jax.random.fold_in(
                            jax.random.key(seeds[r]), i)
                        outs.append(self._sample_and_decode(
                            k, contexts[r:r + 1],
                            uncond_contexts[r:r + 1],
                            ys[r:r + 1] if has_y else None,
                            uys[r:r + 1] if has_y else None,
                            spec, B, sigmas, weights=weights))
                    return jnp.concatenate(outs, axis=0)

                out = jax.vmap(per_dp)(jnp.arange(n_dp))
            out = jax.lax.with_sharding_constraint(
                out, NamedSharding(mesh, P(dp_axis, None, None, None,
                                           None)))
            return out.reshape((n_dp * R * B,) + out.shape[2:])

        return bind_weights(jax.jit(run), weights, label="txt2img_mb_tp",
                            steps=len(sigmas) - 1)

    def _stack_requests(self, seeds, contexts, uncond_contexts, ys, uys):
        """Pad a request list to the next power-of-two bucket and stack
        the per-request inputs for a microbatched program (compile-count
        bound: programs exist only for R ∈ {1, 2, 4, 8, …}; pad slots
        repeat request 0 and are dropped at demux). One definition for
        the fused and latent (stage-split) microbatch entry points."""
        R = len(seeds)
        if not (R == len(contexts) == len(uncond_contexts)):
            raise ValueError("seeds/contexts/uncond_contexts length mismatch")
        adm = self.unet.config.adm_in_channels

        def norm_y(y):
            return (jnp.zeros((1, max(adm, 1)), jnp.float32)
                    if y is None else jnp.asarray(y, jnp.float32))

        ys = [norm_y(y) for y in (ys or [None] * R)]
        uys = [norm_y(y) for y in (uys or [None] * R)]
        bucket = 1
        while bucket < R:
            bucket *= 2
        pad = bucket - R
        seeds_arr = jnp.asarray(list(seeds) + [seeds[0]] * pad, jnp.int32)
        ctx = jnp.concatenate(list(contexts) + [contexts[0]] * pad, axis=0)
        unc = jnp.concatenate(
            list(uncond_contexts) + [uncond_contexts[0]] * pad, axis=0)
        y_s = jnp.concatenate(ys + [ys[0]] * pad, axis=0)
        uy_s = jnp.concatenate(uys + [uys[0]] * pad, axis=0)
        return bucket, seeds_arr, ctx, unc, y_s, uy_s

    def _microbatch_dispatch(self, mesh, spec, seeds, contexts,
                             uncond_contexts, ys, uys, latent: bool):
        """Shared bucket/cache/route/demux core of
        :meth:`generate_microbatch` and :meth:`generate_latents`."""
        bucket, seeds_arr, ctx, unc, y_s, uy_s = self._stack_requests(
            seeds, contexts, uncond_contexts, ys, uys)
        if not hasattr(self, "_mb_cache"):
            self._mb_cache: "dict[tuple, Any]" = {}
        key = (self._mesh_cache_key(mesh), spec, bucket,
               tuple(ctx.shape[1:]), tuple(unc.shape[1:]),
               tuple(y_s.shape[1:]), latent)
        # mesh tier: a tp axis in the serving mesh routes the group to
        # the tp-sharded program (docs/parallelism.md) — same unrolled
        # subgraphs, weights sharded instead of replicated.
        # CDT_MESH_TIER=0 keeps the replicated-weights fan-out (the
        # shard_map program ignores the tp axis).
        from ..parallel.serving import mesh_tier_enabled

        tp = dict(mesh.shape).get(constants.AXIS_TENSOR, 1)
        use_tp = tp > 1 and mesh_tier_enabled()
        key += (use_tp,)
        if latent:
            build = (lambda: self.latent_microbatch_tp_fn(mesh, spec, bucket)
                     if use_tp
                     else self.latent_microbatch_fn(mesh, spec, bucket))
        else:
            build = (lambda: self.microbatch_tp_fn(mesh, spec, bucket)
                     if use_tp else self.microbatch_fn(mesh, spec, bucket))
        fn = cached_build(self._mb_cache, key, build, self._CACHE_MAX)
        out = fn(seeds_arr, ctx, unc, y_s, uy_s)
        return demux_microbatch(out, mesh, bucket,
                                spec.per_device_batch)[:len(seeds)]

    def generate_microbatch(
        self,
        mesh: Mesh,
        spec: GenerationSpec,
        seeds: "list[int]",
        contexts: "list[jax.Array]",
        uncond_contexts: "list[jax.Array]",
        ys: "list[Optional[jax.Array]] | None" = None,
        uys: "list[Optional[jax.Array]] | None" = None,
    ) -> "list[jax.Array]":
        """Execute N same-shape requests as one microbatched program and
        demux: returns one ``[n_dp · per_device_batch, H, W, 3]`` array
        per request, each bit-identical to
        ``generate(mesh, spec, seeds[r], contexts[r], …)``.

        Group size is bucketed to the next power of two (compile-count
        bound: programs exist only for R ∈ {2, 4, 8, …}); the pad slots
        repeat request 0 and their outputs are dropped at demux. Every
        request's context/uncond/y must share one shape — the front
        door's batcher sub-groups by shape before calling."""
        return self._microbatch_dispatch(mesh, spec, seeds, contexts,
                                         uncond_contexts, ys, uys,
                                         latent=False)

    # --- stage-split serving (cluster/stages, docs/stages.md) ---------------

    def latent_microbatch_fn(self, mesh: Mesh, spec: GenerationSpec,
                             n_requests: int,
                             axis: str = constants.AXIS_DATA):
        """:meth:`microbatch_fn` stopped at the final latent: the same
        unrolled per-request sampling subgraphs (same fold-in, same
        noise draw, same solo tensor shapes), NO VAE decode. This is the
        denoise pool's program in stage-split serving — the decode pool
        finishes the request with :meth:`decode_latents`, and the pair
        is bit-identical to the fused program (the PR 14 seg/fin
        precedent: a materialized program boundary on the x0 latent
        preserves every byte; tested in
        ``tests/test_stages_equivalence.py``).

        Output: ``[n_dp · R · B, lat_h, lat_w, latent_channels]`` f32,
        row order per :func:`demux_microbatch`. The weight pytree
        carries the UNet only — the decode pool holds the VAE, which is
        exactly the residency win the stage split exists for."""
        if spec.sampler not in DETERMINISTIC_SAMPLERS:
            raise ValueError(
                f"sampler {spec.sampler!r} is stochastic — microbatching "
                f"requires one of {sorted(DETERMINISTIC_SAMPLERS)}")
        if getattr(self, "_control", None) is not None:
            raise ValueError("microbatching does not support ControlNet "
                             "pipelines (per-request hints are not stacked)")
        has_y = self.unet.config.adm_in_channels > 0
        sigmas = make_sigma_ladder(spec, self.schedule)
        R, B = int(n_requests), spec.per_device_batch

        def shard_body(weights, seeds, contexts, uncond_contexts, ys, uys):
            outs = []
            for r in range(R):
                k = participant_key(jax.random.key(seeds[r]), axis)
                outs.append(self._sample_latent(
                    k, contexts[r:r + 1], uncond_contexts[r:r + 1],
                    ys[r:r + 1] if has_y else None,
                    uys[r:r + 1] if has_y else None,
                    spec, B, sigmas, weights=weights))
            return jnp.concatenate(outs, axis=0)

        f = shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P(), P(None, None, None), P(None, None, None),
                      P(None, None), P(None, None)),
            out_specs=P(axis, None, None, None),
        )
        return bind_weights(jax.jit(f), {"unet": self.unet_params},
                            label="txt2img_lat", steps=len(sigmas) - 1)

    def latent_microbatch_tp_fn(self, mesh: Mesh, spec: GenerationSpec,
                                n_requests: int,
                                dp_axis: str = constants.AXIS_DATA,
                                tp_axis: str = constants.AXIS_TENSOR):
        """Mesh-tier denoise-only microbatch: :meth:`microbatch_tp_fn`
        stopped at the final latent. Same equivalence contract as the
        fused tp program (the repo 2e-4 f32 sharding tolerance, NOT
        bit-identity — docs/parallelism.md); ``CDT_MESH_TIER=0``
        restores the bit-identical replicated path."""
        if spec.sampler not in DETERMINISTIC_SAMPLERS:
            raise ValueError(
                f"sampler {spec.sampler!r} is stochastic — microbatching "
                f"requires one of {sorted(DETERMINISTIC_SAMPLERS)}")
        if getattr(self, "_control", None) is not None:
            raise ValueError("microbatching does not support ControlNet "
                             "pipelines (per-request hints are not stacked)")
        from ..ops.attention import tp_shard_scope
        from ..parallel.tensor import (UNET_TP_RULES, require_tp_match,
                                       shard_params)

        has_y = self.unet.config.adm_in_channels > 0
        sigmas = make_sigma_ladder(spec, self.schedule)
        R, B = int(n_requests), spec.per_device_batch
        shape = dict(mesh.shape)
        n_dp, tp = shape[dp_axis], shape[tp_axis]
        require_tp_match(self.unet_params, mesh, UNET_TP_RULES, tp_axis,
                         "unet")
        if not hasattr(self, "_tp_weights_cache"):
            self._tp_weights_cache: "dict[tuple, Any]" = {}
        weights = cached_build(
            self._tp_weights_cache, (mesh_cache_key(mesh), tp_axis),
            lambda: shard_params(self._weights(), mesh, UNET_TP_RULES,
                                 tp_axis), 2)

        def run(weights, seeds, contexts, uncond_contexts, ys, uys):
            with tp_shard_scope(tp):
                def per_dp(i):
                    outs = []
                    for r in range(R):
                        k = jax.random.fold_in(
                            jax.random.key(seeds[r]), i)
                        outs.append(self._sample_latent(
                            k, contexts[r:r + 1],
                            uncond_contexts[r:r + 1],
                            ys[r:r + 1] if has_y else None,
                            uys[r:r + 1] if has_y else None,
                            spec, B, sigmas, weights=weights))
                    return jnp.concatenate(outs, axis=0)

                out = jax.vmap(per_dp)(jnp.arange(n_dp))
            out = jax.lax.with_sharding_constraint(
                out, NamedSharding(mesh, P(dp_axis, None, None, None,
                                           None)))
            return out.reshape((n_dp * R * B,) + out.shape[2:])

        return bind_weights(jax.jit(run), weights,
                            label="txt2img_lat_tp",
                            steps=len(sigmas) - 1)

    def generate_latents(
        self,
        mesh: Mesh,
        spec: GenerationSpec,
        seeds: "list[int]",
        contexts: "list[jax.Array]",
        uncond_contexts: "list[jax.Array]",
        ys: "list[Optional[jax.Array]] | None" = None,
        uys: "list[Optional[jax.Array]] | None" = None,
    ) -> "list[jax.Array]":
        """:meth:`generate_microbatch` for the stage-split denoise pool:
        one ``[n_dp · per_device_batch, lat_h, lat_w, C]`` latent per
        request, each carrying exactly the bytes the fused program would
        have fed its VAE. Feed the results (possibly coalesced across
        groups) to :meth:`decode_latents`."""
        return self._microbatch_dispatch(mesh, spec, seeds, contexts,
                                         uncond_contexts, ys, uys,
                                         latent=True)

    def decode_fn(self, mesh: Mesh, n_items: int,
                  axis: str = constants.AXIS_DATA):
        """Compile ONE batched VAE decode program: ``n_items`` latents
        (stacked on a leading axis, each ``[n_dp · B, h, w, C]``) decode
        as unrolled per-item subgraphs — per-shard shapes equal to the
        fused program's decode, so the images are bit-identical to the
        fused path while the decode pool amortizes one program over
        every concurrent request in the shape bucket
        (docs/stages.md)."""

        def shard_body(weights, lats):
            # lats per shard: [R, B, h, w, C]; each item decodes at the
            # solo shape — stacking into the conv batch dim instead
            # would reassociate reductions (the microbatch_fn lesson)
            outs = [self._decode_latent(lats[r], weights["vae_dec"])
                    for r in range(int(n_items))]
            return jnp.concatenate(outs, axis=0)

        f = shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P(None, axis, None, None, None)),
            out_specs=P(axis, None, None, None),
        )
        return bind_weights(jax.jit(f), {"vae_dec": self.vae.dec_params},
                            label="vae_decode_batch")

    def decode_latents(self, mesh: Mesh, latents: "list",
                       per_device_batch: "int | None" = None) -> "list":
        """Decode N final latents (any mix of requests sharing one shape
        bucket) in one batched program; returns one image array per
        latent, bit-identical to the fused path's decode of the same
        bytes. Batch count is bucketed to the next power of two (pad
        repeats item 0, dropped at demux) so compile count stays
        bounded however the decode pool's windows land."""
        R = len(latents)
        if R == 0:
            return []
        lats = [jnp.asarray(lat, jnp.float32) for lat in latents]
        first = tuple(lats[0].shape)
        for lat in lats[1:]:
            if tuple(lat.shape) != first:
                raise ValueError(
                    f"decode batch mixes latent shapes {first} and "
                    f"{tuple(lat.shape)} — bucket by shape first")
        n_dp = dict(mesh.shape)[constants.AXIS_DATA]
        if first[0] % n_dp:
            raise ValueError(
                f"latent rows {first[0]} not divisible by mesh dp width "
                f"{n_dp}")
        B = (first[0] // n_dp if per_device_batch is None
             else int(per_device_batch))
        bucket = 1
        while bucket < R:
            bucket *= 2
        stacked = jnp.stack(lats + [lats[0]] * (bucket - R), axis=0)
        if not hasattr(self, "_dec_cache"):
            self._dec_cache: "dict[tuple, Any]" = {}
        key = (self._mesh_cache_key(mesh), bucket, first)
        fn = cached_build(self._dec_cache, key,
                          lambda: self.decode_fn(mesh, bucket),
                          self._CACHE_MAX)
        out = fn(stacked)
        return demux_microbatch(out, mesh, bucket, B)[:R]


# samplers whose trajectory is a pure function of (noise, conditioning):
# their compiled step never consumes the sampling key, so N solo runs can
# be replayed exactly inside one microbatched program. The stochastic
# families (euler_ancestral, lcm, dpmpp_sde, ddim with eta>0) draw
# batch-shaped step noise from a single key and are excluded.
DETERMINISTIC_SAMPLERS = frozenset({"euler", "heun", "dpmpp_2m", "ddim"})


def _conditioning_digest(*arrays) -> str:
    """Content digest of a conditioning tuple (shape + dtype + bytes per
    tensor; None slots pinned) — the checkpoint-identity component that
    ties a parked latent to its PROMPT, not just its geometry."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for a in arrays:
        if a is None:
            h.update(b"|none")
            continue
        arr = np.asarray(a)
        h.update(f"|{arr.shape}:{arr.dtype}:".encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def demux_microbatch(out: jax.Array, mesh: Mesh, n_requests: int,
                     per_device_batch: int,
                     axis: str = constants.AXIS_DATA) -> "list[jax.Array]":
    """Split a microbatched program's output back into per-request arrays
    matching each request's solo output row order (shard-major, batch-
    minor — the Collector ordering contract ``generate_fn`` documents)."""
    n_dp = dict(mesh.shape)[axis]
    R, B = int(n_requests), int(per_device_batch)
    if out.shape[0] != n_dp * R * B:
        raise ValueError(
            f"microbatch output has {out.shape[0]} rows, expected "
            f"n_dp({n_dp}) · R({R}) · B({B}) = {n_dp * R * B}")
    per_request = []
    for r in range(R):
        blocks = [out[i * R * B + r * B: i * R * B + (r + 1) * B]
                  for i in range(n_dp)]
        per_request.append(jnp.concatenate(blocks, axis=0))
    return per_request
