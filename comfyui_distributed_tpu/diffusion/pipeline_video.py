"""Text→video pipeline (WAN-class) with dp fan-out and frame sharding.

Parity targets (BASELINE): ``distributed-wan-2.2_14b_t2v.json`` — the
reference generates one video per worker with seed offsets and divides
frame batches afterwards (``ImageBatchDivider``); here:

- ``generate_fn``: dp fan-out — n seed-varied videos in one program;
- ``generate_frames_fn``: ONE video's frames sharded over ``sp`` (ring
  attention over the spatio-temporal token sequence) — single-video
  latency scaling the reference cannot express.

VAE: frames are encoded/decoded per-frame with the image AutoencoderKL
(vmapped over F). A causal temporal VAE (real WAN) slots in behind the
same interface later; the 4n+1 frame rule helpers live in
``models/video_dit.py``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.vae import AutoencoderKL
from ..models.video_dit import VideoDiT, pad_frames_4n1
from ..parallel.rng import participant_key
from ..utils import constants
from .samplers import sample
from .schedules import sigmas_flow


@dataclasses.dataclass(frozen=True)
class VideoSpec:
    frames: int = 17               # will be padded to 4n+1
    height: int = 480
    width: int = 832
    steps: int = 20
    shift: float = 3.0
    guidance_scale: float = 1.0    # CFG (WAN uses real CFG, not distilled)
    sampler: str = "euler"

    @property
    def padded_frames(self) -> int:
        return pad_frames_4n1(self.frames)


class VideoPipeline:
    def __init__(self, dit: VideoDiT, dit_params, vae: AutoencoderKL):
        self.dit = dit
        self.dit_params = dit_params
        self.vae = vae

    def decode_frames(self, latents: jax.Array) -> jax.Array:
        """[B,F,h,w,c] → [B,F,H,W,3] via per-frame VAE decode."""
        B, F = latents.shape[:2]
        flat = latents.reshape((B * F,) + latents.shape[2:])
        frames = self.vae.decode(flat)
        frames = jnp.clip(frames / 2.0 + 0.5, 0.0, 1.0)
        return frames.reshape((B, F) + frames.shape[1:])

    def _denoiser(self, context, pooled, guidance_scale, sp_axis=None):
        def model_call(x, sigma, ctx, pl):
            t = jnp.broadcast_to(sigma, (x.shape[0],))
            v = self.dit.apply(self.dit_params, x, t, ctx, pl, sp_axis=sp_axis)
            return x - sigma * v

        if guidance_scale == 1.0:
            return lambda x, s: model_call(x, s, context, pooled)

        uncond_ctx = jnp.zeros_like(context)
        uncond_pl = jnp.zeros_like(pooled)

        def denoise(x, sigma):
            x2 = jnp.concatenate([x, x], axis=0)
            ctx2 = jnp.concatenate([context, uncond_ctx], axis=0)
            pl2 = jnp.concatenate([pooled, uncond_pl], axis=0)
            out = model_call(x2, sigma, ctx2, pl2)
            cond, uncond = jnp.split(out, 2, axis=0)
            return uncond + guidance_scale * (cond - uncond)

        return denoise

    def generate_fn(self, mesh: Mesh, spec: VideoSpec,
                    axis: str = constants.AXIS_DATA):
        """dp fan-out: each shard samples a full (seed-varied) video."""
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        F = spec.padded_frames
        lat = (F, spec.height // ds, spec.width // ds, self.dit.config.in_channels)

        def per_shard(key, context, pooled):
            k = participant_key(key, axis)
            x = jax.random.normal(k, (1,) + lat, jnp.float32)
            den = self._denoiser(context, pooled, spec.guidance_scale)
            x0 = sample(spec.sampler, den, x, sigmas, key=k)
            return self.decode_frames(x0)

        f = jax.shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(), P(None, None, None), P(None, None)),
            out_specs=P(axis, None, None, None, None),
        )
        return jax.jit(f)

    def generate(self, mesh: Mesh, spec: VideoSpec, seed: int,
                 context: jax.Array, pooled: jax.Array) -> jax.Array:
        return self.generate_fn(mesh, spec)(jax.random.key(seed), context, pooled)

    def generate_frames_fn(self, mesh: Mesh, spec: VideoSpec,
                           axis: str = constants.AXIS_SEQUENCE):
        """ONE video, frame blocks sharded over ``axis``; joint ring
        attention spans the full spatio-temporal sequence so motion stays
        globally coherent (this is exact attention, not windowed)."""
        n_sh = mesh.shape[axis]
        F = spec.padded_frames
        if F % n_sh:
            raise ValueError(
                f"padded frame count {F} must divide over {n_sh} shards "
                f"(choose frames so that 4n+1 ≡ 0 mod shards)")
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        lat_h, lat_w = spec.height // ds, spec.width // ds
        c = self.dit.config.in_channels
        per = F // n_sh

        def per_shard(key, context, pooled):
            idx = jax.lax.axis_index(axis)
            full = jax.random.normal(key, (1, F, lat_h, lat_w, c), jnp.float32)
            x = jax.lax.dynamic_slice_in_dim(full, idx * per, per, axis=1)
            den = self._denoiser(context, pooled, spec.guidance_scale,
                                 sp_axis=axis)
            return sample(spec.sampler, den, x, sigmas, key=key)

        f = jax.shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(), P(None, None, None), P(None, None)),
            out_specs=P(None, axis, None, None, None),
            check_vma=False,
        )

        def run(key, context, pooled):
            latents = f(key, context, pooled)
            return self.decode_frames(latents)

        return jax.jit(run)
