"""Text→video pipeline (WAN-class) with dp fan-out and frame sharding.

Parity targets (BASELINE): ``distributed-wan-2.2_14b_t2v.json`` — the
reference generates one video per worker with seed offsets and divides
frame batches afterwards (``ImageBatchDivider``); here:

- ``generate_fn``: dp fan-out — n seed-varied videos in one program;
- ``generate_frames_fn``: ONE video's frames sharded over ``sp`` (ring
  attention over the spatio-temporal token sequence) — single-video
  latency scaling the reference cannot express.

VAE: either the image ``AutoencoderKL`` applied per frame, or the
WAN-geometry 3D causal VAE (``models/wan_vae.WanVAE3D``) — with the 3D
VAE the DiT runs on a 4×-shorter latent frame axis (the 4n+1 rule's
origin), a direct transformer-sequence reduction. The 4n+1 frame rule
helpers live in ``models/video_dit.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map

from ..models.vae import AutoencoderKL
from ..models.video_dit import VideoDiT, pad_frames_4n1
from ..parallel.rng import participant_key
from ..utils import constants
from .pipeline import bind_weights
from .samplers import sample
from .schedules import sigmas_flow


@dataclasses.dataclass(frozen=True)
class VideoSpec:
    frames: int = 17               # will be padded to 4n+1
    height: int = 480
    width: int = 832
    steps: int = 20
    shift: float = 3.0
    guidance_scale: float = 1.0    # CFG (WAN uses real CFG, not distilled)
    sampler: str = "euler"

    @property
    def padded_frames(self) -> int:
        return pad_frames_4n1(self.frames)


class VideoPipeline:
    """``dit_params_low``/``expert_boundary`` enable WAN-2.2-style
    dual-expert (MoE) sampling: the published 14B t2v/i2v models are TWO
    DiTs — a high-noise expert for timesteps ≥ boundary·1000 and a
    low-noise expert below (t2v boundary 0.875, i2v 0.9). The sigma
    ladder splits at the boundary and each segment runs its expert's
    weights — two clean sampler scans, the XLA-friendly form of
    ComfyUI's two-KSampler-pass graph (no weight-sized ``lax.cond``)."""

    def __init__(self, dit: VideoDiT, dit_params, vae: AutoencoderKL,
                 dit_params_low=None, expert_boundary: Optional[float] = None):
        self.dit = dit
        self.dit_params = dit_params
        self.dit_params_low = dit_params_low
        self.expert_boundary = expert_boundary
        self.vae = vae

    @property
    def is_moe(self) -> bool:
        return (self.dit_params_low is not None
                and self.expert_boundary is not None)

    def _expert_split(self, sigmas) -> int:
        """Number of leading sampler steps the HIGH-noise expert takes:
        a step is 'high' when its current sigma ≥ boundary (flow sigmas
        ARE normalized timesteps: sigma = t/1000)."""
        import numpy as np

        cur = np.asarray(sigmas)[:-1]            # per-step current sigmas
        return int(np.sum(cur >= self.expert_boundary))

    @staticmethod
    def _progress_den(build_den, token, shard_index):
        """Shared progress interposition for every generate_* factory:
        ``build_den(params) -> denoiser``, wrapped with the traced token
        when progress is on — one definition so the token plumbing can't
        drift between the four execution modes."""
        def make_den(params):
            den = build_den(params)
            if token is not None:
                from .progress import wrap_denoiser

                den = wrap_denoiser(den, token, shard_index)
            return den

        return make_den

    def _sample_expert(self, spec: "VideoSpec", make_den, x, sigmas, key,
                       weights):
        """Run the sampler with expert switching. ``make_den(params)``
        builds the (possibly progress-wrapped) denoiser for one expert's
        weights; single-expert pipelines take one scan as before."""
        if not self.is_moe:
            return sample(spec.sampler, make_den(weights["dit"]), x,
                          sigmas, key=key)
        split = self._expert_split(sigmas)
        steps = int(sigmas.shape[0]) - 1
        if split <= 0:
            return sample(spec.sampler, make_den(weights["dit_low"]), x,
                          sigmas, key=key)
        if split >= steps:
            return sample(spec.sampler, make_den(weights["dit"]), x,
                          sigmas, key=key)
        x_mid = sample(spec.sampler, make_den(weights["dit"]), x,
                       sigmas[: split + 1], key=key)
        # distinct fold for the low segment so ancestral samplers never
        # reuse the high segment's noise draws
        return sample(spec.sampler, make_den(weights["dit_low"]), x_mid,
                      sigmas[split:], key=jax.random.fold_in(key, 0x10E))

    @property
    def temporal_downscale(self) -> int:
        return getattr(self.vae.config, "temporal_downscale", 1)

    def latent_frames(self, spec: "VideoSpec") -> int:
        """DiT frame-axis length: padded pixel frames compressed by the
        VAE's temporal factor (1 for the per-frame image VAE)."""
        return (spec.padded_frames - 1) // self.temporal_downscale + 1

    def _weights(self) -> dict:
        """Explicit jit-argument weight pytree (closure capture would
        serialize the params into the lowered module — 28 GB of MLIR for
        WAN-14B; see ``Txt2ImgPipeline._weights``)."""
        w = {"dit": self.dit_params, "vae_dec": self.vae.dec_params}
        if self.dit_params_low is not None:
            w["dit_low"] = self.dit_params_low
        return w

    def decode_frames(self, latents: jax.Array, vae_params=None) -> jax.Array:
        """[B,f,h,w,c] → [B,F,H,W,3]: whole-clip decode through a 3D
        causal VAE, per-frame decode through the image VAE. Large frames
        switch to spatially-tiled decode (``WanVAE3D.decode_tiled``) —
        a 480p whole-frame f32 decode needs >31 GB of activations."""
        if self.temporal_downscale > 1:
            thresh = constants.VAE_TILE_THRESHOLD
            if thresh and latents.shape[2] * latents.shape[3] > thresh:
                frames = self.vae.decode_tiled(
                    latents, params=vae_params, tile=constants.VAE_TILE,
                    overlap=constants.VAE_TILE_OVERLAP)
            else:
                frames = self.vae.decode(latents, params=vae_params)
            return jnp.clip(frames / 2.0 + 0.5, 0.0, 1.0)
        B, F = latents.shape[:2]
        flat = latents.reshape((B * F,) + latents.shape[2:])
        frames = self.vae.decode(flat, params=vae_params)
        frames = jnp.clip(frames / 2.0 + 0.5, 0.0, 1.0)
        return frames.reshape((B, F) + frames.shape[1:])

    def _denoiser(self, context, pooled, guidance_scale, sp_axis=None,
                  inp_fn=None, params=None):
        """``inp_fn`` optionally transforms the latent before the model
        sees it (i2v concatenates mask + conditioning channels); the CFG
        machinery is shared so t2v/i2v guidance can never diverge.
        ``params`` overrides ``self.dit_params`` (tp mode passes the
        tp-sharded tree so GSPMD sees the placements)."""
        wts = self.dit_params if params is None else params

        def model_call(x, sigma, ctx, pl):
            t = jnp.broadcast_to(sigma, (x.shape[0],))
            inp = x if inp_fn is None else inp_fn(x)
            v = self.dit.apply(wts, inp, t, ctx, pl,
                               sp_axis=sp_axis)
            return x - sigma * v

        if guidance_scale == 1.0:
            return lambda x, s: model_call(x, s, context, pooled)

        uncond_ctx = jnp.zeros_like(context)
        uncond_pl = jnp.zeros_like(pooled)

        def denoise(x, sigma):
            x2 = jnp.concatenate([x, x], axis=0)
            ctx2 = jnp.concatenate([context, uncond_ctx], axis=0)
            pl2 = jnp.concatenate([pooled, uncond_pl], axis=0)
            out = model_call(x2, sigma, ctx2, pl2)
            cond, uncond = jnp.split(out, 2, axis=0)
            return uncond + guidance_scale * (cond - uncond)

        return denoise

    def generate_fn(self, mesh: Mesh, spec: VideoSpec,
                    axis: str = constants.AXIS_DATA,
                    progress: bool = False):
        """dp fan-out: each shard samples a full (seed-varied) video.
        ``progress`` threads a traced token through the program and
        streams per-step x0 previews (``diffusion/progress``) — t2v jobs
        are the longest-running work the framework does, exactly where
        the reference's per-step ComfyUI progress matters most."""
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        F = self.latent_frames(spec)
        lat = (F, spec.height // ds, spec.width // ds, self.dit.config.in_channels)

        def per_shard(weights, key, context, pooled, token=None):
            k = participant_key(key, axis)
            x = jax.random.normal(k, (1,) + lat, jnp.float32)
            make_den = self._progress_den(
                lambda p: self._denoiser(context, pooled,
                                         spec.guidance_scale, params=p),
                token, jax.lax.axis_index(axis))
            x0 = self._sample_expert(spec, make_den, x, sigmas, k, weights)
            return self.decode_frames(x0, vae_params=weights["vae_dec"])

        in_specs = (P(), P(), P(None, None, None), P(None, None))
        if progress:
            in_specs += (P(),)          # traced int32 token, replicated
        f = shard_map(
            per_shard, mesh=mesh, in_specs=in_specs,
            out_specs=P(axis, None, None, None, None),
        )
        jitted = jax.jit(f)
        weights = self._weights()

        return bind_weights(jitted, weights, label="video_dp",
                            steps=spec.steps)

    _CACHE_MAX = 4

    # --- host offload (expert too large for one chip, no pod) -------------

    def offload_executor(self, which: str = "high",
                         resident_bytes: Optional[int] = None,
                         stream_dtype: Optional[str] = None):
        """Build-or-fetch the cached ``OffloadedWan`` executor for one
        expert (``"high"`` = ``dit_params``, ``"low"`` =
        ``dit_params_low``)."""
        from .offload import OffloadedWan, normalize_stream_dtype
        from .pipeline import cached_build

        src = (self.dit_params if which == "high"
               else self.dit_params_low)
        if src is None:
            raise ValueError(f"no params for expert {which!r}")
        sd = normalize_stream_dtype(stream_dtype)
        return cached_build(
            self, ("offload", which, resident_bytes, sd, id(src)),
            lambda: OffloadedWan(self.dit, src,
                                 resident_bytes=resident_bytes,
                                 stream_dtype=sd),
            self._CACHE_MAX)

    def _evict_offload(self, which: str) -> None:
        """Release an expert's HBM and drop it from the executor cache —
        the dual-expert swap needs the space for the other expert."""
        cache = getattr(self, "_fn_cache", {})
        for key in [k for k in cache
                    if k[0] == "offload" and k[1] == which]:
            cache.pop(key).release()

    def generate_offloaded(self, spec: VideoSpec, seed: int,
                           context: jax.Array,
                           pooled: Optional[jax.Array] = None,
                           resident_bytes: Optional[int] = None,
                           stream_dtype: Optional[str] = None,
                           on_step=None,
                           progress_token=None,
                           should_stop=None) -> jax.Array:
        """ONE t2v video on ONE device with quantized/streamed expert
        weights (``diffusion/offload.py:OffloadedWan``) — the
        single-chip answer to WAN-14B's 28 GB-per-expert (×2 for the
        2.2 dual-expert pair). Seed derivation matches dp shard 0, so
        offloaded == sharded run. Dual-expert jobs run the high-noise
        segment, then RELEASE that expert's HBM and upload the low
        expert (one swap per video; the low expert stays cached for the
        next video, the high one re-uploads — with
        ``CDT_OFFLOAD_CACHE_DIR`` the re-quantize is skipped). i2v:
        ``generate_offloaded_i2v``."""
        return self._offloaded_sample(
            spec, seed, context, None, None,
            self.dit.config.in_channels, resident_bytes, stream_dtype,
            on_step, progress_token, should_stop)

    def generate_offloaded_i2v(self, spec: VideoSpec, seed: int,
                               image: jax.Array, context: jax.Array,
                               pooled: Optional[jax.Array] = None,
                               resident_bytes: Optional[int] = None,
                               stream_dtype: Optional[str] = None,
                               on_step=None,
                           progress_token=None,
                           should_stop=None) -> jax.Array:
        """Offloaded i2v: the same quantized-resident ladder with the
        first-frame conditioning concat (``i2v_condition`` → mask+y)
        applied per model call, exactly like ``_denoiser_i2v``."""
        if image.shape[0] != 1:
            raise ValueError("offloaded generation is single-video "
                             "(batch 1)")
        y, mask = self.i2v_condition(image, spec)
        c = getattr(self.dit.config, "out_channels",
                    self.dit.config.in_channels)
        return self._offloaded_sample(spec, seed, context, y, mask, c,
                                      resident_bytes, stream_dtype,
                                      on_step, progress_token,
                                      should_stop)

    def _offloaded_sample(self, spec: VideoSpec, seed: int, context,
                          y, mask, lat_channels: int, resident_bytes,
                          stream_dtype, on_step, progress_token=None,
                          should_stop=None) -> jax.Array:
        from .offload import ladder_mode, sample_euler_py

        if context.shape[0] != 1:
            raise ValueError("offloaded generation is single-video "
                             "(batch 1)")
        if ladder_mode() == "step" and spec.sampler != "euler":
            # fail BEFORE any expert quantize/upload — decidable from
            # the env + spec alone
            raise ValueError(
                "the per-step offloaded ladder supports euler only "
                f"(got {spec.sampler!r}); fully-resident executors "
                "with CDT_OFFLOAD_LADDER=jit run every sampler")
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        lat = (self.latent_frames(spec), spec.height // ds,
               spec.width // ds, lat_channels)
        # same key derivation as dp shard 0 (noise AND ancestral draws);
        # the low segment folds 0x10E exactly like _sample_expert
        key = jax.random.fold_in(jax.random.key(seed), 0)
        x = jax.random.normal(key, (1,) + lat, jnp.float32)

        def run(which, x0, sig, seg_key):
            off = self.offload_executor(which, resident_bytes,
                                        stream_dtype)
            if off.stacked and ladder_mode() == "jit":
                # fully resident: the whole segment ladder is ONE
                # compiled program supporting EVERY registered sampler
                # (in-trace progress via the token)
                return off.sample_resident(
                    x0, sig, context, spec.guidance_scale, y, mask,
                    sampler=spec.sampler, key=seg_key,
                    progress_token=progress_token)
            if spec.sampler != "euler":
                raise ValueError(
                    "the per-step offloaded ladder supports euler only "
                    f"(got {spec.sampler!r}); fully-resident executors "
                    "with CDT_OFFLOAD_LADDER=jit run every sampler")
            inp_fn = None if y is None else self._i2v_inp_fn(y, mask)
            den = off.denoiser(context, spec.guidance_scale,
                               inp_fn=inp_fn)
            return sample_euler_py(den, jax.device_put(x0, off.device),
                                   sig, on_step=on_step,
                                   should_stop=should_stop)

        if not self.is_moe:
            x0 = run("high", x, sigmas, key)
        else:
            split = self._expert_split(sigmas)
            steps = int(sigmas.shape[0]) - 1
            if split <= 0:
                x0 = run("low", x, sigmas, key)
            elif split >= steps:
                x0 = run("high", x, sigmas, key)
            else:
                x_mid = run("high", x, sigmas[: split + 1], key)
                jax.block_until_ready(x_mid)
                if should_stop is not None and should_stop():
                    # free host-side boundary — honor an interrupt here
                    # even in jit ladder mode rather than uploading +
                    # running the whole low-expert segment first
                    raise InterruptedError(
                        "offloaded MoE sampling interrupted at the "
                        "expert boundary")
                self._evict_offload("high")     # HBM for the low expert
                x0 = run("low", x_mid, sigmas[split:],
                         jax.random.fold_in(key, 0x10E))
        return self.decode_frames(x0)

    def _cached_fn(self, mesh: Mesh, spec: VideoSpec, mode: str = "dp",
                   progress: bool = False,
                   axis: Optional[str] = None):
        """Value-keyed compile cache across node executions (same
        discipline as ``FlowPipeline._cached_fn`` — a WAN compile is far
        too expensive to pay per prompt)."""
        from .pipeline import cached_build, mesh_cache_key

        if mode in ("sp", "i2v-sp"):
            axis = axis or constants.AXIS_SEQUENCE
        else:
            axis = axis or constants.AXIS_DATA
        builder = {"dp": self.generate_fn,
                   "sp": self.generate_frames_fn,
                   "i2v": self.generate_i2v_fn,
                   "i2v-sp": self.generate_i2v_frames_fn}[mode]
        key = (mesh_cache_key(mesh), spec, mode, progress, axis)
        return cached_build(
            self, key, lambda: builder(mesh, spec, axis=axis,
                                       progress=progress),
            self._CACHE_MAX)

    @staticmethod
    def _token_args(args: list, progress_token) -> list:
        """Single place that knows the token's wire form (trailing int32
        scalar) — the nodes never marshal it themselves."""
        if progress_token is not None:
            args.append(jnp.asarray(progress_token, jnp.int32))
        return args

    def generate(self, mesh: Mesh, spec: VideoSpec, seed: int,
                 context: jax.Array, pooled: jax.Array,
                 progress_token=None) -> jax.Array:
        fn = self._cached_fn(mesh, spec, "dp",
                             progress=progress_token is not None)
        return fn(*self._token_args(
            [jax.random.key(seed), context, pooled], progress_token))

    def generate_frames(self, mesh: Mesh, spec: VideoSpec, seed: int,
                        context: jax.Array, pooled: jax.Array,
                        progress_token=None) -> jax.Array:
        """Public sp entry (ONE video, frame blocks sharded): cached
        compile + progress token, mirroring ``generate``."""
        fn = self._cached_fn(mesh, spec, "sp",
                             progress=progress_token is not None)
        return fn(*self._token_args(
            [jax.random.key(seed), context, pooled], progress_token))

    # -- dp×tp: the WAN-14B enabler --------------------------------------

    def generate_tp_fn(self, mesh: Mesh, spec: VideoSpec,
                       dp_axis: str = constants.AXIS_DATA,
                       tp_axis: str = constants.AXIS_TENSOR):
        """Seeds over ``dp`` AND weights over ``tp`` in one jit. A 14B
        WAN DiT is ~28 GB of bf16 weights — more than a v5e chip's HBM —
        so tp sharding is what makes BASELINE's ``wan-2.2 14B t2v over
        pod`` row runnable at all (the reference requires every GPU to
        hold the whole model, README.md:186-189). Megatron column/row
        rules per model family (``parallel/tensor.py``); GSPMD inserts
        the all-reduces."""
        from ..parallel.tensor import (DIT_TP_RULES, WAN_TP_RULES,
                                       require_tp_match, shard_params,
                                       tp_fanout_call)

        # models declare their rule family (WanModel.tp_family = "wan");
        # MMDiT-style video DiTs use the image-DiT fused-qkv rules
        family = getattr(self.dit, "tp_family", "dit")
        rules = WAN_TP_RULES if family == "wan" else DIT_TP_RULES
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        F = self.latent_frames(spec)
        lat = (F, spec.height // ds, spec.width // ds,
               self.dit.config.in_channels)
        B = mesh.shape[dp_axis]
        require_tp_match(self.dit_params, mesh, rules, tp_axis, family)
        # tp-placed params travel as ARGUMENTS (committed sharded arrays),
        # never closure constants (see _weights). Both experts of a
        # WAN-2.2 MoE shard over tp — per-chip resident weights stay
        # 2·(params/tp_degree), which is what makes the dual-14B config
        # placeable at all.
        weights = {"dit": shard_params(self.dit_params, mesh, rules,
                                       tp_axis)}
        if self.dit_params_low is not None:
            weights["dit_low"] = shard_params(self.dit_params_low, mesh,
                                              rules, tp_axis)
        vae_dec = self.vae.dec_params

        def run(weights, vae_dec, keys, context, pooled):
            noise = jax.vmap(
                lambda k: jax.random.normal(k, lat, jnp.float32))(keys)
            bc = lambda a: jnp.broadcast_to(a, (B,) + a.shape[1:])
            make_den = lambda p: self._denoiser(
                bc(context), bc(pooled), spec.guidance_scale, params=p)
            x0 = self._sample_expert(spec, make_den, noise, sigmas,
                                     keys[0], weights)
            return self.decode_frames(x0, vae_params=vae_dec)

        return tp_fanout_call(jax.jit(run), (weights, vae_dec), mesh,
                              dp_axis, B)

    # -- image→video (WAN-2.2-style latent-concat conditioning) ----------

    def i2v_condition(self, image: jax.Array,
                      spec: VideoSpec) -> tuple[jax.Array, jax.Array]:
        """First-frame conditioning for i2v models.

        ``image`` [B,H,W,3] in [0,1] → ``y`` (the causal VAE encoding of
        the image followed by blank frames) and ``mask`` (one channel per
        compressed-away pixel frame, published WAN polarity: **1 where
        content is given** — the first latent frame — 0 where the model
        must generate). The model input per step is
        ``concat([x_t, mask, y])``, matching the i2v in_channels
        arithmetic (e.g. 16+4+16=36 at 4× temporal)."""
        B, H, W, _ = image.shape
        F = spec.padded_frames
        vid = jnp.concatenate(
            [image[:, None] * 2.0 - 1.0,
             jnp.zeros((B, F - 1, H, W, image.shape[-1]))], axis=1)
        y = self.vae.encode(vid)
        td = max(self.temporal_downscale, 1)
        mask = jnp.zeros(y.shape[:4] + (td,), y.dtype)
        return y, mask.at[:, 0].set(1.0)

    @staticmethod
    def _i2v_inp_fn(y, mask):
        """The i2v model-input concat — ONE definition shared with both
        offloaded ladders (``diffusion/offload.i2v_input_concat``)."""
        from .offload import i2v_input_concat

        return i2v_input_concat(y, mask)

    def _denoiser_i2v(self, context, pooled, y, mask, guidance_scale,
                      sp_axis=None, params=None):
        return self._denoiser(context, pooled, guidance_scale,
                              sp_axis=sp_axis,
                              inp_fn=self._i2v_inp_fn(y, mask),
                              params=params)

    def generate_i2v_fn(self, mesh: Mesh, spec: VideoSpec,
                        axis: str = constants.AXIS_DATA,
                        progress: bool = False):
        """dp fan-out of seed-varied i2v samples from one start image
        (the conditioning latents replicate across shards)."""
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        F = self.latent_frames(spec)
        c = getattr(self.dit.config, "out_channels",
                    self.dit.config.in_channels)
        lat = (F, spec.height // ds, spec.width // ds, c)

        def per_shard(weights, key, context, pooled, y, mask, token=None):
            k = participant_key(key, axis)
            x = jax.random.normal(k, (1,) + lat, jnp.float32)
            make_den = self._progress_den(
                lambda p: self._denoiser_i2v(context, pooled, y, mask,
                                             spec.guidance_scale, params=p),
                token, jax.lax.axis_index(axis))
            x0 = self._sample_expert(spec, make_den, x, sigmas, k, weights)
            return self.decode_frames(x0, vae_params=weights["vae_dec"])

        in_specs = (P(), P(), P(None, None, None), P(None, None),
                    P(None, None, None, None, None),
                    P(None, None, None, None, None))
        if progress:
            in_specs += (P(),)
        f = shard_map(
            per_shard, mesh=mesh, in_specs=in_specs,
            out_specs=P(axis, None, None, None, None),
        )
        jitted = jax.jit(f)
        weights = self._weights()

        return bind_weights(jitted, weights, label="video_i2v",
                            steps=spec.steps)

    def generate_i2v(self, mesh: Mesh, spec: VideoSpec, seed: int,
                     image: jax.Array, context: jax.Array,
                     pooled: jax.Array, progress_token=None) -> jax.Array:
        y, mask = self.i2v_condition(image, spec)
        fn = self._cached_fn(mesh, spec, "i2v",
                             progress=progress_token is not None)
        return fn(*self._token_args(
            [jax.random.key(seed), context, pooled, y, mask],
            progress_token))

    def generate_i2v_frames(self, mesh: Mesh, spec: VideoSpec, seed: int,
                            image: jax.Array, context: jax.Array,
                            pooled: jax.Array,
                            progress_token=None) -> jax.Array:
        """Public i2v sp entry: cached compile + progress token."""
        y, mask = self.i2v_condition(image, spec)
        fn = self._cached_fn(mesh, spec, "i2v-sp",
                             progress=progress_token is not None)
        return fn(*self._token_args(
            [jax.random.key(seed), context, pooled, y, mask],
            progress_token))

    def generate_i2v_frames_fn(self, mesh: Mesh, spec: VideoSpec,
                               axis: str = constants.AXIS_SEQUENCE,
                               progress: bool = False):
        """ONE i2v sample with latent frame blocks sharded over ``axis``:
        ring attention spans the full sequence; each shard sees its own
        slice of the conditioning latents/mask (frame-aligned, so the
        concat happens shard-locally with no collective)."""
        n_sh = mesh.shape[axis]
        F = self.latent_frames(spec)
        if F % n_sh:
            raise ValueError(
                f"latent frame count {F} must divide over {n_sh} shards")
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        lat_h, lat_w = spec.height // ds, spec.width // ds
        c = getattr(self.dit.config, "out_channels",
                    self.dit.config.in_channels)
        per = F // n_sh

        def per_shard(weights, key, context, pooled, y_sh, mask_sh,
                      token=None):
            idx = jax.lax.axis_index(axis)
            full = jax.random.normal(key, (1, F, lat_h, lat_w, c),
                                     jnp.float32)
            x = jax.lax.dynamic_slice_in_dim(full, idx * per, per, axis=1)
            make_den = self._progress_den(
                lambda p: self._denoiser_i2v(context, pooled, y_sh, mask_sh,
                                             spec.guidance_scale,
                                             sp_axis=axis, params=p),
                token, idx)
            # per-shard sampler key: ancestral samplers must inject
            # DIFFERENT noise into each frame block (deterministic
            # samplers ignore the key, so sp==unsharded still holds)
            return self._sample_expert(spec, make_den, x, sigmas,
                                       jax.random.fold_in(key, idx), weights)

        in_specs = (P(), P(), P(None, None, None), P(None, None),
                    P(None, axis), P(None, axis))
        if progress:
            in_specs += (P(),)
        f = shard_map(
            per_shard, mesh=mesh, in_specs=in_specs,
            out_specs=P(None, axis, None, None, None),
            check_vma=False,
        )

        def run(weights, key, context, pooled, y, mask, *token):
            return self.decode_frames(f(weights, key, context, pooled,
                                        y, mask, *token),
                                      vae_params=weights["vae_dec"])

        jitted = jax.jit(run)
        weights = self._weights()

        return bind_weights(jitted, weights, label="video_i2v_sp",
                            steps=spec.steps)

    def generate_frames_fn(self, mesh: Mesh, spec: VideoSpec,
                           axis: str = constants.AXIS_SEQUENCE,
                           progress: bool = False):
        """ONE video, frame blocks sharded over ``axis``; joint ring
        attention spans the full spatio-temporal sequence so motion stays
        globally coherent (this is exact attention, not windowed)."""
        n_sh = mesh.shape[axis]
        F = self.latent_frames(spec)
        if F % n_sh:
            raise ValueError(
                f"latent frame count {F} must divide over {n_sh} shards "
                f"(choose frames so the compressed 4n+1 count ≡ 0 mod "
                f"shards)")
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        lat_h, lat_w = spec.height // ds, spec.width // ds
        c = self.dit.config.in_channels
        per = F // n_sh

        def per_shard(weights, key, context, pooled, token=None):
            idx = jax.lax.axis_index(axis)
            full = jax.random.normal(key, (1, F, lat_h, lat_w, c), jnp.float32)
            x = jax.lax.dynamic_slice_in_dim(full, idx * per, per, axis=1)
            make_den = self._progress_den(
                lambda p: self._denoiser(context, pooled,
                                         spec.guidance_scale,
                                         sp_axis=axis, params=p),
                token, idx)
            # fold the shard index so ancestral samplers draw distinct
            # noise per frame block (deterministic samplers ignore it)
            return self._sample_expert(spec, make_den, x, sigmas,
                                       jax.random.fold_in(key, idx), weights)

        in_specs = (P(), P(), P(None, None, None), P(None, None))
        if progress:
            in_specs += (P(),)
        f = shard_map(
            per_shard, mesh=mesh, in_specs=in_specs,
            out_specs=P(None, axis, None, None, None),
            check_vma=False,
        )

        def run(weights, key, context, pooled, *token):
            latents = f(weights, key, context, pooled, *token)
            return self.decode_frames(latents, vae_params=weights["vae_dec"])

        jitted = jax.jit(run)
        weights = self._weights()

        return bind_weights(jitted, weights, label="video_sp",
                            steps=spec.steps)
