"""AOT warmup pass: make a worker hot the moment it joins the fleet.

Walks the shape catalog (``cluster/shape_catalog.py``) and pre-lowers /
pre-compiles every program with ``jitted.lower(...).compile()`` — the
same AOT idiom ``bench.py`` uses for its compile measurement — entirely
off the request path. With a populated persistent XLA cache
(``utils/compile_cache.py``) each program resolves to a disk read
instead of a 13.9 s compile; the pass classifies every entry as
``cache_hit`` vs ``compiled`` by watching whether jax wrote new cache
artifacts, so the warm-restart win is *measured*, not assumed
(``cdt_warmup_programs_total``).

Arguments are lowered as ``jax.ShapeDtypeStruct`` templates: warmup
never allocates batch-sized activations and never executes a program —
it only traces and compiles.

A :class:`WarmupManager` owns the worker-visible state machine
(``cold → warming → ready``; ``error`` on a failed pass). The health
probe reports it, and ``cluster/dispatch.py`` prefers hot workers, so a
rolling restart drains traffic toward hosts that won't stall it.

Knobs: ``CDT_WARMUP=1`` warms on controller boot; ``CDT_WARMUP_MODELS``
(csv) restricts which catalog models warm (a CPU controller must not
try to build FLUX-12B).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from pathlib import Path
from typing import Callable, Iterable, Optional

from ..cluster.shape_catalog import ProgramKey, ShapeCatalog
from ..utils import constants
from ..utils.logging import debug_log, log

COLD, WARMING, READY, ERROR = "cold", "warming", "ready", "error"
_STATE_GAUGE = {COLD: 0.0, WARMING: 1.0, READY: 2.0, ERROR: -1.0}


@dataclasses.dataclass
class WarmupEntry:
    key: ProgramKey
    outcome: str          # cache_hit | compiled | error | skipped
    seconds: float = 0.0
    detail: str = ""
    # attention geometries this program serves (ops/autotune.py keys) —
    # fed to the autotune stage so the worker stays `warming` until its
    # catalog geometries are tuned
    geometries: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"program": self.key.to_dict(), "outcome": self.outcome,
                "seconds": round(self.seconds, 3), "detail": self.detail,
                "geometries": [g.key_str() for g in self.geometries]}


def _cache_artifacts(cache_dir: Optional[str]) -> set:
    if not cache_dir:
        return set()
    try:
        return {p.name for p in Path(cache_dir).iterdir() if p.is_file()}
    except OSError:
        return set()


def _abstract(shape, dtype="float32"):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def lower_program(bundle, key: ProgramKey, mesh) -> None:
    """Trace + XLA-compile ONE catalog program ahead of time. Shapes come
    from the preset's config (context length / dims) and the key's
    geometry; nothing executes and no batch-sized buffer is allocated.

    The ``progress=True`` variant is compiled — that IS the serving
    program: every sampler node runs with a live ProgressTracker
    (``_ProgressScope`` always yields a token on the server path), and
    the progress token changes the traced HLO, so warming the
    token-less variant would leave the first real request cold."""
    import jax
    import jax.numpy as jnp

    prng = jax.random.key(0)
    token = _abstract((), jnp.int32)
    if key.mesh:
        # mesh-tier program: the key names its own strategy mesh (sp /
        # dp×tp) over the SAME device set the host serves with
        from ..parallel.mesh import build_mesh

        mesh = build_mesh(dict(key.mesh),
                          devices=list(mesh.devices.flat))
    if key.pipeline == "txt2img":
        from .pipeline import GenerationSpec

        spec = GenerationSpec(height=key.height, width=key.width,
                              steps=key.steps,
                              per_device_batch=key.batch)
        fn = bundle.pipeline.generate_fn(mesh, spec, progress=True)
        text = bundle.preset.text
        ctx = _abstract((1, text.max_len, text.output_dim))
        adm = bundle.pipeline.unet.config.adm_in_channels
        y = _abstract((1, max(adm, 1)))
        args = (prng, ctx, ctx, y, y, token)
    elif key.pipeline == "flow_dp":
        from .pipeline_flow import FlowSpec

        spec = FlowSpec(height=key.height, width=key.width,
                        steps=key.steps, per_device_batch=key.batch)
        fn = bundle.pipeline.generate_fn(mesh, spec, progress=True)
        cfg = bundle.pipeline.dit.config
        ctx = _abstract((1, bundle.preset.text.max_len, cfg.context_dim))
        pooled = _abstract((1, cfg.pooled_dim))
        args = (prng, ctx, pooled, token)
    elif key.pipeline == "video_dp":
        from .pipeline_video import VideoSpec

        spec = VideoSpec(frames=key.frames or 17, height=key.height,
                         width=key.width, steps=key.steps)
        fn = bundle.pipeline.generate_fn(mesh, spec, progress=True)
        cfg = bundle.pipeline.dit.config
        ctx = _abstract((1, bundle.preset.text.max_len, cfg.context_dim))
        pooled = _abstract((1, getattr(cfg, "pooled_dim", 768)))
        args = (prng, ctx, pooled, token)
    elif key.pipeline == "flow_sp":
        # mesh tier: single-image latency program — latent rows sharded
        # over sp, ring attention inside every block
        from .pipeline_flow import FlowSpec

        spec = FlowSpec(height=key.height, width=key.width,
                        steps=key.steps, per_device_batch=key.batch)
        fn = bundle.pipeline.generate_sp_fn(mesh, spec)
        cfg = bundle.pipeline.dit.config
        ctx = _abstract((1, bundle.preset.text.max_len, cfg.context_dim))
        pooled = _abstract((1, cfg.pooled_dim))
        args = (prng, ctx, pooled)
    elif key.pipeline == "flow_tp":
        # mesh tier: dp×tp weight-sharded program. The fanout wrapper's
        # key fold-in is part of the traced program, so AOT-lower with a
        # concrete folded key batch (tiny) and abstract conditioning;
        # tp_shard_scope makes the trace resolve PER-SHARD kernel
        # choices — the same scope the serving call runs under.
        from ..ops.attention import tp_shard_scope
        from .pipeline_flow import FlowSpec

        spec = FlowSpec(height=key.height, width=key.width,
                        steps=key.steps, per_device_batch=key.batch)
        fn = bundle.pipeline.generate_tp_fn(mesh, spec)
        cfg = bundle.pipeline.dit.config
        B = dict(key.mesh).get("dp", 1) * key.batch
        # keys must carry the SAME P(dp) placement the serving wrapper
        # commits (tp_fanout_call) — a differently-sharded argument
        # lowers a different executable, and the cache entry warmed
        # here would not be the one serving loads
        from jax.sharding import NamedSharding, PartitionSpec

        keys = jax.device_put(
            jax.vmap(lambda i: jax.random.fold_in(prng, i))(
                jnp.arange(B)),
            NamedSharding(mesh, PartitionSpec("dp")))
        ctx = _abstract((1, bundle.preset.text.max_len, cfg.context_dim))
        pooled = _abstract((1, cfg.pooled_dim))
        with tp_shard_scope(getattr(fn, "tp_shards", 1)):
            fn.jitted.lower(*fn.weights, keys, ctx, pooled).compile()
        return
    else:
        raise ValueError(f"no warmup recipe for pipeline {key.pipeline!r}")
    fn.jitted.lower(fn.weights, *args).compile()


def _mesh_matches(key: ProgramKey, mesh) -> bool:
    """Empty key.mesh = "whatever this host runs"; a concrete one must
    match exactly (a dp=8 program is not a dp=4 program) — OR be a
    mesh-tier strategy layout (sp / dp×tp) over the same device count,
    which warmup builds over the host's own devices
    (``lower_program``)."""
    if not key.mesh:
        return True
    if tuple(sorted(key.mesh)) == tuple(
            sorted((str(a), int(n)) for a, n in mesh.shape.items())):
        return True
    import math

    # strategy meshes may be submeshes (sp width is bounded by the
    # latent row count); lower_program builds them over the host's own
    # device list
    return (key.pipeline in ("flow_sp", "flow_tp")
            and math.prod(n for _, n in key.mesh) <= mesh.devices.size)


def mesh_tier_keys(keys: Iterable[ProgramKey], mesh) -> list[ProgramKey]:
    """The mesh-tier programs a catalog implies: for every flow_dp entry
    the host serves, an sp (single-image latency) and — when the mesh
    tier has a tp degree — a dp×tp (weight-sharded) variant on the same
    geometry, so the front door's default placements are hot from boot
    instead of compiling on first mesh-tier request. Gated by
    ``CDT_MESH_TIER``; a single-device host has no mesh tier.

    The tp degree is ``parallel/serving.derive_tp`` — i.e. the pinned
    ``CDT_MESH_TP`` at key-generation time (model bytes aren't known
    before bundles build, so the HBM-fit derivation can't run here);
    an unpinned fleet warms its tp programs on the second boot via the
    persistent compile cache after the first request resolves them."""
    from ..parallel import serving

    n = int(mesh.devices.size)
    if n < 2 or not serving.mesh_tier_enabled():
        return []
    tp = serving.derive_tp(n)
    while tp > 1 and n % tp:
        tp //= 2
    out: list[ProgramKey] = []
    for key in keys:
        if key.pipeline != "flow_dp":
            continue
        # sp needs latent rows (h/8/patch, patch=2 for the DiT family)
        # to divide the shard count; indivisible geometries stay dp-only
        sp = n
        while sp > 1 and (key.height // 16) % sp:
            sp //= 2
        if sp > 1:
            out.append(dataclasses.replace(
                key, pipeline="flow_sp", mesh=(("sp", sp),)))
        if tp > 1:
            out.append(dataclasses.replace(
                key, pipeline="flow_tp",
                mesh=(("dp", n // tp), ("tp", tp))))
    return out


def run_warmup(registry, mesh, keys: Iterable[ProgramKey],
               models: Optional[Iterable[str]] = None,
               on_entry: Optional[Callable[[WarmupEntry], None]] = None,
               tune: bool = True,
               tune_report: Optional[list] = None) -> list[WarmupEntry]:
    """Warm every catalog program buildable on this host.

    ``models`` (or ``CDT_WARMUP_MODELS``) filters which model bundles are
    eligible — everything else is recorded ``skipped`` (warming is
    best-effort fleet prep, and a CPU smoke host must not materialize a
    14B checkpoint). With NO filter at all, only models already loaded
    in the registry (plus the tiny test presets) warm: the shipped
    workflow catalog references FLUX/WAN/SDXL, and an unqualified
    ``CDT_WARMUP=1`` must not random-initialize tens of GB on boot —
    pass ``CDT_WARMUP_MODELS=all`` (or an explicit list) to opt in.
    Per-entry failures are recorded, never raised: one bad catalog row
    must not leave the worker cold for the rest.

    Two phases with the attention autotune stage BETWEEN them: phase A
    builds the bundles and derives each program's attention geometries;
    ``autotune.ensure_tuned`` then sweeps any untuned geometry (appended
    to ``tune_report``); phase B AOT-compiles. The order matters — the
    kernel choice is baked into the traced HLO at lower time, so tuning
    after compilation would warm programs carrying pre-sweep kernel
    choices and invalidate the cache on the next trace. ``tune=False``
    (or ``CDT_ATTN_TUNE=0``) skips the stage.
    """
    from ..ops import autotune
    from ..telemetry import enabled as _tm_enabled
    from ..telemetry import metrics as _tm
    from ..utils.compile_cache import active_cache_dir

    if models is None:
        env = constants.WARMUP_MODELS.get()
        models = [m.strip() for m in env.split(",") if m.strip()] or None
    if models is not None and set(models) & {"all", "*"}:
        allowed = None                      # explicit everything
    elif models is not None:
        allowed = set(models)
    else:
        # safe default: what's already hot, plus presets cheap anywhere
        allowed = set(getattr(registry, "_cache", {})) | {
            m for m in getattr(registry, "available", list)()
            if "tiny" in m}
        log("warmup: no model filter — warming only loaded/tiny presets "
            f"({sorted(allowed)}); set CDT_WARMUP_MODELS=all to warm "
            "everything in the catalog")
    cache_dir = active_cache_dir()

    # --- phase A: build bundles, derive geometries ------------------------
    plan: list = []   # (key, bundle | None, pre-resolved entry | None)
    geometries: set = set()
    for key in keys:
        if (allowed is not None and key.model not in allowed) \
                or not _mesh_matches(key, mesh):
            plan.append((key, None,
                         WarmupEntry(key, "skipped",
                                     detail="model filtered or mesh "
                                            "mismatch")))
            continue
        t0 = time.perf_counter()
        try:
            # bundle build happens OUTSIDE the classification window:
            # its own init compiles (VAE/text) would otherwise write
            # cache artifacts and mislabel a disk-served target program
            # "compiled"
            bundle = registry.get(key.model)
        except Exception as e:  # noqa: BLE001 — per-entry isolation
            plan.append((key, None,
                         WarmupEntry(key, "error",
                                     time.perf_counter() - t0,
                                     detail=str(e))))
            debug_log(f"warmup: {key} failed: {e}")
            continue
        entry = WarmupEntry(key, "pending")
        try:
            entry.geometries = autotune.geometries_for_program(bundle, key)
            geometries.update(entry.geometries)
        except Exception as e:  # noqa: BLE001 — advisory
            debug_log(f"warmup: geometry derivation for {key} failed: {e}")
        plan.append((key, bundle, entry))

    # --- autotune stage: BEFORE compilation, so the kernel choices the
    # traces bake in are the tuned ones ------------------------------------
    if tune and geometries and autotune.tuning_enabled():
        swept = autotune.ensure_tuned(sorted(geometries))
        if tune_report is not None:
            tune_report.extend(swept)

    # --- phase B: AOT lower + compile -------------------------------------
    report: list[WarmupEntry] = []
    for key, bundle, entry in plan:
        if bundle is not None:
            try:
                before = _cache_artifacts(cache_dir)
                t0 = time.perf_counter()
                lower_program(bundle, key, mesh)
                entry.seconds = time.perf_counter() - t0
                wrote = bool(_cache_artifacts(cache_dir) - before)
                # new cache artifacts ⇒ XLA actually compiled; none (with
                # a cache active) ⇒ the executable was deserialized from
                # disk — the warm-restart fast path this pass exists for
                entry.outcome = ("compiled" if wrote or not cache_dir
                                 else "cache_hit")
            except Exception as e:  # noqa: BLE001 — per-entry isolation
                entry.outcome = "error"
                entry.seconds = time.perf_counter() - t0
                entry.detail = str(e)
                debug_log(f"warmup: {key} failed: {e}")
        report.append(entry)
        if _tm_enabled():
            _tm.WARMUP_PROGRAMS.labels(outcome=entry.outcome).inc()
            if entry.outcome in ("cache_hit", "compiled"):
                _tm.WARMUP_SECONDS.observe(entry.seconds)
        if on_entry is not None:
            on_entry(entry)
    return report


class WarmupManager:
    """Worker warmup state machine + pass runner.

    Built lazily off the controller (registry/mesh are properties that
    may themselves initialize jax — resolved only when a pass runs).
    State is what health probes report: ``cold`` (never warmed),
    ``warming`` (pass in flight), ``ready`` (pass finished), ``error``
    (pass itself crashed — per-program errors still end ``ready``).
    """

    def __init__(self, registry_fn: Callable, mesh_fn: Callable,
                 catalog: Optional[ShapeCatalog] = None):
        self._registry_fn = registry_fn
        self._mesh_fn = mesh_fn
        self._catalog = catalog
        self._state = COLD
        self._lock = threading.Lock()
        self._report: list[WarmupEntry] = []
        self._autotune_report: list = []
        self._started_at: Optional[float] = None
        self._finished_at: Optional[float] = None

    @property
    def state(self) -> str:
        return self._state

    @property
    def catalog(self) -> ShapeCatalog:
        if self._catalog is None:
            from ..cluster.shape_catalog import default_catalog

            self._catalog = default_catalog()
        return self._catalog

    def _set_state(self, state: str) -> None:
        self._state = state
        try:
            from ..telemetry import enabled as _tm_enabled
            from ..telemetry import metrics as _tm

            if _tm_enabled():
                _tm.WARMUP_STATE.set(_STATE_GAUGE[state])
        except Exception:  # noqa: BLE001
            pass

    def run(self, models: Optional[Iterable[str]] = None,
            seed_workflows: bool = True,
            extra_keys: Optional[Iterable[ProgramKey]] = None) -> dict:
        """Execute one warmup pass synchronously (call from a thread
        executor — this compiles). Concurrent calls coalesce: a second
        caller returns the running/last report instead of doubling the
        compile load."""
        if not self._lock.acquire(blocking=False):
            return self.status()
        try:
            self._set_state(WARMING)
            self._started_at = time.monotonic()
            from ..utils.compile_cache import enable_compile_cache

            # persist EVERYTHING the pass compiles (min 0.0): a program
            # too cheap to cache is still a program the next restart
            # would recompile
            enable_compile_cache(min_compile_secs=0.0)
            cat = self.catalog
            if seed_workflows:
                cat.seed_from_workflows()
            keys = list(cat.entries())
            if extra_keys:
                known = set(keys)
                keys += [k for k in extra_keys if k not in known]
            # mesh tier: warm the sp / dp×tp variants of every flow
            # program the catalog serves (docs/parallelism.md) — the
            # default placements must be hot, not benchmark-only
            mesh = self._mesh_fn()
            tier = [k for k in mesh_tier_keys(keys, mesh)
                    if k not in set(keys)]
            keys += tier
            log(f"warmup: starting pass over {len(keys)} catalog "
                f"program(s) ({len(tier)} mesh-tier)")
            # the autotune stage runs INSIDE run_warmup, between bundle
            # build and AOT compile — the worker stays `warming` until
            # every attention geometry its catalog programs serve has a
            # tuned kernel config, and the compiled programs bake those
            # tuned choices into their traces
            self._autotune_report = []
            self._report = run_warmup(self._registry_fn(), self._mesh_fn(),
                                      keys, models=models,
                                      tune_report=self._autotune_report)
            cat.save()
            self._finished_at = time.monotonic()
            self._set_state(READY)
            hits = sum(e.outcome == "cache_hit" for e in self._report)
            comp = sum(e.outcome == "compiled" for e in self._report)
            errs = sum(e.outcome == "error" for e in self._report)
            swept = sum(e.outcome in ("swept", "dry")
                        for e in self._autotune_report)
            log(f"warmup: ready — {hits} cache hit(s), {comp} compiled, "
                f"{errs} error(s), "
                f"{sum(e.outcome == 'skipped' for e in self._report)} "
                f"skipped; autotune: {swept} swept, "
                f"{sum(e.outcome == 'cached' for e in self._autotune_report)}"
                f" cached in "
                f"{self._finished_at - self._started_at:.1f}s")
        except Exception as e:  # noqa: BLE001 — boot must survive warmup
            self._finished_at = time.monotonic()
            self._set_state(ERROR)
            log(f"warmup: pass failed: {e}")
        finally:
            self._lock.release()
        return self.status()

    def status(self) -> dict:
        took = None
        if self._started_at is not None:
            took = (self._finished_at or time.monotonic()) - self._started_at
        counts: dict[str, int] = {}
        for e in self._report:
            counts[e.outcome] = counts.get(e.outcome, 0) + 1
        tune_counts: dict[str, int] = {}
        for e in self._autotune_report:
            tune_counts[e.outcome] = tune_counts.get(e.outcome, 0) + 1
        return {
            "state": self._state,
            "catalog_size": (len(self._catalog)
                            if self._catalog is not None else None),
            "outcomes": counts,
            "seconds": None if took is None else round(took, 3),
            "report": [e.to_dict() for e in self._report],
            "autotune": {
                "outcomes": tune_counts,
                "report": [e.to_dict() for e in self._autotune_report],
            },
        }
