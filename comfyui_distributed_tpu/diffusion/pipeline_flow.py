"""Rectified-flow pipeline (FLUX-class DiT) with two sharding modes.

1. ``generate_fn`` — data-parallel seed fan-out over ``dp`` (the same
   contract as ``Txt2ImgPipeline``: BASELINE's "8 seed-varied images per
   step-time").
2. ``generate_sp_fn`` — ONE image's tokens sharded over ``sp`` with ring
   attention: the sampler's whole scan runs with every shard holding a row
   block of the latent — single-image latency scales with chip count,
   which the reference explicitly cannot do (``README.md:191-194``: "does
   not speed up the generation of a single image").
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map

from ..models.dit import DiT, DiTConfig
from ..models.vae import AutoencoderKL
from ..parallel.rng import participant_key
from ..utils import constants
from .pipeline import bind_weights
from .samplers import sample
from .schedules import sigmas_flow


@dataclasses.dataclass(frozen=True)
class FlowSpec:
    height: int = 1024
    width: int = 1024
    steps: int = 28
    shift: float = 3.0              # resolution-dependent sigma shift
    guidance: float = 3.5           # distilled guidance (FLUX-dev)
    cfg: float = 1.0                # true classifier-free guidance scale
                                    # (SD3-family; 1.0 = off, FLUX-dev
                                    # bakes guidance into `guidance`)
    sampler: str = "euler"
    per_device_batch: int = 1


class FlowPipeline:
    def __init__(self, dit: DiT, dit_params, vae: AutoencoderKL):
        self.dit = dit
        self.dit_params = dit_params
        self.vae = vae

    def _weights(self) -> dict:
        """Explicit jit-argument weight pytree (closure capture would embed
        the params as lowered-module constants — 24 GB of MLIR for FLUX;
        see ``Txt2ImgPipeline._weights``)."""
        return {"dit": self.dit_params, "vae_dec": self.vae.dec_params}

    def _denoiser(self, context, pooled, guidance, sp_axis=None,
                  weights=None, cfg: float = 1.0, uncond_context=None,
                  uncond_pooled=None):
        """``cfg != 1.0`` (SD3-family true CFG) batches the cond/uncond
        passes into one doubled-batch model call (``guidance.cfg_denoiser``
        — same discipline as the UNet path); FLUX-dev keeps cfg=1.0 and
        its distilled ``guidance`` input."""
        dit_params = (self.dit_params if weights is None
                      else weights["dit"])

        def make(ctx, pl):
            def denoise(x, sigma):
                t = jnp.broadcast_to(sigma, (x.shape[0],))
                g = jnp.full((x.shape[0],), guidance)
                v = self.dit.apply(dit_params, x, t, ctx, pl, g,
                                   sp_axis=sp_axis)
                return x - sigma * v
            return denoise

        if cfg == 1.0:
            return make(context, pooled)
        if uncond_context is None:
            # silently sampling WITHOUT guidance a caller asked for would
            # quietly produce the wrong image — fail loudly instead
            raise ValueError(
                f"cfg={cfg} requires negative conditioning: pass "
                "uncond_context (and uncond_pooled) through generate/"
                "generate_sp, or wire the FlowSampler node's 'negative' "
                "input; FLUX-dev distilled guidance wants cfg=1.0 with "
                "the 'guidance' field instead")
        from .guidance import cfg_denoiser

        return cfg_denoiser(make, context, uncond_context, cfg,
                            y=pooled, uncond_y=uncond_pooled)

    def _sample_and_decode(self, key, context, pooled, spec: FlowSpec,
                           batch: int, sigmas, lat_hw, sp_axis=None,
                           decode: bool = True, weights=None,
                           progress=None, uncond_context=None,
                           uncond_pooled=None):
        lat_h, lat_w = lat_hw
        c = self.dit.config.in_channels
        x = jax.random.normal(key, (batch, lat_h, lat_w, c), jnp.float32)
        bc = lambda a: (None if a is None
                        else jnp.broadcast_to(a, (batch,) + a.shape[1:]))
        den = self._denoiser(bc(context), bc(pooled), spec.guidance, sp_axis,
                             weights=weights, cfg=spec.cfg,
                             uncond_context=bc(uncond_context),
                             uncond_pooled=bc(uncond_pooled))
        if progress is not None:
            from .progress import wrap_denoiser

            den = wrap_denoiser(den, progress[0], progress[1])
        x0 = sample(spec.sampler, den, x, sigmas, key=key)
        if not decode:
            return x0
        images = self.vae.decode(
            x0, params=None if weights is None else weights["vae_dec"])
        return jnp.clip(images / 2.0 + 0.5, 0.0, 1.0)

    # --- mode 1: dp seed fan-out -------------------------------------------

    def generate_fn(self, mesh: Mesh, spec: FlowSpec,
                    axis: str = constants.AXIS_DATA,
                    progress: bool = False):
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        lat_hw = (spec.height // ds, spec.width // ds)
        # spec.cfg != 1.0 (SD3-family true CFG) adds replicated
        # uncond_context/uncond_pooled inputs; arity is a function of
        # spec.cfg alone, so the compile cache (keyed on spec) stays
        # consistent
        use_cfg = spec.cfg != 1.0

        def shard_body(weights, key, context, pooled, uncond_context=None,
                       uncond_pooled=None, token=None):
            k = participant_key(key, axis)
            prog = ((token, jax.lax.axis_index(axis))
                    if token is not None else None)
            return self._sample_and_decode(k, context, pooled, spec,
                                           spec.per_device_batch, sigmas,
                                           lat_hw, weights=weights,
                                           progress=prog,
                                           uncond_context=uncond_context,
                                           uncond_pooled=uncond_pooled)

        per_shard = shard_body
        in_specs = (P(), P(), P(None, None, None), P(None, None))
        if use_cfg:
            in_specs += (P(None, None, None), P(None, None))
        if progress:
            if not use_cfg:
                # the 5th positional must skip the uncond slots
                per_shard = (lambda w, key, c, pl, token:
                             shard_body(w, key, c, pl, None, None, token))
            in_specs += (P(),)     # traced int32 token, replicated
        f = shard_map(
            per_shard, mesh=mesh, in_specs=in_specs,
            out_specs=P(axis, None, None, None),
        )
        jitted = jax.jit(f)
        weights = self._weights()

        return bind_weights(jitted, weights, label="flow_dp",
                            steps=spec.steps)

    _CACHE_MAX = 8

    def _cached_fn(self, mesh: Mesh, spec: FlowSpec,
                   progress: bool = False, mode: str = "dp",
                   axis: Optional[str] = None):
        """Value-keyed compile cache (same discipline as
        ``Txt2ImgPipeline._cached_fn`` — without it every node execution
        re-traces the whole sampler). Serves BOTH execution modes: ``dp``
        seed fan-out and ``sp`` ring-attention sharding share the cache,
        keyed by mode so a workflow that alternates between them never
        thrashes recompiles."""
        from .pipeline import cached_build, mesh_cache_key

        if mode == "sp":
            # normalize the key: default axis resolves BEFORE keying so
            # axis=None and axis="sp" hit the same compiled program, and
            # sp has no progress path — a progress=True key would memoize
            # a fn that silently drops it
            axis = axis or constants.AXIS_SEQUENCE
            if progress:
                raise NotImplementedError(
                    "progress streaming is not wired through sp mode")

        def build():
            if mode == "sp":
                return self.generate_sp_fn(mesh, spec, axis=axis)
            return self.generate_fn(mesh, spec, progress=progress)

        key = (mesh_cache_key(mesh), spec, progress, mode, axis)
        return cached_build(self, key, build, self._CACHE_MAX)

    def generate(self, mesh: Mesh, spec: FlowSpec, seed: int,
                 context: jax.Array, pooled: jax.Array,
                 progress_token=None,
                 uncond_context: Optional[jax.Array] = None,
                 uncond_pooled: Optional[jax.Array] = None) -> jax.Array:
        """One-shot generate; ``progress_token`` enables per-step x0
        streaming (``cluster/progress.ProgressTracker.start``).
        ``uncond_context``/``uncond_pooled`` carry the negative
        conditioning when ``spec.cfg != 1.0`` (SD3-family true CFG) —
        required then, ignored otherwise."""
        self._require_uncond(spec, uncond_context)
        fn = self._cached_fn(mesh, spec,
                             progress=progress_token is not None)
        args = [jax.random.key(seed), context, pooled]
        if spec.cfg != 1.0:
            if uncond_pooled is None:
                uncond_pooled = jnp.zeros_like(pooled)
            args += [uncond_context, uncond_pooled]
        if progress_token is not None:
            args.append(jnp.asarray(progress_token, jnp.int32))
        return fn(*args)

    @staticmethod
    def _require_uncond(spec: FlowSpec, uncond_context) -> None:
        if spec.cfg != 1.0 and uncond_context is None:
            raise ValueError(
                f"FlowSpec.cfg={spec.cfg} but no negative conditioning "
                "was provided — pass uncond_context/uncond_pooled (the "
                "FlowSampler node's 'negative' input). FLUX-dev distilled "
                "guidance wants cfg=1.0 with the 'guidance' field.")

    # --- mode 1c: host offload (model too large for one chip, no pod) ------

    def offload_executor(self, params=None,
                         resident_bytes: Optional[int] = None,
                         stream_dtype: Optional[str] = None):
        """Build-or-fetch the cached ``OffloadedFlux`` executor (resident
        upload + compiled programs — minutes at FLUX scale, so cached
        like every other mode; ``bench.py`` reads residency stats off the
        same instance the product path runs)."""
        from .offload import OffloadedFlux, normalize_stream_dtype
        from .pipeline import cached_build

        src = self.dit_params if params is None else params
        sd = normalize_stream_dtype(stream_dtype)
        return cached_build(
            self, ("offload", resident_bytes, sd, id(src)),
            lambda: OffloadedFlux(self.dit, src,
                                  resident_bytes=resident_bytes,
                                  stream_dtype=sd),
            self._CACHE_MAX)

    def generate_offloaded(self, spec: FlowSpec, seed: int,
                           context: jax.Array, pooled: jax.Array,
                           params=None,
                           resident_bytes: Optional[int] = None,
                           stream_dtype: Optional[str] = None,
                           on_step=None, progress_token=None,
                           should_stop=None) -> jax.Array:
        """ONE image on ONE device with weights beyond the HBM budget
        held host-side (``diffusion/offload.py``) — the single-chip
        answer to FLUX-12B's 24 GB of bf16 weights (CDT_OFFLOAD; dp×tp
        over a pod is the fast path when more chips exist). Under the
        default fp8 ``stream_dtype`` the quantized block set usually
        fits resident, nothing streams per step, and the WHOLE sigma
        ladder runs as one compiled program (in-trace progress via
        ``progress_token``); streamed executors fall back to the python
        ladder with host-side ``on_step``. ``"native"`` keeps exact
        dtypes. ``params`` may be a host-numpy tree (the usual case: a
        full-size init can't live on device)."""
        from .offload import sample_euler_py

        if spec.per_device_batch != 1 or context.shape[0] != 1:
            raise ValueError(
                "offloaded generation is single-image (batch 1): the "
                "streamed weight window serves one latent at a time")
        if spec.cfg != 1.0:
            raise ValueError(
                "true CFG (spec.cfg != 1.0) is not wired through the "
                "offload executor — use cfg=1.0 with FLUX distilled "
                "'guidance', or run the dp/sp paths")
        from .offload import ladder_mode

        if ladder_mode() == "step" and spec.sampler != "euler":
            # fail BEFORE the minutes-long quantize/upload — this half
            # of the euler-only rule needs no executor to decide
            raise ValueError(
                "the per-step offloaded ladder supports euler only "
                f"(got {spec.sampler!r}); fully-resident executors "
                "with CDT_OFFLOAD_LADDER=jit run every sampler")
        off = self.offload_executor(params, resident_bytes, stream_dtype)
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        lat_h, lat_w = spec.height // ds, spec.width // ds
        # same key derivation as dp shard 0, so offloaded == sharded run
        # (noise AND the sampler's ancestral draws)
        key = jax.random.fold_in(jax.random.key(seed), 0)
        x = jax.random.normal(
            key, (1, lat_h, lat_w, self.dit.config.in_channels),
            jnp.float32)
        if off.stacked and ladder_mode() == "jit":
            # the in-trace ladder supports EVERY registered sampler
            g = jnp.full((context.shape[0],), float(spec.guidance))
            x0 = off.sample_resident(
                x, sigmas, context, pooled, g, sampler=spec.sampler,
                key=key, progress_token=progress_token)
        else:
            # per-step loop: streamed executors, or CDT_OFFLOAD_LADDER=
            # step (interruptible serving) — resident executors still
            # run one fused program per forward. Euler-only: the python
            # ladder implements just the euler update.
            if spec.sampler != "euler":
                raise ValueError(
                    "the per-step offloaded ladder supports euler only "
                    f"(got {spec.sampler!r}); fully-resident executors "
                    "with CDT_OFFLOAD_LADDER=jit run every sampler")
            den = off.denoiser(context, pooled, spec.guidance)
            x0 = sample_euler_py(den, jax.device_put(x, off.device),
                                 sigmas, on_step=on_step,
                                 should_stop=should_stop)
        images = self.vae.decode(x0)
        return jnp.clip(images / 2.0 + 0.5, 0.0, 1.0)

    # --- mode 1b: dp×tp GSPMD (models too large for one chip) --------------

    def generate_tp_fn(self, mesh: Mesh, spec: FlowSpec,
                       dp_axis: str = constants.AXIS_DATA,
                       tp_axis: str = constants.AXIS_TENSOR):
        """Batch over ``dp`` AND weights over ``tp`` in one jit: parameters
        are placed with Megatron-style column/row rules
        (``parallel/tensor.py``) and GSPMD propagates the layouts +
        inserts the all-reduces. This is how FLUX-scale (12B) models run
        on 16 GB chips — a capability with no reference analogue (its
        workers each need the whole model in VRAM, README.md:186-189)."""
        from ..parallel.tensor import (DIT_TP_RULES, require_tp_match,
                                       shard_params, tp_fanout_call)

        if spec.cfg != 1.0:
            raise ValueError(
                "true CFG (spec.cfg != 1.0) is not wired through tp "
                "mode — use cfg=1.0 with FLUX distilled 'guidance', or "
                "run the dp/sp paths")
        sigmas = sigmas_flow(spec.steps, spec.shift)
        ds = self.vae.config.downscale
        lat_h, lat_w = spec.height // ds, spec.width // ds
        c = self.dit.config.in_channels
        B = mesh.shape[dp_axis] * spec.per_device_batch
        require_tp_match(self.dit_params, mesh, DIT_TP_RULES, tp_axis, "dit")
        # tp-placed params are passed as ARGUMENTS (committed sharded
        # arrays) — closure capture would serialize the full weight set
        # into the lowered module
        params = shard_params(self.dit_params, mesh, DIT_TP_RULES, tp_axis)
        vae_dec = self.vae.dec_params

        def run(params, vae_dec, keys, context, pooled):
            noise = jax.vmap(
                lambda k: jax.random.normal(k, (lat_h, lat_w, c), jnp.float32)
            )(keys)
            bc = lambda a: jnp.broadcast_to(a, (B,) + a.shape[1:])

            def denoise(x, sigma):
                t = jnp.broadcast_to(sigma, (B,))
                g = jnp.full((B,), spec.guidance)
                v = self.dit.apply(params, x, t, bc(context), bc(pooled), g)
                return x - sigma * v

            x0 = sample(spec.sampler, denoise, noise, sigmas, key=keys[0])
            images = self.vae.decode(x0, params=vae_dec)
            return jnp.clip(images / 2.0 + 0.5, 0.0, 1.0)

        return tp_fanout_call(jax.jit(run), (params, vae_dec), mesh,
                              dp_axis, B)

    # --- mode 2: sp single-image sharding ----------------------------------

    def generate_sp_fn(self, mesh: Mesh, spec: FlowSpec,
                       axis: str = constants.AXIS_SEQUENCE):
        """One image, latent rows sharded over ``axis``; ring attention
        inside every DiT block. Noise is drawn from the SAME key on the
        full latent then row-sliced per shard, so the sharded run is
        bit-comparable to a single-chip run of the same seed."""
        n_sh = mesh.shape[axis]
        ds = self.vae.config.downscale
        lat_h, lat_w = spec.height // ds, spec.width // ds
        p = self.dit.config.patch_size
        if (lat_h // p) % n_sh:
            raise ValueError(
                f"latent rows/patch ({lat_h}/{p}) must divide over {n_sh} shards")
        sigmas = sigmas_flow(spec.steps, spec.shift)
        rows_per = lat_h // n_sh
        use_cfg = spec.cfg != 1.0

        def per_shard(weights, key, context, pooled, uncond_context=None,
                      uncond_pooled=None):
            idx = jax.lax.axis_index(axis)
            c = self.dit.config.in_channels
            full_noise = jax.random.normal(key, (1, lat_h, lat_w, c), jnp.float32)
            x = jax.lax.dynamic_slice_in_dim(full_noise, idx * rows_per,
                                             rows_per, axis=1)
            den = self._denoiser(context, pooled, spec.guidance, sp_axis=axis,
                                 weights=weights, cfg=spec.cfg,
                                 uncond_context=uncond_context,
                                 uncond_pooled=uncond_pooled)
            x0 = sample(spec.sampler, den, x, sigmas, key=key)
            return x0

        in_specs = (P(), P(), P(None, None, None), P(None, None))
        if use_cfg:
            in_specs += (P(None, None, None), P(None, None))
        f = shard_map(
            per_shard, mesh=mesh,
            in_specs=in_specs,
            out_specs=P(None, axis, None, None),
            check_vma=False,
        )

        def run(weights, key, context, pooled, *uncond):
            latents = f(weights, key, context, pooled, *uncond)
            images = self.vae.decode(latents, params=weights["vae_dec"])
            return jnp.clip(images / 2.0 + 0.5, 0.0, 1.0)

        jitted = jax.jit(run)
        weights = self._weights()

        return bind_weights(jitted, weights, label="flow_sp",
                            steps=spec.steps)

    def generate_sp(self, mesh: Mesh, spec: FlowSpec, seed: int,
                    context: jax.Array, pooled: jax.Array,
                    uncond_context: Optional[jax.Array] = None,
                    uncond_pooled: Optional[jax.Array] = None) -> jax.Array:
        self._require_uncond(spec, uncond_context)
        fn = self._cached_fn(mesh, spec, mode="sp")
        args = [jax.random.key(seed), context, pooled]
        if spec.cfg != 1.0:
            if uncond_pooled is None:
                uncond_pooled = jnp.zeros_like(pooled)
            args += [uncond_context, uncond_pooled]
        return fn(*args)
