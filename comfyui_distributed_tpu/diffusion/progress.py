"""In-flight sampling progress: per-step x0 streaming out of compiled code.

The reference inherits per-step progress bars and live latent previews
from ComfyUI's executor hooks (its UI polls them; SURVEY "external
substrate"). In a jit-compiled world the sampler scan is one XLA program,
so progress must stream out *through* the compiled boundary:
``wrap_denoiser`` interposes on the (guided) denoiser and emits
``jax.debug.callback`` effects carrying ``(token, shard, sigma, x0)``.
Callbacks are asynchronous host effects — the TPU does not stall on them —
and the payload is one latent (`x0[:1]`, ~256 KB for SDXL), so the
overhead is negligible against a UNet step.

``token`` is a *traced* int32 scalar, so one compiled program serves every
job: the host allocates a fresh token per run and the callback routes on
its runtime value. Callbacks are unordered; ``sigma`` (strictly decreasing
over the ladder) is the ordering key the sink uses to keep the newest
preview and a monotonic step count.

This module is deliberately free of cluster/HTTP imports: sinks are
registered (``add_sink``) by ``cluster/progress.ProgressTracker``.

Multiple sinks may be registered at once (an embedded master+worker pair,
or two Controllers in one test process, each own a tracker): every event
is fanned out to every sink, and routing falls out of token uniqueness —
``next_token`` is a process-global allocator, so a tracker's job table
simply misses on tokens it didn't issue.
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Optional

import jax
import numpy as np

# sink(token:int, shard:int, sigma:float, x0:np.ndarray). Registry keyed by
# handle so removal is exact; empty = events dropped on the floor.
_LOCK = threading.Lock()
_SINKS: "dict[int, Callable]" = {}
_HANDLES = itertools.count(1)
_TOKENS = itertools.count(1)


def next_token() -> int:
    """Process-globally unique progress token. One compiled program, one
    callback route: uniqueness across *all* trackers is what lets every
    sink receive every event and key only on its own jobs."""
    with _LOCK:
        return next(_TOKENS)


def add_sink(fn: Callable) -> int:
    """Register an event sink; returns a handle for ``remove_sink``."""
    with _LOCK:
        handle = next(_HANDLES)
        _SINKS[handle] = fn
        return handle


def remove_sink(handle: int) -> None:
    with _LOCK:
        _SINKS.pop(handle, None)


def set_sink(fn: Optional[Callable]) -> None:
    """Legacy single-sink setter: clears the registry, then installs
    ``fn`` (if not None) as the only sink. Kept for tests/embedders that
    want exclusive capture."""
    with _LOCK:
        _SINKS.clear()
        if fn is not None:
            _SINKS[next(_HANDLES)] = fn


def get_sink() -> Optional[Callable]:
    """Any currently-registered sink (newest), or None. Legacy accessor."""
    with _LOCK:
        if not _SINKS:
            return None
        return _SINKS[max(_SINKS)]


def _dispatch(token, shard, sigma, x0) -> None:
    with _LOCK:
        sinks = list(_SINKS.values())
    for sink in sinks:
        try:
            sink(int(token), int(shard), float(sigma), np.asarray(x0))
        except Exception:  # a broken UI consumer must never kill a job
            pass


# model calls the wrapped (guided) denoiser makes per sampler step; CFG is
# batch-concatenated into one call (guidance.cfg_denoiser) so it doesn't
# multiply. Second-order samplers call twice per step EXCEPT their final
# step (sigma_next == 0 takes the single-call Euler fallback), so their
# exact total is 2*steps - 1 — an exact total keeps the progress bar from
# stalling one call short of 100% until finish() clamps it.
_SECOND_ORDER = {"heun", "dpmpp_sde", "res_2s", "res_2s_ancestral"}


def calls_per_step(sampler: str) -> int:
    return 2 if sampler in _SECOND_ORDER else 1


def total_calls(sampler: str, steps: int) -> int:
    if sampler in _SECOND_ORDER:
        return max(1, 2 * steps - 1)
    return steps


def wrap_denoiser(denoise, token, shard_index):
    """Interpose on a denoiser: after every model call, stream the current
    x0 estimate (first batch element) to the host sink. ``token`` may be a
    traced scalar; ``shard_index`` a traced ``axis_index`` under
    ``shard_map`` (each shard reports itself — the sink keys previews by
    shard and counts steps on shard 0 only)."""

    def wrapped(x, sigma):
        x0 = denoise(x, sigma)
        jax.debug.callback(_dispatch, token, shard_index, sigma, x0[:1])
        return x0

    return wrapped
