"""Noise schedules and sigma ladders (k-diffusion parameterization).

``NoiseSchedule`` holds the VP training schedule (alphas_cumprod) used to
map sigma ↔ model timestep for eps-prediction UNets; the ``sigmas_*``
functions build inference ladders (karras / normal / linear-flow), matching
the schedule names ComfyUI exposes so reference workflows translate 1:1.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class NoiseSchedule:
    """VP schedule: sigma_t = sqrt((1 - acp_t) / acp_t) over training steps."""

    alphas_cumprod: jax.Array       # [T] float32

    @property
    def sigmas(self) -> jax.Array:
        acp = self.alphas_cumprod
        return jnp.sqrt((1.0 - acp) / acp)

    @property
    def sigma_min(self) -> jax.Array:
        return self.sigmas[0]

    @property
    def sigma_max(self) -> jax.Array:
        return self.sigmas[-1]

    def timestep_for_sigma(self, sigma: jax.Array) -> jax.Array:
        """Continuous timestep index whose table sigma matches ``sigma``
        (linear interpolation in log-sigma, clipped to the table)."""
        log_s = jnp.log(jnp.maximum(self.sigmas, 1e-10))
        t = jnp.interp(
            jnp.log(jnp.maximum(sigma, 1e-10)), log_s, jnp.arange(log_s.shape[0], dtype=jnp.float32)
        )
        return t


def vp_schedule(
    num_steps: int = 1000,
    beta_start: float = 0.00085,
    beta_end: float = 0.012,
    kind: str = "scaled_linear",
) -> NoiseSchedule:
    """SD-family betas ("scaled_linear": linear in sqrt(beta))."""
    if kind == "scaled_linear":
        betas = jnp.linspace(beta_start ** 0.5, beta_end ** 0.5, num_steps) ** 2
    elif kind == "linear":
        betas = jnp.linspace(beta_start, beta_end, num_steps)
    else:
        raise ValueError(f"unknown beta schedule {kind!r}")
    return NoiseSchedule(jnp.cumprod(1.0 - betas))


def sigmas_karras(
    n: int, sigma_min: float, sigma_max: float, rho: float = 7.0
) -> jax.Array:
    """Karras et al. (2022) ladder; returns [n+1] descending, last = 0."""
    ramp = jnp.linspace(0, 1, n)
    min_inv = sigma_min ** (1 / rho)
    max_inv = sigma_max ** (1 / rho)
    sigmas = (max_inv + ramp * (min_inv - max_inv)) ** rho
    return jnp.concatenate([sigmas, jnp.zeros((1,))])


def sigmas_normal(n: int, schedule: NoiseSchedule) -> jax.Array:
    """Uniform-in-timestep ladder over the VP table ("normal" in ComfyUI)."""
    table = schedule.sigmas
    T = table.shape[0]
    t = jnp.linspace(T - 1, 0, n)
    sigmas = jnp.interp(t, jnp.arange(T, dtype=jnp.float32), table)
    return jnp.concatenate([sigmas, jnp.zeros((1,))])


def sigmas_exponential(n: int, sigma_min: float, sigma_max: float) -> jax.Array:
    """Log-uniform ladder (k-diffusion ``get_sigmas_exponential``)."""
    sigmas = jnp.exp(jnp.linspace(
        jnp.log(sigma_max), jnp.log(sigma_min), n))
    return jnp.concatenate([sigmas, jnp.zeros((1,))])


def sigmas_sgm_uniform(n: int, schedule: NoiseSchedule) -> jax.Array:
    """SGM-style uniform timesteps: like "normal" but the ladder ends at
    the table's sigma_min instead of duplicating the final step at the
    interpolated zero-point (ComfyUI "sgm_uniform" — the convention
    SDXL-refiner/turbo models were trained with)."""
    table = schedule.sigmas
    T = table.shape[0]
    t = jnp.linspace(T - 1, 0, n + 1)[:-1]
    sigmas = jnp.interp(t, jnp.arange(T, dtype=jnp.float32), table)
    return jnp.concatenate([sigmas, jnp.zeros((1,))])


def _beta_ppf(q: jax.Array, a: float, b: float) -> jax.Array:
    """Inverse regularized incomplete beta (Beta(a,b) quantile) by
    bisection on ``jax.scipy.special.betainc`` — scipy is not in this
    image, and the ladder is built host-side once per job, so 60 fixed
    halvings (≈1e−18 interval) are plenty."""
    from jax.scipy.special import betainc

    lo = jnp.zeros_like(q)
    hi = jnp.ones_like(q)
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        below = betainc(a, b, mid) < q
        lo = jnp.where(below, mid, lo)
        hi = jnp.where(below, hi, mid)
    return 0.5 * (lo + hi)


def sigmas_beta(n: int, schedule: NoiseSchedule, alpha: float = 0.6,
                beta: float = 0.6) -> jax.Array:
    """"beta" scheduler: timesteps placed at Beta(α,β) quantiles of the
    training table (ComfyUI's ``beta_scheduler`` recipe: ppf of
    1 − linspace[0,1), index rounded into the table, 0-terminated). The
    default α=β=0.6 front-loads steps at BOTH ends of the ladder —
    where diffusion needs resolution — relative to "normal"."""
    table = schedule.sigmas
    T = table.shape[0]
    ts = 1.0 - jnp.linspace(0.0, 1.0, n, endpoint=False)
    idx = jnp.rint(_beta_ppf(ts, alpha, beta) * (T - 1)).astype(jnp.int32)
    return jnp.concatenate([table[idx], jnp.zeros((1,))])


def sigmas_linear_quadratic(n: int, threshold_noise: float = 0.025,
                            linear_steps: int | None = None,
                            sigma_max: float = 1.0) -> jax.Array:
    """"linear_quadratic" scheduler (LTX-Video / movie-gen recipe): the
    inverted ladder 1−σ rises linearly to ``threshold_noise`` over the
    first ``linear_steps`` (default n//2), then quadratically to 1 —
    continuous in value and slope at the joint. For flow models
    σ ∈ [0, 1] directly; VP callers scale by their ``sigma_max``.
    Returns [n+1] descending, last = 0."""
    if n == 1:
        return jnp.array([1.0, 0.0]) * sigma_max
    ls = n // 2 if linear_steps is None else min(int(linear_steps), n)
    i = jnp.arange(n + 1, dtype=jnp.float32)
    linear = i * threshold_noise / max(ls, 1)
    qs = max(n - ls, 1)
    # quadratic segment a·j² + b·j + c over j = i − ls ∈ [0, qs], fitted
    # to: value threshold_noise and slope threshold_noise/ls at j=0
    # (C¹ joint), value 1 at j=qs
    slope = threshold_noise / max(ls, 1)
    a = (1.0 - threshold_noise - slope * qs) / (qs * qs)
    j = i - ls
    quad = a * j * j + slope * j + threshold_noise
    inv = jnp.where(i < ls, linear, quad)
    inv = inv.at[-1].set(1.0)
    return (1.0 - inv) * sigma_max


def sigmas_flow(n: int, shift: float = 1.0) -> jax.Array:
    """Rectified-flow ladder: t from 1→0 with resolution shift
    (sigma' = shift·sigma / (1 + (shift−1)·sigma)); FLUX/SD3 convention."""
    sigmas = jnp.linspace(1.0, 0.0, n + 1)
    if shift != 1.0:
        sigmas = shift * sigmas / (1.0 + (shift - 1.0) * sigmas)
    return sigmas
