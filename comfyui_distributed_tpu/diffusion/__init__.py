"""Diffusion numerics: noise schedules, samplers, guidance, pipelines.

The reference drives ComfyUI's ``common_ksampler`` for all of this
(``upscale/tile_ops.py:226-229``); here it is native JAX. Samplers operate in
k-diffusion sigma space (ComfyUI's convention) so step counts/schedules are
comparable, and every loop is a ``lax.scan`` with static step count — one
XLA compilation per (shape, steps) pair, fully on-device.
"""

from .schedules import (  # noqa: F401
    NoiseSchedule,
    vp_schedule,
    sigmas_beta,
    sigmas_karras,
    sigmas_linear_quadratic,
    sigmas_normal,
    sigmas_flow,
    sigmas_exponential,
    sigmas_sgm_uniform,
)
from .samplers import SAMPLERS, sample  # noqa: F401
from .guidance import cfg_denoiser  # noqa: F401
