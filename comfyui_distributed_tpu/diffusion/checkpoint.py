"""Latent checkpoints: the exact state of a denoise loop between segments.

A :class:`LatentCheckpoint` captures the FULL sampler carry at a segment
boundary (``diffusion/samplers.SamplerProgram``): the latent, every
multistep history slot (dpmpp_2m/3m_sde carry D-history and h-history,
uni_pc carries four state-shaped slots), the step cursor, and the run's
identity metadata (sampler, spec geometry, seed, dp width). Because the
samplers fold the key by GLOBAL step index and the carry round-trips
through host numpy bit-exactly, a resumed run — on this worker or any
other with the same mesh width — is bit-identical to an uninterrupted
one (tested in ``tests/test_checkpoint.py``).

Wire format: one ``.npz`` payload (header JSON + carry leaves) with a
SHA-256 recorded next to it. Every load re-checksums; a mismatch is
LOUD, the entry is dropped, and the caller recomputes — the
``cluster/cache/store.py`` corruption contract applied to checkpoints.
``to_payload()`` is the JSON-safe form that rides the existing
dispatch transport (``POST /distributed/queue`` / the checkpoint
routes).

The :class:`CheckpointStore` is the parking lot: a byte-capped in-memory
LRU tier plus an optional persisted tier (``CDT_CKPT_DIR``, atomic
tmp+replace writes, ``utils/jsonio`` index). Restore failures are
bounded: past ``CDT_PREEMPT_RESUME_RETRIES`` attempts the entry moves to
the dead-letter list (forensics survive) and the job restarts from
scratch instead of looping.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import io
import json
import os
import time
from collections import OrderedDict
from pathlib import Path
from typing import Optional

import numpy as np

from ..lint.lockorder import tracked_lock
from ..utils.jsonio import atomic_write_json, read_json
from ..utils.logging import debug_log, log

CHECKPOINT_VERSION = 1


class CheckpointError(Exception):
    """A checkpoint payload is structurally unusable (bad version,
    checksum mismatch, garbled npz)."""


class CheckpointRestoreError(Exception):
    """A checkpoint exists but cannot resume THIS run (identity
    mismatch: different sampler/geometry/seed/mesh width, or corrupt
    state). Counted against the resume-retry bound."""


class PreemptedError(Exception):
    """Raised out of a sampler node when the run was preempted at a
    segment boundary; carries the parked state."""

    def __init__(self, checkpoint: "LatentCheckpoint", reason: str):
        super().__init__(
            f"preempted@{checkpoint.step}/{checkpoint.total_steps} "
            f"({reason})")
        self.checkpoint = checkpoint
        self.reason = reason


def checksum(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


_ID_RE = __import__("re").compile(r"^[A-Za-z0-9._-]{1,128}$")


def valid_checkpoint_id(cid) -> bool:
    """Checkpoint ids name store keys AND files on the persisted tier —
    anything outside a conservative charset (no path separators, no
    control bytes) is rejected so a wire payload can never steer
    ``_entry_path`` outside ``CDT_CKPT_DIR``."""
    return isinstance(cid, str) and bool(_ID_RE.match(cid))


@dataclasses.dataclass
class LatentCheckpoint:
    """One parked denoise run. ``step`` is the NEXT global ladder index
    (``step`` steps are already folded into ``carry``); ``meta`` is the
    run-identity dict the resume site validates (sampler aside — that
    has its own field — it carries spec geometry, seed, dp width,
    prompt id)."""

    sampler: str
    step: int
    total_steps: int
    carry: tuple
    meta: dict = dataclasses.field(default_factory=dict)
    checkpoint_id: str = ""
    version: int = CHECKPOINT_VERSION

    @property
    def nbytes(self) -> int:
        return int(sum(np.asarray(a).nbytes for a in self.carry))

    # --- serialization ------------------------------------------------------

    def to_bytes(self) -> bytes:
        header = {
            "version": self.version,
            "sampler": self.sampler,
            "step": int(self.step),
            "total_steps": int(self.total_steps),
            "meta": self.meta,
            "n_leaves": len(self.carry),
        }
        arrays = {f"carry_{i}": np.asarray(a)
                  for i, a in enumerate(self.carry)}
        arrays["header"] = np.frombuffer(
            json.dumps(header, sort_keys=True).encode(), np.uint8)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, payload: bytes,
                   checkpoint_id: str = "") -> "LatentCheckpoint":
        try:
            with np.load(io.BytesIO(payload)) as z:
                header = json.loads(bytes(z["header"].tobytes()).decode())
                carry = tuple(z[f"carry_{i}"]
                              for i in range(int(header["n_leaves"])))
        except (KeyError, ValueError, OSError, json.JSONDecodeError) as e:
            raise CheckpointError(f"unreadable checkpoint payload: {e}")
        if header.get("version") != CHECKPOINT_VERSION:
            raise CheckpointError(
                f"checkpoint version {header.get('version')!r} != "
                f"{CHECKPOINT_VERSION} (refusing a cross-version restore)")
        return cls(sampler=header["sampler"], step=int(header["step"]),
                   total_steps=int(header["total_steps"]), carry=carry,
                   meta=dict(header.get("meta") or {}),
                   checkpoint_id=checkpoint_id)

    def to_payload(self) -> dict:
        """JSON-safe wire form (rides the queue/dispatch transport);
        the sha256 travels WITH the bytes so the receiving worker
        verifies integrity before parking."""
        payload = self.to_bytes()
        return {
            "version": CHECKPOINT_VERSION,
            "checkpoint_id": self.checkpoint_id,
            "sha256": checksum(payload),
            "data": base64.b64encode(payload).decode("ascii"),
        }

    @classmethod
    def from_payload(cls, obj: dict) -> "LatentCheckpoint":
        if not isinstance(obj, dict) or "data" not in obj:
            raise CheckpointError("checkpoint payload must be an object "
                                  "with a base64 'data' field")
        try:
            payload = base64.b64decode(obj["data"], validate=True)
        except Exception as e:  # noqa: BLE001 — any b64 failure is terminal
            raise CheckpointError(f"bad base64 checkpoint data: {e}")
        want = obj.get("sha256")
        if not want:
            # the checksum is NOT optional: an unverifiable payload is
            # an unusable payload (docstring contract everywhere else)
            raise CheckpointError(
                "checkpoint payload carries no sha256 — refusing an "
                "unverifiable restore")
        if checksum(payload) != want:
            raise CheckpointError(
                "checkpoint CHECKSUM MISMATCH on the wire — rejecting "
                "(a flipped bit must never resume a job)")
        cid = obj.get("checkpoint_id") or ""
        if cid and not valid_checkpoint_id(cid):
            # a hostile/garbled embedded id must never reach the
            # persisted tier's file paths; a fresh content-derived id
            # is assigned at park time instead
            cid = ""
        return cls.from_bytes(payload, checkpoint_id=cid)

    # --- identity -----------------------------------------------------------

    def validate_meta(self, expect: dict) -> None:
        """Raise :class:`CheckpointRestoreError` unless every key in
        ``expect`` matches this checkpoint's meta (plus the sampler
        name when given)."""
        for k, want in expect.items():
            have = (self.sampler if k == "sampler"
                    else self.meta.get(k))
            if have != want:
                raise CheckpointRestoreError(
                    f"checkpoint {self.checkpoint_id or '?'} does not "
                    f"match this run: {k}={have!r}, expected {want!r}")


def _ckpt_metrics():
    try:
        from .. import telemetry
        from ..telemetry import metrics as _tm

        return telemetry.enabled(), _tm
    except Exception:  # noqa: BLE001 — telemetry is never load-bearing
        return False, None


class _Parked:
    __slots__ = ("payload", "sha256", "step", "total_steps", "sampler",
                 "meta", "nbytes", "restore_attempts", "parked_at")

    def __init__(self, payload: bytes, ckpt: LatentCheckpoint):
        self.payload = payload
        self.sha256 = checksum(payload)
        self.step = ckpt.step
        self.total_steps = ckpt.total_steps
        self.sampler = ckpt.sampler
        self.meta = dict(ckpt.meta)
        self.nbytes = len(payload)
        self.restore_attempts = 0
        self.parked_at = time.monotonic()


class CheckpointStore:
    """Byte-capped LRU over serialized checkpoints, with an optional
    checksummed persisted tier and bounded-restore dead-lettering."""

    def __init__(self, max_bytes: Optional[int] = None,
                 directory: "Path | str | None" = None,
                 resume_retries: Optional[int] = None):
        from ..utils import constants

        self.max_bytes = (constants.CKPT_MEM_BYTES.get()
                          if max_bytes is None else int(max_bytes))
        if directory is None:
            directory = constants.CKPT_DIR.get()
        self.dir = Path(directory) if directory else None
        self.resume_retries = (constants.PREEMPT_RESUME_RETRIES.get()
                               if resume_retries is None
                               else int(resume_retries))
        self._entries: "OrderedDict[str, _Parked]" = OrderedDict()
        self.dead: dict[str, dict] = {}
        # restore-attempt counts OUTLIVE the memory entry: a checkpoint
        # evicted to (or imported straight onto) the persisted tier must
        # still get its full CDT_PREEMPT_RESUME_RETRIES budget
        self._attempts: dict[str, int] = {}
        self._lock = tracked_lock("checkpoint.store", reentrant=True)
        self.counts = {"parked": 0, "restored": 0, "dropped": 0,
                       "evicted": 0, "corrupt": 0, "dead_lettered": 0}

    # --- parking ------------------------------------------------------------

    def park(self, ckpt: LatentCheckpoint) -> str:
        """Serialize + store; returns the checkpoint id (content sha
        prefixed with the step cursor for log readability). An invalid
        caller-supplied id is replaced, never trusted — ids become file
        names on the persisted tier."""
        payload = ckpt.to_bytes()
        cid = ckpt.checkpoint_id
        if not valid_checkpoint_id(cid):
            cid = f"ck_{ckpt.step:04d}_{checksum(payload)[:16]}"
        entry = _Parked(payload, ckpt)
        with self._lock:
            existing = self._entries.get(cid)
            if existing is not None and existing.sha256 != entry.sha256:
                # a caller-supplied id colliding with DIFFERENT parked
                # state (e.g. a hostile/buggy wire import reusing a
                # live id) must not clobber someone else's checkpoint
                fresh = f"ck_{ckpt.step:04d}_{entry.sha256[:16]}"
                log(f"checkpoint id collision: {cid} holds different "
                    f"state — parking the new payload as {fresh}")
                cid = fresh
            self._entries.pop(cid, None)
            self._entries[cid] = entry
            self.counts["parked"] += 1
            self._evict_over_budget_locked(keep=cid)
        ckpt.checkpoint_id = cid
        if self.dir is not None:
            self._disk_put(cid, entry)
        self._export_gauges()
        return cid

    def _evict_over_budget_locked(self, keep: str) -> None:
        if self.max_bytes <= 0:
            return
        used = sum(e.nbytes for e in self._entries.values())
        for cid in list(self._entries):
            if used <= self.max_bytes:
                return
            if cid == keep:
                continue        # never evict the entry just parked
            used -= self._entries.pop(cid).nbytes
            self.counts["evicted"] += 1

    # --- retrieval ----------------------------------------------------------

    def get(self, checkpoint_id: str) -> Optional[LatentCheckpoint]:
        """Deserialize a parked checkpoint (memory first, then the
        persisted tier). Corruption is LOUD and the entry is dropped —
        the caller restarts from scratch rather than resuming garbage."""
        cid = str(checkpoint_id)
        with self._lock:
            entry = self._entries.get(cid)
            if entry is not None:
                self._entries.move_to_end(cid)
                payload, want = entry.payload, entry.sha256
            else:
                payload = want = None
        if payload is None and self.dir is not None:
            loaded = self._disk_get(cid)
            if loaded is None:
                return None
            payload, want = loaded
        if payload is None:
            return None
        if checksum(payload) != want:
            log(f"checkpoint {cid}: CHECKSUM MISMATCH — rejecting and "
                "dropping (the job restarts from scratch)")
            self._count_corrupt()
            self.drop(cid)
            return None
        try:
            return LatentCheckpoint.from_bytes(payload, checkpoint_id=cid)
        except CheckpointError as e:
            log(f"checkpoint {cid}: unreadable ({e}) — dropping")
            self._count_corrupt()
            self.drop(cid)
            return None

    def export_payload(self, checkpoint_id: str) -> Optional[dict]:
        """The wire form for cross-worker transfer (checkpoint routes) —
        built straight from the stored serialized payload (no
        deserialize/re-serialize round trip; the recorded sha256 IS the
        wire checksum)."""
        cid = str(checkpoint_id)
        with self._lock:
            entry = self._entries.get(cid)
            payload, want = ((entry.payload, entry.sha256)
                             if entry is not None else (None, None))
        if payload is None and self.dir is not None:
            loaded = self._disk_get(cid)
            if loaded is not None:
                payload, want = loaded
        if payload is None:
            return None
        return {"version": CHECKPOINT_VERSION, "checkpoint_id": cid,
                "sha256": want,
                "data": base64.b64encode(payload).decode("ascii")}

    # --- lifecycle ----------------------------------------------------------

    def drop(self, checkpoint_id: str) -> bool:
        cid = str(checkpoint_id)
        with self._lock:
            existed = self._entries.pop(cid, None) is not None
            self._attempts.pop(cid, None)
            if existed:
                self.counts["dropped"] += 1
        if self.dir is not None:
            self._disk_drop(cid)
        self._export_gauges()
        return existed

    def record_restore_failure(self, checkpoint_id: str,
                               reason: str) -> int:
        """One failed restore attempt. Returns the attempt count; at
        ``resume_retries`` the entry is dead-lettered (payload gone,
        forensics kept) and the caller must restart from scratch.
        Attempts are tracked independently of the memory tier — an
        entry living only on disk still gets its full retry budget."""
        cid = str(checkpoint_id)
        with self._lock:
            attempts = self._attempts.get(cid, 0) + 1
            self._attempts[cid] = attempts
            entry = self._entries.get(cid)
            if entry is not None:
                entry.restore_attempts = attempts
        if attempts >= self.resume_retries:
            self.dead_letter(cid, reason)
        return attempts

    def dead_letter(self, checkpoint_id: str, reason: str) -> None:
        cid = str(checkpoint_id)
        with self._lock:
            entry = self._entries.pop(cid, None)
            attempts = self._attempts.pop(cid, None)
            self.counts["dead_lettered"] += 1
            self.dead[cid] = {
                "checkpoint_id": cid, "reason": reason,
                "step": getattr(entry, "step", None),
                "sampler": getattr(entry, "sampler", None),
                "attempts": attempts if attempts is not None
                else getattr(entry, "restore_attempts", None),
            }
        if self.dir is not None:
            self._disk_drop(cid)
        log(f"checkpoint {cid} DEAD-LETTERED ({reason}) — the job "
            "restarts from scratch instead of looping on restore")
        enabled, _tm = _ckpt_metrics()
        if enabled:
            _tm.CHECKPOINT_DEAD_LETTERS.inc()
        self._export_gauges()

    def mark_restored(self, checkpoint_id: str) -> None:
        with self._lock:
            self.counts["restored"] += 1

    # --- persistence (mirrors cluster/cache/store.py) -----------------------

    def _index_path(self) -> Path:
        return self.dir / "checkpoint_index.json"

    def _entry_path(self, cid: str) -> Path:
        return self.dir / f"{cid}.ckpt"

    def _index_flock(self):
        """Advisory cross-PROCESS lock for the index read-merge-write —
        the cluster/cache/store.py contract: two workers sharing
        CDT_CKPT_DIR (the drain-migration deployment) must union their
        rows, not last-write-win a sidecar into an un-indexed orphan.
        Degrades to lockless where flock is unavailable — worst case a
        lost index row, never a wrong byte (entries are checksummed)."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            try:
                import fcntl
            except ImportError:
                yield
                return
            try:
                fd = os.open(self.dir / "checkpoint_index.lock",
                             os.O_CREAT | os.O_RDWR)
            except OSError:
                yield
                return
            try:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                except OSError:
                    pass
                yield
            finally:
                os.close(fd)

        return _cm()

    def _write_index(self, mutate) -> None:
        with self._lock, self._index_flock():
            data = read_json(self._index_path())
            entries = (data or {}).get("entries")
            entries = entries if isinstance(entries, dict) else {}
            mutate(entries)
            atomic_write_json(self._index_path(),
                              {"version": 1, "entries": entries})

    def _disk_put(self, cid: str, entry: _Parked) -> None:
        try:
            path = self._entry_path(cid)
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(entry.payload)
            os.replace(tmp, path)
            row = {"file": path.name, "sha256": entry.sha256,
                   "bytes": entry.nbytes, "step": entry.step,
                   "sampler": entry.sampler}
            self._write_index(lambda e: e.__setitem__(cid, row))
        except OSError as e:
            debug_log(f"checkpoint: persist of {cid} failed: {e}")

    def _disk_get(self, cid: str) -> "Optional[tuple[bytes, str]]":
        data = read_json(self._index_path())
        row = ((data or {}).get("entries") or {}).get(cid)
        if not isinstance(row, dict):
            return None
        try:
            payload = self._entry_path(cid).read_bytes()
        except OSError:
            return None
        want = row.get("sha256", "")
        if checksum(payload) != want:
            log(f"checkpoint {cid}: persisted CHECKSUM MISMATCH — "
                "rejecting and deleting")
            self._count_corrupt()
            self._disk_drop(cid)
            return None
        return payload, want

    def _disk_drop(self, cid: str) -> None:
        self._write_index(lambda e: e.pop(cid, None))
        try:
            self._entry_path(cid).unlink()
        except OSError:
            pass

    # --- introspection ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": sum(e.nbytes for e in self._entries.values()),
                "max_bytes": self.max_bytes,
                "persist_dir": str(self.dir) if self.dir else None,
                "parked": [
                    {"checkpoint_id": cid, "step": e.step,
                     "total_steps": e.total_steps, "sampler": e.sampler,
                     "bytes": e.nbytes, "attempts": e.restore_attempts}
                    for cid, e in self._entries.items()],
                "dead_letter": list(self.dead.values()),
                **{k: v for k, v in self.counts.items()},
            }

    def _count_corrupt(self) -> None:
        with self._lock:
            self.counts["corrupt"] += 1
        enabled, _tm = _ckpt_metrics()
        if enabled:
            _tm.CACHE_CORRUPT.labels(tier="checkpoint").inc()

    def _export_gauges(self) -> None:
        enabled, _tm = _ckpt_metrics()
        if not enabled:
            return
        with self._lock:
            mem = sum(e.nbytes for e in self._entries.values())
        _tm.CHECKPOINT_BYTES.labels(tier="memory").set(mem)
        if self.dir is not None:
            data = read_json(self._index_path())
            rows = ((data or {}).get("entries") or {})
            _tm.CHECKPOINT_BYTES.labels(tier="persisted").set(
                sum(int(r.get("bytes", 0)) for r in rows.values()
                    if isinstance(r, dict)))
