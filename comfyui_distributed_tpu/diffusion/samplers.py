"""Samplers as ``lax.scan`` loops in sigma space.

A sampler advances ``x`` down a sigma ladder using a *denoiser*
``denoise(x, sigma) -> x0_hat``. The denoiser hides the model
parameterization (eps-pred UNet, flow DiT) and any guidance — see
``guidance.py`` and ``pipeline.py``.

All samplers are data-dependent-control-flow-free: fixed step count, fixed
shapes, stochastic steps derive per-step keys with ``fold_in`` — so a whole
sampling run compiles to a single XLA while/scan and never returns to the
host between steps (the reference pays a Python round-trip per *tile* per
step through ComfyUI's sampler; SURVEY §3.3 "GPU HOT LOOP").
"""

from __future__ import annotations

from typing import Callable, Protocol

import jax
import jax.numpy as jnp

Denoiser = Callable[[jax.Array, jax.Array], jax.Array]   # (x, sigma[]) -> x0_hat


def _to_d(x: jax.Array, sigma: jax.Array, denoised: jax.Array) -> jax.Array:
    """Convert x0 prediction to the k-diffusion ODE derivative."""
    return (x - denoised) / jnp.maximum(sigma, 1e-10)


def sample_euler(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                 key: jax.Array | None = None) -> jax.Array:
    del key

    def step(x, i):
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        d = _to_d(x, sigma, denoised)
        return x + d * (sigma_next - sigma), None

    n = sigmas.shape[0] - 1
    x, _ = jax.lax.scan(step, x, jnp.arange(n))
    return x


def _ancestral_sigmas(sigma_from, sigma_to, eta):
    """Split a σ_from→σ_to transition into a deterministic step plus an
    ancestral noise injection (k-diffusion ``get_ancestral_step``)."""
    var_ratio = jnp.maximum(
        1.0 - (sigma_to / jnp.maximum(sigma_from, 1e-10)) ** 2, 0.0)
    sigma_up = jnp.minimum(sigma_to, eta * sigma_to * jnp.sqrt(var_ratio))
    sigma_down = jnp.sqrt(jnp.maximum(sigma_to ** 2 - sigma_up ** 2, 0.0))
    return sigma_down, sigma_up


def sample_euler_ancestral(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                           key: jax.Array, eta: float = 1.0) -> jax.Array:
    def step(x, i):
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        sigma_down, sigma_up = _ancestral_sigmas(sigma, sigma_next, eta)
        d = _to_d(x, sigma, denoised)
        x = x + d * (sigma_down - sigma)
        noise = jax.random.normal(jax.random.fold_in(key, i), x.shape, x.dtype)
        # last step has sigma_next == 0 → sigma_up == 0 → no noise added
        return x + noise * sigma_up, None

    n = sigmas.shape[0] - 1
    x, _ = jax.lax.scan(step, x, jnp.arange(n))
    return x


def sample_heun(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                key: jax.Array | None = None) -> jax.Array:
    del key

    def step(x, i):
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        d = _to_d(x, sigma, denoised)
        dt = sigma_next - sigma
        x_euler = x + d * dt

        def heun_correct(_):
            denoised2 = denoise(x_euler, sigma_next)
            d2 = _to_d(x_euler, sigma_next, denoised2)
            return x + (d + d2) / 2 * dt

        # at the final step sigma_next==0: plain euler (no second eval at σ=0)
        x = jax.lax.cond(sigma_next > 0, heun_correct, lambda _: x_euler, None)
        return x, None

    n = sigmas.shape[0] - 1
    x, _ = jax.lax.scan(step, x, jnp.arange(n))
    return x


def sample_dpmpp_2m(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                    key: jax.Array | None = None) -> jax.Array:
    """DPM-Solver++(2M): second-order multistep on log-sigma."""
    del key

    def t_of(sigma):
        return -jnp.log(jnp.maximum(sigma, 1e-10))

    def step(carry, i):
        x, old_denoised, have_old = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)

        def first_order(_):
            # exact Euler in exponential-integrator form
            return x * (sigma_next / sigma) + denoised * (1 - sigma_next / sigma)

        def second_order(_):
            h = t_of(sigma_next) - t_of(sigma)
            h_last = t_of(sigma) - t_of(sigmas[i - 1])
            r = h_last / jnp.maximum(h, 1e-10)
            denoised_d = (1 + 1 / (2 * r)) * denoised - (1 / (2 * r)) * old_denoised
            return x * (sigma_next / sigma) + denoised_d * (1 - sigma_next / sigma)

        use_second = jnp.logical_and(have_old, sigma_next > 0)
        x_new = jax.lax.cond(use_second, second_order, first_order, None)
        # sigma_next == 0: x -> denoised exactly
        x_new = jnp.where(sigma_next > 0, x_new, denoised)
        return (x_new, denoised, jnp.array(True)), None

    n = sigmas.shape[0] - 1
    init = (x, jnp.zeros_like(x), jnp.array(False))
    (x, _, _), _ = jax.lax.scan(step, init, jnp.arange(n))
    return x


def sample_ddim(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                key: jax.Array | None = None, eta: float = 0.0) -> jax.Array:
    """DDIM in sigma space. ``eta=0`` is the deterministic solver (the
    x0-form of Euler); ``eta>0`` interpolates toward ancestral sampling."""

    def step(x, i):
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        if eta and key is not None:
            sigma_down, sigma_up = _ancestral_sigmas(sigma, sigma_next, eta)
        else:
            sigma_down, sigma_up = sigma_next, jnp.zeros(())
        x = denoised + (x - denoised) * (sigma_down / jnp.maximum(sigma, 1e-10))
        if eta and key is not None:
            noise = jax.random.normal(jax.random.fold_in(key, i),
                                      x.shape, x.dtype)
            x = x + noise * sigma_up
        return x, None

    n = sigmas.shape[0] - 1
    x, _ = jax.lax.scan(step, x, jnp.arange(n))
    return x


def sample_lcm(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
               key: jax.Array) -> jax.Array:
    """Latent-consistency sampling: jump to x0, re-noise to the next
    sigma (k-diffusion ``sample_lcm``)."""

    def step(x, i):
        denoised = denoise(x, sigmas[i])
        sigma_next = sigmas[i + 1]
        noise = jax.random.normal(jax.random.fold_in(key, i),
                                  x.shape, x.dtype)
        return denoised + jnp.where(sigma_next > 0, sigma_next, 0.0) * noise, None

    n = sigmas.shape[0] - 1
    x, _ = jax.lax.scan(step, x, jnp.arange(n))
    return x


def sample_dpmpp_sde(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                     key: jax.Array, eta: float = 1.0, s_noise: float = 1.0,
                     r: float = 0.5) -> jax.Array:
    """DPM-Solver++ (SDE): single-step second-order with an ancestral
    noise injection at the midpoint and endpoint (k-diffusion
    ``sample_dpmpp_sde``)."""

    def t_of(sigma):
        return -jnp.log(jnp.maximum(sigma, 1e-10))

    def sigma_of(t):
        return jnp.exp(-t)

    def step(x, i):
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)

        def last(_):
            return denoised

        def stage(_):
            t, t_next = t_of(sigma), t_of(sigma_next)
            h = t_next - t
            s = t + h * r
            fac = 1.0 / (2.0 * r)
            # midpoint stage with its own ancestral split
            sd1, su1 = _ancestral_sigmas(sigma_of(t), sigma_of(s), eta)
            s_down = t_of(sd1)
            x2 = (sigma_of(s_down) / sigma_of(t)) * x \
                - jnp.expm1(t - s_down) * denoised
            noise1 = jax.random.normal(jax.random.fold_in(key, 2 * i),
                                       x.shape, x.dtype)
            x2 = x2 + noise1 * su1 * s_noise
            denoised2 = denoise(x2, sigma_of(s))
            # full step
            sd2, su2 = _ancestral_sigmas(sigma_of(t), sigma_of(t_next), eta)
            t_down = t_of(sd2)
            denoised_d = (1 - fac) * denoised + fac * denoised2
            x_new = (sigma_of(t_down) / sigma_of(t)) * x \
                - jnp.expm1(t - t_down) * denoised_d
            noise2 = jax.random.normal(jax.random.fold_in(key, 2 * i + 1),
                                       x.shape, x.dtype)
            return x_new + noise2 * su2 * s_noise

        return jax.lax.cond(sigma_next > 0, stage, last, None), None

    n = sigmas.shape[0] - 1
    x, _ = jax.lax.scan(step, x, jnp.arange(n))
    return x


def sample_dpmpp_2m_sde(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                        key: jax.Array, eta: float = 1.0,
                        s_noise: float = 1.0) -> jax.Array:
    """DPM-Solver++(2M) SDE, midpoint solver (k-diffusion
    ``sample_dpmpp_2m_sde``)."""

    def t_of(sigma):
        return -jnp.log(jnp.maximum(sigma, 1e-10))

    def step(carry, i):
        x, old_denoised, h_last, have_old = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)

        def last(_):
            return denoised, jnp.zeros(())

        def stage(_):
            h = t_of(sigma_next) - t_of(sigma)
            eta_h = eta * h
            x_new = (sigma_next / jnp.maximum(sigma, 1e-10)) \
                * jnp.exp(-eta_h) * x \
                - jnp.expm1(-h - eta_h) * denoised
            r = h_last / jnp.maximum(h, 1e-10)
            second = -jnp.expm1(-h - eta_h) * (0.5 / jnp.maximum(r, 1e-10)) \
                * (denoised - old_denoised)
            x_new = x_new + jnp.where(have_old, second, 0.0)
            noise = jax.random.normal(jax.random.fold_in(key, i),
                                      x.shape, x.dtype)
            x_new = x_new + noise * sigma_next * s_noise \
                * jnp.sqrt(jnp.maximum(-jnp.expm1(-2.0 * eta_h), 0.0))
            return x_new, h

        x_new, h = jax.lax.cond(sigma_next > 0, stage, last, None)
        return (x_new, denoised, h, jnp.array(True)), None

    n = sigmas.shape[0] - 1
    init = (x, jnp.zeros_like(x), jnp.zeros(()), jnp.array(False))
    (x, _, _, _), _ = jax.lax.scan(step, init, jnp.arange(n))
    return x


SAMPLERS: dict[str, Callable] = {
    "euler": sample_euler,
    "euler_ancestral": sample_euler_ancestral,
    "heun": sample_heun,
    "dpmpp_2m": sample_dpmpp_2m,
    "ddim": sample_ddim,
    "lcm": sample_lcm,
    "dpmpp_sde": sample_dpmpp_sde,
    "dpmpp_2m_sde": sample_dpmpp_2m_sde,
}


def sample(
    name: str,
    denoise: Denoiser,
    x: jax.Array,
    sigmas: jax.Array,
    key: jax.Array | None = None,
    **kwargs,
) -> jax.Array:
    try:
        fn = SAMPLERS[name]
    except KeyError:
        raise ValueError(f"unknown sampler {name!r}; have {sorted(SAMPLERS)}")
    return fn(denoise, x, sigmas, key, **kwargs)
