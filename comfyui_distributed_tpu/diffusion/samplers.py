"""Samplers as ``lax.scan`` loops in sigma space — in resumable form.

A sampler advances ``x`` down a sigma ladder using a *denoiser*
``denoise(x, sigma) -> x0_hat``. The denoiser hides the model
parameterization (eps-pred UNet, flow DiT) and any guidance — see
``guidance.py`` and ``pipeline.py``.

All samplers are data-dependent-control-flow-free: fixed step count, fixed
shapes, stochastic steps derive per-step keys with ``fold_in`` — so a whole
sampling run compiles to a single XLA while/scan and never returns to the
host between steps (the reference pays a Python round-trip per *tile* per
step through ComfyUI's sampler; SURVEY §3.3 "GPU HOT LOOP").

Since ISSUE 14 every sampler is expressed as a :class:`SamplerProgram` —
an explicit ``(init, step, extract)`` triple over a pytree *carry* — so
the scan can be cut at ANY step boundary: :func:`run_segment` runs steps
``[start, start+length)`` and returns the carry, which (with the step
cursor) is the complete sampler state. That is what makes step-granular
preemption exact (``diffusion/checkpoint.py``): a run split into
segments, round-tripped through host numpy between them, is bit-identical
to the monolithic scan because each step applies the SAME step closure to
the SAME carry values at the SAME global index ``i`` — stochastic
samplers included, since their per-step noise is ``fold_in(key, i)`` of
the global index, never of a per-segment counter.

Carry contract (relied on by the sharded preemptible pipeline): every
leaf is either *state-shaped* (same shape as ``x`` — latents and D/x0
history slots) or a rank-0 scalar derived only from ``(sigmas, step
index)`` (step-count flags, h-history) — scalars are therefore identical
across dp shards and may be carried replicated.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

Denoiser = Callable[[jax.Array, jax.Array], jax.Array]   # (x, sigma[]) -> x0_hat


@dataclasses.dataclass(frozen=True)
class SamplerProgram:
    """One sampler bound to ``(denoise, sigmas, key, kwargs)``.

    ``init(x) -> carry`` builds the scan carry (a tuple of arrays; slot 0
    is always the evolving latent unless ``extract`` says otherwise);
    ``step(carry, i) -> carry`` advances one GLOBAL ladder index;
    ``extract(carry) -> x0`` picks the output slot after the final step.
    ``init`` and ``extract`` are pure structure — they never call the
    denoiser — so carry shapes can be derived abstractly
    (``jax.eval_shape``) and the output extracted without rebuilding the
    model closure."""

    name: str
    n_steps: int
    init: Callable[[jax.Array], tuple]
    step: Callable[[tuple, jax.Array], tuple]
    extract: Callable[[tuple], jax.Array]


def run_segment(prog: SamplerProgram, carry: tuple, start,
                length: int) -> tuple:
    """Advance ``length`` steps from global index ``start``.

    ``start`` may be traced (one compiled segment program serves every
    offset of that length); ``length`` is static. The xs are
    ``start + arange(length)`` so the step closure sees the same global
    indices the monolithic scan would."""
    if length <= 0:
        return carry
    xs = jnp.asarray(start, jnp.int32) + jnp.arange(length, dtype=jnp.int32)
    carry, _ = jax.lax.scan(lambda c, i: (prog.step(c, i), None), carry, xs)
    return carry


def run_program(prog: SamplerProgram, x: jax.Array) -> jax.Array:
    """The monolithic run: init → scan the whole ladder → extract."""
    carry = prog.init(x)
    carry, _ = jax.lax.scan(lambda c, i: (prog.step(c, i), None), carry,
                            jnp.arange(prog.n_steps, dtype=jnp.int32))
    return prog.extract(carry)


def _extract_first(carry: tuple) -> jax.Array:
    return carry[0]


def _to_d(x: jax.Array, sigma: jax.Array, denoised: jax.Array) -> jax.Array:
    """Convert x0 prediction to the k-diffusion ODE derivative."""
    return (x - denoised) / jnp.maximum(sigma, 1e-10)


def _ancestral_sigmas(sigma_from, sigma_to, eta):
    """Split a σ_from→σ_to transition into a deterministic step plus an
    ancestral noise injection (k-diffusion ``get_ancestral_step``)."""
    var_ratio = jnp.maximum(
        1.0 - (sigma_to / jnp.maximum(sigma_from, 1e-10)) ** 2, 0.0)
    sigma_up = jnp.minimum(sigma_to, eta * sigma_to * jnp.sqrt(var_ratio))
    sigma_down = jnp.sqrt(jnp.maximum(sigma_to ** 2 - sigma_up ** 2, 0.0))
    return sigma_down, sigma_up


def _t_of(sigma):
    """log-SNR time t = −log σ (the exponential-integrator clock all the
    multistep solvers below share)."""
    return -jnp.log(jnp.maximum(sigma, 1e-10))


def _i0(h):
    """∫₀ʰ e^{τ−h} dτ = 1 − e^{−h} — weight of a constant D over one
    exponential-integrator step."""
    return -jnp.expm1(-h)


# --- program builders -------------------------------------------------------


def _euler_program(denoise, sigmas, key=None) -> SamplerProgram:
    del key

    def step(carry, i):
        (x,) = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        d = _to_d(x, sigma, denoised)
        return (x + d * (sigma_next - sigma),)

    return SamplerProgram("euler", sigmas.shape[0] - 1,
                          lambda x: (x,), step, _extract_first)


def _euler_ancestral_program(denoise, sigmas, key,
                             eta: float = 1.0) -> SamplerProgram:
    def step(carry, i):
        (x,) = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        sigma_down, sigma_up = _ancestral_sigmas(sigma, sigma_next, eta)
        d = _to_d(x, sigma, denoised)
        x = x + d * (sigma_down - sigma)
        noise = jax.random.normal(jax.random.fold_in(key, i), x.shape, x.dtype)
        # last step has sigma_next == 0 → sigma_up == 0 → no noise added
        return (x + noise * sigma_up,)

    return SamplerProgram("euler_ancestral", sigmas.shape[0] - 1,
                          lambda x: (x,), step, _extract_first)


def _heun_program(denoise, sigmas, key=None) -> SamplerProgram:
    del key

    def step(carry, i):
        (x,) = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        d = _to_d(x, sigma, denoised)
        dt = sigma_next - sigma
        x_euler = x + d * dt

        def heun_correct(_):
            denoised2 = denoise(x_euler, sigma_next)
            d2 = _to_d(x_euler, sigma_next, denoised2)
            return x + (d + d2) / 2 * dt

        # at the final step sigma_next==0: plain euler (no second eval at σ=0)
        x = jax.lax.cond(sigma_next > 0, heun_correct, lambda _: x_euler, None)
        return (x,)

    return SamplerProgram("heun", sigmas.shape[0] - 1,
                          lambda x: (x,), step, _extract_first)


def _dpmpp_2m_program(denoise, sigmas, key=None) -> SamplerProgram:
    """DPM-Solver++(2M): second-order multistep on log-sigma."""
    del key

    def t_of(sigma):
        return -jnp.log(jnp.maximum(sigma, 1e-10))

    def step(carry, i):
        x, old_denoised, have_old = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)

        def first_order(_):
            # exact Euler in exponential-integrator form
            return x * (sigma_next / sigma) + denoised * (1 - sigma_next / sigma)

        def second_order(_):
            h = t_of(sigma_next) - t_of(sigma)
            h_last = t_of(sigma) - t_of(sigmas[i - 1])
            r = h_last / jnp.maximum(h, 1e-10)
            denoised_d = (1 + 1 / (2 * r)) * denoised - (1 / (2 * r)) * old_denoised
            return x * (sigma_next / sigma) + denoised_d * (1 - sigma_next / sigma)

        use_second = jnp.logical_and(have_old, sigma_next > 0)
        x_new = jax.lax.cond(use_second, second_order, first_order, None)
        # sigma_next == 0: x -> denoised exactly
        x_new = jnp.where(sigma_next > 0, x_new, denoised)
        return (x_new, denoised, jnp.array(True))

    return SamplerProgram(
        "dpmpp_2m", sigmas.shape[0] - 1,
        lambda x: (x, jnp.zeros_like(x), jnp.array(False)),
        step, _extract_first)


def _ddim_program(denoise, sigmas, key=None,
                  eta: float = 0.0) -> SamplerProgram:
    """DDIM in sigma space. ``eta=0`` is the deterministic solver (the
    x0-form of Euler); ``eta>0`` interpolates toward ancestral sampling."""

    def step(carry, i):
        (x,) = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        if eta and key is not None:
            sigma_down, sigma_up = _ancestral_sigmas(sigma, sigma_next, eta)
        else:
            sigma_down, sigma_up = sigma_next, jnp.zeros(())
        x = denoised + (x - denoised) * (sigma_down / jnp.maximum(sigma, 1e-10))
        if eta and key is not None:
            noise = jax.random.normal(jax.random.fold_in(key, i),
                                      x.shape, x.dtype)
            x = x + noise * sigma_up
        return (x,)

    return SamplerProgram("ddim", sigmas.shape[0] - 1,
                          lambda x: (x,), step, _extract_first)


def _lcm_program(denoise, sigmas, key) -> SamplerProgram:
    """Latent-consistency sampling: jump to x0, re-noise to the next
    sigma (k-diffusion ``sample_lcm``)."""

    def step(carry, i):
        (x,) = carry
        denoised = denoise(x, sigmas[i])
        sigma_next = sigmas[i + 1]
        noise = jax.random.normal(jax.random.fold_in(key, i),
                                  x.shape, x.dtype)
        return (denoised + jnp.where(sigma_next > 0, sigma_next, 0.0) * noise,)

    return SamplerProgram("lcm", sigmas.shape[0] - 1,
                          lambda x: (x,), step, _extract_first)


def _dpmpp_sde_program(denoise, sigmas, key, eta: float = 1.0,
                       s_noise: float = 1.0,
                       r: float = 0.5) -> SamplerProgram:
    """DPM-Solver++ (SDE): single-step second-order with an ancestral
    noise injection at the midpoint and endpoint (k-diffusion
    ``sample_dpmpp_sde``)."""

    def t_of(sigma):
        return -jnp.log(jnp.maximum(sigma, 1e-10))

    def sigma_of(t):
        return jnp.exp(-t)

    def step(carry, i):
        (x,) = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)

        def last(_):
            return denoised

        def stage(_):
            t, t_next = t_of(sigma), t_of(sigma_next)
            h = t_next - t
            s = t + h * r
            fac = 1.0 / (2.0 * r)
            # midpoint stage with its own ancestral split
            sd1, su1 = _ancestral_sigmas(sigma_of(t), sigma_of(s), eta)
            s_down = t_of(sd1)
            x2 = (sigma_of(s_down) / sigma_of(t)) * x \
                - jnp.expm1(t - s_down) * denoised
            noise1 = jax.random.normal(jax.random.fold_in(key, 2 * i),
                                       x.shape, x.dtype)
            x2 = x2 + noise1 * su1 * s_noise
            denoised2 = denoise(x2, sigma_of(s))
            # full step
            sd2, su2 = _ancestral_sigmas(sigma_of(t), sigma_of(t_next), eta)
            t_down = t_of(sd2)
            denoised_d = (1 - fac) * denoised + fac * denoised2
            x_new = (sigma_of(t_down) / sigma_of(t)) * x \
                - jnp.expm1(t - t_down) * denoised_d
            noise2 = jax.random.normal(jax.random.fold_in(key, 2 * i + 1),
                                       x.shape, x.dtype)
            return x_new + noise2 * su2 * s_noise

        return (jax.lax.cond(sigma_next > 0, stage, last, None),)

    return SamplerProgram("dpmpp_sde", sigmas.shape[0] - 1,
                          lambda x: (x,), step, _extract_first)


def _dpmpp_2m_sde_program(denoise, sigmas, key, eta: float = 1.0,
                          s_noise: float = 1.0) -> SamplerProgram:
    """DPM-Solver++(2M) SDE, midpoint solver (k-diffusion
    ``sample_dpmpp_2m_sde``)."""

    def t_of(sigma):
        return -jnp.log(jnp.maximum(sigma, 1e-10))

    def step(carry, i):
        x, old_denoised, h_last, have_old = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)

        def last(_):
            return denoised, jnp.zeros(())

        def stage(_):
            h = t_of(sigma_next) - t_of(sigma)
            eta_h = eta * h
            x_new = (sigma_next / jnp.maximum(sigma, 1e-10)) \
                * jnp.exp(-eta_h) * x \
                - jnp.expm1(-h - eta_h) * denoised
            r = h_last / jnp.maximum(h, 1e-10)
            second = -jnp.expm1(-h - eta_h) * (0.5 / jnp.maximum(r, 1e-10)) \
                * (denoised - old_denoised)
            x_new = x_new + jnp.where(have_old, second, 0.0)
            noise = jax.random.normal(jax.random.fold_in(key, i),
                                      x.shape, x.dtype)
            x_new = x_new + noise * sigma_next * s_noise \
                * jnp.sqrt(jnp.maximum(-jnp.expm1(-2.0 * eta_h), 0.0))
            return x_new, h

        x_new, h = jax.lax.cond(sigma_next > 0, stage, last, None)
        return (x_new, denoised, h, jnp.array(True))

    return SamplerProgram(
        "dpmpp_2m_sde", sigmas.shape[0] - 1,
        lambda x: (x, jnp.zeros_like(x), jnp.zeros(()), jnp.array(False)),
        step, _extract_first)


def _res_2m_program(denoise, sigmas, key=None,
                    eta: float = 0.0) -> SamplerProgram:
    """RES second-order multistep (the RES4LYF-family ``res_2m``):
    exponential Adams–Bashforth on the data prediction.

    Exact variation-of-constants: with t = −log σ the probability-flow
    ODE is dx/dt + x = D(x), so
    ``x_{n+1} = e^{−h} x_n + ∫₀ʰ e^{τ−h} D(t_n+τ) dτ``. Approximating D
    linearly through (t_{n−1}, D_{n−1}), (t_n, D_n) and integrating the
    e^{τ−h}-weighted polynomial EXACTLY gives
    ``x_{n+1} = e^{−h} x_n + I0·D_n + (h − I0)·(D_n − D_{n−1})/h_prev``
    (I0 = 1−e^{−h}) — this differs from dpmpp_2m, whose correction uses
    the midpoint coefficient 1/(2r) instead of the exact first-moment
    integral. ``eta > 0`` adds an ancestral split per step (the
    ``res_2m_ancestral`` entry binds eta=1)."""

    def step(carry, i):
        x, old_denoised, h_prev, have_old = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        if eta:
            sigma_down, sigma_up = _ancestral_sigmas(sigma, sigma_next, eta)
        else:
            sigma_down, sigma_up = sigma_next, jnp.zeros(())
        h = _t_of(sigma_down) - _t_of(sigma)
        i0 = _i0(h)
        slope = (denoised - old_denoised) / jnp.maximum(h_prev, 1e-10)
        x_new = jnp.exp(-h) * x + i0 * denoised \
            + jnp.where(have_old, (h - i0), 0.0) * slope
        if eta:
            noise = jax.random.normal(jax.random.fold_in(key, i),
                                      x.shape, x.dtype)
            x_new = x_new + noise * sigma_up
        x_new = jnp.where(sigma_next > 0, x_new, denoised)
        h_real = _t_of(sigma_next) - _t_of(sigma)
        return (x_new, denoised, h_real, jnp.array(True))

    return SamplerProgram(
        "res_2m", sigmas.shape[0] - 1,
        lambda x: (x, jnp.zeros_like(x), jnp.zeros(()), jnp.array(False)),
        step, _extract_first)


def _res_2s_program(denoise, sigmas, key=None, eta: float = 0.0,
                    c2: float = 0.5) -> SamplerProgram:
    """RES second-order single-step (``res_2s``): two-stage exponential
    Runge–Kutta (Hochbruck–Ostermann ExpRK2) with midpoint stage c2.

    Stage:  ``x_s = e^{−c2·h} x + I0(c2·h)·D_n`` at σ_s = σ·e^{−c2·h};
    update: ``x_{n+1} = e^{−h} x + (I0 − Ψ)·D_n + Ψ·D_s`` with
    ``Ψ = (h − I0)/(c2·h)`` — satisfying the order-2 conditions
    b1+b2 = φ1, b2·c2 = φ2 for any c2 ∈ (0, 1]. Two model calls per
    step. ``eta > 0`` adds an ancestral split (``res_2s_ancestral``)."""

    def step(carry, i):
        (x,) = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)
        if eta:
            sigma_down, sigma_up = _ancestral_sigmas(sigma, sigma_next, eta)
        else:
            sigma_down, sigma_up = sigma_next, jnp.zeros(())

        def last(_):
            return denoised

        def stage(_):
            h = _t_of(sigma_down) - _t_of(sigma)
            ch = c2 * h
            x_s = jnp.exp(-ch) * x + _i0(ch) * denoised
            denoised_s = denoise(x_s, sigma * jnp.exp(-ch))
            i0 = _i0(h)
            psi = (h - i0) / jnp.maximum(ch, 1e-10)
            return jnp.exp(-h) * x + (i0 - psi) * denoised \
                + psi * denoised_s

        x_new = jax.lax.cond(sigma_next > 0, stage, last, None)
        if eta:
            noise = jax.random.normal(jax.random.fold_in(key, i),
                                      x.shape, x.dtype)
            x_new = x_new + jnp.where(sigma_next > 0, noise * sigma_up, 0.0)
        return (x_new,)

    return SamplerProgram("res_2s", sigmas.shape[0] - 1,
                          lambda x: (x,), step, _extract_first)


def _dpmpp_3m_sde_program(denoise, sigmas, key, eta: float = 1.0,
                          s_noise: float = 1.0) -> SamplerProgram:
    """DPM-Solver++(3M) SDE: third-order multistep with exponential-decay
    noise (the k-diffusion ``sample_dpmpp_3m_sde`` algorithm, transcribed
    from its published update rule into a scan).

    Per step (h = Δt, h_eta = h·(eta+1)):
    ``x' = e^{−h_eta} x + I0(h_eta)·D`` plus, once two/three history
    points exist, divided-difference corrections weighted by
    ``φ2 = I0/h_eta·(−1)+1 … φ3 = φ2/h_eta − ½`` exactly as published;
    noise scale ``σ_next·√(1 − e^{−2·h·eta})``."""

    def step(carry, i):
        x, d1, d2, h1, h2, count = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        denoised = denoise(x, sigma)

        def last(_):
            return denoised, jnp.zeros(())

        def stage(_):
            h = _t_of(sigma_next) - _t_of(sigma)
            h_eta = h * (eta + 1.0)
            x_new = jnp.exp(-h_eta) * x + _i0(h_eta) * denoised
            phi2 = jnp.expm1(-h_eta) / h_eta + 1.0
            phi3 = phi2 / h_eta - 0.5
            r0 = h1 / h
            r1 = h2 / h
            d1_0 = (denoised - d1) / jnp.maximum(r0, 1e-10)
            d1_1 = (d1 - d2) / jnp.maximum(r1, 1e-10)
            dd1 = d1_0 + (d1_0 - d1_1) * r0 / jnp.maximum(r0 + r1, 1e-10)
            dd2 = (d1_0 - d1_1) / jnp.maximum(r0 + r1, 1e-10)
            third = x_new + phi2 * dd1 - phi3 * dd2
            second = x_new + phi2 * d1_0
            x_new = jnp.where(count >= 2, third,
                              jnp.where(count == 1, second, x_new))
            if eta:
                noise = jax.random.normal(jax.random.fold_in(key, i),
                                          x.shape, x.dtype)
                x_new = x_new + noise * sigma_next * s_noise * jnp.sqrt(
                    jnp.maximum(-jnp.expm1(-2.0 * h * eta), 0.0))
            return x_new, h

        x_new, h = jax.lax.cond(sigma_next > 0, stage, last, None)
        return (x_new, denoised, d1, h, h1, count + 1)

    return SamplerProgram(
        "dpmpp_3m_sde", sigmas.shape[0] - 1,
        lambda x: (x, jnp.zeros_like(x), jnp.zeros_like(x), jnp.zeros(()),
                   jnp.zeros(()), jnp.int32(0)),
        step, _extract_first)


def _uni_pc_program(denoise, sigmas, key=None) -> SamplerProgram:
    """UniPC (UniP-2 predictor + UniC-3 corrector), data-prediction form,
    one model call per step (the corrector reuses the evaluation made at
    the predicted point, per the published predictor–corrector scheme).

    Both pieces integrate ∫ e^{τ−h} P(τ) dτ exactly for a polynomial P
    through the available D points (moments I0 = 1−e^{−h}, I1 = h−I0,
    I2 = h²−2·I1):

    - predictor: linear P through (−h_prev, D_{n−1}), (0, D_n) — the
      same exponential-Adams update as ``res_2m``;
    - corrector (applied to the PREVIOUS transition once D at the
      predicted point is known): quadratic P through (−h_prev, D_{n−1}),
      (0, D_n), (h, D̂_{n+1}), third-order accurate; falls back to the
      exponential-trapezoidal (linear through 0, h) on the first
      transition."""
    del key

    def correct(x_prev, d_prev2, d_prev, d_cur, h, h_prev, count):
        """Re-integrate t_{n−1}→t_n with D̂ at the arrival point."""
        i0 = _i0(h)
        i1 = h - i0
        i2 = h * h - 2.0 * i1
        # trapezoidal (first transition): linear through (0,d_prev),(h,d_cur)
        b_lin = (d_cur - d_prev) / jnp.maximum(h, 1e-10)
        trap = jnp.exp(-h) * x_prev + i0 * d_prev + i1 * b_lin
        # quadratic through (−h_prev, d_prev2), (0, d_prev), (h, d_cur)
        hp = jnp.maximum(h_prev, 1e-10)
        hh = jnp.maximum(h, 1e-10)
        # solve P(τ)=d_prev + bτ + cτ²:  b·h + c·h² = d_cur − d_prev
        #                               −b·hp + c·hp² = d_prev2 − d_prev
        det = hh * hp * (hh + hp)
        b = (hp * hp * (d_cur - d_prev) - hh * hh * (d_prev2 - d_prev)) / det
        c = (hp * (d_cur - d_prev) + hh * (d_prev2 - d_prev)) / det
        quad = jnp.exp(-h) * x_prev + i0 * d_prev + i1 * b + i2 * c
        return jnp.where(count >= 2, quad, trap)

    def predict(x_cur, d_cur, d_prev, h, h_prev, count):
        i0 = _i0(h)
        slope = (d_cur - d_prev) / jnp.maximum(h_prev, 1e-10)
        return jnp.exp(-h) * x_cur + i0 * d_cur \
            + jnp.where(count >= 1, h - i0, 0.0) * slope

    def step(carry, i):
        # x_pred: predicted state at σ_i (uncorrected); x_prev: corrected
        # state at σ_{i−1}; d_prev/d_prev2: D at σ_{i−1}/σ_{i−2}
        x_prev, x_pred, d_prev, d_prev2, h_prev, h_prev2, count = carry
        sigma, sigma_next = sigmas[i], sigmas[i + 1]
        d_cur = denoise(x_pred, sigma)
        # corrector for the transition that produced x_pred
        x_cur = jnp.where(
            count >= 1,
            correct(x_prev, d_prev2, d_prev, d_cur, h_prev, h_prev2, count),
            x_pred)
        h = _t_of(sigma_next) - _t_of(sigma)
        x_next = predict(x_cur, d_cur, d_prev, h, h_prev, count)
        x_next = jnp.where(sigma_next > 0, x_next, d_cur)
        return (x_cur, x_next, d_cur, d_prev, h, h_prev, count + 1)

    return SamplerProgram(
        "uni_pc", sigmas.shape[0] - 1,
        lambda x: (x, x, jnp.zeros_like(x), jnp.zeros_like(x), jnp.zeros(()),
                   jnp.zeros(()), jnp.int32(0)),
        step, lambda carry: carry[1])


PROGRAMS: dict[str, Callable] = {
    "euler": _euler_program,
    "euler_ancestral": _euler_ancestral_program,
    "heun": _heun_program,
    "dpmpp_2m": _dpmpp_2m_program,
    "ddim": _ddim_program,
    "lcm": _lcm_program,
    "dpmpp_sde": _dpmpp_sde_program,
    "dpmpp_2m_sde": _dpmpp_2m_sde_program,
    "res_2m": _res_2m_program,
    "res_2s": _res_2s_program,
    "res_2m_ancestral": lambda d, s, key=None, **kw: _res_2m_program(
        d, s, key, eta=kw.pop("eta", 1.0), **kw),
    "res_2s_ancestral": lambda d, s, key=None, **kw: _res_2s_program(
        d, s, key, eta=kw.pop("eta", 1.0), **kw),
    "dpmpp_3m_sde": _dpmpp_3m_sde_program,
    "uni_pc": _uni_pc_program,
}


def make_program(name: str, denoise: Denoiser, sigmas: jax.Array,
                 key: Optional[jax.Array] = None,
                 **kwargs) -> SamplerProgram:
    """The resumable form of :func:`sample`: same dispatch, same kwargs,
    but the ``(init, step, extract)`` triple instead of a finished run —
    segment it with :func:`run_segment` (diffusion/checkpoint.py)."""
    try:
        builder = PROGRAMS[name]
    except KeyError:
        raise ValueError(f"unknown sampler {name!r}; have {sorted(PROGRAMS)}")
    return builder(denoise, sigmas, key, **kwargs)


def carry_structure(name: str, x_struct, **kwargs) -> tuple:
    """Abstract carry shapes for sampler ``name`` given the latent's
    ``ShapeDtypeStruct`` — no denoiser needed (``init`` is pure
    structure). The preemptible pipeline derives shard_map specs and the
    checkpoint layout from this."""
    prog = make_program(name, None, jnp.zeros((2,), jnp.float32),
                        key=None, **kwargs)
    return jax.eval_shape(prog.init, x_struct)


def extract_output(name: str, carry: tuple, **kwargs) -> jax.Array:
    """Pick sampler ``name``'s output slot out of a finished carry —
    denoiser-free (used by the preemptible pipeline's decode program)."""
    prog = make_program(name, None, jnp.zeros((2,), jnp.float32),
                        key=None, **kwargs)
    return prog.extract(carry)


# --- the classic one-shot API (unchanged signatures) ------------------------


def sample_euler(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                 key: jax.Array | None = None) -> jax.Array:
    return run_program(_euler_program(denoise, sigmas, key), x)


def sample_euler_ancestral(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                           key: jax.Array, eta: float = 1.0) -> jax.Array:
    return run_program(_euler_ancestral_program(denoise, sigmas, key,
                                                eta=eta), x)


def sample_heun(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                key: jax.Array | None = None) -> jax.Array:
    return run_program(_heun_program(denoise, sigmas, key), x)


def sample_dpmpp_2m(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                    key: jax.Array | None = None) -> jax.Array:
    return run_program(_dpmpp_2m_program(denoise, sigmas, key), x)


def sample_ddim(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                key: jax.Array | None = None, eta: float = 0.0) -> jax.Array:
    return run_program(_ddim_program(denoise, sigmas, key, eta=eta), x)


def sample_lcm(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
               key: jax.Array) -> jax.Array:
    return run_program(_lcm_program(denoise, sigmas, key), x)


def sample_dpmpp_sde(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                     key: jax.Array, eta: float = 1.0, s_noise: float = 1.0,
                     r: float = 0.5) -> jax.Array:
    return run_program(_dpmpp_sde_program(denoise, sigmas, key, eta=eta,
                                          s_noise=s_noise, r=r), x)


def sample_dpmpp_2m_sde(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                        key: jax.Array, eta: float = 1.0,
                        s_noise: float = 1.0) -> jax.Array:
    return run_program(_dpmpp_2m_sde_program(denoise, sigmas, key, eta=eta,
                                             s_noise=s_noise), x)


def sample_res_2m(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                  key: jax.Array | None = None, eta: float = 0.0) -> jax.Array:
    return run_program(_res_2m_program(denoise, sigmas, key, eta=eta), x)


def sample_res_2s(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                  key: jax.Array | None = None, eta: float = 0.0,
                  c2: float = 0.5) -> jax.Array:
    return run_program(_res_2s_program(denoise, sigmas, key, eta=eta,
                                       c2=c2), x)


def sample_dpmpp_3m_sde(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                        key: jax.Array, eta: float = 1.0,
                        s_noise: float = 1.0) -> jax.Array:
    return run_program(_dpmpp_3m_sde_program(denoise, sigmas, key, eta=eta,
                                             s_noise=s_noise), x)


def sample_uni_pc(denoise: Denoiser, x: jax.Array, sigmas: jax.Array,
                  key: jax.Array | None = None) -> jax.Array:
    return run_program(_uni_pc_program(denoise, sigmas, key), x)


SAMPLERS: dict[str, Callable] = {
    "euler": sample_euler,
    "euler_ancestral": sample_euler_ancestral,
    "heun": sample_heun,
    "dpmpp_2m": sample_dpmpp_2m,
    "ddim": sample_ddim,
    "lcm": sample_lcm,
    "dpmpp_sde": sample_dpmpp_sde,
    "dpmpp_2m_sde": sample_dpmpp_2m_sde,
    "res_2m": sample_res_2m,
    "res_2s": sample_res_2s,
    "res_2m_ancestral": lambda d, x, s, key=None, **kw: sample_res_2m(
        d, x, s, key, eta=kw.pop("eta", 1.0), **kw),
    "res_2s_ancestral": lambda d, x, s, key=None, **kw: sample_res_2s(
        d, x, s, key, eta=kw.pop("eta", 1.0), **kw),
    "dpmpp_3m_sde": sample_dpmpp_3m_sde,
    "uni_pc": sample_uni_pc,
}


def sample(
    name: str,
    denoise: Denoiser,
    x: jax.Array,
    sigmas: jax.Array,
    key: jax.Array | None = None,
    **kwargs,
) -> jax.Array:
    try:
        fn = SAMPLERS[name]
    except KeyError:
        raise ValueError(f"unknown sampler {name!r}; have {sorted(SAMPLERS)}")
    return fn(denoise, x, sigmas, key, **kwargs)
