"""Denoiser construction: model parameterizations + classifier-free guidance.

``eps_denoiser`` adapts an eps-prediction UNet to the k-diffusion contract
(c_in scaling + sigma→timestep lookup); ``flow_denoiser`` adapts a
velocity-prediction rectified-flow model. ``cfg_denoiser`` batches the
cond/uncond passes into ONE model call (batch-dim concat) so the MXU sees a
2× batch instead of two launches.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .schedules import NoiseSchedule
from .samplers import Denoiser

# model(x, t, context, y) -> prediction
ModelFn = Callable[..., jax.Array]


def eps_denoiser(
    model_fn: ModelFn,
    schedule: NoiseSchedule,
    context: jax.Array,
    y: Optional[jax.Array] = None,
) -> Denoiser:
    """eps-pred VP model → x0 denoiser: D(x,σ) = x − σ·eps(x·c_in, t(σ))."""

    def denoise(x: jax.Array, sigma: jax.Array) -> jax.Array:
        c_in = 1.0 / jnp.sqrt(sigma ** 2 + 1.0)
        t = schedule.timestep_for_sigma(sigma)
        t_b = jnp.broadcast_to(t, (x.shape[0],))
        eps = model_fn(x * c_in, t_b, context, y)
        return x - sigma * eps

    return denoise


def flow_denoiser(
    model_fn: ModelFn,
    context: jax.Array,
    y: Optional[jax.Array] = None,
) -> Denoiser:
    """Rectified-flow velocity model → x0 denoiser: D(x,σ) = x − σ·v(x, σ)."""

    def denoise(x: jax.Array, sigma: jax.Array) -> jax.Array:
        t_b = jnp.broadcast_to(sigma, (x.shape[0],))
        v = model_fn(x, t_b, context, y)
        return x - sigma * v

    return denoise


def cfg_denoiser(
    make_denoiser: Callable[[jax.Array, Optional[jax.Array]], Denoiser],
    context: jax.Array,
    uncond_context: jax.Array,
    guidance_scale: float,
    y: Optional[jax.Array] = None,
    uncond_y: Optional[jax.Array] = None,
) -> Denoiser:
    """Classifier-free guidance with a single doubled-batch model call.

    ``make_denoiser(context, y)`` builds the underlying denoiser; both
    conditionings are stacked along batch so one forward serves both.
    """
    ctx2 = jnp.concatenate([context, uncond_context], axis=0)
    y2 = None
    if y is not None:
        y2 = jnp.concatenate([y, uncond_y if uncond_y is not None else jnp.zeros_like(y)], axis=0)
    inner = make_denoiser(ctx2, y2)

    def denoise(x: jax.Array, sigma: jax.Array) -> jax.Array:
        x2 = jnp.concatenate([x, x], axis=0)
        out = inner(x2, sigma)
        cond, uncond = jnp.split(out, 2, axis=0)
        return uncond + guidance_scale * (cond - uncond)

    return denoise
