"""Host-offloaded FLUX execution: stream transformer blocks through HBM.

A full FLUX-class DiT is ~12B params — ~24 GB of bf16 weights, more than
a v5e chip's 15.75 GB HBM. The reference sidesteps this with ComfyUI's
model offload machinery (``/root/reference/api/job_routes.py:160-203``
reaches into ``comfy.model_management``; lowvram streaming sits under
every node). The TPU-native equivalent here:

- params stay **host-pinned** (numpy); a configurable **resident set**
  (first blocks + all glue: embedders, final head) lives in HBM;
- the remaining blocks stream through a double-buffered window: the
  next block's weights start their async ``device_put`` before the
  current block's compute is dispatched, so transfer and MXU time
  overlap;
- every double block shares ONE compiled program (same shapes), every
  single block another — two block compiles total, not depth-many;
- each block's ~20 param leaves are **flattened into one contiguous
  buffer per dtype** at init, so streaming a block is ONE ``device_put``
  instead of ~20 (measured on the tunneled chip: per-transfer RTT
  dominated the stream — ~1100 puts per forward ran the 1.3 GB/s link
  at an effective 0.05 GB/s; flat blocks restore bandwidth-bound
  streaming, and fewer/larger DMAs are cheaper on real hosts too). The
  block programs slice the buffer back into leaves in-trace (static
  offsets — XLA sees views, not copies).

The sampling loop runs at the Python level (per-block dispatch cannot
live inside one ``jit``), so this path trades scheduler overhead +
interconnect bandwidth for unbounded model size. On hosts with real
DMA (~10-40 GB/s) a streamed step approaches compute-bound; through a
slow tunnel it is bandwidth-dominated — measured and reported honestly
either way (``bench.py``).

Knobs: ``CDT_OFFLOAD=1`` enables the path in the flow pipeline /
bench; ``CDT_OFFLOAD_RESIDENT_GB`` caps the resident set (default 10).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ..models.dit import (DiT, DiTConfig, DoubleBlock, MLPEmbedder,
                          Modulation, SingleBlock, _modulate, image_ids,
                          patchify, rope_freqs, sincos_2d, unpatchify)
from ..models.layers import timestep_embedding

_GLUE_KEYS = ("img_in", "txt_in", "time_in", "vector_in", "guidance_in",
              "final_mod", "img_out")


def offload_enabled(default: bool = False) -> bool:
    """One definition of the CDT_OFFLOAD gate. Server paths default OFF
    (resident execution); the accelerator flux bench defaults ON (full
    depth cannot run any other way on one chip)."""
    v = os.environ.get("CDT_OFFLOAD", "")
    if v == "":
        return default
    return v not in ("0", "false")


def resident_budget_bytes() -> int:
    gb = float(os.environ.get("CDT_OFFLOAD_RESIDENT_GB", "10"))
    return int(gb * (1 << 30))


def tree_bytes(tree) -> int:
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(tree))


def materialize_host_params(abstract_tree, seed: int = 0):
    """ShapeDtypeStruct tree → host numpy tree (random normal ~N(0,0.02)
    — the bench path for models whose random init cannot fit on device;
    real weights arrive via the converter/orbax restore instead).
    ``default_rng`` draws float32 natively — a 12B-param tree fills in
    ~1 min on one core instead of several."""
    rng = np.random.default_rng(seed)

    def leaf(l):
        a = rng.standard_normal(l.shape, dtype=np.float32) * np.float32(0.02)
        return a.astype(l.dtype)

    return jax.tree_util.tree_map(leaf, abstract_tree)


def _flatten_block(blk) -> tuple[dict, Any, tuple]:
    """Host-side: a block's param tree → ``({dtype: 1-D buffer}, treedef,
    metas)`` where ``metas[i] = (dtype_name, offset, shape)`` in leaf
    order. One buffer per dtype (in practice exactly one — bf16 or f32)."""
    leaves, treedef = jax.tree_util.tree_flatten(blk)
    chunks: dict[str, list] = {}
    offsets: dict[str, int] = {}
    metas = []
    for leaf in leaves:
        a = np.asarray(leaf)
        dt = a.dtype.name
        off = offsets.get(dt, 0)
        metas.append((dt, off, a.shape))
        offsets[dt] = off + int(a.size)
        chunks.setdefault(dt, []).append(a.ravel())
    bufs = {dt: np.concatenate(cs) for dt, cs in chunks.items()}
    return bufs, treedef, tuple(metas)


def _unflatten_block(bufs, treedef, metas):
    """In-trace inverse of ``_flatten_block``: static-offset slices +
    reshapes — XLA treats them as views of the streamed buffer."""
    leaves = []
    for dt, off, shape in metas:
        n = 1
        for s in shape:
            n *= int(s)
        seg = jax.lax.slice(bufs[dt], (off,), (off + n,))
        leaves.append(seg.reshape(shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


class _Embed(nn.Module):
    """Pre-block glue of ``DiT.__call__`` with identical submodule names,
    so the full model's param tree slices straight in (equivalence is
    pinned by ``tests/test_offload.py``)."""

    config: DiTConfig

    @nn.compact
    def __call__(self, x, t, context, pooled, guidance):
        cfg = self.config
        dt = cfg.jnp_dtype
        B, H, W, _ = x.shape
        p = cfg.patch_size
        tokens = patchify(x.astype(dt), p)
        img = nn.Dense(cfg.hidden, dtype=dt, name="img_in")(tokens)
        if cfg.pos_embed != "rope":
            img = img + sincos_2d(H // p, W // p, cfg.hidden)[None].astype(dt)
        txt = nn.Dense(cfg.hidden, dtype=dt, name="txt_in")(
            context.astype(dt))
        vec = MLPEmbedder(cfg.hidden, dt, name="time_in")(
            timestep_embedding(t * 1000.0, 256).astype(dt))
        vec = vec + MLPEmbedder(cfg.hidden, dt, name="vector_in")(
            pooled.astype(dt))
        if cfg.guidance_embed:
            gvec = guidance if guidance is not None else jnp.full((B,), 3.5)
            vec = vec + MLPEmbedder(cfg.hidden, dt, name="guidance_in")(
                timestep_embedding(gvec * 1000.0, 256).astype(dt))
        return img, txt, vec


class OffloadedFlux:
    """Single-device FLUX executor with host-resident streamed blocks."""

    def __init__(self, dit: DiT, params, resident_bytes: Optional[int] = None,
                 device=None):
        self.cfg: DiTConfig = dit.config
        self.device = device or jax.devices()[0]
        budget = (resident_budget_bytes() if resident_bytes is None
                  else int(resident_bytes))
        inner = params["params"] if "params" in params else params

        glue = {k: inner[k] for k in _GLUE_KEYS if k in inner}
        self.block_order = (
            [f"double_{i}" for i in range(self.cfg.depth_double)]
            + [f"single_{i}" for i in range(self.cfg.depth_single)])
        used = tree_bytes(glue)
        self.resident: dict[str, Any] = {}
        self.streamed: dict[str, Any] = {}
        # per-kind flat layout (identical across every block of a kind —
        # same module config, same shapes): treedef + (dtype, offset,
        # shape) per leaf, captured statically by the block programs
        self._layout: dict[str, tuple] = {}
        for name in self.block_order:
            blk = inner[name]
            size = tree_bytes(blk)
            bufs, treedef, metas = _flatten_block(blk)
            kind = "double" if name.startswith("double") else "single"
            self._layout.setdefault(kind, (treedef, metas))
            if used + size <= budget:
                self.resident[name] = jax.device_put(bufs, self.device)
                used += size
            else:
                # host numpy: no device residency, fetched per step as
                # ONE put per dtype buffer
                self.streamed[name] = bufs
        self.glue = jax.device_put(glue, self.device)
        self.resident_bytes = used

        cfg = self.cfg
        self._embed = jax.jit(
            lambda gl, x, t, ctx, pl, g: _Embed(cfg).apply(
                {"params": {k: gl[k] for k in
                            ("img_in", "txt_in", "time_in", "vector_in",
                             "guidance_in") if k in gl}},
                x, t, ctx, pl, g))

        def dblock(bufs, img, txt, vec, pe_i, pe_t):
            bp = _unflatten_block(bufs, *self._layout["double"])
            return DoubleBlock(cfg).apply(
                {"params": bp}, img, txt, vec, None, pe_i, pe_t)

        def sblock(bufs, xcat, vec, pe_f, T):
            bp = _unflatten_block(bufs, *self._layout["single"])
            return SingleBlock(cfg).apply(
                {"params": bp}, xcat, vec, T, None, pe_f)

        self._dblock = jax.jit(dblock)
        self._sblock = jax.jit(sblock, static_argnames=("T",))

        def head(gl, img, vec):
            dt = cfg.jnp_dtype
            sh, sc, _ = Modulation(1, cfg.hidden, dt).apply(
                {"params": gl["final_mod"]}, vec)
            img = _modulate(
                nn.LayerNorm(use_scale=False, use_bias=False,
                             dtype=dt).apply({}, img), sh, sc)
            return nn.Dense(cfg.patch_size ** 2 * cfg.in_channels,
                            dtype=jnp.float32).apply(
                {"params": gl["img_out"]}, img.astype(jnp.float32))

        self._head = jax.jit(head)

    # --- forward -----------------------------------------------------------

    def _rope_tables(self, H: int, W: int, txt_len: int):
        """Cached per (H, W, txt_len): the tables are identical for every
        step of a sample, and the python loop can't hide the rebuild."""
        cfg = self.cfg
        if cfg.pos_embed != "rope":
            return None, None, None
        key = (H, W, txt_len)
        cached = getattr(self, "_pe_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        p = cfg.patch_size
        pe_img = rope_freqs(image_ids(H // p, W // p), cfg.axes_dim,
                            cfg.rope_theta)
        pe_txt = rope_freqs(jnp.zeros((txt_len, 3), jnp.int32),
                            cfg.axes_dim, cfg.rope_theta)
        pe_full = (jnp.concatenate([pe_txt[0], pe_img[0]], axis=0),
                   jnp.concatenate([pe_txt[1], pe_img[1]], axis=0))
        put = lambda pe: None if pe is None else jax.device_put(pe, self.device)
        out = (put(pe_img), put(pe_txt), put(pe_full))
        self._pe_cache = (key, out)
        return out

    def _fetch(self, name: str):
        if name in self.resident:
            return self.resident[name], False
        return jax.device_put(self.streamed[name], self.device), True

    def forward(self, x, t, context, pooled, guidance=None):
        """One velocity evaluation, block-streamed. Equivalent to
        ``DiT.apply`` (sp_axis None) — pinned by tests."""
        cfg = self.cfg
        B, H, W, C = x.shape
        pe_img, pe_txt, pe_full = self._rope_tables(H, W, context.shape[1])
        img, txt, vec = self._embed(
            self.glue, x, t, context, pooled,
            None if guidance is None else guidance)

        names = self.block_order
        # double-buffer: block i+1's weights start transferring before
        # block i's compute is dispatched
        cur, cur_streamed = self._fetch(names[0])
        xcat = None
        T = int(txt.shape[1])
        for i, name in enumerate(names):
            nxt = self._fetch(names[i + 1]) if i + 1 < len(names) else None
            if name.startswith("double"):
                img, txt = self._dblock(cur, img, txt, vec, pe_img, pe_txt)
                out = img
            else:
                if xcat is None:
                    xcat = jnp.concatenate([txt, img], axis=1)
                xcat = self._sblock(cur, xcat, vec, pe_full, T=T)
                out = xcat
            if cur_streamed:
                # BACKPRESSURE: without this barrier the python loop
                # enqueues the entire ladder's transfers ahead of the
                # device (30 steps × 24 GB of staged host buffers — a
                # measured 130 GB host OOM). Blocking on the block output
                # keeps at most cur (computing) + nxt (streaming) in
                # flight while still overlapping transfer with compute.
                jax.block_until_ready(out)
                for leaf in jax.tree_util.tree_leaves(cur):
                    leaf.delete()       # free HBM as soon as consumed
            if nxt is not None:
                cur, cur_streamed = nxt
        img = (xcat[:, T:] if xcat is not None else img)
        out = self._head(self.glue, img, vec)
        return unpatchify(out, (H, W), cfg.patch_size, C)

    def denoiser(self, context, pooled, guidance: float):
        g = jnp.full((context.shape[0],), float(guidance))

        def den(x, sigma):
            t = jnp.broadcast_to(jnp.asarray(sigma), (x.shape[0],))
            v = self.forward(x, t, context, pooled, g)
            return x - jnp.asarray(sigma) * v

        return den


def sample_euler_py(denoise, x, sigmas) -> jax.Array:
    """Python-level Euler ladder (exact math of ``samplers.sample``'s
    euler branch — pinned by tests). The offloaded denoiser cannot live
    inside a ``lax.scan``, so the loop runs host-side; for 20-50 steps
    the per-step dispatch cost is noise next to block streaming."""
    sig = np.asarray(sigmas, np.float64)
    for i in range(len(sig) - 1):
        x0 = denoise(x, jnp.asarray(sig[i], jnp.float32))
        if sig[i + 1] == 0.0:
            x = x0
        else:
            d = (x - x0) / sig[i]
            x = x + d * (sig[i + 1] - sig[i])
    return x
