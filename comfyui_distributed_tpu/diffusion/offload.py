"""Host-offloaded FLUX execution: stream transformer blocks through HBM.

A full FLUX-class DiT is ~12B params — ~24 GB of bf16 weights, more than
a v5e chip's 15.75 GB HBM. The reference sidesteps this with ComfyUI's
model offload machinery (``/root/reference/api/job_routes.py:160-203``
reaches into ``comfy.model_management``; lowvram streaming sits under
every node). The TPU-native equivalent here:

- params stay **host-pinned** (numpy); a configurable **resident set**
  (first blocks + all glue: embedders, final head) lives in HBM;
- the remaining blocks stream through a double-buffered window: the
  next block's weights start their async ``device_put`` before the
  current block's compute is dispatched, so transfer and MXU time
  overlap;
- every double block shares ONE compiled program (same shapes), every
  single block another — two block compiles total, not depth-many;
- each block's ~20 param leaves are **flattened into one contiguous
  buffer per dtype** at init, so streaming a block is ONE ``device_put``
  instead of ~20 (measured on the tunneled chip: per-transfer RTT
  dominated the stream — ~1100 puts per forward ran the 1.3 GB/s link
  at an effective 0.05 GB/s; flat blocks restore bandwidth-bound
  streaming, and fewer/larger DMAs are cheaper on real hosts too). The
  block programs slice the buffer back into leaves in-trace (static
  offsets — XLA sees views, not copies).

**fp8 weight residency (r04).** Streaming bf16 blocks moves ~13 GB per
step — bandwidth-bound on any link, and catastrophic through a tunneled
chip. The decisive optimization is the same one the reference ecosystem
ships as its standard low-VRAM FLUX path (fp8 checkpoints): quantize
the block **kernels** to ``float8_e4m3fn`` with per-output-channel
absmax scales. At fp8 the full 12B block set is ~12 GB — it fits
RESIDENT in one v5e's HBM, so after a one-time upload the sampling loop
streams **zero** bytes. When every block of a kind is resident, the
forward collapses to ONE compiled program: ``lax.scan`` over the
stacked per-kind weight buffers (dequant happens in-trace per block —
an elementwise cast+mul XLA fuses into the first matmul's operand
read). Weights-only per-channel e4m3 carries ~0.1% relative output
error per matmul (noise averages over the 3072-wide contraction) —
numerically pinned by ``tests/test_offload.py``.

The python-level per-block loop remains the fallback whenever the
(possibly quantized) model still exceeds the resident budget: blocks
beyond the budget stream per step, at half the bytes under fp8.

Knobs: ``CDT_OFFLOAD=1`` enables the path in the flow pipeline /
bench; ``CDT_OFFLOAD_RESIDENT_GB`` caps the resident set (default 13);
``CDT_OFFLOAD_STREAM_DTYPE`` selects ``float8_e4m3fn`` (default — the
fits-in-HBM fast path) or ``native`` (exact bf16/f32 streaming).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
from flax import linen as nn

from ..models.dit import (DiT, DiTConfig, DoubleBlock, MLPEmbedder,
                          Modulation, SingleBlock, _modulate, image_ids,
                          patchify, rope_freqs, sincos_2d, unpatchify)
from ..models.layers import timestep_embedding
from ..utils import constants

_GLUE_KEYS = ("img_in", "txt_in", "time_in", "vector_in", "guidance_in",
              "final_mod", "img_out")

_F8 = "float8_e4m3fn"
_F8_MAX = 448.0               # largest finite e4m3fn magnitude
_QUANT_MIN_SIZE = 4096        # only kernels are worth quantizing


def offload_enabled(default: bool = False) -> bool:
    """One definition of the CDT_OFFLOAD gate. Server paths default OFF
    (resident execution); the accelerator flux bench defaults ON (full
    depth cannot run any other way on one chip)."""
    v = constants.OFFLOAD.get()
    return default if v is None else v


def resident_budget_bytes() -> int:
    return int(constants.OFFLOAD_RESIDENT_GB.get() * (1 << 30))


def stream_dtype_default() -> str:
    """``float8_e4m3fn`` (default) or ``native``."""
    return constants.OFFLOAD_STREAM_DTYPE.get()


def ladder_mode() -> str:
    """How a FULLY-RESIDENT offloaded sample runs its sigma ladder:

    - ``"jit"`` (default): the whole ladder is ONE compiled program —
      fastest (no per-step dispatch), but not interruptible mid-run and
      recompiled per distinct step count;
    - ``"step"``: python loop over the single-forward program — one
      dispatch per step (µs on a real host), responsive to
      ``/distributed/interrupt`` between steps, no per-step-count
      recompiles. Streamed (partially-resident) executors always run
      per step."""
    return constants.OFFLOAD_LADDER.get()


def normalize_stream_dtype(sd: Optional[str]) -> str:
    """Canonical stream-dtype name — ONE definition, shared by the
    executor and every cache key built over it (aliased spellings must
    not build duplicate multi-GB executors). ``bfloat16``/``bf16`` are
    synonyms for ``native`` — "leave dtypes untouched, don't quantize" —
    NOT a cast: float32 params stream as float32 under every non-fp8
    spelling."""
    sd = sd or stream_dtype_default()
    if sd in ("fp8", "f8", "float8", _F8):
        return _F8
    if sd in ("native", "bfloat16", "bf16", "exact"):
        return "native"
    raise ValueError(f"unknown CDT_OFFLOAD_STREAM_DTYPE {sd!r} "
                     f"(use {_F8!r} or 'native')")


def _should_quantize_meta(shape, dtype, quantize: bool) -> bool:
    """ONE predicate for both the size planner and the packer — if these
    ever disagreed, ``plan_offload`` would mis-place blocks silently.
    Operates on (shape, dtype) so planning also works over ABSTRACT
    trees (``jax.eval_shape`` — plan a 14B model without materializing
    28 GB)."""
    dt = np.dtype(dtype)
    is_float = dt.kind == "f" or dt == ml_dtypes.bfloat16
    size = 1
    for s in shape:
        size *= int(s)
    return (quantize and len(shape) >= 2 and size >= _QUANT_MIN_SIZE
            and is_float)


def _should_quantize(a: np.ndarray, quantize: bool) -> bool:
    return _should_quantize_meta(a.shape, a.dtype, quantize)


def _leaf_packed_bytes(leaf, quantize: bool) -> int:
    """Packed size of one leaf WITHOUT packing it (placement planning
    must not materialize flat copies — peak-RSS discipline). ``leaf``
    only needs ``.shape``/``.dtype`` — ndarray, jax.Array, or
    ShapeDtypeStruct all work."""
    shape, dt = leaf.shape, np.dtype(leaf.dtype)
    size = 1
    for s in shape:
        size *= int(s)
    if _should_quantize_meta(shape, dt, quantize):
        return size + int(shape[-1]) * 4               # fp8 + f32 scales
    return size * dt.itemsize


def block_packed_bytes(blk, quantize: bool) -> int:
    return sum(_leaf_packed_bytes(l, quantize)
               for l in jax.tree_util.tree_leaves(blk))


def _kind_of(name: str) -> str:
    """Block kind = the prefix before the trailing index: ``double_3`` →
    ``double``, ``block_17`` → ``block``. Every block of a kind shares
    one flat layout and one compiled program."""
    return name.rsplit("_", 1)[0]


def plan_offload(params, budget: int,
                 stream_dtype: Optional[str] = None,
                 block_prefixes: tuple = ("double", "single"),
                 glue_keys: tuple = _GLUE_KEYS) -> dict:
    """Placement plan without building anything: which blocks would be
    resident vs streamed under ``budget``, and the per-step streamed
    byte count. ``bench.py`` uses this to run its host-RAM leak guard
    BEFORE the multi-GB executor build. ``block_prefixes`` order is
    execution order (FLUX: doubles then singles; WAN: ``("block",)``)."""
    quantize = normalize_stream_dtype(stream_dtype) == _F8
    inner = params["params"] if "params" in params else params
    order = []
    for prefix in block_prefixes:
        ns = [k for k in inner
              if k.startswith(prefix + "_")
              and k[len(prefix) + 1:].isdigit()]
        order += sorted(ns, key=lambda n: int(n.rsplit("_", 1)[1]))
    glue = {k: inner[k] for k in glue_keys if k in inner}
    used = tree_bytes(glue)
    resident, streamed, streamed_bytes = [], [], 0
    for name in order:
        size = block_packed_bytes(inner[name], quantize)
        if used + size <= budget:
            resident.append(name)
            used += size
        else:
            streamed.append(name)
            streamed_bytes += size
    return {"order": order, "resident": resident, "streamed": streamed,
            "resident_bytes": used, "streamed_bytes": streamed_bytes,
            "fully_resident": not streamed}


def tree_bytes(tree) -> int:
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(tree))


def materialize_host_params(abstract_tree, seed: int = 0):
    """ShapeDtypeStruct tree → host numpy tree (random normal ~N(0,0.02)
    — the bench path for models whose random init cannot fit on device;
    real weights arrive via the converter/orbax restore instead).
    ``default_rng`` draws float32 natively — a 12B-param tree fills in
    ~1 min on one core instead of several."""
    rng = np.random.default_rng(seed)

    def leaf(l):
        a = rng.standard_normal(l.shape, dtype=np.float32) * np.float32(0.02)
        return a.astype(l.dtype)

    return jax.tree_util.tree_map(leaf, abstract_tree)


def _flatten_block(blk, quantize: bool = False) -> tuple[dict, Any, tuple]:
    """Host-side: a block's param tree → ``({key: 1-D buffer}, treedef,
    metas)`` with ``metas[i] = (buf_key, offset, shape, scale_offset,
    out_dtype)`` in leaf order.

    Unquantized leaves pack into one buffer per dtype (``buf_key`` =
    dtype name, ``scale_offset`` = -1). With ``quantize=True``, float
    kernels (ndim≥2, ≥4096 elements) pack into an ``"float8_e4m3fn"``
    buffer with per-output-channel (last-axis) absmax scales appended to
    a float32 ``"scale"`` buffer; the in-trace unflatten dequantizes back
    to ``out_dtype``. Everything small (biases, norms, qk scales) stays
    exact in its native buffer."""
    leaves, treedef = jax.tree_util.tree_flatten(blk)
    chunks: dict[str, list] = {}
    offsets: dict[str, int] = {}
    metas = []
    for leaf in leaves:
        a = np.asarray(leaf)
        quant = _should_quantize(a, quantize)
        if quant:
            w = a.astype(np.float32)
            red = tuple(range(a.ndim - 1))          # all but output axis
            absmax = np.max(np.abs(w), axis=red)
            scale = np.where(absmax == 0.0, 1.0,
                             absmax / _F8_MAX).astype(np.float32)
            q = (w / scale).astype(ml_dtypes.float8_e4m3fn)
            off = offsets.get(_F8, 0)
            s_off = offsets.get("scale", 0)
            metas.append((_F8, off, a.shape, s_off, a.dtype.name))
            offsets[_F8] = off + int(a.size)
            offsets["scale"] = s_off + int(scale.size)
            chunks.setdefault(_F8, []).append(q.ravel())
            chunks.setdefault("scale", []).append(scale)
        else:
            dt = a.dtype.name
            off = offsets.get(dt, 0)
            metas.append((dt, off, a.shape, -1, dt))
            offsets[dt] = off + int(a.size)
            chunks.setdefault(dt, []).append(a.ravel())
    bufs = {dt: np.concatenate(cs) for dt, cs in chunks.items()}
    return bufs, treedef, tuple(metas)


def _unflatten_block(bufs, treedef, metas):
    """In-trace inverse of ``_flatten_block``: static-offset slices +
    reshapes — XLA treats them as views of the streamed buffer. fp8
    segments dequantize via cast + per-output-channel scale (fused by
    XLA into the consuming matmul's operand read)."""
    leaves = []
    for buf_key, off, shape, s_off, out_dtype in metas:
        n = 1
        for s in shape:
            n *= int(s)
        seg = jax.lax.slice(bufs[buf_key], (off,), (off + n,))
        seg = seg.reshape(shape)
        if s_off >= 0:
            scale = jax.lax.slice(bufs["scale"], (s_off,),
                                  (s_off + int(shape[-1]),))
            seg = (seg.astype(jnp.float32) * scale).astype(
                jnp.dtype(out_dtype))
        leaves.append(seg)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def quant_cache_dir() -> Optional[str]:
    """``CDT_OFFLOAD_CACHE_DIR``: directory for cached quantized flat
    blocks. Quantizing a 12B model costs ~5 single-core minutes on every
    process start; the cache cuts a warm executor build to a disk read."""
    return constants.OFFLOAD_CACHE_DIR.get() or None


def _params_fingerprint(inner, names) -> str:
    """Cheap content fingerprint of the block params: per leaf, shape +
    dtype + fnv1a64 of ≤4096 single bytes sampled at an even stride
    across the buffer (full hashing of 24 GB would cost more than it
    saves). Stale-cache safety, not cryptographic integrity: a swapped
    checkpoint with identical shapes whose changes all fall between the
    sampled bytes is the (documented) blind spot."""
    from ..native import hash64

    h = hash64(b"cdt-quant-cache-v1|e4m3-perchannel")
    for name in names:
        for leaf in jax.tree_util.tree_leaves(inner[name]):
            a = np.ascontiguousarray(leaf)
            raw = a.reshape(-1).view(np.uint8)
            stride = max(1, raw.size // 4096)
            sample = raw[::stride][:4096].tobytes()
            mix = hash64(f"{a.shape}|{a.dtype}".encode() + sample)
            h = (h ^ mix) * 1099511628211 & 0xFFFFFFFFFFFFFFFF
    return f"{h:016x}"


class _QuantCache:
    """Per-block ``.npy`` files + a JSON manifest, all inside a
    fingerprint-named subdirectory of the cache root — concurrent cold
    builds of *different* checkpoints sharing one ``CDT_OFFLOAD_CACHE_DIR``
    land in disjoint subdirs, so one can never validate the other's
    block files. Writes are tmp+rename atomic; a fingerprint mismatch
    or any unreadable/garbled entry falls back to re-quantizing (never
    fatal — construct via :func:`_open_quant_cache`)."""

    def __init__(self, root: str, fingerprint: str):
        import json
        import pathlib

        self.fingerprint = fingerprint
        self.dir = pathlib.Path(root) / fingerprint
        self.dir.mkdir(parents=True, exist_ok=True)   # may raise: see
        self.manifest = self.dir / "manifest.json"    # _open_quant_cache
        self.metas: dict[str, tuple] = {}
        self.valid = False
        try:
            m = json.loads(self.manifest.read_text())
            if m.get("fingerprint") == fingerprint:
                self.metas = {
                    kind: tuple((bk, off, tuple(shape), s_off, dt)
                                for bk, off, shape, s_off, dt in rows)
                    for kind, rows in m["metas"].items()}
                self.valid = True
        except (OSError, ValueError, KeyError, TypeError, AttributeError):
            pass

    def load(self, name: str) -> Optional[dict]:
        if not self.valid:
            return None
        out = {}
        rows = self.metas.get(_kind_of(name), ())
        keys = {bk for bk, *_ in rows}
        if any(s_off >= 0 for _, _, _, s_off, _ in rows):
            keys.add("scale")
        for key in keys:
            p = self.dir / f"{name}.{key.replace('/', '_')}.npy"
            try:
                arr = np.load(p)
                # np.save round-trips ml_dtypes bytes but loads them as
                # void ('|V1'/'|V2') — re-view as the real dtype, which
                # is the buffer key itself ('scale' buffers are f32)
                want = jnp.dtype("float32" if key == "scale" else key)
                if arr.dtype != want:
                    arr = arr.view(want)
                out[key] = arr
            except (OSError, ValueError, TypeError):
                return None
        return out or None

    def save(self, name: str, bufs: dict) -> None:
        import os as _os

        for key, arr in bufs.items():
            p = self.dir / f"{name}.{key.replace('/', '_')}.npy"
            tmp = p.with_suffix(".tmp.npy")
            try:
                np.save(tmp, arr)
                _os.replace(tmp, p)
            except OSError:
                return

    def finalize(self, metas_by_kind: dict) -> None:
        import json
        import os as _os

        payload = json.dumps({
            "fingerprint": self.fingerprint,
            "metas": {k: [[bk, off, list(shape), s_off, dt]
                          for bk, off, shape, s_off, dt in rows]
                      for k, rows in metas_by_kind.items()}})
        tmp = self.manifest.with_suffix(".tmp")
        try:
            tmp.write_text(payload)
            _os.replace(tmp, self.manifest)
        except OSError:
            pass
        self.metas = metas_by_kind
        self.valid = True


def _open_quant_cache(root: str, fingerprint: str) -> "Optional[_QuantCache]":
    """Never-fatal constructor: an unwritable/uncreatable cache dir
    (read-only mount, bad env var) degrades to no caching rather than
    failing the executor build."""
    try:
        return _QuantCache(root, fingerprint)
    except OSError:
        return None


class _Embed(nn.Module):
    """Pre-block glue of ``DiT.__call__`` with identical submodule names,
    so the full model's param tree slices straight in (equivalence is
    pinned by ``tests/test_offload.py``)."""

    config: DiTConfig

    @nn.compact
    def __call__(self, x, t, context, pooled, guidance):
        cfg = self.config
        dt = cfg.jnp_dtype
        B, H, W, _ = x.shape
        p = cfg.patch_size
        tokens = patchify(x.astype(dt), p)
        img = nn.Dense(cfg.hidden, dtype=dt, name="img_in")(tokens)
        if cfg.pos_embed != "rope":
            img = img + sincos_2d(H // p, W // p, cfg.hidden)[None].astype(dt)
        txt = nn.Dense(cfg.hidden, dtype=dt, name="txt_in")(
            context.astype(dt))
        vec = MLPEmbedder(cfg.hidden, dt, name="time_in")(
            timestep_embedding(t * 1000.0, 256).astype(dt))
        vec = vec + MLPEmbedder(cfg.hidden, dt, name="vector_in")(
            pooled.astype(dt))
        if cfg.guidance_embed:
            gvec = guidance if guidance is not None else jnp.full((B,), 3.5)
            vec = vec + MLPEmbedder(cfg.hidden, dt, name="guidance_in")(
                timestep_embedding(gvec * 1000.0, 256).astype(dt))
        return img, txt, vec


def _build_block_store(obj, params, budget: int,
                       stream_dtype: Optional[str],
                       block_prefixes: tuple, glue_keys: tuple,
                       expected_blocks: Optional[int] = None) -> None:
    """Shared executor substrate (FLUX and WAN): quantize/flatten the
    transformer blocks, decide residency under ``budget``, and upload.

    Fills on ``obj``: ``stream_dtype``, ``block_order``, ``resident``,
    ``streamed``, ``stacked``, ``_layout`` (per-kind ``(treedef,
    metas)``), ``glue`` (on device), ``resident_bytes``. Requires
    ``obj.device`` set. Packing is plan-first then one-block-at-a-time:
    peak host RSS stays ~one block (or one stack row-fill) above the
    params tree instead of a full flat copy of the model. With the
    ``CDT_OFFLOAD_CACHE_DIR`` quant cache, warm builds skip quantizing
    entirely."""
    sd = normalize_stream_dtype(stream_dtype)
    obj.stream_dtype = sd
    quantize = sd == _F8
    inner = params["params"] if "params" in params else params

    glue = {k: inner[k] for k in glue_keys if k in inner}
    plan = plan_offload(params, budget, sd, block_prefixes, glue_keys)
    if (expected_blocks is not None
            and len(plan["order"]) != expected_blocks):
        # a partially-restored/mis-converted checkpoint must fail LOUDLY
        # at build time, not execute fewer blocks and emit plausible
        # garbage
        raise ValueError(
            f"offload: params hold {len(plan['order'])} transformer "
            f"blocks ({block_prefixes}) but the config declares "
            f"{expected_blocks}")
    obj.block_order = plan["order"]
    obj.resident = {}
    obj.streamed = {}
    obj.stacked = {}
    # per-kind flat layout (identical across every block of a kind —
    # same module config, same shapes): treedef + (buf_key, offset,
    # shape, scale_off, out_dtype) per leaf, captured statically by
    # the block programs
    obj._layout = {}
    cache: Optional[_QuantCache] = None
    if quantize and quant_cache_dir() and obj.block_order:
        cache = _open_quant_cache(
            quant_cache_dir(),
            _params_fingerprint(inner, obj.block_order))

    def pack(name: str):
        """Cached-or-fresh flat buffers for one block; records the
        per-kind layout either way."""
        kind = _kind_of(name)
        if cache is not None and kind in cache.metas:
            bufs = cache.load(name)
            if bufs is not None:
                obj._layout.setdefault(
                    kind, (jax.tree_util.tree_structure(inner[name]),
                           cache.metas[kind]))
                return bufs
        bufs, treedef, metas = _flatten_block(inner[name],
                                              quantize=quantize)
        obj._layout.setdefault(kind, (treedef, metas))
        if cache is not None:
            cache.save(name, bufs)
        return bufs

    if plan["fully_resident"] and obj.block_order:
        # everything fits: upload per-kind STACKS (one put per buffer
        # key) and run the scan fast path — zero bytes streamed per
        # step, one dispatch per forward. Stacks are filled row by row
        # so only stack + one block are live.
        for kind in block_prefixes:
            names = [n for n in obj.block_order if _kind_of(n) == kind]
            if not names:
                continue
            rows: dict[str, np.ndarray] = {}
            for i, name in enumerate(names):
                bufs = pack(name)
                if not rows:
                    rows = {k: np.empty((len(names),) + v.shape, v.dtype)
                            for k, v in bufs.items()}
                for k, v in bufs.items():
                    rows[k][i] = v
            obj.stacked[kind] = jax.device_put(rows, obj.device)
            del rows
    else:
        for name in obj.block_order:
            bufs = pack(name)
            if name in set(plan["resident"]):
                obj.resident[name] = jax.device_put(bufs, obj.device)
            else:
                # host numpy: no device residency, fetched per step as
                # ONE put per flat buffer
                obj.streamed[name] = bufs
    if cache is not None and not cache.valid:
        cache.finalize({k: v[1] for k, v in obj._layout.items()})
    obj.glue = jax.device_put(glue, obj.device)
    obj.resident_bytes = plan["resident_bytes"]


def release_store(obj) -> None:
    """Free every device buffer an executor holds (stacked/resident
    blocks + glue) — the dual-expert video swap uploads the other
    expert into the same HBM. The executor object is dead afterwards;
    build a fresh one to run again."""
    for tree in (obj.stacked, obj.resident,
                 {"glue": getattr(obj, "glue", None)}):
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "delete"):     # device arrays only;
                leaf.delete()               # idempotent on deleted ones
    obj.stacked = {}
    obj.resident = {}


class OffloadedFlux:
    """Single-device FLUX executor with host-resident streamed blocks.

    ``stream_dtype``: ``"float8_e4m3fn"`` (default via
    ``CDT_OFFLOAD_STREAM_DTYPE``) quantizes block kernels host-side; when
    the whole quantized block set fits ``resident_bytes`` the executor
    uploads per-kind STACKED buffers once and runs the forward as one
    compiled ``lax.scan`` program (``self.stacked``), eliminating both
    per-step streaming and per-block dispatch. ``"native"`` keeps exact
    dtypes (the r03 behavior)."""

    def __init__(self, dit: DiT, params, resident_bytes: Optional[int] = None,
                 device=None, stream_dtype: Optional[str] = None):
        import dataclasses as _dc

        # memory-starved by definition (weights fill HBM): the block
        # programs must use the pallas flash kernel — XLA's fused
        # attention OOM'd at compile here (r04: 16.89 GB vs 15.75 HBM
        # at 4608 tokens × 24 heads with the fp8 set resident). Applied
        # unconditionally: this single-device executor always runs
        # blocks with sp_axis=None, so even a "ring"-configured DiT
        # takes the dense branch here and needs the preference.
        self.cfg: DiTConfig = _dc.replace(dit.config, attn_backend="flash")
        self.device = device or jax.devices()[0]
        budget = (resident_budget_bytes() if resident_bytes is None
                  else int(resident_bytes))
        _build_block_store(self, params, budget, stream_dtype,
                           block_prefixes=("double", "single"),
                           glue_keys=_GLUE_KEYS,
                           expected_blocks=(self.cfg.depth_double
                                            + self.cfg.depth_single))

        cfg = self.cfg

        def embed_fn(gl, x, t, ctx, pl, g):
            return _Embed(cfg).apply(
                {"params": {k: gl[k] for k in
                            ("img_in", "txt_in", "time_in", "vector_in",
                             "guidance_in") if k in gl}},
                x, t, ctx, pl, g)

        self._embed = jax.jit(embed_fn)

        def dblock(bufs, img, txt, vec, pe_i, pe_t):
            bp = _unflatten_block(bufs, *self._layout["double"])
            return DoubleBlock(cfg).apply(
                {"params": bp}, img, txt, vec, None, pe_i, pe_t)

        def sblock(bufs, xcat, vec, pe_f, T):
            bp = _unflatten_block(bufs, *self._layout["single"])
            return SingleBlock(cfg).apply(
                {"params": bp}, xcat, vec, T, None, pe_f)

        self._dblock = jax.jit(dblock)
        self._sblock = jax.jit(sblock, static_argnames=("T",))

        def head_fn(gl, img, vec):
            dt = cfg.jnp_dtype
            sh, sc, _ = Modulation(1, cfg.hidden, dt).apply(
                {"params": gl["final_mod"]}, vec)
            img = _modulate(
                nn.LayerNorm(use_scale=False, use_bias=False,
                             dtype=dt).apply({}, img), sh, sc)
            return nn.Dense(cfg.patch_size ** 2 * cfg.in_channels,
                            dtype=jnp.float32).apply(
                {"params": gl["img_out"]}, img.astype(jnp.float32))

        self._head = jax.jit(head_fn)

        def fwd_resident(gl, dstack, sstack, x, t, ctx, pl, g,
                         pe_img, pe_txt, pe_full):
            """Whole forward as ONE program: glue embed → scan over the
            stacked double blocks → scan over the stacked single blocks
            → final head. Per-block dequant happens inside the scan
            bodies."""
            img, txt, vec = embed_fn(gl, x, t, ctx, pl, g)
            if dstack is not None:
                def dbody(carry, bufs):
                    im, tx = carry
                    return dblock(bufs, im, tx, vec, pe_img, pe_txt), None

                (img, txt), _ = jax.lax.scan(dbody, (img, txt), dstack)
            T = txt.shape[1]
            xcat = jnp.concatenate([txt, img], axis=1)
            if sstack is not None:
                def sbody(xc, bufs):
                    return sblock(bufs, xc, vec, pe_full, T), None

                xcat, _ = jax.lax.scan(sbody, xcat, sstack)
            return head_fn(gl, xcat[:, T:], vec)

        self._fwd_resident = jax.jit(fwd_resident)

        def ladder(gl, dstack, sstack, x, sigs, ctx, pl, g,
                   pe_img, pe_txt, pe_full, token, key, sampler):
            """The ENTIRE sigma ladder as one program (fully-resident
            only): sample()'s scan over steps wrapping fwd_resident's
            scan over blocks — zero per-step host dispatch, and since
            the whole thing is in-trace, EVERY registered sampler works
            (the python fallback is euler-only). In-trace progress via
            the same wrap_denoiser the compiled pipelines use."""
            from .progress import wrap_denoiser
            from .samplers import sample

            B, H, W, C = x.shape

            def den(xx, sigma):
                t = jnp.broadcast_to(sigma, (xx.shape[0],))
                out = fwd_resident(gl, dstack, sstack, xx, t, ctx, pl,
                                   g, pe_img, pe_txt, pe_full)
                return xx - sigma * unpatchify(out, (H, W),
                                               cfg.patch_size, C)

            d = den if token is None else wrap_denoiser(den, token, 0)
            return sample(sampler, d, x, sigs, key=key)

        self._ladder = jax.jit(ladder, static_argnames=("sampler",))

    def sample_resident(self, x, sigmas, context, pooled,
                        guidance=None, sampler: str = "euler",
                        key=None, progress_token=None):
        """Run the whole sigma ladder as ONE compiled program — valid
        only when fully resident (``self.stacked``). Removes the
        per-step python dispatch (~70 ms RTT each through a tunneled
        chip ≈ 2 s of a 36 s FLUX image) and supports every registered
        sampler (ancestral ones draw from ``key`` exactly like the dp
        path); math identical to the compiled pipelines (pinned by
        tests)."""
        if not self.stacked:
            raise RuntimeError(
                "sample_resident requires a fully-resident executor "
                "(self.stacked)")
        B, H, W, C = x.shape
        pe_img, pe_txt, pe_full = self._rope_tables(H, W,
                                                    context.shape[1])
        token = (None if progress_token is None
                 else jnp.asarray(progress_token, jnp.int32))
        if key is None:
            key = jax.random.key(0)
        return self._ladder(
            self.glue, self.stacked.get("double"),
            self.stacked.get("single"), jax.device_put(x, self.device),
            jnp.asarray(np.asarray(sigmas), jnp.float32),
            context, pooled, guidance, pe_img, pe_txt, pe_full, token,
            key, sampler)

    # --- forward -----------------------------------------------------------

    def _rope_tables(self, H: int, W: int, txt_len: int):
        """Cached per (H, W, txt_len): the tables are identical for every
        step of a sample, and the python loop can't hide the rebuild."""
        cfg = self.cfg
        if cfg.pos_embed != "rope":
            return None, None, None
        key = (H, W, txt_len)
        cached = getattr(self, "_pe_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        p = cfg.patch_size
        pe_img = rope_freqs(image_ids(H // p, W // p), cfg.axes_dim,
                            cfg.rope_theta)
        pe_txt = rope_freqs(jnp.zeros((txt_len, 3), jnp.int32),
                            cfg.axes_dim, cfg.rope_theta)
        pe_full = (jnp.concatenate([pe_txt[0], pe_img[0]], axis=0),
                   jnp.concatenate([pe_txt[1], pe_img[1]], axis=0))
        put = lambda pe: None if pe is None else jax.device_put(pe, self.device)
        out = (put(pe_img), put(pe_txt), put(pe_full))
        self._pe_cache = (key, out)
        return out

    def _fetch(self, name: str):
        if name in self.resident:
            return self.resident[name], False
        return jax.device_put(self.streamed[name], self.device), True

    def forward(self, x, t, context, pooled, guidance=None):
        """One velocity evaluation. Equivalent to ``DiT.apply``
        (sp_axis None) — pinned by tests (exact under ``native``, to
        quantization tolerance under fp8). Fully-resident executors run
        the single scan program; otherwise blocks stream through the
        double-buffered loop."""
        cfg = self.cfg
        B, H, W, C = x.shape
        pe_img, pe_txt, pe_full = self._rope_tables(H, W, context.shape[1])
        if self.stacked:
            out = self._fwd_resident(
                self.glue, self.stacked.get("double"),
                self.stacked.get("single"), x, t, context, pooled,
                guidance, pe_img, pe_txt, pe_full)
            return unpatchify(out, (H, W), cfg.patch_size, C)
        img, txt, vec = self._embed(
            self.glue, x, t, context, pooled,
            None if guidance is None else guidance)

        names = self.block_order
        # double-buffer: block i+1's weights start transferring before
        # block i's compute is dispatched
        cur, cur_streamed = self._fetch(names[0])
        xcat = None
        T = int(txt.shape[1])
        for i, name in enumerate(names):
            nxt = self._fetch(names[i + 1]) if i + 1 < len(names) else None
            if name.startswith("double"):
                img, txt = self._dblock(cur, img, txt, vec, pe_img, pe_txt)
                out = img
            else:
                if xcat is None:
                    xcat = jnp.concatenate([txt, img], axis=1)
                xcat = self._sblock(cur, xcat, vec, pe_full, T=T)
                out = xcat
            if cur_streamed:
                # BACKPRESSURE: without this barrier the python loop
                # enqueues the entire ladder's transfers ahead of the
                # device (30 steps × 24 GB of staged host buffers — a
                # measured 130 GB host OOM). Blocking on the block output
                # keeps at most cur (computing) + nxt (streaming) in
                # flight while still overlapping transfer with compute.
                jax.block_until_ready(out)
                for leaf in jax.tree_util.tree_leaves(cur):
                    leaf.delete()       # free HBM as soon as consumed
            if nxt is not None:
                cur, cur_streamed = nxt
        img = (xcat[:, T:] if xcat is not None else img)
        out = self._head(self.glue, img, vec)
        return unpatchify(out, (H, W), cfg.patch_size, C)

    def denoiser(self, context, pooled, guidance: float):
        g = jnp.full((context.shape[0],), float(guidance))

        def den(x, sigma):
            t = jnp.broadcast_to(jnp.asarray(sigma), (x.shape[0],))
            v = self.forward(x, t, context, pooled, g)
            return x - jnp.asarray(sigma) * v

        return den


_WAN_GLUE_KEYS = ("patch_embedding", "time_emb_0", "time_emb_2",
                  "time_proj_1", "text_emb_0", "text_emb_2",
                  "head_modulation", "head")


def i2v_input_concat(y, mask):
    """ONE definition of the WAN i2v model-input concat
    (``concat([x_t, mask, y])``) — used by the dp/sp denoiser
    (``VideoPipeline._i2v_inp_fn``), the streamed offload ladder, and
    the resident one-jit ladder, so the conditioning layout can never
    desynchronize between execution modes."""
    def inp_fn(x):
        return jnp.concatenate(
            [x, jnp.broadcast_to(mask, x.shape[:4] + (mask.shape[-1],)),
             jnp.broadcast_to(y, x.shape[:4] + (y.shape[-1],))], axis=-1)

    return inp_fn


class OffloadedWan:
    """Single-device WAN executor with host-resident/streamed blocks —
    the video-side counterpart of :class:`OffloadedFlux`, sharing the
    same substrate (``_build_block_store``): fp8(e4m3) per-channel
    weight quantization, fully-resident ``lax.scan`` fast path, streamed
    double-buffered fallback. This is how WAN-2.1/2.2 **14B** video
    models (28 GB bf16/expert — ~2× one chip's HBM) run on ONE chip:
    quantized, one expert resident at a time (~14 GB fp8; blocks past
    the budget stream per step). The reference covers this scale only
    via multi-GPU fan-out or ComfyUI lowvram streaming
    (``/root/reference/README.md:186-189``)."""

    def __init__(self, wan, params, resident_bytes: Optional[int] = None,
                 device=None, stream_dtype: Optional[str] = None):
        import dataclasses as _dc

        from ..models.wan import WanBlock, WanConfig  # noqa: F401

        # same OOM-measured necessity as OffloadedFlux: memory-starved
        # executors must prefer the pallas flash kernel
        self.cfg = _dc.replace(wan.config, attn_backend="flash")
        self.device = device or jax.devices()[0]
        budget = (resident_budget_bytes() if resident_bytes is None
                  else int(resident_bytes))
        _build_block_store(self, params, budget, stream_dtype,
                           block_prefixes=("block",),
                           glue_keys=_WAN_GLUE_KEYS,
                           expected_blocks=self.cfg.num_layers)

        cfg = self.cfg

        def embed_fn(gl, x, t, ctx_raw):
            sub = {k: gl[k] for k in
                   ("patch_embedding", "time_emb_0", "time_emb_2",
                    "time_proj_1", "text_emb_0", "text_emb_2")
                   if k in gl}
            return _WanEmbed(cfg).apply({"params": sub}, x, t, ctx_raw)

        def block_fn(bufs, tok, e0, ctx, pe):
            bp = _unflatten_block(bufs, *self._layout["block"])
            return WanBlock(cfg).apply({"params": bp}, tok, e0, ctx, pe,
                                       None)

        def head_fn(gl, tok, e, fhw, FHW):
            """Exact tail of ``WanModel.__call__`` (models/wan.py) over
            the glue params."""
            dt = cfg.jnp_dtype
            f, h, w = fhw
            F, H, W = FHW
            hm = (gl["head_modulation"].astype(jnp.float32)
                  + e.astype(jnp.float32)[:, None, :]).astype(dt)
            sh, sc = hm[:, 0][:, None, :], hm[:, 1][:, None, :]
            tok = nn.LayerNorm(use_scale=False, use_bias=False,
                               epsilon=cfg.eps, dtype=dt).apply(
                {}, tok) * (1 + sc) + sh
            pt, ph, pw = cfg.patch_size
            out = nn.Dense(pt * ph * pw * cfg.out_channels,
                           dtype=jnp.float32).apply(
                {"params": gl["head"]}, tok.astype(jnp.float32))
            B = tok.shape[0]
            o = cfg.out_channels
            out = out.reshape(B, f, h, w, pt, ph, pw, o)
            out = out.transpose(0, 1, 4, 2, 5, 3, 6, 7)
            return out.reshape(B, F, H, W, o)

        self._embed = jax.jit(embed_fn)
        self._block = jax.jit(block_fn)
        self._head = jax.jit(head_fn, static_argnames=("fhw", "FHW"))

        def fwd_resident(gl, bstack, x, t, ctx_raw, pe, fhw, FHW):
            tok, e0, e, ctx = embed_fn(gl, x, t, ctx_raw)

            def body(carry, bufs):
                return block_fn(bufs, carry, e0, ctx, pe), None

            tok, _ = jax.lax.scan(body, tok, bstack)
            return head_fn(gl, tok, e, fhw, FHW)

        self._fwd_resident = jax.jit(fwd_resident,
                                     static_argnames=("fhw", "FHW"))

        def wan_ladder(gl, bstack, x, sigs, ctx, gscale, pe, y, mask,
                       token, key, do_cfg, sampler):
            """Whole sigma ladder in one program (fully-resident only;
            any registered sampler). ``y``/``mask`` are TRACED i2v
            conditioning (None for t2v) — traced, not closure-captured,
            so a new start image never recompiles. CFG runs cond/uncond
            as two sequential in-trace forwards (same memory argument
            as ``denoiser``)."""
            from .progress import wrap_denoiser
            from .samplers import sample

            B, F, H, W, _ = x.shape
            pt, ph, pw = cfg.patch_size
            fhw, FHW = (F // pt, H // ph, W // pw), (F, H, W)

            inp = ((lambda xx: xx) if y is None
                   else i2v_input_concat(y, mask))

            def model_call(xx, sigma, c):
                t = jnp.broadcast_to(sigma, (xx.shape[0],))
                v = fwd_resident(gl, bstack, inp(xx), t, c, pe, fhw, FHW)
                return xx - sigma * v

            def den(xx, sigma):
                if not do_cfg:
                    return model_call(xx, sigma, ctx)
                cond = model_call(xx, sigma, ctx)
                uncond = model_call(xx, sigma, jnp.zeros_like(ctx))
                return uncond + gscale * (cond - uncond)

            d = den if token is None else wrap_denoiser(den, token, 0)
            return sample(sampler, d, x, sigs, key=key)

        self._ladder = jax.jit(wan_ladder,
                               static_argnames=("do_cfg", "sampler"))

    def sample_resident(self, x, sigmas, context,
                        guidance_scale: float = 1.0, y=None,
                        mask=None, sampler: str = "euler", key=None,
                        progress_token=None):
        """Run the whole sigma ladder as ONE compiled program — valid
        only when fully resident (``self.stacked``); any registered
        sampler (ancestral ones draw from ``key`` exactly like the dp
        path); math identical to the compiled pipelines (pinned by
        tests)."""
        if not self.stacked:
            raise RuntimeError(
                "sample_resident requires a fully-resident executor "
                "(self.stacked)")
        B, F, H, W, _ = x.shape
        pt, ph, pw = self.cfg.patch_size
        pe = self._pe_tables(F // pt, H // ph, W // pw)
        token = (None if progress_token is None
                 else jnp.asarray(progress_token, jnp.int32))
        if key is None:
            key = jax.random.key(0)
        return self._ladder(
            self.glue, self.stacked["block"],
            jax.device_put(x, self.device),
            jnp.asarray(np.asarray(sigmas), jnp.float32), context,
            jnp.float32(guidance_scale), pe, y, mask, token, key,
            do_cfg=float(guidance_scale) != 1.0, sampler=sampler)

    def _pe_tables(self, f: int, h: int, w: int):
        from ..models.wan import video_ids
        from ..models.dit import rope_freqs as _rope

        key = (f, h, w)
        cached = getattr(self, "_pe_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        pe = _rope(video_ids(f, h, w), self.cfg.axes_dim, 10000.0)
        pe = jax.device_put(pe, self.device)
        self._pe_cache = (key, pe)
        return pe

    def _fetch(self, name: str):
        if name in self.resident:
            return self.resident[name], False
        return jax.device_put(self.streamed[name], self.device), True

    def forward(self, x, t, context):
        """One velocity evaluation; equivalent to ``WanModel.apply``
        (sp_axis None, pooled ignored) — pinned by tests (exact under
        ``native``, to quantization tolerance under fp8)."""
        cfg = self.cfg
        B, F, H, W, C = x.shape
        pt, ph, pw = cfg.patch_size
        fhw = (F // pt, H // ph, W // pw)
        pe = self._pe_tables(*fhw)
        if self.stacked:
            return self._fwd_resident(
                self.glue, self.stacked["block"], x, t, context, pe,
                fhw=fhw, FHW=(F, H, W))
        tok, e0, e, ctx = self._embed(self.glue, x, t, context)
        names = self.block_order
        cur, cur_streamed = self._fetch(names[0])
        for i, name in enumerate(names):
            nxt = self._fetch(names[i + 1]) if i + 1 < len(names) else None
            tok = self._block(cur, tok, e0, ctx, pe)
            if cur_streamed:
                # same backpressure as OffloadedFlux.forward: at most
                # cur (computing) + nxt (streaming) in flight
                jax.block_until_ready(tok)
                for leaf in jax.tree_util.tree_leaves(cur):
                    leaf.delete()
            if nxt is not None:
                cur, cur_streamed = nxt
        return self._head(self.glue, tok, e, fhw=fhw, FHW=(F, H, W))

    def denoiser(self, context, guidance_scale: float = 1.0,
                 inp_fn=None):
        """CFG matching ``VideoPipeline._denoiser`` exactly, but with
        cond/uncond as two sequential forwards instead of a concat batch
        — per-token normalizations make them bit-equivalent while
        halving activation HBM (which is what this executor is short
        of). ``inp_fn`` transforms the latent before the model sees it
        (i2v mask+conditioning concat), mirroring the dp denoiser."""
        uncond_ctx = jnp.zeros_like(context)

        def model_call(x, sigma, ctx):
            t = jnp.broadcast_to(jnp.asarray(sigma), (x.shape[0],))
            inp = x if inp_fn is None else inp_fn(x)
            v = self.forward(inp, t, ctx)
            return x - jnp.asarray(sigma) * v

        if guidance_scale == 1.0:
            return lambda x, s: model_call(x, s, context)

        def denoise(x, sigma):
            cond = model_call(x, sigma, context)
            uncond = model_call(x, sigma, uncond_ctx)
            return uncond + guidance_scale * (cond - uncond)

        return denoise

    def release(self) -> None:
        """Free this expert's HBM for the dual-expert swap."""
        release_store(self)


class _WanEmbed(nn.Module):
    """Pre-block glue of ``WanModel.__call__`` with identical submodule
    names so the full model's param tree slices straight in (equivalence
    pinned by ``tests/test_offload.py``). Returns ``(tok, e0, e, ctx)``
    — ``e`` feeds the head modulation."""

    config: Any

    @nn.compact
    def __call__(self, x, t, context):
        cfg = self.config
        dt = cfg.jnp_dtype
        B = x.shape[0]
        tok = nn.Conv(cfg.dim, kernel_size=cfg.patch_size,
                      strides=cfg.patch_size, dtype=dt,
                      name="patch_embedding")(x.astype(dt))
        tok = tok.reshape(B, -1, cfg.dim)
        emb = timestep_embedding(t * 1000.0, cfg.freq_dim).astype(dt)
        e = nn.Dense(cfg.dim, dtype=dt, name="time_emb_0")(emb)
        e = nn.Dense(cfg.dim, dtype=dt, name="time_emb_2")(nn.silu(e))
        e0 = nn.Dense(cfg.dim * 6, dtype=dt, name="time_proj_1")(
            nn.silu(e)).reshape(B, 6, cfg.dim)
        ctx = nn.Dense(cfg.dim, dtype=dt, name="text_emb_0")(
            context.astype(dt))
        ctx = nn.Dense(cfg.dim, dtype=dt, name="text_emb_2")(
            nn.gelu(ctx, approximate=True))
        return tok, e0, e, ctx


def sample_euler_py(denoise, x, sigmas, on_step=None,
                    should_stop=None) -> jax.Array:
    """Python-level Euler ladder (exact math of ``samplers.sample``'s
    euler branch — pinned by tests). The streamed offloaded denoiser
    cannot live inside a ``lax.scan``, so the loop runs host-side; for
    20-50 steps the per-step dispatch cost is noise next to block
    streaming. ``on_step(sigma, x0)`` fires once per step with the
    denoised estimate — the host-side twin of the compiled samplers'
    in-trace progress callback
    (``cluster/progress.ProgressTracker.report``). ``should_stop()`` is
    checked before every step (the server's ``/distributed/interrupt``
    — the reference likewise interrupts between steps, not inside a
    dispatched kernel) and raises ``InterruptedError``."""
    sig = np.asarray(sigmas, np.float64)
    for i in range(len(sig) - 1):
        if should_stop is not None and should_stop():
            raise InterruptedError(
                f"offloaded sampling interrupted at step {i}/"
                f"{len(sig) - 1}")
        x0 = denoise(x, jnp.asarray(sig[i], jnp.float32))
        if should_stop is not None:
            # interruptibility requires per-step SYNCHRONIZATION: jax
            # dispatch is async, so without this block the loop would
            # enqueue the whole ladder in milliseconds and every
            # should_stop() check would pass before any device compute
            # ran (the check would be theater)
            jax.block_until_ready(x0)
        if on_step is not None:
            on_step(float(sig[i]), x0)
        if sig[i + 1] == 0.0:
            x = x0
        else:
            d = (x - x0) / sig[i]
            x = x + d * (sig[i + 1] - sig[i])
    return x
