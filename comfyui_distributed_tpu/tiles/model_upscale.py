"""SPMD tiled application of a learned upscaler (RRDBNet class).

The reference gets this from ComfyUI's ``ImageUpscaleWithModel`` (tiled
torch loop on one GPU, feeding ``upscaled_image`` into USDU —
``/root/reference/nodes/distributed_upscale.py:84-91``). TPU-first
redesign: the tile batch is sharded over the mesh's data axis inside one
``shard_map`` program — every chip convolves its tile block on the MXU,
and the feather-normalized composite runs as XLA scatter ops. Because a
k× upscale scales the whole grid geometry linearly, the output composite
reuses the same static-grid machinery at k× coordinates.

Compiled programs are cached by value (mesh/config/shape/tiling — same
discipline as ``TileUpscaler._cached_upscale_fn``) with params passed as
arguments, so repeated node executions re-trace nothing and weights are
never baked into executables as constants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map

from ..diffusion.pipeline import Txt2ImgPipeline
from ..ops.blend import composite_tiles, extract_tiles, feather_mask
from ..utils import constants
from .grid import compute_tile_grid, pad_count_to

_CACHE_MAX = 8
_fn_cache: dict = {}


def _build_fn(mesh: Mesh, model, config, in_shape, tile: int, padding: int,
              axis: str):
    B, H, W, _ = in_shape
    s = config.scale
    grid = compute_tile_grid(W, H, tile, tile, padding)
    out_grid = compute_tile_grid(W * s, H * s, tile * s, tile * s,
                                 padding * s)
    assert out_grid.num_tiles == grid.num_tiles
    masks = feather_mask(out_grid, feather=max(1, (padding * s) // 2))

    n_shards = mesh.shape[axis]
    total = B * grid.num_tiles
    padded = pad_count_to(total, n_shards)

    sharded = shard_map(
        lambda params, tiles: model.apply(params, tiles),
        mesh=mesh,
        in_specs=(P(), P(axis, None, None, None)),
        out_specs=P(axis, None, None, None),
    )

    def run(params, images):
        all_tiles = jnp.concatenate(
            [extract_tiles(images[b], grid) for b in range(B)], axis=0)
        if padded > total:
            pad = jnp.zeros((padded - total,) + all_tiles.shape[1:],
                            all_tiles.dtype)
            all_tiles = jnp.concatenate([all_tiles, pad], axis=0)
        done = sharded(params, all_tiles)[:total]
        outs = [
            composite_tiles(
                done[b * grid.num_tiles:(b + 1) * grid.num_tiles],
                masks, out_grid)
            for b in range(B)
        ]
        return jnp.stack(outs, axis=0)

    return jax.jit(run)


def tiled_model_upscale(
    mesh: Mesh,
    bundle,                      # models.upscaler.UpscalerBundle
    images: jax.Array,           # [B, H, W, C] in [0,1]
    tile: int = 256,
    padding: int = 16,
    axis: str = constants.AXIS_DATA,
) -> jax.Array:
    """Upscale ``images`` by the bundle's scale, tile-sharded over ``axis``.

    Deterministic and shard-count invariant: tiles are keyed by global
    index and composited in grid order regardless of which chip computed
    them.
    """
    B, H, W, _ = images.shape
    s = bundle.scale
    # x2/x1 checkpoints run a pixel-unshuffle stem: every crop dimension
    # must divide by the unshuffle factor, so align the geometry and
    # edge-pad the image, cropping the output back at the end
    f = {4: 1, 2: 2, 1: 4}.get(s, 1)
    tile = max(f, (tile // f) * f)
    padding = (padding // f) * f
    pad_h = (-H) % f
    pad_w = (-W) % f
    if pad_h or pad_w:
        images = jnp.pad(images, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)),
                         mode="edge")

    from ..diffusion.pipeline import cached_build

    key = (Txt2ImgPipeline._mesh_cache_key(mesh), bundle.model.config,
           images.shape, tile, padding, axis)
    fn = cached_build(
        _fn_cache, key,
        lambda: _build_fn(mesh, bundle.model, bundle.model.config,
                          images.shape, tile, padding, axis),
        _CACHE_MAX)
    return fn(bundle.params, images)[:, :H * s, :W * s, :]
