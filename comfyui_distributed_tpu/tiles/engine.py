"""Sharded tile upscaler — distributed Ultimate-SD-Upscale, TPU-native.

Reference flow (SURVEY §3.3): master seeds an HTTP pull queue of tile IDs;
worker processes pull tile IDs, VAE-encode → ksample → decode each tile,
POST PNGs back; master blends sequentially and re-processes stragglers
(``upscale/modes/static.py``, ``upscale/tile_ops.py``).

TPU-native flow — ONE compiled SPMD program per (image size, spec):
  resize → extract all crops (static origins) → pad tile count to the shard
  multiple → ``shard_map`` img2img over the tile axis (each shard processes
  ``T/n`` tiles; per-tile noise keys derive from the *global* tile index so
  results are identical for any shard count) → feather-mask normalized
  composite. There is no pull queue, no heartbeat, no requeue *inside* the
  program — host-level failure handling lives in ``cluster/`` and operates
  at whole-program granularity (static shapes are what make TPUs fast;
  SURVEY §7 "hard parts" #2).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map

from ..diffusion.guidance import cfg_denoiser
from ..diffusion.pipeline import (GenerationSpec, Txt2ImgPipeline,
                                  bind_weights, make_sigma_ladder)
from ..diffusion.samplers import sample
from ..ops.blend import composite_tiles, extract_tiles, feather_mask
from ..ops.resize import upscale_image
from ..utils import constants
from .grid import TileGrid, compute_tile_grid, pad_count_to


@dataclasses.dataclass(frozen=True)
class UpscaleSpec:
    scale: float = 2.0
    tile_w: int = 512
    tile_h: int = 512
    padding: int = 32
    feather: Optional[int] = None     # None → padding
    steps: int = 20
    denoise: float = 0.3
    sampler: str = "euler"
    scheduler: str = "karras"
    guidance_scale: float = 5.0
    resize_method: str = "lanczos3"

    def generation_spec(self) -> GenerationSpec:
        return GenerationSpec(
            steps=self.steps,
            denoise=self.denoise,
            sampler=self.sampler,
            scheduler=self.scheduler,
            guidance_scale=self.guidance_scale,
        )


class TileUpscaler:
    """Drives a ``Txt2ImgPipeline``'s model stack over a sharded tile axis."""

    _CACHE_MAX = 8

    def __init__(self, pipeline: Txt2ImgPipeline):
        self.pipeline = pipeline
        self._fn_cache: dict = {}

    def _cached_upscale_fn(self, mesh: Mesh, image_hw, spec: UpscaleSpec,
                          batch: int, axis: str, with_spatial: bool,
                          with_control: bool = False):
        """Compiled-program cache (same value-keyed discipline as
        ``Txt2ImgPipeline._cached_fn``): dynamic per-image farming calls
        upscale() once per image — without this it would re-trace and
        re-compile the identical program every time."""
        from ..diffusion.pipeline import cached_build

        key = (Txt2ImgPipeline._mesh_cache_key(mesh), tuple(image_hw), spec,
               batch, axis, with_spatial, with_control)
        return cached_build(
            self, key,
            lambda: self.upscale_fn(mesh, tuple(image_hw), spec, batch=batch,
                                    axis=axis, with_spatial=with_spatial,
                                    with_control=with_control),
            self._CACHE_MAX)

    def grid_for(self, image_h: int, image_w: int, spec: UpscaleSpec) -> TileGrid:
        out_h = int(round(image_h * spec.scale))
        out_w = int(round(image_w * spec.scale))
        return compute_tile_grid(out_w, out_h, spec.tile_w, spec.tile_h, spec.padding)

    def _img2img_tiles(self, tiles, key, context, uncond_context, y, uncond_y,
                       spec: UpscaleSpec, sigmas, global_idx,
                       tile_masks=None, hint_tiles=None, weights=None):
        """img2img a [n, ch, cw, C] tile batch on one shard.

        Per-tile noise keys fold in the *global* tile index, so the output
        for tile i never depends on which shard processed it — the property
        that lets host-level requeue re-shard freely (reference analogue:
        tiles carry global IDs through the queue, ``upscale/job_store.py``).

        ``tile_masks`` ([n, ch, cw, 1], optional) is this shard's slice of
        the spatial conditioning map, already cropped per tile with the
        same grid as the image — the engine's analogue of the reference's
        per-tile conditioning crop (``utils/usdu_utils.py`` ``crop_cond``
        at ``:506``): mask 1 = denoise, 0 = keep the source pixels.
        """
        pipe = self.pipeline
        vae = pipe.vae
        n = tiles.shape[0]
        latents = vae.encode(
            tiles * 2.0 - 1.0,
            params=None if weights is None else weights["vae_enc"])

        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(global_idx)
        noise = jax.vmap(
            lambda k, lat: jax.random.normal(k, lat.shape, lat.dtype)
        )(keys, latents)
        noised = latents + noise * sigmas[0]

        gspec = spec.generation_spec()
        bc = lambda a: jnp.broadcast_to(a, (n,) + a.shape[1:])
        if gspec.guidance_scale != 1.0:
            denoise_fn = cfg_denoiser(
                lambda ctx, yy: pipe._denoiser(ctx, yy, hint=hint_tiles,
                                               weights=weights),
                bc(context), bc(uncond_context), gspec.guidance_scale,
                None if y is None else bc(y),
                None if uncond_y is None else bc(uncond_y),
            )
        else:
            denoise_fn = pipe._denoiser(bc(context),
                                        None if y is None else bc(y),
                                        hint=hint_tiles, weights=weights)
        # sampler key uses a sentinel fold well above any global tile index
        x0 = sample(gspec.sampler, denoise_fn, noised, sigmas,
                    key=jax.random.fold_in(key, jnp.uint32(0xFFFFFFFF)))
        out = vae.decode(
            x0, params=None if weights is None else weights["vae_dec"])
        out = jnp.clip(out / 2.0 + 0.5, 0.0, 1.0)
        if tile_masks is not None:
            out = tiles * (1.0 - tile_masks) + out * tile_masks
        return out

    def upscale_fn(self, mesh: Mesh, image_hw: tuple[int, int], spec: UpscaleSpec,
                   batch: int = 1, axis: str = constants.AXIS_DATA,
                   with_spatial: bool = False, with_control: bool = False):
        """Compile the full upscale: (images, key, ctx, unc, y, unc_y
        [, spatial]) → upscaled images [B, H·s, W·s, C].

        With ``with_spatial`` the last argument is a spatial conditioning
        map [B, H·s, W·s, 1] (denoise mask: 1 = regenerate, 0 = keep). It
        is cropped per tile with the image's own grid — seam-free region
        control matching the reference's conditioning-crop semantics
        (``utils/usdu_utils.py:506``, ``utils/crop_model_patch.py:9-114``).
        """
        H, W = image_hw
        grid = self.grid_for(H, W, spec)
        n_shards = mesh.shape[axis]
        total = batch * grid.num_tiles
        padded = pad_count_to(total, n_shards)
        per_shard = padded // n_shards
        sigmas = make_sigma_ladder(spec.generation_spec(), self.pipeline.schedule)
        masks = feather_mask(grid, spec.feather)
        has_y = self.pipeline.unet.config.adm_in_channels > 0
        # control hints live in the hint stem's space (latent-res × 8):
        # the hint grid is the image grid scaled by 8/vae_downscale, so
        # every tile's hint crop aligns exactly with its image crop — the
        # reference's per-tile ControlNet crop (usdu_utils.py:506)
        hf = 8 // self.pipeline.vae.config.downscale if with_control else 1
        hint_grid = grid if hf == 1 else compute_tile_grid(
            grid.image_w * hf, grid.image_h * hf,
            grid.tile_w * hf, grid.tile_h * hf, grid.padding * hf)

        def process_shard(weights, tiles, stiles, htiles, key, context,
                          uncond_context, y, uncond_y):
            # tiles: [per_shard, ch, cw, C] block of this shard
            shard_i = jax.lax.axis_index(axis)
            global_idx = shard_i * per_shard + jnp.arange(per_shard)
            return self._img2img_tiles(
                tiles, key, context, uncond_context,
                y if has_y else None, uncond_y if has_y else None,
                spec, sigmas, global_idx,
                tile_masks=stiles if with_spatial else None,
                hint_tiles=htiles if with_control else None,
                weights=weights,
            )

        sharded = shard_map(
            process_shard,
            mesh=mesh,
            in_specs=(P(),
                      P(axis, None, None, None), P(axis, None, None, None),
                      P(axis, None, None, None),
                      P(), P(None, None, None),
                      P(None, None, None), P(None, None), P(None, None)),
            out_specs=P(axis, None, None, None),
        )

        def tile_and_pad(per_image_fn, arrs):
            stacked = jnp.concatenate(
                [per_image_fn(a) for a in arrs], axis=0)
            if padded > total:
                pad = jnp.zeros((padded - total,) + stacked.shape[1:],
                                stacked.dtype)
                stacked = jnp.concatenate([stacked, pad], axis=0)
            return stacked

        def run(weights, images, key, context, uncond_context, y, uncond_y,
                spatial=None, hint=None):
            up = upscale_image(images, spec.scale, spec.resize_method)
            all_tiles = tile_and_pad(lambda im: extract_tiles(im, grid),
                                     [up[b] for b in range(batch)])
            if with_spatial:
                stiles = tile_and_pad(lambda m: extract_tiles(m, grid),
                                      [spatial[b] for b in range(batch)])
            else:
                stiles = jnp.ones(all_tiles.shape[:3] + (1,), all_tiles.dtype)
            if with_control:
                htiles = tile_and_pad(
                    lambda m: extract_tiles(m, hint_grid),
                    [hint[b] for b in range(batch)])
            else:
                htiles = jnp.zeros(
                    (all_tiles.shape[0], 8, 8, 1), all_tiles.dtype)
            done = sharded(weights, all_tiles, stiles, htiles, key, context,
                           uncond_context, y, uncond_y)
            done = done[:total]
            outs = [
                composite_tiles(
                    done[b * grid.num_tiles:(b + 1) * grid.num_tiles], masks, grid
                )
                for b in range(batch)
            ]
            return jnp.stack(outs, axis=0)

        jitted = jax.jit(run)
        weights = self.pipeline._weights(img2img=True)

        return bind_weights(jitted, weights)

    def upscale(
        self,
        mesh: Mesh,
        images: jax.Array,
        spec: UpscaleSpec,
        seed: int,
        context: jax.Array,
        uncond_context: jax.Array,
        y: Optional[jax.Array] = None,
        uncond_y: Optional[jax.Array] = None,
        axis: str = constants.AXIS_DATA,
        spatial_cond: Optional[jax.Array] = None,
        control_hint: Optional[jax.Array] = None,
    ) -> jax.Array:
        """``spatial_cond``: [B, H, W, 1] (input res) or [B, H·s, W·s, 1]
        (output res) region mask, cropped per tile inside the program.
        ``control_hint``: [B, h, w, C] control map for the pipeline's
        ControlNet (``with_control`` clone), cropped per tile in the hint
        stem's space — the reference's per-tile ControlNet crop."""
        B, H, W, _ = images.shape
        with_control = (control_hint is not None
                        and getattr(self.pipeline, "_control", None) is not None)
        fn = self._cached_upscale_fn(mesh, (H, W), spec, batch=B, axis=axis,
                                     with_spatial=spatial_cond is not None,
                                     with_control=with_control)
        adm = self.pipeline.unet.config.adm_in_channels
        if y is None:
            y = jnp.zeros((1, max(adm, 1)), jnp.float32)
        if uncond_y is None:
            uncond_y = jnp.zeros_like(y)
        args = (images, jax.random.key(seed), context, uncond_context, y, uncond_y)
        grid = self.grid_for(H, W, spec)
        if spatial_cond is not None:
            if spatial_cond.shape[1:3] != (grid.image_h, grid.image_w):
                spatial_cond = jax.image.resize(
                    spatial_cond.astype(jnp.float32),
                    (B, grid.image_h, grid.image_w, spatial_cond.shape[-1]),
                    method="bilinear")
        if with_control:
            hb = control_hint.shape[0]
            if hb not in (1, B):
                raise ValueError(
                    f"control hint batch {hb} incompatible with image "
                    f"batch {B} (must be 1 or {B})")
            hfac = 8 // self.pipeline.vae.config.downscale
            target = (grid.image_h * hfac, grid.image_w * hfac)
            if control_hint.shape[1:3] != target:
                # resize per image — never interpolate across the batch dim
                control_hint = jax.image.resize(
                    control_hint.astype(jnp.float32),
                    (hb, *target, control_hint.shape[-1]), method="bilinear")
            if hb == 1 and B > 1:
                control_hint = jnp.broadcast_to(
                    control_hint, (B, *control_hint.shape[1:]))
        # None is an empty pytree under jit; unused trailing inputs cost
        # nothing when the matching with_* flag compiled them out
        return fn(*args, spatial_cond,
                  control_hint if with_control else None)

    # --- cross-host farm support -------------------------------------------

    @staticmethod
    def tiles_per_device_default(tile_w: int, tile_h: int) -> int:
        """Per-device tile batch for the farm's fixed-chunk program.

        Batch-1 tiles under-fill the MXU badly: a 512² tile is a 64²
        latent whose self-attention blocks run at 1024/256 tokens —
        matmuls far below the 128×128 systolic tile at batch 1. Measured
        on the v5e chip (r04, `benchmarks/r04_tpu_usdu.json`): batching
        tiles per dispatch cuts the 4K USDU wall-clock vs the one-tile
        chunks r02 shipped. Memory bounds the batch: activations scale
        with tile area, so the default halves as tiles grow past 512².
        ``CDT_TILES_PER_DEVICE`` overrides.
        """
        from ..utils.constants import TILES_PER_DEVICE

        env = TILES_PER_DEVICE.get()
        if env > 0:
            return env
        try:
            if jax.devices()[0].platform == "cpu":
                return 1     # tests/tiny stacks: don't pad tiny jobs 8-wide
        except RuntimeError:
            return 1
        area = tile_w * tile_h
        if area <= 512 * 512:
            return 8
        if area <= 1024 * 1024:
            return 4
        return 1

    def range_plan(
        self,
        mesh: Mesh,
        image: jax.Array,
        spec: UpscaleSpec,
        seed: int,
        context: jax.Array,
        uncond_context: jax.Array,
        y: Optional[jax.Array] = None,
        uncond_y: Optional[jax.Array] = None,
        axis: str = constants.AXIS_DATA,
        spatial_cond: Optional[jax.Array] = None,
        tiles_per_device: Optional[int] = None,
    ) -> "TileRangePlan":
        """Prepare arbitrary-range tile processing for the cross-host farm
        (``cluster/tile_farm.py``): resize + extract all crops once, and
        compile ONE fixed-chunk SPMD program reused for every pulled task.

        Per-tile noise keys fold the *global* tile index exactly as
        ``upscale_fn`` does, so any host processing any range produces the
        same tiles the single-program path would — the shard-count /
        host-assignment invariance that makes requeue safe (the reference
        gets this from tile IDs travelling through its HTTP queue,
        ``upscale/job_store.py:34-80``). Results are also invariant to
        ``tiles_per_device`` (the per-dispatch tile batch) for the same
        reason; it is purely a throughput/memory knob.
        """
        H, W, _ = image.shape
        grid = self.grid_for(H, W, spec)
        n_shards = mesh.shape[axis]
        if tiles_per_device is None:
            tiles_per_device = self.tiles_per_device_default(
                spec.tile_w, spec.tile_h)
        # never compile a chunk wider than the job itself — a 4-tile job
        # on an 8-device host must not pad (and denoise) 60 zero tiles
        per_job = -(-grid.num_tiles // n_shards)
        per_shard = max(1, min(tiles_per_device, per_job))
        chunk = n_shards * per_shard
        sigmas = make_sigma_ladder(spec.generation_spec(), self.pipeline.schedule)
        has_y = self.pipeline.unet.config.adm_in_channels > 0
        if y is None:
            adm = self.pipeline.unet.config.adm_in_channels
            y = jnp.zeros((1, max(adm, 1)), jnp.float32)
        if uncond_y is None:
            uncond_y = jnp.zeros_like(y)

        @jax.jit
        def prepare(img):
            up = upscale_image(img[None], spec.scale, spec.resize_method)[0]
            return extract_tiles(up, grid)

        all_tiles = prepare(image)              # [T, ch, cw, C]
        use_spatial = spatial_cond is not None
        if use_spatial:
            # same per-tile crop as the image (reference crop_cond
            # semantics, usdu_utils.py:506), resized to the output grid
            smap = jnp.asarray(spatial_cond, jnp.float32)
            if smap.ndim == 2:
                smap = smap[..., None]
            if smap.shape[:2] != (grid.image_h, grid.image_w):
                smap = jax.image.resize(
                    smap, (grid.image_h, grid.image_w, smap.shape[-1]),
                    method="bilinear")
            all_stiles = extract_tiles(smap, grid)
        else:
            all_stiles = jnp.ones(all_tiles.shape[:3] + (1,), all_tiles.dtype)

        def process_shard(weights, tiles, stiles, start, key, ctx, unc,
                          yy, uyy):
            shard_i = jax.lax.axis_index(axis)
            global_idx = start + shard_i * per_shard + jnp.arange(per_shard)
            return self._img2img_tiles(
                tiles, key, ctx, unc,
                yy if has_y else None, uyy if has_y else None,
                spec, sigmas, global_idx,
                tile_masks=stiles if use_spatial else None,
                weights=weights,
            )

        jitted = jax.jit(shard_map(
            process_shard,
            mesh=mesh,
            in_specs=(P(),
                      P(axis, None, None, None), P(axis, None, None, None),
                      P(), P(), P(None, None, None),
                      P(None, None, None), P(None, None), P(None, None)),
            out_specs=P(axis, None, None, None),
        ))
        sharded = bind_weights(jitted, self.pipeline._weights(img2img=True))
        key = jax.random.key(seed)

        def run_one(start: int, end: int):
            seg = all_tiles[start:end]
            sseg = all_stiles[start:end]
            if seg.shape[0] < chunk:
                pad = jnp.zeros((chunk - seg.shape[0],) + seg.shape[1:],
                                seg.dtype)
                seg = jnp.concatenate([seg, pad], axis=0)
                spad = jnp.ones((chunk - sseg.shape[0],) + sseg.shape[1:],
                                sseg.dtype)
                sseg = jnp.concatenate([sseg, spad], axis=0)
            return sharded(seg, sseg, jnp.int32(start), key, context,
                           uncond_context, y, uncond_y)[: end - start]

        _empty_spec: list = []   # cached eval_shape result for empty ranges

        def flops_per_dispatch() -> float:
            """Analytic matmul+conv FLOPs of ONE fixed-chunk dispatch,
            per-shard body counted once (= one chip's work) — the MFU
            accounting hook for the USDU bench (r04 VERDICT weak #1:
            only SDXL txt2img carried an mfu field)."""
            from ..utils.flops import estimate_flops

            seg = jax.ShapeDtypeStruct(
                (chunk,) + tuple(all_tiles.shape[1:]), all_tiles.dtype)
            sseg = jax.ShapeDtypeStruct(
                (chunk,) + tuple(all_stiles.shape[1:]), all_stiles.dtype)
            return estimate_flops(sharded, seg, sseg, jnp.int32(0), key,
                                  context, uncond_context, y, uncond_y)

        def run_range(start: int, end: int):
            """Process [start, end) with the compiled fixed-chunk program.

            Ranges wider than this host's chunk loop over sub-chunks, so
            a farm task sized by the MASTER's chunk still runs correctly
            on a worker whose own chunk differs (fewer local devices, a
            different ``CDT_TILES_PER_DEVICE``, a CPU fallback host) —
            chunk mismatch costs only padding, never correctness. All
            sub-chunks are dispatched before any result is fetched: JAX
            dispatch is async, so chunk i's device→host transfer
            overlaps chunk i+1's compute (the fetch rides a slow link on
            tunneled hosts)."""
            import numpy as np

            if start >= end:
                # zero-width task (e.g. a requeue race handed out an
                # empty range): no-op instead of crashing the worker on
                # np.concatenate([]) — shape/dtype from the compiled
                # program's own output spec so the two paths can't
                # drift. The abstract trace is cached after the first
                # empty call (and never paid by plans that only run
                # real ranges).
                if not _empty_spec:
                    seg = jax.ShapeDtypeStruct(
                        (chunk,) + tuple(all_tiles.shape[1:]),
                        all_tiles.dtype)
                    sseg = jax.ShapeDtypeStruct(
                        (chunk,) + tuple(all_stiles.shape[1:]),
                        all_stiles.dtype)
                    _empty_spec.append(jax.eval_shape(
                        sharded, seg, sseg, jnp.int32(0), key, context,
                        uncond_context, y, uncond_y))
                out = _empty_spec[0]
                return np.zeros((0,) + tuple(out.shape[1:]),
                                dtype=out.dtype)
            outs = [run_one(s, min(s + chunk, end))
                    for s in range(start, end, chunk)]       # all async
            return np.concatenate([np.asarray(o) for o in outs], axis=0)

        def source_range(start: int, end: int):
            import numpy as np

            return np.asarray(all_tiles[start:end], np.float32)

        return TileRangePlan(grid=grid, chunk=chunk, run_range=run_range,
                             feather=spec.feather,
                             flops_per_dispatch=flops_per_dispatch,
                             source_range=source_range)

    def composite(self, tiles, plan: "TileRangePlan"):
        """Blend a complete [T, ch, cw, C] tile set into the output image
        (same normalized feather composite the single-program path uses)."""
        masks = feather_mask(plan.grid, plan.feather)
        return composite_tiles(jnp.asarray(tiles), masks, plan.grid)


@dataclasses.dataclass
class TileRangePlan:
    """Host-side handle the farm drivers use: tile geometry + the compiled
    fixed-chunk range processor."""

    grid: TileGrid
    chunk: int
    run_range: "callable"
    feather: Optional[int]
    flops_per_dispatch: Optional["callable"] = None
    # degraded fallback for dead-lettered farm tasks: the plain-resized
    # source crops, no diffusion (cluster/tile_farm.assemble_tiles)
    source_range: Optional["callable"] = None

    @property
    def num_tiles(self) -> int:
        return self.grid.num_tiles
