"""Tile engine — TPU-native Ultimate-SD-Upscale (reference L2, ``upscale/``).

The reference scatters tiles to worker GPUs through an HTTP pull queue and
blends them back sequentially on the master (``upscale/modes/static.py``).
Here the tile axis is a *sharded batch axis*: all tiles are extracted with
static origins, processed in one SPMD img2img program over the mesh, and
composited with normalized feathered masks — order-independent, so no
master-side sequential blend loop exists at all.
"""

from .grid import TileGrid, compute_tile_grid  # noqa: F401
from .engine import TileUpscaler, UpscaleSpec  # noqa: F401
