"""Static tile-grid math.

Parity: reference ``upscale/tile_ops.py:18-155`` — origin-anchored
``ceil(H/th) × ceil(W/tw)`` grid, padded crop regions, uniform crop sizing
("multiple-of-8" rounding there; here crops are uniform *by construction*
because XLA wants one static shape for the whole tile batch). Near image
borders the crop origin is shifted inward (not shrunk), so border tiles
simply overlap their neighbours more; the normalized blend (ops/blend.py)
makes overlap harmless.

Everything in this module is host-side Python over static ints — it runs
once per (image size, tile size) and parameterizes the compiled program.
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class TileRegion:
    """One tile: crop rect (uniform size) + its core rect in crop coords."""

    x0: int                 # crop origin in image coords
    y0: int
    core_x0: int            # core (unpadded cell) origin within the crop
    core_y0: int
    core_w: int
    core_h: int


@dataclasses.dataclass(frozen=True)
class TileGrid:
    image_w: int
    image_h: int
    tile_w: int
    tile_h: int
    padding: int
    crop_w: int             # uniform crop width  (tile_w + 2·padding, clamped)
    crop_h: int
    cols: int
    rows: int
    regions: tuple[TileRegion, ...]

    @property
    def num_tiles(self) -> int:
        return self.cols * self.rows


def compute_tile_grid(
    image_w: int, image_h: int, tile_w: int, tile_h: int, padding: int = 32
) -> TileGrid:
    """Build the static grid. ``ceil`` cell counts as in the reference
    (``upscale/tile_ops.py:18-32``); every crop is exactly
    ``(crop_h, crop_w)`` and lies fully inside the image."""
    cols = max(1, math.ceil(image_w / tile_w))
    rows = max(1, math.ceil(image_h / tile_h))
    crop_w = min(image_w, tile_w + 2 * padding)
    crop_h = min(image_h, tile_h + 2 * padding)

    regions = []
    for r in range(rows):
        for c in range(cols):
            cell_x0 = c * tile_w
            cell_y0 = r * tile_h
            cell_w = min(tile_w, image_w - cell_x0)
            cell_h = min(tile_h, image_h - cell_y0)
            # padded crop, shifted inward to stay in bounds
            x0 = min(max(cell_x0 - padding, 0), image_w - crop_w)
            y0 = min(max(cell_y0 - padding, 0), image_h - crop_h)
            regions.append(
                TileRegion(
                    x0=x0,
                    y0=y0,
                    core_x0=cell_x0 - x0,
                    core_y0=cell_y0 - y0,
                    core_w=cell_w,
                    core_h=cell_h,
                )
            )
    return TileGrid(
        image_w=image_w,
        image_h=image_h,
        tile_w=tile_w,
        tile_h=tile_h,
        padding=padding,
        crop_w=crop_w,
        crop_h=crop_h,
        cols=cols,
        rows=rows,
        regions=tuple(regions),
    )


def pad_count_to(n: int, multiple: int) -> int:
    """Tiles are padded to a multiple of the shard count so the sharded
    batch divides evenly (TPU static-shape discipline; the reference's
    dynamic pull queue has no analogue of this)."""
    if multiple <= 0:
        return n
    return ((n + multiple - 1) // multiple) * multiple
