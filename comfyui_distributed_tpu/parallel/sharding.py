"""NamedSharding helpers and host→device placement.

Thin, convention-setting wrappers: batch axis 0 shards over ``dp`` (the
reference's job fan-out), weights replicate (or shard over ``tp`` when tensor
parallelism is enabled), everything else replicates.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import constants


def batch_sharding(mesh: Mesh, ndim: int, axis: str = constants.AXIS_DATA) -> NamedSharding:
    """Shard dim 0 over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree: Any, axis: str = constants.AXIS_DATA) -> Any:
    """Place a pytree on the mesh with leaf dim 0 sharded over ``axis``."""
    return jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding(mesh, x.ndim, axis)), tree
    )


def replicate(mesh: Mesh, tree: Any) -> Any:
    sh = replicated_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def batch_spec(ndim: int, axis: str = constants.AXIS_DATA) -> P:
    return P(axis, *([None] * (ndim - 1)))
