"""Multi-host JAX runtime bootstrap.

The reference scales across machines by HTTP port registration: every
worker is a separately-launched ComfyUI process that the master reaches
over the network (``workers/process/lifecycle.py:78-96``, config hosts).
On TPU the runtime-level membership is JAX's distributed runtime instead:
one coordinator, N host processes, after which ``jax.devices()`` returns
the GLOBAL device list and a single ``Mesh`` spans hosts — collectives
ride ICI within a slice and DCN across slices (SURVEY §5.8). The HTTP
control plane stays for orchestration/UI exactly like the reference's.

Deployment flow (see ``docs/deployment.md``):

    # host 0 (coordinator)
    python -m comfyui_distributed_tpu serve \
        --coordinator host0:9911 --num-hosts 4 --host-index 0
    # hosts 1..3
    python -m comfyui_distributed_tpu serve \
        --coordinator host0:9911 --num-hosts 4 --host-index i

Env-var equivalents (for k8s/pod launchers that template manifests):
``CDT_COORDINATOR``, ``CDT_NUM_HOSTS``, ``CDT_HOST_INDEX``.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..utils.logging import log

_initialized = False


def multihost_env() -> dict:
    """The multi-host settings resolved from env (CLI flags override)."""
    from ..utils import constants

    return {
        "coordinator_address": constants.COORDINATOR.get() or None,
        "num_processes": constants.NUM_HOSTS.get(),
        "process_id": constants.HOST_INDEX.get(),
    }


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    initialize_fn: Optional[Callable] = None,
) -> bool:
    """Initialize JAX's distributed runtime when a coordinator is given.

    Arguments fall back to ``CDT_COORDINATOR`` / ``CDT_NUM_HOSTS`` /
    ``CDT_HOST_INDEX``. Returns True when the runtime was initialized,
    False for the single-host no-op. Must run before the first device
    query — JAX's backend is frozen once touched.

    ``initialize_fn`` exists for tests (the real
    ``jax.distributed.initialize`` wants a live coordinator).
    """
    global _initialized
    env = multihost_env()
    coordinator_address = coordinator_address or env["coordinator_address"]
    if not coordinator_address:
        return False
    if _initialized:
        log("multi-host runtime already initialized; skipping")
        return True
    num_processes = num_processes if num_processes is not None else env["num_processes"]
    process_id = process_id if process_id is not None else env["process_id"]
    if num_processes is None or process_id is None:
        raise ValueError(
            "multi-host bootstrap needs --num-hosts and --host-index "
            "(or CDT_NUM_HOSTS / CDT_HOST_INDEX) alongside the coordinator")
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"host index {process_id} out of range for {num_processes} hosts")

    if initialize_fn is None:                      # pragma: no cover - needs pod
        import jax

        initialize_fn = jax.distributed.initialize
    log(f"initializing multi-host runtime: coordinator={coordinator_address} "
        f"hosts={num_processes} index={process_id}")
    initialize_fn(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True
