"""Multi-host JAX runtime bootstrap.

The reference scales across machines by HTTP port registration: every
worker is a separately-launched ComfyUI process that the master reaches
over the network (``workers/process/lifecycle.py:78-96``, config hosts).
On TPU the runtime-level membership is JAX's distributed runtime instead:
one coordinator, N host processes, after which ``jax.devices()`` returns
the GLOBAL device list and a single ``Mesh`` spans hosts — collectives
ride ICI within a slice and DCN across slices (SURVEY §5.8). The HTTP
control plane stays for orchestration/UI exactly like the reference's.

Deployment flow (see ``docs/deployment.md``):

    # host 0 (coordinator)
    python -m comfyui_distributed_tpu serve \
        --coordinator host0:9911 --num-hosts 4 --host-index 0
    # hosts 1..3
    python -m comfyui_distributed_tpu serve \
        --coordinator host0:9911 --num-hosts 4 --host-index i

Env-var equivalents (for k8s/pod launchers that template manifests):
``CDT_COORDINATOR``, ``CDT_NUM_HOSTS``, ``CDT_HOST_INDEX``.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Callable, Optional

from ..utils.logging import log

_initialized = False


def ensure_virtual_devices(n: Optional[int] = None) -> Optional[int]:
    """Stand up an ``n``-device virtual CPU mesh BEFORE jax initializes.

    The executed mesh tier (sp / dp×tp shard_map programs,
    docs/parallelism.md) is tier-1-testable off hardware by running on
    XLA's virtual host devices (``--xla_force_host_platform_device_count``).
    ``n`` falls back to ``CDT_VIRTUAL_DEVICES``; unset/0 is a no-op.

    XLA reads the flag once at backend init, so this MUST run before the
    first ``import jax`` anywhere in the process — a silent late call
    would leave the caller executing a "mesh" program on one device
    while believing it validated eight. Fails loudly instead.
    """
    from ..utils import constants

    n = n if n is not None else constants.VIRTUAL_DEVICES.get()
    if not n:
        return None
    if n < 2:
        raise ValueError(f"CDT_VIRTUAL_DEVICES={n}: a virtual mesh needs "
                         "at least 2 devices")
    flags = os.environ.get("XLA_FLAGS", "")
    existing = re.search(
        r"xla_force_host_platform_device_count=(\d+)", flags)
    if existing:
        have = int(existing.group(1))
        if have != n:
            # silently proceeding would leave the caller executing an
            # n-device "mesh" on `have` devices — the exact state this
            # function exists to prevent
            raise RuntimeError(
                f"CDT_VIRTUAL_DEVICES={n} conflicts with XLA_FLAGS "
                f"already forcing {have} host devices")
        return n         # already configured (test conftest, driver env)
    if "xla_force_host_platform_device_count" in flags:
        raise RuntimeError(
            "XLA_FLAGS carries a malformed "
            "xla_force_host_platform_device_count; refusing to guess")
    if "jax" in sys.modules:
        raise RuntimeError(
            f"CDT_VIRTUAL_DEVICES={n} but jax is already imported — the "
            "virtual device count is frozen at backend init. Set the "
            "knob (or call ensure_virtual_devices) before anything "
            "imports jax.")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n}".strip())
    # virtual devices exist only on the host platform; an accelerator
    # plugin registering first would shadow them
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    log(f"virtual mesh: {n} host devices "
        f"(--xla_force_host_platform_device_count)")
    return n


def multihost_env() -> dict:
    """The multi-host settings resolved from env (CLI flags override)."""
    from ..utils import constants

    return {
        "coordinator_address": constants.COORDINATOR.get() or None,
        "num_processes": constants.NUM_HOSTS.get(),
        "process_id": constants.HOST_INDEX.get(),
    }


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    initialize_fn: Optional[Callable] = None,
) -> bool:
    """Initialize JAX's distributed runtime when a coordinator is given.

    Arguments fall back to ``CDT_COORDINATOR`` / ``CDT_NUM_HOSTS`` /
    ``CDT_HOST_INDEX``. Returns True when the runtime was initialized,
    False for the single-host no-op. Must run before the first device
    query — JAX's backend is frozen once touched.

    ``initialize_fn`` exists for tests (the real
    ``jax.distributed.initialize`` wants a live coordinator).
    """
    global _initialized
    env = multihost_env()
    coordinator_address = coordinator_address or env["coordinator_address"]
    if not coordinator_address:
        return False
    if _initialized:
        log("multi-host runtime already initialized; skipping")
        return True
    num_processes = num_processes if num_processes is not None else env["num_processes"]
    process_id = process_id if process_id is not None else env["process_id"]
    if num_processes is None or process_id is None:
        raise ValueError(
            "multi-host bootstrap needs --num-hosts and --host-index "
            "(or CDT_NUM_HOSTS / CDT_HOST_INDEX) alongside the coordinator")
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"host index {process_id} out of range for {num_processes} hosts")

    if initialize_fn is None:                      # pragma: no cover - needs pod
        import jax

        initialize_fn = jax.distributed.initialize
    log(f"initializing multi-host runtime: coordinator={coordinator_address} "
        f"hosts={num_processes} index={process_id}")
    initialize_fn(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True
