"""TPU parallel substrate: mesh bootstrap, sharding, RNG, collectives.

This package is the TPU-native replacement for the reference's entire
transport/parallelism story (SURVEY §2.10): where the reference fans jobs to
worker *processes* over HTTP and gathers base64-PNG envelopes
(``nodes/collector.py``), we shard computations over a ``jax.sharding.Mesh``
and gather with XLA collectives over ICI.

Exports resolve lazily (PEP 562): ``bootstrap.ensure_virtual_devices``
must be importable BEFORE jax initializes (``CDT_VIRTUAL_DEVICES`` sets
``--xla_force_host_platform_device_count``, which XLA reads exactly
once), so importing this package must not itself pull jax in.
"""

_EXPORTS = {
    "MeshSpec": ".mesh",
    "build_mesh": ".mesh",
    "device_census": ".mesh",
    "local_device_count": ".mesh",
    "mesh_from_config": ".mesh",
    "participant_key": ".rng",
    "participant_keys": ".rng",
    "seed_to_key": ".rng",
    "batch_sharding": ".sharding",
    "replicated_sharding": ".sharding",
    "shard_batch": ".sharding",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod, __name__), name)
