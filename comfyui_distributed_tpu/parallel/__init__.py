"""TPU parallel substrate: mesh bootstrap, sharding, RNG, collectives.

This package is the TPU-native replacement for the reference's entire
transport/parallelism story (SURVEY §2.10): where the reference fans jobs to
worker *processes* over HTTP and gathers base64-PNG envelopes
(``nodes/collector.py``), we shard computations over a ``jax.sharding.Mesh``
and gather with XLA collectives over ICI.
"""

from .mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    device_census,
    local_device_count,
    mesh_from_config,
)
from .rng import participant_key, participant_keys, seed_to_key  # noqa: F401
from .sharding import (  # noqa: F401
    batch_sharding,
    replicated_sharding,
    shard_batch,
)
