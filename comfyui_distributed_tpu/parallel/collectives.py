"""Collective wrappers — the in-graph replacements for the reference's
HTTP result plumbing.

Reference mapping (SURVEY §2.10):
- Collector gather (worker POSTs base64-PNG envelopes to master
  ``/distributed/job_complete``, master drains an asyncio queue,
  ``nodes/collector.py:143-178,381-499``) → ``gather_batch`` (all_gather
  over ICI, zero serialization, deterministic participant order).
- Tile submission (chunked multipart POSTs, ``upscale/worker_comms.py:16-108``)
  → tiles simply live in the sharded output array.

These helpers are meant to be called *inside* ``shard_map``-decorated
functions; they are thin by design so XLA can fuse and schedule them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from ..utils.jax_compat import axis_size as _axis_size, shard_map

from ..utils import constants


def gather_batch(x: jax.Array, axis: str = constants.AXIS_DATA) -> jax.Array:
    """All-gather shards along dim 0, concatenated in participant order.

    Participant order is mesh-index order: index 0 first — the same
    deterministic "master first, then workers in enabled order" contract as
    the reference's ``_reorder_and_combine_tensors``
    (``nodes/collector.py:252-295``).

    Under ``CDT_MESH_OVERLAP`` (default on) the gather is the ring
    decomposition (``parallel/overlap.all_gather_ring``): n-1 per-block
    ppermute hops whose already-arrived blocks unblock downstream
    compute while later hops are in flight. Bit-exact either way —
    gathering moves bytes, never recomputes them.

    Note: under ``jax.shard_map`` the gathered value is equal on every shard
    but is still *tracked* as axis-varying, so callers that declare it
    replicated via ``out_specs=P(None, ...)`` must pass ``check_vma=False``.
    """
    from .overlap import all_gather_ring, overlap_enabled

    if overlap_enabled():
        return all_gather_ring(x, axis, dim=0)
    return jax.lax.all_gather(x, axis, axis=0, tiled=True)


def mean_over(x: jax.Array, axis: str) -> jax.Array:
    """Cross-shard mean; the overlap-scheduled ring under
    ``CDT_MESH_OVERLAP`` (see ``sum_over``)."""
    from .overlap import overlap_enabled

    if overlap_enabled():
        from ..utils.jax_compat import axis_size

        return sum_over(x, axis) / axis_size(axis)
    return jax.lax.pmean(x, axis)


def sum_over(x: jax.Array, axis: str) -> jax.Array:
    """Cross-shard sum. ``CDT_MESH_OVERLAP`` (default on) routes it
    through the ring reduce-scatter + all-gather decomposition
    (``parallel/overlap.all_reduce`` — per-block ppermute steps XLA can
    overlap with the compute each block unblocks; the opt-in
    ``CDT_COLLECTIVE_QUANT`` int8 wire rides the same path); otherwise
    one fused ``psum``."""
    from .overlap import all_reduce, overlap_enabled

    if overlap_enabled():
        return all_reduce(x, axis)
    return jax.lax.psum(x, axis)


def shard_index(axis: str) -> jax.Array:
    return jax.lax.axis_index(axis)


def ring_shift(x: jax.Array, axis: str, shift: int = 1) -> jax.Array:
    """Rotate shards around the ring: shard i receives shard i-shift.

    Building block for ring attention / ring-overlapped pipelines; compiles
    to ``ppermute`` which XLA maps onto ICI neighbour links.
    """
    n = _axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def all_to_all_heads(x: jax.Array, axis: str, split_dim: int, concat_dim: int) -> jax.Array:
    """All-to-all used by Ulysses-style sequence parallelism: redistribute
    from sequence-sharded to head-sharded layout (and back)."""
    return jax.lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True)
