"""Overlap-scheduled and quantized mesh collectives.

One fused ``psum``/``all_gather`` is a barrier: every byte must land
before ANY dependent compute starts. The executed mesh tier
(docs/parallelism.md) instead decomposes its collectives into per-block
``ppermute`` ring steps — the dependency structure then lets XLA's
latency-hiding scheduler run each hop's neighbour transfer concurrently
with the compute the previously-arrived blocks already unblocked
(T3-style fine-grained compute/communication overlap, arXiv 2401.16677).
The ring order is fixed (shard 0 → 1 → … → n-1 → 0), so results are
deterministic run-to-run and host-to-host.

On top of the ring decomposition rides an opt-in quantized wire format
(EQuARX, arXiv 2506.17615): payloads cross the interconnect as int8 with
a per-tensor absmax scale, halving bf16 collective bytes. The default
(``CDT_COLLECTIVE_QUANT=none``) keeps every collective bit-exact; the
``int8`` tier's error is bounded and documented per function.

Every function here is meant to be called INSIDE ``shard_map`` — the
same contract as ``parallel/collectives.py``.

Knobs: ``CDT_MESH_OVERLAP`` (default on — ring decomposition),
``CDT_COLLECTIVE_QUANT`` (``none``/``int8``, default ``none``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import constants
from ..utils.jax_compat import axis_size as _axis_size


def overlap_enabled() -> bool:
    return constants.MESH_OVERLAP.get()


def collective_quant_mode() -> str:
    """``none`` (bit-exact, the default) or ``int8``."""
    return constants.COLLECTIVE_QUANT.get()


def quant_error_bound(absmax: float, hops: int = 1) -> float:
    """Worst-case per-element absolute error of the int8 wire format.

    One quantization round is absmax-scaled round-to-nearest:
    ``scale = absmax / 127``, so ``|x - deq(q)| <= scale/2 = absmax/254``.
    A payload re-quantized on every ring hop (reduce-scatter partials)
    compounds at most ``hops`` rounds; payloads quantized once and
    rotated as int8 (all-gather, ring-attention K/V) hold at one round
    regardless of ring length.
    """
    return hops * absmax / 254.0


# --- int8 wire format --------------------------------------------------------


def wire_quantize(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-tensor absmax int8 quantization of a collective payload.

    Returns ``(q, scale)`` with ``q`` int8 and ``scale`` a float32
    scalar; ``dequantize(q, scale)`` is within ``absmax/254`` of ``x``
    per element (see :func:`quant_error_bound`). An all-zero payload
    quantizes to scale 0 and dequantizes exactly.
    """
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = absmax / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / safe), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def wire_dequantize(q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


# --- ring decompositions -----------------------------------------------------


def _right_perm(n: int) -> list[tuple[int, int]]:
    return [(j, (j + 1) % n) for j in range(n)]


def _chunks(x: jax.Array, n: int, dim: int) -> jax.Array:
    """[n, ...chunk...] stack of ``x`` split ``n``-ways along ``dim``."""
    if x.shape[dim] % n:
        raise ValueError(
            f"ring collective: dim {dim} of shape {x.shape} must divide "
            f"over {n} shards")
    return jnp.stack(jnp.split(x, n, axis=dim))


def _take(chunks: jax.Array, j: jax.Array, n: int) -> jax.Array:
    return jax.lax.dynamic_index_in_dim(chunks, jnp.mod(j, n), 0,
                                        keepdims=False)


def reduce_scatter_ring(x: jax.Array, axis: str, dim: int = 0,
                        quant: Optional[str] = None) -> jax.Array:
    """Ring reduce-scatter: shard ``i`` ends with chunk ``i`` of the
    cross-shard sum of ``x`` (split ``n``-ways along ``dim``).

    ``n-1`` per-block ppermute steps, each carrying one chunk-sized
    payload; the blocks not in flight stay available to downstream
    compute, which is the whole point of the decomposition. Accumulation
    is float32 in ring order (deterministic).

    ``quant="int8"`` quantizes every hop's partial-sum payload for the
    wire; error compounds at most ``(n-1) * absmax / 254`` per element
    (:func:`quant_error_bound` with ``hops=n-1``).
    """
    n = _axis_size(axis)
    if n == 1:
        return x
    idx = jax.lax.axis_index(axis)
    chunks = _chunks(x.astype(jnp.float32), n, dim)
    perm = _right_perm(n)
    carry = _take(chunks, idx - 1, n)
    for t in range(1, n):
        if quant == "int8":
            q, scale = wire_quantize(carry)
            q = jax.lax.ppermute(q, axis, perm)
            scale = jax.lax.ppermute(scale, axis, perm)
            carry = wire_dequantize(q, scale)
        else:
            carry = jax.lax.ppermute(carry, axis, perm)
        carry = carry + _take(chunks, idx - 1 - t, n)
    return carry.astype(x.dtype)


def all_gather_ring(x: jax.Array, axis: str, dim: int = 0,
                    quant: Optional[str] = None) -> jax.Array:
    """Ring all-gather: every shard ends with the shards' ``x`` blocks
    concatenated in shard order along ``dim``.

    ``n-1`` per-block ppermute hops instead of one fused all-gather —
    block ``t`` arrives at hop ``t`` and immediately unblocks whatever
    consumes it while later hops are still in flight.

    ``quant="int8"`` quantizes each shard's block ONCE and rotates the
    int8 payload, so every remote block carries exactly one quantization
    round (``absmax/254``); the local block stays exact.
    """
    n = _axis_size(axis)
    if n == 1:
        return x
    idx = jax.lax.axis_index(axis)
    perm = [(j, (j - 1) % n) for j in range(n)]     # receive from i+1
    if quant == "int8":
        q, scale = wire_quantize(x)
        collected = [x.astype(jnp.float32)]
        for _ in range(1, n):
            q = jax.lax.ppermute(q, axis, perm)
            scale = jax.lax.ppermute(scale, axis, perm)
            collected.append(wire_dequantize(q, scale))
    else:
        carry = x
        collected = [carry]
        for _ in range(1, n):
            carry = jax.lax.ppermute(carry, axis, perm)
            collected.append(carry)
    # collected[t] holds shard (idx+t) % n's block; roll to absolute order
    stacked = jnp.stack(collected)
    rolled = jnp.roll(stacked, idx, axis=0)
    return jnp.concatenate(
        [rolled[t] for t in range(n)], axis=dim).astype(x.dtype)


def _scatter_dim(shape: tuple, n: int) -> Optional[int]:
    for d, s in enumerate(shape):
        if s >= n and s % n == 0:
            return d
    return None


def all_reduce(x: jax.Array, axis: str,
               quant: Optional[str] = None,
               overlap: Optional[bool] = None) -> jax.Array:
    """Cross-shard sum with the mesh tier's scheduling policy.

    Default (``CDT_MESH_OVERLAP=1``): reduce-scatter + all-gather over
    per-block ppermute rings — 2(n-1) chunk transfers XLA can overlap
    with the compute each finished block unblocks, vs one fused barrier.
    ``CDT_MESH_OVERLAP=0`` (or a shape with no shard-divisible dim)
    falls back to one ``psum``.

    ``quant`` defaults to ``CDT_COLLECTIVE_QUANT``; ``"int8"`` halves
    bf16 wire bytes with error bounded by ``quant_error_bound(absmax,
    hops=n-1)`` from the reduce-scatter plus one round from the gather.
    The ``none`` default is bit-exact with respect to this function's
    own f32 ring order (deterministic, and on a 1-shard axis the input
    passes through untouched).
    """
    n = _axis_size(axis)
    if n == 1:
        return x
    if quant is None:
        quant = collective_quant_mode()
        quant = None if quant == "none" else quant
    if overlap is None:
        overlap = overlap_enabled()
    dim = _scatter_dim(x.shape, n)
    if not overlap or dim is None:
        out = jax.lax.psum(x, axis)
        return out
    scattered = reduce_scatter_ring(x, axis, dim=dim, quant=quant)
    return all_gather_ring(scattered, axis, dim=dim, quant=quant)
