"""Device census and mesh construction.

The reference enumerates CUDA devices to auto-populate one worker process per
GPU (``api/worker_routes.py:237-289`` + ``web/masterDetection.js:36-100``).
The TPU equivalent enumerates ``jax.devices()`` and lays them out as a named
``Mesh``; "workers" on-pod are mesh slots, not OS processes (SURVEY §7).

Multi-host: when JAX's distributed runtime is initialized, ``jax.devices()``
returns the global device list and the same mesh spans hosts over DCN; this
module needs no special casing beyond using global devices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.exceptions import ShardingError


def device_census() -> list[dict[str, Any]]:
    """Describe every visible device — the TPU analogue of the reference's
    CUDA census used for worker auto-population."""
    out = []
    for d in jax.devices():
        info: dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "unknown"),
            "process_index": d.process_index,
        }
        coords = getattr(d, "coords", None)
        if coords is not None:
            info["coords"] = tuple(coords)
        out.append(info)
    return out


def local_device_count() -> int:
    return jax.local_device_count()


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Mesh shape as an ordered mapping of axis name → size.

    At most one axis may be ``-1`` ("all remaining devices"), mirroring the
    config schema (``utils/config.py`` ``mesh.shape``).
    """

    shape: tuple[tuple[str, int], ...]

    @classmethod
    def from_mapping(cls, mapping: Mapping[str, int]) -> "MeshSpec":
        items = tuple((str(k), int(v)) for k, v in mapping.items())
        if not items:
            raise ShardingError("mesh shape must have at least one axis")
        if sum(1 for _, v in items if v == -1) > 1:
            raise ShardingError("at most one mesh axis may be -1")
        for name, v in items:
            if v == 0 or v < -1:
                raise ShardingError(f"invalid size {v} for mesh axis {name!r}")
        return cls(items)

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(n for n, _ in self.shape)

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        """Concrete per-axis sizes for ``n_devices`` total devices."""
        sizes = [v for _, v in self.shape]
        known = math.prod(v for v in sizes if v != -1)
        if -1 in sizes:
            if n_devices % known:
                raise ShardingError(
                    f"{n_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[sizes.index(-1)] = n_devices // known
        elif known > n_devices:
            raise ShardingError(
                f"mesh {dict(self.shape)} needs {known} devices, have {n_devices}"
            )
        return tuple(sizes)


def build_mesh(
    spec: MeshSpec | Mapping[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``Mesh`` from a spec, using all visible devices by default.

    Devices are laid out in enumeration order reshaped to the spec — on TPU,
    ``jax.devices()`` order follows the physical torus so contiguous mesh
    axes ride ICI neighbours; we deliberately do not permute it.
    """
    if not isinstance(spec, MeshSpec):
        spec = MeshSpec.from_mapping(spec)
    devs = list(devices) if devices is not None else list(jax.devices())
    sizes = spec.resolve(len(devs))
    used = math.prod(sizes)
    grid = np.array(devs[:used], dtype=object).reshape(sizes)
    return Mesh(grid, spec.axis_names)


def mesh_from_config(config: dict, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Mesh from the ``mesh.shape`` config section.

    With no explicit shape the mesh-tier placement planner decides
    (``parallel/serving.plan_placement``): a pinned ``CDT_MESH_TP``
    yields the dp×tp layout (tp innermost — ICI-neighbour shards),
    otherwise the flat dp fan-out, exactly as before. An explicit
    config shape always wins — operators stay authoritative."""
    shape = (config.get("mesh") or {}).get("shape")
    if not shape:
        from . import serving

        n = len(devices) if devices is not None else len(jax.devices())
        shape = serving.plan_placement(n, batch=2).mesh_shape
    return build_mesh(MeshSpec.from_mapping(shape), devices)


def axis_size(mesh: Mesh, axis: str) -> int:
    try:
        return mesh.shape[axis]
    except KeyError:
        raise ShardingError(f"mesh has no axis {axis!r}; axes: {mesh.axis_names}")


def describe_mesh(mesh: Mesh) -> dict[str, Any]:
    """JSON-friendly mesh summary for the control plane's system_info
    (parity: reference ``api/worker_routes.py:393-430``)."""
    return {
        "axes": dict(mesh.shape),
        "n_devices": mesh.devices.size,
        "platform": mesh.devices.flat[0].platform,
        "process_indices": sorted({d.process_index for d in mesh.devices.flat}),
    }
