"""Mesh serving tier: placement planning for executed sp / dp×tp.

Until ISSUE 13 the multi-chip modes only ever ran under the driver's
dry-run validation; serving always placed work on a flat ``dp`` mesh.
This module is the policy that makes the mesh the DEFAULT tier:

- :func:`derive_tp` — the tp degree a model needs on this fleet:
  ``CDT_MESH_TP`` wins; otherwise the smallest power-of-two shard count
  whose per-chip weight slice fits the HBM budget (the residency
  planner's tp-shard arithmetic, ``cluster/residency.py``).
- :func:`plan_placement` — one strategy per request class:
  ``dp_tp`` when the weights need sharding (or the operator pinned a tp
  degree), ``sp`` for single-image latency when the model has a
  sequence-parallel path, ``dp`` seed fan-out otherwise.
- :func:`mesh_for` — the concrete ``Mesh`` for a plan, laid out so tp
  rides the fastest (innermost/ICI-neighbour) axis.

``CDT_MESH_TIER=0`` collapses everything back to the flat dp tier (the
pre-ISSUE-13 behavior) — the kill switch every serving subsystem ships
with.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..utils import constants

STRATEGIES = ("dp", "dp_tp", "sp")


def mesh_tier_enabled() -> bool:
    return constants.MESH_TIER.get()


def derive_tp(n_devices: int, param_bytes: Optional[int] = None,
              budget_bytes: Optional[int] = None) -> int:
    """The tp degree serving should shard weights over.

    ``CDT_MESH_TP`` pins it (clamped to the device count). Otherwise,
    with known weight bytes and a per-chip HBM budget, the smallest
    power-of-two shard count whose per-chip slice fits; 1 when the
    weights fit replicated (tp overhead is pure cost then) or when
    nothing is known.
    """
    pinned = constants.MESH_TP.get()
    if pinned:
        return max(1, min(int(pinned), n_devices))
    if not param_bytes or not budget_bytes or budget_bytes <= 0:
        return 1
    tp = 1
    while tp * 2 <= n_devices and param_bytes / tp > budget_bytes:
        tp *= 2
    return tp


@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    """One request class's resolved placement."""

    strategy: str                      # dp | dp_tp | sp
    n_devices: int
    tp: int = 1
    reason: str = ""

    @property
    def mesh_shape(self) -> dict:
        if self.strategy == "sp":
            return {constants.AXIS_SEQUENCE: self.n_devices}
        if self.strategy == "dp_tp":
            return {constants.AXIS_DATA: self.n_devices // self.tp,
                    constants.AXIS_TENSOR: self.tp}
        return {constants.AXIS_DATA: self.n_devices}

    def to_dict(self) -> dict:
        return {"strategy": self.strategy, "n_devices": self.n_devices,
                "tp": self.tp, "mesh": self.mesh_shape,
                "reason": self.reason}


def plan_placement(n_devices: int, *, batch: int = 1,
                   param_bytes: Optional[int] = None,
                   budget_bytes: Optional[int] = None,
                   supports_sp: bool = False,
                   supports_tp: bool = True) -> PlacementPlan:
    """Pick the serving strategy for one request class.

    Precedence: weights that don't fit replicated (or a pinned
    ``CDT_MESH_TP``) force ``dp_tp``; a single-image request on a model
    with a sequence-parallel path takes ``sp`` (latency scales with
    chips — the thing the reference architecture explicitly cannot do);
    everything else fans seeds out over ``dp``. ``CDT_MESH_TIER=0`` or a
    1-device host always yields flat dp.
    """
    if n_devices <= 1 or not mesh_tier_enabled():
        return PlacementPlan("dp", max(n_devices, 1),
                             reason="mesh tier off or single device")
    tp = derive_tp(n_devices, param_bytes, budget_bytes) if supports_tp \
        else 1
    if tp > 1:
        while n_devices % tp:          # keep the mesh factorable
            tp //= 2
    if tp > 1:
        why = ("CDT_MESH_TP pinned" if constants.MESH_TP.get()
               else f"weights ({param_bytes / 1e9:.1f} GB) exceed the "
                    f"per-chip budget")
        return PlacementPlan("dp_tp", n_devices, tp, reason=why)
    if batch <= 1 and supports_sp:
        return PlacementPlan("sp", n_devices,
                             reason="single-image latency: shard the "
                                    "sequence, not the batch")
    return PlacementPlan("dp", n_devices, reason="seed fan-out")


def mesh_for(plan: PlacementPlan, devices=None):
    """Concrete ``Mesh`` for a plan. Axis order puts tp LAST so tp
    shards land on enumeration-adjacent (ICI-neighbour) devices — the
    all-reduces ride the fastest links while dp stays pure fan-out."""
    from .mesh import build_mesh

    return build_mesh(plan.mesh_shape, devices)
