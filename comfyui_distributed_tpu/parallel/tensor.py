"""Tensor parallelism: weight sharding over the ``tp`` mesh axis.

The reference has no TP (SURVEY §2.10) — it cannot run models larger than
one GPU. Here large models (FLUX-class 12B DiT, WAN-class 14B) shard their
weight matrices over ``tp`` and XLA/GSPMD inserts the collectives: we
annotate parameter leaves with ``NamedSharding`` and jit with sharded
inputs; the compiler propagates layouts through the graph (the
scaling-book recipe: pick a mesh, annotate, let XLA insert collectives).

Rules are path-regex → PartitionSpec. The defaults implement Megatron-style
column/row splits for transformer blocks:
- QKV / MLP-up kernels: shard the OUTPUT feature dim (column parallel);
- attention-out / MLP-down kernels: shard the INPUT feature dim (row
  parallel — GSPMD adds the all-reduce after the matmul);
- everything else (norms, embeddings, modulation) replicates.
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import constants
from ..utils.logging import debug_log

# (path regex, spec builder given tp axis name). Kernel shapes are
# [in_features, out_features] for flax Dense.
DIT_TP_RULES: tuple[tuple[str, tuple], ...] = (
    (r".*qkv/qkv/kernel$",        (None, "tp")),     # column
    (r".*mlp_up/kernel$",         (None, "tp")),     # column
    (r".*(img|txt)_proj/kernel$", ("tp", None)),     # row
    (r".*mlp_down/kernel$",       ("tp", None)),     # row
    (r".*single_\d+/out/kernel$", ("tp", None)),     # row (fused attn+mlp out)
)

UNET_TP_RULES: tuple[tuple[str, tuple], ...] = (
    (r".*to_q/kernel$",    (None, "tp")),
    (r".*to_k/kernel$",    (None, "tp")),
    (r".*to_v/kernel$",    (None, "tp")),
    (r".*to_out/kernel$",  ("tp", None)),
    (r".*ff/proj_in/kernel$",  (None, "tp")),
    (r".*ff/proj_out/kernel$", ("tp", None)),
)

# WAN-class video DiT (models/wan.py): separate q/k/v/o Dense layers in
# self/cross attention, ffn_0 (up) / ffn_2 (down). The q/k RMSNorms
# normalize over the FULL feature dim, so GSPMD inserts the partial-sum
# all-reduce there; attention itself stays head-local because the column
# split lands on the head axis after the [B,N,H,D] reshape.
WAN_TP_RULES: tuple[tuple[str, tuple], ...] = (
    (r".*(self|cross)_attn/[qkv]/kernel$", (None, "tp")),   # column
    (r".*(self|cross)_attn/o/kernel$",     ("tp", None)),   # row
    (r".*ffn_0/kernel$",                   (None, "tp")),   # column
    (r".*ffn_2/kernel$",                   ("tp", None)),   # row
)


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def spec_for_param(path: str, shape: tuple[int, ...],
                   rules: Sequence[tuple[str, tuple]],
                   axis: str, axis_size: int) -> P:
    for pattern, spec_dims in rules:
        if re.match(pattern, path):
            dims = tuple(axis if d == "tp" else None for d in spec_dims)
            # the sharded dim must divide; fall back to replication if not
            ok = all(
                d is None or (i < len(shape) and shape[i] % axis_size == 0)
                for i, d in enumerate(dims)
            )
            if ok and len(dims) == len(shape):
                return P(*dims)
            debug_log(f"tp rule {pattern} skipped for {path} (shape {shape})")
    return P()


def shard_params(
    params: Any,
    mesh: Mesh,
    rules: Sequence[tuple[str, tuple]] = DIT_TP_RULES,
    axis: str = constants.AXIS_TENSOR,
) -> Any:
    """Place a parameter pytree with TP rules applied; returns the sharded
    tree (unmatched leaves replicated)."""
    axis_size = mesh.shape[axis]

    def place(path, leaf):
        spec = spec_for_param(_path_str(path), leaf.shape, rules, axis, axis_size)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def require_tp_match(params: Any, mesh: Mesh,
                     rules: Sequence[tuple[str, tuple]], axis: str,
                     family: str) -> None:
    """Fail fast when no parameter matches the TP rules: a model that
    needs this mode would OOM every chip with fully-replicated weights,
    and the failure would otherwise surface as an opaque allocator error
    mid-compile."""
    if mesh.shape[axis] <= 1:
        return
    summary = tp_sharding_summary(params, mesh, rules, axis)
    if summary["sharded"] == 0:
        raise ValueError(
            f"no parameters matched the {family!r} TP rules — a model "
            f"this mode exists for would OOM every chip with "
            f"fully-replicated weights")


def tp_fanout_call(jitted, weight_args: tuple, mesh: Mesh, dp_axis: str,
                   B: int, tp_axis: str = constants.AXIS_TENSOR):
    """Shared dp×tp call wrapper: folds a base key into ``B`` per-sample
    keys placed over ``dp``, and supplies the (tp-placed) weight args to
    the jitted program. ``.jitted``/``.weights`` expose the AOT handles
    (same contract as ``diffusion.pipeline.bind_weights``);
    ``.tp_shards`` carries the tp degree so AOT lowerers can restore the
    same per-shard kernel-selection scope this wrapper traces under."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    from ..ops.attention import tp_shard_scope

    key_sharding = NamedSharding(mesh, PartitionSpec(dp_axis))
    tp = dict(mesh.shape).get(tp_axis, 1)

    def call(key, *rest):
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(B))
        # per-shard geometry scope: tracing (first call) must resolve
        # attention kernels for H/tp heads — what each shard executes
        with tp_shard_scope(tp):
            return jitted(*weight_args, jax.device_put(keys, key_sharding),
                          *rest)

    call.jitted = jitted
    call.weights = weight_args
    call.tp_shards = tp
    return call


def tp_sharding_summary(params: Any, mesh: Mesh,
                        rules: Sequence[tuple[str, tuple]] = DIT_TP_RULES,
                        axis: str = constants.AXIS_TENSOR) -> dict[str, int]:
    """How many leaves (and bytes) each placement class got — for logs and
    capacity planning."""
    axis_size = mesh.shape[axis]
    out = {"sharded": 0, "replicated": 0, "sharded_bytes": 0, "replicated_bytes": 0}

    def visit(path, leaf):
        spec = spec_for_param(_path_str(path), leaf.shape, rules, axis, axis_size)
        nbytes = leaf.size * leaf.dtype.itemsize
        if any(d is not None for d in spec):
            out["sharded"] += 1
            out["sharded_bytes"] += nbytes
        else:
            out["replicated"] += 1
            out["replicated_bytes"] += nbytes

    jax.tree_util.tree_map_with_path(visit, params)
    return out
