"""Per-participant RNG derivation.

Parity: the reference's ``DistributedSeed`` node gives worker ``N`` the seed
``seed + N + 1`` while the master keeps ``seed`` (``nodes/utilities.py:52-75``)
so every participant samples a different image. The TPU-native version derives
statistically independent keys with ``jax.random.fold_in`` — inside a sharded
computation via ``lax.axis_index``, or host-side for a whole batch at once.

fold_in is used instead of additive offsets because nearby integer seeds do
not guarantee independent streams; fold_in does, and it composes with JAX's
key semantics under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def seed_to_key(seed: int) -> jax.Array:
    return jax.random.key(jnp.uint32(seed))


def participant_key(base_key: jax.Array, axis: str) -> jax.Array:
    """Per-participant key *inside* a ``shard_map``/``pmap`` over ``axis``.

    Index 0 (the reference's "master") folds in 0, worker ``N`` folds in
    ``N`` — preserving the reference's deterministic master-first ordering
    (``nodes/collector.py:252-295``) without special-casing the master.
    """
    return jax.random.fold_in(base_key, jax.lax.axis_index(axis))


def participant_keys(base_key: jax.Array, n: int) -> jax.Array:
    """Host-side: stacked keys for ``n`` participants; row ``i`` equals what
    ``participant_key`` yields at mesh index ``i``."""
    return jax.vmap(lambda i: jax.random.fold_in(base_key, i))(jnp.arange(n))


def participant_seeds(seed: int, n: int) -> list[int]:
    """Plain-integer view for UIs/logs: the reference's visible seed list
    (master = seed, worker N = seed + N + 1, ``nodes/utilities.py:52-75``).
    Kept for API/display parity only — sampling uses fold_in keys."""
    return [seed] + [seed + i + 1 for i in range(n - 1)]
